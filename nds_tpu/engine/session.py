# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Session: the engine's user-facing entry point (the role SparkSession plays
for the reference drivers; ref: nds/nds_power.py:204-248).

Holds the table catalog and configuration, parses and executes SQL, and
exposes collect()/write() result surfaces. DML (INSERT/DELETE for Data
Maintenance) routes through the snapshot warehouse when one is attached.
"""

from __future__ import annotations

import os
import time

import pyarrow as pa

from nds_tpu.engine.column import from_arrow
from nds_tpu.engine.table import DeviceTable
from nds_tpu.obs import trace as _obs
from nds_tpu.sql import ast as A
from nds_tpu.sql.parser import parse
from nds_tpu.sql.planner import ExecError, Planner


class Result:
    """A materialized query result."""

    def __init__(self, table: DeviceTable):
        self.table = table

    @property
    def num_rows(self) -> int:
        from nds_tpu.engine import ops as E
        return E.count_int(self.table.nrows)

    @property
    def column_names(self):
        return self.table.column_names

    def to_arrow(self) -> pa.Table:
        # the device->host result fetch: the "materialize" phase of the
        # query trace (collect() and the write path both land here)
        with _obs.span("materialize"):
            return self.table.to_arrow()

    def collect(self):
        """Device -> host gather; returns list of row tuples (the reference's
        df.collect() contract; ref: nds/nds_power.py:125-135)."""
        arrow = self.to_arrow()
        cols = [arrow.column(i).to_pylist() for i in range(arrow.num_columns)]
        return list(zip(*cols)) if cols else []

    def write(self, path: str, fmt: str = "parquet"):
        from nds_tpu.io.columnar import write_table
        write_table(self.to_arrow(), path, fmt)


class Session:
    def __init__(self, conf: dict | None = None):
        import os

        from nds_tpu.parallel.multihost import maybe_initialize
        maybe_initialize()       # multi-host federation precedes backend use
        from nds_tpu import enable_compile_cache
        enable_compile_cache()   # backend is resolved by session time
        self.conf = dict(conf or {})
        self.catalog: dict[str, DeviceTable] = {}
        self.base_tables: set[str] = set()   # names loaded as pristine scans
        self.warehouse = None            # attached by maintenance driver
        self.view_setup_times: list = [] # (name, ms) like setup_tables timing
        # the role Spark's applicationId plays in time logs
        # (ref: nds/nds_power.py:246,265)
        self.app_id = f"nds-tpu-{int(time.time() * 1000)}"
        self.app_name = "nds-tpu"
        # SPMD execution: with a >1 mesh (power-of-two device count; the
        # launch templates export NDS_MESH_SHAPE, base.template), base-table
        # columns are row-sharded over the mesh and GSPMD partitions every
        # engine primitive, inserting ICI collectives where Spark would
        # shuffle (SURVEY.md §2.4.1, §5.8). Bucketed physical lengths are
        # powers of two >= 16, so any such mesh divides them evenly.
        self.mesh = None
        # whole-query trace-replay compilation (engine/replay.py): keyed
        # on (query text, data version). Default ON for accelerator
        # backends (where per-dispatch tunnel/launch latency dominates);
        # CPU opts in with NDS_TPU_REPLAY=force, everything off with =off.
        self._data_version = 0
        self._replay_cache: dict = {}
        self._replay_seen: set = set()
        self._replay_blacklist: set = set()
        # hybrid policy state: first-sight eager host-sync count per key,
        # consulted by 'auto' mode (see _replay_mode)
        self._replay_syncs: dict = {}
        shape = int(self.conf.get("mesh_shape") or
                    os.environ.get("NDS_MESH_SHAPE", "1"))
        if shape > 1:
            if shape & (shape - 1):
                raise ValueError(f"mesh_shape must be a power of two, "
                                 f"got {shape}")
            # every physical bucket must divide evenly across the mesh; the
            # floor is a process-wide shape contract, so it is configured by
            # environment (NDS_TPU_MIN_BUCKET) at import, never mutated here
            from nds_tpu.engine import ops as _ops
            if shape > _ops._MIN_BUCKET:
                raise ValueError(
                    f"mesh_shape {shape} exceeds the physical bucket floor "
                    f"{_ops._MIN_BUCKET}; start the process with "
                    f"NDS_TPU_MIN_BUCKET={shape} (or larger power of two)")
            import jax
            n_avail = len(jax.devices())
            if n_avail < shape:
                raise ValueError(
                    f"mesh_shape {shape} exceeds the {n_avail} available "
                    f"device(s); silent truncation would under-shard")
            from nds_tpu.parallel import make_mesh
            self.mesh = make_mesh(shape)

    # -- catalog ------------------------------------------------------------

    def _shard_table(self, table: DeviceTable) -> DeviceTable:
        """Place a table over the session mesh (no-op without one).

        The broadcast-vs-shard decision is made here, at load time: tables
        under the broadcast byte threshold are REPLICATED (every device
        holds the whole table, so joins against them are local probes — the
        all-gather-join side of the planner's broadcast/repartition choice,
        Spark's autoBroadcastJoinThreshold analog); larger tables are
        row-sharded, and big x big joins repartition through the ICI
        all-to-all exchange (engine/ops.py join path, parallel/exchange.py).
        Ref: SURVEY.md §5.8, nds/power_run_cpu.template:30."""
        if self.mesh is None:
            return table
        import os

        import jax
        from dataclasses import replace as _replace
        from jax.sharding import NamedSharding, PartitionSpec as P
        limit = int(self.conf.get(
            "broadcast_bytes",
            os.environ.get("NDS_TPU_BROADCAST_BYTES", str(128 << 20))))
        approx = sum(c.data.nbytes +
                     (c.valid.nbytes if c.valid is not None else 0)
                     for c in table.columns.values())
        spec = P() if approx <= limit else P("part")
        sh = NamedSharding(self.mesh, spec)
        cols = {}
        for n, c in table.columns.items():
            cols[n] = _replace(
                c, data=jax.device_put(c.data, sh),
                valid=None if c.valid is None else jax.device_put(c.valid, sh))
        return DeviceTable(cols, table.nrows, plen=table.plen)

    def create_temp_view(self, name: str, table, base: bool = False,
                         arrow=None) -> None:
        """Register a table. ``base=True`` marks a pristine base-table load
        (raw/columnar/warehouse readers), which lets the planner trust
        schema facts like primary-key uniqueness; any re-registration under
        the same name through a non-base path revokes the marker.
        ``arrow`` optionally passes the host-side source table so load-time
        statistics can be collected without any device->host read."""
        from nds_tpu.engine.table import ChunkedTable
        if isinstance(table, pa.Table):
            arrow = table if arrow is None else arrow
            table = from_arrow(table)
        key = name.lower()
        if isinstance(table, ChunkedTable):
            self.catalog[key] = table        # host-resident; never sharded
        else:
            self.catalog[key] = self._shard_table(table)
        if base and arrow is not None:
            self._collect_load_stats(key, arrow)
        if base:
            self.base_tables.add(key)
        else:
            self.base_tables.discard(key)
        # invalidate compiled replays: keys embed the version, so nothing
        # compiled before this mutation can ever hit again — clear all
        # three (the blacklist re-derives per data version)
        self._data_version += 1
        self._replay_cache.clear()
        self._replay_seen.clear()
        self._replay_blacklist.clear()

    def _collect_load_stats(self, key: str, arrow) -> None:
        """Load-time key statistics from HOST data (DESIGN.md item 2: one
        scan at load instead of a device->host sync at query time).

        Today this prewarms the dense-dimension position map for a table
        whose FIRST column is a unique dense integer key (every TPC-DS
        dimension PK is; ref: nds/nds_schema.py surrogate keys), so the
        first star join against it needs no whole-column device fetch."""
        import numpy as np
        t = self.catalog.get(key)
        if self.mesh is not None or not isinstance(t, DeviceTable) or \
                not t.columns:
            return
        first = next(iter(t.columns))
        col = t.columns[first]
        n = t.nrows if isinstance(t.nrows, int) else None
        if not n or n > (1 << 24) or col.kind == "str" or \
                first not in arrow.column_names:
            return
        src = arrow.column(first)
        if src.null_count or not pa.types.is_integer(src.type):
            return
        live = src.to_numpy(zero_copy_only=False).astype(np.int64)
        if len(live) != n:
            return
        mn = int(live.min())
        span = int(live.max()) - mn + 1
        # the same density gate _dense_dim_info applies at query time
        if span > max(4 * n, 1 << 16) or span > (1 << 26):
            return
        pos = np.full(span, n, dtype=np.int64)
        pos[live - mn] = np.arange(n)
        if int((pos != n).sum()) != n:
            return                            # duplicate keys: not a PK
        from nds_tpu.engine import ops as E
        import jax.numpy as jnp
        E._identity_cache(E._dense_dim_cache, 64, (col.data,),
                          lambda: (mn, jnp.asarray(pos)), static_key=n)

    def read_raw_view(self, name: str, path: str, fields) -> float:
        """Register a raw '|'-delimited table; returns elapsed seconds (the
        per-view creation timing in the reference's setup_tables;
        ref: nds/nds_power.py:79-106)."""
        from nds_tpu.io import read_raw_table
        start = time.perf_counter()
        arrow = read_raw_table(path, fields)
        canonical = {f.name: f.type for f in fields}
        self.create_temp_view(name, from_arrow(arrow, canonical), base=True,
                              arrow=arrow)
        return time.perf_counter() - start

    def read_columnar_view(self, name: str, path: str, fmt: str = "parquet",
                           canonical_types: dict | None = None) -> float:
        import os

        from nds_tpu.engine.table import ChunkedTable
        from nds_tpu.io import read_table
        start = time.perf_counter()
        arrow = read_table(path, fmt)
        # >HBM streaming decision: a table past the stream threshold stays
        # host-resident and is bound chunk-by-chunk by the planner (the
        # role of Spark's file splits; SURVEY.md §5.7). A meshed session
        # row-shards instead — the mesh multiplies device capacity.
        # float() first: operators write thresholds like "1.5e9"
        limit = int(float(self.conf.get(
            "stream_bytes",
            os.environ.get("NDS_TPU_STREAM_BYTES", str(8 << 30)))))
        if self.mesh is None and arrow.nbytes > limit:
            self.create_temp_view(
                name, ChunkedTable(arrow, canonical_types), base=True)
        else:
            self.create_temp_view(name, from_arrow(arrow, canonical_types),
                                  base=True, arrow=arrow)
        return time.perf_counter() - start

    # -- SQL ----------------------------------------------------------------

    def _replay_mode(self) -> str:
        """Replay policy: 'off' | 'auto' | 'on' | 'force'.

        Measured both ways on the tunneled chip (round 3): replayed
        queries floor at ~1 round trip, and for LOW-sync queries the
        pipelined eager stream is faster end to end — but every eager
        host sync pays a ~0.5-1s tunnel round trip, so HIGH-sync queries
        (q14 16 syncs, q28/q77 12) lose multiples of that. The default
        'auto' is the hybrid (round-4 verdict #4): a query records+replays
        only when its first-sight eager run counted more host syncs than
        NDS_TPU_REPLAY_SYNC_THR (default 6 — the reference pays one round
        trip per query, ref nds/nds_power.py:125-135); everything else
        stays eager. 'on'/'force' replay unconditionally (local-chip
        deployments), 'off' disables.
        """
        default = self.conf.get("replay")
        if default is None:
            # accelerator backends default to the hybrid: every eager host
            # sync pays the dispatch-path round trip there. CPU (the test
            # platform) stays off — XLA:CPU megaprogram compile sequences
            # are flaky on small hosts and tests opt in explicitly.
            import jax
            default = "off" if jax.default_backend() == "cpu" else "auto"
        env = os.environ.get("NDS_TPU_REPLAY", str(default))
        env = env.lower()
        if env in ("on", "1", "true"):
            return "on"
        if env == "force":
            return "force"
        if env == "auto":
            return "auto"
        return "off"

    def _replay_on(self) -> bool:
        return self._replay_mode() != "off"

    def _sync_threshold(self) -> int:
        return int(os.environ.get(
            "NDS_TPU_REPLAY_SYNC_THR",
            str(self.conf.get("replay_sync_threshold", 6))))

    def _replay_wanted(self, key) -> bool:
        """Should the 2nd sight of ``key`` record+compile a replay?"""
        mode = self._replay_mode()
        if mode in ("on", "force"):
            return True
        return self._replay_syncs.get(key, 0) > self._sync_threshold()

    def replay_pending(self, text: str) -> bool:
        """True if the next sql(text) would record or trace a replay
        program (drivers use this to fold the record/trace passes into
        warmup so timed passes measure steady state)."""
        key = (text, self._data_version)
        if self._replay_mode() == "off" or key in self._replay_blacklist:
            return False
        if key in self._replay_cache:
            hit = self._replay_cache[key]
            return bool(hit.first_run)
        return key in self._replay_seen and self._replay_wanted(key)

    def _sql_replay(self, text: str, stmt, planner) -> Result:
        """Trace-replay execution tiers (engine/replay.py): 1st sight of a
        query runs eagerly; 2nd records host decisions and compiles the
        whole pipeline into one XLA program; 3rd+ is one dispatch."""
        from nds_tpu.engine import ops as E
        from nds_tpu.engine import replay as R
        import time as _time
        key = (text, self._data_version)
        hit = self._replay_cache.get(key)
        if hit is not None:
            try:
                t0 = _time.perf_counter()
                out = hit.run(block=True)
                replay_s = _time.perf_counter() - t0
                # SELF-TUNING: a giant fused program is not always faster
                # than the pipelined eager stream (measured both ways on
                # the tunneled chip). Compare against the recorded eager
                # wall (both sides block-to-completion); two consecutive
                # slower runs evict the program and the query stays eager
                # for this data version. The FIRST hit pays the one-time
                # XLA compile and is excluded from strike accounting.
                if hit.first_run:
                    hit.first_run = False
                elif replay_s > hit.eager_s * 1.1:
                    hit.strikes += 1
                    if hit.strikes >= 2:
                        self._replay_cache.pop(key, None)
                        self._replay_blacklist.add(key)
                else:
                    hit.strikes = 0
                self.last_scanned = dict(hit.scan_bytes)
                return Result(out)
            except E.ReplayMismatch:
                # structural divergence: permanently unreplayable
                self._replay_cache.pop(key, None)
                self._replay_blacklist.add(key)
            except Exception as exc:
                # transient runtime failure (device preemption, transfer
                # error): surface it, keep the compiled program, fall back
                # eager for THIS execution only
                from nds_tpu.listener import report_task_failure
                report_task_failure(
                    "replayed query dispatch (one-off eager fallback)", exc)
        if key in self._replay_seen and key not in self._replay_blacklist \
                and key not in self._replay_cache \
                and self._replay_wanted(key):
            if not R.record_eligible(self, stmt):
                # binds a >HBM chunked scan: whole-query record/replay
                # never applies — its streaming is compiled one layer down
                # by the chunk pipeline (engine/stream.py, via
                # _stream_join_parts). Blacklisting stops replay_pending()
                # from advertising a record pass that will never happen.
                self._replay_blacklist.add(key)
            else:
                E.resolve_counts()   # stray pending counts must not enter
                t0 = _time.perf_counter()
                with _obs.span("replay.record"):
                    with E.recording() as log:
                        table = planner.query(stmt)
                # block to completion so eager_s is a true wall, comparable
                # to the blocked replay wall (async dispatch would
                # otherwise under-count the eager side and mis-tune the
                # eviction)
                import jax as _jax
                if table.columns:
                    _jax.block_until_ready(
                        next(iter(table.columns.values())).data)
                eager_s = _time.perf_counter() - t0
                # deferred SQL runtime checks from the record pass must
                # raise NOW: inside compile() they would be swallowed by
                # the blacklist handler below and the error lost for good
                E.flush_deferred_checks()
                try:
                    cq = R.CompiledQuery(self, stmt, log,
                                         R.out_template_of(table)).compile()
                    cq.scan_bytes = dict(planner.scanned)
                    cq.eager_s = eager_s
                    cq.strikes = 0
                    cq.first_run = True
                    self._replay_cache[key] = cq
                except Exception:
                    self._replay_blacklist.add(key)
                return Result(table)
        self._replay_seen.add(key)
        # first sight: count this query's eager host syncs — the signal
        # 'auto' mode gates recording on (fetch-time syncs land after the
        # return and are not counted; the threshold is calibrated for that)
        s0 = E.sync_count()
        out = Result(planner.query(stmt))
        self._replay_syncs[key] = E.sync_count() - s0
        return out

    def sql(self, text: str) -> Result:
        # scope this thread's trace ring (mirrors the thread-scoped
        # listener): a query-executing thread drains only its own spans
        _obs.attach()
        stmt = parse(text)
        planner = Planner(self.catalog, base_tables=self.base_tables)
        # roofline accounting: bytes of every catalog table the statement
        # binds (read by the Power Run's per-query summaries)
        self.last_scanned = planner.scanned
        from nds_tpu.engine import ops as E
        # statement-end barrier around EVERY dispatch path (not just
        # A.Query): CREATE TEMP VIEW ... AS SELECT, INSERT ... SELECT and
        # DELETE all run planner.query() and can register lazy
        # scalar-subquery checks; without the barrier those leak and raise
        # inside a later statement's first resolution (misattributed), and
        # a failed statement's half-registered checks mask its real error
        # per-statement watchdog scope (engine/faults.py): with
        # NDS_TPU_STATEMENT_DEADLINE_S armed, every blocking wait below
        # charges ONE shared statement budget — a hung sync or stuck
        # peer raises a classified StatementTimeout (drivers mark the
        # statement `timeout`) instead of hanging the process. Unset:
        # zero overhead.
        from nds_tpu.engine import faults as _F
        try:
            with _F.statement_scope():
                out = self._sql_dispatch(text, stmt, planner)
        except BaseException:
            E.discard_deferred_checks()
            raise
        E.flush_deferred_checks()
        return out

    def _sql_dispatch(self, text: str, stmt, planner) -> Result:
        if isinstance(stmt, A.Query):
            if self._replay_on():
                return self._sql_replay(text, stmt, planner)
            return Result(planner.query(stmt))
        if isinstance(stmt, A.CreateTempView):
            # route through create_temp_view so a meshed session re-shards
            # the view like every other catalog entry
            self.create_temp_view(stmt.name, planner.query(stmt.query))
            return Result(DeviceTable({}, 0))
        if isinstance(stmt, A.InsertInto):
            if self.warehouse is None:
                raise ExecError("INSERT requires an attached warehouse")
            rows = planner.query(stmt.query)
            self.warehouse.insert(stmt.table, rows.to_arrow())
            # route through create_temp_view so a meshed session re-shards
            # the refreshed table like every other catalog entry
            self.create_temp_view(stmt.table,
                                  from_arrow(self.warehouse.read(stmt.table)))
            return Result(DeviceTable({}, 0))
        if isinstance(stmt, A.DeleteFrom):
            if self.warehouse is None:
                raise ExecError("DELETE requires an attached warehouse")
            # evaluate the predicate against the current table; delete by mask
            import jax.numpy as jnp
            from nds_tpu.engine import ops as E
            table = self.catalog[stmt.table.lower()]
            aliased = planner._alias_table(table, stmt.table)
            if stmt.where is None:
                keep_mask = jnp.zeros(table.plen, dtype=bool)
            else:
                mask = planner._conjunct_mask(aliased,
                                              planner._split_conjuncts(stmt.where))
                keep_mask = ~mask
            # maintenance boundary: shrink eagerly — the kept table is
            # re-registered and written back, so tight buckets pay off
            kept = E.compact_table(table, keep_mask, shrink=True)
            self.warehouse.overwrite(stmt.table, kept.to_arrow())
            self.create_temp_view(stmt.table, kept)
            return Result(DeviceTable({}, 0))
        raise ExecError(f"unsupported statement {type(stmt).__name__}")
