# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Compiled streaming executor: sync-budgeted chunk pipeline for >HBM scans.

The eager chunk loop (``Planner._stream_join_parts``) re-plans the join
graph per chunk, and every chunk pays the per-chunk host syncs (join pair
sizing, adaptive compaction) — at SF10 that put 73 of 91 queries past the
<=6-sync budget the device-resident path holds (query37: 128 syncs). The
fix is the same one whole-query replay (engine/replay.py) applies to
device-resident queries, specialized to the streaming shape:

1. RECORD — run the join graph ONCE, eagerly, over the first padded chunk
   under ``ops.recording()`` + ``ops.stream_bounds()``. Stream-bounds mode
   forbids any chunk-data-dependent host decision (``StreamSyncError`` =>
   the query stays on the eager loop), so the only recorded host reads are
   chunk-INVARIANT dimension-side plans (dense key maps, key ranges) —
   which makes the log valid for every chunk, not just the recorded one.
2. COMPILE — re-run the same planner code under ``jax.jit`` with the
   chunk's device buffers (and every other part's columns) as arguments
   and ``ops.replaying(log)`` serving the recorded reads. Because
   ``ChunkedTable.padded_chunks`` pads every chunk (including the final
   partial one) to one fixed power-of-two capacity with a uniform pytree
   structure, the single traced program serves all chunks.
3. DRIVE — loop the chunks through that one executable with
   double-buffered host->device prefetch (chunk k+1 converts and uploads
   while chunk k's compute is in flight — dispatch is asynchronous, so
   issuing compute first overlaps the two), accumulating survivors into
   donated on-device buffers with a device-side running row count.
4. SYNC — one materializing host read at pipeline end fetches the
   survivor count plus the overflow flag. Overflow (a bound-sized pair
   bucket or the accumulator capacity ran out of room on some chunk) means
   rows were dropped on device: the result is discarded and the query
   re-runs through the eager loop, so streamability is only ever a
   performance property, never a correctness one.

The eager loop remains reachable as ``NDS_TPU_STREAM_EXEC=eager`` (escape
hatch) and as the automatic fallback for graphs that are not
chunk-invariant (cartesian layouts, exotic trace divergence).

MULTI-PASS streamed pipelines convert the formerly-eager shapes:

* **Subquery residuals** — a subquery nested in the graph's conjuncts is
  chunk-invariant once decorrelated, so the record phase plans the inner
  query FIRST (``Planner._residual_table`` under
  ``ops.suspend_stream_record()`` — the inner may use its own compiled
  pipeline; two pipelines chain with one materializing sync each) into a
  device-resident residual whose columns become ordinary jit operands of
  the per-chunk program. Cache hits re-plan the residuals per execution
  and shape-validate them against the compiled program.
* **Deferred outer joins** — an eligible LEFT join rides INTO the graph:
  ``_OuterProbe`` (chunked side preserved, ON keys = the probe side's
  PK) applies a sync-free per-chunk gather; ``_OuterBuild`` (chunked
  side null-introducing) emits per-dispatch matched pairs and ORs
  matched-build-row masks into an on-device unmatched-key accumulator —
  the outer extras emit once at materialize time, their counts riding
  the single materializing transfer.
* **Recorded chunk scalars** — ``ops.guarded_scalar_read`` replays a
  first-chunk host scalar for every chunk under a device-side staleness
  guard (mismatch ⇒ overflow flag ⇒ bit-for-bit eager rerun).

``NDS_TPU_STREAM_STRICT=1`` re-raises any record/trace failure that is
not a ``StreamSyncError``/``ReplayMismatch`` (the A/B tests and both
differential harnesses run strict); without it the fallback reason is
tagged with the exception class, so engine bugs stay auditable in
``streamedScans``.

Survivor accumulators are sized from the statement's PROVEN row bound
(the static memory model of ``nds_tpu/analysis/mem_audit.py``: schema PK
uniqueness + stream-fanout pair buckets), so a statement whose bound fits
the ``NDS_TPU_HBM_BYTES`` capacity model can never trip the overflow
rerun; unprovable or over-capacity bounds fall back to the legacy 2^23
guess.

PARTITIONED (grace-style) fan-out accumulation: a provable graph whose
whole-statement bound exceeds the capacity model — the q17-class fan-out
joins — is decomposed by join-key hash instead of falling back to the
legacy clamp. A second tiny jitted pass assigns every live chunk row a
partition id (multiplicative hash of the streamed slot's equi-join keys,
``mem_audit.stream_partition_keys``) and keeps a device-resident
partition histogram; the per-chunk join program gains the id vector and
a traced partition-id operand, masking the chunk to one partition before
the recorded graph runs (a lazy compact — same shapes, same replay log,
so ONE compiled program serves every (chunk, partition) pair). Each
partition accumulates into its OWN proof-sized accumulator
(``mem_audit.partition_row_bound`` — skew-conditional, ENFORCED by a
per-partition overflow flag), and the single materializing sync fetches
every partition's count + flag + the histogram in one transfer, so the
<=6-sync budget holds at any partition count. The partition count is
chosen statically from the proof (``mem_audit.choose_partitions``) and
joins the pipeline-cache key; partition count 1 is byte-for-byte
today's unpartitioned pipeline.

SHARDED execution (``NDS_TPU_STREAM_SHARDS`` > 1, with that many local
devices): the one compiled per-chunk program runs under ``shard_map``
over a 1-D device mesh — every padded chunk's row range splits
contiguously across the shards, dimension-side parts/operands/residuals
ride replicated (the broadcast-join side of the exchange choice), and
each shard accumulates survivors into its OWN proof-sized slice of the
donated accumulators (per-shard overflow flags enforce the per-shard
bound of ``mem_audit.shard_row_bound``). When the graph is ALSO
partitioned (fan-out joins — the case where a join's keys are not
co-partitioned with an arbitrary row split), a per-chunk EXCHANGE pass
hash-routes rows over ICI with the ``parallel/exchange.py`` all-to-all
primitives so each shard owns a key range (encoded codes ride the wire,
so the exchange moves the narrow representation); ``NDS_TPU_STREAM_
EXCHANGE=0`` keeps the local partition pass instead. ONE cross-shard
reduce (all-gather of per-shard counts + psum of overflow flags /
histogram / outer-build bitmaps) runs at the single materializing sync,
so the <=6-host-sync budget holds at any shard count and the explicit
collective count per pipeline pass is a static budget
(``exec_audit``), checked against the trace-time collective accounting
of ``parallel.exchange.collective_trace`` via ``StreamEvent.collectives``
/ ``bytes_ici``. Shard count 1 is byte-for-byte the single-device
pipeline.

Env knobs (all read at pipeline-BUILD time, never frozen at
import): ``NDS_TPU_STREAM_EXEC`` (compiled|eager),
``NDS_TPU_STREAM_ACC_ROWS`` (explicit hard accumulator ceiling / escape
hatch, applied per partition; unset = proof-sized),
``NDS_TPU_STREAM_FANOUT`` (ops.py: stream-mode join pair-bucket
allowance, default 4), ``NDS_TPU_HBM_BYTES`` (capacity model, default
16 GiB), ``NDS_TPU_STREAM_PARTITIONS`` (pin the partition count; unset =
proof-chosen, <=1 disables), ``NDS_TPU_STREAM_SKEW`` (hash-skew safety
factor of the per-partition and per-shard bounds, default 2),
``NDS_TPU_STREAM_SHARDS`` (mesh shard count; <=1 or too few local
devices = single-device), ``NDS_TPU_STREAM_MESH_AXIS`` (mesh axis name,
default ``shard``), ``NDS_TPU_STREAM_EXCHANGE`` (0 disables the
partitioned hash-exchange pass).

ASYNC INGEST (DESIGN.md "Async ingest"): all three drive loops and the
eager chunk loop pull chunks through the bounded prefetch ring of
``engine/prefetch.py`` (``NDS_TPU_PREFETCH_DEPTH``, default 2; 0 = the
inline pump, bit-for-bit the old loops): a worker thread runs the host
slice + narrow encode + async upload for upcoming chunks — sharded
runs place each shard's row slice on its own device inside the worker —
while the driver dispatches compute, and the driver's blocked-on-ring
time is measured per scan as ``StreamEvent.prefetch_stall_ms``. The
ring's extra live set (depth × chunk bytes) is priced off the admitting
capacity by every accumulator-sizing decision here and by
``mem_audit`` statically (the lockstep rule), and the depth joins the
pipeline-cache key. ``NDS_TPU_CHUNK_STORE`` points chunk production at
the persistent pre-encoded store (``io/chunk_store.py``): warm runs
mmap whole-table wire arrays instead of slicing arrow and re-planning
codecs.
"""

from __future__ import annotations

import logging
import os
import threading
import weakref

import jax
import jax.numpy as jnp

from nds_tpu.engine import exprs as _X
from nds_tpu.engine import faults as _F
from nds_tpu.engine import kernels as _K
from nds_tpu.engine import ops as E
from nds_tpu.engine import prefetch as _PF
from nds_tpu.engine.column import Column, slice_col_prefix
from nds_tpu.engine.table import DeviceTable
from nds_tpu.listener import record_stream_event
from nds_tpu.obs import metrics as _metrics
from nds_tpu.obs import trace as _obs

log = logging.getLogger(__name__)

# legacy survivor-accumulator row guess: the clamp applied only when the
# static memory proof cannot admit a bound (unprovable multiplicity, or a
# proven bound past the HBM capacity model). Provable statements size
# their accumulator from the proof instead (see _acc_row_budget), so a
# statement whose bound fits can never trip the overflow rerun.
_DEFAULT_ACC_ROWS = 1 << 23


def _acc_ceiling() -> int | None:
    """NDS_TPU_STREAM_ACC_ROWS: the explicit hard ceiling / escape hatch.
    Read at pipeline-BUILD time (not import) so tests and Throughput
    children that set it after import are honored."""
    env = os.environ.get("NDS_TPU_STREAM_ACC_ROWS")
    return int(env) if env else None


def _strict() -> bool:
    """NDS_TPU_STREAM_STRICT=1: re-raise any record/trace failure that is
    not a StreamSyncError/ReplayMismatch instead of converting it into an
    eager fallback — the mode both differential harnesses and the A/B
    tests run under, so a genuine engine bug can never hide behind the
    fallback's correctness guarantee."""
    return bool(os.environ.get("NDS_TPU_STREAM_STRICT"))


def _proved_plan(parts, keep, join_preds, where_conjuncts, sources, nrows):
    """``(proved_rows, k, part_keys)`` of the streamed graph, from the
    static memory model (analysis/mem_audit.py): the whole-statement
    survivor bound ``bucket(rows) x fanout^k`` (k = join batches with no
    PK-unique side), plus the chunk-side equi-key names a grace-style
    partition pass may hash on. Deferred outer joins (_OuterProbe /
    _OuterBuild) contribute their ON conjuncts and pristine sources —
    their PK-covered edges keep per-row multiplicity at <= 1 exactly like
    inner PK batches. ``(None, None, None)`` when unprovable (unconnected
    graph — a chunk-data-dependent cartesian layout the eager loop serves
    anyway)."""
    try:
        from nds_tpu.analysis.mem_audit import (stream_graph_fanout,
                                                stream_partition_keys,
                                                structural_row_bound)
        from nds_tpu.sql.planner import _OuterBuild, _OuterProbe
        part_cols = [{str(c).lower() for c in p.column_names}
                     for p in parts]
        srcs = list(sources)
        conj = list(join_preds) + list(where_conjuncts)
        for i, p in enumerate(parts):
            if isinstance(p, (_OuterProbe, _OuterBuild)):
                srcs[i] = p.src
                conj.extend(p.conjuncts)
        srcs = [s.lower() if isinstance(s, str) else None for s in srcs]
        k = stream_graph_fanout(part_cols, srcs, keep, conj)
        if k is None:
            return None, None, None
        return (structural_row_bound(int(nrows), k, E.stream_fanout()), k,
                stream_partition_keys(part_cols, srcs, keep, conj))
    except Exception:                    # never let the proof break a query
        return None, None, None


def _ring_bytes(chunk_nbytes: int) -> int:
    """Extra live bytes of the bounded prefetch ring: up to
    ``NDS_TPU_PREFETCH_DEPTH`` prepared chunks wait in the ring beyond
    the one the dispatch loop is consuming. Priced into every admission
    decision below (effective capacity = NDS_TPU_HBM_BYTES − ring) so
    turning the ring up can never size accumulators into memory the
    ring itself is holding — the lockstep twin of
    ``mem_audit.MemModel.ring_bytes``. Depth <= 0 (ring off) prices
    zero: bit-for-bit today's admission arithmetic."""
    return max(_PF.prefetch_depth(), 0) * max(int(chunk_nbytes), 0)


def _partition_plan(nrows, fan_k, part_keys, proved, row_bytes, n_chunks,
                    chunk_out_plen, ring_bytes=0):
    """``(n_partitions, per_partition_row_bound)`` for the pipeline being
    built: >1 only for a provable graph with chunk-side equi keys whose
    whole bound is past capacity (or when NDS_TPU_STREAM_PARTITIONS pins
    a count). Statically derived — it joins the pipeline-cache key via
    the env knobs + table rows. The partition TRIGGER mirrors
    mem_audit's rule shape: the accumulator the whole-graph proof would
    size — ``min(chunk-sum, structural)``, clamped by the env ceiling —
    is what gets compared against capacity (an explicit ceiling already
    pins the allocation, so capacity pressure never forces a partition
    pass under it). ``ring_bytes`` — the prefetch ring's live set —
    comes off the capacity side."""
    if fan_k is None or not part_keys or proved is None:
        return 1, None
    try:
        from nds_tpu.analysis.mem_audit import (choose_partitions,
                                                stream_partitions_env)
        forced = stream_partitions_env()
        bound = min(n_chunks * chunk_out_plen, proved)
        ceiling = _acc_ceiling()
        if ceiling is not None:
            bound = min(bound, ceiling)
        cap = max(_hbm_bytes() - ring_bytes, 1)
        need = bound * row_bytes > cap
        if not need and (forced is None or forced <= 1):
            return 1, None
        return choose_partitions(int(nrows), fan_k, E.stream_fanout(),
                                 row_bytes, cap, forced=forced)
    except Exception:
        return 1, None


def _acc_row_budget(n_chunks, chunk_out_plen, proved, row_bytes,
                    ring_bytes=0):
    """Rows the survivor accumulator is sized for. Always bounded by the
    per-chunk-bucket sum (each chunk contributes at most its output
    bucket); the proof tightens it. The env ceiling, when set, stays a
    hard clamp (overflow then reruns eagerly — correctness never depends
    on the proof); without one, a bound the capacity model cannot admit
    falls back to the legacy guess. ``ring_bytes`` (prefetch live set)
    shrinks the admitting capacity."""
    rows = n_chunks * chunk_out_plen
    if proved is not None:
        rows = min(rows, proved)
    ceiling = _acc_ceiling()
    if ceiling is not None:
        return min(rows, ceiling)
    if proved is None or \
            rows * row_bytes > max(_hbm_bytes() - ring_bytes, 1):
        return min(rows, _DEFAULT_ACC_ROWS)
    return rows


def _part_acc_budget(n_chunks, chunk_out_plen, part_bound, row_bytes,
                     n_parts, ring_bytes=0):
    """Per-partition accumulator rows. The per-partition proof admits the
    bound by construction (choose_partitions), but every partition's
    accumulator is live until the single materializing sync, so the
    TOTAL allocation is additionally clamped to the capacity model —
    past it, actual survivors beyond the clamp trip the per-partition
    overflow flag and rerun eagerly (a perf fallback, never a
    correctness one). The env ceiling stays a hard per-partition clamp;
    the prefetch ring's live set comes off the capacity side."""
    rows = n_chunks * chunk_out_plen
    if part_bound is not None:
        rows = min(rows, part_bound)
    share = max(_hbm_bytes() - ring_bytes, 1) // max(n_parts * row_bytes,
                                                     1)
    rows = min(rows, max(share, chunk_out_plen))
    ceiling = _acc_ceiling()
    if ceiling is not None:
        rows = min(rows, ceiling)
    return rows


def _hbm_bytes() -> int:
    try:
        from nds_tpu.analysis.mem_audit import hbm_capacity_bytes
        return hbm_capacity_bytes()
    except Exception:
        return 16 << 30


def _shard_plan(chunk_cap: int):
    """``(n_shards, mesh, axis)`` of the pipeline being built: >1 only
    when ``NDS_TPU_STREAM_SHARDS`` asks for a power-of-two count this
    process can serve (enough local devices, chunk capacity divisible).
    Statically derived — the count joins the pipeline-cache key via the
    env knob."""
    try:
        from nds_tpu.analysis.mem_audit import stream_shards_env
        from nds_tpu.parallel.exchange import stream_mesh, stream_mesh_axis
        n = stream_shards_env()
        if n <= 1 or chunk_cap % n or chunk_cap // n < 1:
            return 1, None, None
        mesh = stream_mesh(n)
        if mesh is None:
            return 1, None, None
        return n, mesh, stream_mesh_axis()
    except Exception:
        return 1, None, None

# compiled pipelines are cached across statements (a Power Run executes
# each query text 2-4 times); bounded FIFO, identity-validated on hit.
# Mutations take the lock: concurrent Throughput streams share the cache.
# A miss goes through the _PIPELINE_BUILDS singleflight registry
# (key -> Event of the thread currently compiling that shape): waiters
# block OFF-lock and take the winner's entry, so concurrent first sights
# of one shape cost exactly ONE compile — and the compile itself never
# runs under the lock (it would serialize every Throughput stream; the
# conc-audit `compile-under-lock` rule rejects the pattern statically).
_PIPELINE_CACHE: dict = {}
_PIPELINE_MAX = 64
_PIPELINE_LOCK = threading.Lock()
_PIPELINE_BUILDS: dict = {}
# per-shape successful-compile counts (guarded by _PIPELINE_LOCK): the
# evidence tools/conc_audit_diff.py's exactly-one-compile check reads.
_PIPELINE_BUILD_COUNTS: dict = {}


def pipeline_build_counts() -> dict:
    """Snapshot of per-shape compile counts since process start (or the
    last :func:`reset_pipeline_cache`)."""
    with _PIPELINE_LOCK:
        return dict(_PIPELINE_BUILD_COUNTS)


def reset_pipeline_cache() -> None:
    """Drop the pipeline cache and the compile counters (test/harness
    helper: a cold-cache differential needs a known-empty start)."""
    with _PIPELINE_LOCK:
        _PIPELINE_CACHE.clear()
        _PIPELINE_BUILD_COUNTS.clear()


class _NotStreamable(Exception):
    """The recorded join graph made a chunk-data-dependent host decision
    (or its trace diverged); the caller falls back to the eager loop."""


def _restore_counts(snapshot, checks_snapshot):
    """Drop DeviceCounts/deferred checks created by a record or trace
    attempt: their values belong to a discarded execution, and left in the
    pending list they would cost (or poison) a later batched resolve."""
    lst = E._pending_counts()
    lst[:] = [c for c in lst if any(c is s for s in snapshot)]
    E._sync_tls.checks = [
        (c, f) for c, f in (getattr(E._sync_tls, "checks", None) or [])
        if any(c is s for s in checks_snapshot)]


def _flatten_part(part: DeviceTable):
    """(spec, flat) for one non-streamed part: spec is static metadata
    (names, kinds, dictionaries, valid presence, logical count, physical
    length), flat the device buffers in spec order."""
    spec, flat = [], []
    nrows = E.count_int(part.nrows)   # resolved up front by the caller
    for name in part.column_names:
        c = part[name]
        spec.append((name, c.kind, c.dict_values, c.valid is not None,
                     c.enc))
        flat.append(c.data)
        if c.valid is not None:
            flat.append(c.valid)
    return (tuple(spec), nrows, part.plen), flat


def _rebuild_part(spec, flat):
    (cols_spec, nrows, plen) = spec
    cols, i = {}, 0
    for name, kind, dv, has_valid, enc in cols_spec:
        data = flat[i]
        i += 1
        valid = None
        if has_valid:
            valid = flat[i]
            i += 1
        cols[name] = Column(kind, data, valid, dv, enc)
    return DeviceTable(cols, nrows, plen=plen)


def _chunk_signature(chunk: DeviceTable, alias: str):
    """Static chunk metadata: aliased names (the per-chunk program sees the
    chunk as the planner's FROM-alias binding), kinds, dictionaries, and
    narrow encodings (host metadata baked into the trace, so a pipeline
    compiled for one encoding must never serve another)."""
    spec = []
    for name in chunk.column_names:
        c = chunk[name]
        aliased = f"{alias.lower()}.{name.split('.')[-1].lower()}"
        spec.append((aliased, c.kind, c.dict_values, c.enc))
    return tuple(spec)


_LOGICAL_WIDTHS = {"i32": 4, "date": 4, "bool": 1, "f64": 8, "str": 4}


def _logical_chunk_bytes(chunk_spec, chunk_cap, n_chunks) -> int:
    """Unencoded upload bytes the same padded chunks WOULD have moved
    (wide device widths + the validity byte) — the denominator of the
    compression win tools/trace_report.py prices against bytesH2d."""
    per_row = sum(_LOGICAL_WIDTHS.get(k, 8) + 1
                  for (_n, k, _dv, _en) in chunk_spec)
    return per_row * chunk_cap * max(n_chunks, 0)


# THE partition/shard routing hash, shared with the fused Pallas scan
# kernel (engine/kernels.hash_mix) so both arms route rows identically —
# per-partition evidence must be bit-for-bit between NDS_TPU_PALLAS arms
_hash_mix = _K.hash_mix


class StreamPipeline:
    """One compiled per-chunk program plus the metadata to drive it.

    ``n_partitions`` > 1 turns on grace-style partitioned accumulation:
    ``key_slots`` index the chunk's flattened buffers that the partition
    hash folds (the streamed slot's equi-join keys), the per-chunk
    program takes the per-row partition ids plus a traced partition-id
    scalar and masks the chunk before the recorded graph runs, and
    ``run`` keeps one proof-sized accumulator per partition — all
    fetched in the single materializing sync."""

    def __init__(self, chunk_spec, chunk_cap, part_specs, keep, log_entries,
                 operands, out_template, acc_cap, part_refs,
                 n_partitions=1, key_slots=(), outer_meta=(),
                 residuals=(), resid_specs=(), build_slots=(),
                 name_catalog=None, n_shards=1, mesh=None,
                 mesh_axis="shard", exchange=False, cap_ex=0,
                 scan_spec=None, param_nodes=(), param_tags=()):
        self.chunk_spec = chunk_spec      # ((aliased name, kind, dict), ...)
        self.chunk_cap = chunk_cap
        self.part_specs = part_specs      # specs of non-streamed parts
        self.keep = keep
        self.log = log_entries
        self.operands = operands
        self.out_template = out_template  # (names, kinds, dicts, valided)
        self.acc_cap = acc_cap
        # weakrefs to the part buffers, compared by identity on cache hit:
        # a dead ref or different object is a miss (bare id() ints could
        # collide after address reuse), and weakrefs don't pin dropped
        # tables' device memory for the cache entry's lifetime
        self.part_refs = part_refs
        self.n_partitions = n_partitions
        self.key_slots = tuple(key_slots)
        # multi-pass streaming metadata: per non-keep part, None or the
        # deferred-outer-join marker ("probe"/"build", condition AST,
        # conjunct ASTs, src); subquery residuals as (registry key,
        # replan payload) plus their flattened specs (validated against a
        # fresh replan on every cache hit); build_slots index the
        # part_specs whose unmatched-key bitmaps the accumulator carries
        self.outer_meta = tuple(outer_meta)
        self.residuals = tuple(residuals)
        self.resid_specs = tuple(resid_specs)
        self.build_slots = tuple(build_slots)
        self.name_catalog = dict(name_catalog or {})
        # sharded execution: the per-chunk program runs under shard_map
        # over this 1-D local-device mesh; acc_cap is then the PER-SHARD
        # accumulator capacity. ``exchange`` turns on the per-chunk
        # hash-exchange pass (partitioned graphs), with ``cap_ex`` the
        # per-(source shard, destination) bucket capacity.
        self.n_shards = n_shards
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        self.exchange = exchange
        self.cap_ex = cap_ex
        # per-shard physical chunk length the compiled program sees
        self.body_plen = chunk_cap if n_shards == 1 else \
            (n_shards * cap_ex if exchange else chunk_cap // n_shards)
        # fused Pallas chunk-scan pass (DESIGN.md "Fused chunk kernels"):
        # the chunk-invariant predicate/codec spec extracted at record
        # time (engine/exprs.lower_scan_spec); None = XLA chain only
        self.scan_spec = scan_spec
        # parameter binding (DESIGN.md "Parameterized plans"): the build
        # statement's audited-bindable Literal AST nodes, kept alive here
        # so their id()s stay stable for the compiled program's lifetime.
        # At dispatch, each execution's literal VALUES ride as extra jit
        # operands appended after ``operands``; the traced body peels
        # them off and installs the exprs.param_binding the planner's
        # Literal arm consults. Slot ORDER is the cache key's slot
        # signature order — a hit is guaranteed to agree.
        self.param_nodes = tuple(param_nodes)
        self.param_tags = tuple(param_tags)
        self.jitted = None
        self._pid_jit = None
        self._scan_jit = None
        self._exch_jit = None
        self._reduce_jit = None
        # explicit-collective accounting per compiled program, captured
        # at trace time (parallel.exchange.collective_trace) on the first
        # dispatch — the runtime evidence of the static collective budget
        self.coll_chunk = None
        self.coll_exchange = None
        self.coll_reduce = None
        # fused-kernel launch accounting, captured the same trace-time
        # way (kernels.kernel_trace): launches per scan pre-pass / per
        # chunk-program dispatch — the evidence exec_audit's static
        # kernel prediction is checked against
        self.kern_scan = None
        self.kern_chunk = None
        # first jitted dispatch traces+compiles the per-chunk program;
        # the trace layer labels that dispatch "stream.compile"
        self.traced_once = False

    # ------------------------------------------------------------- compile

    def compile(self, join_preds, where_conjuncts, sources):
        from nds_tpu.sql.planner import Planner, _OuterBuild, _OuterProbe
        chunk_spec, chunk_cap = self.chunk_spec, self.chunk_cap
        part_specs, keep = self.part_specs, self.keep
        rec_log, operands = self.log, self.operands
        names, kinds, dicts, valided, dtypes, encs = self.out_template
        acc_cap = self.acc_cap
        base_sources = list(sources)
        n_partitions, key_slots = self.n_partitions, self.key_slots
        outer_meta = self.outer_meta
        residual_keys = tuple(k for (k, _p) in self.residuals)
        resid_specs = self.resid_specs
        n_builds = len(self.build_slots)
        name_cat = self.name_catalog
        param_nodes, param_tags = self.param_nodes, self.param_tags
        n_params = len(param_nodes)

        body_plen = self.body_plen

        def traced(chunk_flat, n_dev, parts_flat, ops_flat, acc,
                   resid_flat, pids=None, part_id=None, live=None):
            acc_datas, acc_valids, acc_n, acc_ovf, acc_outer = acc
            cols, i = {}, 0
            for (aname, kind, dv, cenc) in chunk_spec:
                cols[aname] = Column(kind, chunk_flat[i], chunk_flat[i + 1],
                                     dv, cenc)
                i += 2
            chunk = DeviceTable(cols, E.DeviceCount(n_dev, body_plen),
                                plen=body_plen)
            mask = live
            if pids is not None:
                pm = pids == part_id
                mask = pm if mask is None else (mask & pm)
            if mask is not None:
                # partition/exchange mask BEFORE the recorded graph: a
                # lazy compact keeps the chunk's physical shape and bound
                # (plen=body_plen), so the recorded host-read log stays
                # valid for every (chunk, partition, shard) combination.
                # Under its own stream-bounds region: at production chunk
                # sizes (plen > NDS_TPU_LAZY_SHRINK_ROWS) compact_table's
                # adaptive resolve would otherwise host-sync on a tracer
                # and silently divert the whole pipeline to eager
                with E.stream_bounds():
                    chunk = E.compact_table(chunk, mask)
            sub, pi = [], 0
            for j in range(len(part_specs) + 1):
                if j == keep:
                    sub.append(chunk)
                    continue
                t = _rebuild_part(part_specs[pi], parts_flat[pi])
                meta = outer_meta[pi] if pi < len(outer_meta) else None
                if meta is not None:
                    mk, mcond, mconjs, msrc = meta
                    t = (_OuterProbe if mk == "probe" else _OuterBuild)(
                        t, mcond, list(mconjs), msrc)
                sub.append(t)
                pi += 1
            # a fresh planner with an EMPTY catalog: the per-chunk program
            # must close over no device-resident state (a cached pipeline
            # would pin it for process lifetime). Subquery residuals are
            # pre-planned DEVICE OPERANDS: the registry is seeded from the
            # pipeline's residual arguments, so the subquery eval arms
            # consume them without ever touching a catalog
            pl = Planner({}, base_tables=set())
            pl.name_catalog = name_cat
            for rkey, rspec, rflat in zip(residual_keys, resid_specs,
                                          resid_flat):
                pl._subquery_residuals[rkey] = (
                    None, _rebuild_part(rspec, rflat))
            # audited-bindable literal operands ride at the END of
            # ops_flat (appended per execution by run); peel them off so
            # the replay log sees exactly its recorded operand count, and
            # install the binding the planner's Literal arm consults —
            # the bound conjuncts then trace against operand Columns
            # instead of baking this execution's values as constants
            bindings = {}
            if n_params:
                params = ops_flat[-n_params:]
                ops_flat = ops_flat[:-n_params]
                bindings = {id(nd): (tag, v) for nd, tag, v
                            in zip(param_nodes, param_tags, params)}
            with _X.param_binding(bindings):
                with E.replaying(rec_log, ops_flat):
                    with E.stream_bounds() as sb:
                        with E.outer_match_collector() as omc:
                            out = pl._join_parts(sub, list(join_preds),
                                                 list(where_conjuncts),
                                                 list(base_sources))
                        flags = list(sb.flags)
                        matched = list(omc.masks)
            if list(out.column_names) != list(names):
                raise E.ReplayMismatch(
                    "streamed trace produced a different output schema "
                    "than the recording")
            if len(matched) != n_builds:
                raise E.ReplayMismatch(
                    "streamed trace registered a different outer-build "
                    "mask count than the recording")
            out_n = E.count_arr(out.nrows)
            live = jnp.arange(out.plen) < out_n
            pos = jnp.where(live, acc_n + jnp.arange(out.plen), acc_cap)
            new_datas, new_valids = [], []
            for j, n in enumerate(names):
                c = out[n]
                new_datas.append(
                    acc_datas[j].at[pos].set(c.data, mode="drop"))
                if valided[j]:
                    new_valids.append(
                        acc_valids[j].at[pos].set(c.valid_mask(),
                                                  mode="drop"))
                else:
                    new_valids.append(acc_valids[j])
            new_n = acc_n + out_n
            ovf = acc_ovf | (new_n > acc_cap)
            for f in flags:
                ovf = ovf | f
            new_outer = tuple(b | m for b, m in zip(acc_outer, matched))
            return (tuple(new_datas), tuple(new_valids), new_n, ovf,
                    new_outer)

        # donate the accumulators: the pipeline's working set stays
        # (chunk in flight) + (chunk uploading) + ONE accumulator copy
        # per partition (the partition mask routes each dispatch to its
        # own accumulator, donated through)
        scan_spec = self.scan_spec
        # the Pallas mode is a pipeline-cache-key member, so freezing it
        # at compile time is consistent with the program's lifetime
        interp = _K._pallas_mode() == "interpret"
        if self.n_shards == 1:
            self.jitted = jax.jit(traced, donate_argnums=(4,))

            if n_partitions > 1:
                P = n_partitions

                if scan_spec is not None:
                    def scanpid_fn(chunk_flat, n_dev, hist):
                        # ONE fused VMEM pass: predicates + partition
                        # hash; the histogram keeps its pre-filter
                        # semantics (counts every LIVE row, not just
                        # predicate survivors — part_input evidence is
                        # identical between Pallas arms)
                        mask, h = _K.fused_chunk_scan(chunk_flat, n_dev,
                                                      scan_spec, interp)
                        pids = (h & jnp.uint32(P - 1)).astype(jnp.int32)
                        live = jnp.arange(chunk_cap) < n_dev
                        counts = jnp.bincount(jnp.where(live, pids, P),
                                              length=P + 1)[:P]
                        return (mask, pids,
                                hist + counts.astype(hist.dtype))

                    self._scan_jit = jax.jit(scanpid_fn,
                                             donate_argnums=(2,))
                    return self

                def pid_fn(chunk_flat, n_dev, hist):
                    h = jnp.full((chunk_cap,), 2166136261, dtype=jnp.uint32)
                    for s in key_slots:
                        h = _hash_mix(h, chunk_flat[s])
                    pids = (h & jnp.uint32(P - 1)).astype(jnp.int32)
                    live = jnp.arange(chunk_cap) < n_dev
                    counts = jnp.bincount(jnp.where(live, pids, P),
                                          length=P + 1)[:P]
                    return pids, hist + counts.astype(hist.dtype)

                # the extra jitted partition pass: per-row partition ids +
                # the device-resident input histogram (donated through) —
                # no host syncs anywhere in it
                self._pid_jit = jax.jit(pid_fn, donate_argnums=(2,))
            elif scan_spec is not None:
                def scan_fn(chunk_flat, n_dev):
                    mask, _h = _K.fused_chunk_scan(chunk_flat, n_dev,
                                                   scan_spec, interp)
                    return mask

                self._scan_jit = jax.jit(scan_fn)
            return self

        # ---- sharded compile: the SAME traced body under shard_map ----
        from jax.sharding import PartitionSpec as PSpec
        from nds_tpu.parallel.exchange import shard_map_compat
        S, axis = self.n_shards, self.mesh_axis
        shard_plen = body_plen
        contiguous = not self.exchange
        row, rep = PSpec(axis), PSpec()

        def shard_body(chunk_flat, n_dev, parts_flat, ops_flat, acc,
                       resid_flat, pids, part_id, live):
            # contiguous row split: shard s owns rows [s*plen, (s+1)*plen)
            # of the chunk, so its live count derives from the global one
            # (no collective). Exchanged chunks carry liveness in ``live``
            # instead — every physical slot is in range, the mask decides.
            if contiguous:
                s = jax.lax.axis_index(axis).astype(jnp.int64)
                n_local = jnp.clip(n_dev - s * shard_plen, 0, shard_plen)
            else:
                n_local = jnp.asarray(shard_plen, dtype=jnp.int64)
            return traced(chunk_flat, n_local, parts_flat, ops_flat, acc,
                          resid_flat, pids, part_id, live)

        # accumulators are row-sharded (each shard scatters into its own
        # acc_cap slice); un-valided columns keep their replicated scalar
        # placeholder. Parts/operands/residuals ride replicated — the
        # broadcast-join side of the exchange choice.
        acc_spec = (tuple(row for _ in names),
                    tuple(row if v else rep for v in valided),
                    row, row, tuple(row for _ in self.build_slots))
        in_specs = (row, rep, rep, rep, acc_spec, rep, row, rep, row)
        sm = shard_map_compat(shard_body, self.mesh, in_specs, acc_spec)
        self.jitted = jax.jit(sm, donate_argnums=(4,))

        if self.exchange:
            self._exch_jit = self._make_exchange()
        elif n_partitions > 1:
            P = n_partitions

            if scan_spec is not None:
                def scanpid_fn(chunk_flat, n_dev, hist):
                    s = jax.lax.axis_index(axis).astype(jnp.int64)
                    n_local = jnp.clip(n_dev - s * shard_plen, 0,
                                       shard_plen)
                    mask, h = _K.fused_chunk_scan(chunk_flat, n_local,
                                                  scan_spec, interp)
                    pids = (h & jnp.uint32(P - 1)).astype(jnp.int32)
                    live = jnp.arange(shard_plen) < n_local
                    counts = jnp.bincount(jnp.where(live, pids, P),
                                          length=P + 1)[:P]
                    return (mask, pids,
                            hist + counts.astype(hist.dtype).reshape(
                                hist.shape))

                sm_scan = shard_map_compat(scanpid_fn, self.mesh,
                                           (row, rep, row),
                                           (row, row, row))
                self._scan_jit = jax.jit(sm_scan, donate_argnums=(2,))
                self._reduce_jit = self._make_reduce()
                return self

            def pid_fn(chunk_flat, n_dev, hist):
                s = jax.lax.axis_index(axis).astype(jnp.int64)
                n_local = jnp.clip(n_dev - s * shard_plen, 0, shard_plen)
                h = jnp.full((shard_plen,), 2166136261, dtype=jnp.uint32)
                for ks in key_slots:
                    h = _hash_mix(h, chunk_flat[ks])
                pids = (h & jnp.uint32(P - 1)).astype(jnp.int32)
                live = jnp.arange(shard_plen) < n_local
                counts = jnp.bincount(jnp.where(live, pids, P),
                                      length=P + 1)[:P]
                return pids, hist + counts.astype(hist.dtype).reshape(
                    hist.shape)

            sm_pid = shard_map_compat(pid_fn, self.mesh,
                                      (row, rep, row), (row, row))
            self._pid_jit = jax.jit(sm_pid, donate_argnums=(2,))
        elif scan_spec is not None:
            def scan_fn(chunk_flat, n_dev):
                s = jax.lax.axis_index(axis).astype(jnp.int64)
                n_local = jnp.clip(n_dev - s * shard_plen, 0, shard_plen)
                mask, _h = _K.fused_chunk_scan(chunk_flat, n_local,
                                               scan_spec, interp)
                return mask

            sm_scan = shard_map_compat(scan_fn, self.mesh, (row, rep),
                                       row)
            self._scan_jit = jax.jit(sm_scan)
        self._reduce_jit = self._make_reduce()
        return self

    def _make_exchange(self):
        """Jitted per-chunk hash-EXCHANGE pass of a sharded partitioned
        pipeline: each shard hashes its contiguous row slice on the
        graph's equi keys (the same hash the partition ids use), packs
        rows into per-destination-shard buckets, and the
        ``parallel/exchange.py`` all-to-all routes them so every shard
        owns a key range — the repartition a join needs when its keys
        are not co-partitioned with the arbitrary upload split. Returns
        the exchanged buffers + validity + partition ids + the updated
        per-shard histogram and overflow flag (a bucket past ``cap_ex``
        drops rows on device ⇒ the flag forces the eager rerun). No host
        syncs anywhere in it; its collectives are counted at trace time
        against the static budget."""
        from jax.sharding import PartitionSpec as PSpec
        from nds_tpu.parallel.exchange import (all_to_all_exchange,
                                               shard_map_compat)
        S, P = self.n_shards, self.n_partitions
        axis = self.mesh_axis
        shard_plen = self.chunk_cap // S
        cap_ex = self.cap_ex
        key_slots = self.key_slots
        pshift = max(P.bit_length() - 1, 0)      # partition ids use the
        #                                          low bits; shard routing
        #                                          the next log2(S) bits

        scan_spec = self.scan_spec
        interp = _K._pallas_mode() == "interpret"

        def exch_body(chunk_flat, n_dev, hist, ovf):
            s = jax.lax.axis_index(axis).astype(jnp.int64)
            n_local = jnp.clip(n_dev - s * shard_plen, 0, shard_plen)
            alive = jnp.arange(shard_plen) < n_local
            if scan_spec is not None:
                # fused scan pass INSIDE the exchange: predicates + the
                # routing hash in one VMEM pass; rows failing a lowered
                # predicate dead-route (never cross the wire). The
                # histogram keeps counting every alive row — part_input
                # evidence stays identical between Pallas arms.
                mask, h = _K.fused_chunk_scan(chunk_flat, n_local,
                                              scan_spec, interp)
            else:
                mask = alive
                h = jnp.full((shard_plen,), 2166136261, dtype=jnp.uint32)
                for ks in key_slots:
                    h = _hash_mix(h, chunk_flat[ks])
            pids = (h & jnp.uint32(P - 1)).astype(jnp.int32)
            hist = hist + jnp.bincount(jnp.where(alive, pids, P),
                                       length=P + 1)[:P].astype(
                hist.dtype).reshape(hist.shape)
            dest = jnp.where(
                mask,
                ((h >> pshift) & jnp.uint32(S - 1)).astype(jnp.int32),
                jnp.int32(S))                    # dead rows route past S
            order = jnp.argsort(dest)
            sd = jnp.take(dest, order)
            first = jnp.searchsorted(sd, sd, side="left")
            pos = jnp.arange(shard_plen) - first
            fits = (pos < cap_ex) & (sd < S)
            counts = jax.ops.segment_sum(
                (sd < S).astype(jnp.int32), sd, num_segments=S + 1)[:S]
            over = jnp.any(counts > cap_ex)
            valid = jnp.zeros((S, cap_ex), dtype=bool).at[sd, pos].set(
                fits, mode="drop")
            bufs = {}
            for i, buf in enumerate(chunk_flat):
                if buf is None:
                    continue
                v = jnp.take(buf, order)
                bufs[str(i)] = jnp.zeros(
                    (S, cap_ex), dtype=buf.dtype).at[sd, pos].set(
                    jnp.where(fits, v, jnp.zeros((), dtype=buf.dtype)),
                    mode="drop")
            pv = jnp.take(pids, order)
            bufs["pids"] = jnp.zeros(
                (S, cap_ex), dtype=pids.dtype).at[sd, pos].set(
                jnp.where(fits, pv, jnp.zeros((), dtype=pids.dtype)),
                mode="drop")
            ex, vex = all_to_all_exchange(bufs, valid, axis)
            out_flat = tuple(
                ex[str(i)].reshape(-1) if b is not None else None
                for i, b in enumerate(chunk_flat))
            return (out_flat, vex.reshape(-1), ex["pids"].reshape(-1),
                    hist, ovf | over.reshape(ovf.shape))

        row, rep = PSpec(axis), PSpec()
        sm = shard_map_compat(exch_body, self.mesh,
                              (row, rep, row, row),
                              (row, row, row, row, row))
        return jax.jit(sm, donate_argnums=(2, 3))

    def _make_reduce(self):
        """THE one cross-shard reduce of a sharded pipeline, fused at the
        single materializing sync: all-gather of per-shard survivor
        counts, psum of the per-shard overflow flags and the partition
        histogram, and a psum-OR of each outer-build bitmap (build rows
        matched by ANY shard of ANY partition are matched) — replicated
        outputs, so the following host fetch is one plain transfer. Its
        collectives are counted at trace time against the static
        budget."""
        from jax.sharding import PartitionSpec as PSpec
        from nds_tpu.parallel.exchange import (all_gather_counted,
                                               psum_counted,
                                               shard_map_compat)
        axis = self.mesh_axis
        build_meta = [(self.part_specs[s][1], self.part_specs[s][2])
                      for s in self.build_slots]

        def body(ns, flags, hist, *bitmaps):
            counts = all_gather_counted(ns, axis, tiled=True)     # (S, P)
            ovf = psum_counted(flags.astype(jnp.int32), axis)[0]  # (P,)
            hist_tot = psum_counted(hist, axis)[0]                # (P,)
            outs = [counts, ovf, hist_tot]
            for (n_live, plen), bm in zip(build_meta, bitmaps):
                matched = psum_counted(bm.astype(jnp.int32),
                                       axis)[0] > 0               # (plen,)
                miss = ~matched & (jnp.arange(plen) < n_live)
                outs.append(miss)
                outs.append(jnp.sum(miss))
            return tuple(outs)

        row, rep = PSpec(axis), PSpec()
        sm = shard_map_compat(
            body, self.mesh,
            (row, row, row) + tuple(row for _ in build_meta),
            tuple(rep for _ in range(3 + 2 * len(build_meta))))
        return jax.jit(sm)

    # ---------------------------------------------------------------- run

    def _flatten_chunk(self, chunk: DeviceTable):
        flat = []
        for name in chunk.column_names:
            c = chunk[name]
            flat.append(c.data)
            flat.append(c.valid)
        return tuple(flat)

    def _prepare_chunk(self, chunk: DeviceTable):
        """The per-chunk host work the prefetch ring runs OFF the driver
        thread: flatten the padded chunk's buffers (the jnp conversion
        inside ``padded_chunks`` already queued the async upload), stamp
        the live count, and account the actual h2d bytes. NO host reads,
        NO spans — the ``host-sync-in-prefetch-worker`` contract (padded
        chunks carry a plain-int live count, so no DeviceCount resolve
        is ever needed here)."""
        _F.fault_point("device-put")       # upload seam (transient;
        #                                    recovered by the prefetch
        #                                    ring's bounded retry)
        flat = self._flatten_chunk(chunk)
        n_dev = jnp.asarray(int(chunk.nrows), dtype=jnp.int64)
        h2d = sum(int(x.nbytes) for x in flat if x is not None)
        return flat, n_dev, h2d

    def _prepare_chunk_sharded(self, chunk: DeviceTable):
        """Sharded twin of :meth:`_prepare_chunk`: additionally places
        each shard's row slice on its own device (row-sharded
        ``device_put``) INSIDE the worker, so the h2d uploads fan out
        across the mesh off the driver thread instead of funneling
        through one inline upload."""
        from jax.sharding import NamedSharding, PartitionSpec as PSpec
        _F.fault_point("device-put")
        row = NamedSharding(self.mesh, PSpec(self.mesh_axis))
        flat = self._flatten_chunk(chunk)
        n_dev = jnp.asarray(int(chunk.nrows), dtype=jnp.int64)
        h2d = sum(int(x.nbytes) for x in flat if x is not None)
        flat = tuple(None if x is None else jax.device_put(x, row)
                     for x in flat)
        return flat, n_dev, h2d

    def _first_kern(self, attr, call):
        """Capture trace-time fused-kernel launch counts on the first
        (tracing) dispatch of one compiled program — the same pattern
        the sharded path uses for collectives: a kernel traced into a
        jit program launches once per dispatch, so the counts are exact
        per-dispatch evidence at zero runtime cost."""
        if getattr(self, attr) is None:
            with _K.kernel_trace() as kc:
                out = call()
            setattr(self, attr, dict(kc))
            return out
        return call()

    def _kernel_evidence(self, n_chunks: int, dispatches: int) -> dict:
        """StreamEvent kernel evidence of one drive: total fused-kernel
        launches (scan pre-pass per chunk + probes per chunk-program
        dispatch) and the per-launch fused stage count of the scan
        spec — the numbers tools/exec_audit_diff.py checks against the
        static prediction."""
        ks = (self.kern_scan or {}).get("launches", 0)
        kc = (self.kern_chunk or {}).get("launches", 0)
        return {"kernel_launches": ks * n_chunks + kc * dispatches,
                "kernel_stages": self.scan_spec.stages()
                if self.scan_spec is not None else 0}

    def init_acc(self):
        names, kinds, dicts, valided, dtypes, encs = self.out_template
        if self.n_shards > 1:
            return self._init_acc_sharded()
        datas, valids = [], []
        for j, dtype in enumerate(dtypes):
            datas.append(jnp.zeros(self.acc_cap, dtype=dtype))
            valids.append(jnp.zeros(self.acc_cap, dtype=bool)
                          if valided[j] else jnp.zeros((), dtype=bool))
        outer = tuple(jnp.zeros(self.part_specs[s][2], dtype=bool)
                      for s in self.build_slots)
        return (tuple(datas), tuple(valids),
                jnp.asarray(0, dtype=jnp.int64), jnp.asarray(False), outer)

    def _init_acc_sharded(self):
        """Sharded accumulators: every array is row-sharded over the
        mesh, so each shard owns its ``acc_cap`` slice (datas), its count
        and overflow slot, and its outer-build bitmap row — donated
        through every dispatch like the single-device accumulator."""
        from jax.sharding import NamedSharding, PartitionSpec as PSpec
        names, kinds, dicts, valided, dtypes, encs = self.out_template
        S = self.n_shards
        row = NamedSharding(self.mesh, PSpec(self.mesh_axis))
        rep = NamedSharding(self.mesh, PSpec())
        datas, valids = [], []
        for j, dtype in enumerate(dtypes):
            datas.append(jax.device_put(
                jnp.zeros(S * self.acc_cap, dtype=dtype), row))
            valids.append(jax.device_put(
                jnp.zeros(S * self.acc_cap, dtype=bool), row)
                if valided[j]
                else jax.device_put(jnp.zeros((), dtype=bool), rep))
        outer = tuple(jax.device_put(
            jnp.zeros((S, self.part_specs[s][2]), dtype=bool), row)
            for s in self.build_slots)
        return (tuple(datas), tuple(valids),
                jax.device_put(jnp.zeros((S,), dtype=jnp.int64), row),
                jax.device_put(jnp.zeros((S,), dtype=bool), row), outer)

    def _outer_miss(self, bitmaps):
        """(miss mask, device miss count) per outer-build slot: build
        rows no dispatch matched — the outer extras. The counts ride the
        single materializing transfer; the masks stay on device for the
        extras gather."""
        out = []
        for slot, bm in zip(self.build_slots, bitmaps):
            _spec, n_live, plen = self.part_specs[slot]
            miss = ~bm & (jnp.arange(plen) < n_live)
            out.append((miss, jnp.sum(miss)))
        return out

    def run(self, chunks, first_chunk, parts_flat, resid_flat=(),
            params=()):
        """Drive every chunk through the compiled program; returns
        ``(survivor DeviceTable | None-on-overflow, n_chunks, evidence)``
        (overflow => the caller re-runs eagerly). ``evidence`` carries the
        partition counts of a partitioned run and the outer-extras
        masks/counts of deferred outer-build joins. ``chunks`` continues
        AFTER ``first_chunk`` (already converted). ``params`` — THIS
        execution's bound-literal operand values, slot order (passed
        per call, never stored: concurrent cache-hit executions share
        the pipeline object)."""
        if self.n_shards > 1:
            return _run_sharded(self, chunks, first_chunk, parts_flat,
                                resid_flat, params)
        if self.n_partitions > 1:
            return self._run_partitioned(chunks, first_chunk, parts_flat,
                                         resid_flat, params)
        ops = self.operands + tuple(params)
        acc = self.init_acc()
        # bounded prefetch ring (engine/prefetch.py): a worker thread
        # runs the host slice + encode + async upload for upcoming
        # chunks while the driver below dispatches compute — depth 0
        # (NDS_TPU_PREFETCH_DEPTH=0) degrades to the inline pump, bit
        # for bit the old drive loop. The first chunk was already
        # converted by the record phase, so it prepares inline.
        ring = _PF.chunk_ring(chunks, prepare=self._prepare_chunk)
        n_chunks = 0
        h2d = 0
        try:
            # the first chunk prepares INLINE (the record phase already
            # converted it): same bounded-retry policy as the ring's
            # worker, on the driver (the device-put transient seam)
            cur = _F.with_retry(
                "device-put", lambda: self._prepare_chunk(first_chunk))
            while cur is not None:
                flat, n_dev, nb = cur
                # actual host->device prefetch bytes (buffer metadata,
                # no sync): encoded columns upload their NARROW form
                h2d += nb
                # asynchronous dispatch: the compiled call returns
                # immediately, so the ring's conversion of upcoming
                # chunks overlaps this chunk's device compute. The first
                # dispatch of a fresh pipeline traces+compiles the
                # per-chunk program; the span names that cost so the
                # compile-vs-drive split is visible per chunk.
                live = None
                if self._scan_jit is not None:
                    # the fused Pallas pre-pass: one VMEM-resident launch
                    # evaluates every lowered predicate; the chunk program
                    # consumes the survivor mask as a lazy compact. Device-
                    # only by construction (zero host syncs — the span's
                    # delta is cross-checked by tools/exec_audit_diff.py)
                    with _obs.span("stream.kernel", chunk=n_chunks):
                        live = self._first_kern(
                            "kern_scan",
                            lambda f=flat, nd=n_dev: self._scan_jit(f, nd))
                phase = "stream.drive" if self.traced_once \
                    else "stream.compile"
                with _obs.span(phase, chunk=n_chunks):
                    acc = self._first_kern(
                        "kern_chunk",
                        lambda a=acc, f=flat, nd=n_dev, lv=live:
                        self.jitted(f, nd, parts_flat, ops, a,
                                    resid_flat, live=lv))
                self.traced_once = True
                n_chunks += 1
                # stall span: driver time BLOCKED on the ring for the
                # next chunk (ring off: the inline slice+upload). Only
                # real fetches record a span, labeled with the chunk
                # they fetch; the end-of-stream probe drops its span.
                with _obs.span("stream.prefetch", chunk=n_chunks) as sp:
                    cur = ring.next_chunk()
                    if cur is None:
                        sp.drop()
            stall_ms = ring.stall_ms()
        finally:
            ring.close()
        datas, valids, n_dev, ovf, bitmaps = acc
        miss = self._outer_miss(bitmaps)

        def fetch():
            got = jax.device_get([n_dev, ovf] + [n for (_m, n) in miss])
            return (int(got[0]), bool(got[1]),
                    [int(x) for x in got[2:]])

        # THE one materializing sync of the pipeline (outer-extras counts
        # ride the same transfer)
        with _obs.span("stream.materialize", chunks=n_chunks):
            total, overflowed, extras_n = E.timed_read("stream_final",
                                                       fetch)
        evidence = {"h2d": h2d, "stall_ms": stall_ms,
                    "outer": [(slot, m, n) for (slot, (m, _nd), n)
                              in zip(self.build_slots, miss, extras_n)],
                    **self._kernel_evidence(n_chunks, n_chunks)}
        if overflowed:
            return None, n_chunks, evidence
        return self._slice_acc(datas, valids, total), n_chunks, evidence

    def _slice_acc(self, datas, valids, total):
        """Survivor prefix of one accumulator as a DeviceTable."""
        names, kinds, dicts, valided, dtypes, encs = self.out_template
        cap = E.bucket_len(total)
        cols = {}
        for j, n in enumerate(names):
            col = Column(kinds[j], datas[j],
                         valids[j] if valided[j] else None, dicts[j],
                         encs[j])
            cols[n] = slice_col_prefix(col, cap) if cap < self.acc_cap \
                else col
        return DeviceTable(cols, total, plen=min(cap, self.acc_cap))

    def _run_partitioned(self, chunks, first_chunk, parts_flat,
                         resid_flat=(), params=()):
        """Grace-style drive: each chunk uploads ONCE, the partition pass
        assigns row partition ids (histogram stays device-resident), and
        the one compiled program dispatches once per partition into that
        partition's own donated accumulator. Chunk-major order keeps the
        double-buffered prefetch; partition-major survivor order is
        row-order-independent downstream (joins/filters/aggregation
        distribute over union). ONE materializing sync fetches every
        partition's count + overflow flag + the input histogram (+ any
        outer-extras counts: per-partition unmatched-key bitmaps OR
        together first — a build row matched by ANY partition of ANY
        chunk is matched)."""
        P = self.n_partitions
        ops = self.operands + tuple(params)
        accs = [self.init_acc() for _ in range(P)]
        hist = jnp.zeros(P, dtype=jnp.int64)
        pid_consts = [jnp.asarray(p, dtype=jnp.int32) for p in range(P)]
        ring = _PF.chunk_ring(chunks, prepare=self._prepare_chunk)
        n_chunks = 0
        h2d = 0
        try:
            cur = _F.with_retry(
                "device-put", lambda: self._prepare_chunk(first_chunk))
            while cur is not None:
                flat, n_dev, nb = cur
                h2d += nb
                mask = None
                if self._scan_jit is not None:
                    # fused pass: predicates + partition ids + histogram
                    # in ONE VMEM launch (replaces the XLA radix pass)
                    with _obs.span("stream.kernel", chunk=n_chunks,
                                   partitions=P):
                        mask, pids, hist = self._first_kern(
                            "kern_scan",
                            lambda f=flat, nd=n_dev, h=hist:
                            self._scan_jit(f, nd, h))
                else:
                    with _obs.span("stream.partition", chunk=n_chunks,
                                   partitions=P):
                        pids, hist = self._pid_jit(flat, n_dev, hist)
                for p in range(P):
                    phase = "stream.drive" if self.traced_once \
                        else "stream.compile"
                    with _obs.span(phase, chunk=n_chunks, part=p):
                        accs[p] = self._first_kern(
                            "kern_chunk",
                            lambda a=accs[p], f=flat, nd=n_dev, pv=pids,
                            pc=pid_consts[p], lv=mask:
                            self.jitted(f, nd, parts_flat, ops,
                                        a, resid_flat, pids=pv,
                                        part_id=pc, live=lv))
                    self.traced_once = True
                n_chunks += 1
                with _obs.span("stream.prefetch", chunk=n_chunks) as sp:
                    cur = ring.next_chunk()
                    if cur is None:
                        sp.drop()
            stall_ms = ring.stall_ms()
        finally:
            ring.close()

        bitmaps = [accs[0][4][j] for j in range(len(self.build_slots))]
        for p in range(1, P):
            bitmaps = [b | accs[p][4][j] for j, b in enumerate(bitmaps)]
        miss = self._outer_miss(bitmaps)

        def fetch():
            got = jax.device_get([a[2] for a in accs]
                                 + [a[3] for a in accs] + [hist]
                                 + [n for (_m, n) in miss])
            return ([int(x) for x in got[:P]],
                    [bool(x) for x in got[P:2 * P]],
                    [int(x) for x in got[2 * P]],
                    [int(x) for x in got[2 * P + 1:]])

        # still THE one materializing sync: P counts + P flags + the
        # histogram (+ extras counts) ride one transfer
        with _obs.span("stream.materialize", chunks=n_chunks,
                       partitions=P):
            totals, overflowed, hist_host, extras_n = E.timed_read(
                "stream_final", fetch)
        evidence = {"partitions": P, "part_rows": tuple(totals),
                    "part_input": tuple(hist_host), "h2d": h2d,
                    "stall_ms": stall_ms,
                    "outer": [(slot, m, n) for (slot, (m, _nd), n)
                              in zip(self.build_slots, miss, extras_n)],
                    **self._kernel_evidence(n_chunks, n_chunks * P)}
        if any(overflowed):
            return None, n_chunks, evidence
        tables = [self._slice_acc(accs[p][0], accs[p][1], totals[p])
                  for p in range(P) if totals[p] > 0]
        if not tables:                   # every partition empty
            out = self._slice_acc(accs[0][0], accs[0][1], 0)
        elif len(tables) == 1:
            out = tables[0]
        else:
            # counts are host-known here, so the union costs no sync
            out = E.concat_tables(tables)
        return out, n_chunks, evidence


def _run_sharded(pipe, chunks, first_chunk, parts_flat, resid_flat=(),
                 params=()):
    """Mesh-sharded drive (any partition count): every chunk uploads
    ROW-SHARDED over the local-device mesh, dimension parts / replay
    operands / residuals ride replicated, and the one shard_map'd
    compiled program dispatches per partition into per-shard donated
    accumulators. Partitioned graphs route rows first — the hash-
    EXCHANGE pass (parallel/exchange.py all-to-alls, so each shard owns
    a key range) or the local partition pass under
    ``NDS_TPU_STREAM_EXCHANGE=0``. ONE cross-shard reduce at the single
    materializing sync fetches every (shard, partition) count, overflow
    flag, the histogram and any outer-extras — the <=6-sync budget holds
    at any shard count, and the explicit collectives are accounted at
    trace time against the static budget."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as PSpec
    from nds_tpu.parallel.exchange import collective_trace
    S, P = pipe.n_shards, pipe.n_partitions
    row = NamedSharding(pipe.mesh, PSpec(pipe.mesh_axis))
    rep = NamedSharding(pipe.mesh, PSpec())

    def put_rep(x):
        return None if x is None else jax.device_put(x, rep)

    parts_rep = tuple(tuple(put_rep(x) for x in p) for p in parts_flat)
    resid_rep = tuple(tuple(put_rep(x) for x in p) for p in resid_flat)
    # bound-literal operands ride replicated like the replay operands
    ops_rep = tuple(put_rep(x) for x in pipe.operands + tuple(params))
    accs = [pipe.init_acc() for _ in range(P)]
    hist = jax.device_put(jnp.zeros((S, P), dtype=jnp.int64), row)
    ex_ovf = jax.device_put(jnp.zeros((S,), dtype=bool), row)
    pid_consts = [jnp.asarray(p, dtype=jnp.int32) for p in range(P)]

    def first_traced(coll_attr, call):
        """Dispatch; capture the program's trace-time collective counts
        on its first (tracing) call."""
        if getattr(pipe, coll_attr) is None:
            with collective_trace() as ct:
                out = call()
            setattr(pipe, coll_attr, dict(ct.counts))
            return out
        return call()

    # sharded prefetch ring: the worker places each shard's row slice on
    # its OWN device (row-sharded device_put inside _prepare_chunk_
    # sharded), so the h2d bandwidth scales with the mesh instead of
    # funneling through one inline upload on the driver thread
    ring = _PF.chunk_ring(chunks, prepare=pipe._prepare_chunk_sharded)
    n_chunks = 0
    h2d = 0
    try:
        cur = _F.with_retry(
            "device-put", lambda: pipe._prepare_chunk_sharded(first_chunk))
        while cur is not None:
            flat, n_dev, nb = cur
            h2d += nb
            pids = live = None
            if pipe.exchange:
                # collective-dispatch seam (degradable): an injected
                # exchange fault propagates to stream_execute, which
                # degrades the statement to the single-device eager
                # rerun and records the FaultEvent
                _F.fault_point("exchange")
                with _obs.span("stream.exchange", chunk=n_chunks,
                               shards=S, partitions=P):
                    flat, live, pids, hist, ex_ovf = first_traced(
                        "coll_exchange",
                        lambda f=flat, nd=n_dev, h=hist, o=ex_ovf:
                        pipe._first_kern("kern_scan",
                                         lambda: pipe._exch_jit(f, nd,
                                                                h, o)))
            elif pipe._scan_jit is not None and P > 1:
                with _obs.span("stream.kernel", chunk=n_chunks,
                               partitions=P, shards=S):
                    live, pids, hist = pipe._first_kern(
                        "kern_scan",
                        lambda f=flat, nd=n_dev, h=hist:
                        pipe._scan_jit(f, nd, h))
            elif pipe._scan_jit is not None:
                with _obs.span("stream.kernel", chunk=n_chunks,
                               shards=S):
                    live = pipe._first_kern(
                        "kern_scan",
                        lambda f=flat, nd=n_dev: pipe._scan_jit(f, nd))
            elif P > 1:
                with _obs.span("stream.partition", chunk=n_chunks,
                               partitions=P, shards=S):
                    pids, hist = pipe._pid_jit(flat, n_dev, hist)
            for p in range(P):
                phase = "stream.drive" if pipe.traced_once \
                    else "stream.compile"
                args = (flat, n_dev, parts_rep, ops_rep, accs[p],
                        resid_rep, pids,
                        pid_consts[p] if P > 1 else None, live)
                with _obs.span(phase, chunk=n_chunks, part=p):
                    accs[p] = first_traced(
                        "coll_chunk",
                        lambda a=args: pipe._first_kern(
                            "kern_chunk", lambda: pipe.jitted(*a)))
                pipe.traced_once = True
            n_chunks += 1
            with _obs.span("stream.prefetch", chunk=n_chunks) as sp:
                cur = ring.next_chunk()
                if cur is None:
                    sp.drop()
        stall_ms = ring.stall_ms()
    finally:
        ring.close()

    # one cross-shard reduce, one materializing transfer
    ns = jnp.stack([a[2] for a in accs], axis=1)          # (S, P)
    flags = jnp.stack([a[3] for a in accs], axis=1)       # (S, P)
    flags = flags | ex_ovf[:, None]
    bitmaps = []
    for j in range(len(pipe.build_slots)):
        bm = accs[0][4][j]
        for p in range(1, P):
            bm = bm | accs[p][4][j]
        bitmaps.append(bm)

    with _obs.span("stream.materialize", chunks=n_chunks, shards=S,
                   partitions=P):
        outs = first_traced("coll_reduce",
                            lambda: pipe._reduce_jit(ns, flags, hist,
                                                     *bitmaps))
        got = E.timed_read("stream_final",
                           lambda: jax.device_get(list(outs)))
    counts = np.asarray(got[0], dtype=np.int64)           # (S, P)
    ovf_host = [int(x) for x in np.asarray(got[1]).ravel()]
    hist_host = [int(x) for x in np.asarray(got[2]).ravel()]
    extras_pairs = list(zip(outs[3::2], [int(x) for x in got[4::2]]))

    def ops_of(c):
        return (c["a2a"] + c["psum"] + c["all_gather"]) if c else 0

    def bytes_of(c):
        return c["bytes"] if c else 0

    dispatches = n_chunks * P
    collectives = (ops_of(pipe.coll_chunk) * dispatches
                   + ops_of(pipe.coll_exchange) * n_chunks
                   + ops_of(pipe.coll_reduce))
    bytes_ici = (bytes_of(pipe.coll_chunk) * dispatches
                 + bytes_of(pipe.coll_exchange) * n_chunks
                 + bytes_of(pipe.coll_reduce))
    evidence = {"h2d": h2d, "shards": S, "stall_ms": stall_ms,
                "shard_rows": tuple(int(x) for x in counts.sum(axis=1)),
                "collectives": collectives, "bytes_ici": bytes_ici,
                "outer": [(slot, m, n) for (slot, (m, n)) in
                          zip(pipe.build_slots, extras_pairs)],
                **pipe._kernel_evidence(n_chunks, dispatches)}
    if P > 1:
        evidence["partitions"] = P
        evidence["part_rows"] = tuple(int(x) for x in counts.sum(axis=0))
        evidence["part_input"] = tuple(hist_host)
    if any(ovf_host):
        return None, n_chunks, evidence
    tables = [_slice_acc_sharded(pipe, accs[p][0], accs[p][1],
                                 counts[:, p])
              for p in range(P) if counts[:, p].sum() > 0]
    if not tables:                       # every shard of every partition
        out = _slice_acc_sharded(pipe, accs[0][0], accs[0][1],
                                 np.zeros(S, dtype=np.int64))
    elif len(tables) == 1:
        out = tables[0]
    else:
        # counts are host-known here, so the union costs no sync
        out = E.concat_tables(tables)
    return out, n_chunks, evidence


def _slice_acc_sharded(pipe, datas, valids, shard_counts):
    """Survivor rows of one sharded accumulator as a DeviceTable: shard
    ``s``'s survivors live at ``[s*acc_cap, s*acc_cap + count_s)`` of the
    row-sharded arrays — counts are host-known after the materializing
    transfer, so the gather index builds on host and the device gather
    costs no sync. Pad rows zero out, matching the zero-initialized
    accumulator padding of the single-device path."""
    import numpy as np
    names, kinds, dicts, valided, dtypes, encs = pipe.out_template
    counts = [int(c) for c in shard_counts]
    total = sum(counts)
    cap = E.bucket_len(total)
    idx_host = np.concatenate(
        [np.arange(c, dtype=np.int64) + s * pipe.acc_cap
         for s, c in enumerate(counts)] + [np.zeros(0, np.int64)])
    idx = jnp.asarray(np.concatenate(
        [idx_host, np.zeros(cap - total, np.int64)]))
    live = jnp.arange(cap) < total
    cols = {}
    for j, n in enumerate(names):
        d = jnp.take(datas[j], idx, mode="clip")
        d = jnp.where(live, d, jnp.zeros((), dtype=d.dtype))
        v = None
        if valided[j]:
            v = jnp.take(valids[j], idx, mode="clip") & live
        cols[n] = Column(kinds[j], d, v, dicts[j], encs[j])
    return DeviceTable(cols, total, plen=cap)


def _weak(x):
    """weakref.ref when the buffer supports it; a strong closure otherwise
    (plain ndarrays aren't weakref-able) — callers just call the ref."""
    try:
        return weakref.ref(x)
    except TypeError:
        return lambda obj=x: obj


def _dicts_equal(a, b) -> bool:
    import numpy as np
    if a is None or b is None:
        return a is b
    return a is b or np.array_equal(a, b)


def _param_bind_active() -> bool:
    """Parameter binding is ON by default (``NDS_TPU_PARAM_BIND=0`` is
    the escape hatch) but always OFF under the fused-kernel arm: the
    Pallas scan specs bake comparison thresholds into their lowered
    predicate entries on host, so a bound operand could never reach
    them — rather than splitting conjuncts between arms, the kernel arm
    keeps today's bake-everything behaviour (both modes are cache-key
    members, so the arms never share an entry)."""
    return os.environ.get("NDS_TPU_PARAM_BIND", "1") != "0" \
        and not _K.scan_kernels_active()


def _param_slots(planner, parts, keep, where_conjuncts, chunk_spec):
    """Audited-bindable slots of THIS statement's WHERE conjuncts:
    ``((conjunct index, field path, typetag, Literal node), ...)`` in
    deterministic walk order. Ownership mirrors ``_build_pipeline``'s
    ``owned()`` exactly — ``planner._expr_tables`` owners == {keep} —
    so a slot can only come from a conjunct the planner evaluates
    purely over chunk columns in-trace. The classification rule itself
    (comparand positions, type tags, safe domains) is
    ``analysis/param_audit.conjunct_bind_slots`` — the ONE rule the
    static auditor proves corpus-wide and the diff harness locks."""
    from nds_tpu.analysis.param_audit import (conjunct_bind_slots,
                                              drift_active)
    names_keep = {nm for (nm, _k, _dv, _en) in chunk_spec}
    sub_cols = [names_keep if i == keep else set(p.column_names)
                for i, p in enumerate(parts)]
    all_cols = set().union(*sub_cols)
    drift = drift_active()
    slots = []
    for ci, c in enumerate(where_conjuncts):
        has_sub = planner._has_subquery(c)
        owned = False
        if not has_sub:
            tabs = planner._expr_tables(c, all_cols)
            owners = set()
            for p_i, pc in enumerate(sub_cols):
                for t in tabs:
                    if any(cc.startswith(t + ".") for cc in pc):
                        owners.add(p_i)
            owned = owners == {keep}
        for (path, node, tag) in conjunct_bind_slots(
                c, owned, has_sub, drift=drift):
            slots.append((ci, path, tag, node))
    return tuple(slots)


def _param_operands(bind_slots):
    """This execution's bound-literal operand values, slot order —
    device-typed scalars (a Python int would re-trace as a weak type)."""
    from nds_tpu.analysis.param_audit import slot_param_value
    out = []
    for (_ci, _path, tag, node) in bind_slots:
        v = slot_param_value(node.value, tag)
        out.append(jnp.asarray(
            v, dtype=jnp.float64 if tag == "f64" else jnp.int64))
    return tuple(out)


def _cache_key(alias, keep, join_preds, where_conjuncts, sources,
               part_infos, chunk_spec, chunk_cap, stream_rows, outer_meta,
               bind_slots=()):
    from nds_tpu.analysis.mem_audit import (stream_partitions_env,
                                            stream_shards_env,
                                            stream_skew_factor)
    from nds_tpu.analysis.param_audit import skeleton_conjunct_key
    from nds_tpu.engine.column import enc_key
    from nds_tpu.sql.parser import expr_key
    # audited-bindable conjuncts key on their template SKELETON (literal
    # values become typed placeholders): K parameter vectors of one
    # template collapse onto one entry, one compile. The slot signature
    # rides alongside — two statements only share an entry when their
    # bindable slots line up exactly (count, position, operand type).
    by_conj = {}
    for (ci, path, tag, node) in bind_slots:
        by_conj.setdefault(ci, []).append((path, node, tag))
    return (
        tuple(expr_key(c) for c in join_preds),
        tuple(skeleton_conjunct_key(c, by_conj[i]) if i in by_conj
              else expr_key(c)
              for i, c in enumerate(where_conjuncts)),
        tuple((ci, path, tag) for (ci, path, tag, _n) in bind_slots),
        # bind/drift mode are key members read AT KEY TIME (conc-audit
        # cache-key completeness): flipping either can never serve a
        # pipeline compiled under the other mode
        os.environ.get("NDS_TPU_PARAM_BIND", "1"),
        os.environ.get("NDS_TPU_PARAM_DRIFT"),
        keep, tuple(sources), alias.lower(), chunk_cap,
        tuple((n, k, enc_key(en)) for (n, k, _dv, en) in chunk_spec),
        tuple(((tuple((cn, ck, hv, enc_key(en))
                      for (cn, ck, _dv, hv, en) in spec[0]),
                spec[1], spec[2]))
              for (spec, _flat) in part_infos),
        # deferred outer joins are part of the compiled program's shape
        tuple((m[0], expr_key(m[1]), m[3]) if m else None
              for m in outer_meta),
        # accumulator-sizing knobs: a pipeline built under a different
        # ceiling/capacity/fanout/partitioning must not be reused (its
        # compiled acc shapes bake the old budget in), and the streamed
        # table's row count feeds both the proof and the static
        # partition count
        _acc_ceiling(), _hbm_bytes(), E.stream_fanout(),
        stream_partitions_env(), stream_skew_factor(), int(stream_rows),
        # the prefetch ring's depth shapes the admission arithmetic
        # (effective capacity = HBM − depth × chunk bytes), which sizes
        # the compiled accumulator shapes — a depth change must MISS
        _PF.prefetch_depth(),
        # sharded-execution knobs: a pipeline compiled for one mesh shape
        # (or exchange mode) must never serve another
        stream_shards_env(), os.environ.get("NDS_TPU_STREAM_EXCHANGE"),
        os.environ.get("NDS_TPU_STREAM_MESH_AXIS"),
        # fused-kernel arm: a pipeline whose conjuncts were split into a
        # Pallas scan spec must never serve the XLA-only arm (and vice
        # versa) — the spec itself derives from conjuncts + encodings,
        # both already key members
        _K.scan_kernels_active(), _K._pallas_mode(),
        # read-at-use engine knobs reachable from the traced per-chunk
        # program (cache-key completeness, enforced statically by
        # analysis/conc_audit.py): pair-bucket budget and group-pack
        # threshold shape the compiled join/group plan; the kernel
        # eligibility budgets pick which segment implementation traces;
        # lazy-shrink is stream-gated off but keyed anyway — the key is
        # the ONE place a knob change is allowed to surface.
        E.pair_budget(), E.group_pack_min(), E.lazy_shrink_rows(),
        _K.max_groups(), _K.exact_onehot_budget(),
    )


def _spec_match(a, b) -> bool:
    """Structural equality of two flattened-part specs (names, kinds,
    validity presence, logical count, physical length, dictionary
    CONTENT) — the test a freshly replanned subquery residual must pass
    before a cached pipeline (whose program baked the old residual's
    shapes and recorded reads) may serve it."""
    from nds_tpu.engine.column import encs_equal
    (ac, an, ap), (bc, bn, bp) = a, b
    if an != bn or ap != bp or len(ac) != len(bc):
        return False
    for (n1, k1, d1, v1, e1), (n2, k2, d2, v2, e2) in zip(ac, bc):
        if n1 != n2 or k1 != k2 or v1 != v2 or not _dicts_equal(d1, d2) \
                or not encs_equal(e1, e2):
            return False
    return True


def _replan_residuals(planner, pipe):
    """Cache-hit path: re-plan every subquery residual for THIS execution
    (its data may have changed) and flatten the results as pipeline
    operands. Returns the flattened infos, or None when any residual's
    shape no longer matches the cached program (caller rebuilds). The
    replanned tables also seed the statement planner's registry, so an
    eventual eager fallback reuses them instead of re-planning per
    chunk."""
    infos = []
    for (rkey, payload), want in zip(pipe.residuals, pipe.resid_specs):
        rt = E.resolve_table(planner._plan_residual(payload))
        planner._subquery_residuals[rkey] = (payload, rt)
        spec, flat = _flatten_part(rt)
        if not _spec_match(spec, want):
            return None
        infos.append((spec, flat))
    return infos


def _resolve_residuals(planner, key, pipe):
    """Per-EXECUTION residual replan for a validated cache hit:
    ``(pipe, resid_infos)`` ready to run, or ``(None, ())`` on residual
    shape drift (the stale entry is evicted under the lock — the caller
    rebuilds). Replan failures PROPAGATE: a device OOM or planner bug
    while re-planning a subquery residual must never be mistaken for an
    unkeyable statement. Shared by the fast path and the singleflight
    waiters."""
    if not pipe.residuals:
        return pipe, ()
    got = _replan_residuals(planner, pipe)
    if got is None:
        with _PIPELINE_LOCK:
            if _PIPELINE_CACHE.get(key) is pipe:
                _PIPELINE_CACHE.pop(key, None)
                _PIPELINE_BUILD_COUNTS.pop(key, None)
        _metrics.default().inc(_metrics.PIPE_EVICT)
        return None, ()
    return pipe, got


def _cache_hit(key, chunk_spec, part_infos):
    pipe = _PIPELINE_CACHE.get(key)
    if pipe is None:
        return None
    # identity-validate part buffers (a maintenance refresh swaps them:
    # the recorded dimension-side host reads would be stale) and
    # content-validate chunk dictionaries (a re-registered streamed table
    # re-encodes; same shapes, different value tables). A stale entry can
    # never hit again — evict it now rather than waiting for FIFO churn.
    from nds_tpu.engine.column import encs_equal
    flat_now = [x for (_spec, flat) in part_infos for x in flat]
    then = [r() for r in pipe.part_refs]
    stale = len(flat_now) != len(then) or \
        any(b is None or a is not b for a, b in zip(flat_now, then)) or \
        any(not _dicts_equal(dv_now, dv_then)
            or not encs_equal(en_now, en_then)
            for (_, _, dv_now, en_now), (_, _, dv_then, en_then)
            in zip(chunk_spec, pipe.chunk_spec))
    if stale:
        with _PIPELINE_LOCK:
            if _PIPELINE_CACHE.get(key) is pipe:
                _PIPELINE_CACHE.pop(key, None)
                _PIPELINE_BUILD_COUNTS.pop(key, None)
        _metrics.default().inc(_metrics.PIPE_EVICT)
        return None
    return pipe


def stream_execute(planner, parts, keep, join_preds, where_conjuncts,
                   sources):
    """Execute a join graph whose ``keep``-th part is a ``_StreamedScan``
    through the compiled chunk pipeline. Returns ``(table, None)`` on
    success, or ``(None, reason)`` when the graph is not streamable /
    overflowed — the caller (``Planner._stream_join_parts``) falls back
    to the eager chunk loop and records the eager StreamEvent AFTER that
    loop, so its syncs cover the whole fallback path, not just the failed
    compile attempt. A ``(None, None)`` return means fall back silently
    (no event)."""
    if E.replay_mode() != "off":
        # never nest inside whole-query record/replay: the pipeline's own
        # recording would interleave with the outer log
        return None, None
    from nds_tpu.sql.planner import _OuterBuild, _OuterProbe
    scan = parts[keep]
    chunked, alias = scan.chunked, scan.alias
    syncs0 = E.sync_count()

    # resolve every non-streamed part's count up front (one batched
    # transfer, usually free): part counts are per-statement constants of
    # the compiled program. Deferred outer joins flatten their tables like
    # any other part; the marker metadata rides outer_meta.
    E.resolve_counts()
    part_infos = []
    outer_meta = []
    for i, p in enumerate(parts):
        if i == keep:
            continue
        if isinstance(p, _OuterProbe):
            part_infos.append(_flatten_part(p.table))
            outer_meta.append(("probe", p.condition, tuple(p.conjuncts),
                               p.src))
        elif isinstance(p, _OuterBuild):
            part_infos.append(_flatten_part(p.table))
            outer_meta.append(("build", p.condition, tuple(p.conjuncts),
                               p.src))
        else:
            part_infos.append(_flatten_part(p))
            outer_meta.append(None)
    # the chunk slot must never be the dimension side of a PK-gather plan:
    # that plan fetches the dim side's key ranges on host, which would
    # bake CHUNK data into the chunk-invariant program
    masked_sources = list(sources)
    masked_sources[keep] = None

    chunk_iter = chunked.padded_chunks()
    first = next(chunk_iter)
    chunk_spec = _chunk_signature(first, alias)
    chunk_cap = chunked.chunk_cap
    n_chunks = chunked.num_chunks()

    key = None
    hit0 = None
    bind_slots = ()
    pipe, resid_infos = None, ()
    try:
        if _param_bind_active():
            bind_slots = _param_slots(planner, parts, keep,
                                      where_conjuncts, chunk_spec)
        key = _cache_key(alias, keep, join_preds, where_conjuncts,
                         masked_sources, part_infos, chunk_spec, chunk_cap,
                         chunked.nrows, outer_meta, bind_slots)
        hit0 = _cache_hit(key, chunk_spec, part_infos)
    except Exception:
        hit0, key = None, None           # unkeyable statement: no cache
    # residual replan runs OUTSIDE the unkeyable guard: its failures are
    # real execution errors, not cache-key problems
    if hit0 is not None:
        pipe, resid_infos = _resolve_residuals(planner, key, hit0)
    parts_flat = tuple(tuple(flat) for (_spec, flat) in part_infos)

    claim = None
    if pipe is None and key is not None:
        # singleflight: claim the compile for this shape or wait (off-
        # lock) for the thread already compiling it, then take its
        # entry. A waiter whose post-wait lookup misses again (the
        # winner's entry was FIFO-evicted or went stale) LOOPS back to
        # claim rather than building unclaimed — exactly one compile
        # per shape holds even under churn. A build that REFUSES (not
        # chunk-invariant) is deliberately not negative-cached: the
        # refusal can depend on chunk DATA the key cannot see, so each
        # waiter retries in turn — a serialized retry of a trace that
        # fails during GIL-bound planner replay, which the pre-
        # singleflight "parallel" attempts serialized anyway.
        while pipe is None and claim is None:
            with _PIPELINE_LOCK:
                in_cache = key in _PIPELINE_CACHE
                pending = None if in_cache else _PIPELINE_BUILDS.get(key)
                if not in_cache and pending is None:
                    claim = _PIPELINE_BUILDS[key] = threading.Event()
                    break
            if in_cache:
                hit = _cache_hit(key, chunk_spec, part_infos)
                if hit is not None:
                    pipe, resid_infos = _resolve_residuals(
                        planner, key, hit)
                # stale entry evicted: next iteration claims or waits
            else:
                pending.wait(timeout=300.0)
    # label the planner's enclosing "stream" span with the cache outcome
    # and feed the metrics plane (the cache-efficacy evidence the
    # parameterized plan bank is judged by: obs_live columns, rollups)
    _obs.annotate(pipelineCache="hit" if pipe is not None else "miss")
    _metrics.default().inc(_metrics.PIPE_HIT if pipe is not None
                           else _metrics.PIPE_MISS)

    degrade_reason = None
    if pipe is None:
        try:
            try:
                pipe, resid_infos = _build_pipeline(
                    planner, parts, keep, alias, join_preds,
                    where_conjuncts, masked_sources, part_infos,
                    outer_meta, first, chunk_spec, chunk_cap, n_chunks,
                    bind_slots=bind_slots)
            except _F.FaultInjected as exc:
                # pipeline-compile seam (degradable): the designed
                # recovery is the compiled->eager ladder step — record
                # the evidence and fall back, even under strict (this
                # IS the policy the fault matrix proves, not a bug
                # hiding in a fallback)
                _F.record_fault_event(exc.seam, "degrade",
                                      detail="compiled->eager: "
                                      f"{exc}")
                pipe, resid_infos = None, ()
                degrade_reason = (f"fault: {exc.seam} "
                                  "(degraded compiled->eager)")
            if pipe is not None and key is not None:
                n_evicted = 0
                with _PIPELINE_LOCK:
                    _PIPELINE_BUILD_COUNTS[key] = \
                        _PIPELINE_BUILD_COUNTS.get(key, 0) + 1
                    while len(_PIPELINE_CACHE) >= _PIPELINE_MAX:
                        evicted = next(iter(_PIPELINE_CACHE))
                        _PIPELINE_CACHE.pop(evicted)
                        # the counter follows its entry out: a long-
                        # lived serving process must not grow one
                        # counter key per shape it ever saw
                        _PIPELINE_BUILD_COUNTS.pop(evicted, None)
                        n_evicted += 1
                    _PIPELINE_CACHE[key] = pipe
                if n_evicted:            # count OFF-lock, like the feeds
                    _metrics.default().inc(_metrics.PIPE_EVICT, n_evicted)
        finally:
            if claim is not None:
                with _PIPELINE_LOCK:
                    _PIPELINE_BUILDS.pop(key, None)
                claim.set()
        if pipe is None:
            return None, degrade_reason or "not chunk-invariant"

    resid_flat = tuple(tuple(flat) for (_spec, flat) in resid_infos)
    # THIS statement's literal values for the pipe's bound slots (a hit
    # is key-guaranteed to agree on slot count/order/types — only the
    # values differ, and they ride as jit operands, not trace constants)
    params = _param_operands(bind_slots) if pipe.param_nodes else ()
    snapshot = list(E._pending_counts())
    checks_snapshot = [c for c, _f in
                       (getattr(E._sync_tls, "checks", None) or [])]
    try:
        out, ran, evidence = pipe.run(chunk_iter, first, parts_flat,
                                      resid_flat, params)
        # tracing the first call replays planner code that registers
        # DeviceCounts/deferred checks holding TRACER values; they belong
        # to the trace, not this execution — drop them before any
        # downstream resolve_counts() would device_get them
        _restore_counts(snapshot, checks_snapshot)
    except _F.StatementTimeout:
        # the statement watchdog fired inside a drive-time wait: the
        # statement is MARKED timeout (drivers map the classified error
        # to status "timeout") — degrading to an eager rerun would pay
        # the hang again. The event was recorded at the wait.
        _restore_counts(snapshot, checks_snapshot)
        raise
    except _F.FaultError as exc:
        # a drive-time fault at a degradable seam (exchange dispatch, an
        # exhausted transient retry): the designed recovery is the
        # degradation ladder — sharded/compiled -> single-device eager
        # rerun, bit-for-bit. Recorded as evidence; deliberate even
        # under strict (the fault matrix proves this path).
        _restore_counts(snapshot, checks_snapshot)
        with _PIPELINE_LOCK:
            _PIPELINE_CACHE.pop(key, None)
            _PIPELINE_BUILD_COUNTS.pop(key, None)
        _metrics.default().inc(_metrics.PIPE_EVICT)
        _F.record_fault_event(exc.seam, "degrade",
                              detail=f"drive fault -> eager rerun: {exc}")
        log.info("streamed pipeline hit fault seam %s; re-running %s "
                 "eagerly", exc.seam, alias)
        return None, f"fault: {exc.seam} (degraded to eager)"
    except (E.ReplayMismatch, E.StreamSyncError, ValueError, TypeError,
            NotImplementedError, jax.errors.TracerArrayConversionError,
            jax.errors.ConcretizationTypeError,
            jax.errors.TracerBoolConversionError) as exc:
        # first-call trace divergence: unstreamable after all. The reason
        # carries the exception CLASS so a fallback caused by a genuine
        # engine bug (ValueError/TypeError/...) is distinguishable from
        # the two legitimate routing exceptions; NDS_TPU_STREAM_STRICT=1
        # re-raises everything else outright (the diff harnesses and the
        # A/B tests run strict).
        _restore_counts(snapshot, checks_snapshot)
        with _PIPELINE_LOCK:
            _PIPELINE_CACHE.pop(key, None)
            _PIPELINE_BUILD_COUNTS.pop(key, None)
        _metrics.default().inc(_metrics.PIPE_EVICT)
        if _strict() and not isinstance(exc, (E.StreamSyncError,
                                              E.ReplayMismatch)):
            raise
        log.info("streamed pipeline fell back to eager: %s", exc)
        return None, f"trace diverged [{type(exc).__name__}]: {exc}"
    evidence = evidence or {}
    if out is None:
        # device-side overflow (partitioned: some partition's enforced
        # per-partition bucket): rows were dropped, rerun eagerly. Keep
        # the compiled program — other statements over smaller data may
        # fit.
        log.info("streamed pipeline overflowed its bound buckets; "
                 "re-running %s eagerly", alias)
        return None, "bound-bucket overflow"
    survivor_total = int(out.nrows)
    # deferred outer-build joins: emit the outer extras ONCE, from the
    # unmatched-key bitmaps the pipeline accumulated (counts rode the
    # single materializing transfer — no extra sync)
    extras = []
    nonkeep_parts = [p for i, p in enumerate(parts) if i != keep]
    for (slot, miss_mask, n_extras) in evidence.get("outer", ()):
        if not n_extras:
            continue
        from nds_tpu.sql.planner import outer_extras_table
        idx = E.compact_indices(miss_mask, n_extras)
        extras.append(outer_extras_table(nonkeep_parts[slot].table, idx,
                                         n_extras, out))
    if extras:
        out = E.concat_tables([out] + extras)
    h2d = evidence.get("h2d", -1)
    stall_ms = evidence.get("stall_ms", -1.0)
    record_stream_event(alias, ran, E.sync_count() - syncs0, "compiled",
                        rows=survivor_total,
                        partitions=evidence.get("partitions", 1),
                        part_rows=evidence.get("part_rows", ()),
                        bytes_h2d=h2d,
                        shards=evidence.get("shards", 1),
                        collectives=evidence.get("collectives", -1),
                        bytes_ici=evidence.get("bytes_ici", -1),
                        shard_rows=evidence.get("shard_rows", ()),
                        kernel_launches=evidence.get("kernel_launches", 0),
                        kernel_fused_stages=evidence.get("kernel_stages",
                                                         0),
                        prefetch_stall_ms=stall_ms)
    _obs.annotate(path="compiled", chunks=ran,
                  prefetchStallMs=stall_ms,
                  partitions=evidence.get("partitions", 1),
                  shards=evidence.get("shards", 1),
                  collectives=evidence.get("collectives", -1),
                  bytesIci=evidence.get("bytes_ici", -1),
                  bytesH2d=h2d,
                  bytesLogical=_logical_chunk_bytes(pipe.chunk_spec,
                                                    pipe.chunk_cap, ran),
                  # kernel coverage per query: the arm the segment/scan
                  # kernels take (incl. the permanent-fallback flip) +
                  # this scan's fused launch/stage evidence —
                  # tools/trace_report.py prices fused-vs-XLA from these
                  kernelArm=_K.active_arm(),
                  kernelLaunches=evidence.get("kernel_launches", 0),
                  kernelStages=evidence.get("kernel_stages", 0))
    return out, None


def _build_pipeline(planner, parts, keep, alias, join_preds,
                    where_conjuncts, masked_sources, part_infos,
                    outer_meta, first, chunk_spec, chunk_cap, n_chunks,
                    bind_slots=()):
    """RECORD the per-chunk join graph on the first padded chunk and
    compile the chunk-invariant program; ``(None, None)`` when not
    streamable. Returns ``(pipe, resid_infos)`` — the flattened subquery
    residuals the record phase pre-planned, which are THIS execution's
    residual operands."""
    from nds_tpu.engine.replay import _lift_log
    from nds_tpu.sql.planner import _OuterBuild, _OuterProbe
    # pipeline-compile seam (degradable): an injected build/compile
    # fault degrades this statement to the eager chunk loop (the
    # handler lives in stream_execute, which records the FaultEvent)
    _F.fault_point("pipeline-compile")
    snapshot = list(E._pending_counts())
    checks_snapshot = [c for c, _f in
                       (getattr(E._sync_tls, "checks", None) or [])]
    sub = list(parts)
    aliased = planner._alias_table(first, alias)
    sub[keep] = DeviceTable(
        aliased.columns,
        E.DeviceCount(jnp.asarray(E.count_int(first.nrows),
                                  dtype=jnp.int64), chunk_cap),
        plen=chunk_cap)
    pi = 0
    for i in range(len(parts)):
        if i == keep:
            continue
        t = _rebuild_part(part_infos[pi][0], part_infos[pi][1])
        meta = outer_meta[pi]
        if meta is not None:
            mk, mcond, mconjs, msrc = meta
            t = (_OuterProbe if mk == "probe" else _OuterBuild)(
                t, mcond, list(mconjs), msrc)
        sub[i] = t
        pi += 1
    # fused Pallas chunk-scan pass (DESIGN.md "Fused chunk kernels"):
    # split the chunk-owned WHERE conjuncts the shared eligibility rule
    # (analysis/kernel_spec.py) accepts into a chunk-invariant spec; the
    # record/trace then run WITHOUT them — at drive time the fused
    # kernel evaluates them in encoded space and the chunk program
    # consumes the survivor mask as a lazy compact (same shapes, same
    # replay log). Non-lowerable conjuncts stay in the graph
    # per-conjunct; outer-join graphs keep the whole XLA chain (their
    # pre/post conjunct split must not be disturbed).
    scan_spec = None
    where_kept = list(where_conjuncts)
    # bind_slots nonempty means the cache key promised operand-backed
    # conjuncts (computed under kernels-off); never lower them into a
    # host-baked Pallas spec even if the kernel arm flipped since
    if _K.scan_kernels_active() and not bind_slots \
            and not any(m is not None for m in outer_meta):
        from nds_tpu.engine.exprs import lower_scan_spec
        cols_meta = []
        for pos, cname in enumerate(first.column_names):
            c = first[cname]
            cols_meta.append({
                "name": f"{alias.lower()}.{cname.split('.')[-1].lower()}",
                "kind": c.kind, "enc": c.enc,
                "dict_values": c.dict_values,
                "data_slot": 2 * pos,
                "valid_slot": 2 * pos + 1 if c.valid is not None else -1})
        all_cols = set()
        for p in sub:
            all_cols |= set(p.column_names)
        sub_cols = [set(p.column_names) for p in sub]

        def owned(c):
            # the planner's single-ownership test (_join_parts): only a
            # conjunct the planner would push down to the streamed slot
            # may leave the graph
            if planner._has_subquery(c):
                return False
            tabs = planner._expr_tables(c, all_cols)
            owners = set()
            for p_i, pc in enumerate(sub_cols):
                for t in tabs:
                    if any(cc.startswith(t + ".") for cc in pc):
                        owners.add(p_i)
            return owners == {keep}

        try:
            scan_spec, where_kept = lower_scan_spec(where_conjuncts,
                                                    cols_meta, owned)
        except Exception:            # never let lowering break a query
            scan_spec, where_kept = None, list(where_conjuncts)
        if scan_spec is not None:
            flat0 = tuple(x for cname in first.column_names
                          for x in (first[cname].data,
                                    first[cname].valid))
            # smoke-compile on this chunk's real shapes: a Mosaic-
            # refusing attachment degrades to the XLA chain at BUILD
            # time, never mid-drive
            if not _K.scan_spec_ready(scan_spec, flat0, chunk_cap):
                scan_spec, where_kept = None, list(where_conjuncts)
    # save/restore: a subquery residual planned DURING this record may
    # itself stream through a nested pipeline build on the same planner —
    # its record must not clobber the outer record's touched list
    prev_touched = planner._residuals_touched
    planner._residuals_touched = touched = []
    try:
        with _obs.span("stream.record", table=alias):
            with E.recording() as rec_log:
                with E.stream_bounds():
                    with E.outer_match_collector() as omc:
                        out0 = planner._join_parts(sub, list(join_preds),
                                                   list(where_kept),
                                                   list(masked_sources))
    except E.StreamSyncError as exc:
        log.info("streamed scan %s not chunk-invariant: %s", alias, exc)
        return None, None
    finally:
        planner._residuals_touched = prev_touched
        _restore_counts(snapshot, checks_snapshot)
    # subquery residuals the record phase planned (or reused): they become
    # jit operands of the per-chunk program
    resid_infos = [_flatten_part(rt) for (_k, _p, rt) in touched]
    residuals = [(k, p) for (k, p, _rt) in touched]
    # names-only catalog snapshot: the traced planner's correlation
    # analysis (_find_correlation/_select_output_cols) must resolve
    # subquery scopes exactly like the record phase did, without closing
    # over any device-resident table
    name_cat = {}
    if residuals:
        for scope in planner.cte_stack:
            for k, t in scope.items():
                name_cat[k.lower()] = tuple(t.column_names)
        for k, t in planner.catalog.items():
            name_cat.setdefault(k.lower(), tuple(t.column_names))
    # outer-build bitmap slots: the record phase registered one matched
    # mask per deferred outer-build join, in part order
    build_slots = [i for i, m in enumerate(outer_meta)
                   if m is not None and m[0] == "build"]
    if len(omc.masks) != len(build_slots):
        log.info("streamed scan %s: outer-build mask count mismatch "
                 "(%d masks, %d builds)", alias, len(omc.masks),
                 len(build_slots))
        return None, None
    names = list(out0.column_names)
    template = (names,
                [out0[n].kind for n in names],
                [out0[n].dict_values for n in names],
                [out0[n].valid is not None for n in names],
                [out0[n].data.dtype for n in names],
                # survivors carry their narrow encodings into the
                # accumulator (decode only at materialize) — the proof-
                # sized allocation shrinks with the data
                [out0[n].enc for n in names])
    # size the survivor accumulator from the statement's proven row bound
    # (static memory model) instead of the old global guess: a statement
    # whose bound fits the capacity model can never overflow-rerun
    row_bytes = sum(out0[n].data.dtype.itemsize
                    + (1 if out0[n].valid is not None else 0)
                    for n in names)
    stream_rows = parts[keep].chunked.nrows
    proved, fan_k, part_keys = _proved_plan(parts, keep, join_preds,
                                            where_conjuncts, masked_sources,
                                            stream_rows)
    # the prefetch ring's live set (depth × one padded chunk's actual
    # upload bytes) comes off the capacity every admission decision
    # below sees — mem_audit prices the same term statically (lockstep)
    ring_bytes = _ring_bytes(sum(
        int(first[c].data.nbytes)
        + (0 if first[c].valid is None else int(first[c].valid.nbytes))
        for c in first.column_names))
    n_parts, part_bound = _partition_plan(stream_rows, fan_k, part_keys,
                                          proved, max(row_bytes, 1),
                                          n_chunks, out0.plen,
                                          ring_bytes=ring_bytes)
    key_slots = []
    if n_parts > 1:
        # map the partition keys (bare names) to the chunk's flattened
        # buffer slots (2 slots per column: data, valid)
        spec_names = [nm for (nm, _k, _dv, _en) in chunk_spec]
        for key in part_keys:
            hit = [i for i, nm in enumerate(spec_names)
                   if nm.split(".")[-1] == key]
            if not hit:
                n_parts, part_bound = 1, None    # key pruned off the scan
                break
            key_slots.append(2 * hit[0])
    if n_parts > 1:
        budget = _part_acc_budget(n_chunks, out0.plen, part_bound,
                                  max(row_bytes, 1), n_parts,
                                  ring_bytes=ring_bytes)
    else:
        budget = _acc_row_budget(n_chunks, out0.plen, proved,
                                 max(row_bytes, 1),
                                 ring_bytes=ring_bytes)
    # mesh-sharded execution: each shard accumulates its own slice, so
    # the budget re-shares over the mesh (skew-factored like the
    # partition share — mem_audit.shard_row_bound, the lockstep rule);
    # the recorded out bucket stays the floor, so a per-shard dispatch
    # can always land one full chunk output
    n_shards, mesh, axis_name = _shard_plan(chunk_cap)
    exchange, cap_ex = False, 0
    if n_shards > 1:
        from nds_tpu.analysis.mem_audit import stream_skew_factor
        budget = min(budget, -(-budget // n_shards) * stream_skew_factor())
        if n_parts > 1 and key_slots and \
                os.environ.get("NDS_TPU_STREAM_EXCHANGE", "1") != "0":
            # the partitioned graph's keys are not co-partitioned with
            # the arbitrary row split: hash-exchange rows over ICI so
            # each shard owns a key range
            exchange = True
            cap_ex = E.bucket_len(
                max((chunk_cap // n_shards) // n_shards, 1)
                * stream_skew_factor())
    acc_cap = E.bucket_len(max(budget, out0.plen))
    if scan_spec is not None and n_parts > 1 and key_slots:
        # the fused pass also computes the partition/shard routing hash
        # (one more fused stage); key slots are the SAME buffers the XLA
        # radix pass folds, so both arms route rows identically
        scan_spec = _K.ScanSpec(scan_spec.entries, scan_spec.cols,
                                tables=scan_spec.tables,
                                key_slots=tuple(key_slots),
                                n_conjuncts=scan_spec.n_conjuncts)
    _obs.annotate(accRows=acc_cap, partitions=n_parts, shards=n_shards,
                  provedRows=proved if proved is not None else "unproven",
                  residuals=len(residuals), outerBuilds=len(build_slots))
    lifted, operands = _lift_log(list(rec_log))
    pipe = StreamPipeline(
        chunk_spec, chunk_cap,
        tuple(spec for (spec, _flat) in part_infos), keep, lifted,
        tuple(operands), template, acc_cap,
        [_weak(x) for (_spec, flat) in part_infos for x in flat],
        n_partitions=n_parts, key_slots=key_slots,
        outer_meta=outer_meta, residuals=residuals,
        resid_specs=tuple(spec for (spec, _flat) in resid_infos),
        build_slots=build_slots, name_catalog=name_cat,
        n_shards=n_shards, mesh=mesh, mesh_axis=axis_name or "shard",
        exchange=exchange, cap_ex=cap_ex, scan_spec=scan_spec,
        # bound slots reference where_conjuncts Literal nodes; with the
        # kernel arm off (binding's precondition) where_kept IS
        # where_conjuncts, so the traced replay sees those same nodes
        param_nodes=tuple(nd for (_ci, _p, _t, nd) in bind_slots),
        param_tags=tuple(t for (_ci, _p, t, _nd) in bind_slots))
    return (pipe.compile(join_preds, where_kept, masked_sources),
            resid_infos)
