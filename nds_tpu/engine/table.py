# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""DeviceTable: an ordered set of named device columns of equal length.

Padded-prefix invariant: columns may be physically longer than the table's
logical row count (``nrows``); rows past ``nrows`` are garbage pads that
every operator ignores (see :mod:`nds_tpu.engine.ops` — bucketed shapes).
``plen`` is the physical length.
"""

from __future__ import annotations

from nds_tpu.engine.column import Column


class DeviceTable:
    def __init__(self, columns: dict[str, Column], nrows: int | None = None,
                 plen: int | None = None):
        self.columns = dict(columns)
        if nrows is None:
            nrows = len(next(iter(columns.values()))) if columns else 0
        self.nrows = nrows
        # physical length; only meaningful to pass for column-less tables
        # (aggregation contexts carry capacity without materialized columns)
        if plen is None:
            plen = len(next(iter(columns.values()))) if columns else nrows
        self._plen = plen

    @property
    def plen(self) -> int:
        if self.columns:
            return len(next(iter(self.columns.values())))
        return self._plen

    @property
    def column_names(self):
        return list(self.columns.keys())

    def __getitem__(self, name: str) -> Column:
        return self.columns[name]

    def __contains__(self, name: str) -> bool:
        return name in self.columns

    def select(self, names) -> "DeviceTable":
        return DeviceTable({n: self.columns[n] for n in names}, self.nrows,
                           self.plen)

    def with_column(self, name: str, col: Column) -> "DeviceTable":
        cols = dict(self.columns)
        cols[name] = col
        return DeviceTable(cols, self.nrows, self.plen)

    def rename(self, mapping: dict[str, str]) -> "DeviceTable":
        return DeviceTable(
            {mapping.get(n, n): c for n, c in self.columns.items()},
            self.nrows, self.plen)

    def take(self, indices, nrows: int | None = None) -> "DeviceTable":
        """Row gather (one fused device dispatch for every column): logical
        length defaults to the index count (exact materialization). Pass
        ``nrows`` when gathering with a padded index vector or permutation
        to preserve the logical count."""
        from nds_tpu.engine.ops import gather_table_rows
        n = int(indices.shape[0]) if nrows is None else nrows
        if not self.columns:
            return DeviceTable({}, n, plen=int(indices.shape[0]))
        return gather_table_rows(self, indices, n)

    def to_arrow(self):
        from nds_tpu.engine.column import to_arrow
        return to_arrow(self)

    @staticmethod
    def from_arrow(table, canonical_types=None) -> "DeviceTable":
        from nds_tpu.engine.column import from_arrow
        return from_arrow(table, canonical_types)

    def __repr__(self):
        cols = ", ".join(f"{n}:{c.kind}" for n, c in self.columns.items())
        return f"DeviceTable[{self.nrows}/{self.plen} rows]({cols})"


class ChunkedTable:
    """A host-resident (arrow) table streamed through queries in row
    chunks — the scan path for tables larger than device HBM (SURVEY.md
    §5.7: "operators must stream/partition tables larger than HBM", the
    structural place sequence parallelism occupies in a model framework;
    the reference's analog is Spark file splits +
    spark.sql.files.maxPartitionBytes, ref: nds/power_run_gpu.template:30).

    The planner binds each device chunk in turn and runs the normal join
    graph per chunk (filters and joins shrink the chunk before anything is
    kept), concatenating the surviving rows; aggregation runs downstream on
    the union, so no operator ever sees the whole table on device. Chunk
    row counts are a fixed power of two, so every full chunk reuses the
    same XLA executables.
    """

    def __init__(self, arrow, canonical_types: dict | None = None,
                 chunk_rows: int | None = None):
        import os
        self.arrow = arrow
        self.canonical_types = canonical_types or {}
        self.chunk_rows = int(chunk_rows or os.environ.get(
            "NDS_TPU_STREAM_CHUNK_ROWS", str(1 << 22)))
        # unified per-column string encodings for the compiled streaming
        # executor (built lazily by padded_chunks; shared across select()
        # views, since a projection never changes column contents)
        self._str_store: dict = {}
        # per-column narrow codecs (io/columnar.plan_column_codec): whole-
        # table FOR/dict encodings the padded chunks slice — same shared-
        # store discipline as the string dictionaries. None marks a column
        # already found unencodable, so the stats pass runs once.
        self._enc_store: dict = {}
        # persistent wire plans (io/chunk_store.py, NDS_TPU_CHUNK_STORE):
        # one whole-table pre-encoded plan per column set, loaded (mmap)
        # or built+saved once — shared across select() views like the
        # codec stores above. Keyed by column-name tuple so a pruned
        # view's plan never serves the full table's.
        self._wire_store: dict = {}

    @property
    def nrows(self) -> int:
        return self.arrow.num_rows

    @property
    def nbytes(self) -> int:
        return self.arrow.nbytes

    @property
    def column_names(self):
        return list(self.arrow.column_names)

    def select(self, names) -> "ChunkedTable":
        out = ChunkedTable(self.arrow.select(names), self.canonical_types,
                           self.chunk_rows)
        out._str_store = self._str_store
        out._enc_store = self._enc_store
        out._wire_store = self._wire_store
        return out

    def device_chunks(self):
        """Yield DeviceTable chunks (at least one, possibly empty, so the
        schema always survives to the consumer)."""
        from nds_tpu.engine.column import from_arrow
        n = self.arrow.num_rows
        if n == 0:
            yield from_arrow(self.arrow, self.canonical_types)
            return
        for s in range(0, n, self.chunk_rows):
            sl = self.arrow.slice(s, min(self.chunk_rows, n - s))
            yield from_arrow(sl.combine_chunks(), self.canonical_types)

    @property
    def chunk_cap(self) -> int:
        """Uniform physical capacity of every padded chunk."""
        from nds_tpu.engine.ops import bucket_len
        return bucket_len(self.chunk_rows)

    def num_chunks(self) -> int:
        n = self.arrow.num_rows
        return max(1, -(-n // self.chunk_rows))

    def _string_encodings(self) -> dict:
        """name -> (int32 codes, shared value table, valid | None) for every
        string column, encoded ONCE against a single whole-table dictionary.

        The compiled streaming executor runs one traced program over every
        chunk; dictionary codes are device DATA in that program while the
        value table is host metadata baked into the trace, so all chunks
        must share one dictionary (per-chunk encodings would make the same
        code mean different strings chunk to chunk). The value table is
        also handed out as the SAME host object for every chunk, keeping
        identity-keyed caches (rank maps, expression fusion) warm. Cached
        per column in a store shared with select() views."""
        import numpy as np
        import pyarrow as pa
        import pyarrow.compute as pc
        from nds_tpu import types as _t
        enc: dict = {}
        for name in self.arrow.column_names:
            hit = self._str_store.get(name)
            if hit is not None:
                enc[name] = hit
                continue
            ct = self.canonical_types.get(name) or _t.arrow_to_canonical(
                self.arrow.schema.field(name).type)
            if _t.device_kind(ct) != "str":
                continue
            col = self.arrow[name].combine_chunks()
            if not pa.types.is_dictionary(col.type):
                col = pc.dictionary_encode(col)
            codes = np.asarray(
                pc.fill_null(col.indices, 0).to_numpy(zero_copy_only=False),
                dtype=np.int32)
            values = np.asarray(col.dictionary.to_pylist(), dtype=object)
            if values.size == 0:
                values = np.asarray([""], dtype=object)
            valid = None
            if col.null_count:
                valid = ~np.asarray(pc.is_null(col).to_numpy(
                    zero_copy_only=False))
            enc[name] = self._str_store[name] = (codes, values, valid)
        return enc

    def _int_encodings(self) -> dict:
        """name -> (narrow whole-table codes, valid | None, Encoding) for
        every encodable int-path column (io/columnar.plan_column_codec),
        computed ONCE per table and shared across select() views — the
        same chunk-invariance discipline as the string dictionaries, so
        the compiled streaming executor's single traced program serves
        every chunk and the Encoding objects are cache-key members.
        Empty when NDS_TPU_ENCODED=0 (the escape hatch; read per call,
        the computed plan stays cached for a later re-enable)."""
        from nds_tpu.io.columnar import encoded_enabled, plan_column_codec
        if not encoded_enabled():
            return {}
        from nds_tpu import types as _t
        out = {}
        for name in self.arrow.column_names:
            if name not in self._enc_store:
                ct = self.canonical_types.get(name) or _t.arrow_to_canonical(
                    self.arrow.schema.field(name).type)
                self._enc_store[name] = plan_column_codec(self.arrow[name],
                                                          ct)
            got = self._enc_store[name]
            if got is not None:
                out[name] = got
        return out

    def _wire_plan(self):
        """``name -> io.chunk_store.WireColumn`` when the persistent
        chunk store is active (``NDS_TPU_CHUNK_STORE``): the whole-table
        pre-encoded wire arrays ``padded_chunks`` slices per chunk. A
        warm store entry memory-maps straight back (no arrow slicing, no
        codec planning); a miss or a stale fingerprint builds the plan
        from the live codecs and persists it. None when the store is off
        — ``padded_chunks`` then keeps the inline arrow path, bit for
        bit."""
        from nds_tpu.io import chunk_store
        from nds_tpu.io.columnar import encoded_enabled
        root = chunk_store.store_root()
        if root is None:
            return None
        # keyed by column set AND the encoded gate: a post-build
        # NDS_TPU_ENCODED flip must rebuild (the on-disk entry's
        # fingerprint covers the same flag, so disk stays honest too)
        key = (tuple(self.arrow.column_names), encoded_enabled())
        hit = self._wire_store.get(key)
        if hit is not None:
            return hit
        from nds_tpu.engine import faults as _F
        try:
            plan = chunk_store.load_plan(root, self.arrow,
                                         self.canonical_types)
        except (chunk_store.ChunkStoreCorrupt, _F.FaultInjected) as exc:
            # chunk-store-read seam recovery (transient, bounded at one
            # re-encode): the store is a CACHE of the source arrow data,
            # so a corrupt entry (torn write, bit rot, injected fault)
            # is deleted and rebuilt from source — evidence-recorded,
            # never a failed statement, never corrupt codes uploaded.
            # Version drift stays a loud ChunkStoreError (fatal).
            _F.record_fault_event("chunk-store-read", "recovered",
                                  attempt=1, detail=str(exc)[:200])
            chunk_store.invalidate_entry(root, self.arrow,
                                         self.canonical_types)
            plan = None
        if plan is None:
            plan = self._build_wire_plan()
            # persisting is best-effort: a full disk, a read-only store
            # or a concurrent writer's rename race must degrade to the
            # in-memory plan just built, never fail the statement (a
            # LOAD problem — version drift, checksum — stays loud)
            try:
                chunk_store.save_plan(root, self.arrow,
                                      self.canonical_types, plan)
            except Exception as exc:
                # chunk-store-write seam degrade (evidence-recorded):
                # the statement proceeds on the plan just built
                _F.record_fault_event("chunk-store-write", "degrade",
                                      detail=str(exc)[:200])
                import logging
                logging.getLogger(__name__).warning(
                    "chunk store save failed (%s); serving the "
                    "in-memory wire plan for this process", exc)
        self._wire_store[key] = plan
        return plan

    def _build_wire_plan(self) -> dict:
        """The wire form of every column, from the live whole-table
        codecs: string dictionaries, narrow FOR/dict codes, and a host
        lowering of the remaining plain columns — exactly the arrays the
        inline ``padded_chunks`` path derives, assembled once so the
        chunk store can persist them."""
        from nds_tpu import types as _t
        from nds_tpu.io.chunk_store import WireColumn, lower_plain_column
        strings = self._string_encodings()
        narrow = self._int_encodings()
        plan = {}
        for name in self.arrow.column_names:
            ct = self.canonical_types.get(name) or _t.arrow_to_canonical(
                self.arrow.schema.field(name).type)
            if name in strings:
                codes, values, valid = strings[name]
                plan[name] = WireColumn("str", codes, valid, values,
                                        None, "str")
            elif name in narrow:
                codes, valid, enc = narrow[name]
                plan[name] = WireColumn("enc", codes, valid, None, enc,
                                        _t.device_kind(ct))
            else:
                data, valid = lower_plain_column(self.arrow[name], ct)
                plan[name] = WireColumn("plain", data, valid, None, None,
                                        _t.device_kind(ct))
        return plan

    def padded_chunks(self):
        """Yield DeviceTable chunks at ONE uniform physical capacity
        (``chunk_cap``), the final partial chunk zero-padded up to it, with
        every column carrying an explicit validity mask (False past the
        live prefix). Chunk k then differs from chunk j only in buffer
        CONTENTS — same shapes, same pytree structure, same dictionaries —
        which is what lets the compiled streaming executor drive every
        chunk through a single traced program (engine/stream.py).

        With the persistent chunk store active (``NDS_TPU_CHUNK_STORE``)
        the chunks slice pre-encoded whole-table wire arrays — possibly
        memory-mapped from a previous run — instead of slicing arrow and
        re-planning codecs; the store path produces bit-identical
        buffers (same codecs, same lowering math)."""
        import jax.numpy as jnp
        import numpy as np
        from nds_tpu import types as _t
        from nds_tpu.engine.column import Column, from_arrow_array
        cap = self.chunk_cap
        n = self.arrow.num_rows
        wire = self._wire_plan()
        if wire is not None:
            yield from self._padded_chunks_wire(wire, cap, n)
            return
        strings = self._string_encodings()
        narrow = self._int_encodings()
        for s in (range(0, n, self.chunk_rows) if n else (0,)):
            live = min(self.chunk_rows, n - s) if n else 0
            live_np = np.arange(cap) < live
            sl = self.arrow.slice(s, live)
            cols = {}
            for name in self.arrow.column_names:
                if name in strings:
                    codes, values, valid = strings[name]
                    data = np.zeros(cap, dtype=np.int32)
                    data[:live] = codes[s:s + live]
                    v = live_np if valid is None else \
                        live_np & np.concatenate(
                            [valid[s:s + live],
                             np.zeros(cap - live, dtype=bool)])
                    cols[name] = Column("str", jnp.asarray(data),
                                        jnp.asarray(v), values)
                    continue
                ct = self.canonical_types.get(name) or _t.arrow_to_canonical(
                    self.arrow.schema.field(name).type)
                if name in narrow:
                    # encoded upload: slice the whole-table narrow codes
                    # (host->device moves 2/4 B per row instead of 4/8)
                    codes, valid, enc = narrow[name]
                    data = np.zeros(cap, dtype=codes.dtype)
                    data[:live] = codes[s:s + live]
                    v = live_np if valid is None else \
                        live_np & np.concatenate(
                            [valid[s:s + live],
                             np.zeros(cap - live, dtype=bool)])
                    cols[name] = Column(_t.device_kind(ct),
                                        jnp.asarray(data),
                                        jnp.asarray(v), None, enc)
                    continue
                c = from_arrow_array(sl[name], ct, cap)
                # canonical validity structure: a chunk without nulls must
                # present the same pytree as a sibling with them, or every
                # null-pattern change would retrace the compiled program
                v = jnp.asarray(live_np) if c.valid is None else \
                    c.valid & jnp.asarray(live_np)
                cols[name] = Column(c.kind, c.data, v, c.dict_values)
            yield DeviceTable(cols, live, plen=cap)

    def _padded_chunks_wire(self, wire: dict, cap: int, n: int):
        """The store-backed twin of the inline ``padded_chunks`` body:
        slice every column's whole-table wire array (codes / lowered
        values, possibly mmapped) into zero-padded chunk buffers. Same
        shapes, same dictionaries, same validity structure — a pipeline
        compiled against either path serves the other."""
        import jax.numpy as jnp
        import numpy as np
        from nds_tpu.engine.column import Column
        for s in (range(0, n, self.chunk_rows) if n else (0,)):
            live = min(self.chunk_rows, n - s) if n else 0
            live_np = np.arange(cap) < live
            cols = {}
            for name in self.arrow.column_names:
                wc = wire[name]
                data = np.zeros(cap, dtype=wc.data.dtype)
                data[:live] = wc.data[s:s + live]
                v = live_np if wc.valid is None else \
                    live_np & np.concatenate(
                        [wc.valid[s:s + live],
                         np.zeros(cap - live, dtype=bool)])
                cols[name] = Column(wc.kind, jnp.asarray(data),
                                    jnp.asarray(v), wc.values, wc.enc)
            yield DeviceTable(cols, live, plen=cap)

    def materialize(self) -> DeviceTable:
        from nds_tpu.engine.column import from_arrow
        return from_arrow(self.arrow, self.canonical_types)

    def __repr__(self):
        return (f"ChunkedTable[{self.nrows} rows x "
                f"{len(self.arrow.column_names)} cols, "
                f"chunk={self.chunk_rows}]")
