# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""DeviceTable: an ordered set of named device columns of equal length."""

from __future__ import annotations

from nds_tpu.engine.column import Column


class DeviceTable:
    def __init__(self, columns: dict[str, Column], nrows: int | None = None):
        self.columns = dict(columns)
        if nrows is None:
            nrows = len(next(iter(columns.values()))) if columns else 0
        self.nrows = nrows

    @property
    def column_names(self):
        return list(self.columns.keys())

    def __getitem__(self, name: str) -> Column:
        return self.columns[name]

    def __contains__(self, name: str) -> bool:
        return name in self.columns

    def select(self, names) -> "DeviceTable":
        return DeviceTable({n: self.columns[n] for n in names}, self.nrows)

    def with_column(self, name: str, col: Column) -> "DeviceTable":
        cols = dict(self.columns)
        cols[name] = col
        return DeviceTable(cols, self.nrows)

    def rename(self, mapping: dict[str, str]) -> "DeviceTable":
        return DeviceTable(
            {mapping.get(n, n): c for n, c in self.columns.items()}, self.nrows)

    def take(self, indices) -> "DeviceTable":
        cols = {n: c.take(indices) for n, c in self.columns.items()}
        n = int(indices.shape[0])
        return DeviceTable(cols, n)

    def to_arrow(self):
        from nds_tpu.engine.column import to_arrow
        return to_arrow(self)

    @staticmethod
    def from_arrow(table, canonical_types=None) -> "DeviceTable":
        from nds_tpu.engine.column import from_arrow
        return from_arrow(table, canonical_types)

    def __repr__(self):
        cols = ", ".join(f"{n}:{c.kind}" for n, c in self.columns.items())
        return f"DeviceTable[{self.nrows} rows]({cols})"
