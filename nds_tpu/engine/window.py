# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Window functions: rank/row_number/dense_rank and partition aggregates.

Implementation: one lexsort over (partition keys, order keys), segment
boundary detection, then prefix-scan arithmetic within segments — all static
dtype device ops. Results scatter back to the original row order.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from nds_tpu.engine.column import Column, is_dec
from nds_tpu.engine.ops import lexsort_indices, sortable_view


def _boundaries(cols, order):
    """Sorted-order boundary mask: True where a new run of equal keys starts."""
    n = int(order.shape[0])
    if n == 0:
        return jnp.zeros(0, dtype=bool)
    b = jnp.zeros(n, dtype=bool).at[0].set(True)
    for col in cols:
        v = sortable_view(col)
        if col.valid is not None:
            v = jnp.where(col.valid, v, jnp.zeros((), dtype=v.dtype))
        sv = jnp.take(v, order)
        b = b | jnp.concatenate([jnp.ones(1, dtype=bool), sv[1:] != sv[:-1]])
        if col.valid is not None:
            nv = jnp.take(col.valid, order)
            b = b | jnp.concatenate([jnp.zeros(1, dtype=bool), nv[1:] != nv[:-1]])
    return b


class WindowContext:
    """One sort shared by every window function over the same
    (partition, order) spec."""

    def __init__(self, partition_cols, order_cols=(), descending=None,
                 nulls_last=None, n_valid: int | None = None):
        self.n = len(partition_cols[0]) if partition_cols else len(order_cols[0])
        if n_valid is None:
            n_valid = self.n
        all_cols = list(partition_cols) + list(order_cols)
        desc = [False] * len(partition_cols) + list(
            descending or [False] * len(order_cols))
        nl = [False] * len(partition_cols) + list(
            nulls_last or [d for d in (descending or [False] * len(order_cols))])
        # pad rows sort last and are walled off into their own partition so
        # no real partition's aggregate sees pad garbage
        self.order = lexsort_indices(all_cols, desc, nl, n_valid=n_valid)
        pos = jnp.arange(self.n)
        if self.n == 0:
            self.part_boundary = jnp.zeros(0, dtype=bool)
        elif partition_cols:
            self.part_boundary = _boundaries(partition_cols, self.order)
        else:
            self.part_boundary = jnp.zeros(self.n, dtype=bool).at[0].set(True)
        # wall off the pad suffix into its own partition. A device count
        # applies the traced form unconditionally: at n_valid == n the
        # boundary lands past every row (no-op), at 0 it re-marks row 0.
        from nds_tpu.engine.ops import DeviceCount, count_arr
        if isinstance(n_valid, DeviceCount):
            if self.n:
                self.part_boundary = self.part_boundary | (
                    pos == count_arr(n_valid))
        elif 0 < n_valid < self.n:
            self.part_boundary = self.part_boundary | (pos == n_valid)
        self.gid_sorted = jnp.cumsum(self.part_boundary) - 1
        # segment capacity: physical length is a static upper bound on the
        # partition count — no host sync, canonical shapes
        self.ngroups = self.n
        # start position of each row's segment
        seg_starts = jnp.where(self.part_boundary, pos, 0)
        self.start_for_row = jax.ops.segment_max(
            seg_starts, self.gid_sorted, num_segments=self.ngroups)[self.gid_sorted]
        self.pos = pos
        self.order_boundary = (self.part_boundary |
                               _boundaries(order_cols, self.order)
                               if order_cols else self.part_boundary)

    def _scatter(self, sorted_vals, kind="i64", valid_sorted=None, dict_values=None):
        out = jnp.zeros(self.n, dtype=sorted_vals.dtype).at[self.order].set(sorted_vals)
        valid = None
        if valid_sorted is not None:
            valid = jnp.zeros(self.n, dtype=bool).at[self.order].set(valid_sorted)
        return Column(kind, out, valid, dict_values)

    def row_number(self) -> Column:
        rn = self.pos - self.start_for_row + 1
        return self._scatter(rn.astype(jnp.int64))

    def rank(self) -> Column:
        # rank = position of the last order-boundary at or before this row
        last_b = jax.lax.cummax(jnp.where(self.order_boundary, self.pos, -1))
        rk = last_b - self.start_for_row + 1
        return self._scatter(rk.astype(jnp.int64))

    def dense_rank(self) -> Column:
        cb = jnp.cumsum(self.order_boundary)
        cb_at_start = jax.ops.segment_max(
            jnp.where(self.part_boundary, cb, 0), self.gid_sorted,
            num_segments=self.ngroups)[self.gid_sorted]
        dr = cb - cb_at_start + 1
        return self._scatter(dr.astype(jnp.int64))

    def partition_agg(self, col: Column, agg: str) -> Column:
        """sum/avg/min/max/count over the whole partition, broadcast per row."""
        col = col.plain()                 # window math needs logical values
        valid = jnp.take(col.valid_mask(), self.order)
        data = jnp.take(col.data, self.order)
        if agg == "count":
            red = jax.ops.segment_sum(valid.astype(jnp.int64), self.gid_sorted,
                                      num_segments=self.ngroups)
            per_row = red[self.gid_sorted]
            return self._scatter(per_row, "i64")
        if agg in ("sum", "avg"):
            f = data.astype(jnp.float64) if col.kind == "f64" else data.astype(jnp.int64)
            f = jnp.where(valid, f, 0)
            s = jax.ops.segment_sum(f, self.gid_sorted, num_segments=self.ngroups)
            c = jax.ops.segment_sum(valid.astype(jnp.int64), self.gid_sorted,
                                    num_segments=self.ngroups)
            if agg == "avg":
                sf = s.astype(jnp.float64)
                if is_dec(col.kind):
                    sf = sf / (10.0 ** col.scale)
                per_row = (sf / jnp.maximum(c, 1))[self.gid_sorted]
                return self._scatter(per_row, "f64", valid_sorted=(c > 0)[self.gid_sorted])
            per_row = s[self.gid_sorted]
            kind = ("f64" if col.kind == "f64"
                    else (f"dec(38,{col.scale})" if is_dec(col.kind) else "i64"))
            return self._scatter(per_row, kind, valid_sorted=(c > 0)[self.gid_sorted])
        if agg in ("min", "max"):
            big = jnp.iinfo(jnp.int64).max if col.kind != "f64" else jnp.inf
            sent = -big if agg == "max" else big
            f = data.astype(jnp.float64) if col.kind == "f64" else data.astype(jnp.int64)
            f = jnp.where(valid, f, sent)
            seg = jax.ops.segment_max if agg == "max" else jax.ops.segment_min
            red = seg(f, self.gid_sorted, num_segments=self.ngroups)
            c = jax.ops.segment_sum(valid.astype(jnp.int64), self.gid_sorted,
                                    num_segments=self.ngroups)
            per_row = red[self.gid_sorted]
            kind = "f64" if col.kind == "f64" else (col.kind if is_dec(col.kind) else "i64")
            if col.kind != "f64":
                per_row = per_row.astype(jnp.int64)
            return self._scatter(per_row, kind, valid_sorted=(c > 0)[self.gid_sorted])
        raise ValueError(f"unsupported window aggregate: {agg}")

    def _segmented_scan(self, vals: jnp.ndarray, op: str) -> jnp.ndarray:
        """Inclusive scan of ``vals`` (already in sorted order) that resets at
        partition boundaries. Classic segmented-scan formulation over
        (reset-flag, value) pairs — associative, so it runs as one
        ``associative_scan`` on device."""
        flags = self.part_boundary

        if op == "sum":
            def combine(a, b):
                fa, va = a
                fb, vb = b
                return fa | fb, jnp.where(fb, vb, va + vb)
        elif op == "min":
            def combine(a, b):
                fa, va = a
                fb, vb = b
                return fa | fb, jnp.where(fb, vb, jnp.minimum(va, vb))
        elif op == "max":
            def combine(a, b):
                fa, va = a
                fb, vb = b
                return fa | fb, jnp.where(fb, vb, jnp.maximum(va, vb))
        else:
            raise ValueError(op)
        _, out = jax.lax.associative_scan(combine, (flags, vals))
        return out

    def _range_extend(self, run: jnp.ndarray) -> jnp.ndarray:
        """RANGE frames include order-key peers: every row takes the scan
        value of the LAST row of its peer run."""
        rid = jnp.cumsum(self.order_boundary) - 1
        # capacity bound, not exact count: avoids a host sync and keeps the
        # segment-op shape canonical
        nruns = self.n
        last_pos = jax.ops.segment_max(self.pos, rid, num_segments=nruns)
        return jnp.take(run, jnp.take(last_pos, rid))

    def running_agg(self, col: Column, agg: str, rows_frame: bool = False) -> Column:
        """sum/count/avg/min/max over (partition ... order ... unbounded
        preceding .. current row). ``rows_frame`` selects ROWS semantics;
        the SQL default frame is RANGE (order-key peers included)."""
        col = col.plain()
        valid = jnp.take(col.valid_mask(), self.order)
        data = jnp.take(col.data, self.order)
        is_f = col.kind == "f64"
        f = data.astype(jnp.float64) if is_f else data.astype(jnp.int64)

        vcount = self._segmented_scan(valid.astype(jnp.int64), "sum")
        if not rows_frame:
            vcount = self._range_extend(vcount)
        has_any = vcount > 0

        if agg == "count":
            return self._scatter(vcount, "i64")
        if agg in ("sum", "avg"):
            run = self._segmented_scan(jnp.where(valid, f, 0), "sum")
            if not rows_frame:
                run = self._range_extend(run)
            if agg == "avg":
                sf = run.astype(jnp.float64)
                if is_dec(col.kind):
                    sf = sf / (10.0 ** col.scale)
                return self._scatter(sf / jnp.maximum(vcount, 1), "f64",
                                     valid_sorted=has_any)
            kind = ("f64" if is_f
                    else (f"dec(38,{col.scale})" if is_dec(col.kind) else "i64"))
            return self._scatter(run, kind, valid_sorted=has_any)
        if agg in ("min", "max"):
            big = jnp.inf if is_f else jnp.iinfo(jnp.int64).max
            sent = -big if agg == "max" else big
            run = self._segmented_scan(jnp.where(valid, f, sent), agg)
            if not rows_frame:
                run = self._range_extend(run)
            kind = "f64" if is_f else (col.kind if is_dec(col.kind) else "i64")
            if not is_f:
                run = run.astype(jnp.int64)
            return self._scatter(run, kind, valid_sorted=has_any)
        raise ValueError(f"unsupported running aggregate: {agg}")

    def running_sum(self, col: Column) -> Column:
        """Back-compat alias: ROWS-frame running sum."""
        return self.running_agg(col, "sum", rows_frame=True)
