# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""IO layer: raw '|'-delimited CSV ingest and columnar (Parquet/ORC) output."""

from nds_tpu.io.csv import read_raw_table  # noqa: F401
from nds_tpu.io.columnar import read_table, write_table  # noqa: F401
