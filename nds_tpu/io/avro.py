# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Minimal Avro Object Container File codec for arrow tables.

The reference's Load Test can transcode to avro through spark-avro
(ref: nds/nds_transcode.py:61,85,257,263); this environment ships no avro
library, so the subset of the format the NDS schemas need is implemented
here directly against the Avro 1.11 spec: null-union primitives, the
``date`` logical type on int, and the ``decimal`` logical type on bytes.
Container layout: magic ``Obj\\x01``, metadata map (``avro.schema``,
``avro.codec``), 16-byte sync marker, then blocks of
``(row count, byte size, data, sync)`` with optional deflate codec.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib

import pyarrow as pa

MAGIC = b"Obj\x01"
_BLOCK_ROWS = 4096


# -- varint / primitive encoders --------------------------------------------

def _w_long(buf: io.BytesIO, n: int) -> None:
    n = (n << 1) ^ (n >> 63)                     # zigzag
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes((b | 0x80,)))
        else:
            buf.write(bytes((b,)))
            return


def _w_bytes(buf: io.BytesIO, b: bytes) -> None:
    _w_long(buf, len(b))
    buf.write(b)


def _r_long(buf) -> int:
    shift, acc = 0, 0
    while True:
        byte = buf.read(1)
        if not byte:
            raise ValueError("truncated avro file: unexpected EOF in varint")
        b = byte[0]
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)               # un-zigzag


def _r_bytes(buf) -> bytes:
    return buf.read(_r_long(buf))


# -- arrow <-> avro schema mapping ------------------------------------------

def _avro_type(t: pa.DataType):
    if pa.types.is_boolean(t):
        return "boolean"
    if pa.types.is_date32(t):
        return {"type": "int", "logicalType": "date"}
    if pa.types.is_integer(t):
        return "int" if t.bit_width <= 32 else "long"
    if pa.types.is_float32(t):
        return "float"
    if pa.types.is_floating(t):
        return "double"
    if pa.types.is_decimal(t):
        return {"type": "bytes", "logicalType": "decimal",
                "precision": t.precision, "scale": t.scale}
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return "string"
    raise ValueError(f"avro: unsupported arrow type {t}")


def _arrow_type(t) -> pa.DataType:
    if isinstance(t, list):                      # ["null", T]
        inner = [x for x in t if x != "null"]
        return _arrow_type(inner[0])
    if isinstance(t, dict):
        lt = t.get("logicalType")
        if lt == "date":
            return pa.date32()
        if lt == "decimal":
            return pa.decimal128(t["precision"], t["scale"])
        return _arrow_type(t["type"])
    return {"boolean": pa.bool_(), "int": pa.int32(), "long": pa.int64(),
            "float": pa.float32(), "double": pa.float64(),
            "string": pa.string(), "bytes": pa.binary()}[t]


def _schema_json(schema: pa.Schema, name: str) -> str:
    fields = [{"name": f.name, "type": ["null", _avro_type(f.type)]}
              for f in schema]
    return json.dumps({"type": "record", "name": name or "row",
                       "fields": fields})


# -- value encoders (one closure per column type, applied row-wise) ----------

def _encoder(t: pa.DataType):
    if pa.types.is_decimal(t):
        scale = t.scale

        def enc(buf, v):
            unscaled = int(v.scaleb(scale))      # decimal.Decimal in
            length = max(1, (unscaled.bit_length() + 8) // 8)
            _w_bytes(buf, unscaled.to_bytes(length, "big", signed=True))
        return enc
    if pa.types.is_date32(t):
        epoch = __import__("datetime").date(1970, 1, 1)
        return lambda buf, v: _w_long(buf, (v - epoch).days)
    if pa.types.is_boolean(t):
        return lambda buf, v: buf.write(b"\x01" if v else b"\x00")
    if pa.types.is_integer(t):
        return _w_long
    if pa.types.is_float32(t):
        return lambda buf, v: buf.write(struct.pack("<f", v))
    if pa.types.is_floating(t):
        return lambda buf, v: buf.write(struct.pack("<d", v))
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return lambda buf, v: _w_bytes(buf, v.encode("utf-8"))
    raise ValueError(f"avro: unsupported arrow type {t}")


def _decoder(t):
    if isinstance(t, list):
        inner = _decoder([x for x in t if x != "null"][0])

        def dec(buf):
            return None if _r_long(buf) == 0 else inner(buf)
        return dec
    if isinstance(t, dict):
        lt = t.get("logicalType")
        if lt == "date":
            import datetime
            epoch = datetime.date(1970, 1, 1)
            day = datetime.timedelta(days=1)
            return lambda buf: epoch + day * _r_long(buf)
        if lt == "decimal":
            import decimal
            scale = t["scale"]

            def dec(buf):
                raw = _r_bytes(buf)
                return decimal.Decimal(
                    int.from_bytes(raw, "big", signed=True)).scaleb(-scale)
            return dec
        return _decoder(t["type"])
    return {
        "boolean": lambda buf: buf.read(1) == b"\x01",
        "int": _r_long, "long": _r_long,
        "float": lambda buf: struct.unpack("<f", buf.read(4))[0],
        "double": lambda buf: struct.unpack("<d", buf.read(8))[0],
        "string": lambda buf: _r_bytes(buf).decode("utf-8"),
        "bytes": _r_bytes,
    }[t]


# -- container read/write ----------------------------------------------------

def write_avro(table: pa.Table, path: str, compression: str | None = None,
               name: str | None = None) -> None:
    """Write an arrow table as one Avro Object Container File.

    Row-at-a-time pure-python codec: functional-only by design — avro is
    for format coverage and round-trip validation, not the timed Load
    Test path (use parquet/orc there; this encoder is orders of magnitude
    slower on SF>=1 fact tables).
    """
    if compression in ("deflate", "zlib"):
        codec = "deflate"
    elif compression in (None, "none", "null", "uncompressed"):
        codec = "null"
    else:
        raise ValueError(
            f"unsupported avro codec {compression!r}: this writer "
            "implements deflate and null only")
    sync = os.urandom(16)
    encoders = [_encoder(f.type) for f in table.schema]
    cols = [table.column(i).to_pylist() for i in range(table.num_columns)]
    with open(path, "wb") as f:
        head = io.BytesIO()
        head.write(MAGIC)
        meta = {"avro.schema": _schema_json(table.schema, name),
                "avro.codec": codec}
        _w_long(head, len(meta))
        for k, v in meta.items():
            _w_bytes(head, k.encode())
            _w_bytes(head, v.encode())
        _w_long(head, 0)                          # end of metadata map
        head.write(sync)
        f.write(head.getvalue())
        for lo in range(0, table.num_rows, _BLOCK_ROWS):
            hi = min(lo + _BLOCK_ROWS, table.num_rows)
            block = io.BytesIO()
            for r in range(lo, hi):
                for enc, col in zip(encoders, cols):
                    v = col[r]
                    if v is None:
                        _w_long(block, 0)         # union branch: null
                    else:
                        _w_long(block, 1)
                        enc(block, v)
            data = block.getvalue()
            if codec == "deflate":
                data = zlib.compress(data)[2:-4]  # raw deflate per spec
            out = io.BytesIO()
            _w_long(out, hi - lo)
            _w_bytes(out, data)
            out.write(sync)
            f.write(out.getvalue())


def read_avro(path: str) -> pa.Table:
    """Read an Avro Object Container File back into arrow."""
    with open(path, "rb") as f:
        raw = f.read()
    buf = io.BytesIO(raw)
    if buf.read(4) != MAGIC:
        raise ValueError(f"not an avro container file: {path}")
    meta = {}
    while True:
        n = _r_long(buf)
        if n == 0:
            break
        if n < 0:
            # spec: a negative block count is followed by the block's
            # byte size, then |n| entries
            _r_long(buf)
            n = -n
        for _ in range(n):
            k = _r_bytes(buf).decode()
            meta[k] = _r_bytes(buf)
    sync = buf.read(16)
    schema = json.loads(meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode()
    decoders = [(_decoder(fld["type"])) for fld in schema["fields"]]
    names = [fld["name"] for fld in schema["fields"]]
    rows = [[] for _ in names]
    while buf.tell() < len(raw):
        count = _r_long(buf)
        data = _r_bytes(buf)
        if buf.read(16) != sync:
            raise ValueError("avro: sync marker mismatch")
        if codec == "deflate":
            data = zlib.decompress(data, wbits=-15)
        block = io.BytesIO(data)
        for _ in range(count):
            for dec, acc in zip(decoders, rows):
                acc.append(dec(block))
    arrow_types = [_arrow_type(fld["type"]) for fld in schema["fields"]]
    arrays = [pa.array(vals, type=t) for vals, t in zip(rows, arrow_types)]
    return pa.table(arrays, names=names)
