# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Persistent pre-encoded chunk store: the wire format, written once.

The compiled streaming executor uploads every chunk in a WIRE format
that is whole-table-stable by construction: string columns as int32
codes into one whole-table dictionary, int-path columns as narrow
FOR/sorted-dict codes (``io/columnar.plan_column_codec``), everything
else as the lowered device representation (dates as int32 days,
decimals as scaled int64). Until now every process re-derived that
format from arrow on every run — dictionary encodes, codec stats
passes, per-chunk arrow slices — even though none of it can change
while the data doesn't. This module persists the wire format ONCE:

* :func:`save_plan` writes one directory per table under
  ``NDS_TPU_CHUNK_STORE``: a schema-versioned ``manifest.json`` (codec
  plan, dtypes, per-file CRCs, a content fingerprint of the source
  arrow table) plus one ``.npy`` per buffer — the whole-table code /
  validity arrays ``ChunkedTable.padded_chunks`` slices per chunk.
* :func:`load_plan` memory-maps those arrays straight back
  (``np.load(mmap_mode="r")``): a warm run slices mmapped codes into
  the prefetch ring and never touches arrow slicing or codec planning
  again — the files ARE the upload format.

Integrity is refused at load, recovered at the caller, staleness
silent (the ``chunk-store-read``/``-write`` seams of DESIGN.md
"Fault-tolerance contract"):

* **version gate** — a manifest whose ``version`` is not this module's
  :data:`STORE_VERSION` raises :class:`ChunkStoreError`: FATAL — an old
  (or newer) writer's layout must never be silently reinterpreted.
* **checksum** — every buffer file carries a CRC32 in the manifest,
  verified at load before the mmap is handed out; a mismatch (torn
  write, bit rot) raises :class:`ChunkStoreCorrupt` rather than
  uploading corrupt codes. TRANSIENT: the engine caller
  (``ChunkedTable._wire_plan``) deletes the entry, re-encodes from the
  source arrow once, and records a FaultEvent — the statement survives,
  wrong codes never upload.
* **stale-codec-plan invalidation** — the manifest records a content
  fingerprint of the source table (row count, schema, buffer sizes and
  head/tail samples, the codec-relevant knobs); a table whose data
  changed no longer matches, the stale entry reads as a MISS, and the
  caller re-encodes and overwrites. Data changes are legitimate; only
  corruption is an error.

The store is keyed by table IDENTITY (column names + canonical types +
row count), so a re-generated table of the same shape reuses the same
directory slot and invalidation-by-fingerprint does the rest.

Concurrent-writer safety: writers serialize on a pid-stamped lock file
per entry slot (:func:`_acquire_entry_lock`; a second LIVE writer
yields — the first writer's entry is equally valid — while a dead
pid's or over-age lock is stolen), buffers land in a temp dir, and ONE
atomic ``os.replace`` swaps the entry in. Two processes warming one
store directory can never interleave inside a slot, and a writer
killed mid-write leaves either the old-valid entry or none — never a
half entry — plus a stale lock the next writer reclaims (proven by the
killed-writer subprocess test in ``tests/test_chunk_store.py``).

Env: ``NDS_TPU_CHUNK_STORE`` (directory; unset/empty = store off),
``NDS_TPU_CHUNK_STORE_VERIFY`` (default on; ``0`` skips the full CRC
read at load for very large trusted stores) and
``NDS_TPU_CHUNK_STORE_LOCK_STALE_S`` (writer-lock steal age, default
600), all read at use time like every other knob.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
import zlib
from dataclasses import dataclass
from hashlib import sha256

import numpy as np

# schema version of the on-disk layout; bump on any incompatible change
STORE_VERSION = 1

# content-fingerprint sampling: CRC the head and tail plus evenly
# spaced interior blocks of every arrow buffer — bounded per column
# (<= (2 + _SAMPLE_STRIDES) x _SAMPLE_BYTES), yet a data regeneration
# that changes values anywhere in the buffer is overwhelmingly likely
# to touch a sampled page (a single flipped value between two sample
# points can in principle slip through — the residual risk of not
# hashing whole >HBM buffers; delete the entry to force a re-encode)
_SAMPLE_BYTES = 1 << 16
_SAMPLE_STRIDES = 16

_MANIFEST = "manifest.json"


class ChunkStoreError(RuntimeError):
    """A store entry that must not be used. Version drift stays in this
    base class — FATAL by classification (an old layout must never be
    silently reinterpreted; the operator deletes or upgrades)."""


class ChunkStoreCorrupt(ChunkStoreError):
    """A corrupt entry: checksum mismatch, torn write, missing buffer
    file, unreadable manifest. TRANSIENT by classification
    (``chunk-store-read`` seam): the store is a cache of the source
    arrow data, so the caller (``engine/table.ChunkedTable._wire_plan``)
    deletes the entry, re-encodes from source ONCE, and records a
    FaultEvent — wrong codes are never uploaded, and a single flipped
    bit no longer fails the statement. Loaded directly (tests, tools),
    this still raises loudly."""


def store_root() -> str | None:
    """``NDS_TPU_CHUNK_STORE`` (read at use time): the store directory,
    or None when the store is off."""
    root = os.environ.get("NDS_TPU_CHUNK_STORE", "").strip()
    return root or None


@dataclass
class WireColumn:
    """The wire form of one column — exactly what ``padded_chunks``
    slices per chunk.

    ``codec``: ``"str"`` (dictionary codes + host value table),
    ``"enc"`` (narrow FOR/dict codes + ``Encoding``), or ``"plain"``
    (the lowered device representation). ``data`` is the whole-table
    code/value array (possibly a read-only mmap), ``valid`` the
    whole-table validity or None, ``values`` the host value table
    (str: object array; enc-dict: the Encoding carries it), ``enc`` the
    :class:`nds_tpu.engine.column.Encoding` for ``"enc"`` columns, and
    ``kind`` the device kind the sliced Column is built with."""

    codec: str
    data: np.ndarray
    valid: np.ndarray | None
    values: np.ndarray | None
    enc: object | None
    kind: str


def _identity_digest(arrow, canonical_types: dict) -> str:
    """Directory key: table shape identity (names, canonical types, row
    count). Content changes keep the slot and invalidate by
    fingerprint."""
    from nds_tpu import types as _t
    h = sha256()
    h.update(str(arrow.num_rows).encode())
    for name in arrow.column_names:
        ct = (canonical_types or {}).get(name) or _t.arrow_to_canonical(
            arrow.schema.field(name).type)
        h.update(f"{name}:{ct};".encode())
    return h.hexdigest()[:24]


def table_fingerprint(arrow, canonical_types: dict) -> str:
    """Content fingerprint of the source table: row count, schema, per
    column null count + byte size + CRC of head/tail buffer samples,
    plus the codec-relevant knobs (``NDS_TPU_ENCODED``,
    ``DICT_MAX_VALUES``). Any data regeneration that changes values
    moves this; the stale store entry then reads as a miss."""
    from nds_tpu import types as _t
    from nds_tpu.io.columnar import DICT_MAX_VALUES, encoded_enabled
    h = sha256()
    h.update(f"v{STORE_VERSION};rows={arrow.num_rows};"
             f"enc={int(encoded_enabled())};dict={DICT_MAX_VALUES};"
             .encode())
    for name in arrow.column_names:
        ct = (canonical_types or {}).get(name) or _t.arrow_to_canonical(
            arrow.schema.field(name).type)
        col = arrow.column(name)
        h.update(f"{name}:{ct}:{col.null_count}:{col.nbytes};".encode())
        crc = 0
        for chunk in getattr(col, "chunks", [col]):
            for buf in chunk.buffers():
                if buf is None:
                    continue
                mv = memoryview(buf)
                n = len(mv)
                crc = zlib.crc32(bytes(mv[:_SAMPLE_BYTES]), crc)
                if n > _SAMPLE_BYTES:
                    crc = zlib.crc32(bytes(mv[-_SAMPLE_BYTES:]), crc)
                # interior strides: mid-buffer edits must move the
                # fingerprint too, not just head/tail pages
                if n > 2 * _SAMPLE_BYTES:
                    step = max((n - 2 * _SAMPLE_BYTES)
                               // (_SAMPLE_STRIDES + 1), 1)
                    for s in range(_SAMPLE_BYTES + step,
                                   n - _SAMPLE_BYTES,
                                   step)[:_SAMPLE_STRIDES]:
                        crc = zlib.crc32(
                            bytes(mv[s:s + _SAMPLE_BYTES]), crc)
        h.update(crc.to_bytes(4, "little"))
    return h.hexdigest()


def _crc_file(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                return crc
            crc = zlib.crc32(block, crc)


def _entry_dir(root: str, arrow, canonical_types: dict) -> str:
    return os.path.join(root, _identity_digest(arrow, canonical_types))


def invalidate_entry(root: str, arrow, canonical_types: dict) -> None:
    """Delete one table's store entry (the corrupt-entry recovery of the
    ``chunk-store-read`` seam): the next ``load_plan`` reads a MISS and
    the caller re-encodes from source."""
    import shutil
    shutil.rmtree(_entry_dir(root, arrow, canonical_types),
                  ignore_errors=True)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True      # e.g. EPERM: exists but not ours
    return True


def lock_stale_s() -> float:
    """``NDS_TPU_CHUNK_STORE_LOCK_STALE_S`` (default 600, read at use):
    age past which a writer lock is stolen even when its recorded pid
    appears alive (pid reuse on a long-lived host)."""
    try:
        return float(os.environ.get("NDS_TPU_CHUNK_STORE_LOCK_STALE_S",
                                    "600"))
    except ValueError:
        return 600.0


def _acquire_entry_lock(final: str):
    """The concurrent-writer lock of one entry slot: an ``O_EXCL`` lock
    file beside the entry dir, pid recorded inside. Returns the lock
    path, or None when another LIVE writer holds it (the caller then
    skips persisting — the other writer's entry is equally valid). A
    lock whose pid is dead (killed writer) or whose mtime is past the
    staleness bound is STOLEN: a kill mid-write must never wedge the
    slot forever."""
    path = final + ".lock"
    for _attempt in (0, 1):
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            try:
                os.write(fd, str(os.getpid()).encode())
            finally:
                os.close(fd)
            return path
        except FileExistsError:
            try:
                with open(path) as f:
                    pid = int(f.read().strip() or "0")
            except FileNotFoundError:
                continue                 # lock vanished: retry O_EXCL
            except (OSError, ValueError):
                pid = 0
            try:
                age = time.time() - os.path.getmtime(path)
            except OSError:
                continue                 # lock vanished: retry O_EXCL
            # pid 0 = not yet stamped (a writer caught between its
            # O_EXCL and its write): only the AGE bound may steal it —
            # treating unstamped-as-dead would unlink a live writer's
            # fresh lock and let two writers interleave in one slot
            stale = age > lock_stale_s() or \
                (pid > 0 and not _pid_alive(pid))
            if not stale:
                return None              # live writer: let it win
            # steal ATOMICALLY via rename: of N concurrent stealers
            # exactly one wins (the losers' rename raises ENOENT), so a
            # freshly re-acquired lock can never be unlinked out from
            # under its new holder; the winner retries the O_EXCL
            grave = f"{path}.stale-{os.getpid()}"
            try:
                os.rename(path, grave)
                os.unlink(grave)
            except OSError:
                pass                     # lost the steal race: retry
    return None


def _release_entry_lock(path: str) -> None:
    """Unlink the lock ONLY while it still holds our pid: after an
    age-based steal the slot's lock belongs to the STEALER, and blindly
    unlinking it would invite a third writer in beside them."""
    try:
        with open(path) as f:
            if f.read().strip() != str(os.getpid()):
                return
    except OSError:
        return                           # gone or unreadable: not ours
    try:
        os.unlink(path)
    except OSError:
        pass


def save_plan(root: str, arrow, canonical_types: dict,
              plan: dict) -> str | None:
    """Persist one table's wire plan (``name -> WireColumn``) under
    ``root``; returns the entry directory, or None when another live
    writer holds the entry's lock (its entry is equally valid — the
    caller serves its in-memory plan).

    Concurrent-writer safety (the ``chunk-store-write`` seam): writers
    serialize on a pid-stamped lock file per entry slot, buffers land in
    a temp dir, and ONE atomic ``os.replace`` swaps the entry in — so
    two processes warming one store directory can never interleave
    inside a slot, and a writer killed mid-write leaves either the
    old-valid entry or none (plus a stale lock the next writer steals by
    pid liveness / age), never a half entry."""
    import shutil
    final = _entry_dir(root, arrow, canonical_types)
    os.makedirs(root, exist_ok=True)
    lock = _acquire_entry_lock(final)
    if lock is None:
        return None
    tmp = None
    try:
        from nds_tpu.engine import faults as _F
        tmp = tempfile.mkdtemp(prefix=".chunkstore-", dir=root)
        cols = []
        for i, name in enumerate(arrow.column_names):
            wc = plan[name]
            rec = {"name": name, "codec": wc.codec, "kind": wc.kind,
                   "dtype": np.dtype(wc.data.dtype).str,
                   "has_valid": wc.valid is not None, "crc": {}}
            dp = os.path.join(tmp, f"{i:03d}.data.npy")
            np.save(dp, np.ascontiguousarray(wc.data))
            rec["crc"]["data"] = _crc_file(dp)
            # chunk-store-write seam: a hang-kind injection parks the
            # writer mid-entry — the killed-writer test SIGKILLs here
            # and the old-valid-or-none guarantee must hold
            _F.fault_point("chunk-store-write", detail=name)
            if wc.valid is not None:
                vp = os.path.join(tmp, f"{i:03d}.valid.npy")
                np.save(vp, np.ascontiguousarray(wc.valid))
                rec["crc"]["valid"] = _crc_file(vp)
            if wc.codec == "str":
                sp = os.path.join(tmp, f"{i:03d}.values.json")
                with open(sp, "w") as f:
                    json.dump([str(v) for v in wc.values], f)
                rec["crc"]["values"] = _crc_file(sp)
            elif wc.codec == "enc":
                rec["enc_mode"] = wc.enc.mode
                rec["enc_base"] = int(wc.enc.base)
                if wc.enc.values is not None:
                    ep = os.path.join(tmp, f"{i:03d}.values.npy")
                    np.save(ep, np.ascontiguousarray(wc.enc.values))
                    rec["crc"]["values"] = _crc_file(ep)
            cols.append(rec)
        manifest = {"version": STORE_VERSION,
                    "fingerprint": table_fingerprint(arrow,
                                                     canonical_types),
                    "nrows": int(arrow.num_rows), "columns": cols}
        with open(os.path.join(tmp, _MANIFEST), "w") as f:
            json.dump(manifest, f, indent=1)
        # the one swap: under the lock no concurrent writer can land
        # between the rmtree and the replace, so the slot is always
        # old-valid, new-valid, or (for the instant between the two
        # calls under a kill) absent — never interleaved
        if os.path.isdir(final):
            shutil.rmtree(final, ignore_errors=True)
        os.replace(tmp, final)
        tmp = None
        return final
    except BaseException:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)
        raise
    finally:
        _release_entry_lock(lock)


def verify_enabled() -> bool:
    """``NDS_TPU_CHUNK_STORE_VERIFY`` (default on): full CRC
    verification of every wire file at load. The read streams each
    buffer once (it also warms the page cache the mmap will hit);
    operators of very large stores who trust their storage layer can
    set ``0`` to hand the mmap out unchecked — corruption then
    surfaces as wrong data, not a refusal, so the default stays on."""
    return os.environ.get("NDS_TPU_CHUNK_STORE_VERIFY", "1") != "0"


def _load_buffer(d: str, fname: str, want_crc: int, mmap: bool):
    path = os.path.join(d, fname)
    if not os.path.exists(path):
        raise ChunkStoreCorrupt(
            f"chunk store entry {d} is missing {fname} (torn write?); "
            "delete the entry to re-encode")
    if verify_enabled():
        got = _crc_file(path)
        if got != want_crc:
            raise ChunkStoreCorrupt(
                f"chunk store checksum mismatch on {path}: manifest "
                f"{want_crc:#010x} != file {got:#010x}; refusing to "
                "upload corrupt wire data — delete the entry to "
                "re-encode")
    return np.load(path, mmap_mode="r" if mmap else None)


def load_plan(root: str, arrow, canonical_types: dict,
              mmap: bool = True) -> dict | None:
    """The stored wire plan (``name -> WireColumn``) for this table, or
    None on a MISS (no entry, or the entry's fingerprint no longer
    matches the source data — the stale-codec-plan invalidation).
    Raises :class:`ChunkStoreError` on version drift or checksum
    failure — never silently serves a suspect entry."""
    from nds_tpu.engine import faults as _F
    from nds_tpu.engine.column import Encoding
    # chunk-store-read seam (transient): an injected read fault takes
    # the same recovery as a real corrupt entry — delete + re-encode at
    # the caller, evidence-recorded
    _F.fault_point("chunk-store-read")
    d = _entry_dir(root, arrow, canonical_types)
    mpath = os.path.join(d, _MANIFEST)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as exc:
        raise ChunkStoreCorrupt(
            f"chunk store manifest {mpath} unreadable: {exc}; delete "
            "the entry to re-encode") from exc
    if manifest.get("version") != STORE_VERSION:
        raise ChunkStoreError(
            f"chunk store entry {d} has layout version "
            f"{manifest.get('version')!r}, this build reads "
            f"{STORE_VERSION}; delete the entry (or upgrade) to "
            "re-encode")
    if manifest.get("fingerprint") != table_fingerprint(arrow,
                                                        canonical_types):
        return None                      # data changed: stale, re-encode
    if manifest.get("nrows") != arrow.num_rows or \
            [c["name"] for c in manifest.get("columns", [])] != \
            list(arrow.column_names):
        return None                      # shape drift: stale, re-encode
    plan = {}
    for i, rec in enumerate(manifest["columns"]):
        data = _load_buffer(d, f"{i:03d}.data.npy", rec["crc"]["data"],
                            mmap)
        valid = None
        if rec["has_valid"]:
            valid = _load_buffer(d, f"{i:03d}.valid.npy",
                                 rec["crc"]["valid"], mmap)
        values, enc = None, None
        if rec["codec"] == "str":
            sp = os.path.join(d, f"{i:03d}.values.json")
            if not os.path.exists(sp):
                raise ChunkStoreCorrupt(
                    f"chunk store entry {d} is missing {sp} (torn "
                    "write?); delete the entry to re-encode")
            if verify_enabled() and _crc_file(sp) != rec["crc"]["values"]:
                raise ChunkStoreCorrupt(
                    f"chunk store checksum mismatch on {sp}; refusing "
                    "to decode against a corrupt dictionary")
            with open(sp) as f:
                values = np.asarray(json.load(f), dtype=object)
            if values.size == 0:
                values = np.asarray([""], dtype=object)
        elif rec["codec"] == "enc":
            ev = None
            if "values" in rec["crc"]:
                ev = np.asarray(_load_buffer(
                    d, f"{i:03d}.values.npy", rec["crc"]["values"],
                    mmap=False))
            enc = Encoding(rec["enc_mode"], rec["enc_base"], ev)
        plan[rec["name"]] = WireColumn(rec["codec"], data, valid,
                                       values, enc, rec["kind"])
    return plan


def lower_plain_column(arr, canonical_type: str):
    """Whole-table HOST lowering of one non-encoded column to its device
    representation (the numpy math of
    ``engine/column.from_arrow_array``, minus the upload and padding):
    dates as int32 days, decimals as scaled int64, numerics at their
    device dtype. Returns ``(data, valid | None)`` — the arrays
    ``padded_chunks`` slices per chunk instead of re-slicing arrow."""
    import pyarrow as pa
    import pyarrow.compute as pc

    from nds_tpu import types as _t
    from nds_tpu.engine.column import _decimal_to_int64, dec_scale

    if isinstance(arr, pa.Array):
        arr = pa.chunked_array([arr])
    kind = _t.device_kind(canonical_type)
    valid = None
    if arr.null_count:
        valid = ~np.asarray(pc.is_null(arr).combine_chunks().to_numpy(
            zero_copy_only=False))
    if kind.startswith("dec("):
        s = dec_scale(kind)
        if pa.types.is_decimal(arr.type):
            filled = pc.fill_null(arr, pa.scalar(0, arr.type)) \
                if arr.null_count else arr
            data = _decimal_to_int64(filled, arr.type.scale, s)
        else:
            data = np.asarray(pc.fill_null(arr, 0).combine_chunks()
                              .to_numpy(zero_copy_only=False))
            data = np.round(data * (10 ** s)).astype(np.int64)
        return data, valid
    if kind == "date":
        arr = pc.cast(arr, pa.int32())
    filled = pc.fill_null(arr, 0) if arr.null_count else arr
    np_dtype = {"i32": np.int32, "i64": np.int64, "f64": np.float64,
                "date": np.int32, "bool": np.bool_}[kind]
    data = np.asarray(filled.combine_chunks().to_numpy(
        zero_copy_only=False)).astype(np_dtype)
    return data, valid
