# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Columnar table IO: Parquet / ORC / Avro / CSV / JSON read+write with
hive-style date partitioning.

Covers the reference's Load Test output surface (ref: nds/nds_transcode.py:
69-152): the seven fact tables are date-partitioned, everything else is
written as a single file, with per-format compression options.
"""

from __future__ import annotations

import os

import pyarrow as pa
import pyarrow.dataset as pads

# ---------------------------------------------------------------------------
# encoded columnar execution: per-column narrow-upload codecs
# ---------------------------------------------------------------------------
#
# The streamed scan path (engine/table.py padded_chunks) uploads int-path
# columns in a narrow ENCODED representation chosen here, once per table,
# from whole-table stats — chunk-invariant by construction, exactly like
# the whole-table string dictionaries (per "GPU Acceleration of SQL
# Analytics on Compressed Data", PAPERS.md):
#
#   * frame-of-reference ("for"): store value - base as int16/int32 where
#     the table's value span proves the narrow width (dates and surrogate
#     keys span tiny windows; decimal(7,2) always fits int32 by type);
#   * sorted dictionary ("dict"): int16 codes into a sorted host value
#     table for low-cardinality ints whose span is too wide for FOR.
#
# Both are order-preserving, so predicates/joins/group-bys evaluate on
# encoded values inside the jitted chunk program and decode happens only
# at materialize (engine/column.py). A column whose span fits no narrow
# width stays unencoded — the narrow-width overflow guard.
# NDS_TPU_ENCODED=0 disables the whole path.


def encoded_enabled() -> bool:
    """``NDS_TPU_ENCODED`` gate (default on; "0" preserves the unencoded
    path). Read at USE time, never frozen at import."""
    return os.environ.get("NDS_TPU_ENCODED", "1") != "0"


# max distinct values for the sorted-dictionary codec (int16 codes with
# headroom; past this the value-table gather stops paying for itself)
DICT_MAX_VALUES = 4096


def plan_column_codec(arr, canonical_type: str):
    """``(narrow whole-table codes, valid | None, Encoding)`` for one
    arrow column, or None when the column is not narrowably encodable
    (non-int kind, empty table, or value span past every narrow width —
    the overflow guard; an ALL-NULL int column encodes as trivial FOR so
    the static width model never under-prices it). ``arr`` is the WHOLE
    table's column (Array or ChunkedArray): stats and codes are computed
    once, so the encoding is identical for every chunk sliced from it.

    Every numeric claim this function makes (the 2^15 / 2^31 - 1 span
    rules, dict refusal past DICT_MAX_VALUES, all-null/empty trivial FOR,
    order preservation) is an executable boundary check in
    ``analysis/num_audit.codec_claim_checks`` — a ``num-claim`` lint
    finding fires if any of them stops being true — and the per-statement
    codec-fit proofs mirror the width rules in
    ``num_audit.codec_width_verdict``."""
    import numpy as np

    from nds_tpu import types as _t
    from nds_tpu.engine.column import Encoding, _decimal_to_int64

    kind = _t.device_kind(canonical_type)
    if kind not in ("i32", "i64", "date") and not kind.startswith("dec("):
        return None
    if isinstance(arr, pa.Array):
        arr = pa.chunked_array([arr])
    n = len(arr)
    if n == 0:
        # empty table: same trivial-FOR rule as all-null below — the
        # padded chunk still allocates full capacity, so the upload must
        # stay at (or below) the static model's narrow pricing
        import numpy as np

        from nds_tpu.engine.column import Encoding
        return np.zeros(0, dtype=np.int16), None, Encoding("for", 0, None)
    import pyarrow.compute as pc
    valid = None
    if arr.null_count:
        valid = ~np.asarray(pc.is_null(arr).combine_chunks().to_numpy(
            zero_copy_only=False))
    # logical device values (the exact representation engine/column.py
    # lowers to): dates as int32 days, decimals as scaled int64
    if kind.startswith("dec("):
        from nds_tpu.engine.column import dec_scale
        s = dec_scale(kind)
        if pa.types.is_decimal(arr.type):
            filled = pc.fill_null(arr, pa.scalar(0, arr.type)) \
                if arr.null_count else arr
            vals = _decimal_to_int64(filled, arr.type.scale, s)
        else:
            vals = np.asarray(pc.fill_null(arr, 0).combine_chunks()
                              .to_numpy(zero_copy_only=False))
            vals = np.round(vals * (10 ** s)).astype(np.int64)
    else:
        if kind == "date":
            arr = pc.cast(arr, pa.int32())
        filled = pc.fill_null(arr, 0) if arr.null_count else arr
        vals = np.asarray(filled.combine_chunks().to_numpy(
            zero_copy_only=False)).astype(np.int64)
    live = vals if valid is None else vals[valid]
    if live.size == 0:
        # all-null column: trivially FOR-encodable (every slot invalid),
        # so the static width model's narrow pricing stays an upper
        # bound on what the runtime actually uploads and accumulates
        return (np.zeros(n, dtype=np.int16), valid,
                Encoding("for", 0, None))
    lo, hi = int(live.min()), int(live.max())
    span = hi - lo
    logical_bytes = 4 if kind in ("i32", "date") else 8
    # frame-of-reference first (cheapest decode: one fused add)
    if span < (1 << 15):
        dtype = np.int16
    elif span < (1 << 31) - 1 and logical_bytes == 8:
        dtype = np.int32
    else:
        dtype = None
    if dtype is None:
        # no FOR width fits: a sorted dictionary is the only narrow
        # option (wide-span low-cardinality columns). Distinct-count a
        # SAMPLE first — a full np.unique sorts the whole fact column on
        # host, and sequence-like keys always blow past DICT_MAX_VALUES
        if live.size > (1 << 16) and \
                len(np.unique(live[:1 << 16])) > DICT_MAX_VALUES:
            return None                  # narrow-width overflow guard
        uniq = np.unique(live)
        if len(uniq) <= DICT_MAX_VALUES:
            codes = np.searchsorted(uniq, vals).astype(np.int16)
            codes = np.clip(codes, 0, len(uniq) - 1)
            if valid is not None:
                codes = np.where(valid, codes, np.int16(0))
            return codes, valid, Encoding("dict", 0, uniq.astype(np.int64))
        return None                      # narrow-width overflow guard
    codes = (vals - lo).astype(dtype)
    if valid is not None:
        codes = np.where(valid, codes, dtype(0))
    return codes, valid, Encoding("for", lo, None)


def plan_table_codecs(table: pa.Table, canonical_types: dict | None = None):
    """name -> (codes, valid, Encoding) for every encodable column of an
    arrow table — the per-table encoding plan ``ChunkedTable`` caches and
    ``padded_chunks`` slices per chunk."""
    from nds_tpu import types as _t
    out = {}
    for name in table.column_names:
        ct = (canonical_types or {}).get(name) or _t.arrow_to_canonical(
            table.schema.field(name).type)
        got = plan_column_codec(table[name], ct)
        if got is not None:
            out[name] = got
    return out


# The 7 date-partitioned fact tables (ref: nds/nds_transcode.py:45-53)
TABLE_PARTITIONING = {
    "catalog_sales": "cs_sold_date_sk",
    "catalog_returns": "cr_returned_date_sk",
    "inventory": "inv_date_sk",
    "store_sales": "ss_sold_date_sk",
    "store_returns": "sr_returned_date_sk",
    "web_sales": "ws_sold_date_sk",
    "web_returns": "wr_returned_date_sk",
}


def _hive_partition_runs(table: pa.Table, partition_col: str):
    """Yield (partition dir name, partition slice) by sorting on the
    partition column and slicing contiguous runs — ONE pass, one file per
    partition. pyarrow's dataset writer churns past its open-file cap when
    a fact table has a 5-year daily date_sk domain (observed: 54M tiny
    write syscalls on store_sales SF1), so both formats partition through
    this path (Spark's partitionBy sort-within semantics; ref:
    nds/nds_transcode.py:69-152 date-partitioned fact tables)."""
    import numpy as np
    order = pa.compute.sort_indices(
        table, sort_keys=[(partition_col, "ascending")])
    sorted_tbl = table.take(order)
    col = sorted_tbl[partition_col].to_numpy(zero_copy_only=False)
    # nulls sort to the end and surface as NaN; NaN != NaN would split
    # them into 1-row runs, so bound the non-null region first
    n_null = int(pa.compute.is_null(sorted_tbl[partition_col]).to_numpy(
        zero_copy_only=False).sum())
    n_valid = len(col) - n_null
    valid = col[:n_valid]
    boundaries = [0] + list(np.nonzero(valid[1:] != valid[:-1])[0] + 1) + \
        [n_valid]
    if n_null:
        boundaries.append(len(col))
    for lo, hi in zip(boundaries[:-1], boundaries[1:]):
        value = col[lo]
        if value is None or value != value:          # null (None or NaN)
            part_name = "__HIVE_DEFAULT_PARTITION__"
        else:
            # nullable int columns surface as floats in numpy; keep
            # integral partition names so hive read-back types match
            part_name = str(int(value)) if float(value).is_integer() \
                else str(value)
        yield (f"{partition_col}={part_name}",
               sorted_tbl.slice(lo, hi - lo).drop_columns([partition_col]))


def write_table(table: pa.Table, path: str, fmt: str = "parquet",
                partition_col: str | None = None, compression: str | None = None) -> None:
    os.makedirs(path, exist_ok=True)
    if fmt == "parquet":
        import pyarrow.parquet as pq
        comp = compression or "snappy"
        if partition_col:
            for part_dir, part in _hive_partition_runs(table, partition_col):
                sub = os.path.join(path, part_dir)
                os.makedirs(sub, exist_ok=True)
                pq.write_table(part, os.path.join(sub, "part-0.parquet"),
                               compression=comp)
        else:
            pq.write_table(table, os.path.join(path, "part-0.parquet"), compression=comp)
    elif fmt == "orc":
        import pyarrow.orc as paorc
        comp = compression or "zstd"
        if partition_col:
            for part_dir, part in _hive_partition_runs(table, partition_col):
                sub = os.path.join(path, part_dir)
                os.makedirs(sub, exist_ok=True)
                paorc.write_table(part, os.path.join(sub, "part-0.orc"),
                                  compression=comp)
        else:
            paorc.write_table(table, os.path.join(path, "part-0.orc"),
                              compression=comp)
    elif fmt == "avro":
        from nds_tpu.io.avro import write_avro
        if partition_col:
            for part_dir, part in _hive_partition_runs(table, partition_col):
                sub = os.path.join(path, part_dir)
                os.makedirs(sub, exist_ok=True)
                write_avro(part, os.path.join(sub, "part-0.avro"),
                           compression=compression)
        else:
            write_avro(table, os.path.join(path, "part-0.avro"),
                       compression=compression)
    elif fmt == "csv":
        import pyarrow.csv as pacsv
        pacsv.write_csv(table, os.path.join(path, "part-0.csv"))
    elif fmt == "json":
        import json
        with open(os.path.join(path, "part-0.json"), "w") as f:
            for row in table.to_pylist():
                f.write(json.dumps(row, default=str) + "\n")
    else:
        raise ValueError(f"unsupported output format: {fmt}")


def read_table(path: str, fmt: str = "parquet") -> pa.Table:
    """Read a table written by :func:`write_table` (including hive-partitioned
    layouts) back into arrow."""
    if fmt in ("parquet", "orc"):
        ds = pads.dataset(path, format=fmt, partitioning="hive")
        return ds.to_table()
    if fmt == "avro":
        from nds_tpu.io.avro import read_avro
        parts = []
        for root, _dirs, files in sorted(os.walk(path)):
            for fn in sorted(files):
                if not fn.endswith(".avro"):
                    continue
                t = read_avro(os.path.join(root, fn))
                # restore hive partition columns from the directory path
                rel = os.path.relpath(root, path)
                if rel != ".":
                    for seg in rel.split(os.sep):
                        col, _, val = seg.partition("=")
                        if val == "__HIVE_DEFAULT_PARTITION__":
                            arr = pa.nulls(t.num_rows, type=pa.int64())
                        else:
                            try:
                                arr = pa.array([int(val)] * t.num_rows,
                                               type=pa.int64())
                            except ValueError:  # non-integral partition
                                arr = pa.array([float(val)] * t.num_rows,
                                               type=pa.float64())
                        t = t.append_column(col, arr)
                parts.append(t)
        if not parts:
            raise FileNotFoundError(f"no .avro files under {path}")
        return pa.concat_tables(parts, promote_options="default")
    if fmt == "csv":
        import pyarrow.csv as pacsv
        files = [os.path.join(path, f) for f in sorted(os.listdir(path))
                 if f.endswith(".csv")]
        return pa.concat_tables([pacsv.read_csv(f) for f in files])
    if fmt == "json":
        import pyarrow.json as pajson
        files = [os.path.join(path, f) for f in sorted(os.listdir(path))
                 if f.endswith(".json")]
        return pa.concat_tables([pajson.read_json(f) for f in files])
    raise ValueError(f"unsupported input format: {fmt}")
