# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Columnar table IO: Parquet / ORC / Avro / CSV / JSON read+write with
hive-style date partitioning.

Covers the reference's Load Test output surface (ref: nds/nds_transcode.py:
69-152): the seven fact tables are date-partitioned, everything else is
written as a single file, with per-format compression options.
"""

from __future__ import annotations

import os

import pyarrow as pa
import pyarrow.dataset as pads

# The 7 date-partitioned fact tables (ref: nds/nds_transcode.py:45-53)
TABLE_PARTITIONING = {
    "catalog_sales": "cs_sold_date_sk",
    "catalog_returns": "cr_returned_date_sk",
    "inventory": "inv_date_sk",
    "store_sales": "ss_sold_date_sk",
    "store_returns": "sr_returned_date_sk",
    "web_sales": "ws_sold_date_sk",
    "web_returns": "wr_returned_date_sk",
}


def _hive_partition_runs(table: pa.Table, partition_col: str):
    """Yield (partition dir name, partition slice) by sorting on the
    partition column and slicing contiguous runs — ONE pass, one file per
    partition. pyarrow's dataset writer churns past its open-file cap when
    a fact table has a 5-year daily date_sk domain (observed: 54M tiny
    write syscalls on store_sales SF1), so both formats partition through
    this path (Spark's partitionBy sort-within semantics; ref:
    nds/nds_transcode.py:69-152 date-partitioned fact tables)."""
    import numpy as np
    order = pa.compute.sort_indices(
        table, sort_keys=[(partition_col, "ascending")])
    sorted_tbl = table.take(order)
    col = sorted_tbl[partition_col].to_numpy(zero_copy_only=False)
    # nulls sort to the end and surface as NaN; NaN != NaN would split
    # them into 1-row runs, so bound the non-null region first
    n_null = int(pa.compute.is_null(sorted_tbl[partition_col]).to_numpy(
        zero_copy_only=False).sum())
    n_valid = len(col) - n_null
    valid = col[:n_valid]
    boundaries = [0] + list(np.nonzero(valid[1:] != valid[:-1])[0] + 1) + \
        [n_valid]
    if n_null:
        boundaries.append(len(col))
    for lo, hi in zip(boundaries[:-1], boundaries[1:]):
        value = col[lo]
        if value is None or value != value:          # null (None or NaN)
            part_name = "__HIVE_DEFAULT_PARTITION__"
        else:
            # nullable int columns surface as floats in numpy; keep
            # integral partition names so hive read-back types match
            part_name = str(int(value)) if float(value).is_integer() \
                else str(value)
        yield (f"{partition_col}={part_name}",
               sorted_tbl.slice(lo, hi - lo).drop_columns([partition_col]))


def write_table(table: pa.Table, path: str, fmt: str = "parquet",
                partition_col: str | None = None, compression: str | None = None) -> None:
    os.makedirs(path, exist_ok=True)
    if fmt == "parquet":
        import pyarrow.parquet as pq
        comp = compression or "snappy"
        if partition_col:
            for part_dir, part in _hive_partition_runs(table, partition_col):
                sub = os.path.join(path, part_dir)
                os.makedirs(sub, exist_ok=True)
                pq.write_table(part, os.path.join(sub, "part-0.parquet"),
                               compression=comp)
        else:
            pq.write_table(table, os.path.join(path, "part-0.parquet"), compression=comp)
    elif fmt == "orc":
        import pyarrow.orc as paorc
        comp = compression or "zstd"
        if partition_col:
            for part_dir, part in _hive_partition_runs(table, partition_col):
                sub = os.path.join(path, part_dir)
                os.makedirs(sub, exist_ok=True)
                paorc.write_table(part, os.path.join(sub, "part-0.orc"),
                                  compression=comp)
        else:
            paorc.write_table(table, os.path.join(path, "part-0.orc"),
                              compression=comp)
    elif fmt == "avro":
        from nds_tpu.io.avro import write_avro
        if partition_col:
            for part_dir, part in _hive_partition_runs(table, partition_col):
                sub = os.path.join(path, part_dir)
                os.makedirs(sub, exist_ok=True)
                write_avro(part, os.path.join(sub, "part-0.avro"),
                           compression=compression)
        else:
            write_avro(table, os.path.join(path, "part-0.avro"),
                       compression=compression)
    elif fmt == "csv":
        import pyarrow.csv as pacsv
        pacsv.write_csv(table, os.path.join(path, "part-0.csv"))
    elif fmt == "json":
        import json
        with open(os.path.join(path, "part-0.json"), "w") as f:
            for row in table.to_pylist():
                f.write(json.dumps(row, default=str) + "\n")
    else:
        raise ValueError(f"unsupported output format: {fmt}")


def read_table(path: str, fmt: str = "parquet") -> pa.Table:
    """Read a table written by :func:`write_table` (including hive-partitioned
    layouts) back into arrow."""
    if fmt in ("parquet", "orc"):
        ds = pads.dataset(path, format=fmt, partitioning="hive")
        return ds.to_table()
    if fmt == "avro":
        from nds_tpu.io.avro import read_avro
        parts = []
        for root, _dirs, files in sorted(os.walk(path)):
            for fn in sorted(files):
                if not fn.endswith(".avro"):
                    continue
                t = read_avro(os.path.join(root, fn))
                # restore hive partition columns from the directory path
                rel = os.path.relpath(root, path)
                if rel != ".":
                    for seg in rel.split(os.sep):
                        col, _, val = seg.partition("=")
                        if val == "__HIVE_DEFAULT_PARTITION__":
                            arr = pa.nulls(t.num_rows, type=pa.int64())
                        else:
                            try:
                                arr = pa.array([int(val)] * t.num_rows,
                                               type=pa.int64())
                            except ValueError:  # non-integral partition
                                arr = pa.array([float(val)] * t.num_rows,
                                               type=pa.float64())
                        t = t.append_column(col, arr)
                parts.append(t)
        if not parts:
            raise FileNotFoundError(f"no .avro files under {path}")
        return pa.concat_tables(parts, promote_options="default")
    if fmt == "csv":
        import pyarrow.csv as pacsv
        files = [os.path.join(path, f) for f in sorted(os.listdir(path))
                 if f.endswith(".csv")]
        return pa.concat_tables([pacsv.read_csv(f) for f in files])
    if fmt == "json":
        import pyarrow.json as pajson
        files = [os.path.join(path, f) for f in sorted(os.listdir(path))
                 if f.endswith(".json")]
        return pa.concat_tables([pajson.read_json(f) for f in files])
    raise ValueError(f"unsupported input format: {fmt}")
