# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Raw-data ingest: '|'-delimited, ISO-8859-1, schema-typed CSV reading.

Mirrors the reference load path (ref: nds/nds_transcode.py:56-66: delimiter
'|', encoding ISO-8859-1, explicit schema) on pyarrow. Handles the
dsdgen/ndsgen trailing delimiter by parsing (and dropping) a sentinel last
column. Empty fields are nulls.
"""

from __future__ import annotations

import os

import pyarrow as pa
import pyarrow.csv as pacsv

from nds_tpu import types

_TRAILER = "__nds_trailer__"


def _convert_options(fields) -> pacsv.ConvertOptions:
    column_types = {f.name: types.to_arrow(f.type) for f in fields}
    column_types[_TRAILER] = pa.string()
    return pacsv.ConvertOptions(
        column_types=column_types,
        strings_can_be_null=True,
        quoted_strings_can_be_null=False,
    )


def _read_one(path: str, fields) -> pa.Table:
    names = [f.name for f in fields] + [_TRAILER]
    read_opts = pacsv.ReadOptions(column_names=names, encoding="iso8859-1")
    parse_opts = pacsv.ParseOptions(delimiter="|", quote_char=False)
    table = pacsv.read_csv(path, read_options=read_opts, parse_options=parse_opts,
                           convert_options=_convert_options(fields))
    return table.drop_columns([_TRAILER])


def read_raw_table(path: str, fields) -> pa.Table:
    """Read one raw table from a file or a per-table directory of ``.dat``
    chunk files, returning a typed arrow Table.

    ``fields`` is the schema tuple from :func:`nds_tpu.schema.get_schemas`.
    """
    if os.path.isdir(path):
        chunks = sorted(
            os.path.join(path, f) for f in os.listdir(path) if f.endswith(".dat")
        )
        if not chunks:
            raise FileNotFoundError(f"no .dat chunks under {path}")
        tables = [_read_one(c, fields) for c in chunks]
        return pa.concat_tables(tables)
    return _read_one(path, fields)
