# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Runtime failure listener: the TPU-native stand-in for the reference's
Scala SparkListener + Py4J bridge (ref: nds/jvm_listener/src/main/scala/com/
nvidia/spark/rapids/listener/TaskFailureListener.scala:27-36 and
nds/python_listener/PythonListener.py:21-61).

The reference registers an in-JVM listener that captures every non-Success
task end reason and fans it out to Python callbacks. Here the execution
engine is in-process, so the bridge collapses to a process-local registry:
the engine's partition executor reports every retried/failed partition task
and every device runtime error (XLA/PJRT) to all registered listeners, which
feed the ``CompletedWithTaskFailures`` status taxonomy in
:mod:`nds_tpu.report`.
"""

from __future__ import annotations

import threading
import traceback
from collections import deque
from dataclasses import dataclass, field


@dataclass
class TaskFailure:
    """One failed/retried unit of work inside an otherwise-running query."""

    where: str        # e.g. "partition 3/8 of hash_join probe"
    reason: str       # exception text / device error
    fatal: bool = False


class FailureListener:
    """Accumulates task-failure reasons for one query run
    (ref: nds/python_listener/PythonListener.py:30-49)."""

    def __init__(self):
        self.failures: list[TaskFailure] = []
        self._lock = threading.Lock()

    def notify(self, where: str, reason: str, fatal: bool = False) -> None:
        with self._lock:
            self.failures.append(TaskFailure(where, reason, fatal))

    def register(self) -> "FailureListener":
        Manager.register(self)
        return self

    def unregister(self) -> None:
        Manager.unregister(self)


class Manager:
    """Fan-out registry (ref: nds/jvm_listener/.../Manager.scala:24-63).

    Listeners are scoped to the thread that registered them: concurrent
    in-process query streams (Throughput Run) each see only their own task
    failures. Failures raised from a thread with no scoped listener (e.g. a
    shared device-runtime callback thread) are recorded in
    ``Manager.unattributed`` for diagnostics but are NOT fanned out — one
    stream's device error must never mark every concurrent stream
    ``CompletedWithTaskFailures``.
    """

    _listeners: list[FailureListener] = []       # (owner_thread_id, listener) pairs
    _owners: list[int] = []
    _lock = threading.Lock()
    # bounded ring (newest kept): a failure storm on an unattributed
    # thread must evict O(1) per record, not O(n) list.pop(0)
    _UNATTRIBUTED_MAX = 1000
    unattributed: deque = deque(maxlen=_UNATTRIBUTED_MAX)

    @classmethod
    def register(cls, listener: FailureListener) -> None:
        with cls._lock:
            if listener not in cls._listeners:
                cls._listeners.append(listener)
                cls._owners.append(threading.get_ident())

    @classmethod
    def unregister(cls, listener: FailureListener) -> None:
        with cls._lock:
            if listener in cls._listeners:
                i = cls._listeners.index(listener)
                cls._listeners.pop(i)
                cls._owners.pop(i)

    @classmethod
    def notify_all(cls, where: str, reason: str, fatal: bool = False) -> None:
        me = threading.get_ident()
        with cls._lock:
            targets = [l for l, o in zip(cls._listeners, cls._owners)
                       if o == me]
            if not targets:
                cls.unattributed.append(TaskFailure(where, reason, fatal))
                return
        for l in targets:
            l.notify(where, reason, fatal)


@dataclass
class StreamEvent:
    """Accounting record for one >HBM streamed scan execution: which path
    served it (the compiled chunk pipeline or the eager chunk loop), how
    many chunks flowed, and how many host syncs the pipeline charged —
    the number the streamed-path sync budget (tests/test_synccount.py)
    pins. Drained per query by the drivers (power.py / bench.py) into the
    per-query summaries, next to the plain sync counters."""

    where: str                 # e.g. "store_sales"
    chunks: int
    syncs: int                 # host syncs charged while the scan executed
    path: str                  # "compiled" | "eager"
    reason: str = ""           # why the compiled path was not taken
    rows: int = -1             # survivor rows the scan kept (compiled
    #                            pipeline: the accumulator's final count —
    #                            the number tools/mem_audit_diff.py checks
    #                            against the static bound; -1 = unknown)
    partitions: int = 1        # grace-style partition count of the
    #                            compiled pipeline (1 = unpartitioned)
    part_rows: tuple = ()      # per-partition survivor counts (partition
    #                            order) — checked against the static
    #                            per-partition bounds by mem_audit_diff
    bytes_h2d: int = -1        # actual host->device prefetch bytes the
    #                            scan uploaded (encoded columnar: the
    #                            NARROW representation — compression wins
    #                            are measured here, not asserted; -1 =
    #                            unknown)
    shards: int = 1            # mesh shard count of the compiled pipeline
    #                            (NDS_TPU_STREAM_SHARDS; 1 = single-device)
    collectives: int = -1      # explicit ICI collective ops the sharded
    #                            pipeline issued (exchange all-to-alls x
    #                            chunks + the one cross-shard materialize
    #                            reduce) — the evidence exec_audit's
    #                            static collective budget is checked
    #                            against; -1 = unknown/unsharded
    bytes_ici: int = -1        # wire bytes those collectives moved
    #                            (encoded codes ride the exchange, so
    #                            compression shrinks this too)
    shard_rows: tuple = ()     # per-shard survivor counts (shard order,
    #                            summed over partitions) — checked against
    #                            mem_audit's per-shard bound
    kernel_launches: int = -1  # fused Pallas kernel launches the drive
    #                            issued (scan pre-pass per chunk + join
    #                            probes per dispatch, trace-time counted
    #                            like collectives) — checked against
    #                            exec_audit's static kernel prediction
    #                            by tools/exec_audit_diff.py; -1 =
    #                            unknown (eager path / old events)
    kernel_fused_stages: int = -1  # fused stages per scan-pass launch
    #                            (lowered conjuncts + the routing-hash
    #                            stage); 0 = no fused scan pass ran
    prefetch_stall_ms: float = -1.0  # driver milliseconds BLOCKED on the
    #                            bounded prefetch ring (engine/prefetch)
    #                            across the whole drive; with the ring
    #                            off (NDS_TPU_PREFETCH_DEPTH=0) the
    #                            inline slice+encode+upload time instead
    #                            — the overlap win is this number
    #                            shrinking, measured per scan, never
    #                            asserted; -1 = unknown (old events)


_stream_tls = threading.local()


def record_stream_event(where: str, chunks: int, syncs: int, path: str,
                        reason: str = "", rows: int = -1,
                        partitions: int = 1, part_rows=(),
                        bytes_h2d: int = -1, shards: int = 1,
                        collectives: int = -1, bytes_ici: int = -1,
                        shard_rows=(), kernel_launches: int = -1,
                        kernel_fused_stages: int = -1,
                        prefetch_stall_ms: float = -1.0) -> None:
    """Engine-side hook (engine/stream.py, sql/planner.py): record how a
    streamed scan executed. Thread-scoped like the sync counters, so
    concurrent Throughput streams account their own pipelines."""
    lst = getattr(_stream_tls, "events", None)
    if lst is None:
        # deque(maxlen): diagnostics ring, never unbounded, O(1) evict
        lst = _stream_tls.events = deque(maxlen=1000)
    lst.append(StreamEvent(where, chunks, syncs, path, reason, rows,
                           partitions, tuple(part_rows), bytes_h2d,
                           shards, collectives, bytes_ici,
                           tuple(shard_rows), kernel_launches,
                           kernel_fused_stages, prefetch_stall_ms))


def drain_stream_events() -> list:
    """Return and clear the calling thread's streamed-scan events
    (oldest-first drain order; the ring keeps the newest 1000)."""
    lst = getattr(_stream_tls, "events", None)
    if not lst:
        return []
    out = list(lst)
    lst.clear()
    return out


def stream_event_json(e: StreamEvent) -> dict:
    """The ONE JSON shape of a StreamEvent in driver summaries
    (power.py ``streamedScans`` / bench.py per-query results) — optional
    fields appear only when meaningful, so existing consumers see no new
    keys on unpartitioned scans."""
    return {
        "table": e.where, "chunks": e.chunks, "syncs": e.syncs,
        "path": e.path,
        **({"rows": e.rows} if e.rows >= 0 else {}),
        **({"bytesH2d": e.bytes_h2d} if e.bytes_h2d >= 0 else {}),
        **({"partitions": e.partitions, "partRows": list(e.part_rows)}
           if e.partitions > 1 else {}),
        **({"shards": e.shards, "shardRows": list(e.shard_rows),
            "collectives": e.collectives, "bytesIci": e.bytes_ici}
           if e.shards > 1 else {}),
        **({"kernelLaunches": e.kernel_launches,
            "kernelStages": e.kernel_fused_stages}
           if e.kernel_launches > 0 else {}),
        **({"prefetchStallMs": round(e.prefetch_stall_ms, 3)}
           if e.prefetch_stall_ms >= 0 else {}),
        **({"reason": e.reason} if e.reason else {}),
    }


def stream_evidence(events) -> dict:
    """Aggregate drained :class:`StreamEvent` objects into the compact
    per-query evidence dict the campaign ledger records
    (:mod:`nds_tpu.obs.ledger`): total syncs/chunks, h2d upload and ICI
    wire bytes, partition/shard/collective counts, the compiled-vs-eager
    path split and the fallback reasons. Same aggregation as
    ``ledger.evidence_from_scans`` runs over the JSON shape — this is
    the in-process form for drivers that hold the live events."""
    from nds_tpu.obs.ledger import evidence_from_scans
    return evidence_from_scans([stream_event_json(e) for e in events])


def report_task_failure(where: str, exc: BaseException | str,
                        fatal: bool = False) -> None:
    """Engine-side hook: call on any retried partition task, capacity
    retry, kernel fallback, or device error. ``exc`` may be a caught
    exception or a plain reason string (for retries that raised nothing)."""
    if isinstance(exc, BaseException):
        reason = "".join(
            traceback.format_exception_only(type(exc), exc)).strip()
    else:
        reason = str(exc)
    Manager.notify_all(where, reason, fatal)
