# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Query-trace observability layer.

The reference harness answers "where did the time go?" with Spark's event
log + listener bus; the TPU engine's only slice of that was the failure
listener (:mod:`nds_tpu.listener`) and raw sync counters. This package is
the rest: process-local, thread-scoped span tracing and per-phase metrics
over the planner, the streaming executor and the replay compiler, with a
hard contract — **tracing adds zero host syncs** (host-clock spans only;
device numbers are harvested exclusively at syncs the engine already
pays; ``tests/test_obs.py`` proves sync-count parity traced vs untraced).

* :mod:`nds_tpu.obs.trace` — nestable spans with sync/wait/compile
  counters bridged from :mod:`nds_tpu.engine.ops`, ring-buffer bounded
  and thread-scoped with an explicit drain (the
  ``drain_stream_events`` discipline).
* :mod:`nds_tpu.obs.export` — Chrome ``trace_event`` export
  (``chrome://tracing`` / Perfetto) and the per-query rollup dict the
  drivers merge into their JSON summaries.
* :mod:`nds_tpu.obs.ledger` — the campaign evidence ledger: the
  schema-versioned, flush-per-query, append-only JSONL record both
  drivers write and every post-hoc tool (``tools/bench_compare.py``,
  ``tools/trace_report.py``, ``tools/sync_profile.py``) reads, plus the
  campaign heartbeat thread.
* :mod:`nds_tpu.obs.metrics` — the live half: the process-local
  rolling-rollup registry (counters, gauges, mergeable fixed-bucket
  histograms with deterministic p50/p95/p99) fed only at existing
  drain/evidence points, snapshotted atomically to
  ``NDS_TPU_METRICS_FILE`` for the mid-run monitor
  (``tools/obs_live.py``) and carried in the ledger as ``metrics``
  records.
"""

from nds_tpu.obs.ledger import (LEDGER_VERSION, Heartbeat,  # noqa: F401
                                Ledger, LedgerData, LedgerError,
                                evidence_from_scans, load_ledger)
from nds_tpu.obs.metrics import (METRICS_VERSION, Registry,  # noqa: F401
                                 export_live, merge_hist_snapshots,
                                 quantile_from_buckets)
from nds_tpu.obs.metrics import default as default_registry  # noqa: F401
from nds_tpu.obs.trace import (NULL_SPAN, SpanRecord, SyncSite,  # noqa: F401
                               annotate, attach, drain_spans, on,
                               set_enabled, span, unattributed)
