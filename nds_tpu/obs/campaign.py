# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Campaign orchestration: a declarative arm matrix of bench runs.

ROADMAP item 1 names one measurement campaign that prices every landed
mechanism at once — fused kernels on/off, prefetch on/off, warm/cold
chunk store, 1/2/4/8 shards, encoded upload on/off. Until now that was
an evening of manual env juggling; this module is the arm model and the
unattended driver behind ``tools/campaign.py``:

* **Arm matrix** — a campaign is an ordered list of :class:`Arm`\\ s
  (name + env overlay), from a built-in preset (:data:`PRESETS`) or a
  JSON matrix file, expanded by :func:`expand_arms`. Each arm runs
  ``bench.py`` with its overlay applied plus per-arm
  ``NDS_BENCH_RESULTS_JSONL`` / ``NDS_BENCH_TRACE_DIR`` artifacts under
  one campaign directory with a schema-versioned ``manifest.json``.
* **Env fingerprint** — :func:`env_fingerprint` canonicalizes the knob
  set that changes what a run measures (:data:`FINGERPRINT_KNOBS`).
  bench.py stamps it (plus the arm name, :func:`campaign_stamp`) into
  EVERY ledger record, and :func:`check_resume_fingerprint` refuses to
  resume a ledger recorded under different knobs
  (:class:`CampaignResumeError` names both fingerprints) — a resumed
  run must never silently mix arms.
* **Kill-proof resume** — per-arm resume rides the ledger loader: an
  arm whose ledger carries a clean terminal ``completed`` record is
  skipped; a partial arm resumes from its own ledger (bench.py
  ``load_resume``); the manifest is rewritten atomically after every
  arm so a SIGKILL costs at most the arm in flight.
* **Classified arm failures** — a failed arm (nonzero bench exit, spawn
  failure, fingerprint mismatch, corrupt ledger) is classified via the
  fault-matrix ladder's ``bench-child`` seam (engine/faults.py) and
  recorded in the manifest; the remaining arms still run. SIGTERM/
  SIGINT finalize the manifest the way bench.py's ``finalize()``
  closes its ledger.

This module is deliberately STDLIB-ONLY (no jax, no nds_tpu imports):
the bench.py parent and the ``tools/campaign.py`` CLI load it by file
path (``tools/_ledger_load.campaign_mod``), bypassing the jax-importing
package root — exactly the ``obs/ledger.py`` / ``engine/faults.py``
discipline.

Concurrency contract (analysis/conc_audit.py entry point): the driver
is single-threaded — all run state (manifest dict, in-flight child
handle) is local to :func:`run_campaign`; module level holds only
import-time constants. The fault evidence it records rides the fault
registry's thread-local ring.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field

CAMPAIGN_VERSION = 1

# the knobs that change WHAT a run measures — the arm axes of ROADMAP's
# evidence campaign plus the scale factor. Canonical order; an unset
# knob fingerprints as the explicit sentinel so "unset" and "set to the
# default's value" are distinguishable (they are different experiments:
# defaults can move between commits).
FINGERPRINT_KNOBS = (
    "NDS_TPU_PALLAS",            # fused Pallas chunk kernels: auto/off
    "NDS_TPU_PREFETCH_DEPTH",    # bounded prefetch ring: 0 = inline
    "NDS_TPU_CHUNK_STORE",       # persistent chunk store dir ("" = cold)
    "NDS_TPU_STREAM_SHARDS",     # mesh shard count: 1/2/4/8
    "NDS_TPU_ENCODED",           # encoded upload: 0 = raw wire
    "NDS_BENCH_SCALE",           # scale factor (different data = arm)
)

_UNSET = "<unset>"


class CampaignError(ValueError):
    """A campaign input that cannot be trusted: unknown manifest schema
    version, malformed arm matrix, duplicate arm names. Loud by design —
    a misread matrix would burn hours of unattended device time on the
    wrong experiment."""


class CampaignResumeError(CampaignError):
    """A ledger recorded under DIFFERENT knobs than the arm trying to
    resume it: resuming would mix two experiments into one artifact.
    The message names both fingerprints so the operator can see exactly
    which knob moved."""


def env_fingerprint(env=None) -> str:
    """Canonical fingerprint of the arm-relevant knobs in ``env``
    (default: this process's environment). Deterministic — fixed knob
    order, explicit unset sentinel — so equality means "same
    experiment" and nothing else."""
    env = os.environ if env is None else env
    parts = []
    for k in FINGERPRINT_KNOBS:
        v = env.get(k)
        parts.append(f"{k}={_UNSET if v is None else v}")
    return ";".join(parts)


def campaign_stamp(env=None) -> dict:
    """The provenance stamp bench.py merges into every ledger record:
    the env fingerprint always, plus the campaign arm name when the
    driver set ``NDS_CAMPAIGN_ARM``. Stamping the fingerprint even
    OUTSIDE a campaign means a later manual rerun against the same
    ledger still gets the mixed-arm refusal."""
    env = os.environ if env is None else env
    stamp = {"envFingerprint": env_fingerprint(env)}
    arm = env.get("NDS_CAMPAIGN_ARM")
    if arm:
        stamp["arm"] = arm
    return stamp


def check_resume_fingerprint(recorded, current, path="") -> None:
    """Refuse a resume whose recorded fingerprint mismatches the current
    one. A ledger with NO recorded fingerprint (pre-campaign artifact)
    resumes freely — the refusal protects stamped artifacts, it does not
    orphan legacy ones."""
    if recorded and recorded != current:
        raise CampaignResumeError(
            f"{path or 'ledger'}: recorded env fingerprint does not match "
            "the current environment —\n"
            f"  recorded: {recorded}\n"
            f"  current:  {current}\n"
            "refusing to resume (the results would mix two arms into one "
            "artifact); rerun under the recorded knobs or point this arm "
            "at a fresh ledger")


def _ledger_mod():
    """The ledger module (``nds_tpu/obs/ledger.py``, stdlib-only)
    without the jax-importing package root: reuse an already-imported
    copy, else load the sibling file by path — the same pattern
    ledger.py uses for engine/faults.py."""
    m = sys.modules.get("nds_tpu.obs.ledger")
    if m is not None:
        return m
    m = sys.modules.get("_nds_ledger_stdlib")
    if m is not None:
        return m
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "ledger.py")
    spec = importlib.util.spec_from_file_location("_nds_ledger_stdlib",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_nds_ledger_stdlib"] = mod
    spec.loader.exec_module(mod)
    return mod


def _faults_mod():
    """The fault registry (``engine/faults.py``), via the ledger's own
    path loader — the ``bench-child`` seam the arm-failure ladder
    classifies against."""
    return _ledger_mod()._faults_mod()


# ---------------------------------------------------------------------------
# arm model
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Arm:
    """One campaign arm: a name (also the artifact subdirectory) and an
    env overlay applied on top of the inherited environment. An overlay
    value of ``""`` REMOVES the variable from the child env (e.g.
    ``NDS_TPU_CHUNK_STORE: ""`` is the cold-store arm)."""

    name: str
    env: dict = field(default_factory=dict)


# built-in arm matrices. ``env`` is the campaign-level overlay every arm
# inherits; each arm's own overlay wins on conflict. ``{dir}`` in a
# value expands to the campaign directory at expansion time, so the
# warm chunk store lands inside the campaign's own artifact tree.
PRESETS = {
    "sf10-full": {
        "description": "the ROADMAP item-1 SF10 sweep: every landed "
                       "mechanism priced in one unattended campaign",
        "env": {"NDS_BENCH_SCALE": "10",
                "NDS_TPU_CHUNK_STORE": "{dir}/chunk_store"},
        "arms": [
            # base runs FIRST: it warms the shared chunk store the
            # later default-knob arms reuse (store-cold opts out)
            {"name": "base", "env": {}},
            {"name": "pallas-off", "env": {"NDS_TPU_PALLAS": "off"}},
            {"name": "prefetch-off",
             "env": {"NDS_TPU_PREFETCH_DEPTH": "0"}},
            {"name": "store-cold", "env": {"NDS_TPU_CHUNK_STORE": ""}},
            {"name": "encoded-off", "env": {"NDS_TPU_ENCODED": "0"}},
            {"name": "shards-1", "env": {"NDS_TPU_STREAM_SHARDS": "1"}},
            {"name": "shards-2", "env": {"NDS_TPU_STREAM_SHARDS": "2"}},
            {"name": "shards-4", "env": {"NDS_TPU_STREAM_SHARDS": "4"}},
            {"name": "shards-8", "env": {"NDS_TPU_STREAM_SHARDS": "8"}},
        ],
    },
    "smoke": {
        "description": "three-arm bench-scale shakeout of the driver "
                       "itself (minutes, not hours)",
        "env": {"NDS_BENCH_SCALE": "0.05"},
        "arms": [
            {"name": "base", "env": {}},
            {"name": "pallas-off", "env": {"NDS_TPU_PALLAS": "off"}},
            {"name": "prefetch-off",
             "env": {"NDS_TPU_PREFETCH_DEPTH": "0"}},
        ],
    },
}


def expand_arms(matrix: dict, campaign_dir: str) -> list:
    """Expand one matrix dict (a :data:`PRESETS` entry or a loaded JSON
    file) into the ordered :class:`Arm` list. Validates loudly: version
    drift, missing/duplicate/unsafe arm names. ``{dir}`` in any env
    value expands to the campaign directory."""
    if not isinstance(matrix, dict) or not matrix.get("arms"):
        raise CampaignError("arm matrix must be an object with a "
                            "non-empty 'arms' list")
    v = matrix.get("v", CAMPAIGN_VERSION)
    if v != CAMPAIGN_VERSION:
        raise CampaignError(
            f"arm matrix schema version {v!r} is not the supported "
            f"version {CAMPAIGN_VERSION} — refusing to guess at unknown "
            "arm semantics")
    base = matrix.get("env") or {}
    arms = []
    seen = set()
    for spec in matrix["arms"]:
        name = (spec or {}).get("name")
        if not name or not isinstance(name, str):
            raise CampaignError("every arm needs a non-empty 'name'")
        if os.sep in name or name.startswith("."):
            raise CampaignError(f"arm name {name!r} is not a safe "
                                "artifact directory name")
        if name in seen:
            raise CampaignError(f"duplicate arm name {name!r}")
        seen.add(name)
        overlay = dict(base)
        overlay.update(spec.get("env") or {})
        overlay = {k: str(v).replace("{dir}", campaign_dir)
                   for k, v in overlay.items()}
        arms.append(Arm(name, overlay))
    return arms


def arm_env(arm: Arm, base_env=None) -> dict:
    """The effective environment an arm runs under: the inherited env
    with the overlay applied (``""`` removes the knob)."""
    env = dict(os.environ if base_env is None else base_env)
    for k, v in arm.env.items():
        if v == "":
            env.pop(k, None)
        else:
            env[k] = v
    return env


def arm_fingerprint(arm: Arm, base_env=None) -> str:
    return env_fingerprint(arm_env(arm, base_env))


def arm_paths(campaign_dir: str, name: str) -> dict:
    """Per-arm artifact layout under the campaign directory."""
    d = os.path.join(campaign_dir, name)
    return {"dir": d,
            "ledger": os.path.join(d, "ledger.jsonl"),
            "traces": os.path.join(d, "traces"),
            "metrics": os.path.join(d, "metrics.json"),
            "log": os.path.join(d, "bench.log")}


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------


def manifest_path(campaign_dir: str) -> str:
    return os.path.join(campaign_dir, "manifest.json")


def write_manifest(campaign_dir: str, manifest: dict) -> None:
    """Atomic write (tmp + rename): a kill mid-write leaves the previous
    manifest intact, never a torn one — resume reads either a complete
    old state or a complete new one."""
    manifest["v"] = CAMPAIGN_VERSION
    path = manifest_path(campaign_dir)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_manifest(campaign_dir: str):
    """The campaign manifest, or None when the directory has none yet.
    An unknown schema version refuses loudly — same discipline as the
    ledger loader."""
    path = manifest_path(campaign_dir)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        try:
            doc = json.load(f)
        except ValueError as exc:
            raise CampaignError(f"{path}: unreadable manifest ({exc})")
    v = doc.get("v") if isinstance(doc, dict) else None
    if v != CAMPAIGN_VERSION:
        raise CampaignError(
            f"{path}: manifest schema version {v!r} is not the supported "
            f"version {CAMPAIGN_VERSION} — refusing to misread a "
            "campaign state (upgrade the reader, or start a fresh "
            "campaign directory)")
    return doc


def new_manifest(arms, campaign_dir: str, preset=None) -> dict:
    return {
        "v": CAMPAIGN_VERSION,
        "preset": preset,
        "dir": os.path.abspath(campaign_dir),
        "status": "running",
        "startedAt": round(time.time(), 3),
        "arms": [{"name": a.name, "env": dict(a.env),
                  "fingerprint": arm_fingerprint(a),
                  "ledger": os.path.join(a.name, "ledger.jsonl"),
                  "status": "pending"} for a in arms],
    }


# ---------------------------------------------------------------------------
# per-arm resume admission
# ---------------------------------------------------------------------------


def arm_status(arm: Arm, campaign_dir: str, base_env=None):
    """Resume admission for one arm, off its own ledger:

    ``("pending", None)``  no ledger yet — run from scratch;
    ``("partial", None)``  ledger without a clean terminal record — the
    arm resumes (bench.py ``load_resume`` skips measured queries);
    ``("done", None)``     clean terminal ``completed`` record — skip;
    ``("corrupt", why)``   unreadable ledger — the arm is classified
    failed, never silently re-run over a poisoned artifact.

    Raises :class:`CampaignResumeError` when the ledger's recorded
    fingerprint mismatches this arm's effective knobs."""
    paths = arm_paths(campaign_dir, arm.name)
    ledger = paths["ledger"]
    if not os.path.exists(ledger) or os.path.getsize(ledger) == 0:
        return "pending", None
    L = _ledger_mod()
    try:
        data = L.load_ledger(ledger)
    except L.LedgerError as exc:
        return "corrupt", str(exc)
    check_resume_fingerprint(data.meta.get("envFingerprint"),
                             arm_fingerprint(arm, base_env), ledger)
    if data.end is not None and data.end.get("status") == "completed":
        return "done", None
    return "partial", None


def classify_arm_failure(arm_name: str, detail: str) -> dict:
    """The fault-matrix ladder applied to one failed arm: the
    ``bench-child`` seam's registered classification and recovery
    policy, plus whatever fault events the attempt left in the ring —
    drained HERE so the evidence lands in the manifest instead of dying
    thread-local. The campaign-level recovery is the seam's own:
    transient — the next rerun of the same command retries the arm off
    its ledger; the remaining arms run regardless."""
    F = _faults_mod()
    seam = F.SEAMS["bench-child"]
    events = [F.fault_event_json(e) for e in F.drain_fault_events()]
    out = {"seam": seam.name, "class": seam.classify,
           "recovery": seam.recovery, "detail": str(detail)[:300]}
    if events:
        out["faultEvents"] = events
    return out


# ---------------------------------------------------------------------------
# the unattended driver
# ---------------------------------------------------------------------------


def default_bench_cmd() -> list:
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return [sys.executable, os.path.join(repo, "bench.py")]


def run_campaign(arms, campaign_dir, bench_cmd=None, env=None,
                 preset=None, out=None):
    """Run (or resume) every arm in order; returns the final manifest.

    Kill-proof by construction: the manifest is atomically rewritten
    after every arm transition, each arm's evidence is its own ledger
    (bench.py's flush-per-record discipline), and rerunning the same
    command skips clean-completed arms and resumes the partial one. A
    SIGTERM/SIGINT terminates the in-flight bench child (whose own
    handler finalizes its ledger), finalizes the manifest as
    ``aborted``, and exits — the bench.py ``finalize()`` discipline one
    layer up. Arm failures are classified (``bench-child`` seam) and
    never abort the remaining arms."""
    out = sys.stderr if out is None else out
    os.makedirs(campaign_dir, exist_ok=True)
    load_manifest(campaign_dir)          # version refusal before overwrite
    base_env = dict(os.environ if env is None else env)
    manifest = new_manifest(arms, campaign_dir, preset=preset)
    write_manifest(campaign_dir, manifest)
    F = _faults_mod()
    cmd = list(bench_cmd) if bench_cmd else default_bench_cmd()
    state = {"child": None, "finalized": False}

    def finalize(status):
        if state["finalized"]:
            return
        state["finalized"] = True
        manifest["status"] = status
        manifest["endedAt"] = round(time.time(), 3)
        write_manifest(campaign_dir, manifest)

    def on_signal(signum, frame):
        # external kill mid-campaign: stop the in-flight arm's bench
        # run with SIGTERM (its own handler flushes the partial geomean
        # + terminal ledger record), label the arm, finalize the
        # manifest — the campaign artifact stays self-describing
        child = state["child"]
        if child is not None and child.poll() is None:
            child.terminate()
            try:
                child.wait(timeout=30)
            except subprocess.TimeoutExpired:
                child.kill()
        for rec in manifest["arms"]:
            if rec["status"] == "running":
                rec["status"] = "aborted"
                rec["error"] = "signal"
        finalize("aborted")
        os._exit(1)

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    for arm, rec in zip(arms, manifest["arms"]):
        paths = arm_paths(campaign_dir, arm.name)
        try:
            status, why = arm_status(arm, campaign_dir, base_env)
        except CampaignResumeError as exc:
            rec["status"] = "failed"
            rec["error"] = str(exc)[:500]
            rec["classified"] = classify_arm_failure(
                arm.name, "fingerprint-mismatch")
            print(f"# arm {arm.name}: REFUSED resume "
                  "(fingerprint mismatch); arm marked failed, campaign "
                  "continues", file=out)
            write_manifest(campaign_dir, manifest)
            continue
        if status == "done":
            rec["status"] = "done"
            print(f"# arm {arm.name}: already completed (clean terminal "
                  "record); skipped", file=out)
            write_manifest(campaign_dir, manifest)
            continue
        if status == "corrupt":
            rec["status"] = "failed"
            rec["error"] = f"corrupt ledger: {why}"[:500]
            rec["classified"] = classify_arm_failure(arm.name,
                                                     "corrupt ledger")
            print(f"# arm {arm.name}: corrupt ledger ({why}); arm marked "
                  "failed, campaign continues", file=out)
            write_manifest(campaign_dir, manifest)
            continue
        if status == "partial":
            print(f"# arm {arm.name}: resuming off its ledger", file=out)
        os.makedirs(paths["dir"], exist_ok=True)
        child_env = arm_env(arm, base_env)
        child_env["NDS_CAMPAIGN_ARM"] = arm.name
        child_env["NDS_BENCH_RESULTS_JSONL"] = paths["ledger"]
        child_env["NDS_BENCH_TRACE_DIR"] = paths["traces"]
        # per-arm live status file (atomic snapshot on the heartbeat
        # cadence): tools/obs_live.py renders the campaign directory as
        # a mid-run per-arm progress table
        child_env["NDS_TPU_METRICS_FILE"] = paths["metrics"]
        rec["status"] = "running"
        write_manifest(campaign_dir, manifest)
        t0 = time.time()
        print(f"# arm {arm.name}: running {' '.join(cmd)}", file=out)
        rc = None
        try:
            # the arm spawn is the same bench-child seam as
            # ChildServer.start: injectable, classified, never fatal to
            # the arms behind it
            F.fault_point("bench-child", detail=arm.name)
            with open(paths["log"], "ab") as logf:
                state["child"] = subprocess.Popen(
                    cmd, env=child_env, stdout=logf,
                    stderr=subprocess.STDOUT)
                rc = state["child"].wait()
        except (F.FaultError, OSError) as exc:
            F.record_fault_event("bench-child", "degrade",
                                 detail=f"arm {arm.name}: {exc}"[:200])
            rec["status"] = "failed"
            rec["error"] = f"{type(exc).__name__}: {exc}"[:300]
            rec["classified"] = classify_arm_failure(arm.name, str(exc))
            print(f"# arm {arm.name}: spawn failed ({exc}); classified, "
                  "campaign continues", file=out)
            write_manifest(campaign_dir, manifest)
            continue
        finally:
            state["child"] = None
        rec["wallS"] = round(time.time() - t0, 1)
        if rc == 0:
            rec["status"] = "completed"
            print(f"# arm {arm.name}: completed in {rec['wallS']}s",
                  file=out)
        else:
            F.record_fault_event("bench-child", "degrade",
                                 detail=f"arm {arm.name}: bench exit {rc}")
            rec["status"] = "failed"
            rec["rc"] = rc
            rec["error"] = f"bench exit {rc}"
            rec["classified"] = classify_arm_failure(arm.name,
                                                     f"bench exit {rc}")
            print(f"# arm {arm.name}: bench exit {rc}; classified "
                  f"({rec['classified']['class']}), campaign continues",
                  file=out)
        write_manifest(campaign_dir, manifest)
    ok = sum(1 for r in manifest["arms"] if r["status"] in
             ("completed", "done"))
    manifest["completedArms"] = ok
    manifest["failedArms"] = len(manifest["arms"]) - ok
    finalize("completed")
    return manifest
