# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Trace exporters: Chrome ``trace_event`` files and per-query rollups.

One file per query, loadable in ``chrome://tracing`` / Perfetto: spans
become ``"ph": "X"`` complete events (microsecond ts/dur from the span's
host clock), sync-site events become thin ``"X"`` slices whose width is
the time the host spent BLOCKED on that read — the stall is visible at a
glance. The whole document stays plain JSON, so ``tools/trace_report.py``
aggregates the same files the browser loads.
"""

from __future__ import annotations

import json
from collections import Counter

from nds_tpu.obs.trace import SpanRecord, SyncSite


def to_chrome(records, query: str = "", pid: int = 0,
              tid: int = 0, roll: dict | None = None) -> dict:
    """Chrome trace_event document (object form) for one drained record
    list. Extra top-level keys are legal in the format; ``nds`` carries
    the query name and the rollup so readers need not re-aggregate.
    Callers that already computed :func:`rollup` (the drivers stamp it
    into the query summary too) pass it as ``roll`` to skip the rewalk."""
    events = []
    for r in records:
        if isinstance(r, SpanRecord):
            args = {"syncs": r.syncs,
                    "syncWaitMs": round(r.sync_wait_ns / 1e6, 3),
                    "compileMs": round(r.compile_ns / 1e6, 3)}
            args.update(r.attrs)
            events.append({
                "name": r.name, "cat": "query", "ph": "X",
                "ts": r.ts_ns / 1e3, "dur": r.dur_ns / 1e3,
                "pid": pid, "tid": tid, "args": args})
        elif isinstance(r, SyncSite):
            events.append({
                "name": f"sync:{r.tag}", "cat": "sync", "ph": "X",
                "ts": r.ts_ns / 1e3 - r.wait_ns / 1e3,
                "dur": max(r.wait_ns / 1e3, 1.0),
                "pid": pid, "tid": tid,
                "args": {"site": r.site, "syncs": r.syncs}})
    return {"traceEvents": events,
            "displayTimeUnit": "ms",
            "nds": {"query": query,
                    "rollup": rollup(records) if roll is None else roll}}


def write_chrome_trace(path: str, records, query: str = "",
                       roll: dict | None = None) -> None:
    with open(path, "w") as f:
        # compact: the consumers (chrome://tracing, Perfetto,
        # tools/trace_report.py) are all programmatic, and a ~2500-chunk
        # streamed scan emits thousands of events per file
        json.dump(to_chrome(records, query=query, roll=roll), f,
                  separators=(",", ":"))


def rollup(records, top_sites: int = 5) -> dict:
    """Per-query aggregate the drivers merge into their JSON summaries:
    per-phase totals (ms/count/syncs, by span name), the top sync-charging
    host-read sites, and any eager-fallback streamed scans with their
    reason — the phase-attribution slice of the full trace."""
    phases: dict = {}
    sites: Counter = Counter()
    site_tag: dict = {}
    fallbacks = []
    for r in records:
        if isinstance(r, SpanRecord):
            p = phases.setdefault(r.name, {"ms": 0.0, "count": 0,
                                           "syncs": 0})
            p["ms"] = round(p["ms"] + r.dur_ns / 1e6, 3)
            p["count"] += 1
            p["syncs"] += r.syncs
            if r.name == "stream" and r.attrs.get("path") == "eager":
                fallbacks.append({
                    "table": r.attrs.get("table", "?"),
                    "reason": r.attrs.get("reason", ""),
                    "ms": round(r.dur_ns / 1e6, 3), "syncs": r.syncs})
        elif isinstance(r, SyncSite):
            sites[r.site] += r.syncs
            site_tag.setdefault(r.site, r.tag)
    out = {"phases": phases,
           "syncSites": [{"site": s, "tag": site_tag[s], "syncs": n}
                         for s, n in sites.most_common(top_sites)]}
    if fallbacks:
        out["fallbacks"] = fallbacks
    return out
