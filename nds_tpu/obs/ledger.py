# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Campaign evidence ledger: the durable, validated artifact of a run.

Every benchmark campaign so far wrote its evidence into four disjoint
shapes — bench.py resume lines, power.py per-query JSON summaries,
``streamedScans`` lists and ``tracePhases`` rollups — none of which was
schema-versioned, validated on load, or guaranteed to survive a kill
(BENCH_r05 died at rc=124 with ``{"value": null, "n_queries": 0}``).
The ledger is the ONE append-only JSONL record both drivers write and
every post-hoc tool reads:

* **schema-versioned**: every record carries ``"v": LEDGER_VERSION``;
  a loader meeting a version it does not understand refuses loudly
  instead of silently misreading fields;
* **flushed per record**: each ``write()`` flushes and fsyncs, so a
  SIGKILL loses at most the in-flight statement — and the loader
  tolerates a torn final line (reported, never fatal). Non-JSON lines
  elsewhere are skipped like legacy chatter (a resumed-after-kill file
  legitimately carries an old torn line mid-file); a VERSIONED record
  that fails validation is rejected wherever it sits;
* **self-describing**: a ``meta`` record opens the campaign (driver,
  platform, scale), a terminal ``end`` record closes it
  (``completed`` / ``aborted``, queries done, wall seconds), so a
  ledger with no ``end`` record IS the signature of a kill;
* **evidence-bearing**: each ``query`` record carries the wall time,
  phase rollup, sync counts and the :func:`nds_tpu.listener
  .stream_evidence` aggregate (bytes_h2d/ici, partitions, shards,
  collectives, fallback reasons) — the runtime half of the exec/mem
  audit lockstep contract, per query, in one validated place.

Record kinds and their required fields (beyond ``v``/``kind``/``t``):

======== ==================================================
meta     driver; optional platform, scale, anything else
query    name, status ("ok" | "error" | "timeout")
progress (heartbeat) — optional query/done/total/elapsedS
metrics  scope ("query" | "stream"), metricsV — live-metrics
         rollup (nds_tpu/obs/metrics.py): rolling or stream
         QPS / quantile / queue-wait / timeout-shed fields
end      status ("completed" | "aborted")
======== ==================================================

Legacy bench.py resume lines (bare ``{"name":…, "ms":…}`` query results
and ``{"platform":…}`` meta lines) are normalized by the loader so
pre-ledger campaign artifacts stay resumable.

This module is deliberately STDLIB-ONLY (no jax, no nds_tpu imports):
the bench.py parent — the budget supervisor that must never touch the
device attachment — loads it by file path, bypassing the jax-importing
package root.
"""

from __future__ import annotations

import io
import json
import os
import sys
import threading
import time

LEDGER_VERSION = 1
# the live-metrics rollup schema carried by `metrics` records — its own
# gate, separate from the envelope version: rollup shapes (bucket
# layout, quantile keys) can evolve without re-versioning every record.
# Must match nds_tpu/obs/metrics.py METRICS_VERSION (pinned by test).
METRICS_VERSION = 1


def _faults_mod():
    """The fault registry (``nds_tpu/engine/faults.py``) WITHOUT pulling
    the jax-importing package root: reuse the already-imported module
    when the engine is loaded (power.py in-process), else load the file
    by path (the bench.py parent, which must never touch jax — faults.py
    is stdlib-only by contract). The ``ledger-write`` / ``bench-child``
    seams route through this."""
    m = sys.modules.get("nds_tpu.engine.faults")
    if m is not None:
        return m
    m = sys.modules.get("_nds_tpu_faults_standalone")
    if m is not None:
        return m
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, "engine", "faults.py")
    spec = importlib.util.spec_from_file_location(
        "_nds_tpu_faults_standalone", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_nds_tpu_faults_standalone"] = mod
    spec.loader.exec_module(mod)
    return mod


def _metrics_mod():
    """The live-metrics registry (``nds_tpu/obs/metrics.py``) under the
    same dual-identity discipline as :func:`_faults_mod`: reuse the
    package import when the engine loaded it, else the stdlib-only
    file-path load — SHARING the canonical ``sys.modules`` name with
    ``tools/_ledger_load.py`` so the bench parent's feeds and the
    heartbeat exporter see the one process-default registry."""
    m = sys.modules.get("nds_tpu.obs.metrics")
    if m is not None:
        return m
    m = sys.modules.get("_nds_metrics_stdlib")
    if m is not None:
        return m
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "metrics.py")
    spec = importlib.util.spec_from_file_location(
        "_nds_metrics_stdlib", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_nds_metrics_stdlib"] = mod
    spec.loader.exec_module(mod)
    return mod


# record kinds -> required fields (beyond v/kind/t)
_REQUIRED = {
    "meta": ("driver",),
    "query": ("name", "status"),
    "progress": (),
    "metrics": ("scope",),
    "end": ("status",),
}

_QUERY_STATUSES = ("ok", "error", "timeout")
_END_STATUSES = ("completed", "aborted")


class LedgerError(ValueError):
    """A ledger file that cannot be trusted: unknown schema version,
    invalid record shape, or mid-file corruption. Deliberately loud —
    resuming a campaign from a misread ledger would silently re-pay or
    drop measured queries."""


def _validate(rec: dict, lineno: int) -> dict:
    if not isinstance(rec, dict):
        raise LedgerError(f"ledger line {lineno}: record is not an object")
    v = rec.get("v")
    if v != LEDGER_VERSION:
        raise LedgerError(
            f"ledger line {lineno}: schema version {v!r} is not the "
            f"supported version {LEDGER_VERSION} — refusing to guess at "
            "an unknown record shape (upgrade the reader, or re-record)")
    kind = rec.get("kind")
    if kind not in _REQUIRED:
        raise LedgerError(f"ledger line {lineno}: unknown record kind "
                          f"{kind!r} (known: {sorted(_REQUIRED)})")
    missing = [k for k in _REQUIRED[kind] if k not in rec]
    if missing:
        raise LedgerError(f"ledger line {lineno}: {kind} record missing "
                          f"required field(s) {missing}")
    if kind == "query" and rec["status"] not in _QUERY_STATUSES:
        raise LedgerError(f"ledger line {lineno}: query status "
                          f"{rec['status']!r} not in {_QUERY_STATUSES}")
    if kind == "end" and rec["status"] not in _END_STATUSES:
        raise LedgerError(f"ledger line {lineno}: end status "
                          f"{rec['status']!r} not in {_END_STATUSES}")
    if kind == "metrics" and rec.get("metricsV") != METRICS_VERSION:
        # same refusal discipline as the envelope version: silently
        # misreading an evolved rollup shape would corrupt a comparison
        raise LedgerError(
            f"ledger line {lineno}: metrics record version "
            f"{rec.get('metricsV')!r} is not the supported version "
            f"{METRICS_VERSION} — refusing to guess at an unknown "
            "rollup shape (upgrade the reader, or re-record)")
    return rec


def _normalize_legacy(msg: dict) -> dict | None:
    """Map a pre-ledger bench.py resume line onto a v1 record, or None
    for unrecognized chatter (old files tolerated stray lines).
    Records claiming to be ledger-shaped ('v'/'kind' present) never
    reach here — iter_ledger validates (and raises on) those."""
    if "v" in msg or "kind" in msg:
        return None
    if "name" in msg and "ms" in msg:
        return {"v": LEDGER_VERSION, "kind": "query", "t": 0.0,
                "status": "ok", **msg}
    if "name" in msg and "error" in msg:
        return {"v": LEDGER_VERSION, "kind": "query", "t": 0.0,
                "status": "error", **msg}
    if "platform" in msg and len(msg) == 1:
        return {"v": LEDGER_VERSION, "kind": "meta", "t": 0.0,
                "driver": "bench", "platform": msg["platform"]}
    return None


def iter_ledger(path: str):
    """Yield validated records from a ledger file, oldest first.

    Tolerances, exactly two: a torn FINAL line (the in-flight statement
    of a kill — yielded as a ``progress`` record with ``torn: True`` so
    :func:`load_ledger` can report it) and legacy pre-ledger resume
    lines (normalized). A versioned record that fails validation —
    unknown version, unknown kind, missing fields — raises
    :class:`LedgerError` wherever it sits: a poisoned record is
    corruption, not weather."""
    with open(path) as f:
        lines = f.read().split("\n")
    # trailing newline yields one empty tail element; drop empties at the
    # end but keep interior blanks visible to the numbering
    while lines and lines[-1] == "":
        lines.pop()
    last = len(lines)
    for lineno, ln in enumerate(lines, 1):
        ln = ln.strip()
        if not ln:
            continue
        try:
            msg = json.loads(ln)
        except ValueError:
            if lineno == last:
                # torn final write from a kill: the ledger contract says
                # this costs at most the in-flight statement
                yield lineno, {"v": LEDGER_VERSION, "kind": "progress",
                               "t": 0.0, "torn": True}
                return
            # mid-file garbage: legacy resume files carried stray
            # non-JSON chatter; tolerate (skip) rather than poison
            continue
        if isinstance(msg, dict) and msg.get("v") == LEDGER_VERSION \
                and msg.get("kind") in _REQUIRED:
            yield lineno, _validate(msg, lineno)
            continue
        if isinstance(msg, dict) and ("v" in msg or "kind" in msg):
            # claims to be a ledger record but is not a valid one
            # (unknown version, unknown kind, or missing 'v'): raise —
            # silently dropping it would re-pay or undercount a query
            _validate(msg, lineno)
            continue
        legacy = _normalize_legacy(msg) if isinstance(msg, dict) else None
        if legacy is not None:
            yield lineno, legacy


class LedgerData:
    """One loaded campaign: meta, per-query records, heartbeat count,
    the terminal record (None = the campaign was killed mid-flight),
    and whether the final line was torn."""

    def __init__(self):
        self.meta: dict = {}
        self.queries: dict = {}          # name -> best record (ok wins)
        self.attempts: list = []         # every query record, file order
        self.progress = 0
        self.metrics: list = []          # live-metrics rollups, file order
        self.end: dict | None = None
        self.torn = False

    @property
    def platform(self) -> str | None:
        return self.meta.get("platform")

    def times(self) -> dict:
        """name -> wall ms over queries that COMPLETED (status ok)."""
        return {n: r["ms"] for n, r in self.queries.items()
                if r["status"] == "ok" and "ms" in r}

    def complete(self) -> bool:
        """Did the campaign close itself (terminal record present)?"""
        return self.end is not None


def load_ledger(path: str) -> LedgerData:
    """Load and validate a whole ledger file. Raises :class:`LedgerError`
    on unknown versions or malformed records; a torn final line is
    absorbed (``data.torn``) so a killed campaign still resumes."""
    data = LedgerData()
    for _lineno, rec in iter_ledger(path):
        kind = rec["kind"]
        if kind == "meta":
            # later meta refines earlier (platform discovered mid-run)
            data.meta.update(rec)
        elif kind == "query":
            # activity AFTER a terminal record means a RESUMED run is in
            # flight: the old end record no longer closes this file, and
            # only a fresh one can ("no end record = kill signature"
            # must hold for the resumed segment too)
            data.end = None
            prev = data.queries.get(rec["name"])
            data.attempts.append(rec)
            # an ok record always wins over a timeout/error retry; among
            # equals the LATEST wins (a retried success replaces)
            if prev is None or rec["status"] == "ok" \
                    or prev["status"] != "ok":
                data.queries[rec["name"]] = rec
        elif kind == "progress":
            if rec.get("torn"):
                data.torn = True
            else:
                data.progress += 1
                data.end = None          # heartbeat after end: resumed run
        elif kind == "metrics":
            # rollup activity is activity: like a heartbeat, a metrics
            # record after an end record means a resumed run is in flight
            data.end = None
            data.metrics.append(rec)
        elif kind == "end":
            data.end = rec
    return data


def evidence_from_scans(scans) -> dict:
    """Aggregate a ``streamedScans`` JSON list (the
    :func:`nds_tpu.listener.stream_event_json` shape) into the compact
    per-query evidence dict the ledger carries and
    ``tools/bench_compare.py`` diffs: total syncs/chunks, upload and
    wire bytes, partition/shard/collective counts, path split and
    fallback reasons — the runtime numbers the exec/mem audits bound."""
    ev = {"scans": len(scans), "chunks": 0, "syncs": 0, "bytesH2d": 0,
          "bytesIci": 0, "collectives": 0, "partitions": 1, "shards": 1,
          "compiled": 0, "eager": 0}
    reasons = []
    for s in scans:
        ev["chunks"] += s.get("chunks", 0)
        ev["syncs"] += s.get("syncs", 0)
        ev["bytesH2d"] += max(s.get("bytesH2d", 0), 0)
        ev["bytesIci"] += max(s.get("bytesIci", 0), 0)
        ev["collectives"] += max(s.get("collectives", 0), 0)
        # driver ms blocked on the prefetch ring (measured, non-
        # deterministic — informational only, never a gated key)
        ev["prefetchStallMs"] = round(
            ev.get("prefetchStallMs", 0.0)
            + max(s.get("prefetchStallMs", 0.0), 0.0), 3)
        ev["partitions"] = max(ev["partitions"], s.get("partitions", 1))
        ev["shards"] = max(ev["shards"], s.get("shards", 1))
        if s.get("path") == "compiled":
            ev["compiled"] += 1
        else:
            ev["eager"] += 1
            if s.get("reason"):
                reasons.append(s["reason"])
    if reasons:
        ev["fallbackReasons"] = reasons
    return ev


class Ledger:
    """Append-only writer. Every record is validated before it is
    written and durably flushed (flush + fsync) so a kill can lose at
    most the statement in flight — the write discipline the BENCH_r05
    postmortem demanded. Thread-safe: the heartbeat thread interleaves
    ``progress`` records with the main thread's ``query`` records."""

    def __init__(self, path: str, stamp: dict | None = None, **meta):
        self.path = path
        # provenance stamp merged into EVERY record (campaign arm name,
        # env-knob fingerprint): cross-arm merges key on what the record
        # SAYS it measured, not on which file it sat in. Set before the
        # meta write below so the stamp rides that record too.
        self._stamp = dict(stamp or {})
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        preexisting = os.path.exists(path) and os.path.getsize(path) > 0
        self._f = open(path, "a")
        if preexisting:
            # seal a torn tail: a SIGKILL mid-write leaves the last line
            # unterminated, and appending straight onto it would MERGE
            # our first record into invalid JSON (losing both). A lone
            # newline turns the torn fragment into a mid-file skip the
            # loader already tolerates, and our records start clean.
            with open(path, "rb") as rf:
                rf.seek(-1, os.SEEK_END)
                sealed = rf.read(1) == b"\n"
            if not sealed:
                self._f.write("\n")
                self._f.flush()
        # REENTRANT: bench.py's SIGTERM handler calls close() from the
        # main thread, which may be interrupted INSIDE write() holding
        # this lock (fsync is slow) — a plain Lock would deadlock the
        # handler and the process would hang until the -k SIGKILL,
        # exactly the killed-campaign scenario the ledger exists to
        # survive
        self._lock = threading.RLock()
        self._closed = False
        # ledger-write seam evidence: writes that degraded (skipped
        # after the bounded retry) — the campaign continues, the loss
        # is counted, finalize() can surface it
        self.write_failures = 0
        if meta and not preexisting:
            self.write("meta", **meta)

    def write(self, kind: str, **fields) -> dict:
        """One validated, durably-flushed record. The physical write is
        the ``ledger-write`` transient seam (engine/faults.py registry):
        a failed flush/fsync (full disk, injected fault) takes ONE
        bounded retry, then DEGRADES — the record is dropped with a
        stderr note and a ``write_failures`` count, because losing one
        evidence record must never kill the campaign writing it. The
        loader's torn-line tolerance absorbs any partial line a failed
        attempt left."""
        rec = {"v": LEDGER_VERSION, "kind": kind, "t": round(time.time(), 3)}
        rec.update(self._stamp)
        rec.update(fields)
        _validate(rec, 0)
        line = json.dumps(rec, sort_keys=True)
        F = _faults_mod()

        def emit():
            F.fault_point("ledger-write", detail=kind)
            with self._lock:
                if self._closed:
                    return
                self._f.write(line + "\n")
                self._f.flush()
                try:
                    os.fsync(self._f.fileno())
                except (OSError, io.UnsupportedOperation):
                    pass                 # pipes/pytest capture: flush is all

        try:
            F.with_retry("ledger-write", emit)
        except (OSError, F.FaultError) as exc:
            F.record_fault_event("ledger-write", "degrade",
                                 detail=str(exc)[:200])
            self.write_failures += 1
            print(f"# ledger write failed ({exc}); record dropped, "
                  "campaign continues", file=sys.stderr)
        return rec

    def meta(self, **fields) -> dict:
        return self.write("meta", driver=fields.pop("driver", "bench"),
                          **fields)

    def query(self, name: str, status: str = "ok", **fields) -> dict:
        """One validated per-query record. Derives the ``evidence``
        aggregate from ``streamedScans`` when the caller did not."""
        if "streamedScans" in fields and "evidence" not in fields:
            fields["evidence"] = evidence_from_scans(fields["streamedScans"])
        return self.write("query", name=name, status=status, **fields)

    def progress(self, **fields) -> dict:
        return self.write("progress", **fields)

    def metrics(self, scope: str, **fields) -> dict:
        """One schema-versioned live-metrics rollup record (the
        :mod:`nds_tpu.obs.metrics` snapshot vocabulary): ``scope
        "query"`` rides the drivers' rolling rollup per completed
        query, ``scope "stream"`` the end-of-stream QPS / quantile /
        queue-wait / timeout-shed aggregate."""
        return self.write("metrics", scope=scope,
                          metricsV=METRICS_VERSION, **fields)

    def close(self, status: str | None = None, **fields) -> None:
        """Write the terminal record (idempotent) and close the file.
        ``status=None`` closes without a terminal record (the caller
        already wrote one, or wants the kill signature preserved)."""
        with self._lock:
            closed = self._closed
        if status is not None and not closed:
            self.write("end", status=status, **fields)
        with self._lock:
            self._closed = True
            try:
                self._f.close()
            except OSError:
                pass


class Heartbeat:
    """Liveness thread for a long campaign: every ``interval_s`` it
    writes one ``progress`` record to the ledger and one ``#`` line to
    stderr, so a hung child is visible within seconds — not at the
    rc=124 autopsy. Sync-free by construction: the beat reads the host
    clock and whatever the ``status`` callable returns (which must
    itself touch no device — the drivers pass dict snapshots of counters
    they already maintain); the traced-vs-untraced parity test runs an
    arm under a live heartbeat to pin this."""

    _STDERR = object()       # default sentinel: out=None silences

    def __init__(self, interval_s: float, ledger: "Ledger | None" = None,
                 status=None, out=_STDERR):
        self.interval_s = max(float(interval_s), 0.05)
        self.ledger = ledger
        self.status = status
        self.out = sys.stderr if out is Heartbeat._STDERR else out
        self.beats = 0
        self._survived = 0       # beat() exceptions the loop outlived
        self._stop = threading.Event()
        self._thread = None
        self._t0 = None

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.beat()
            except Exception as exc:
                # the liveness thread must outlive its own bugs: a beat
                # that raised records a progress NOTE (best effort) and
                # the loop continues — a silently dead heartbeat would
                # un-detect the very hangs it exists to surface
                self._survived += 1
                try:
                    if self.ledger is not None:
                        self.ledger.progress(
                            note="heartbeat-exception",
                            error=f"{type(exc).__name__}: {exc}"[:200])
                except Exception:
                    pass
                if self.out is not None:
                    try:
                        print(f"# heartbeat survived {type(exc).__name__}:"
                              f" {exc}", file=self.out, flush=True)
                    except Exception:
                        pass

    def beat(self) -> dict:
        """One heartbeat (also callable directly, e.g. from tests)."""
        self.beats += 1
        elapsed = time.perf_counter() - self._t0 if self._t0 else 0.0
        fields = {"elapsedS": round(elapsed, 1), "beat": self.beats}
        try:
            extra = self.status() if self.status is not None else None
        except Exception:                 # liveness must outlive status bugs
            extra = None
        if isinstance(extra, dict):
            fields.update(extra)
        if self.ledger is not None:
            try:
                self.ledger.progress(**fields)
            except (OSError, ValueError):
                pass                      # a full disk must not kill the run
        if self.out is not None:
            desc = " ".join(f"{k}={v}" for k, v in fields.items()
                            if k not in ("beat",))
            print(f"# heartbeat {self.beats}: {desc}", file=self.out,
                  flush=True)
        try:
            # live-metrics snapshot on the heartbeat cadence: a cheap
            # no-op unless NDS_TPU_METRICS_FILE is set (atomic
            # write-temp-then-rename; registry reads only — sync-free
            # like the rest of the beat)
            _metrics_mod().export_live(extra=fields)
        except Exception:
            pass          # liveness must outlive exporter bugs too
        return fields

    def start(self) -> "Heartbeat":
        self._t0 = time.perf_counter()
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="nds-ledger-heartbeat")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 1.0)
            self._thread = None

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
