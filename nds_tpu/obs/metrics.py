# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Live-metrics plane: the process-local rolling-rollup registry.

Every observability surface before this one was post-hoc — spans,
StreamEvents and FaultEvents drain per query into the ledger and are
read back after the run ends. This module is the in-flight half the
reference harness got from its Spark listener: counters, gauges and
bounded fixed-bucket rolling-window histograms with deterministic,
mergeable p50/p95/p99, cheap enough to feed from the drivers' hot
loops and exported mid-run for ``tools/obs_live.py``.

Contract (DESIGN.md "Live metrics rollups"):

* **feeds only from existing drain/evidence points** — the registry is
  fed exclusively where the drivers already do host-side bookkeeping
  (span drains, ``drain_stream_events``/``drain_fault_events``,
  admission slot acquire, ledger writes, heartbeat beats). It never
  reads the device, so the zero-added-sync parity pin
  (``tests/test_obs.py``) holds with metrics ON.
* **fixed shared bucket layout** — every histogram uses the ONE
  module-level geometric edge table (:data:`EDGES`,
  8 buckets/decade over 1e-1..~7.5e7, ~33% resolution), so snapshots
  from different processes/streams merge by summing bucket counts;
  quantiles are the upper edge of the smallest bucket whose cumulative
  count reaches the rank — deterministic and merge-order-independent
  (:func:`quantile_from_buckets`, :func:`merge_hist_snapshots`).
* **bounded rolling window** — each histogram keeps ``slots``
  epoch-tagged sub-windows of ``window_s / slots`` seconds; recording
  into a slot whose epoch is stale resets it, so memory is fixed and
  no timer thread exists. The injectable ``clock`` makes rotation
  tests deterministic.
* **one dedicated lock per registry** — all counter/gauge/histogram
  state is INSTANCE-scoped on the :class:`Registry`, guarded by its
  single ``_lock``; the runtime half is ``tools/conc_audit_diff.py``'s
  ``metrics`` lock probe (threaded-quantile drift).
* **schema-versioned exports** — snapshots carry ``metricsV``
  (:data:`METRICS_VERSION`); the ledger writer stamps the same version
  on ``metrics`` records and the loader refuses an unknown one loudly.
* **atomic live file** — :func:`export_live` writes the snapshot to
  ``NDS_TPU_METRICS_FILE`` via write-temp-then-rename (the campaign
  manifest discipline): a reader sees a complete old file or a
  complete new one, never a torn write. A literal ``{pid}`` in the
  path expands to the writing process id, so N throughput streams
  sharing one env can land N distinct files in one directory.

This module is deliberately STDLIB-ONLY (no jax, no nds_tpu imports):
the bench.py parent — which must never touch the device attachment —
loads it by file path via ``tools/_ledger_load.py`` under the same
discipline as the ledger.
"""

from __future__ import annotations

import json
import math
import os
import sys
import threading
import time

METRICS_VERSION = 1

# canonical metric names the drivers feed (shared vocabulary so the
# rollup helpers, the readers and the docs agree)
QUERY_WALL = "query.wall_ms"
QUEUE_WAIT = "admission.queue_wait_ms"
STALL = "prefetch.stall_ms"
SYNC_WAIT = "query.sync_wait_ms"
# pipeline-cache efficacy (engine/stream.py feeds these at the cache
# decision + every eviction): the evidence the parameterized plan bank
# is judged by — a throughput stream of K literal permutations per
# template should show K-1 hits per shape, not K misses
PIPE_HIT = "pipeline.cache.hit"
PIPE_MISS = "pipeline.cache.miss"
PIPE_EVICT = "pipeline.cache.evict"

# the ONE bucket edge table every histogram shares: geometric,
# 8 buckets/decade (~33% resolution), 1e-1 .. 10^7.875 (~21 h in ms).
# Values at or below the first edge land in bucket 0; values past the
# last edge clamp into the final bucket (quantiles saturate at its
# edge instead of inventing precision).
_BUCKETS_PER_DECADE = 8
EDGES = tuple(10.0 ** (i / _BUCKETS_PER_DECADE - 1) for i in range(72))


def bucket_index(value: float) -> int:
    """Index of the smallest edge >= value (clamped into the table)."""
    if not (value > EDGES[0]):          # also catches NaN -> bucket 0
        return 0
    if value >= EDGES[-1]:
        return len(EDGES) - 1
    # geometric edges: the index is a log, not a scan
    i = int(math.ceil((math.log10(value) + 1.0) * _BUCKETS_PER_DECADE))
    # float rounding at an exact edge can land one off either way
    while EDGES[i] < value:
        i += 1
    while i > 0 and EDGES[i - 1] >= value:
        i -= 1
    return i


def bucket_value(index: int) -> float:
    """The quantile value a bucket reports: its upper edge."""
    return EDGES[min(max(index, 0), len(EDGES) - 1)]


def quantile_from_buckets(buckets, q: float):
    """Deterministic quantile over ``{index: count}`` (or ``[[i, n],
    ...]``) bucket counts: the upper edge of the smallest bucket whose
    cumulative count reaches ``ceil(q * total)``. Merge-order
    independent by construction — the answer depends only on the
    summed counts. Returns None on an empty distribution."""
    if not isinstance(buckets, dict):
        buckets = dict(buckets)
    total = sum(buckets.values())
    if total <= 0:
        return None
    rank = max(1, math.ceil(q * total))
    cum = 0
    for i in sorted(buckets):
        cum += buckets[i]
        if cum >= rank:
            return round(bucket_value(i), 6)
    return round(bucket_value(sorted(buckets)[-1]), 6)


def merge_hist_snapshots(snaps):
    """Merge histogram snapshot dicts (the :meth:`Registry.snapshot`
    per-histogram shape) by summing bucket counts and recomputing the
    quantiles — associative and commutative, so cross-stream /
    cross-arm rollups do not depend on merge order. The EWMA is a
    feed-order construct and does not merge; it is omitted."""
    merged = {"count": 0, "sum": 0.0, "min": None, "max": None}
    buckets: dict = {}
    roll_buckets: dict = {}
    roll_count = 0
    roll_sum = 0.0
    window_s = None
    for s in snaps:
        merged["count"] += s.get("count", 0)
        merged["sum"] += s.get("sum", 0.0)
        for bound, key in ((min, "min"), (max, "max")):
            v = s.get(key)
            if v is not None:
                merged[key] = v if merged[key] is None else \
                    bound(merged[key], v)
        for i, n in s.get("buckets", ()):
            buckets[i] = buckets.get(i, 0) + n
        roll = s.get("rolling") or {}
        roll_count += roll.get("count", 0)
        roll_sum += roll.get("sum", 0.0)
        if window_s is None:
            window_s = roll.get("windowS")
        for i, n in roll.get("buckets", ()):
            roll_buckets[i] = roll_buckets.get(i, 0) + n
    merged["sum"] = round(merged["sum"], 6)
    merged["buckets"] = sorted(buckets.items())
    for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
        merged[key] = quantile_from_buckets(buckets, q)
    merged["rolling"] = {
        "windowS": window_s, "count": roll_count,
        "sum": round(roll_sum, 6),
        "buckets": sorted(roll_buckets.items()),
    }
    for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
        merged["rolling"][key] = quantile_from_buckets(roll_buckets, q)
    return merged


class _Hist:
    """One histogram: cumulative bucket counts plus ``n_slots``
    epoch-tagged rolling sub-windows. NOT self-locking — every access
    goes through the owning registry's one dedicated lock."""

    __slots__ = ("count", "sum", "min", "max", "ewma", "buckets",
                 "slots")

    def __init__(self, n_slots: int):
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self.ewma = None
        self.buckets: dict = {}
        # slot = [epoch, count, sum, {bucket: n}]
        self.slots = [[-1, 0, 0.0, {}] for _ in range(n_slots)]

    def record(self, value: float, now: float, slot_s: float,
               alpha: float) -> None:
        i = bucket_index(value)
        self.count += 1
        self.sum += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)
        self.ewma = value if self.ewma is None else (
            alpha * value + (1.0 - alpha) * self.ewma)
        self.buckets[i] = self.buckets.get(i, 0) + 1
        epoch = int(now // slot_s)
        slot = self.slots[epoch % len(self.slots)]
        if slot[0] != epoch:             # stale sub-window: recycle it
            slot[0] = epoch
            slot[1] = 0
            slot[2] = 0.0
            slot[3] = {}
        slot[1] += 1
        slot[2] += value
        slot[3][i] = slot[3].get(i, 0) + 1

    def rolling(self, now: float, slot_s: float):
        """(count, sum, merged buckets) over the live window."""
        floor = int(now // slot_s) - len(self.slots) + 1
        count = 0
        total = 0.0
        buckets: dict = {}
        for epoch, n, s, b in self.slots:
            if epoch < floor:
                continue
            count += n
            total += s
            for i, bn in b.items():
                buckets[i] = buckets.get(i, 0) + bn
        return count, total, buckets


class Registry:
    """Process-local, thread-safe live-metrics registry. All state is
    instance-scoped under the ONE dedicated ``_lock``; feed methods do
    dict arithmetic only (no IO, no device, no other lock), so holding
    the lock never blocks on anything slower than the GIL."""

    def __init__(self, window_s: float = 60.0, slots: int = 12,
                 clock=time.monotonic, ewma_alpha: float = 0.25):
        self.window_s = max(float(window_s), 1e-3)
        self.n_slots = max(int(slots), 1)
        self.slot_s = self.window_s / self.n_slots
        self.ewma_alpha = ewma_alpha
        self._clock = clock
        self._lock = threading.Lock()
        self._counters: dict = {}
        self._gauges: dict = {}
        self._hists: dict = {}

    # -- feeds (called at existing drain/evidence points only) ----------

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        now = self._clock()
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Hist(self.n_slots)
            h.record(float(value), now, self.slot_s, self.ewma_alpha)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()

    # -- reads ----------------------------------------------------------

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def hist_count(self, name: str) -> int:
        with self._lock:
            h = self._hists.get(name)
            return 0 if h is None else h.count

    def _hist_snapshot(self, h: _Hist, now: float) -> dict:
        count, total, buckets = h.rolling(now, self.slot_s)
        snap = {
            "count": h.count, "sum": round(h.sum, 6),
            "min": h.min, "max": h.max,
            "ewma": None if h.ewma is None else round(h.ewma, 6),
            "buckets": sorted(h.buckets.items()),
            "rolling": {
                "windowS": self.window_s, "count": count,
                "sum": round(total, 6),
                "perMin": round(count * 60.0 / self.window_s, 4),
                "buckets": sorted(buckets.items()),
            },
        }
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            snap[key] = quantile_from_buckets(h.buckets, q)
            snap["rolling"][key] = quantile_from_buckets(buckets, q)
        return snap

    def snapshot(self) -> dict:
        """The full schema-versioned state: counters, gauges, and every
        histogram with cumulative + rolling bucket counts and
        deterministic quantiles. Safe to json.dump as-is."""
        now = self._clock()
        with self._lock:
            return {
                "metricsV": METRICS_VERSION,
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "hists": {name: self._hist_snapshot(h, now)
                          for name, h in sorted(self._hists.items())},
            }

    # -- driver rollups (compact, ledger-record sized) ------------------

    def _rolling_stats(self, name: str, now: float):
        h = self._hists.get(name)
        if h is None:
            return None
        count, total, buckets = h.rolling(now, self.slot_s)
        if count == 0:
            return None
        return count, total, buckets, h.ewma

    def query_rollup(self) -> dict:
        """Rolling-window rollup for per-query ``metrics`` ledger
        records and heartbeat notes: queries/min, rolling wall
        quantiles, EWMA wall, queue-wait quantiles, stall share."""
        now = self._clock()
        with self._lock:
            out = {"queries": self._counters.get("queries.total", 0)}
            for key in ("ok", "error", "timeout"):
                n = self._counters.get(f"queries.{key}", 0)
                if n:
                    out[f"{key}Count"] = n
            faults = self._counters.get("faults.total", 0)
            if faults:
                out["faults"] = faults
            # pipeline-cache efficacy (appear only once streaming ran:
            # a dim-only run keeps the record clean)
            for field, name in (("pipeHit", PIPE_HIT),
                                ("pipeMiss", PIPE_MISS),
                                ("pipeEvict", PIPE_EVICT)):
                n = self._counters.get(name, 0)
                if n:
                    out[field] = n
            wall = self._rolling_stats(QUERY_WALL, now)
            if wall is not None:
                count, total, buckets, ewma = wall
                out["qpm"] = round(count * 60.0 / self.window_s, 4)
                out["wallP50Ms"] = quantile_from_buckets(buckets, 0.5)
                out["wallP95Ms"] = quantile_from_buckets(buckets, 0.95)
                out["wallP99Ms"] = quantile_from_buckets(buckets, 0.99)
                if ewma is not None:
                    out["ewmaWallMs"] = round(ewma, 3)
                stall = self._rolling_stats(STALL, now)
                if stall is not None and total > 0:
                    out["stallPct"] = round(100.0 * stall[1] / total, 2)
            queue = self._rolling_stats(QUEUE_WAIT, now)
            if queue is not None:
                out["queueWaitP50Ms"] = quantile_from_buckets(queue[2],
                                                              0.5)
                out["queueWaitP99Ms"] = quantile_from_buckets(queue[2],
                                                              0.99)
            return out

    def heartbeat_rollup(self) -> dict:
        """The two rolling-throughput fields the bench heartbeat rides
        in its progress record and stderr liveness line; {} before the
        first completed query (liveness lines stay clean at startup)."""
        now = self._clock()
        with self._lock:
            wall = self._rolling_stats(QUERY_WALL, now)
            if wall is None:
                return {}
            count, _total, _buckets, ewma = wall
            out = {"qpm": round(count * 60.0 / self.window_s, 2)}
            if ewma is not None:
                out["ewmaWallMs"] = round(ewma, 1)
            return out

    def stream_rollup(self, wall_s: float) -> dict:
        """End-of-stream CUMULATIVE rollup for the per-stream
        ``metrics`` ledger record: QPS, wall quantiles over every
        query, queue-wait quantiles, timeout-shed and fault counts."""
        with self._lock:
            out = {
                "queries": self._counters.get("queries.total", 0),
                "okCount": self._counters.get("queries.ok", 0),
                "errorCount": self._counters.get("queries.error", 0),
                "timeoutShed": self._counters.get("queries.timeout", 0),
                "faults": self._counters.get("faults.total", 0),
                "wallS": round(max(wall_s, 0.0), 3),
            }
            if wall_s > 0:
                out["qps"] = round(out["queries"] / wall_s, 4)
                out["qpm"] = round(out["qps"] * 60.0, 2)
            for field, name in (("pipeHit", PIPE_HIT),
                                ("pipeMiss", PIPE_MISS),
                                ("pipeEvict", PIPE_EVICT)):
                n = self._counters.get(name, 0)
                if n:
                    out[field] = n
            h = self._hists.get(QUERY_WALL)
            if h is not None and h.count:
                out["wallP50Ms"] = quantile_from_buckets(h.buckets, 0.5)
                out["wallP95Ms"] = quantile_from_buckets(h.buckets, 0.95)
                out["wallP99Ms"] = quantile_from_buckets(h.buckets, 0.99)
                out["wallMeanMs"] = round(h.sum / h.count, 3)
            queue = self._hists.get(QUEUE_WAIT)
            if queue is not None and queue.count:
                out["queueWaitP50Ms"] = quantile_from_buckets(
                    queue.buckets, 0.5)
                out["queueWaitP99Ms"] = quantile_from_buckets(
                    queue.buckets, 0.99)
                out["queueWaitMaxMs"] = round(queue.max, 3)
            stall = self._hists.get(STALL)
            if stall is not None and stall.count:
                out["stallMs"] = round(stall.sum, 3)
            return out


# the process-default registry every feed point shares. A plain
# import-time binding (no env read, no lazy singleton lock): the
# object itself is the synchronization point, and tests swap state via
# default().reset(), never by rebinding.
_DEFAULT = Registry()


def default() -> Registry:
    """The process-local default registry (one per driver process; a
    Throughput stream is a process, so per-stream == per-registry)."""
    return _DEFAULT


def export_live(path: str | None = None, registry: Registry | None = None,
                extra: dict | None = None) -> str | None:
    """Atomically replace the live status file with the current
    snapshot. ``path`` defaults to ``NDS_TPU_METRICS_FILE`` (read at
    call time — the env-freeze rule); unset means metrics export is
    off and the call is a cheap no-op. ``{pid}`` in the path expands
    to this process id so concurrent streams sharing one env write
    distinct files. Returns the path written, or None."""
    path = path or os.environ.get("NDS_TPU_METRICS_FILE")
    if not path:
        return None
    path = path.replace("{pid}", str(os.getpid()))
    reg = registry if registry is not None else default()
    doc = reg.snapshot()
    doc["t"] = time.time()
    if extra:
        doc.update(extra)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(doc, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError as exc:
        # a full disk or a yanked mount must never kill the driver the
        # live file merely watches; the stale file stays readable
        print(f"# live metrics export failed ({exc}); continuing",
              file=sys.stderr)
        return None
    return path
