# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Zero-sync span tracing: the engine-side half of the obs layer.

A span is a host-clock interval (``time.perf_counter_ns`` at enter/exit)
with the thread's sync accounting deltas attached: host syncs charged,
nanoseconds blocked on device->host reads, and XLA backend-compile
nanoseconds — all read from the counters :mod:`nds_tpu.engine.ops`
already maintains, so opening a span never touches the device. Sync-site
events (:class:`SyncSite`) are emitted by ``ops.host_read`` itself when a
fetch actually charged syncs, carrying the first-class call-site tag that
``tools/sync_profile.py`` used to recover by monkeypatching.

Scoping mirrors :class:`nds_tpu.listener.Manager`: records land in the
ring of the thread that produced them (concurrent Throughput streams each
drain only their own), and a span finished on a thread that never
attached a ring (e.g. a shared device-runtime callback thread) lands in
the module-level :data:`unattributed` diagnostics deque instead of
leaking or cross-charging a stream.

Hazard guards:

* a span opened while ``ops.replay_mode() == "replay"`` is a no-op — the
  replay/stream compilers re-run planner code under ``jax.jit``, and a
  host clock read there would measure trace time, not run time (the
  ``span-in-jit`` lint rule enforces the static side of this);
* disabled tracing (``NDS_TPU_TRACE=off`` or :func:`set_enabled`) makes
  ``span()`` return a shared null context: no clock reads at all.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

# ring capacity per thread: diagnostics, never unbounded. A >HBM scan
# emits ~3 records per chunk, so the default keeps a full per-query
# pipeline of ~2500 chunks; drivers drain per query. Read at ring-ATTACH
# time (not import): a Throughput child that sets NDS_TPU_TRACE_RING
# after import sizes its threads' rings from the live value.
def _ring_max() -> int:
    return int(os.environ.get("NDS_TPU_TRACE_RING", "8192"))

# NDS_TPU_TRACE is only the import DEFAULT of this runtime flag;
# set_enabled() is the post-import control path, so the conc-audit
# env-freeze rule is waived on the next line.
# nds-lint: ignore[env-freeze]
_enabled = os.environ.get("NDS_TPU_TRACE", "on").lower() not in (
    "off", "0", "false")

_tls = threading.local()

# spans/sync events from threads with no attached ring (mirrors
# Manager.unattributed: never fanned into another stream's drain)
unattributed: deque = deque(maxlen=1000)

_E = None


def _ops():
    """Late-bound engine.ops (ops imports this module at its top, so the
    reverse import must happen after both modules exist)."""
    global _E
    if _E is None:
        from nds_tpu.engine import ops
        _E = ops
    return _E


def on() -> bool:
    """Is tracing live for new spans/sync events?"""
    return _enabled


def set_enabled(value: bool) -> None:
    """Process-wide switch (tests; ``NDS_TPU_TRACE=off`` sets the import
    default). Open spans finish normally either way."""
    global _enabled
    _enabled = bool(value)


def attach() -> None:
    """Give the calling thread its own span ring (idempotent). Called by
    ``Session.sql`` so every query-executing thread is scoped; a record
    finished on a never-attached thread goes to :data:`unattributed`."""
    if getattr(_tls, "ring", None) is None:
        _tls.ring = deque(maxlen=_ring_max())


def drain_spans() -> list:
    """Return and clear the calling thread's trace records (spans and
    sync-site events, completion order). Attaches the thread."""
    attach()
    out = list(_tls.ring)
    _tls.ring.clear()
    return out


def _emit(rec) -> None:
    ring = getattr(_tls, "ring", None)
    if ring is None:
        unattributed.append(rec)
    else:
        ring.append(rec)


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def attributed() -> tuple:
    """(syncs, wait_ns) already attributed to sync-site events on this
    thread — ``ops.host_read`` subtracts these so a fetch that re-enters
    ``host_read`` (nested reads) charges each site exactly once."""
    return (getattr(_tls, "attr_syncs", 0), getattr(_tls, "attr_wait", 0))


class SyncSite:
    """One host_read fetch that charged host syncs: the first-class form
    of tools/sync_profile.py's call-site attribution."""

    __slots__ = ("tag", "site", "syncs", "wait_ns", "ts_ns", "depth")

    def __init__(self, tag, site, syncs, wait_ns, ts_ns, depth):
        self.tag = tag            # host_read tag ("sync", "counts3", ...)
        self.site = site          # "file.py:lineno:function" above ops.py
        self.syncs = syncs
        self.wait_ns = wait_ns
        self.ts_ns = ts_ns
        self.depth = depth

    def __repr__(self):
        return (f"SyncSite({self.tag!r}, {self.site!r}, "
                f"syncs={self.syncs})")


def note_sync(tag: str, syncs: int, wait_ns: int, site: str) -> None:
    """Record one sync-charging host read (called from ``ops.host_read``
    only when ``syncs`` not already attributed by a nested read)."""
    _tls.attr_syncs = getattr(_tls, "attr_syncs", 0) + syncs
    _tls.attr_wait = getattr(_tls, "attr_wait", 0) + wait_ns
    _emit(SyncSite(tag, site, syncs, wait_ns, time.perf_counter_ns(),
                   len(_stack())))


class SpanRecord:
    """One finished span. ``syncs``/``sync_wait_ns``/``compile_ns`` are
    deltas of the thread's existing ops counters over the span (children
    included — it is a tree, readers subtract for self-time)."""

    __slots__ = ("name", "attrs", "ts_ns", "dur_ns", "syncs",
                 "sync_wait_ns", "compile_ns", "depth", "dropped",
                 "_s0", "_w0", "_c0")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.ts_ns = 0
        self.dur_ns = 0
        self.syncs = 0
        self.sync_wait_ns = 0
        self.compile_ns = 0
        self.depth = 0
        self.dropped = False

    def set(self, **kw) -> None:
        """Attach counters/labels mid-span (chunks=…, cache="hit", …)."""
        self.attrs.update(kw)

    def drop(self) -> None:
        """Discard this span: it still unwinds normally at ``__exit__``
        but is never emitted. For spans whose subject turns out not to
        exist — e.g. the drive loop's ``stream.prefetch`` stall span
        when the ring reports end-of-stream: there was no chunk, so
        there must be no span record for one."""
        self.dropped = True

    def __enter__(self) -> "SpanRecord":
        E = _ops()
        st = _stack()
        self.depth = len(st)
        st.append(self)
        self._s0 = E.sync_count()
        self._w0 = E.sync_wait_ns()
        self._c0 = E.compile_ns()
        self.ts_ns = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> bool:
        self.dur_ns = time.perf_counter_ns() - self.ts_ns
        E = _ops()
        self.syncs = E.sync_count() - self._s0
        self.sync_wait_ns = E.sync_wait_ns() - self._w0
        self.compile_ns = E.compile_ns() - self._c0
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        elif self in st:                  # defensive: mis-nested exits
            st.remove(self)
        if not self.dropped:
            _emit(self)
        return False

    def __repr__(self):
        return (f"SpanRecord({self.name!r}, {self.dur_ns / 1e6:.3f}ms, "
                f"syncs={self.syncs}, attrs={self.attrs})")


class _NullSpan:
    """Shared no-op span: returned when tracing is off or the caller is
    inside a replay re-trace (host clock reads under jit tracing measure
    compile time, not run time)."""

    __slots__ = ()

    def set(self, **kw) -> None:
        pass

    def drop(self) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NULL_SPAN = _NullSpan()


def span(name: str, **attrs):
    """Open a nestable span on the calling thread. Usage::

        with obs.span("stream.drive", chunk=i) as sp:
            ...
            sp.set(rows=n)

    Zero host syncs by construction: enter/exit read the host clock and
    the thread's existing sync/wait/compile counters, nothing else."""
    if not _enabled or _ops().replay_mode() == "replay":
        return NULL_SPAN
    return SpanRecord(name, attrs)


def annotate(**attrs) -> None:
    """Set attributes on the innermost OPEN span of the calling thread
    (no-op when tracing is off or no span is open) — lets a callee deep
    in the engine label the phase span its caller opened (e.g. the
    streaming executor stamping cache hit/miss on the planner's
    ``stream`` span). Same replay guard as :func:`span`: under a replay
    re-trace the caller's own span was a null context, so the innermost
    open span would be an OUTER compile-phase span — annotating it would
    stamp another scan's attrs onto it at jit-trace time."""
    if not _enabled or _ops().replay_mode() == "replay":
        return
    st = getattr(_tls, "stack", None)
    if st:
        st[-1].attrs.update(attrs)
