# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Multi-chip execution: device mesh, partitioned operators, ICI exchange."""

from nds_tpu.parallel.exchange import (
    all_to_all_exchange,
    bucketize,
    hash_partition_dest,
    make_mesh,
    sharded_filter_agg_step,
)

__all__ = [
    "make_mesh",
    "hash_partition_dest",
    "bucketize",
    "all_to_all_exchange",
    "sharded_filter_agg_step",
]
