# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Cross-process device admission control for concurrent query streams.

The reference throttles device sharing with ``concurrentGpuTasks`` (ref:
nds/power_run_gpu.template:34,38 — how many Spark tasks may hold the GPU
at once). The TPU analog: Throughput Run streams are independent
processes (nds-throughput fans out one Power Run per stream), and with no
admission policy every stream's dispatches interleave on the chip's one
execution queue — measured sub-linear but uncontrolled (round-4 verdict
weak #7). This module is the knob: a slot directory of ``flock``-guarded
files shared by every process pointed at the same path. A stream holds a
slot for one WHOLE query (this engine interleaves parse/plan host work
with device dispatch, so there is no clean device-only span to guard):
at most N queries are in flight at once; queued streams still overlap
their between-query work (table setup, result IO, stream file reads).
The default slot dir is one fixed path per host, deliberately: the knob
throttles the one physical device, so every campaign targeting it shares
the same slots — point NDS_TPU_ADMISSION_DIR elsewhere to scope a run.

flock (not a named semaphore) because slots must survive crashed holders:
the kernel drops the lock with the process, so a killed stream never
leaks device capacity.

Env contract (read by nds_power.py per query):
  NDS_TPU_CONCURRENT_QUERIES  number of slots; unset/0 = unlimited
  NDS_TPU_ADMISSION_DIR       slot directory (default: shared host path)
"""

from __future__ import annotations

import contextlib
import fcntl
import os
import time


class DeviceAdmission:
    """N-slot cross-process semaphore over flock'd slot files."""

    def __init__(self, slots: int, dir_path: str | None = None):
        if slots <= 0:
            raise ValueError("slots must be positive")
        self.slots = slots
        self.dir = dir_path or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "nds_tpu_admission")
        try:
            os.makedirs(self.dir, exist_ok=True)
        except PermissionError as e:
            raise PermissionError(self._perm_msg(e)) from e
        self._held: int | None = None
        self._fds: dict[int, int] = {}

    def _perm_msg(self, e: OSError) -> str:
        return (f"admission dir {self.dir!r} is owned by another user "
                f"({e.strerror}) — set NDS_TPU_ADMISSION_DIR to a path "
                "this user can write (each dir is an independent slot "
                "pool, so scoping a run also un-shares its throttle)")

    def _slot_fd(self, i: int) -> int:
        fd = self._fds.get(i)
        if fd is None:
            # the default dir is shared across users on purpose (one host,
            # one device, one slot pool) — but another user's 0o644 slot
            # files are EACCES on O_RDWR, which must fail loudly instead
            # of crashing (or silently spinning) mid-campaign
            try:
                fd = os.open(os.path.join(self.dir, f"slot{i}"),
                             os.O_CREAT | os.O_RDWR, 0o644)
            except PermissionError as e:
                raise PermissionError(self._perm_msg(e)) from e
            self._fds[i] = fd
        return fd

    def try_acquire(self) -> bool:
        """Grab any free slot without blocking."""
        if self._held is not None:
            raise RuntimeError("slot already held")
        for i in range(self.slots):
            # _slot_fd outside the flock try: its PermissionError must
            # propagate, not be mistaken for a busy slot (which would turn
            # acquire() into an infinite poll loop)
            fd = self._slot_fd(i)
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                continue
            self._held = i
            return True
        return False

    def acquire(self, poll_s: float = 0.05) -> float:
        """Block until a slot frees; returns seconds spent queued.

        The wait is an ``admission.acquire`` span and feeds the
        ``admission.queue_wait_ms`` rolling histogram — slot
        acquisition is an EXISTING host-side blocking point, so the
        live-metrics feed here adds zero device syncs (span and
        registry read host clocks only); surfaced as ``queueWaitMs``
        in throughput per-query summaries and ledger records."""
        # lazy: admission runs inside engine processes (jax already
        # loaded); the bench parent never imports this module
        from nds_tpu.obs import metrics as _metrics
        from nds_tpu.obs import trace as _trace
        t0 = time.perf_counter()
        with _trace.span("admission.acquire", slots=self.slots):
            while not self.try_acquire():
                time.sleep(poll_s)
        queued = time.perf_counter() - t0
        reg = _metrics.default()
        reg.observe(_metrics.QUEUE_WAIT, queued * 1e3)
        reg.gauge("admission.slots", self.slots)
        return queued

    def release(self) -> None:
        if self._held is None:
            return
        fcntl.flock(self._fds[self._held], fcntl.LOCK_UN)
        self._held = None

    @contextlib.contextmanager
    def slot(self):
        """``with admission.slot() as queued_s:`` around one execution."""
        queued = self.acquire()
        try:
            yield queued
        finally:
            self.release()

    def close(self) -> None:
        self.release()
        for fd in self._fds.values():
            os.close(fd)
        self._fds.clear()


def from_env() -> DeviceAdmission | None:
    """The driver-facing constructor: None when the knob is off."""
    n = int(os.environ.get("NDS_TPU_CONCURRENT_QUERIES", "0") or 0)
    if n <= 0:
        return None
    return DeviceAdmission(n, os.environ.get("NDS_TPU_ADMISSION_DIR"))
