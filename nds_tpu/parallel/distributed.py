# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Distributed query execution over a device mesh.

The scaling recipe (pick a mesh, annotate shardings, let XLA insert the
collectives) applied to the NDS flagship query shape: scan a row-sharded
fact table, broadcast-join replicated dimension tables, and merge partial
aggregates with ``psum`` — the TPU analog of a Spark stage with a broadcast
hash join feeding a partial/final hash aggregate (the plan RAPIDS lowers for
q3-class queries; SURVEY.md §2.2 N4, §5.8).

Sharding layout:

- **fact columns**: padded to a multiple of the mesh size and placed with
  ``NamedSharding(mesh, P('part'))`` — rows ride HBM shards, pad rows carry
  ``alive=False`` and are masked at the filter (XLA static shapes; the pad
  is the capacity slack of the exchange design, exchange.py).
- **dimension columns**: replicated (``P()``) — TPC-DS dimensions are tiny
  next to facts, so a broadcast join wins over a repartition join exactly as
  Spark prefers broadcast under ``spark.sql.autoBroadcastJoinThreshold``
  (ref: nds/power_run_cpu.template:30 broadcastTimeout tuning).
- **join**: each device probes its fact shard against the replicated
  dimension hash (searchsorted on sorted keys) — no collective needed.
- **aggregate**: per-device ``segment_sum`` into the dense group-id space,
  then ``psum`` over the mesh axis — the all-reduce that replaces the
  shuffle-to-single-reducer stage.

The generic eager engine stays single-device this round (data-dependent
shapes force host syncs that would serialize a mesh); this module is the
distributed path for the filter→broadcast-join→aggregate pipelines that
dominate the NDS query mix.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from nds_tpu.parallel.exchange import make_mesh  # noqa: F401  (re-export)


def _pad_to(arr: jnp.ndarray, n: int, fill=0) -> jnp.ndarray:
    k = n - arr.shape[0]
    if k == 0:
        return arr
    return jnp.concatenate(
        [arr, jnp.full((k,), fill, dtype=arr.dtype)])


def shard_fact_columns(mesh, cols: dict, nrows: int):
    """Pad each 1-D column to a multiple of the mesh size and shard it
    row-wise. Returns (sharded_cols, alive_mask) — alive marks real rows."""
    n_dev = mesh.devices.size
    n_pad = (nrows + n_dev - 1) // n_dev * n_dev
    sharding = NamedSharding(mesh, P("part"))
    out = {}
    for name, arr in cols.items():
        out[name] = jax.device_put(_pad_to(arr, n_pad), sharding)
    alive = jax.device_put(
        _pad_to(jnp.ones(nrows, dtype=bool), n_pad, False), sharding)
    return out, alive


def replicate(mesh, arr: jnp.ndarray) -> jnp.ndarray:
    return jax.device_put(arr, NamedSharding(mesh, P()))


def dim_probe_map(dim_key: jnp.ndarray):
    """Sorted build side for a broadcast join: returns (sorted_keys, order)
    so probes are two searchsorteds + a gather."""
    order = jnp.argsort(dim_key)
    return jnp.take(dim_key, order), order


def broadcast_join_agg(mesh, fact, alive, dim_keys_sorted, dim_order,
                       dim_payload_codes, num_groups: int,
                       weight_name: str, fact_key_name: str):
    """The jitted distributed pipeline: filter (alive mask) -> broadcast-join
    the fact key against the dimension -> group by the joined dimension
    payload code -> psum partial aggregates.

    Inner-join semantics: fact rows whose key misses the dimension drop out
    (weight zeroed), exactly one dimension match per key (FK -> PK join).
    Returns (sums f64[G], counts i64[G]) replicated on every device.
    """

    def step(fact_cols, alive_mask, dks, dorder, dcodes):
        fk = fact_cols[fact_key_name]
        w = fact_cols[weight_name]
        lo = jnp.searchsorted(dks, fk, side="left")
        hi = jnp.searchsorted(dks, fk, side="right")
        matched = (hi - lo) > 0
        # payload code of the (unique) matching dimension row
        didx = jnp.take(dorder, jnp.clip(lo, 0, dks.shape[0] - 1))
        gid = jnp.take(dcodes, didx)
        live = alive_mask & matched
        wz = jnp.where(live, w, jnp.zeros((), dtype=w.dtype))
        gid_safe = jnp.where(live, gid, 0)
        sums = jax.ops.segment_sum(
            wz.astype(jnp.float64), gid_safe, num_segments=num_groups)
        counts = jax.ops.segment_sum(
            live.astype(jnp.int64), gid_safe, num_segments=num_groups)
        return sums, counts

    out_sharding = NamedSharding(mesh, P())
    jitted = jax.jit(step, out_shardings=(out_sharding, out_sharding))
    return jitted(fact, alive, dim_keys_sorted, dim_order, dim_payload_codes)


def run_distributed_q3(mesh, store_sales, date_dim, item,
                       manufact_id: int = 128, moy: int = 11):
    """TPC-DS q3 over the mesh (the minimum end-to-end distributed slice):

        select d_year, i_brand_id, i_brand, sum(ss_ext_sales_price)
        from date_dim, store_sales, item
        where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
          and i_manufact_id = [M] and d_moy = [MOY]
        group by d_year, i_brand_id, i_brand

    ``store_sales``/``date_dim``/``item`` are dicts of host or device int64/
    int32 arrays (pre-decoded columns). The brand dimension is the group key:
    group id = item row index (dense, static), filtered after the reduce.
    Returns host arrays (year, brand_id, brand_code, sum) for matched groups.
    """
    n_items = int(item["i_item_sk"].shape[0])
    n_dates = int(date_dim["d_date_sk"].shape[0])

    # replicated dimension build sides
    item_keys_sorted, item_order = dim_probe_map(jnp.asarray(item["i_item_sk"]))
    date_keys_sorted, date_order = dim_probe_map(jnp.asarray(date_dim["d_date_sk"]))

    # dimension predicates fold into the payload: a fact row joins a
    # "kept" dimension row or contributes nothing
    keep_item = jnp.asarray(item["i_manufact_id"]) == manufact_id
    keep_date = jnp.asarray(date_dim["d_moy"]) == moy

    # composite group id: item index × year-slot (years are enumerable)
    d_year = jnp.asarray(date_dim["d_year"])
    year_lo = int(jnp.min(d_year))
    n_years = int(jnp.max(d_year)) - year_lo + 1
    num_groups = n_items * n_years

    nrows = int(store_sales["ss_item_sk"].shape[0])
    fact, alive = shard_fact_columns(mesh, {
        "ss_item_sk": jnp.asarray(store_sales["ss_item_sk"]),
        "ss_sold_date_sk": jnp.asarray(store_sales["ss_sold_date_sk"]),
        "ss_ext_sales_price": jnp.asarray(store_sales["ss_ext_sales_price"]),
    }, nrows)

    def step(fact_cols, alive_mask, iks, iorder, ikeep,
             dks, dorder, dkeep, dyear):
        ss_item = fact_cols["ss_item_sk"]
        ss_date = fact_cols["ss_sold_date_sk"]
        w = fact_cols["ss_ext_sales_price"]

        ilo = jnp.searchsorted(iks, ss_item, side="left")
        ihit = (jnp.searchsorted(iks, ss_item, side="right") - ilo) > 0
        iidx = jnp.take(iorder, jnp.clip(ilo, 0, iks.shape[0] - 1))
        ilive = ihit & jnp.take(ikeep, iidx)

        dlo = jnp.searchsorted(dks, ss_date, side="left")
        dhit = (jnp.searchsorted(dks, ss_date, side="right") - dlo) > 0
        didx = jnp.take(dorder, jnp.clip(dlo, 0, dks.shape[0] - 1))
        dlive = dhit & jnp.take(dkeep, didx)

        live = alive_mask & ilive & dlive
        yslot = jnp.take(dyear, didx) - year_lo
        gid = iidx * n_years + yslot
        gid_safe = jnp.where(live, gid, 0)
        wz = jnp.where(live, w, jnp.zeros((), dtype=w.dtype))
        sums = jax.ops.segment_sum(
            wz.astype(jnp.float64), gid_safe, num_segments=num_groups)
        counts = jax.ops.segment_sum(
            live.astype(jnp.int64), gid_safe, num_segments=num_groups)
        return sums, counts

    rep = NamedSharding(mesh, P())
    jitted = jax.jit(step, out_shardings=(rep, rep))
    sums, counts = jitted(
        fact, alive, item_keys_sorted, item_order, keep_item,
        date_keys_sorted, date_order, keep_date, d_year)

    sums = np.asarray(sums)
    counts = np.asarray(counts)
    hit = np.nonzero(counts > 0)[0]
    item_idx = hit // n_years
    years = hit % n_years + year_lo
    return {
        "d_year": years,
        "i_brand_id": np.asarray(item["i_brand_id"])[item_idx],
        "item_index": item_idx,
        "sum_agg": sums[hit],
        "count": counts[hit],
    }
