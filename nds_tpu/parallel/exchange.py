# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""ICI exchange: the TPU-native replacement for the network shuffle.

The reference's accelerated stack moves shuffle data through the RAPIDS
UCX shuffle manager between Spark executors (SURVEY.md §2.2 N4, §5.8). On a
TPU pod the same role is played by XLA collectives over ICI: a fixed-capacity
``all_to_all`` repartitions rows by key hash between chips (hash-exchange
joins / aggregations), ``psum`` reduces partial aggregates (pre-aggregated
group-by), and ``all_gather`` broadcasts build sides (broadcast joins).

XLA requires static shapes, so the exchange uses capacity-bucketed send
buffers: each device packs its rows into a ``(P, capacity)`` buffer slotted
by destination device, with a validity plane marking real rows. Capacity is a
planner choice (rows_per_device / P × slack); overflow is detectable via
``bucket_overflow`` so the planner can re-run with a bigger capacity — the
static-shape analog of a shuffle spill.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices: int | None = None, axis: str = "part") -> Mesh:
    """1-D device mesh over the row-partition axis.

    Intra-query parallelism in the reference is Spark tasks over file splits
    (SURVEY.md §2.4.1); here it is row shards over mesh devices, with ICI
    collectives where Spark would shuffle.
    """
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def hash_partition_dest(key: jnp.ndarray, n_parts: int) -> jnp.ndarray:
    """Destination partition of each row: mix the key then mod P (the hash
    exchange's partitioning function)."""
    x = key.astype(jnp.uint64)
    x = (x ^ (x >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> 27)) * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> 31)
    return (x % jnp.uint64(n_parts)).astype(jnp.int32)


def bucketize(dest: jnp.ndarray, cols: dict, n_parts: int, capacity: int):
    """Pack rows into per-destination send buffers.

    Returns (buffers, valid, overflow): ``buffers[name]`` is ``(P, capacity)``
    with rows grouped by destination, ``valid`` marks occupied slots, and
    ``overflow`` counts rows dropped because a destination bucket was full
    (0 on a correctly-capacity-planned run).
    """
    n = dest.shape[0]
    order = jnp.argsort(dest)
    sd = jnp.take(dest, order)
    # slot of each row within its destination bucket
    first = jnp.searchsorted(sd, sd, side="left")
    pos = jnp.arange(n) - first
    fits = pos < capacity
    overflow = jnp.sum(~fits)
    valid = jnp.zeros((n_parts, capacity), dtype=bool).at[sd, pos].set(
        fits, mode="drop")
    bufs = {}
    for name, arr in cols.items():
        v = jnp.take(arr, order)
        buf = jnp.zeros((n_parts, capacity), dtype=arr.dtype).at[sd, pos].set(
            jnp.where(fits, v, jnp.zeros((), dtype=arr.dtype)), mode="drop")
        bufs[name] = buf
    return bufs, valid, overflow


def all_to_all_exchange(bufs: dict, valid: jnp.ndarray, axis: str = "part"):
    """The ICI all-to-all: bucket j of every device lands on device j.

    Inside ``shard_map`` only. After the exchange each device holds
    ``(P, capacity)`` rows — one bucket from every peer — all sharing its key
    range.
    """
    out = {name: jax.lax.all_to_all(buf, axis, split_axis=0, concat_axis=0)
           for name, buf in bufs.items()}
    vout = jax.lax.all_to_all(valid, axis, split_axis=0, concat_axis=0)
    return out, vout


def sharded_filter_agg_step(mesh: Mesh, num_groups: int, capacity: int,
                            axis: str = "part"):
    """Build the jitted partitioned filter→exchange→aggregate step.

    The flagship distributed query step (the TPU analog of one Spark stage
    pair around a hash exchange, ref: nds/power_run_gpu.template:29-30 shuffle
    partition knobs): each device filters its row shard, repartitions
    surviving rows by group-key hash over ICI, locally segment-aggregates its
    key range, and a final ``psum`` of the group counts cross-checks that no
    row was lost. Returns a function of sharded columns:

        (group_key i32[N], qty i64[N], sold i32[N], lo, hi)
            -> (sums i64[G_local per device], counts i64[G], total i64)
    """
    n_parts = mesh.devices.size

    def local_step(group_key, qty, sold, lo, hi):
        # filter: NULL-free predicate on the date column (masked rows keep
        # slot but zero weight — static shapes, no compaction)
        keep = (sold >= lo) & (sold <= hi)
        dest = hash_partition_dest(group_key.astype(jnp.uint64), n_parts)
        # dead rows all route to bucket of key 0 with zero weight; cheaper is
        # keeping them in place with weight 0 so buckets stay balanced
        w = jnp.where(keep, qty, jnp.zeros((), dtype=qty.dtype))
        bufs, valid, _ = bucketize(
            dest, {"key": group_key, "w": w}, n_parts, capacity)
        ex, vex = all_to_all_exchange(bufs, valid, axis)
        keys = ex["key"].reshape(-1)
        wts = ex["w"].reshape(-1)
        vflat = vex.reshape(-1)
        # this device owns group ids g with hash(g)%P == my index; segment-sum
        # over the full group-id space, zero elsewhere
        gids = jnp.clip(keys, 0, num_groups - 1)
        w_live = jnp.where(vflat, wts, jnp.zeros((), dtype=wts.dtype))
        sums = jax.ops.segment_sum(w_live, gids, num_segments=num_groups)
        ones = jnp.where(vflat, jnp.ones_like(wts), jnp.zeros_like(wts))
        counts_local = jax.ops.segment_sum(ones, gids, num_segments=num_groups)
        counts = jax.lax.psum(counts_local, axis)
        total = jax.lax.psum(jnp.sum(w_live), axis)
        return sums, counts, total

    try:
        from jax import shard_map
        rep_kw = {"check_vma": False}
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
        rep_kw = {"check_rep": False}

    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(), P()),
        out_specs=(P(axis), P(), P()),
        **rep_kw)
    in_shardings = (
        NamedSharding(mesh, P(axis)), NamedSharding(mesh, P(axis)),
        NamedSharding(mesh, P(axis)), NamedSharding(mesh, P()),
        NamedSharding(mesh, P()))
    return jax.jit(sharded, in_shardings=in_shardings)
