# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""ICI exchange: the TPU-native replacement for the network shuffle.

The reference's accelerated stack moves shuffle data through the RAPIDS
UCX shuffle manager between Spark executors (SURVEY.md §2.2 N4, §5.8). On a
TPU pod the same role is played by XLA collectives over ICI: a fixed-capacity
``all_to_all`` repartitions rows by key hash between chips (hash-exchange
joins / aggregations), ``psum`` reduces partial aggregates (pre-aggregated
group-by), and ``all_gather`` broadcasts build sides (broadcast joins).

XLA requires static shapes, so the exchange uses capacity-bucketed send
buffers: each device packs its rows into a ``(P, capacity)`` buffer slotted
by destination device, with a validity plane marking real rows. Capacity is a
planner choice (rows_per_device / P × slack); overflow is detectable via
``bucket_overflow`` so the planner can re-run with a bigger capacity — the
static-shape analog of a shuffle spill.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# ---------------------------------------------------------------------------
# collective accounting: the runtime half of the static collective budget
# (analysis/exec_audit.py). Every explicit ICI collective this module (or
# the sharded streamed pipeline, engine/stream.py) issues notes itself at
# TRACE time — the note runs once per compiled program, so a program's
# collective count is captured when its first dispatch traces and is then
# exact for every later dispatch. tools/exec_audit_diff.py checks the
# resulting ``StreamEvent.collectives``/``bytes_ici`` evidence against the
# audit's per-statement budget. GSPMD-inserted data-placement copies
# (replicated operand broadcast) are not collectives of the pipeline's
# programs and are out of scope by definition.
# ---------------------------------------------------------------------------

_coll_tls = threading.local()


class _CollectiveTrace:
    def __enter__(self):
        self._prev = getattr(_coll_tls, "counts", None)
        self.counts = {"a2a": 0, "psum": 0, "all_gather": 0, "bytes": 0}
        _coll_tls.counts = self.counts
        return self

    def __exit__(self, *exc):
        _coll_tls.counts = self._prev


def collective_trace():
    """Context collecting (at trace time) the explicit collective ops and
    their wire bytes issued while tracing one jitted program."""
    return _CollectiveTrace()


def _note_collective(kind: str, n: int = 1, nbytes: int = 0) -> None:
    c = getattr(_coll_tls, "counts", None)
    if c is not None:
        c[kind] += n
        c["bytes"] += int(nbytes)


def _aval_bytes(x) -> int:
    """Static byte size of a (traced or concrete) array — the wire bytes
    one collective moves, readable at trace time from shape metadata."""
    try:
        return int(np.prod(x.shape)) * x.dtype.itemsize
    except Exception:
        return 0


def psum_counted(x, axis: str):
    """``jax.lax.psum`` with collective accounting (use inside shard_map
    bodies the streamed pipeline compiles)."""
    _note_collective("psum", 1, _aval_bytes(x))
    return jax.lax.psum(x, axis)


def all_gather_counted(x, axis: str, tiled: bool = True):
    """``jax.lax.all_gather`` with collective accounting."""
    _note_collective("all_gather", 1, _aval_bytes(x))
    return jax.lax.all_gather(x, axis, tiled=tiled)


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """``shard_map`` across jax versions (the replication-check kwarg was
    renamed when it moved out of experimental); checks disabled — the
    engine's bodies are manual SPMD by design."""
    try:
        from jax import shard_map as _sm
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)


def make_mesh(n_devices: int | None = None, axis: str = "part") -> Mesh:
    """1-D device mesh over the row-partition axis.

    Intra-query parallelism in the reference is Spark tasks over file splits
    (SURVEY.md §2.4.1); here it is row shards over mesh devices, with ICI
    collectives where Spark would shuffle.
    """
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def hash_partition_dest(key: jnp.ndarray, n_parts: int) -> jnp.ndarray:
    """Destination partition of each row: mix the key then mod P (the hash
    exchange's partitioning function)."""
    x = key.astype(jnp.uint64)
    x = (x ^ (x >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> 27)) * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> 31)
    return (x % jnp.uint64(n_parts)).astype(jnp.int32)


def bucketize(dest: jnp.ndarray, cols: dict, n_parts: int, capacity: int):
    """Pack rows into per-destination send buffers.

    Returns (buffers, valid, overflow): ``buffers[name]`` is ``(P, capacity)``
    with rows grouped by destination, ``valid`` marks occupied slots, and
    ``overflow`` counts rows dropped because a destination bucket was full
    (0 on a correctly-capacity-planned run).
    """
    n = dest.shape[0]
    order = jnp.argsort(dest)
    sd = jnp.take(dest, order)
    # slot of each row within its destination bucket
    first = jnp.searchsorted(sd, sd, side="left")
    pos = jnp.arange(n) - first
    fits = pos < capacity
    overflow = jnp.sum(~fits)
    valid = jnp.zeros((n_parts, capacity), dtype=bool).at[sd, pos].set(
        fits, mode="drop")
    bufs = {}
    for name, arr in cols.items():
        v = jnp.take(arr, order)
        buf = jnp.zeros((n_parts, capacity), dtype=arr.dtype).at[sd, pos].set(
            jnp.where(fits, v, jnp.zeros((), dtype=arr.dtype)), mode="drop")
        bufs[name] = buf
    return bufs, valid, overflow


def all_to_all_exchange(bufs: dict, valid: jnp.ndarray, axis: str = "part"):
    """The ICI all-to-all: bucket j of every device lands on device j.

    Inside ``shard_map`` only. After the exchange each device holds
    ``(P, capacity)`` rows — one bucket from every peer — all sharing its key
    range.
    """
    _note_collective("a2a", len(bufs) + 1,
                     sum(_aval_bytes(b) for b in bufs.values())
                     + _aval_bytes(valid))
    out = {name: jax.lax.all_to_all(buf, axis, split_axis=0, concat_axis=0)
           for name, buf in bufs.items()}
    vout = jax.lax.all_to_all(valid, axis, split_axis=0, concat_axis=0)
    return out, vout


def sharded_filter_agg_step(mesh: Mesh, num_groups: int, capacity: int,
                            axis: str = "part"):
    """Build the jitted partitioned filter→exchange→aggregate step.

    The flagship distributed query step (the TPU analog of one Spark stage
    pair around a hash exchange, ref: nds/power_run_gpu.template:29-30 shuffle
    partition knobs): each device filters its row shard, repartitions
    surviving rows by group-key hash over ICI, locally segment-aggregates its
    key range, and a final ``psum`` of the group counts cross-checks that no
    row was lost. Returns a function of sharded columns:

        (group_key i32[N], qty i64[N], sold i32[N], lo, hi)
            -> (sums i64[G_local per device], counts i64[G], total i64)
    """
    n_parts = mesh.devices.size

    def local_step(group_key, qty, sold, lo, hi):
        # filter: NULL-free predicate on the date column (masked rows keep
        # slot but zero weight — static shapes, no compaction)
        keep = (sold >= lo) & (sold <= hi)
        dest = hash_partition_dest(group_key.astype(jnp.uint64), n_parts)
        # dead rows all route to bucket of key 0 with zero weight; cheaper is
        # keeping them in place with weight 0 so buckets stay balanced
        w = jnp.where(keep, qty, jnp.zeros((), dtype=qty.dtype))
        bufs, valid, _ = bucketize(
            dest, {"key": group_key, "w": w}, n_parts, capacity)
        ex, vex = all_to_all_exchange(bufs, valid, axis)
        keys = ex["key"].reshape(-1)
        wts = ex["w"].reshape(-1)
        vflat = vex.reshape(-1)
        # this device owns group ids g with hash(g)%P == my index; segment-sum
        # over the full group-id space, zero elsewhere
        gids = jnp.clip(keys, 0, num_groups - 1)
        w_live = jnp.where(vflat, wts, jnp.zeros((), dtype=wts.dtype))
        sums = jax.ops.segment_sum(w_live, gids, num_segments=num_groups)
        ones = jnp.where(vflat, jnp.ones_like(wts), jnp.zeros_like(wts))
        counts_local = jax.ops.segment_sum(ones, gids, num_segments=num_groups)
        counts = jax.lax.psum(counts_local, axis)
        total = jax.lax.psum(jnp.sum(w_live), axis)
        return sums, counts, total

    sharded = shard_map_compat(
        local_step, mesh,
        in_specs=(P(axis), P(axis), P(axis), P(), P()),
        out_specs=(P(axis), P(), P()))
    in_shardings = (
        NamedSharding(mesh, P(axis)), NamedSharding(mesh, P(axis)),
        NamedSharding(mesh, P(axis)), NamedSharding(mesh, P()),
        NamedSharding(mesh, P()))
    return jax.jit(sharded, in_shardings=in_shardings)


def stream_mesh_axis() -> str:
    """``NDS_TPU_STREAM_MESH_AXIS``: name of the streamed pipeline's mesh
    axis (default ``shard``; must differ from the session mesh's ``part``
    axis when both are active)."""
    import os
    return os.environ.get("NDS_TPU_STREAM_MESH_AXIS", "shard")


# mesh cache: concurrent Throughput streams building sharded pipelines
# share it, so mutations take the dedicated lock (double-checked insert —
# the Mesh constructor is pure host object construction, legal under the
# lock; no host read or jit compile ever runs here)
_STREAM_MESHES: dict = {}
_MESH_LOCK = threading.Lock()


def stream_mesh(n_shards: int, axis: str | None = None) -> Mesh | None:
    """LOCAL-device 1-D mesh the sharded streamed pipeline runs over, or
    None when this process has fewer than ``n_shards`` local devices
    (the pipeline then builds unsharded). Local by design: chunk sharding
    is an ICI-level optimization of one host's scan; cross-host (DCN)
    distribution stays the loader's ``host_shard_range`` split, so a
    federated Power Run shards its local chunk pipelines under the
    multi-controller runtime without any cross-host collective."""
    axis = axis or stream_mesh_axis()
    key = (int(n_shards), axis)
    m = _STREAM_MESHES.get(key)
    if m is None:
        devs = jax.local_devices()
        if len(devs) < n_shards:
            return None
        with _MESH_LOCK:
            m = _STREAM_MESHES.get(key)
            if m is None:
                m = _STREAM_MESHES[key] = Mesh(np.asarray(devs[:n_shards]),
                                               (axis,))
    return m


def mesh_of(*arrays):
    """The >1-device mesh a set of arrays is row-sharded over, or None.
    Arrays are self-describing (their NamedSharding carries the mesh), so
    the engine needs no session plumbing to detect distributed inputs."""
    for a in arrays:
        sh = getattr(a, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.mesh.devices.size > 1 and \
                any(s is not None for s in sh.spec):
            return sh.mesh
    return None


def _pow2(n: int) -> int:
    p = 16
    while p < n:
        p *= 2
    return p


def _exchange_join_step(mesh, cap_in: int, pair_cap: int, axis: str):
    """Jitted shard_map step of the repartition join: hash-bucketize both
    sides' (hash, global row id) pairs, all_to_all them so equal hashes
    co-locate, then locally sort/probe and emit matched row-id pairs at
    fixed capacity. Overflow counts come back host-visible so the caller
    can retry with doubled capacities (the static-shape analog of a
    shuffle spill; SURVEY.md §5.8)."""
    n_parts = mesh.devices.size

    def local(lh, lrow, rh, rrow):
        out = []
        for h, row in ((lh, lrow), (rh, rrow)):
            # bit 2 marks a REAL (matchable) hash (_key_hash_impl tags
            # unmatchable rows with per-row sentinels); dead rows are
            # dropped before the exchange so they never consume capacity
            real = (h & jnp.uint64(4)) != 0
            # dead rows route to bucket n_parts — past the last real bucket,
            # so the argsort key IS dest and the sorted ``sd`` stays a valid
            # searchsorted haystack (taking the raw dest, with dead rows at
            # 0, left sd unsorted whenever dead rows existed and the binary
            # search then misplaced real rows). Out-of-range sd drops out of
            # both the scatter (mode="drop") and the segment_sum below.
            dest = jnp.where(real, hash_partition_dest(h, n_parts),
                             jnp.int32(n_parts))
            n = h.shape[0]
            order = jnp.argsort(dest)
            sd = jnp.take(dest, order)
            sreal = jnp.take(real, order)
            first = jnp.searchsorted(sd, sd, side="left")
            pos = jnp.arange(n) - first
            fits = (pos < cap_in) & sreal
            # deficit (not count): the retry sizes capacity in ONE step
            # even under quadratic key skew
            bucket_counts = jax.ops.segment_sum(
                sreal.astype(jnp.int64), sd, num_segments=n_parts)
            over = jnp.maximum(jnp.max(bucket_counts) - cap_in, 0)
            valid = jnp.zeros((n_parts, cap_in), dtype=bool).at[
                sd, pos].set(fits, mode="drop")
            bufs = {}
            for name, arr in (("h", jnp.take(h, order)),
                              ("row", jnp.take(row, order))):
                bufs[name] = jnp.zeros(
                    (n_parts, cap_in), dtype=arr.dtype).at[sd, pos].set(
                    jnp.where(fits, arr, jnp.zeros((), dtype=arr.dtype)),
                    mode="drop")
            ex, vex = all_to_all_exchange(bufs, valid, axis)
            out.append((ex["h"].reshape(-1), ex["row"].reshape(-1),
                        vex.reshape(-1), over))
        (lhx, lrx, lvx, lover), (rhx, rrx, rvx, rover) = out
        # local probe: equal hashes are now co-resident on this device
        m = rhx.shape[0]
        rh_key = jnp.where(rvx, rhx, jnp.uint64(0))     # invalid -> hash 0
        rorder = jnp.argsort(rh_key)
        rh_sorted = jnp.take(rh_key, rorder)
        lh_key = jnp.where(lvx, lhx, jnp.uint64(1))     # never matches 0
        lo = jnp.searchsorted(rh_sorted, lh_key, side="left")
        hi = jnp.searchsorted(rh_sorted, lh_key, side="right")
        counts = jnp.where(lvx, hi - lo, 0)
        total = jnp.sum(counts)
        l_pos = jnp.repeat(jnp.arange(m), counts,
                           total_repeat_length=pair_cap)
        starts = jnp.cumsum(counts) - counts
        pos = jnp.arange(pair_cap) - jnp.repeat(starts, counts,
                                                total_repeat_length=pair_cap)
        r_pos = jnp.repeat(lo, counts, total_repeat_length=pair_cap) + pos
        pair_live = jnp.arange(pair_cap) < jnp.minimum(total, pair_cap)
        l_out = jnp.take(lrx, l_pos, mode="clip")
        r_out = jnp.take(rrx, jnp.take(rorder, jnp.clip(r_pos, 0, m - 1)),
                         mode="clip")
        p_over = jnp.maximum(total - pair_cap, 0)
        overs = jax.lax.pmax(
            jnp.stack([lover.astype(jnp.int64), rover.astype(jnp.int64),
                       p_over.astype(jnp.int64)]), axis)
        return l_out, r_out, pair_live, overs

    sharded = shard_map_compat(
        local, mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis)),
        out_specs=(P(axis), P(axis), P(axis), P()))
    return jax.jit(sharded)


# jitted exchange-step cache: building the jax.jit WRAPPER is lazy and
# cheap (the underlying compile happens at first dispatch, off-lock);
# setdefault-under-lock keeps one winner per key so concurrent streams
# dispatch the same wrapper and XLA compiles each shape exactly once
_exchange_step_cache: dict = {}
_EXCHANGE_STEP_LOCK = threading.Lock()


def exchange_join_pairs(lh, lrow, rh, rrow, mesh, axis: str = "part"):
    """Repartition (all-to-all) join of two row-sharded hash columns.

    Returns ``(l_idx, r_idx, pair_live)`` — global row-id pairs whose
    hashes matched, at a fixed capacity with a validity mask — after
    retrying with doubled capacities whenever a bucket or the pair buffer
    overflowed (detected via the psum'd overflow counters; the implemented
    overflow recovery the capacity-bucket design calls for)."""
    n_parts = mesh.devices.size
    n_l, n_r = int(lh.shape[0]), int(rh.shape[0])
    # expected rows per (device, destination) bucket with 2x slack
    cap_in = _pow2(max(n_l, n_r) * 2 // (n_parts * n_parts) + 16)
    pair_cap = _pow2(max(n_l, n_r) * 2 // n_parts + 16)
    for _ in range(5):
        key = (id(mesh), cap_in, pair_cap, axis)
        step = _exchange_step_cache.get(key)
        if step is None:
            built = _exchange_join_step(mesh, cap_in, pair_cap, axis)
            with _EXCHANGE_STEP_LOCK:
                step = _exchange_step_cache.setdefault(key, built)
        l_idx, r_idx, live, overs = step(lh, lrow, rh, rrow)
        from nds_tpu.engine.ops import timed_read
        lo, ro, po = timed_read(
            "exch_overs", lambda: tuple(int(x) for x in overs))
        if lo == 0 and ro == 0 and po == 0:
            return l_idx, r_idx, live
        # overs carry the max DEFICIT, so one retry reaches a sufficient
        # capacity even under quadratic key skew. A retry is a recovered
        # task failure in the reference's taxonomy (a shuffle spill/retry):
        # surface it to the run's failure listener.
        if lo or ro:
            cap_in = _pow2(cap_in + max(lo, ro))
        if po:
            pair_cap = _pow2(pair_cap + po)
        from nds_tpu.listener import report_task_failure
        report_task_failure(
            "exchange join capacity retry",
            f"bucket deficit l={lo} r={ro}, pair deficit {po}; "
            f"retrying with cap_in={cap_in}, pair_cap={pair_cap}")
    raise RuntimeError("exchange join: capacity retry limit exceeded")
