# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Multi-host (pod / multi-slice) initialization: the DCN story.

The reference scales past one machine with a Hadoop/Spark cluster (MR
data-gen wrapper, Spark RPC + shuffle; ref:
nds/tpcds-gen/src/main/java/org/notmysock/tpcds/GenTable.java:120-141).
The TPU analog is JAX's multi-controller runtime: one Python process per
host, federated through ``jax.distributed.initialize`` — after which
``jax.devices()`` spans every host, a ``Mesh`` over it makes GSPMD insert
ICI collectives within a slice and DCN collectives across slices, and the
whole engine (including the exchange join, parallel/exchange.py) runs
unchanged over the global mesh.

Environment contract (exported by the launch templates, base.template):

    NDS_TPU_MULTIHOST=1         opt in (or auto: set on TPU pod slices)
    NDS_COORDINATOR=host:port   coordinator (omit on TPU pods: auto-detect)
    NDS_NUM_PROCESSES=N         process count (omit on TPU pods)
    NDS_PROCESS_ID=i            this process's id (omit on TPU pods)
    JAX_CPU_COLLECTIVES_IMPLEMENTATION=gloo
                                cross-process collectives on the CPU
                                backend (the DCN stand-in CI federates
                                with). jax does NOT read this env var
                                into its config flag, so initialization
                                applies it via ``jax.config.update``
                                before the backend client exists —
                                without it every cross-process
                                computation fails with "Multiprocess
                                computations aren't implemented on the
                                CPU backend".

On Cloud TPU pods all three specifics auto-detect from the metadata
server, so ``NDS_TPU_MULTIHOST=1`` alone is sufficient there.

Like the reference — whose multi-node behavior is only ever exercised on a
real cluster (SURVEY.md §4) — the federation itself needs real hosts; CI
covers the plumbing (env parsing, idempotence, host-shard arithmetic) and
the single-process mesh path.
"""

from __future__ import annotations

import os

_initialized = False


def maybe_initialize() -> bool:
    """Idemptotently initialize the multi-controller runtime when the
    environment opts in. Returns True when running multi-host (after
    successful initialization), False in single-process mode.

    Called from Session construction and the driver CLIs before any
    device query — ``jax.distributed.initialize`` must precede backend
    initialization.
    """
    global _initialized
    if _initialized:
        return True
    if not os.environ.get("NDS_TPU_MULTIHOST"):
        return False
    from nds_tpu.engine import faults as _F
    try:
        # federation-peer seam (fatal): a refused/failed peer attach
        # raises a CLASSIFIED error promptly — a half-formed federation
        # must never run a collective, and no silent retry loop may
        # mask a dead coordinator
        _F.fault_point("peer")
    except _F.FaultInjected as exc:
        _F.record_fault_event("peer", "fatal", detail=str(exc)[:200])
        raise
    import jax
    impl = os.environ.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION")
    if impl:
        # the env spelling is NOT auto-read by jax's flag machinery: wire
        # it through the config before the CPU client is created, or the
        # federated mesh cannot run a single cross-process computation
        try:
            jax.config.update("jax_cpu_collectives_implementation", impl)
        except Exception:  # pragma: no cover - flagless jax build
            pass
    kwargs = {}
    if os.environ.get("NDS_COORDINATOR"):
        kwargs["coordinator_address"] = os.environ["NDS_COORDINATOR"]
    if os.environ.get("NDS_NUM_PROCESSES"):
        kwargs["num_processes"] = int(os.environ["NDS_NUM_PROCESSES"])
    if os.environ.get("NDS_PROCESS_ID"):
        kwargs["process_id"] = int(os.environ["NDS_PROCESS_ID"])
    # the attach blocks on the coordinator and every peer; under
    # NDS_TPU_STATEMENT_DEADLINE_S a stuck peer raises StatementTimeout
    # (classified, status 'timeout') instead of hanging the process
    _F.bounded_call("peer", lambda: jax.distributed.initialize(**kwargs))
    _initialized = True
    return True


def process_info():
    """(process_index, process_count) — (0, 1) before/without init."""
    import jax
    try:
        return jax.process_index(), jax.process_count()
    except RuntimeError:  # backend not initialized yet
        return 0, 1


def host_shard_range(n: int, process_index: int | None = None,
                     process_count: int | None = None) -> tuple[int, int]:
    """[start, end) of the rows/chunks this host owns out of ``n`` — the
    per-host split used by data loading and generation so each process
    feeds only its local devices (the MR wrapper's one-command-per-mapper
    split, re-expressed; ref: GenTable.java:140-141)."""
    if process_index is None or process_count is None:
        process_index, process_count = process_info()
    per = (n + process_count - 1) // process_count
    start = min(process_index * per, n)
    return start, min(start + per, n)
