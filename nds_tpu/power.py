# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Power Run core: stream parsing, table registration, the query loop.

TPU-native equivalent of the reference Power Run driver library
(ref: nds/nds_power.py). The hot loop holds the same contract: every query
runs under a BenchReport (JSON summary + status taxonomy), per-query times
land in a CSV time log (header ``application_id,query,time/milliseconds``,
ref: nds/nds_power.py:294-303), and the process exits non-zero when any
query failed or completed with task failures (ref: nds/nds_power.py:310-322).
"""

from __future__ import annotations

import csv
import os
import sys
import time
from collections import OrderedDict

from nds_tpu.check import check_json_summary_folder, check_query_subset_exists
from nds_tpu.queries import split_special_query
from nds_tpu.report import BenchReport
from nds_tpu.schema import get_schemas


def gen_sql_from_stream(query_stream_file_path: str) -> "OrderedDict[str, str]":
    """Split a generated query stream into an ordered {name: sql} dict,
    splitting the two-statement queries 14/23/24/39 into _part1/_part2
    (same parse as ref: nds/nds_power.py:50-77)."""
    with open(query_stream_file_path) as f:
        stream = f.read()
    all_queries = stream.split("-- start")[1:]
    extended = OrderedDict()
    for q in all_queries:
        query_name = q[q.find("template") + 9: q.find(".tpl")]
        if "select" in q.split(";")[1]:
            part_1, part_2 = split_special_query(q)
            extended[query_name + "_part1"] = part_1
            extended[query_name + "_part2"] = part_2
        else:
            extended[query_name] = q
    for name, content in extended.items():
        extended[name] = "-- start" + content
    return extended


def get_query_subset(query_dict: "OrderedDict", subset) -> "OrderedDict":
    """Select a subset of queries from the stream, preserving order
    (ref: nds/nds_power.py:177-182)."""
    check_query_subset_exists(query_dict, subset)
    return OrderedDict((name, query_dict[name]) for name in subset)


def strip_stream_markers(sql: str) -> str:
    """Remove the '-- start/-- end' marker lines and trailing ';' so the
    bare statement can be handed to the engine parser."""
    lines = [ln for ln in sql.splitlines()
             if not ln.strip().startswith("-- start")
             and not ln.strip().startswith("-- end")]
    text = "\n".join(lines).strip()
    if text.endswith(";"):
        text = text[:-1]
    return text


def setup_tables(session, input_prefix: str, input_format: str,
                 use_decimal: bool, execution_time_list: list) -> list:
    """Register the 24 source tables as engine views, timing each
    registration (ref: nds/nds_power.py:79-106)."""
    schemas = get_schemas(use_decimal=use_decimal)
    for table_name, fields in schemas.items():
        start = time.time()
        if input_format in ("csv", "raw"):
            path = os.path.join(input_prefix, f"{table_name}.dat")
            if not os.path.exists(path):
                path = os.path.join(input_prefix, table_name)
            session.read_raw_view(table_name, path, fields)
        else:
            path = os.path.join(input_prefix, table_name)
            canonical = {f.name: str(f.type) for f in fields}
            session.read_columnar_view(table_name, path, input_format,
                                       canonical)
        end = time.time()
        print(f"====== Creating TempView for table {table_name} ======")
        print(f"Time taken: {end - start} s for table {table_name}")
        execution_time_list.append(
            (session.app_id, f"CreateTempView {table_name}",
             int((end - start) * 1000)))
    return execution_time_list


def ensure_valid_column_names(result):
    """The reference rewrites invalid parquet column names before writing
    (ref: nds/nds_power.py:137-174); our writer quotes arbitrary names, so
    only spec-format backtick-quoted aggregates need renaming."""
    import re
    arrow = result.to_arrow()
    renames = {}
    for name in arrow.column_names:
        clean = re.sub(r"[ ,;{}()\n\t=]", "_", name)
        if clean != name:
            renames[name] = clean
    if renames:
        arrow = arrow.rename_columns(
            [renames.get(n, n) for n in arrow.column_names])
    return arrow


def run_one_query(session, query: str, query_name: str,
                  output_path: str | None, output_format: str) -> None:
    """Execute one query; collect() to host or write to the output prefix
    (ref: nds/nds_power.py:125-135)."""
    result = session.sql(strip_stream_markers(query))
    if not output_path:
        result.collect()
    else:
        from nds_tpu.io.columnar import write_table
        write_table(ensure_valid_column_names(result),
                    os.path.join(output_path, query_name), output_format)


def run_query_stream(input_prefix: str,
                     property_file: str | None,
                     query_dict: "OrderedDict",
                     time_log_output_path: str,
                     extra_time_log_output_path: str | None = None,
                     sub_queries=None,
                     input_format: str = "parquet",
                     use_decimal: bool = True,
                     output_path: str | None = None,
                     output_format: str = "parquet",
                     json_summary_folder: str | None = None,
                     allow_failure: bool = False,
                     warehouse_type: str | None = None,
                     profile_folder: str | None = None,
                     warm: bool = False,
                     trace_dir: str | None = None,
                     ledger_path: str | None = None) -> None:
    """The Power Run loop (ref: nds/nds_power.py:184-322).

    ``warm=True`` is the precompile pass (round-4 verdict missing #3):
    execute the stream once purely to fill the persistent XLA compile
    cache, so a following official run's TPower is execution, not
    shape-universe compilation — the analog of the warmed JVM+plugin the
    reference assumes. The same loop runs (cache keys come from real
    compiles), but the time-log marker rows say Warm, never Power.

    ``trace_dir`` writes one Chrome ``trace_event`` JSON per query
    (``{query}.trace.json``, loadable in chrome://tracing / Perfetto)
    from the obs span layer; the per-phase rollup lands in every query's
    JSON summary either way (tracing is default-on and adds zero host
    syncs).

    ``ledger_path`` (or ``NDS_TPU_LEDGER``) appends every query to the
    campaign evidence ledger (:mod:`nds_tpu.obs.ledger`): one validated,
    schema-versioned record per query — wall, sync counts, phase rollup,
    streamed-scan evidence — flushed as it lands, plus a terminal
    ``end`` record, so a killed campaign still leaves a complete,
    self-describing artifact for ``tools/bench_compare.py``."""
    from nds_tpu.engine.session import Session

    queries_reports = []
    execution_time_list: list = []
    total_time_start = time.time()
    if len(query_dict) == 1:
        app_name = "NDS - " + list(query_dict.keys())[0]
    else:
        app_name = "NDS - Power Run"

    conf = load_properties(property_file) if property_file else {}
    session = Session(conf)
    session.app_name = app_name
    if input_format in ("iceberg", "delta") or warehouse_type:
        # warehouse-backed tables: input_prefix is the warehouse root
        from nds_tpu.warehouse import Warehouse
        wh = Warehouse(input_prefix)
        session.warehouse = wh
        for table_name in wh.tables():
            start = time.time()
            session.create_temp_view(table_name, wh.read(table_name),
                                     base=True)
            execution_time_list.append(
                (session.app_id, f"CreateTempView {table_name}",
                 int((time.time() - start) * 1000)))
    else:
        execution_time_list = setup_tables(
            session, input_prefix, input_format, use_decimal,
            execution_time_list)

    check_json_summary_folder(json_summary_folder)
    if sub_queries:
        query_dict = get_query_subset(query_dict, sub_queries)

    # device-sharing policy for concurrent Throughput streams: the
    # concurrentGpuTasks analog (ref: nds/power_run_gpu.template:34,38) —
    # at most NDS_TPU_CONCURRENT_QUERIES queries in flight on the chip
    # across ALL streams sharing the admission dir; unset = unlimited
    from nds_tpu.parallel.admission import from_env as admission_from_env
    admission = admission_from_env()

    from nds_tpu.obs import export as _obs_export
    from nds_tpu.obs import metrics as _obs_metrics
    from nds_tpu.obs import trace as _obs_trace
    if trace_dir:
        os.makedirs(trace_dir, exist_ok=True)

    # live-metrics registry (nds_tpu/obs/metrics.py): reset at stream
    # start so the end-of-stream rollup record covers exactly this pass
    # — each Throughput stream is its own process, so per-stream ==
    # per-registry. Fed ONLY at the existing drain points below; the
    # mid-run snapshot file (NDS_TPU_METRICS_FILE) is refreshed per
    # query for tools/obs_live.py.
    metrics_reg = _obs_metrics.default()
    metrics_reg.reset()

    ledger = None
    ledger_path = ledger_path or os.environ.get("NDS_TPU_LEDGER")
    if ledger_path:
        from nds_tpu.obs.ledger import Ledger
        try:
            import jax as _jax
            _platform = _jax.devices()[0].platform
        except Exception:
            _platform = "unknown"
        ledger = Ledger(ledger_path, driver="power", platform=_platform,
                        app=app_name, format=input_format)

    power_start = int(time.time())
    # float twin of power_start: the reference time-log rows are
    # whole-second, but the stream metrics record needs a wall that
    # does not round a sub-second pass to zero (qps would vanish)
    power_start_f = time.time()
    for query_name, q_content in query_dict.items():
        print(f"====== Run {query_name} ======")
        q_report = BenchReport(session)
        trace_ctx = None
        if profile_folder:
            # per-query device trace (XProf/TensorBoard dump) — the TPU
            # analog of naming the query in the Spark UI via setJobGroup
            # (ref: nds/nds_power.py:257) plus a real profiler, which the
            # reference lacks (SURVEY.md §5.1)
            import jax.profiler as _prof
            trace_ctx = _prof.trace(os.path.join(profile_folder, query_name))
            trace_ctx.__enter__()
        from nds_tpu.engine import ops as _ops
        from nds_tpu.listener import drain_stream_events as _drain_stream
        _ops.enable_compile_meter()
        _drain_stream()          # setup leftovers must not charge query 1
        _obs_trace.drain_spans()  # same for trace records
        syncs_before = _ops.sync_count()
        wait_before = _ops.sync_wait_ns()
        fetch_before = _ops.fetch_bytes()
        compile_before = _ops.compile_ns()
        try:
            import jax as _jax
            stats_before = _jax.devices()[0].memory_stats() or {}
        except Exception:
            stats_before = {}
        import contextlib
        slot_ctx = (admission.slot() if admission is not None
                    else contextlib.nullcontext(0.0))
        try:
            with slot_ctx as queued_s:
                with _obs_trace.span("query", query=query_name):
                    elapsed = q_report.report_on(run_one_query, session,
                                                 q_content, query_name,
                                                 output_path, output_format)
        finally:
            if trace_ctx is not None:
                trace_ctx.__exit__(None, None, None)
        # roofline decomposition (DESIGN.md / SURVEY §5.1): host syncs are
        # dispatch-queue flushes (full-mesh barriers under GSPMD);
        # syncWaitMs is the wall time BLOCKED on device->host reads — the
        # rest of the wall overlaps dispatch with device compute; scanBytes
        # over wall time yields the effective scan bandwidth to hold
        # against the chip's HBM roofline
        q_report.summary["hostSyncs"] = _ops.sync_count() - syncs_before
        sync_ms = (_ops.sync_wait_ns() - wait_before) / 1e6
        q_report.summary["syncWaitMs"] = round(sync_ms, 3)
        q_report.summary["fetchBytes"] = _ops.fetch_bytes() - fetch_before
        # >HBM streamed scans (engine/stream.py): which path served each
        # ChunkedTable-bound scan — the compiled chunk pipeline or the
        # eager chunk loop — with chunk/sync counts, so a query blowing
        # the streamed sync budget names the scan (and fallback reason)
        # that charged it
        stream_events = _drain_stream()
        if stream_events:
            from nds_tpu.listener import stream_event_json
            q_report.summary["streamedScans"] = [
                stream_event_json(e) for e in stream_events]
        # fault-recovery evidence (engine/faults.py): retries, ladder
        # degradations and watchdog timeouts this query survived — the
        # reference's task-failure-listener idea applied to the
        # engine's own recovery paths, ridden into the ledger
        from nds_tpu.engine.faults import (drain_fault_events,
                                           fault_event_json)
        fault_events = drain_fault_events()
        if fault_events:
            q_report.summary["faultEvents"] = [
                fault_event_json(e) for e in fault_events]
        # per-phase trace rollup (nds_tpu/obs): where the query's wall
        # went — plan, stream record/compile/drive, materialize — plus
        # the top sync-charging host-read sites; the full span tree goes
        # to --trace-dir as a Chrome trace_event file
        trace_records = _obs_trace.drain_spans()
        if trace_records:
            roll = _obs_export.rollup(trace_records)
            q_report.summary["trace"] = roll
            if trace_dir:
                _obs_export.write_chrome_trace(
                    os.path.join(trace_dir, f"{query_name}.trace.json"),
                    trace_records, query=query_name, roll=roll)
        # compile-vs-execute split (round-4 verdict missing #3): compileMs
        # is XLA backend compilation charged to this query's wall (zero on
        # a warm shape universe / persistent-cache hit); the remainder is
        # dispatch + device execution + host IO
        compile_ms = (_ops.compile_ns() - compile_before) / 1e6
        q_report.summary["compileMs"] = round(compile_ms, 1)
        q_report.summary["execMs"] = round(max(elapsed - compile_ms, 0.0), 1)
        if admission is not None:
            # time spent waiting for a device slot (admission control);
            # NOT part of elapsed — the slot is held only while executing.
            # queueWaitMs is the live-metrics vocabulary for the same
            # number (admissionQueuedMs kept for older readers).
            q_report.summary["admissionQueuedMs"] = round(queued_s * 1e3, 1)
            q_report.summary["queueWaitMs"] = round(queued_s * 1e3, 1)
            q_report.summary["concurrentQueries"] = admission.slots
        scanned = getattr(session, "last_scanned", {})
        scan_bytes = sum(scanned.values())
        q_report.summary["scanBytes"] = scan_bytes
        if elapsed > 0:
            q_report.summary["scanGBps"] = round(
                scan_bytes / (elapsed / 1e3) / 1e9, 3)
            q_report.summary["syncWaitPct"] = round(
                100.0 * sync_ms / elapsed, 1)
        # per-query device-memory accounting where the backend exposes
        # allocator stats (local TPU; the tunneled attachment returns
        # none). peak_bytes_in_use is a PROCESS-lifetime high-water mark,
        # so the per-query fields are the current in-use footprint and
        # the amount THIS query raised the high-water mark by (nonzero
        # exactly when it became the heaviest so far) — the cumulative
        # peak is also recorded for the stream-level roofline.
        # (round-3 verdict missing #2: peak-HBM-per-query)
        try:
            import jax as _jax
            stats = _jax.devices()[0].memory_stats()
        except Exception:
            stats = None
        if stats:
            peak = int(stats.get("peak_bytes_in_use", 0))
            q_report.summary["hbmBytesInUse"] = int(
                stats.get("bytes_in_use", 0))
            q_report.summary["peakHbmCumulativeBytes"] = peak
            q_report.summary["peakHbmRaisedBy"] = peak - int(
                stats_before.get("peak_bytes_in_use", 0))
            q_report.summary["hbmLimitBytes"] = int(
                stats.get("bytes_limit", 0))
        else:
            q_report.summary["hbmStatsAvailable"] = False
            q_report.summary["residentBytes"] = scan_bytes
        print(f"Time taken: [{elapsed}] millis for {query_name}")
        # 4th column: compile split (readers index rows [0:3], so the
        # reference's 3-column contract is preserved for marker rows)
        execution_time_list.append((session.app_id, query_name, elapsed,
                                    round(compile_ms, 1)))
        q_report.summary["query"] = query_name
        # JSON summaries must be distinguishable from official Power
        # summaries the same way the time-log CSV marker rows are
        # (test_warm.py): collectors globbing json_summary_folder filter
        # on phase != 'Warm'
        q_report.summary["phase"] = "Warm" if warm else "Power"
        status = "ok" if q_report.is_success() else "error"
        if status == "error" and any(
                e.action == "timeout" for e in fault_events):
            # the statement watchdog fired inside this query: the
            # classified status is `timeout` (the run continued)
            status = "timeout"
        # live-metrics feeds — at THIS existing drain point only (the
        # numbers above are already harvested; the registry reads no
        # device state, so sync parity holds with metrics ON)
        metrics_reg.inc("queries.total")
        metrics_reg.inc(f"queries.{status}")
        metrics_reg.observe(_obs_metrics.QUERY_WALL, elapsed)
        metrics_reg.observe(_obs_metrics.SYNC_WAIT, sync_ms)
        for s in q_report.summary.get("streamedScans", ()):
            stall = s.get("prefetchStallMs", 0.0)
            if stall > 0:
                metrics_reg.observe(_obs_metrics.STALL, stall)
        if fault_events:
            metrics_reg.inc("faults.total", len(fault_events))
        if ledger is not None:
            # the ledger record: the durable, validated slice of the
            # summary (flushed now, so a kill loses at most the query in
            # flight); evidence is derived from streamedScans by the
            # ledger writer
            rec = {"ms": elapsed, "phase": q_report.summary["phase"]}
            for k in ("hostSyncs", "syncWaitMs", "scanBytes", "scanGBps",
                      "compileMs", "execMs", "queueWaitMs",
                      "streamedScans", "faultEvents"):
                if k in q_report.summary:
                    rec[k] = q_report.summary[k]
            if "trace" in q_report.summary:
                rec["tracePhases"] = q_report.summary["trace"]
            if status == "error" and q_report.summary["exceptions"]:
                rec["error"] = str(q_report.summary["exceptions"][-1])[:300]
            ledger.query(query_name, status=status, **rec)
            # the rolling rollup as of this query (queries/min, rolling
            # wall quantiles, queue wait): the per-query metrics record
            ledger.metrics(scope="query", query=query_name,
                           **metrics_reg.query_rollup())
        queries_reports.append(q_report)
        # mid-run live snapshot (atomic replace; no-op unless
        # NDS_TPU_METRICS_FILE is set) — written while later queries
        # are still executing, which is the whole point
        _obs_metrics.export_live(
            registry=metrics_reg,
            extra={"driver": "power", "app": app_name,
                   "query": query_name, "done": len(queries_reports),
                   "total": len(query_dict),
                   "phase": q_report.summary["phase"]})
        if json_summary_folder:
            if property_file:
                summary_prefix = os.path.join(
                    json_summary_folder,
                    os.path.basename(property_file).split(".")[0])
            else:
                summary_prefix = os.path.join(json_summary_folder, "")
            q_report.write_summary(query_name, prefix=summary_prefix)
    power_end = int(time.time())
    power_elapse = int((power_end - power_start) * 1000)
    total_elapse = int((time.time() - total_time_start) * 1000)
    phase = "Warm" if warm else "Power"
    print(f"====== {phase} Test Time: {power_elapse} milliseconds ======")
    print(f"====== Total Time: {total_elapse} milliseconds ======")
    execution_time_list.append(
        (session.app_id, f"{phase} Start Time", power_start))
    execution_time_list.append(
        (session.app_id, f"{phase} End Time", power_end))
    execution_time_list.append(
        (session.app_id, f"{phase} Test Time", power_elapse))
    execution_time_list.append((session.app_id, "Total Time", total_elapse))
    if ledger is not None:
        # per-stream rollup (QPS, p50/p99 wall, queue-wait quantiles,
        # timeout-shed) over the whole pass — the Throughput driver's
        # stream-level metrics record, written before the terminal one
        ledger.metrics(scope="stream", app=app_name,
                       phase=phase,
                       **metrics_reg.stream_rollup(
                           time.time() - power_start_f))
        # terminal record: a ledger WITHOUT one is the signature of a
        # killed campaign (bench_compare reports it as incomplete)
        ledger.close("completed", queries=len(queries_reports),
                     wallS=round(total_elapse / 1e3, 1))

    header = ["application_id", "query", "time/milliseconds",
              "compile/milliseconds"]
    print(header)
    for row in execution_time_list:
        print(row)
    if time_log_output_path:
        with open(time_log_output_path, "w", encoding="UTF8") as f:
            writer = csv.writer(f)
            writer.writerow(header)
            writer.writerows(execution_time_list)
    if extra_time_log_output_path:
        os.makedirs(extra_time_log_output_path, exist_ok=True)
        with open(os.path.join(extra_time_log_output_path, "part-0.csv"),
                  "w", encoding="UTF8") as f:
            writer = csv.writer(f)
            writer.writerow(header)
            writer.writerows(execution_time_list)

    exit_code = 0
    for q in queries_reports:
        if not q.is_success():
            if exit_code == 0:
                print("====== Queries with failure ======")
            print("{} status: {}".format(q.summary["query"],
                                         q.summary["queryStatus"]))
            exit_code = 1
    if exit_code:
        print("Above queries failed or completed with failed tasks. "
              "Please check the logs for the detailed reason.")
    if not allow_failure and exit_code:
        sys.exit(exit_code)


def load_properties(filename: str) -> dict:
    """java-properties overlay file -> dict (ref: nds/nds_power.py:324-330)."""
    myvars = {}
    with open(filename) as myfile:
        for line in myfile:
            if line.strip().startswith("#") or "=" not in line:
                continue
            name, var = line.partition("=")[::2]
            myvars[name.strip()] = var.strip()
    return myvars
