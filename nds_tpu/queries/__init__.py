# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Query corpus + stream generation (the dsqgen role).

The reference drives the TPC-DS toolkit's ``dsqgen`` over user-supplied query
templates to emit permuted 99-query streams (ref: nds/nds_gen_query_stream.py:
42-89). This package is the TPU build's native equivalent: the 99 query
templates ship in ``templates/`` as Spark-dialect SQL with parameter
placeholders, and :func:`generate_query_streams` instantiates them into
stream files in the exact dsqgen output format the downstream drivers parse
(``-- start query N in stream S using template queryX.tpl`` markers;
consumed by gen_sql_from_stream, ref: nds/nds_power.py:50-77).

Template parameter syntax (one directive per line, before the SQL):

    --@ NAME = uniform(1998, 2002)        random integer, inclusive
    --@ NAME = pick('a', 'b', 'c')        one literal from the list
    --@ NAME = pool(category)             one value from a named data pool
    --@ NAME = sample(5, state)           5 distinct pool values -> [NAME.1..5]
    --@ NAME = sample(3, 1, 100)          3 distinct ints in range
    --@ NAME = date(1998-01-01, 2002-12-31)  random calendar date
    --@ NAME = expr([OTHER] + 30)         arithmetic on earlier params

Placeholders ``[NAME]`` / ``[NAME.i]`` substitute as raw text; templates
carry their own quotes. The pools mirror the native generator's value
vocabularies (native/ndsgen/ndsgen.cc POOL tables) so instantiated
predicates always hit real data.
"""

from __future__ import annotations

import datetime
import os
import re

import numpy as np

TEMPLATE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "templates")

# Queries whose template holds two statements and is split into _part1/_part2
# downstream (ref: nds/nds_gen_query_stream.py:75-89).
SPECIAL_SPLIT = (14, 23, 24, 39)

# value pools aligned with native/ndsgen/ndsgen.cc
POOLS = {
    "category": ["Women", "Men", "Children", "Sports", "Music", "Books",
                 "Home", "Electronics", "Jewelry", "Shoes"],
    "state": ["AL", "AK", "AZ", "AR", "CA", "CO", "CT", "DE", "FL", "GA",
              "HI", "ID", "IL", "IN", "IA", "KS", "KY", "LA", "ME", "MD",
              "MA", "MI", "MN", "MS", "MO", "MT", "NE", "NV", "NH", "NJ",
              "NM", "NY", "NC", "ND", "OH", "OK", "OR", "PA", "RI", "SC",
              "SD", "TN", "TX", "UT", "VT", "VA", "WA", "WV", "WI", "WY"],
    "county": ["Williamson County", "Walker County", "Ziebach County",
               "Daviess County", "Barrow County", "Franklin Parish",
               "Luce County", "Richland County", "Furnas County",
               "Maverick County", "Huron County", "Kittitas County",
               "Mobile County", "Fairfield County", "Jackson County",
               "Dauphin County", "San Miguel County", "Pennington County",
               "Bronx County", "Orange County", "Perry County",
               "Halifax County", "Dona Ana County", "Gogebic County",
               "Lea County", "Mesa County", "Wadena County",
               "Pipestone County"],
    "city": ["Midway", "Fairview", "Oak Grove", "Five Points", "Oakland",
             "Riverside", "Salem", "Georgetown", "Franklin", "New Hope",
             "Bunker Hill", "Hopewell", "Antioch", "Concord", "Clifton",
             "Marion", "Springfield", "Greenville", "Bridgeport", "Oakdale",
             "Glendale", "Lakeview", "Centerville", "Mount Olive", "Union",
             "Glenwood", "Pleasant Hill", "Liberty", "Sulphur Springs",
             "Pine Grove", "Waterloo", "Edgewood", "Friendship", "Greenwood",
             "Deerfield", "Shiloh", "Mountain View", "Lakewood", "Summit",
             "Plainview", "Pleasant Valley", "Woodville", "White Oak",
             "Oakwood", "Harmony", "Highland Park", "Kingston", "Red Hill",
             "Enterprise", "Arlington", "Lebanon", "Clinton", "Spring Hill",
             "Buena Vista", "Newport", "Florence", "Jamestown", "Ashland",
             "Wildwood", "Macedonia"],
    "education": ["Primary", "Secondary", "College", "2 yr Degree",
                  "4 yr Degree", "Advanced Degree", "Unknown"],
    "marital": ["M", "S", "D", "W", "U"],
    "gender": ["M", "F"],
    "credit": ["Low Risk", "Good", "High Risk", "Unknown"],
    "buy_potential": [">10000", "5001-10000", "1001-5000", "501-1000",
                      "0-500", "Unknown"],
    "color": ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
              "black", "blanched", "blue", "blush", "brown", "burlywood",
              "burnished", "chartreuse", "chiffon", "chocolate", "coral",
              "cornflower", "cornsilk", "cream", "cyan", "dark", "deep",
              "dim", "dodger", "drab", "firebrick", "floral", "forest",
              "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey",
              "honeydew", "hot", "indian", "ivory", "khaki", "lace",
              "lavender", "lawn", "lemon", "light", "lime", "linen",
              "magenta", "maroon", "medium", "metallic", "midnight", "mint",
              "misty", "moccasin", "navajo", "navy", "olive", "orange",
              "orchid", "pale", "papaya", "peach", "peru", "pink", "plum",
              "powder", "puff", "purple", "red", "rose", "rosy", "royal",
              "saddle", "salmon", "sandy", "seashell", "sienna", "sky",
              "slate", "smoke", "snow", "spring", "steel", "tan", "thistle",
              "tomato", "turquoise", "violet", "wheat", "white", "yellow"],
    "units": ["Each", "Dozen", "Case", "Pallet", "Gross", "Box", "Bundle",
              "Tsp", "Oz", "Lb", "Ton", "Dram", "Cup", "Gram", "Pound",
              "Ounce", "Unknown", "Carton", "Bunch", "N/A"],
    "size": ["small", "medium", "large", "extra large", "economy", "N/A",
             "petite"],
    "ship_mode_type": ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR",
                       "TWO DAY"],
}

def active_states(scale: float | None) -> int:
    """Scale-banded state-vocabulary size, kept in sync with the native
    generator (native/ndsgen/ndsgen.cc states_active) so state predicates
    sample values the data actually contains — the role the toolkit's
    scale-banded fips_county distribution plays for dsdgen+dsqgen."""
    if scale is None:
        return len(POOLS["state"])
    sf = float(scale)
    if sf < 1.0:
        return 8
    if sf < 100.0:
        return 16
    if sf < 1000.0:
        return 32
    return 50


_DEFINE_RE = re.compile(r"^--@\s*(\w+)\s*=\s*(.+?)\s*$", re.MULTILINE)
_CALL_RE = re.compile(r"^(\w+)\((.*)\)$", re.DOTALL)
_PLACEHOLDER_RE = re.compile(r"\[(\w+)(?:\.(\d+))\]|\[(\w+)\]")


def _parse_args(argstr: str):
    """Split a define call's arguments, honouring quoted strings."""
    args, cur, depth, quote = [], "", 0, None
    for ch in argstr:
        if quote:
            cur += ch
            if ch == quote:
                quote = None
        elif ch in "'\"":
            quote = ch
            cur += ch
        elif ch == "(":
            depth += 1
            cur += ch
        elif ch == ")":
            depth -= 1
            cur += ch
        elif ch == "," and depth == 0:
            args.append(cur.strip())
            cur = ""
        else:
            cur += ch
    if cur.strip():
        args.append(cur.strip())
    return args


def _literal(tok: str):
    if tok and tok[0] in "'\"":
        return tok[1:-1]
    return int(tok)


def _eval_define(expr: str, rng: np.random.Generator, env: dict,
                 pools: dict | None = None):
    pools = POOLS if pools is None else pools
    m = _CALL_RE.match(expr.strip())
    if not m:
        raise ValueError(f"bad template define: {expr}")
    fn, argstr = m.group(1), m.group(2)
    args = _parse_args(argstr)
    if fn == "uniform":
        lo, hi = int(args[0]), int(args[1])
        return int(rng.integers(lo, hi + 1))
    if fn == "pick":
        vals = [_literal(a) for a in args]
        return vals[int(rng.integers(0, len(vals)))]
    if fn == "pool":
        pool = pools[args[0]]
        return pool[int(rng.integers(0, len(pool)))]
    if fn == "sample":
        k = int(args[0])
        if len(args) == 2:          # sample(k, poolname)
            pool = pools[args[1]]
            idx = rng.choice(len(pool), size=min(k, len(pool)), replace=False)
            return [pool[int(i)] for i in idx]
        lo, hi = int(args[1]), int(args[2])   # sample(k, lo, hi)
        vals = rng.choice(np.arange(lo, hi + 1), size=k, replace=False)
        return [int(v) for v in vals]
    if fn == "date":
        lo = datetime.date.fromisoformat(args[0])
        hi = datetime.date.fromisoformat(args[1])
        span = (hi - lo).days
        return str(lo + datetime.timedelta(days=int(rng.integers(0, span + 1))))
    if fn == "expr":
        text = argstr
        for name, val in env.items():
            text = text.replace(f"[{name}]", str(val))
        return eval(text, {"__builtins__": {}}, {})  # arithmetic only
    raise ValueError(f"unknown template function: {fn}")


def instantiate_template(text: str, rng: np.random.Generator,
                         scale: float | None = None) -> str:
    """Resolve the --@ defines and substitute placeholders; returns bare SQL
    (no defines, no stream markers). ``scale`` bands the state pool to the
    vocabulary the generator emits at that scale factor."""
    pools = dict(POOLS)
    k = active_states(scale)
    for geo in ("state", "city", "county"):   # banded with the generator
        pools[geo] = POOLS[geo][:min(k, len(POOLS[geo]))]
    env: dict = {}
    for m in _DEFINE_RE.finditer(text):
        env[m.group(1)] = _eval_define(m.group(2), rng, env, pools)
    sql = _DEFINE_RE.sub("", text)

    def repl(m: re.Match) -> str:
        if m.group(1) is not None:       # [NAME.i]
            return str(env[m.group(1)][int(m.group(2)) - 1])
        return str(env[m.group(3)])

    out = _PLACEHOLDER_RE.sub(repl, sql)
    return out.strip("\n")


def list_templates(template_dir: str | None = None) -> list:
    """templates.lst order (ref: the toolkit's templates.lst consumed at
    nds/nds_gen_query_stream.py:64)."""
    lst = os.path.join(template_dir or TEMPLATE_DIR, "templates.lst")
    with open(lst) as f:
        return [ln.strip() for ln in f if ln.strip()]


def load_template(name: str, template_dir: str | None = None) -> str:
    with open(os.path.join(template_dir or TEMPLATE_DIR, name)) as f:
        return f.read()


def _stream_text(order, stream_id: int, rng: np.random.Generator,
                 template_dir: str | None = None,
                 scale: float | None = None) -> str:
    parts = []
    for pos, tpl_name in enumerate(order):
        sql = instantiate_template(load_template(tpl_name, template_dir), rng,
                                   scale)
        head = (f"-- start query {pos + 1} in stream {stream_id} "
                f"using template {tpl_name}")
        tail = (f"-- end query {pos + 1} in stream {stream_id} "
                f"using template {tpl_name}")
        if not sql.rstrip().endswith(";"):
            sql = sql.rstrip() + "\n;"
        parts.append(f"{head}\n{sql}\n{tail}\n\n")
    return "".join(parts)


def generate_query_streams(output_dir: str, streams: int | None = None,
                           template: str | None = None,
                           rngseed: int | None = None,
                           templates: list | None = None,
                           template_dir: str | None = None,
                           scale: float | None = None) -> list:
    """Write ``query_<i>.sql`` stream files (or a single named query file).

    Mirrors dsqgen semantics: ``streams`` permuted full streams, or one
    ``template`` instantiated as stream 0 (ref: nds/nds_gen_query_stream.py:
    42-89 incl. the _part1/_part2 rename for the 4 split queries).
    """
    os.makedirs(output_dir, exist_ok=True)
    seed = 19620718 if rngseed is None else int(rngseed)
    all_templates = templates if templates is not None else \
        list_templates(template_dir)
    written = []

    if template is not None:
        rng = np.random.default_rng(seed)
        text = _stream_text([template], 0, rng, template_dir, scale)
        qname = template[:-4]  # strip .tpl
        if any(str(q) in template for q in SPECIAL_SPLIT):
            part1, part2 = split_special_query(text)
            for suffix, body in (("_part1", part1), ("_part2", part2)):
                path = os.path.join(output_dir, f"{qname}{suffix}.sql")
                with open(path, "w") as f:
                    f.write(body)
                written.append(path)
        else:
            path = os.path.join(output_dir, f"{qname}.sql")
            with open(path, "w") as f:
                f.write(text)
            written.append(path)
        return written

    for s in range(int(streams)):
        rng = np.random.default_rng((seed, s))
        order = list(all_templates)
        # stream 0 runs the canonical template order; others are permutations
        if s > 0:
            order = [order[i] for i in rng.permutation(len(order))]
        path = os.path.join(output_dir, f"query_{s}.sql")
        with open(path, "w") as f:
            f.write(_stream_text(order, s, rng, template_dir, scale))
        written.append(path)
    return written


def split_special_query(q: str):
    """Split a two-statement query text into its _part1/_part2 texts
    (same contract as ref: nds/nds_gen_query_stream.py:91-103)."""
    split_q = q.split(";")
    part_1 = split_q[0].replace(".tpl", "_part1.tpl") + ";"
    head = split_q[0].split("\n")[0]
    part_2 = head.replace(".tpl", "_part2.tpl") + "\n" + split_q[1] + ";"
    return part_1, part_2


def supported_queries() -> list:
    """Template names the current planner is known to execute (the coverage
    ratchet; grows as SQL features land)."""
    lst = os.path.join(TEMPLATE_DIR, "supported.lst")
    if not os.path.exists(lst):
        return []
    with open(lst) as f:
        return [ln.strip() for ln in f if ln.strip() and not ln.startswith("#")]


SUPPORTED_QUERIES = supported_queries()
