--@ YEAR = uniform(1998, 2000)
--@ STATE = pool(state)
with customer_total_return as
(select sr_customer_sk as ctr_customer_sk,
        sr_store_sk as ctr_store_sk,
        sum(sr_return_amt) as ctr_total_return
 from store_returns, date_dim
 where sr_returned_date_sk = d_date_sk and d_year = [YEAR]
 group by sr_customer_sk, sr_store_sk)
select c_customer_id
from customer_total_return ctr1, store, customer
where ctr1.ctr_total_return > (select avg(ctr_total_return) * 1.2
                               from customer_total_return ctr2
                               where ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  and s_store_sk = ctr1.ctr_store_sk
  and s_state = '[STATE]'
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id
limit 100
