--@ YEAR = uniform(1999, 2002)
--@ MONTH = uniform(1, 4)
--@ COUNTY = sample(5, county)
select cd_gender, cd_marital_status, cd_education_status, count(*) cnt1,
       cd_purchase_estimate, count(*) cnt2, cd_credit_rating, count(*) cnt3,
       cd_dep_count, count(*) cnt4, cd_dep_employed_count, count(*) cnt5,
       cd_dep_college_count, count(*) cnt6
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
  and ca_county in ('[COUNTY.1]', '[COUNTY.2]', '[COUNTY.3]', '[COUNTY.4]', '[COUNTY.5]')
  and cd_demo_sk = c.c_current_cdemo_sk
  and exists (select * from store_sales, date_dim
              where c.c_customer_sk = ss_customer_sk
                and ss_sold_date_sk = d_date_sk
                and d_year = [YEAR]
                and d_moy between [MONTH] and [MONTH] + 3)
  and (exists (select * from web_sales, date_dim
               where c.c_customer_sk = ws_bill_customer_sk
                 and ws_sold_date_sk = d_date_sk
                 and d_year = [YEAR]
                 and d_moy between [MONTH] and [MONTH] + 3)
       or exists (select * from catalog_sales, date_dim
                  where c.c_customer_sk = cs_ship_customer_sk
                    and cs_sold_date_sk = d_date_sk
                    and d_year = [YEAR]
                    and d_moy between [MONTH] and [MONTH] + 3))
group by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
order by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
limit 100
