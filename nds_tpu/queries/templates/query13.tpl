--@ YEAR = uniform(1998, 2002)
--@ MS1 = pool(marital)
--@ MS2 = pool(marital)
--@ MS3 = pool(marital)
--@ ES1 = pool(education)
--@ ES2 = pool(education)
--@ ES3 = pool(education)
--@ STATE1 = sample(3, state)
--@ STATE2 = sample(3, state)
--@ STATE3 = sample(3, state)
select avg(ss_quantity),
       avg(ss_ext_sales_price),
       avg(ss_ext_wholesale_cost),
       sum(ss_ext_wholesale_cost)
from store_sales,
     store,
     customer_demographics,
     household_demographics,
     customer_address,
     date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk and d_year = [YEAR]
  and ((ss_hdemo_sk = hd_demo_sk
        and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '[MS1]'
        and cd_education_status = '[ES1]'
        and ss_sales_price between 100.00 and 150.00
        and hd_dep_count = 3)
    or (ss_hdemo_sk = hd_demo_sk
        and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '[MS2]'
        and cd_education_status = '[ES2]'
        and ss_sales_price between 50.00 and 100.00
        and hd_dep_count = 1)
    or (ss_hdemo_sk = hd_demo_sk
        and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '[MS3]'
        and cd_education_status = '[ES3]'
        and ss_sales_price between 150.00 and 200.00
        and hd_dep_count = 1))
  and ((ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('[STATE1.1]', '[STATE1.2]', '[STATE1.3]')
        and ss_net_profit between 100 and 200)
    or (ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('[STATE2.1]', '[STATE2.2]', '[STATE2.3]')
        and ss_net_profit between 150 and 300)
    or (ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('[STATE3.1]', '[STATE3.2]', '[STATE3.3]')
        and ss_net_profit between 50 and 250))
