--@ YEAR = uniform(1999, 2000)
--@ DAY = uniform(1, 28)
with cross_items as
 (select i_item_sk ss_item_sk
  from item,
   (select iss.i_brand_id brand_id, iss.i_class_id class_id, iss.i_category_id category_id
    from store_sales, item iss, date_dim d1
    where ss_item_sk = iss.i_item_sk
      and ss_sold_date_sk = d1.d_date_sk
      and d1.d_year between 1999 and 1999 + 2
    intersect
    select ics.i_brand_id, ics.i_class_id, ics.i_category_id
    from catalog_sales, item ics, date_dim d2
    where cs_item_sk = ics.i_item_sk
      and cs_sold_date_sk = d2.d_date_sk
      and d2.d_year between 1999 and 1999 + 2
    intersect
    select iws.i_brand_id, iws.i_class_id, iws.i_category_id
    from web_sales, item iws, date_dim d3
    where ws_item_sk = iws.i_item_sk
      and ws_sold_date_sk = d3.d_date_sk
      and d3.d_year between 1999 and 1999 + 2) x
  where i_brand_id = brand_id
    and i_class_id = class_id
    and i_category_id = category_id),
 avg_sales as
 (select avg(quantity * list_price) average_sales
  from (select ss_quantity quantity, ss_list_price list_price
        from store_sales, date_dim
        where ss_sold_date_sk = d_date_sk and d_year between 1999 and 1999 + 2
        union all
        select cs_quantity quantity, cs_list_price list_price
        from catalog_sales, date_dim
        where cs_sold_date_sk = d_date_sk and d_year between 1999 and 1999 + 2
        union all
        select ws_quantity quantity, ws_list_price list_price
        from web_sales, date_dim
        where ws_sold_date_sk = d_date_sk and d_year between 1999 and 1999 + 2) x)
select channel, i_brand_id, i_class_id, i_category_id, sum(sales), sum(number_sales)
from (select 'store' channel, i_brand_id, i_class_id, i_category_id,
             sum(ss_quantity * ss_list_price) sales, count(*) number_sales
      from store_sales, item, date_dim
      where ss_item_sk in (select ss_item_sk from cross_items)
        and ss_item_sk = i_item_sk
        and ss_sold_date_sk = d_date_sk
        and d_year = 1999 + 2 and d_moy = 11
      group by i_brand_id, i_class_id, i_category_id
      having sum(ss_quantity * ss_list_price) > (select average_sales from avg_sales)
      union all
      select 'catalog' channel, i_brand_id, i_class_id, i_category_id,
             sum(cs_quantity * cs_list_price) sales, count(*) number_sales
      from catalog_sales, item, date_dim
      where cs_item_sk in (select ss_item_sk from cross_items)
        and cs_item_sk = i_item_sk
        and cs_sold_date_sk = d_date_sk
        and d_year = 1999 + 2 and d_moy = 11
      group by i_brand_id, i_class_id, i_category_id
      having sum(cs_quantity * cs_list_price) > (select average_sales from avg_sales)
      union all
      select 'web' channel, i_brand_id, i_class_id, i_category_id,
             sum(ws_quantity * ws_list_price) sales, count(*) number_sales
      from web_sales, item, date_dim
      where ws_item_sk in (select ss_item_sk from cross_items)
        and ws_item_sk = i_item_sk
        and ws_sold_date_sk = d_date_sk
        and d_year = 1999 + 2 and d_moy = 11
      group by i_brand_id, i_class_id, i_category_id
      having sum(ws_quantity * ws_list_price) > (select average_sales from avg_sales)) y
group by rollup (channel, i_brand_id, i_class_id, i_category_id)
order by channel, i_brand_id, i_class_id, i_category_id
limit 100
;
with cross_items as
 (select i_item_sk ss_item_sk
  from item,
   (select iss.i_brand_id brand_id, iss.i_class_id class_id, iss.i_category_id category_id
    from store_sales, item iss, date_dim d1
    where ss_item_sk = iss.i_item_sk
      and ss_sold_date_sk = d1.d_date_sk
      and d1.d_year between 1999 and 1999 + 2
    intersect
    select ics.i_brand_id, ics.i_class_id, ics.i_category_id
    from catalog_sales, item ics, date_dim d2
    where cs_item_sk = ics.i_item_sk
      and cs_sold_date_sk = d2.d_date_sk
      and d2.d_year between 1999 and 1999 + 2
    intersect
    select iws.i_brand_id, iws.i_class_id, iws.i_category_id
    from web_sales, item iws, date_dim d3
    where ws_item_sk = iws.i_item_sk
      and ws_sold_date_sk = d3.d_date_sk
      and d3.d_year between 1999 and 1999 + 2) x
  where i_brand_id = brand_id
    and i_class_id = class_id
    and i_category_id = category_id),
 avg_sales as
 (select avg(quantity * list_price) average_sales
  from (select ss_quantity quantity, ss_list_price list_price
        from store_sales, date_dim
        where ss_sold_date_sk = d_date_sk and d_year between 1999 and 1999 + 2
        union all
        select cs_quantity quantity, cs_list_price list_price
        from catalog_sales, date_dim
        where cs_sold_date_sk = d_date_sk and d_year between 1999 and 1999 + 2
        union all
        select ws_quantity quantity, ws_list_price list_price
        from web_sales, date_dim
        where ws_sold_date_sk = d_date_sk and d_year between 1999 and 1999 + 2) x)
select this_year.channel ty_channel,
       this_year.i_brand_id ty_brand,
       this_year.i_class_id ty_class,
       this_year.i_category_id ty_category,
       this_year.sales ty_sales,
       this_year.number_sales ty_number_sales,
       last_year.channel ly_channel,
       last_year.i_brand_id ly_brand,
       last_year.i_class_id ly_class,
       last_year.i_category_id ly_category,
       last_year.sales ly_sales,
       last_year.number_sales ly_number_sales
from
 (select 'store' channel, i_brand_id, i_class_id, i_category_id,
         sum(ss_quantity * ss_list_price) sales, count(*) number_sales
  from store_sales, item, date_dim
  where ss_item_sk in (select ss_item_sk from cross_items)
    and ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and d_week_seq = (select d_week_seq from date_dim
                      where d_year = [YEAR] + 1 and d_moy = 12 and d_dom = [DAY])
  group by i_brand_id, i_class_id, i_category_id
  having sum(ss_quantity * ss_list_price) > (select average_sales from avg_sales)) this_year,
 (select 'store' channel, i_brand_id, i_class_id, i_category_id,
         sum(ss_quantity * ss_list_price) sales, count(*) number_sales
  from store_sales, item, date_dim
  where ss_item_sk in (select ss_item_sk from cross_items)
    and ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and d_week_seq = (select d_week_seq from date_dim
                      where d_year = [YEAR] and d_moy = 12 and d_dom = [DAY])
  group by i_brand_id, i_class_id, i_category_id
  having sum(ss_quantity * ss_list_price) > (select average_sales from avg_sales)) last_year
where this_year.i_brand_id = last_year.i_brand_id
  and this_year.i_class_id = last_year.i_class_id
  and this_year.i_category_id = last_year.i_category_id
order by this_year.channel, this_year.i_brand_id, this_year.i_class_id, this_year.i_category_id
limit 100
