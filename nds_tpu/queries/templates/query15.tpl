--@ YEAR = uniform(1998, 2002)
--@ QOY = uniform(1, 2)
select ca_zip, sum(cs_sales_price)
from catalog_sales, customer, customer_address, date_dim
where cs_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and (substr(ca_zip, 1, 5) in ('85669', '86197', '88274', '83405', '86475',
                                '85392', '85460', '80348', '81792')
       or ca_state in ('CA', 'WA', 'GA')
       or cs_sales_price > 500)
  and cs_sold_date_sk = d_date_sk
  and d_qoy = [QOY] and d_year = [YEAR]
group by ca_zip
order by ca_zip
limit 100
