--@ SDATE = date(1999-02-01, 2002-02-01)
--@ COUNTY = sample(5, county)
select count(distinct cs_order_number) as `order count`,
       sum(cs_ext_ship_cost) as `total shipping cost`,
       sum(cs_net_profit) as `total net profit`
from catalog_sales cs1, date_dim, customer_address, call_center
where d_date between cast('[SDATE]' as date) and (cast('[SDATE]' as date) + interval 60 days)
  and cs1.cs_ship_date_sk = d_date_sk
  and cs1.cs_ship_addr_sk = ca_address_sk
  and ca_state = 'GA'
  and cs1.cs_call_center_sk = cc_call_center_sk
  and cc_county in ('[COUNTY.1]', '[COUNTY.2]', '[COUNTY.3]', '[COUNTY.4]', '[COUNTY.5]')
  and exists (select * from catalog_sales cs2
              where cs1.cs_order_number = cs2.cs_order_number
                and cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
  and not exists (select * from catalog_returns cr1
                  where cs1.cs_order_number = cr1.cr_order_number)
order by count(distinct cs_order_number)
limit 100
