--@ YEAR = uniform(1998, 2002)
select i_item_id, i_item_desc, s_state,
       count(ss_quantity) as store_sales_quantitycount,
       avg(ss_quantity) as store_sales_quantityave,
       stddev_samp(ss_quantity) as store_sales_quantitystdev,
       stddev_samp(ss_quantity) / avg(ss_quantity) as store_sales_quantitycov,
       count(sr_return_quantity) as store_returns_quantitycount,
       avg(sr_return_quantity) as store_returns_quantityave,
       stddev_samp(sr_return_quantity) as store_returns_quantitystdev,
       stddev_samp(sr_return_quantity) / avg(sr_return_quantity) as store_returns_quantitycov,
       count(cs_quantity) as catalog_sales_quantitycount,
       avg(cs_quantity) as catalog_sales_quantityave,
       stddev_samp(cs_quantity) as catalog_sales_quantitystdev,
       stddev_samp(cs_quantity) / avg(cs_quantity) as catalog_sales_quantitycov
from store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_quarter_name = '[YEAR]Q1'
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk
  and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_quarter_name in ('[YEAR]Q1', '[YEAR]Q2', '[YEAR]Q3')
  and sr_customer_sk = cs_bill_customer_sk
  and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_quarter_name in ('[YEAR]Q1', '[YEAR]Q2', '[YEAR]Q3')
group by i_item_id, i_item_desc, s_state
order by i_item_id, i_item_desc, s_state
limit 100
