--@ YEAR = uniform(1998, 2002)
--@ MONTH = uniform(1, 12)
--@ GEN = pool(gender)
--@ ES = pool(education)
--@ STATE = sample(7, state)
select i_item_id, ca_country, ca_state, ca_county,
       avg(cast(cs_quantity as decimal(12,2))) agg1,
       avg(cast(cs_list_price as decimal(12,2))) agg2,
       avg(cast(cs_coupon_amt as decimal(12,2))) agg3,
       avg(cast(cs_sales_price as decimal(12,2))) agg4,
       avg(cast(cs_net_profit as decimal(12,2))) agg5,
       avg(cast(c_birth_year as decimal(12,2))) agg6,
       avg(cast(cd1.cd_dep_count as decimal(12,2))) agg7
from catalog_sales, customer_demographics cd1, customer_demographics cd2,
     customer, customer_address, date_dim, item
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd1.cd_demo_sk
  and cs_bill_customer_sk = c_customer_sk
  and cd1.cd_gender = '[GEN]'
  and cd1.cd_education_status = '[ES]'
  and c_current_cdemo_sk = cd2.cd_demo_sk
  and c_current_addr_sk = ca_address_sk
  and c_birth_month in ([MONTH], [MONTH] + 1, 3, 6, 9, 12)
  and d_year = [YEAR]
  and ca_state in ('[STATE.1]', '[STATE.2]', '[STATE.3]', '[STATE.4]',
                   '[STATE.5]', '[STATE.6]', '[STATE.7]')
group by rollup (i_item_id, ca_country, ca_state, ca_county)
order by ca_country, ca_state, ca_county, i_item_id
limit 100
