--@ YEAR = uniform(1998, 2002)
--@ MONTH = uniform(11, 12)
--@ MANAGER = uniform(1, 100)
select i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = [MANAGER]
  and d_moy = [MONTH]
  and d_year = [YEAR]
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
  and ss_store_sk = s_store_sk
group by i_brand, i_brand_id, i_manufact_id, i_manufact
order by ext_price desc, i_brand, i_brand_id, i_manufact_id, i_manufact
limit 100
