--@ YEAR = uniform(1998, 2001)
with wscs as
 (select sold_date_sk, sales_price
  from (select ws_sold_date_sk sold_date_sk, ws_ext_sales_price sales_price
        from web_sales
        union all
        select cs_sold_date_sk sold_date_sk, cs_ext_sales_price sales_price
        from catalog_sales) x),
 wswscs as
 (select d_week_seq,
         sum(case when (d_day_name = 'Sunday') then sales_price else null end) sun_sales,
         sum(case when (d_day_name = 'Monday') then sales_price else null end) mon_sales,
         sum(case when (d_day_name = 'Tuesday') then sales_price else null end) tue_sales,
         sum(case when (d_day_name = 'Wednesday') then sales_price else null end) wed_sales,
         sum(case when (d_day_name = 'Thursday') then sales_price else null end) thu_sales,
         sum(case when (d_day_name = 'Friday') then sales_price else null end) fri_sales,
         sum(case when (d_day_name = 'Saturday') then sales_price else null end) sat_sales
  from wscs, date_dim
  where d_date_sk = sold_date_sk
  group by d_week_seq)
select d_week_seq1,
       round(sun_sales1 / sun_sales2, 2),
       round(mon_sales1 / mon_sales2, 2),
       round(tue_sales1 / tue_sales2, 2),
       round(wed_sales1 / wed_sales2, 2),
       round(thu_sales1 / thu_sales2, 2),
       round(fri_sales1 / fri_sales2, 2),
       round(sat_sales1 / sat_sales2, 2)
from
 (select wswscs.d_week_seq d_week_seq1,
         sun_sales sun_sales1, mon_sales mon_sales1, tue_sales tue_sales1,
         wed_sales wed_sales1, thu_sales thu_sales1, fri_sales fri_sales1,
         sat_sales sat_sales1
  from wswscs, date_dim
  where date_dim.d_week_seq = wswscs.d_week_seq and d_year = [YEAR]) y,
 (select wswscs.d_week_seq d_week_seq2,
         sun_sales sun_sales2, mon_sales mon_sales2, tue_sales tue_sales2,
         wed_sales wed_sales2, thu_sales thu_sales2, fri_sales fri_sales2,
         sat_sales sat_sales2
  from wswscs, date_dim
  where date_dim.d_week_seq = wswscs.d_week_seq and d_year = [YEAR] + 1) z
where d_week_seq1 = d_week_seq2 - 53
order by d_week_seq1
