--@ SDATE = date(1998-01-01, 2002-06-01)
select *
from (select w_warehouse_name, i_item_id,
             sum(case when (cast(d_date as date) < cast('[SDATE]' as date))
                      then inv_quantity_on_hand else 0 end) as inv_before,
             sum(case when (cast(d_date as date) >= cast('[SDATE]' as date))
                      then inv_quantity_on_hand else 0 end) as inv_after
      from inventory, warehouse, item, date_dim
      where i_current_price between 0.99 and 1.49
        and i_item_sk = inv_item_sk
        and inv_warehouse_sk = w_warehouse_sk
        and inv_date_sk = d_date_sk
        and d_date between (cast('[SDATE]' as date) - interval 30 days)
                       and (cast('[SDATE]' as date) + interval 30 days)
      group by w_warehouse_name, i_item_id) x
where (case when inv_before > 0 then inv_after / inv_before else null end)
      between 2.0 / 3.0 and 3.0 / 2.0
order by w_warehouse_name, i_item_id
limit 100
