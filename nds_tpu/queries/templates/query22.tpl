--@ MONTH = uniform(1189, 1199)
select i_product_name, i_brand, i_class, i_category,
       avg(inv_quantity_on_hand) qoh
from inventory, date_dim, item
where inv_date_sk = d_date_sk
  and inv_item_sk = i_item_sk
  and d_month_seq between [MONTH] and [MONTH] + 11
group by rollup(i_product_name, i_brand, i_class, i_category)
order by qoh, i_product_name, i_brand, i_class, i_category
limit 100
