--@ YEAR = uniform(1998, 2000)
--@ MONTH = uniform(1, 7)
--@ TOPK = uniform(4, 4)
with frequent_ss_items as
 (select substr(i_item_desc, 1, 30) itemdesc, i_item_sk item_sk, d_date solddate, count(*) cnt
  from store_sales, date_dim, item
  where ss_sold_date_sk = d_date_sk
    and ss_item_sk = i_item_sk
    and d_year in ([YEAR], [YEAR] + 1, [YEAR] + 2, [YEAR] + 3)
  group by substr(i_item_desc, 1, 30), i_item_sk, d_date
  having count(*) > 4),
 max_store_sales as
 (select max(csales) tpcds_cmax
  from (select c_customer_sk, sum(ss_quantity * ss_sales_price) csales
        from store_sales, customer, date_dim
        where ss_customer_sk = c_customer_sk
          and ss_sold_date_sk = d_date_sk
          and d_year in ([YEAR], [YEAR] + 1, [YEAR] + 2, [YEAR] + 3)
        group by c_customer_sk) x),
 best_ss_customer as
 (select c_customer_sk, sum(ss_quantity * ss_sales_price) ssales
  from store_sales, customer
  where ss_customer_sk = c_customer_sk
  group by c_customer_sk
  having sum(ss_quantity * ss_sales_price) > (50 / 100.0) *
    (select * from max_store_sales))
select sum(sales)
from (select cs_quantity * cs_list_price sales
      from catalog_sales, date_dim
      where d_year = [YEAR] and d_moy = [MONTH]
        and cs_sold_date_sk = d_date_sk
        and cs_item_sk in (select item_sk from frequent_ss_items)
        and cs_bill_customer_sk in (select c_customer_sk from best_ss_customer)
      union all
      select ws_quantity * ws_list_price sales
      from web_sales, date_dim
      where d_year = [YEAR] and d_moy = [MONTH]
        and ws_sold_date_sk = d_date_sk
        and ws_item_sk in (select item_sk from frequent_ss_items)
        and ws_bill_customer_sk in (select c_customer_sk from best_ss_customer)) y
limit 100
;
with frequent_ss_items as
 (select substr(i_item_desc, 1, 30) itemdesc, i_item_sk item_sk, d_date solddate, count(*) cnt
  from store_sales, date_dim, item
  where ss_sold_date_sk = d_date_sk
    and ss_item_sk = i_item_sk
    and d_year in ([YEAR], [YEAR] + 1, [YEAR] + 2, [YEAR] + 3)
  group by substr(i_item_desc, 1, 30), i_item_sk, d_date
  having count(*) > 4),
 max_store_sales as
 (select max(csales) tpcds_cmax
  from (select c_customer_sk, sum(ss_quantity * ss_sales_price) csales
        from store_sales, customer, date_dim
        where ss_customer_sk = c_customer_sk
          and ss_sold_date_sk = d_date_sk
          and d_year in ([YEAR], [YEAR] + 1, [YEAR] + 2, [YEAR] + 3)
        group by c_customer_sk) x),
 best_ss_customer as
 (select c_customer_sk, sum(ss_quantity * ss_sales_price) ssales
  from store_sales, customer
  where ss_customer_sk = c_customer_sk
  group by c_customer_sk
  having sum(ss_quantity * ss_sales_price) > (50 / 100.0) *
    (select * from max_store_sales))
select c_last_name, c_first_name, sales
from (select c_last_name, c_first_name, sum(cs_quantity * cs_list_price) sales
      from catalog_sales, customer, date_dim
      where d_year = [YEAR] and d_moy = [MONTH]
        and cs_sold_date_sk = d_date_sk
        and cs_item_sk in (select item_sk from frequent_ss_items)
        and cs_bill_customer_sk in (select c_customer_sk from best_ss_customer)
        and cs_bill_customer_sk = c_customer_sk
      group by c_last_name, c_first_name
      union all
      select c_last_name, c_first_name, sum(ws_quantity * ws_list_price) sales
      from web_sales, customer, date_dim
      where d_year = [YEAR] and d_moy = [MONTH]
        and ws_sold_date_sk = d_date_sk
        and ws_item_sk in (select item_sk from frequent_ss_items)
        and ws_bill_customer_sk in (select c_customer_sk from best_ss_customer)
        and ws_bill_customer_sk = c_customer_sk
      group by c_last_name, c_first_name) y
order by c_last_name, c_first_name, sales
limit 100
