--@ COLOR1 = pool(color)
--@ COLOR2 = pool(color)
with ssales as
 (select c_last_name, c_first_name, s_store_name, ca_state, s_state,
         i_color, i_current_price, i_manager_id, i_units, i_size,
         sum(ss_net_paid) netpaid
  from store_sales, store_returns, store, item, customer, customer_address
  where ss_ticket_number = sr_ticket_number
    and ss_item_sk = sr_item_sk
    and ss_customer_sk = c_customer_sk
    and ss_item_sk = i_item_sk
    and ss_store_sk = s_store_sk
    and c_current_addr_sk = ca_address_sk
    and c_birth_country <> upper(ca_country)
    and s_zip = ca_zip
    and s_market_id = 8
  group by c_last_name, c_first_name, s_store_name, ca_state, s_state,
           i_color, i_current_price, i_manager_id, i_units, i_size)
select c_last_name, c_first_name, s_store_name, sum(netpaid) paid
from ssales
where i_color = '[COLOR1]'
group by c_last_name, c_first_name, s_store_name
having sum(netpaid) > (select 0.05 * avg(netpaid) from ssales)
order by c_last_name, c_first_name, s_store_name
;
with ssales as
 (select c_last_name, c_first_name, s_store_name, ca_state, s_state,
         i_color, i_current_price, i_manager_id, i_units, i_size,
         sum(ss_net_paid) netpaid
  from store_sales, store_returns, store, item, customer, customer_address
  where ss_ticket_number = sr_ticket_number
    and ss_item_sk = sr_item_sk
    and ss_customer_sk = c_customer_sk
    and ss_item_sk = i_item_sk
    and ss_store_sk = s_store_sk
    and c_current_addr_sk = ca_address_sk
    and c_birth_country <> upper(ca_country)
    and s_zip = ca_zip
    and s_market_id = 8
  group by c_last_name, c_first_name, s_store_name, ca_state, s_state,
           i_color, i_current_price, i_manager_id, i_units, i_size)
select c_last_name, c_first_name, s_store_name, sum(netpaid) paid
from ssales
where i_color = '[COLOR2]'
group by c_last_name, c_first_name, s_store_name
having sum(netpaid) > (select 0.05 * avg(netpaid) from ssales)
order by c_last_name, c_first_name, s_store_name
