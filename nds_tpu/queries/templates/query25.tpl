--@ YEAR = uniform(1998, 2002)
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) as store_sales_profit,
       sum(sr_net_loss) as store_returns_loss,
       sum(cs_net_profit) as catalog_sales_profit
from store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,
     date_dim d3, store, item
where d1.d_moy = 4
  and d1.d_year = [YEAR]
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk
  and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_moy between 4 and 10
  and d2.d_year = [YEAR]
  and sr_customer_sk = cs_bill_customer_sk
  and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_moy between 4 and 10
  and d3.d_year = [YEAR]
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
