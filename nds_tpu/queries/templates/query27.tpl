--@ YEAR = uniform(1998, 2002)
--@ GEN = pool(gender)
--@ MS = pool(marital)
--@ ES = pool(education)
--@ STATE = pool(state)
select i_item_id, s_state, grouping(s_state) g_state,
       avg(ss_quantity) agg1,
       avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk
  and ss_cdemo_sk = cd_demo_sk
  and cd_gender = '[GEN]'
  and cd_marital_status = '[MS]'
  and cd_education_status = '[ES]'
  and d_year = [YEAR]
  and s_state = '[STATE]'
group by rollup (i_item_id, s_state)
order by i_item_id, s_state
limit 100
