--@ LP1 = uniform(0, 190)
--@ LP2 = uniform(0, 190)
--@ LP3 = uniform(0, 190)
--@ LP4 = uniform(0, 190)
--@ LP5 = uniform(0, 190)
--@ LP6 = uniform(0, 190)
select *
from (select avg(ss_list_price) B1_LP, count(ss_list_price) B1_CNT,
             count(distinct ss_list_price) B1_CNTD
      from store_sales
      where ss_quantity between 0 and 5
        and (ss_list_price between [LP1] and [LP1] + 10
             or ss_coupon_amt between 459 and 459 + 1000
             or ss_wholesale_cost between 57 and 57 + 20)) B1,
     (select avg(ss_list_price) B2_LP, count(ss_list_price) B2_CNT,
             count(distinct ss_list_price) B2_CNTD
      from store_sales
      where ss_quantity between 6 and 10
        and (ss_list_price between [LP2] and [LP2] + 10
             or ss_coupon_amt between 2323 and 2323 + 1000
             or ss_wholesale_cost between 31 and 31 + 20)) B2,
     (select avg(ss_list_price) B3_LP, count(ss_list_price) B3_CNT,
             count(distinct ss_list_price) B3_CNTD
      from store_sales
      where ss_quantity between 11 and 15
        and (ss_list_price between [LP3] and [LP3] + 10
             or ss_coupon_amt between 1495 and 1495 + 1000
             or ss_wholesale_cost between 52 and 52 + 20)) B3,
     (select avg(ss_list_price) B4_LP, count(ss_list_price) B4_CNT,
             count(distinct ss_list_price) B4_CNTD
      from store_sales
      where ss_quantity between 16 and 20
        and (ss_list_price between [LP4] and [LP4] + 10
             or ss_coupon_amt between 3854 and 3854 + 1000
             or ss_wholesale_cost between 26 and 26 + 20)) B4,
     (select avg(ss_list_price) B5_LP, count(ss_list_price) B5_CNT,
             count(distinct ss_list_price) B5_CNTD
      from store_sales
      where ss_quantity between 21 and 25
        and (ss_list_price between [LP5] and [LP5] + 10
             or ss_coupon_amt between 7826 and 7826 + 1000
             or ss_wholesale_cost between 38 and 38 + 20)) B5,
     (select avg(ss_list_price) B6_LP, count(ss_list_price) B6_CNT,
             count(distinct ss_list_price) B6_CNTD
      from store_sales
      where ss_quantity between 26 and 30
        and (ss_list_price between [LP6] and [LP6] + 10
             or ss_coupon_amt between 5270 and 5270 + 1000
             or ss_wholesale_cost between 42 and 42 + 20)) B6
limit 100
