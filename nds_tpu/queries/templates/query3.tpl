--@ MONTH = uniform(11, 12)
--@ MANUFACT = uniform(1, 1000)
--@ AGGC = pick('ss_ext_sales_price', 'ss_sales_price', 'ss_ext_discount_amt', 'ss_net_profit')
select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
       sum([AGGC]) sum_agg
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manufact_id = [MANUFACT]
  and dt.d_moy = [MONTH]
group by dt.d_year, item.i_brand_id, item.i_brand
order by dt.d_year, sum_agg desc, brand_id
limit 100
