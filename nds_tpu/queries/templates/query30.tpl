--@ YEAR = uniform(1999, 2002)
--@ STATE = pool(state)
with customer_total_return as
 (select wr_returning_customer_sk as ctr_customer_sk,
         ca_state as ctr_state,
         sum(wr_return_amt) as ctr_total_return
  from web_returns, date_dim, customer_address
  where wr_returned_date_sk = d_date_sk
    and d_year = [YEAR]
    and wr_returning_addr_sk = ca_address_sk
  group by wr_returning_customer_sk, ca_state)
select c_customer_id, c_salutation, c_first_name, c_last_name,
       c_preferred_cust_flag, c_birth_day, c_birth_month, c_birth_year,
       c_birth_country, c_login, c_email_address, c_last_review_date_sk,
       ctr_total_return
from customer_total_return ctr1, customer_address, customer
where ctr1.ctr_total_return > (select avg(ctr_total_return) * 1.2
                               from customer_total_return ctr2
                               where ctr1.ctr_state = ctr2.ctr_state)
  and ca_address_sk = c_current_addr_sk
  and ca_state = '[STATE]'
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id, c_salutation, c_first_name, c_last_name,
         c_preferred_cust_flag, c_birth_day, c_birth_month, c_birth_year,
         c_birth_country, c_login, c_email_address, c_last_review_date_sk,
         ctr_total_return
limit 100
