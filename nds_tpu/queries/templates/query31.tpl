--@ YEAR = uniform(1999, 2001)
with ss as
 (select ca_county, d_qoy, d_year, sum(ss_ext_sales_price) as store_sales
  from store_sales, date_dim, customer_address
  where ss_sold_date_sk = d_date_sk and ss_addr_sk = ca_address_sk
  group by ca_county, d_qoy, d_year),
 ws as
 (select ca_county, d_qoy, d_year, sum(ws_ext_sales_price) as web_sales
  from web_sales, date_dim, customer_address
  where ws_sold_date_sk = d_date_sk and ws_bill_addr_sk = ca_address_sk
  group by ca_county, d_qoy, d_year)
select ss1.ca_county,
       ss1.d_year,
       ws2.web_sales / ws1.web_sales web_q1_q2_increase,
       ss2.store_sales / ss1.store_sales store_q1_q2_increase,
       ws3.web_sales / ws2.web_sales web_q2_q3_increase,
       ss3.store_sales / ss2.store_sales store_q2_q3_increase
from ss ss1, ss ss2, ss ss3, ws ws1, ws ws2, ws ws3
where ss1.d_qoy = 1 and ss1.d_year = [YEAR]
  and ss1.ca_county = ss2.ca_county
  and ss2.d_qoy = 2 and ss2.d_year = [YEAR]
  and ss2.ca_county = ss3.ca_county
  and ss3.d_qoy = 3 and ss3.d_year = [YEAR]
  and ss1.ca_county = ws1.ca_county
  and ws1.d_qoy = 1 and ws1.d_year = [YEAR]
  and ws1.ca_county = ws2.ca_county
  and ws2.d_qoy = 2 and ws2.d_year = [YEAR]
  and ws1.ca_county = ws3.ca_county
  and ws3.d_qoy = 3 and ws3.d_year = [YEAR]
  and case when ws1.web_sales > 0 then ws2.web_sales / ws1.web_sales else null end
      > case when ss1.store_sales > 0 then ss2.store_sales / ss1.store_sales else null end
  and case when ws2.web_sales > 0 then ws3.web_sales / ws2.web_sales else null end
      > case when ss2.store_sales > 0 then ss3.store_sales / ss2.store_sales else null end
order by ss1.ca_county
