--@ SDATE = date(1998-01-01, 2002-10-01)
--@ MANUFACT = uniform(1, 1000)
select sum(cs_ext_discount_amt) as `excess discount amount`
from catalog_sales, item, date_dim
where i_manufact_id = [MANUFACT]
  and i_item_sk = cs_item_sk
  and d_date between cast('[SDATE]' as date) and (cast('[SDATE]' as date) + interval 90 days)
  and d_date_sk = cs_sold_date_sk
  and cs_ext_discount_amt > (select 1.3 * avg(cs_ext_discount_amt)
                             from catalog_sales, date_dim
                             where cs_item_sk = i_item_sk
                               and d_date between cast('[SDATE]' as date)
                                              and (cast('[SDATE]' as date) + interval 90 days)
                               and d_date_sk = cs_sold_date_sk)
limit 100
