--@ YEAR = uniform(1998, 2002)
--@ MONTH = uniform(1, 12)
--@ CAT = pool(category)
with ss as
 (select i_manufact_id, sum(ss_ext_sales_price) total_sales
  from store_sales, date_dim, customer_address, item
  where i_manufact_id in (select i_manufact_id from item where i_category in ('[CAT]'))
    and ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and d_year = [YEAR] and d_moy = [MONTH]
    and ss_addr_sk = ca_address_sk
    and ca_gmt_offset = -5
  group by i_manufact_id),
 cs as
 (select i_manufact_id, sum(cs_ext_sales_price) total_sales
  from catalog_sales, date_dim, customer_address, item
  where i_manufact_id in (select i_manufact_id from item where i_category in ('[CAT]'))
    and cs_item_sk = i_item_sk
    and cs_sold_date_sk = d_date_sk
    and d_year = [YEAR] and d_moy = [MONTH]
    and cs_bill_addr_sk = ca_address_sk
    and ca_gmt_offset = -5
  group by i_manufact_id),
 ws as
 (select i_manufact_id, sum(ws_ext_sales_price) total_sales
  from web_sales, date_dim, customer_address, item
  where i_manufact_id in (select i_manufact_id from item where i_category in ('[CAT]'))
    and ws_item_sk = i_item_sk
    and ws_sold_date_sk = d_date_sk
    and d_year = [YEAR] and d_moy = [MONTH]
    and ws_bill_addr_sk = ca_address_sk
    and ca_gmt_offset = -5
  group by i_manufact_id)
select i_manufact_id, sum(total_sales) total_sales
from (select * from ss
      union all
      select * from cs
      union all
      select * from ws) tmp1
group by i_manufact_id
order by total_sales
limit 100
