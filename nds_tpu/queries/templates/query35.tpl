--@ YEAR = uniform(1999, 2002)
--@ AGG = pick('min', 'max', 'avg', 'sum')
select ca_state, cd_gender, cd_marital_status, cd_dep_count,
       count(*) cnt1,
       [AGG](cd_dep_count) agg1,
       cd_dep_employed_count,
       count(*) cnt2,
       [AGG](cd_dep_employed_count) agg2,
       cd_dep_college_count,
       count(*) cnt3,
       [AGG](cd_dep_college_count) agg3
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
  and cd_demo_sk = c.c_current_cdemo_sk
  and exists (select * from store_sales, date_dim
              where c.c_customer_sk = ss_customer_sk
                and ss_sold_date_sk = d_date_sk
                and d_year = [YEAR] and d_qoy < 4)
  and (exists (select * from web_sales, date_dim
               where c.c_customer_sk = ws_bill_customer_sk
                 and ws_sold_date_sk = d_date_sk
                 and d_year = [YEAR] and d_qoy < 4)
       or exists (select * from catalog_sales, date_dim
                  where c.c_customer_sk = cs_ship_customer_sk
                    and cs_sold_date_sk = d_date_sk
                    and d_year = [YEAR] and d_qoy < 4))
group by ca_state, cd_gender, cd_marital_status, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
order by ca_state, cd_gender, cd_marital_status, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
limit 100
