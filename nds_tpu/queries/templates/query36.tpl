--@ YEAR = uniform(1998, 2002)
--@ STATE = sample(8, state)
select sum(ss_net_profit) / sum(ss_ext_sales_price) as gross_margin,
       i_category, i_class,
       grouping(i_category) + grouping(i_class) as lochierarchy,
       rank() over (partition by grouping(i_category) + grouping(i_class),
                    case when grouping(i_class) = 0 then i_category end
                    order by sum(ss_net_profit) / sum(ss_ext_sales_price) asc) as rank_within_parent
from store_sales, date_dim d1, item, store
where d1.d_year = [YEAR]
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and s_state in ('[STATE.1]', '[STATE.2]', '[STATE.3]', '[STATE.4]',
                  '[STATE.5]', '[STATE.6]', '[STATE.7]', '[STATE.8]')
group by rollup(i_category, i_class)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent
limit 100
