--@ MONTH = uniform(1189, 1199)
select count(*)
from (select distinct c_last_name, c_first_name, d_date
      from store_sales, date_dim, customer
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_customer_sk = customer.c_customer_sk
        and d_month_seq between [MONTH] and [MONTH] + 11
      intersect
      select distinct c_last_name, c_first_name, d_date
      from catalog_sales, date_dim, customer
      where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
        and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
        and d_month_seq between [MONTH] and [MONTH] + 11
      intersect
      select distinct c_last_name, c_first_name, d_date
      from web_sales, date_dim, customer
      where web_sales.ws_sold_date_sk = date_dim.d_date_sk
        and web_sales.ws_bill_customer_sk = customer.c_customer_sk
        and d_month_seq between [MONTH] and [MONTH] + 11) hot_cust
limit 100
