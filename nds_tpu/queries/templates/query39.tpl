--@ YEAR = uniform(1998, 2002)
--@ MONTH = uniform(1, 11)
with inv as
 (select w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy, stdev, mean,
         case mean when 0 then null else stdev / mean end cov
  from (select w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,
               stddev_samp(inv_quantity_on_hand) stdev,
               avg(inv_quantity_on_hand) mean
        from inventory, item, warehouse, date_dim
        where inv_item_sk = i_item_sk
          and inv_warehouse_sk = w_warehouse_sk
          and inv_date_sk = d_date_sk
          and d_year = [YEAR]
        group by w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy) foo
  where case mean when 0 then 0 else stdev / mean end > 1)
select inv1.w_warehouse_sk, inv1.i_item_sk, inv1.d_moy, inv1.mean, inv1.cov,
       inv2.w_warehouse_sk, inv2.i_item_sk, inv2.d_moy, inv2.mean, inv2.cov
from inv inv1, inv inv2
where inv1.i_item_sk = inv2.i_item_sk
  and inv1.w_warehouse_sk = inv2.w_warehouse_sk
  and inv1.d_moy = [MONTH]
  and inv2.d_moy = [MONTH] + 1
order by inv1.w_warehouse_sk, inv1.i_item_sk, inv1.d_moy, inv1.mean, inv1.cov,
         inv2.d_moy, inv2.mean, inv2.cov
;
with inv as
 (select w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy, stdev, mean,
         case mean when 0 then null else stdev / mean end cov
  from (select w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,
               stddev_samp(inv_quantity_on_hand) stdev,
               avg(inv_quantity_on_hand) mean
        from inventory, item, warehouse, date_dim
        where inv_item_sk = i_item_sk
          and inv_warehouse_sk = w_warehouse_sk
          and inv_date_sk = d_date_sk
          and d_year = [YEAR]
        group by w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy) foo
  where case mean when 0 then 0 else stdev / mean end > 1)
select inv1.w_warehouse_sk, inv1.i_item_sk, inv1.d_moy, inv1.mean, inv1.cov,
       inv2.w_warehouse_sk, inv2.i_item_sk, inv2.d_moy, inv2.mean, inv2.cov
from inv inv1, inv inv2
where inv1.i_item_sk = inv2.i_item_sk
  and inv1.w_warehouse_sk = inv2.w_warehouse_sk
  and inv1.d_moy = [MONTH]
  and inv2.d_moy = [MONTH] + 1
  and inv1.cov > 1.5
order by inv1.w_warehouse_sk, inv1.i_item_sk, inv1.d_moy, inv1.mean, inv1.cov,
         inv2.d_moy, inv2.mean, inv2.cov
