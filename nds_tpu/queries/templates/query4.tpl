--@ YEAR = uniform(1998, 2001)
--@ SELECTONE = pick('t_s_secyear.customer_preferred_cust_flag', 't_s_secyear.customer_birth_country', 't_s_secyear.customer_login', 't_s_secyear.customer_email_address')
with year_total as (
 select c_customer_id customer_id,
        c_first_name customer_first_name,
        c_last_name customer_last_name,
        c_preferred_cust_flag customer_preferred_cust_flag,
        c_birth_country customer_birth_country,
        c_login customer_login,
        c_email_address customer_email_address,
        d_year dyear,
        sum(((ss_ext_list_price - ss_ext_wholesale_cost - ss_ext_discount_amt) + ss_ext_sales_price) / 2) year_total,
        's' sale_type
 from customer, store_sales, date_dim
 where c_customer_sk = ss_customer_sk and ss_sold_date_sk = d_date_sk
 group by c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag,
          c_birth_country, c_login, c_email_address, d_year
 union all
 select c_customer_id customer_id,
        c_first_name customer_first_name,
        c_last_name customer_last_name,
        c_preferred_cust_flag customer_preferred_cust_flag,
        c_birth_country customer_birth_country,
        c_login customer_login,
        c_email_address customer_email_address,
        d_year dyear,
        sum((((cs_ext_list_price - cs_ext_wholesale_cost - cs_ext_discount_amt) + cs_ext_sales_price) / 2)) year_total,
        'c' sale_type
 from customer, catalog_sales, date_dim
 where c_customer_sk = cs_bill_customer_sk and cs_sold_date_sk = d_date_sk
 group by c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag,
          c_birth_country, c_login, c_email_address, d_year
 union all
 select c_customer_id customer_id,
        c_first_name customer_first_name,
        c_last_name customer_last_name,
        c_preferred_cust_flag customer_preferred_cust_flag,
        c_birth_country customer_birth_country,
        c_login customer_login,
        c_email_address customer_email_address,
        d_year dyear,
        sum((((ws_ext_list_price - ws_ext_wholesale_cost - ws_ext_discount_amt) + ws_ext_sales_price) / 2)) year_total,
        'w' sale_type
 from customer, web_sales, date_dim
 where c_customer_sk = ws_bill_customer_sk and ws_sold_date_sk = d_date_sk
 group by c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag,
          c_birth_country, c_login, c_email_address, d_year
)
select t_s_secyear.customer_id,
       t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name,
       [SELECTONE]
from year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_c_firstyear, year_total t_c_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
where t_s_secyear.customer_id = t_s_firstyear.customer_id
  and t_s_firstyear.customer_id = t_c_secyear.customer_id
  and t_s_firstyear.customer_id = t_c_firstyear.customer_id
  and t_s_firstyear.customer_id = t_w_firstyear.customer_id
  and t_s_firstyear.customer_id = t_w_secyear.customer_id
  and t_s_firstyear.sale_type = 's'
  and t_c_firstyear.sale_type = 'c'
  and t_w_firstyear.sale_type = 'w'
  and t_s_secyear.sale_type = 's'
  and t_c_secyear.sale_type = 'c'
  and t_w_secyear.sale_type = 'w'
  and t_s_firstyear.dyear = [YEAR]
  and t_s_secyear.dyear = [YEAR] + 1
  and t_c_firstyear.dyear = [YEAR]
  and t_c_secyear.dyear = [YEAR] + 1
  and t_w_firstyear.dyear = [YEAR]
  and t_w_secyear.dyear = [YEAR] + 1
  and t_s_firstyear.year_total > 0
  and t_c_firstyear.year_total > 0
  and t_w_firstyear.year_total > 0
  and case when t_c_firstyear.year_total > 0 then t_c_secyear.year_total / t_c_firstyear.year_total else null end
      > case when t_s_firstyear.year_total > 0 then t_s_secyear.year_total / t_s_firstyear.year_total else null end
  and case when t_c_firstyear.year_total > 0 then t_c_secyear.year_total / t_c_firstyear.year_total else null end
      > case when t_w_firstyear.year_total > 0 then t_w_secyear.year_total / t_w_firstyear.year_total else null end
order by t_s_secyear.customer_id, t_s_secyear.customer_first_name,
         t_s_secyear.customer_last_name, [SELECTONE]
limit 100
