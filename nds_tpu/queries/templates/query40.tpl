--@ SDATE = date(1998-01-01, 2002-10-01)
select w_state, i_item_id,
       sum(case when (cast(d_date as date) < cast('[SDATE]' as date))
                then cs_sales_price - coalesce(cr_refunded_cash, 0) else 0 end) as sales_before,
       sum(case when (cast(d_date as date) >= cast('[SDATE]' as date))
                then cs_sales_price - coalesce(cr_refunded_cash, 0) else 0 end) as sales_after
from catalog_sales
     left outer join catalog_returns on (cs_order_number = cr_order_number
                                         and cs_item_sk = cr_item_sk),
     warehouse, item, date_dim
where i_current_price between 0.99 and 1.49
  and i_item_sk = cs_item_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_sold_date_sk = d_date_sk
  and d_date between (cast('[SDATE]' as date) - interval 30 days)
                 and (cast('[SDATE]' as date) + interval 30 days)
group by w_state, i_item_id
order by w_state, i_item_id
limit 100
