--@ MANUF = uniform(1, 1000)
select distinct (i_product_name)
from item i1
where i_manufact_id between [MANUF] and [MANUF] + 40
  and (select count(*) as item_cnt
       from item
       where (i_manufact = i1.i_manufact
              and ((i_category = 'Women'
                    and (i_color = 'powder' or i_color = 'khaki')
                    and (i_units = 'Ounce' or i_units = 'Oz')
                    and (i_size = 'medium' or i_size = 'extra large'))
                or (i_category = 'Women'
                    and (i_color = 'brown' or i_color = 'honeydew')
                    and (i_units = 'Bunch' or i_units = 'Ton')
                    and (i_size = 'N/A' or i_size = 'small'))
                or (i_category = 'Men'
                    and (i_color = 'floral' or i_color = 'deep')
                    and (i_units = 'N/A' or i_units = 'Dozen')
                    and (i_size = 'petite' or i_size = 'large'))
                or (i_category = 'Men'
                    and (i_color = 'light' or i_color = 'cornflower')
                    and (i_units = 'Box' or i_units = 'Pound')
                    and (i_size = 'medium' or i_size = 'extra large'))))
          or (i_manufact = i1.i_manufact
              and ((i_category = 'Women'
                    and (i_color = 'midnight' or i_color = 'snow')
                    and (i_units = 'Pallet' or i_units = 'Gross')
                    and (i_size = 'medium' or i_size = 'extra large'))
                or (i_category = 'Women'
                    and (i_color = 'cyan' or i_color = 'papaya')
                    and (i_units = 'Cup' or i_units = 'Dram')
                    and (i_size = 'N/A' or i_size = 'small'))
                or (i_category = 'Men'
                    and (i_color = 'orange' or i_color = 'frosted')
                    and (i_units = 'Each' or i_units = 'Tsp')
                    and (i_size = 'petite' or i_size = 'large'))
                or (i_category = 'Men'
                    and (i_color = 'forest' or i_color = 'ghost')
                    and (i_units = 'Lb' or i_units = 'Bundle')
                    and (i_size = 'medium' or i_size = 'extra large'))))) > 0
order by i_product_name
limit 100
