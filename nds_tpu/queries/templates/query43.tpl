--@ YEAR = uniform(1998, 2002)
--@ GMT = pick(-5, -6, -7, -8)
select s_store_name, s_store_id,
       sum(case when (d_day_name = 'Sunday') then ss_sales_price else null end) sun_sales,
       sum(case when (d_day_name = 'Monday') then ss_sales_price else null end) mon_sales,
       sum(case when (d_day_name = 'Tuesday') then ss_sales_price else null end) tue_sales,
       sum(case when (d_day_name = 'Wednesday') then ss_sales_price else null end) wed_sales,
       sum(case when (d_day_name = 'Thursday') then ss_sales_price else null end) thu_sales,
       sum(case when (d_day_name = 'Friday') then ss_sales_price else null end) fri_sales,
       sum(case when (d_day_name = 'Saturday') then ss_sales_price else null end) sat_sales
from date_dim, store_sales, store
where d_date_sk = ss_sold_date_sk
  and s_store_sk = ss_store_sk
  and s_gmt_offset = [GMT]
  and d_year = [YEAR]
group by s_store_name, s_store_id
order by s_store_name, s_store_id, sun_sales, mon_sales, tue_sales, wed_sales,
         thu_sales, fri_sales, sat_sales
limit 100
