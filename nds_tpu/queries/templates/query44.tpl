--@ NULLCOL = pick('ss_net_profit', 'ss_ext_sales_price', 'ss_net_paid')
select asceding.rnk, i1.i_product_name best_performing, i2.i_product_name worst_performing
from (select *
      from (select item_sk, rank() over (order by rank_col asc) rnk
            from (select ss_item_sk item_sk, avg(ss_net_profit) rank_col
                  from store_sales ss1
                  where ss_store_sk = 4
                  group by ss_item_sk
                  having avg(ss_net_profit) > 0.9 *
                    (select avg(ss_net_profit) rank_col
                     from store_sales
                     where ss_store_sk = 4
                       and [NULLCOL] is null
                     group by ss_store_sk)) V1) V11
      where rnk < 11) asceding,
     (select *
      from (select item_sk, rank() over (order by rank_col desc) rnk
            from (select ss_item_sk item_sk, avg(ss_net_profit) rank_col
                  from store_sales ss1
                  where ss_store_sk = 4
                  group by ss_item_sk
                  having avg(ss_net_profit) > 0.9 *
                    (select avg(ss_net_profit) rank_col
                     from store_sales
                     where ss_store_sk = 4
                       and [NULLCOL] is null
                     group by ss_store_sk)) V2) V21
      where rnk < 11) descending,
     item i1, item i2
where asceding.rnk = descending.rnk
  and i1.i_item_sk = asceding.item_sk
  and i2.i_item_sk = descending.item_sk
order by asceding.rnk
limit 100
