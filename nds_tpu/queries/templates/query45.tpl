--@ YEAR = uniform(1998, 2002)
--@ QOY = uniform(1, 2)
select ca_zip, ca_city, sum(ws_sales_price)
from web_sales, customer, customer_address, date_dim, item
where ws_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ws_item_sk = i_item_sk
  and (substr(ca_zip, 1, 5) in ('85669', '86197', '88274', '83405', '86475',
                                '85392', '85460', '80348', '81792')
       or i_item_id in (select i_item_id
                        from item
                        where i_item_sk in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29)))
  and ws_sold_date_sk = d_date_sk
  and d_qoy = [QOY] and d_year = [YEAR]
group by ca_zip, ca_city
order by ca_zip, ca_city
limit 100
