--@ YEAR = uniform(1998, 2000)
--@ DEP = uniform(0, 9)
--@ VEH = uniform(-1, 4)
--@ CITY = sample(5, city)
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       amt, profit
from (select ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      from store_sales, date_dim, store, household_demographics, customer_address
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and store_sales.ss_addr_sk = customer_address.ca_address_sk
        and (household_demographics.hd_dep_count = [DEP]
             or household_demographics.hd_vehicle_count = [VEH])
        and date_dim.d_dow in (6, 0)
        and date_dim.d_year in ([YEAR], [YEAR] + 1, [YEAR] + 2)
        and store.s_city in ('[CITY.1]', '[CITY.2]', '[CITY.3]', '[CITY.4]', '[CITY.5]')
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and customer.c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number
limit 100
