--@ YEAR = uniform(1999, 2001)
with v1 as (
 select i_category, i_brand, s_store_name, s_company_name, d_year, d_moy,
        sum(ss_sales_price) sum_sales,
        avg(sum(ss_sales_price)) over (partition by i_category, i_brand,
                                       s_store_name, s_company_name, d_year) avg_monthly_sales,
        rank() over (partition by i_category, i_brand, s_store_name, s_company_name
                     order by d_year, d_moy) rn
 from item, store_sales, date_dim, store
 where ss_item_sk = i_item_sk
   and ss_sold_date_sk = d_date_sk
   and ss_store_sk = s_store_sk
   and (d_year = [YEAR]
        or (d_year = [YEAR] - 1 and d_moy = 12)
        or (d_year = [YEAR] + 1 and d_moy = 1))
 group by i_category, i_brand, s_store_name, s_company_name, d_year, d_moy),
 v2 as (
 select v1.i_category, v1.i_brand, v1.s_store_name, v1.s_company_name,
        v1.d_year, v1.d_moy, v1.avg_monthly_sales, v1.sum_sales,
        v1_lag.sum_sales psum, v1_lead.sum_sales nsum
 from v1, v1 v1_lag, v1 v1_lead
 where v1.i_category = v1_lag.i_category
   and v1.i_category = v1_lead.i_category
   and v1.i_brand = v1_lag.i_brand
   and v1.i_brand = v1_lead.i_brand
   and v1.s_store_name = v1_lag.s_store_name
   and v1.s_store_name = v1_lead.s_store_name
   and v1.s_company_name = v1_lag.s_company_name
   and v1.s_company_name = v1_lead.s_company_name
   and v1.rn = v1_lag.rn + 1
   and v1.rn = v1_lead.rn - 1)
select *
from v2
where d_year = [YEAR]
  and avg_monthly_sales > 0
  and case when avg_monthly_sales > 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else null end > 0.1
order by sum_sales - avg_monthly_sales, 3
limit 100
