--@ YEAR = uniform(1998, 2002)
--@ MS = pool(marital)
--@ ES = pool(education)
--@ STATE1 = sample(3, state)
--@ STATE2 = sample(3, state)
--@ STATE3 = sample(3, state)
select sum(ss_quantity)
from store_sales, store, customer_demographics, customer_address, date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk and d_year = [YEAR]
  and ((cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '[MS]'
        and cd_education_status = '[ES]'
        and ss_sales_price between 100.00 and 150.00)
    or (cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '[MS]'
        and cd_education_status = '[ES]'
        and ss_sales_price between 50.00 and 100.00)
    or (cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = '[MS]'
        and cd_education_status = '[ES]'
        and ss_sales_price between 150.00 and 200.00))
  and ((ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('[STATE1.1]', '[STATE1.2]', '[STATE1.3]')
        and ss_net_profit between 0 and 2000)
    or (ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('[STATE2.1]', '[STATE2.2]', '[STATE2.3]')
        and ss_net_profit between 150 and 3000)
    or (ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('[STATE3.1]', '[STATE3.2]', '[STATE3.3]')
        and ss_net_profit between 50 and 25000))
