--@ YEAR = uniform(1998, 2002)
--@ MONTH = uniform(11, 12)
select channel, item, return_ratio, return_rank, currency_rank
from (select 'web' as channel, web.item, web.return_ratio,
             web.return_rank, web.currency_rank
      from (select item, return_ratio, currency_ratio,
                   rank() over (order by return_ratio) as return_rank,
                   rank() over (order by currency_ratio) as currency_rank
            from (select ws.ws_item_sk as item,
                         (cast(sum(coalesce(wr.wr_return_quantity, 0)) as decimal(15,4)) /
                          cast(sum(coalesce(ws.ws_quantity, 0)) as decimal(15,4))) as return_ratio,
                         (cast(sum(coalesce(wr.wr_return_amt, 0)) as decimal(15,4)) /
                          cast(sum(coalesce(ws.ws_net_paid, 0)) as decimal(15,4))) as currency_ratio
                  from web_sales ws
                       left outer join web_returns wr
                         on (ws.ws_order_number = wr.wr_order_number
                             and ws.ws_item_sk = wr.wr_item_sk),
                       date_dim
                  where wr.wr_return_amt > 10000
                    and ws.ws_net_profit > 1
                    and ws.ws_net_paid > 0
                    and ws.ws_quantity > 0
                    and ws_sold_date_sk = d_date_sk
                    and d_year = [YEAR] and d_moy = [MONTH]
                  group by ws.ws_item_sk) in_web) web
      where (web.return_rank <= 10 or web.currency_rank <= 10)
      union
      select 'catalog' as channel, catalog.item, catalog.return_ratio,
             catalog.return_rank, catalog.currency_rank
      from (select item, return_ratio, currency_ratio,
                   rank() over (order by return_ratio) as return_rank,
                   rank() over (order by currency_ratio) as currency_rank
            from (select cs.cs_item_sk as item,
                         (cast(sum(coalesce(cr.cr_return_quantity, 0)) as decimal(15,4)) /
                          cast(sum(coalesce(cs.cs_quantity, 0)) as decimal(15,4))) as return_ratio,
                         (cast(sum(coalesce(cr.cr_return_amount, 0)) as decimal(15,4)) /
                          cast(sum(coalesce(cs.cs_net_paid, 0)) as decimal(15,4))) as currency_ratio
                  from catalog_sales cs
                       left outer join catalog_returns cr
                         on (cs.cs_order_number = cr.cr_order_number
                             and cs.cs_item_sk = cr.cr_item_sk),
                       date_dim
                  where cr.cr_return_amount > 10000
                    and cs.cs_net_profit > 1
                    and cs.cs_net_paid > 0
                    and cs.cs_quantity > 0
                    and cs_sold_date_sk = d_date_sk
                    and d_year = [YEAR] and d_moy = [MONTH]
                  group by cs.cs_item_sk) in_cat) catalog
      where (catalog.return_rank <= 10 or catalog.currency_rank <= 10)
      union
      select 'store' as channel, store.item, store.return_ratio,
             store.return_rank, store.currency_rank
      from (select item, return_ratio, currency_ratio,
                   rank() over (order by return_ratio) as return_rank,
                   rank() over (order by currency_ratio) as currency_rank
            from (select sts.ss_item_sk as item,
                         (cast(sum(coalesce(sr.sr_return_quantity, 0)) as decimal(15,4)) /
                          cast(sum(coalesce(sts.ss_quantity, 0)) as decimal(15,4))) as return_ratio,
                         (cast(sum(coalesce(sr.sr_return_amt, 0)) as decimal(15,4)) /
                          cast(sum(coalesce(sts.ss_net_paid, 0)) as decimal(15,4))) as currency_ratio
                  from store_sales sts
                       left outer join store_returns sr
                         on (sts.ss_ticket_number = sr.sr_ticket_number
                             and sts.ss_item_sk = sr.sr_item_sk),
                       date_dim
                  where sr.sr_return_amt > 10000
                    and sts.ss_net_profit > 1
                    and sts.ss_net_paid > 0
                    and sts.ss_quantity > 0
                    and ss_sold_date_sk = d_date_sk
                    and d_year = [YEAR] and d_moy = [MONTH]
                  group by sts.ss_item_sk) in_store) store
      where (store.return_rank <= 10 or store.currency_rank <= 10)) x
order by 1, 4, 5, 2
limit 100
