--@ SALES_DATE = date(1998-08-01, 2002-10-01)
with ssr as
 (select s_store_id,
         sum(sales_price) as sales,
         sum(profit) as profit,
         sum(return_amt) as returns,
         sum(net_loss) as profit_loss
  from
   (select ss_store_sk as store_sk,
           ss_sold_date_sk as date_sk,
           ss_ext_sales_price as sales_price,
           ss_net_profit as profit,
           cast(0 as decimal(7,2)) as return_amt,
           cast(0 as decimal(7,2)) as net_loss
    from store_sales
    union all
    select sr_store_sk as store_sk,
           sr_returned_date_sk as date_sk,
           cast(0 as decimal(7,2)) as sales_price,
           cast(0 as decimal(7,2)) as profit,
           sr_return_amt as return_amt,
           sr_net_loss as net_loss
    from store_returns) salesreturns,
   date_dim, store
  where date_sk = d_date_sk
    and d_date between cast('[SALES_DATE]' as date) and (cast('[SALES_DATE]' as date) + interval 14 days)
    and store_sk = s_store_sk
  group by s_store_id),
 csr as
 (select cp_catalog_page_id,
         sum(sales_price) as sales,
         sum(profit) as profit,
         sum(return_amt) as returns,
         sum(net_loss) as profit_loss
  from
   (select cs_catalog_page_sk as page_sk,
           cs_sold_date_sk as date_sk,
           cs_ext_sales_price as sales_price,
           cs_net_profit as profit,
           cast(0 as decimal(7,2)) as return_amt,
           cast(0 as decimal(7,2)) as net_loss
    from catalog_sales
    union all
    select cr_catalog_page_sk as page_sk,
           cr_returned_date_sk as date_sk,
           cast(0 as decimal(7,2)) as sales_price,
           cast(0 as decimal(7,2)) as profit,
           cr_return_amount as return_amt,
           cr_net_loss as net_loss
    from catalog_returns) salesreturns,
   date_dim, catalog_page
  where date_sk = d_date_sk
    and d_date between cast('[SALES_DATE]' as date) and (cast('[SALES_DATE]' as date) + interval 14 days)
    and page_sk = cp_catalog_page_sk
  group by cp_catalog_page_id),
 wsr as
 (select web_site_id,
         sum(sales_price) as sales,
         sum(profit) as profit,
         sum(return_amt) as returns,
         sum(net_loss) as profit_loss
  from
   (select ws_web_site_sk as wsr_web_site_sk,
           ws_sold_date_sk as date_sk,
           ws_ext_sales_price as sales_price,
           ws_net_profit as profit,
           cast(0 as decimal(7,2)) as return_amt,
           cast(0 as decimal(7,2)) as net_loss
    from web_sales
    union all
    select ws_web_site_sk as wsr_web_site_sk,
           wr_returned_date_sk as date_sk,
           cast(0 as decimal(7,2)) as sales_price,
           cast(0 as decimal(7,2)) as profit,
           wr_return_amt as return_amt,
           wr_net_loss as net_loss
    from web_returns
    left outer join web_sales on (wr_item_sk = ws_item_sk and wr_order_number = ws_order_number)) salesreturns,
   date_dim, web_site
  where date_sk = d_date_sk
    and d_date between cast('[SALES_DATE]' as date) and (cast('[SALES_DATE]' as date) + interval 14 days)
    and wsr_web_site_sk = web_site_sk
  group by web_site_id)
select channel, id,
       sum(sales) as sales,
       sum(returns) as returns,
       sum(profit) as profit
from
 (select 'store channel' as channel, concat('store', s_store_id) as id,
         sales, returns, (profit - profit_loss) as profit
  from ssr
  union all
  select 'catalog channel' as channel, concat('catalog_page', cp_catalog_page_id) as id,
         sales, returns, (profit - profit_loss) as profit
  from csr
  union all
  select 'web channel' as channel, concat('web_site', web_site_id) as id,
         sales, returns, (profit - profit_loss) as profit
  from wsr) x
group by rollup (channel, id)
order by channel, id
limit 100
