--@ YEAR = uniform(1998, 2002)
--@ MONTH = uniform(8, 10)
select s_store_name, s_company_id, s_street_number, s_street_name,
       s_street_type, s_suite_number, s_city, s_county, s_state, s_zip,
       sum(case when (sr_returned_date_sk - ss_sold_date_sk <= 30) then 1 else 0 end) as `30 days`,
       sum(case when (sr_returned_date_sk - ss_sold_date_sk > 30)
                 and (sr_returned_date_sk - ss_sold_date_sk <= 60) then 1 else 0 end) as `31-60 days`,
       sum(case when (sr_returned_date_sk - ss_sold_date_sk > 60)
                 and (sr_returned_date_sk - ss_sold_date_sk <= 90) then 1 else 0 end) as `61-90 days`,
       sum(case when (sr_returned_date_sk - ss_sold_date_sk > 90)
                 and (sr_returned_date_sk - ss_sold_date_sk <= 120) then 1 else 0 end) as `91-120 days`,
       sum(case when (sr_returned_date_sk - ss_sold_date_sk > 120) then 1 else 0 end) as `>120 days`
from store_sales, store_returns, store, date_dim d1, date_dim d2
where d2.d_year = [YEAR]
  and d2.d_moy = [MONTH]
  and ss_ticket_number = sr_ticket_number
  and ss_item_sk = sr_item_sk
  and ss_sold_date_sk = d1.d_date_sk
  and sr_returned_date_sk = d2.d_date_sk
  and ss_customer_sk = sr_customer_sk
  and ss_store_sk = s_store_sk
group by s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
order by s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
limit 100
