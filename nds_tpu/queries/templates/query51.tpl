--@ MONTH = uniform(1189, 1199)
with web_v1 as (
 select ws_item_sk item_sk, d_date,
        sum(sum(ws_sales_price)) over (partition by ws_item_sk order by d_date
                                       rows between unbounded preceding and current row) cume_sales
 from web_sales, date_dim
 where ws_sold_date_sk = d_date_sk
   and d_month_seq between [MONTH] and [MONTH] + 11
   and ws_item_sk is not null
 group by ws_item_sk, d_date),
 store_v1 as (
 select ss_item_sk item_sk, d_date,
        sum(sum(ss_sales_price)) over (partition by ss_item_sk order by d_date
                                       rows between unbounded preceding and current row) cume_sales
 from store_sales, date_dim
 where ss_sold_date_sk = d_date_sk
   and d_month_seq between [MONTH] and [MONTH] + 11
   and ss_item_sk is not null
 group by ss_item_sk, d_date)
select *
from (select item_sk, d_date, web_sales, store_sales,
             max(web_sales) over (partition by item_sk order by d_date
                                  rows between unbounded preceding and current row) web_cumulative,
             max(store_sales) over (partition by item_sk order by d_date
                                    rows between unbounded preceding and current row) store_cumulative
      from (select case when web.item_sk is not null then web.item_sk else store.item_sk end item_sk,
                   case when web.d_date is not null then web.d_date else store.d_date end d_date,
                   web.cume_sales web_sales,
                   store.cume_sales store_sales
            from web_v1 web full outer join store_v1 store
                 on (web.item_sk = store.item_sk and web.d_date = store.d_date)) x) y
where web_cumulative > store_cumulative
order by item_sk, d_date
limit 100
