--@ YEAR = uniform(1998, 2002)
--@ MONTH = uniform(11, 12)
select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = [MONTH]
  and dt.d_year = [YEAR]
group by dt.d_year, item.i_brand, item.i_brand_id
order by dt.d_year, ext_price desc, brand_id
limit 100
