--@ YEAR = uniform(1998, 2002)
--@ MONTH = uniform(1, 7)
--@ CAT = pool(category)
--@ CLASS = pool(state)
with my_customers as (
 select distinct c_customer_sk, c_current_addr_sk
 from (select cs_sold_date_sk sold_date_sk,
              cs_bill_customer_sk customer_sk,
              cs_item_sk item_sk
       from catalog_sales
       union all
       select ws_sold_date_sk sold_date_sk,
              ws_bill_customer_sk customer_sk,
              ws_item_sk item_sk
       from web_sales) cs_or_ws_sales,
      item, date_dim, customer
 where sold_date_sk = d_date_sk
   and item_sk = i_item_sk
   and i_category = '[CAT]'
   and i_class = 'maternity'
   and c_customer_sk = cs_or_ws_sales.customer_sk
   and d_moy = [MONTH]
   and d_year = [YEAR]),
 my_revenue as (
 select c_customer_sk, sum(ss_ext_sales_price) as revenue
 from my_customers, store_sales, customer_address, store, date_dim
 where c_current_addr_sk = ca_address_sk
   and ca_county = s_county
   and ca_state = s_state
   and ss_sold_date_sk = d_date_sk
   and c_customer_sk = ss_customer_sk
   and d_month_seq between (select distinct d_month_seq + 1
                            from date_dim where d_year = [YEAR] and d_moy = [MONTH])
                       and (select distinct d_month_seq + 3
                            from date_dim where d_year = [YEAR] and d_moy = [MONTH])
 group by c_customer_sk),
 segments as
 (select cast((revenue / 50) as int) as segment from my_revenue)
select segment, count(*) as num_customers, segment * 50 as segment_base
from segments
group by segment
order by segment, num_customers
limit 100
