--@ YEAR = uniform(1998, 2002)
--@ MONTH = uniform(11, 12)
--@ MANAGER = uniform(1, 100)
select i_brand_id brand_id, i_brand brand, sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = [MANAGER]
  and d_moy = [MONTH]
  and d_year = [YEAR]
group by i_brand, i_brand_id
order by ext_price desc, brand_id
limit 100
