--@ YEAR = uniform(1999, 2001)
with v1 as (
 select i_category, i_brand, cc_name, d_year, d_moy,
        sum(cs_sales_price) sum_sales,
        avg(sum(cs_sales_price)) over (partition by i_category, i_brand,
                                       cc_name, d_year) avg_monthly_sales,
        rank() over (partition by i_category, i_brand, cc_name
                     order by d_year, d_moy) rn
 from item, catalog_sales, date_dim, call_center
 where cs_item_sk = i_item_sk
   and cs_sold_date_sk = d_date_sk
   and cc_call_center_sk = cs_call_center_sk
   and (d_year = [YEAR]
        or (d_year = [YEAR] - 1 and d_moy = 12)
        or (d_year = [YEAR] + 1 and d_moy = 1))
 group by i_category, i_brand, cc_name, d_year, d_moy),
 v2 as (
 select v1.i_category, v1.i_brand, v1.cc_name, v1.d_year, v1.d_moy,
        v1.avg_monthly_sales, v1.sum_sales,
        v1_lag.sum_sales psum, v1_lead.sum_sales nsum
 from v1, v1 v1_lag, v1 v1_lead
 where v1.i_category = v1_lag.i_category
   and v1.i_category = v1_lead.i_category
   and v1.i_brand = v1_lag.i_brand
   and v1.i_brand = v1_lead.i_brand
   and v1.cc_name = v1_lag.cc_name
   and v1.cc_name = v1_lead.cc_name
   and v1.rn = v1_lag.rn + 1
   and v1.rn = v1_lead.rn - 1)
select *
from v2
where d_year = [YEAR]
  and avg_monthly_sales > 0
  and case when avg_monthly_sales > 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else null end > 0.1
order by sum_sales - avg_monthly_sales, 3
limit 100
