--@ SDATE = date(1998-01-01, 2002-10-01)
with ss_items as
 (select i_item_id item_id, sum(ss_ext_sales_price) ss_item_rev
  from store_sales, item, date_dim
  where ss_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq = (select d_week_seq from date_dim
                                       where d_date = cast('[SDATE]' as date)))
    and ss_sold_date_sk = d_date_sk
  group by i_item_id),
 cs_items as
 (select i_item_id item_id, sum(cs_ext_sales_price) cs_item_rev
  from catalog_sales, item, date_dim
  where cs_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq = (select d_week_seq from date_dim
                                       where d_date = cast('[SDATE]' as date)))
    and cs_sold_date_sk = d_date_sk
  group by i_item_id),
 ws_items as
 (select i_item_id item_id, sum(ws_ext_sales_price) ws_item_rev
  from web_sales, item, date_dim
  where ws_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq = (select d_week_seq from date_dim
                                       where d_date = cast('[SDATE]' as date)))
    and ws_sold_date_sk = d_date_sk
  group by i_item_id)
select ss_items.item_id,
       ss_item_rev,
       ss_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100 ss_dev,
       cs_item_rev,
       cs_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100 cs_dev,
       ws_item_rev,
       ws_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100 ws_dev,
       (ss_item_rev + cs_item_rev + ws_item_rev) / 3 average
from ss_items, cs_items, ws_items
where ss_items.item_id = cs_items.item_id
  and ss_items.item_id = ws_items.item_id
  and ss_item_rev between 0.9 * cs_item_rev and 1.1 * cs_item_rev
  and ss_item_rev between 0.9 * ws_item_rev and 1.1 * ws_item_rev
  and cs_item_rev between 0.9 * ss_item_rev and 1.1 * ss_item_rev
  and cs_item_rev between 0.9 * ws_item_rev and 1.1 * ws_item_rev
  and ws_item_rev between 0.9 * ss_item_rev and 1.1 * ss_item_rev
  and ws_item_rev between 0.9 * cs_item_rev and 1.1 * cs_item_rev
order by item_id, ss_item_rev
limit 100
