--@ MONTH = uniform(1189, 1199)
with wss as
 (select d_week_seq, ss_store_sk,
         sum(case when (d_day_name = 'Sunday') then ss_sales_price else null end) sun_sales,
         sum(case when (d_day_name = 'Monday') then ss_sales_price else null end) mon_sales,
         sum(case when (d_day_name = 'Tuesday') then ss_sales_price else null end) tue_sales,
         sum(case when (d_day_name = 'Wednesday') then ss_sales_price else null end) wed_sales,
         sum(case when (d_day_name = 'Thursday') then ss_sales_price else null end) thu_sales,
         sum(case when (d_day_name = 'Friday') then ss_sales_price else null end) fri_sales,
         sum(case when (d_day_name = 'Saturday') then ss_sales_price else null end) sat_sales
  from store_sales, date_dim
  where d_date_sk = ss_sold_date_sk
  group by d_week_seq, ss_store_sk)
select s_store_name1, s_store_id1, d_week_seq1,
       sun_sales1 / sun_sales2, mon_sales1 / mon_sales2,
       tue_sales1 / tue_sales2, wed_sales1 / wed_sales2,
       thu_sales1 / thu_sales2, fri_sales1 / fri_sales2,
       sat_sales1 / sat_sales2
from (select s_store_name s_store_name1, wss.d_week_seq d_week_seq1,
             s_store_id s_store_id1, sun_sales sun_sales1,
             mon_sales mon_sales1, tue_sales tue_sales1, wed_sales wed_sales1,
             thu_sales thu_sales1, fri_sales fri_sales1, sat_sales sat_sales1
      from wss, store, date_dim d
      where d.d_week_seq = wss.d_week_seq
        and ss_store_sk = s_store_sk
        and d_month_seq between [MONTH] and [MONTH] + 11) y,
     (select s_store_name s_store_name2, wss.d_week_seq d_week_seq2,
             s_store_id s_store_id2, sun_sales sun_sales2,
             mon_sales mon_sales2, tue_sales tue_sales2, wed_sales wed_sales2,
             thu_sales thu_sales2, fri_sales fri_sales2, sat_sales sat_sales2
      from wss, store, date_dim d
      where d.d_week_seq = wss.d_week_seq
        and ss_store_sk = s_store_sk
        and d_month_seq between [MONTH] + 12 and [MONTH] + 23) x
where s_store_id1 = s_store_id2
  and d_week_seq1 = d_week_seq2 - 52
order by s_store_name1, s_store_id1, d_week_seq1
limit 100
