--@ YEAR = uniform(1998, 2002)
--@ MONTH = uniform(1, 7)
select a.ca_state state, count(*) cnt
from customer_address a, customer c, store_sales s, date_dim d, item i
where a.ca_address_sk = c.c_current_addr_sk
  and c.c_customer_sk = s.ss_customer_sk
  and s.ss_sold_date_sk = d.d_date_sk
  and s.ss_item_sk = i.i_item_sk
  and d.d_month_seq =
      (select distinct (d_month_seq)
       from date_dim
       where d_year = [YEAR] and d_moy = [MONTH])
  and i.i_current_price > 1.2 *
      (select avg(j.i_current_price)
       from item j
       where j.i_category = i.i_category)
group by a.ca_state
having count(*) >= 10
order by cnt, a.ca_state
limit 100
