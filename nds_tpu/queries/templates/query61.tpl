--@ YEAR = uniform(1998, 2002)
--@ MONTH = uniform(11, 12)
--@ CAT = pool(category)
--@ GMT = pick(-5, -6, -7, -8)
select promotions, total, cast(promotions as decimal(15,4)) / cast(total as decimal(15,4)) * 100
from (select sum(ss_ext_sales_price) promotions
      from store_sales, store, promotion, date_dim, customer, customer_address, item
      where ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and ss_promo_sk = p_promo_sk
        and ss_customer_sk = c_customer_sk
        and ca_address_sk = c_current_addr_sk
        and ss_item_sk = i_item_sk
        and ca_gmt_offset = [GMT]
        and i_category = '[CAT]'
        and (p_channel_dmail = 'Y' or p_channel_email = 'Y' or p_channel_tv = 'Y')
        and s_gmt_offset = [GMT]
        and d_year = [YEAR]
        and d_moy = [MONTH]) promotional_sales,
     (select sum(ss_ext_sales_price) total
      from store_sales, store, date_dim, customer, customer_address, item
      where ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and ss_customer_sk = c_customer_sk
        and ca_address_sk = c_current_addr_sk
        and ss_item_sk = i_item_sk
        and ca_gmt_offset = [GMT]
        and i_category = '[CAT]'
        and s_gmt_offset = [GMT]
        and d_year = [YEAR]
        and d_moy = [MONTH]) all_sales
order by promotions, total
limit 100
