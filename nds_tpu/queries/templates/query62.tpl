--@ MONTH = uniform(1189, 1199)
select substr(w_warehouse_name, 1, 20), sm_type, web_name,
       sum(case when (ws_ship_date_sk - ws_sold_date_sk <= 30) then 1 else 0 end) as `30 days`,
       sum(case when (ws_ship_date_sk - ws_sold_date_sk > 30)
                 and (ws_ship_date_sk - ws_sold_date_sk <= 60) then 1 else 0 end) as `31-60 days`,
       sum(case when (ws_ship_date_sk - ws_sold_date_sk > 60)
                 and (ws_ship_date_sk - ws_sold_date_sk <= 90) then 1 else 0 end) as `61-90 days`,
       sum(case when (ws_ship_date_sk - ws_sold_date_sk > 90)
                 and (ws_ship_date_sk - ws_sold_date_sk <= 120) then 1 else 0 end) as `91-120 days`,
       sum(case when (ws_ship_date_sk - ws_sold_date_sk > 120) then 1 else 0 end) as `>120 days`
from web_sales, warehouse, ship_mode, web_site, date_dim
where d_month_seq between [MONTH] and [MONTH] + 11
  and ws_ship_date_sk = d_date_sk
  and ws_warehouse_sk = w_warehouse_sk
  and ws_ship_mode_sk = sm_ship_mode_sk
  and ws_web_site_sk = web_site_sk
group by substr(w_warehouse_name, 1, 20), sm_type, web_name
order by substr(w_warehouse_name, 1, 20), sm_type, web_name
limit 100
