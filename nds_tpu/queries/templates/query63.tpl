--@ MONTH = uniform(1189, 1199)
select *
from (select i_manager_id,
             sum(ss_sales_price) sum_sales,
             avg(sum(ss_sales_price)) over (partition by i_manager_id) avg_monthly_sales
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk
        and ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and d_month_seq in ([MONTH], [MONTH] + 1, [MONTH] + 2, [MONTH] + 3,
                            [MONTH] + 4, [MONTH] + 5, [MONTH] + 6, [MONTH] + 7,
                            [MONTH] + 8, [MONTH] + 9, [MONTH] + 10, [MONTH] + 11)
        and ((i_category in ('Books', 'Children', 'Electronics')
              and i_class in ('personal', 'portable', 'reference', 'self-help')
              and i_brand in ('scholaramalgamalg #14', 'scholaramalgamalg #7',
                              'exportiunivamalg #9', 'scholaramalgamalg #9'))
          or (i_category in ('Women', 'Music', 'Men')
              and i_class in ('accessories', 'classical', 'fragrances', 'pants')
              and i_brand in ('amalgimporto #1', 'edu packscholar #1',
                              'exportiimporto #1', 'importoamalg #1')))
      group by i_manager_id, d_moy) tmp1
where case when avg_monthly_sales > 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else null end > 0.1
order by i_manager_id, avg_monthly_sales, sum_sales
limit 100
