--@ YEAR = uniform(1999, 2001)
--@ PRICE = uniform(0, 85)
--@ COLOR1 = sample(8, color)
with cs_ui as
 (select cs_item_sk,
         sum(cs_ext_list_price) as sale,
         sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit) as refund
  from catalog_sales, catalog_returns
  where cs_item_sk = cr_item_sk and cs_order_number = cr_order_number
  group by cs_item_sk
  having sum(cs_ext_list_price) > 2 * sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit)),
 cross_sales as
 (select i_product_name product_name, i_item_sk item_sk,
         s_store_name store_name, s_zip store_zip,
         ad1.ca_street_number b_street_number, ad1.ca_street_name b_street_name,
         ad1.ca_city b_city, ad1.ca_zip b_zip,
         ad2.ca_street_number c_street_number, ad2.ca_street_name c_street_name,
         ad2.ca_city c_city, ad2.ca_zip c_zip,
         d1.d_year as syear, d2.d_year as fsyear, d3.d_year s2year,
         count(*) cnt,
         sum(ss_wholesale_cost) s1, sum(ss_list_price) s2, sum(ss_coupon_amt) s3
  from store_sales, store_returns, cs_ui, date_dim d1, date_dim d2, date_dim d3,
       store, customer, customer_demographics cd1, customer_demographics cd2,
       promotion, household_demographics hd1, household_demographics hd2,
       customer_address ad1, customer_address ad2, income_band ib1,
       income_band ib2, item
  where ss_store_sk = s_store_sk
    and ss_sold_date_sk = d1.d_date_sk
    and ss_customer_sk = c_customer_sk
    and ss_cdemo_sk = cd1.cd_demo_sk
    and ss_hdemo_sk = hd1.hd_demo_sk
    and ss_addr_sk = ad1.ca_address_sk
    and ss_item_sk = i_item_sk
    and ss_item_sk = sr_item_sk
    and ss_ticket_number = sr_ticket_number
    and ss_item_sk = cs_ui.cs_item_sk
    and c_current_cdemo_sk = cd2.cd_demo_sk
    and c_current_hdemo_sk = hd2.hd_demo_sk
    and c_current_addr_sk = ad2.ca_address_sk
    and c_first_sales_date_sk = d2.d_date_sk
    and c_first_shipto_date_sk = d3.d_date_sk
    and ss_promo_sk = p_promo_sk
    and hd1.hd_income_band_sk = ib1.ib_income_band_sk
    and hd2.hd_income_band_sk = ib2.ib_income_band_sk
    and cd1.cd_marital_status <> cd2.cd_marital_status
    and i_color in ('[COLOR1.1]', '[COLOR1.2]', '[COLOR1.3]', '[COLOR1.4]',
                    '[COLOR1.5]', '[COLOR1.6]', '[COLOR1.7]', '[COLOR1.8]')
    and i_current_price between [PRICE] and [PRICE] + 10
    and i_current_price between [PRICE] + 1 and [PRICE] + 15
  group by i_product_name, i_item_sk, s_store_name, s_zip,
           ad1.ca_street_number, ad1.ca_street_name, ad1.ca_city, ad1.ca_zip,
           ad2.ca_street_number, ad2.ca_street_name, ad2.ca_city, ad2.ca_zip,
           d1.d_year, d2.d_year, d3.d_year)
select cs1.product_name, cs1.store_name, cs1.store_zip,
       cs1.b_street_number, cs1.b_street_name, cs1.b_city, cs1.b_zip,
       cs1.c_street_number, cs1.c_street_name, cs1.c_city, cs1.c_zip,
       cs1.syear, cs1.cnt, cs1.s1 as s11, cs1.s2 as s21, cs1.s3 as s31,
       cs2.s1 as s12, cs2.s2 as s22, cs2.s3 as s32, cs2.syear, cs2.cnt
from cross_sales cs1, cross_sales cs2
where cs1.item_sk = cs2.item_sk
  and cs1.syear = [YEAR]
  and cs2.syear = [YEAR] + 1
  and cs2.cnt <= cs1.cnt
  and cs1.store_name = cs2.store_name
  and cs1.store_zip = cs2.store_zip
order by cs1.product_name, cs1.store_name, cs2.cnt, cs1.s1, cs2.s1
