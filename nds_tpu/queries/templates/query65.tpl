--@ MONTH = uniform(1189, 1199)
select s_store_name, i_item_desc, sc.revenue, i_current_price, i_wholesale_cost, i_brand
from store, item,
     (select ss_store_sk, avg(revenue) as ave
      from (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
            from store_sales, date_dim
            where ss_sold_date_sk = d_date_sk
              and d_month_seq between [MONTH] and [MONTH] + 11
            group by ss_store_sk, ss_item_sk) sa
      group by ss_store_sk) sb,
     (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk
        and d_month_seq between [MONTH] and [MONTH] + 11
      group by ss_store_sk, ss_item_sk) sc
where sb.ss_store_sk = sc.ss_store_sk
  and sc.revenue <= 0.1 * sb.ave
  and s_store_sk = sc.ss_store_sk
  and i_item_sk = sc.ss_item_sk
order by s_store_name, i_item_desc
limit 100
