--@ YEAR = uniform(1998, 2002)
--@ TIME = uniform(0, 57597)
--@ SMC = sample(2, ship_mode_type)
select w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state, w_country,
       ship_carriers, year,
       sum(jan_sales) as jan_sales,
       sum(feb_sales) as feb_sales,
       sum(mar_sales) as mar_sales,
       sum(apr_sales) as apr_sales,
       sum(may_sales) as may_sales,
       sum(jun_sales) as jun_sales,
       sum(jul_sales) as jul_sales,
       sum(aug_sales) as aug_sales,
       sum(sep_sales) as sep_sales,
       sum(oct_sales) as oct_sales,
       sum(nov_sales) as nov_sales,
       sum(dec_sales) as dec_sales,
       sum(jan_sales / w_warehouse_sq_ft) as jan_sales_per_sq_foot,
       sum(feb_sales / w_warehouse_sq_ft) as feb_sales_per_sq_foot,
       sum(mar_sales / w_warehouse_sq_ft) as mar_sales_per_sq_foot,
       sum(apr_sales / w_warehouse_sq_ft) as apr_sales_per_sq_foot,
       sum(may_sales / w_warehouse_sq_ft) as may_sales_per_sq_foot,
       sum(jun_sales / w_warehouse_sq_ft) as jun_sales_per_sq_foot,
       sum(jul_sales / w_warehouse_sq_ft) as jul_sales_per_sq_foot,
       sum(aug_sales / w_warehouse_sq_ft) as aug_sales_per_sq_foot,
       sum(sep_sales / w_warehouse_sq_ft) as sep_sales_per_sq_foot,
       sum(oct_sales / w_warehouse_sq_ft) as oct_sales_per_sq_foot,
       sum(nov_sales / w_warehouse_sq_ft) as nov_sales_per_sq_foot,
       sum(dec_sales / w_warehouse_sq_ft) as dec_sales_per_sq_foot,
       sum(jan_net) as jan_net,
       sum(feb_net) as feb_net,
       sum(mar_net) as mar_net,
       sum(apr_net) as apr_net,
       sum(may_net) as may_net,
       sum(jun_net) as jun_net,
       sum(jul_net) as jul_net,
       sum(aug_net) as aug_net,
       sum(sep_net) as sep_net,
       sum(oct_net) as oct_net,
       sum(nov_net) as nov_net,
       sum(dec_net) as dec_net
from (select w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
             w_country,
             concat('[SMC.1]', ',', '[SMC.2]') as ship_carriers,
             d_year as year,
             sum(case when d_moy = 1 then ws_ext_sales_price * ws_quantity else 0 end) as jan_sales,
             sum(case when d_moy = 2 then ws_ext_sales_price * ws_quantity else 0 end) as feb_sales,
             sum(case when d_moy = 3 then ws_ext_sales_price * ws_quantity else 0 end) as mar_sales,
             sum(case when d_moy = 4 then ws_ext_sales_price * ws_quantity else 0 end) as apr_sales,
             sum(case when d_moy = 5 then ws_ext_sales_price * ws_quantity else 0 end) as may_sales,
             sum(case when d_moy = 6 then ws_ext_sales_price * ws_quantity else 0 end) as jun_sales,
             sum(case when d_moy = 7 then ws_ext_sales_price * ws_quantity else 0 end) as jul_sales,
             sum(case when d_moy = 8 then ws_ext_sales_price * ws_quantity else 0 end) as aug_sales,
             sum(case when d_moy = 9 then ws_ext_sales_price * ws_quantity else 0 end) as sep_sales,
             sum(case when d_moy = 10 then ws_ext_sales_price * ws_quantity else 0 end) as oct_sales,
             sum(case when d_moy = 11 then ws_ext_sales_price * ws_quantity else 0 end) as nov_sales,
             sum(case when d_moy = 12 then ws_ext_sales_price * ws_quantity else 0 end) as dec_sales,
             sum(case when d_moy = 1 then ws_net_paid * ws_quantity else 0 end) as jan_net,
             sum(case when d_moy = 2 then ws_net_paid * ws_quantity else 0 end) as feb_net,
             sum(case when d_moy = 3 then ws_net_paid * ws_quantity else 0 end) as mar_net,
             sum(case when d_moy = 4 then ws_net_paid * ws_quantity else 0 end) as apr_net,
             sum(case when d_moy = 5 then ws_net_paid * ws_quantity else 0 end) as may_net,
             sum(case when d_moy = 6 then ws_net_paid * ws_quantity else 0 end) as jun_net,
             sum(case when d_moy = 7 then ws_net_paid * ws_quantity else 0 end) as jul_net,
             sum(case when d_moy = 8 then ws_net_paid * ws_quantity else 0 end) as aug_net,
             sum(case when d_moy = 9 then ws_net_paid * ws_quantity else 0 end) as sep_net,
             sum(case when d_moy = 10 then ws_net_paid * ws_quantity else 0 end) as oct_net,
             sum(case when d_moy = 11 then ws_net_paid * ws_quantity else 0 end) as nov_net,
             sum(case when d_moy = 12 then ws_net_paid * ws_quantity else 0 end) as dec_net
      from web_sales, warehouse, date_dim, time_dim, ship_mode
      where ws_warehouse_sk = w_warehouse_sk
        and ws_sold_date_sk = d_date_sk
        and ws_sold_time_sk = t_time_sk
        and ws_ship_mode_sk = sm_ship_mode_sk
        and d_year = [YEAR]
        and t_time between [TIME] and [TIME] + 28800
        and sm_carrier in ('UPS', 'FEDEX')
      group by w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
               w_country, d_year
      union all
      select w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
             w_country,
             concat('[SMC.1]', ',', '[SMC.2]') as ship_carriers,
             d_year as year,
             sum(case when d_moy = 1 then cs_sales_price * cs_quantity else 0 end) as jan_sales,
             sum(case when d_moy = 2 then cs_sales_price * cs_quantity else 0 end) as feb_sales,
             sum(case when d_moy = 3 then cs_sales_price * cs_quantity else 0 end) as mar_sales,
             sum(case when d_moy = 4 then cs_sales_price * cs_quantity else 0 end) as apr_sales,
             sum(case when d_moy = 5 then cs_sales_price * cs_quantity else 0 end) as may_sales,
             sum(case when d_moy = 6 then cs_sales_price * cs_quantity else 0 end) as jun_sales,
             sum(case when d_moy = 7 then cs_sales_price * cs_quantity else 0 end) as jul_sales,
             sum(case when d_moy = 8 then cs_sales_price * cs_quantity else 0 end) as aug_sales,
             sum(case when d_moy = 9 then cs_sales_price * cs_quantity else 0 end) as sep_sales,
             sum(case when d_moy = 10 then cs_sales_price * cs_quantity else 0 end) as oct_sales,
             sum(case when d_moy = 11 then cs_sales_price * cs_quantity else 0 end) as nov_sales,
             sum(case when d_moy = 12 then cs_sales_price * cs_quantity else 0 end) as dec_sales,
             sum(case when d_moy = 1 then cs_net_paid_inc_tax * cs_quantity else 0 end) as jan_net,
             sum(case when d_moy = 2 then cs_net_paid_inc_tax * cs_quantity else 0 end) as feb_net,
             sum(case when d_moy = 3 then cs_net_paid_inc_tax * cs_quantity else 0 end) as mar_net,
             sum(case when d_moy = 4 then cs_net_paid_inc_tax * cs_quantity else 0 end) as apr_net,
             sum(case when d_moy = 5 then cs_net_paid_inc_tax * cs_quantity else 0 end) as may_net,
             sum(case when d_moy = 6 then cs_net_paid_inc_tax * cs_quantity else 0 end) as jun_net,
             sum(case when d_moy = 7 then cs_net_paid_inc_tax * cs_quantity else 0 end) as jul_net,
             sum(case when d_moy = 8 then cs_net_paid_inc_tax * cs_quantity else 0 end) as aug_net,
             sum(case when d_moy = 9 then cs_net_paid_inc_tax * cs_quantity else 0 end) as sep_net,
             sum(case when d_moy = 10 then cs_net_paid_inc_tax * cs_quantity else 0 end) as oct_net,
             sum(case when d_moy = 11 then cs_net_paid_inc_tax * cs_quantity else 0 end) as nov_net,
             sum(case when d_moy = 12 then cs_net_paid_inc_tax * cs_quantity else 0 end) as dec_net
      from catalog_sales, warehouse, date_dim, time_dim, ship_mode
      where cs_warehouse_sk = w_warehouse_sk
        and cs_sold_date_sk = d_date_sk
        and cs_sold_time_sk = t_time_sk
        and cs_ship_mode_sk = sm_ship_mode_sk
        and d_year = [YEAR]
        and t_time between [TIME] and [TIME] + 28800
        and sm_carrier in ('UPS', 'FEDEX')
      group by w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
               w_country, d_year) x
group by w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
         w_country, ship_carriers, year
order by w_warehouse_name
limit 100
