--@ MONTH = uniform(1189, 1199)
select *
from (select i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
             d_moy, s_store_id, sumsales,
             rank() over (partition by i_category
                          order by sumsales desc) rk
      from (select i_category, i_class, i_brand, i_product_name, d_year,
                   d_qoy, d_moy, s_store_id,
                   sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales
            from store_sales, date_dim, store, item
            where ss_sold_date_sk = d_date_sk
              and ss_item_sk = i_item_sk
              and ss_store_sk = s_store_sk
              and d_month_seq between [MONTH] and [MONTH] + 11
            group by rollup(i_category, i_class, i_brand, i_product_name,
                            d_year, d_qoy, d_moy, s_store_id)) dw1) dw2
where rk <= 100
order by i_category, i_class, i_brand, i_product_name, d_year, d_qoy, d_moy,
         s_store_id, sumsales, rk
limit 100
