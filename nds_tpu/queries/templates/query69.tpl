--@ YEAR = uniform(1999, 2002)
--@ MONTH = uniform(1, 3)
--@ STATE = sample(3, state)
select cd_gender, cd_marital_status, cd_education_status, count(*) cnt1,
       cd_purchase_estimate, count(*) cnt2, cd_credit_rating, count(*) cnt3
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
  and ca_state in ('[STATE.1]', '[STATE.2]', '[STATE.3]')
  and cd_demo_sk = c.c_current_cdemo_sk
  and exists (select * from store_sales, date_dim
              where c.c_customer_sk = ss_customer_sk
                and ss_sold_date_sk = d_date_sk
                and d_year = [YEAR]
                and d_moy between [MONTH] and [MONTH] + 2)
  and (not exists (select * from web_sales, date_dim
                   where c.c_customer_sk = ws_bill_customer_sk
                     and ws_sold_date_sk = d_date_sk
                     and d_year = [YEAR]
                     and d_moy between [MONTH] and [MONTH] + 2)
       and not exists (select * from catalog_sales, date_dim
                       where c.c_customer_sk = cs_ship_customer_sk
                         and cs_sold_date_sk = d_date_sk
                         and d_year = [YEAR]
                         and d_moy between [MONTH] and [MONTH] + 2))
group by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
order by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
limit 100
