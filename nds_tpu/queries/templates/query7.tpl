--@ YEAR = uniform(1998, 2002)
--@ GEN = pool(gender)
--@ MS = pool(marital)
--@ ES = pool(education)
select i_item_id,
       avg(ss_quantity) agg1,
       avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk
  and ss_promo_sk = p_promo_sk
  and cd_gender = '[GEN]'
  and cd_marital_status = '[MS]'
  and cd_education_status = '[ES]'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = [YEAR]
group by i_item_id
order by i_item_id
limit 100
