--@ MONTH = uniform(1189, 1199)
select sum(ss_net_profit) as total_sum, s_state, s_county,
       grouping(s_state) + grouping(s_county) as lochierarchy,
       rank() over (partition by grouping(s_state) + grouping(s_county),
                    case when grouping(s_county) = 0 then s_state end
                    order by sum(ss_net_profit) desc) as rank_within_parent
from store_sales, date_dim d1, store
where d1.d_month_seq between [MONTH] and [MONTH] + 11
  and d1.d_date_sk = ss_sold_date_sk
  and s_store_sk = ss_store_sk
  and s_state in (select s_state
                  from (select s_state as s_state,
                               rank() over (partition by s_state
                                            order by sum(ss_net_profit) desc) ranking
                        from store_sales, store, date_dim
                        where d_month_seq between [MONTH] and [MONTH] + 11
                          and d_date_sk = ss_sold_date_sk
                          and s_store_sk = ss_store_sk
                        group by s_state) tmp1
                  where ranking <= 5)
group by rollup(s_state, s_county)
order by lochierarchy desc,
         case when lochierarchy = 0 then s_state end,
         rank_within_parent
limit 100
