--@ YEAR = uniform(1998, 2002)
--@ MONTH = uniform(11, 12)
--@ MANAGER = uniform(1, 100)
select i_brand_id brand_id, i_brand brand, t_hour, t_minute,
       sum(ext_price) ext_price
from item,
     (select ws_ext_sales_price as ext_price,
             ws_sold_date_sk as sold_date_sk,
             ws_item_sk as sold_item_sk,
             ws_sold_time_sk as time_sk
      from web_sales, date_dim
      where d_date_sk = ws_sold_date_sk
        and d_moy = [MONTH] and d_year = [YEAR]
      union all
      select cs_ext_sales_price as ext_price,
             cs_sold_date_sk as sold_date_sk,
             cs_item_sk as sold_item_sk,
             cs_sold_time_sk as time_sk
      from catalog_sales, date_dim
      where d_date_sk = cs_sold_date_sk
        and d_moy = [MONTH] and d_year = [YEAR]
      union all
      select ss_ext_sales_price as ext_price,
             ss_sold_date_sk as sold_date_sk,
             ss_item_sk as sold_item_sk,
             ss_sold_time_sk as time_sk
      from store_sales, date_dim
      where d_date_sk = ss_sold_date_sk
        and d_moy = [MONTH] and d_year = [YEAR]) tmp,
     time_dim
where sold_item_sk = i_item_sk
  and i_manager_id = [MANAGER]
  and time_sk = t_time_sk
  and (t_meal_time = 'breakfast' or t_meal_time = 'dinner')
group by i_brand, i_brand_id, t_hour, t_minute
order by ext_price desc, i_brand_id
