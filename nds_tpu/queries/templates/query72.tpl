--@ YEAR = uniform(1998, 2002)
--@ BP = pool(buy_potential)
--@ MS = pool(marital)
select i_item_desc, w_warehouse_name, d1.d_week_seq,
       sum(case when p_promo_sk is null then 1 else 0 end) no_promo,
       sum(case when p_promo_sk is not null then 1 else 0 end) promo,
       count(*) total_cnt
from catalog_sales
join inventory on (cs_item_sk = inv_item_sk)
join warehouse on (w_warehouse_sk = inv_warehouse_sk)
join item on (i_item_sk = cs_item_sk)
join customer_demographics on (cs_bill_cdemo_sk = cd_demo_sk)
join household_demographics on (cs_bill_hdemo_sk = hd_demo_sk)
join date_dim d1 on (cs_sold_date_sk = d1.d_date_sk)
join date_dim d2 on (inv_date_sk = d2.d_date_sk)
join date_dim d3 on (cs_ship_date_sk = d3.d_date_sk)
left outer join promotion on (cs_promo_sk = p_promo_sk)
left outer join catalog_returns on (cr_item_sk = cs_item_sk
                                    and cr_order_number = cs_order_number)
where d1.d_week_seq = d2.d_week_seq
  and inv_quantity_on_hand < cs_quantity
  and d3.d_date > d1.d_date + interval 5 days
  and hd_buy_potential = '[BP]'
  and d1.d_year = [YEAR]
  and cd_marital_status = '[MS]'
group by i_item_desc, w_warehouse_name, d1.d_week_seq
order by total_cnt desc, i_item_desc, w_warehouse_name, d1.d_week_seq
limit 100
