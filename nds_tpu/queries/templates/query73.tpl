--@ YEAR = uniform(1998, 2000)
--@ BPONE = pool(buy_potential)
--@ BPTWO = pool(buy_potential)
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from store_sales, date_dim, store, household_demographics
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and date_dim.d_dom between 1 and 2
        and (household_demographics.hd_buy_potential = '[BPONE]'
             or household_demographics.hd_buy_potential = '[BPTWO]')
        and household_demographics.hd_vehicle_count > 0
        and case when household_demographics.hd_vehicle_count > 0
                 then household_demographics.hd_dep_count / household_demographics.hd_vehicle_count
                 else null end > 1
        and date_dim.d_year in ([YEAR], [YEAR] + 1, [YEAR] + 2)
        and store.s_county in ('Williamson County', 'Franklin Parish',
                               'Bronx County', 'Orange County')
      group by ss_ticket_number, ss_customer_sk) dj, customer
where ss_customer_sk = c_customer_sk
  and cnt between 1 and 5
order by cnt desc, c_last_name asc
