--@ YEAR = uniform(1998, 2001)
--@ AGG = pick('max', 'sum', 'min', 'avg')
with year_total as (
 select c_customer_id customer_id,
        c_first_name customer_first_name,
        c_last_name customer_last_name,
        d_year as year,
        [AGG](ss_net_paid) year_total,
        's' sale_type
 from customer, store_sales, date_dim
 where c_customer_sk = ss_customer_sk
   and ss_sold_date_sk = d_date_sk
   and d_year in ([YEAR], [YEAR] + 1)
 group by c_customer_id, c_first_name, c_last_name, d_year
 union all
 select c_customer_id customer_id,
        c_first_name customer_first_name,
        c_last_name customer_last_name,
        d_year as year,
        [AGG](ws_net_paid) year_total,
        'w' sale_type
 from customer, web_sales, date_dim
 where c_customer_sk = ws_bill_customer_sk
   and ws_sold_date_sk = d_date_sk
   and d_year in ([YEAR], [YEAR] + 1)
 group by c_customer_id, c_first_name, c_last_name, d_year)
select t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name
from year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
where t_s_secyear.customer_id = t_s_firstyear.customer_id
  and t_s_firstyear.customer_id = t_w_secyear.customer_id
  and t_s_firstyear.customer_id = t_w_firstyear.customer_id
  and t_s_firstyear.sale_type = 's'
  and t_w_firstyear.sale_type = 'w'
  and t_s_secyear.sale_type = 's'
  and t_w_secyear.sale_type = 'w'
  and t_s_firstyear.year = [YEAR]
  and t_s_secyear.year = [YEAR] + 1
  and t_w_firstyear.year = [YEAR]
  and t_w_secyear.year = [YEAR] + 1
  and t_s_firstyear.year_total > 0
  and t_w_firstyear.year_total > 0
  and case when t_w_firstyear.year_total > 0
           then t_w_secyear.year_total / t_w_firstyear.year_total
           else null end
      > case when t_s_firstyear.year_total > 0
             then t_s_secyear.year_total / t_s_firstyear.year_total
             else null end
order by 1, 1, 1
limit 100
