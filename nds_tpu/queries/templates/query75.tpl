--@ YEAR = uniform(1999, 2001)
--@ CAT = pool(category)
with all_sales as (
 select d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
        sum(sales_cnt) as sales_cnt, sum(sales_amt) as sales_amt
 from (select d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
              cs_quantity - coalesce(cr_return_quantity, 0) as sales_cnt,
              cs_ext_sales_price - coalesce(cr_return_amount, 0.0) as sales_amt
       from catalog_sales
       join item on i_item_sk = cs_item_sk
       join date_dim on d_date_sk = cs_sold_date_sk
       left join catalog_returns on (cs_order_number = cr_order_number
                                     and cs_item_sk = cr_item_sk)
       where i_category = '[CAT]'
       union
       select d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
              ss_quantity - coalesce(sr_return_quantity, 0) as sales_cnt,
              ss_ext_sales_price - coalesce(sr_return_amt, 0.0) as sales_amt
       from store_sales
       join item on i_item_sk = ss_item_sk
       join date_dim on d_date_sk = ss_sold_date_sk
       left join store_returns on (ss_ticket_number = sr_ticket_number
                                   and ss_item_sk = sr_item_sk)
       where i_category = '[CAT]'
       union
       select d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
              ws_quantity - coalesce(wr_return_quantity, 0) as sales_cnt,
              ws_ext_sales_price - coalesce(wr_return_amt, 0.0) as sales_amt
       from web_sales
       join item on i_item_sk = ws_item_sk
       join date_dim on d_date_sk = ws_sold_date_sk
       left join web_returns on (ws_order_number = wr_order_number
                                 and ws_item_sk = wr_item_sk)
       where i_category = '[CAT]') sales_detail
 group by d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id)
select prev_yr.d_year as prev_year, curr_yr.d_year as year,
       curr_yr.i_brand_id, curr_yr.i_class_id, curr_yr.i_category_id,
       curr_yr.i_manufact_id,
       prev_yr.sales_cnt as prev_yr_cnt,
       curr_yr.sales_cnt as curr_yr_cnt,
       curr_yr.sales_cnt - prev_yr.sales_cnt as sales_cnt_diff,
       curr_yr.sales_amt - prev_yr.sales_amt as sales_amt_diff
from all_sales curr_yr, all_sales prev_yr
where curr_yr.i_brand_id = prev_yr.i_brand_id
  and curr_yr.i_class_id = prev_yr.i_class_id
  and curr_yr.i_category_id = prev_yr.i_category_id
  and curr_yr.i_manufact_id = prev_yr.i_manufact_id
  and curr_yr.d_year = [YEAR]
  and prev_yr.d_year = [YEAR] - 1
  and cast(curr_yr.sales_cnt as decimal(17,2)) / cast(prev_yr.sales_cnt as decimal(17,2)) < 0.9
order by sales_cnt_diff, sales_amt_diff
limit 100
