--@ NULLSS = pick('ss_store_sk', 'ss_addr_sk', 'ss_hdemo_sk', 'ss_cdemo_sk', 'ss_customer_sk', 'ss_promo_sk')
--@ NULLWS = pick('ws_web_page_sk', 'ws_bill_addr_sk', 'ws_ship_hdemo_sk', 'ws_bill_customer_sk', 'ws_promo_sk')
--@ NULLCS = pick('cs_warehouse_sk', 'cs_bill_addr_sk', 'cs_ship_hdemo_sk', 'cs_bill_customer_sk', 'cs_promo_sk')
select channel, col_name, d_year, d_qoy, i_category, count(*) sales_cnt,
       sum(ext_sales_price) sales_amt
from (select 'store' as channel, '[NULLSS]' col_name, d_year, d_qoy,
             i_category, ss_ext_sales_price ext_sales_price
      from store_sales, item, date_dim
      where [NULLSS] is null
        and ss_sold_date_sk = d_date_sk
        and ss_item_sk = i_item_sk
      union all
      select 'web' as channel, '[NULLWS]' col_name, d_year, d_qoy,
             i_category, ws_ext_sales_price ext_sales_price
      from web_sales, item, date_dim
      where [NULLWS] is null
        and ws_sold_date_sk = d_date_sk
        and ws_item_sk = i_item_sk
      union all
      select 'catalog' as channel, '[NULLCS]' col_name, d_year, d_qoy,
             i_category, cs_ext_sales_price ext_sales_price
      from catalog_sales, item, date_dim
      where [NULLCS] is null
        and cs_sold_date_sk = d_date_sk
        and cs_item_sk = i_item_sk) foo
group by channel, col_name, d_year, d_qoy, i_category
order by channel, col_name, d_year, d_qoy, i_category
limit 100
