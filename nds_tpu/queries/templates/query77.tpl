--@ SDATE = date(1998-08-01, 2002-10-01)
with ss as
 (select s_store_sk, sum(ss_ext_sales_price) as sales, sum(ss_net_profit) as profit
  from store_sales, date_dim, store
  where ss_sold_date_sk = d_date_sk
    and d_date between cast('[SDATE]' as date) and (cast('[SDATE]' as date) + interval 30 days)
    and ss_store_sk = s_store_sk
  group by s_store_sk),
 sr as
 (select s_store_sk, sum(sr_return_amt) as returns, sum(sr_net_loss) as profit_loss
  from store_returns, date_dim, store
  where sr_returned_date_sk = d_date_sk
    and d_date between cast('[SDATE]' as date) and (cast('[SDATE]' as date) + interval 30 days)
    and sr_store_sk = s_store_sk
  group by s_store_sk),
 cs as
 (select cs_call_center_sk, sum(cs_ext_sales_price) as sales, sum(cs_net_profit) as profit
  from catalog_sales, date_dim
  where cs_sold_date_sk = d_date_sk
    and d_date between cast('[SDATE]' as date) and (cast('[SDATE]' as date) + interval 30 days)
  group by cs_call_center_sk),
 cr as
 (select cr_call_center_sk, sum(cr_return_amount) as returns, sum(cr_net_loss) as profit_loss
  from catalog_returns, date_dim
  where cr_returned_date_sk = d_date_sk
    and d_date between cast('[SDATE]' as date) and (cast('[SDATE]' as date) + interval 30 days)
  group by cr_call_center_sk),
 ws as
 (select wp_web_page_sk, sum(ws_ext_sales_price) as sales, sum(ws_net_profit) as profit
  from web_sales, date_dim, web_page
  where ws_sold_date_sk = d_date_sk
    and d_date between cast('[SDATE]' as date) and (cast('[SDATE]' as date) + interval 30 days)
    and ws_web_page_sk = wp_web_page_sk
  group by wp_web_page_sk),
 wr as
 (select wp_web_page_sk, sum(wr_return_amt) as returns, sum(wr_net_loss) as profit_loss
  from web_returns, date_dim, web_page
  where wr_returned_date_sk = d_date_sk
    and d_date between cast('[SDATE]' as date) and (cast('[SDATE]' as date) + interval 30 days)
    and wr_web_page_sk = wp_web_page_sk
  group by wp_web_page_sk)
select channel, id, sum(sales) as sales, sum(returns) as returns, sum(profit) as profit
from (select 'store channel' as channel, ss.s_store_sk as id, sales,
             coalesce(returns, 0) as returns,
             (profit - coalesce(profit_loss, 0)) as profit
      from ss left join sr on ss.s_store_sk = sr.s_store_sk
      union all
      select 'catalog channel' as channel, cs_call_center_sk as id, sales,
             returns, (profit - profit_loss) as profit
      from cs, cr
      union all
      select 'web channel' as channel, ws.wp_web_page_sk as id, sales,
             coalesce(returns, 0) returns,
             (profit - coalesce(profit_loss, 0)) as profit
      from ws left join wr on ws.wp_web_page_sk = wr.wp_web_page_sk) x
group by rollup(channel, id)
order by channel, id
limit 100
