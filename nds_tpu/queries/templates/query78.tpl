--@ YEAR = uniform(1998, 2002)
with ws as
 (select d_year as ws_sold_year, ws_item_sk,
         ws_bill_customer_sk ws_customer_sk,
         sum(ws_quantity) ws_qty,
         sum(ws_wholesale_cost) ws_wc,
         sum(ws_sales_price) ws_sp
  from web_sales
  left join web_returns on wr_order_number = ws_order_number and ws_item_sk = wr_item_sk
  join date_dim on ws_sold_date_sk = d_date_sk
  where wr_order_number is null
  group by d_year, ws_item_sk, ws_bill_customer_sk),
 cs as
 (select d_year as cs_sold_year, cs_item_sk,
         cs_bill_customer_sk cs_customer_sk,
         sum(cs_quantity) cs_qty,
         sum(cs_wholesale_cost) cs_wc,
         sum(cs_sales_price) cs_sp
  from catalog_sales
  left join catalog_returns on cr_order_number = cs_order_number and cs_item_sk = cr_item_sk
  join date_dim on cs_sold_date_sk = d_date_sk
  where cr_order_number is null
  group by d_year, cs_item_sk, cs_bill_customer_sk),
 ss as
 (select d_year as ss_sold_year, ss_item_sk,
         ss_customer_sk,
         sum(ss_quantity) ss_qty,
         sum(ss_wholesale_cost) ss_wc,
         sum(ss_sales_price) ss_sp
  from store_sales
  left join store_returns on sr_ticket_number = ss_ticket_number and ss_item_sk = sr_item_sk
  join date_dim on ss_sold_date_sk = d_date_sk
  where sr_ticket_number is null
  group by d_year, ss_item_sk, ss_customer_sk)
select ss_sold_year, ss_item_sk, ss_customer_sk,
       round(ss_qty / (coalesce(ws_qty, 0) + coalesce(cs_qty, 0)), 2) ratio,
       ss_qty store_qty, ss_wc store_wholesale_cost, ss_sp store_sales_price,
       coalesce(ws_qty, 0) + coalesce(cs_qty, 0) other_chan_qty,
       coalesce(ws_wc, 0) + coalesce(cs_wc, 0) other_chan_wholesale_cost,
       coalesce(ws_sp, 0) + coalesce(cs_sp, 0) other_chan_sales_price
from ss
left join ws on (ws_sold_year = ss_sold_year and ws_item_sk = ss_item_sk
                 and ws_customer_sk = ss_customer_sk)
left join cs on (cs_sold_year = ss_sold_year and cs_item_sk = ss_item_sk
                 and cs_customer_sk = ss_customer_sk)
where (coalesce(ws_qty, 0) > 0 or coalesce(cs_qty, 0) > 0)
  and ss_sold_year = [YEAR]
order by ss_sold_year, ss_item_sk, ss_customer_sk, ss_qty desc, ss_wc desc,
         ss_sp desc, other_chan_qty, other_chan_wholesale_cost,
         other_chan_sales_price, ratio
limit 100
