--@ YEAR = uniform(1998, 2000)
--@ DEP = uniform(0, 9)
--@ VEH = uniform(-1, 4)
select c_last_name, c_first_name, substr(s_city, 1, 30), ss_ticket_number,
       amt, profit
from (select ss_ticket_number, ss_customer_sk, store.s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      from store_sales, date_dim, store, household_demographics
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and (household_demographics.hd_dep_count = [DEP]
             or household_demographics.hd_vehicle_count > [VEH])
        and date_dim.d_dow = 1
        and date_dim.d_year in ([YEAR], [YEAR] + 1, [YEAR] + 2)
        and store.s_number_employees between 200 and 295
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, store.s_city) ms,
     customer
where ss_customer_sk = c_customer_sk
order by c_last_name, c_first_name, substr(s_city, 1, 30), profit
limit 100
