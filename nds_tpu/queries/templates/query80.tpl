--@ SDATE = date(1998-08-01, 2002-10-01)
with ssr as
 (select s_store_id as store_id,
         sum(ss_ext_sales_price) as sales,
         sum(coalesce(sr_return_amt, 0)) as returns,
         sum(ss_net_profit - coalesce(sr_net_loss, 0)) as profit
  from store_sales
       left outer join store_returns on (ss_item_sk = sr_item_sk
                                         and ss_ticket_number = sr_ticket_number),
       date_dim, store, item, promotion
  where ss_sold_date_sk = d_date_sk
    and d_date between cast('[SDATE]' as date) and (cast('[SDATE]' as date) + interval 30 days)
    and ss_store_sk = s_store_sk
    and ss_item_sk = i_item_sk
    and i_current_price > 50
    and ss_promo_sk = p_promo_sk
    and p_channel_tv = 'N'
  group by s_store_id),
 csr as
 (select cp_catalog_page_id as catalog_page_id,
         sum(cs_ext_sales_price) as sales,
         sum(coalesce(cr_return_amount, 0)) as returns,
         sum(cs_net_profit - coalesce(cr_net_loss, 0)) as profit
  from catalog_sales
       left outer join catalog_returns on (cs_item_sk = cr_item_sk
                                           and cs_order_number = cr_order_number),
       date_dim, catalog_page, item, promotion
  where cs_sold_date_sk = d_date_sk
    and d_date between cast('[SDATE]' as date) and (cast('[SDATE]' as date) + interval 30 days)
    and cs_catalog_page_sk = cp_catalog_page_sk
    and cs_item_sk = i_item_sk
    and i_current_price > 50
    and cs_promo_sk = p_promo_sk
    and p_channel_tv = 'N'
  group by cp_catalog_page_id),
 wsr as
 (select web_site_id,
         sum(ws_ext_sales_price) as sales,
         sum(coalesce(wr_return_amt, 0)) as returns,
         sum(ws_net_profit - coalesce(wr_net_loss, 0)) as profit
  from web_sales
       left outer join web_returns on (ws_item_sk = wr_item_sk
                                       and ws_order_number = wr_order_number),
       date_dim, web_site, item, promotion
  where ws_sold_date_sk = d_date_sk
    and d_date between cast('[SDATE]' as date) and (cast('[SDATE]' as date) + interval 30 days)
    and ws_web_site_sk = web_site_sk
    and ws_item_sk = i_item_sk
    and i_current_price > 50
    and ws_promo_sk = p_promo_sk
    and p_channel_tv = 'N'
  group by web_site_id)
select channel, id, sum(sales) as sales, sum(returns) as returns,
       sum(profit) as profit
from (select 'store channel' as channel, concat('store', store_id) as id,
             sales, returns, profit
      from ssr
      union all
      select 'catalog channel' as channel,
             concat('catalog_page', catalog_page_id) as id,
             sales, returns, profit
      from csr
      union all
      select 'web channel' as channel, concat('web_site', web_site_id) as id,
             sales, returns, profit
      from wsr) x
group by rollup(channel, id)
order by channel, id
limit 100
