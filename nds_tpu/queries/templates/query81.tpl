--@ YEAR = uniform(1999, 2002)
--@ STATE = pool(state)
with customer_total_return as
 (select cr_returning_customer_sk as ctr_customer_sk,
         ca_state as ctr_state,
         sum(cr_return_amt_inc_tax) as ctr_total_return
  from catalog_returns, date_dim, customer_address
  where cr_returned_date_sk = d_date_sk
    and d_year = [YEAR]
    and cr_returning_addr_sk = ca_address_sk
  group by cr_returning_customer_sk, ca_state)
select c_customer_id, c_salutation, c_first_name, c_last_name,
       ca_street_number, ca_street_name, ca_street_type, ca_suite_number,
       ca_city, ca_county, ca_state, ca_zip, ca_country, ca_gmt_offset,
       ca_location_type, ctr_total_return
from customer_total_return ctr1, customer_address, customer
where ctr1.ctr_total_return > (select avg(ctr_total_return) * 1.2
                               from customer_total_return ctr2
                               where ctr1.ctr_state = ctr2.ctr_state)
  and ca_address_sk = c_current_addr_sk
  and ca_state = '[STATE]'
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id, c_salutation, c_first_name, c_last_name,
         ca_street_number, ca_street_name, ca_street_type, ca_suite_number,
         ca_city, ca_county, ca_state, ca_zip, ca_country, ca_gmt_offset,
         ca_location_type, ctr_total_return
limit 100
