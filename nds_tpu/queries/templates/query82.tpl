--@ SDATE = date(1998-01-01, 2002-10-01)
--@ MANUF = sample(4, 1, 1000)
--@ PRICE = uniform(0, 90)
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, store_sales
where i_current_price between [PRICE] and [PRICE] + 30
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between cast('[SDATE]' as date) and (cast('[SDATE]' as date) + interval 60 days)
  and i_manufact_id in ([MANUF.1], [MANUF.2], [MANUF.3], [MANUF.4])
  and inv_quantity_on_hand between 100 and 500
  and ss_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
