--@ RDATE1 = date(1998-01-01, 2002-10-01)
--@ RDATE2 = date(1998-01-01, 2002-10-01)
--@ RDATE3 = date(1998-01-01, 2002-10-01)
with sr_items as
 (select i_item_id item_id, sum(sr_return_quantity) sr_item_qty
  from store_returns, item, date_dim
  where sr_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq in (select d_week_seq from date_dim
                                        where d_date in (cast('[RDATE1]' as date),
                                                         cast('[RDATE2]' as date),
                                                         cast('[RDATE3]' as date))))
    and sr_returned_date_sk = d_date_sk
  group by i_item_id),
 cr_items as
 (select i_item_id item_id, sum(cr_return_quantity) cr_item_qty
  from catalog_returns, item, date_dim
  where cr_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq in (select d_week_seq from date_dim
                                        where d_date in (cast('[RDATE1]' as date),
                                                         cast('[RDATE2]' as date),
                                                         cast('[RDATE3]' as date))))
    and cr_returned_date_sk = d_date_sk
  group by i_item_id),
 wr_items as
 (select i_item_id item_id, sum(wr_return_quantity) wr_item_qty
  from web_returns, item, date_dim
  where wr_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq in (select d_week_seq from date_dim
                                        where d_date in (cast('[RDATE1]' as date),
                                                         cast('[RDATE2]' as date),
                                                         cast('[RDATE3]' as date))))
    and wr_returned_date_sk = d_date_sk
  group by i_item_id)
select sr_items.item_id,
       sr_item_qty,
       sr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100 sr_dev,
       cr_item_qty,
       cr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100 cr_dev,
       wr_item_qty,
       wr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100 wr_dev,
       (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 average
from sr_items, cr_items, wr_items
where sr_items.item_id = cr_items.item_id
  and sr_items.item_id = wr_items.item_id
order by sr_items.item_id, sr_item_qty
limit 100
