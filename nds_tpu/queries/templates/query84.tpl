--@ CITY = pool(city)
--@ INCOME = uniform(0, 70000)
select c_customer_id as customer_id,
       concat(coalesce(c_last_name, ''), ', ', coalesce(c_first_name, '')) as customername
from customer, customer_address, customer_demographics,
     household_demographics, income_band, store_returns
where ca_city = '[CITY]'
  and c_current_addr_sk = ca_address_sk
  and ib_lower_bound >= [INCOME]
  and ib_upper_bound <= [INCOME] + 50000
  and ib_income_band_sk = hd_income_band_sk
  and cd_demo_sk = c_current_cdemo_sk
  and hd_demo_sk = c_current_hdemo_sk
  and sr_cdemo_sk = cd_demo_sk
order by c_customer_id
limit 100
