--@ YEAR = uniform(1998, 2002)
--@ MS1 = pool(marital)
--@ MS2 = pool(marital)
--@ MS3 = pool(marital)
--@ ES1 = pool(education)
--@ ES2 = pool(education)
--@ ES3 = pool(education)
--@ STATE1 = sample(3, state)
--@ STATE2 = sample(3, state)
--@ STATE3 = sample(3, state)
select substr(r_reason_desc, 1, 20), avg(ws_quantity), avg(wr_refunded_cash),
       avg(wr_fee)
from web_sales, web_returns, web_page, customer_demographics cd1,
     customer_demographics cd2, customer_address, date_dim, reason
where ws_web_page_sk = wp_web_page_sk
  and ws_item_sk = wr_item_sk
  and ws_order_number = wr_order_number
  and ws_sold_date_sk = d_date_sk and d_year = [YEAR]
  and cd1.cd_demo_sk = wr_refunded_cdemo_sk
  and cd2.cd_demo_sk = wr_returning_cdemo_sk
  and ca_address_sk = wr_refunded_addr_sk
  and r_reason_sk = wr_reason_sk
  and ((cd1.cd_marital_status = '[MS1]'
        and cd1.cd_marital_status = cd2.cd_marital_status
        and cd1.cd_education_status = '[ES1]'
        and cd1.cd_education_status = cd2.cd_education_status
        and ws_sales_price between 100.00 and 150.00)
    or (cd1.cd_marital_status = '[MS2]'
        and cd1.cd_marital_status = cd2.cd_marital_status
        and cd1.cd_education_status = '[ES2]'
        and cd1.cd_education_status = cd2.cd_education_status
        and ws_sales_price between 50.00 and 100.00)
    or (cd1.cd_marital_status = '[MS3]'
        and cd1.cd_marital_status = cd2.cd_marital_status
        and cd1.cd_education_status = '[ES3]'
        and cd1.cd_education_status = cd2.cd_education_status
        and ws_sales_price between 150.00 and 200.00))
  and ((ca_country = 'United States'
        and ca_state in ('[STATE1.1]', '[STATE1.2]', '[STATE1.3]')
        and ws_net_profit between 100 and 200)
    or (ca_country = 'United States'
        and ca_state in ('[STATE2.1]', '[STATE2.2]', '[STATE2.3]')
        and ws_net_profit between 150 and 300)
    or (ca_country = 'United States'
        and ca_state in ('[STATE3.1]', '[STATE3.2]', '[STATE3.3]')
        and ws_net_profit between 50 and 250))
group by r_reason_desc
order by substr(r_reason_desc, 1, 20), avg(ws_quantity),
         avg(wr_refunded_cash), avg(wr_fee)
limit 100
