--@ MONTH = uniform(1189, 1199)
select sum(ws_net_paid) as total_sum, i_category, i_class,
       grouping(i_category) + grouping(i_class) as lochierarchy,
       rank() over (partition by grouping(i_category) + grouping(i_class),
                    case when grouping(i_class) = 0 then i_category end
                    order by sum(ws_net_paid) desc) as rank_within_parent
from web_sales, date_dim d1, item
where d1.d_month_seq between [MONTH] and [MONTH] + 11
  and d1.d_date_sk = ws_sold_date_sk
  and i_item_sk = ws_item_sk
group by rollup(i_category, i_class)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent
limit 100
