--@ STORE = pool(city)
--@ DEP = uniform(-1, 4)
select *
from (select count(*) h8_30_to_9
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 8
        and time_dim.t_minute >= 30
        and ((household_demographics.hd_dep_count = [DEP] and household_demographics.hd_vehicle_count <= [DEP] + 2)
          or (household_demographics.hd_dep_count = [DEP] + 1 and household_demographics.hd_vehicle_count <= [DEP] + 3)
          or (household_demographics.hd_dep_count = [DEP] + 2 and household_demographics.hd_vehicle_count <= [DEP] + 4))
        and store.s_store_name = 'ese') s1,
     (select count(*) h9_to_9_30
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 9
        and time_dim.t_minute < 30
        and ((household_demographics.hd_dep_count = [DEP] and household_demographics.hd_vehicle_count <= [DEP] + 2)
          or (household_demographics.hd_dep_count = [DEP] + 1 and household_demographics.hd_vehicle_count <= [DEP] + 3)
          or (household_demographics.hd_dep_count = [DEP] + 2 and household_demographics.hd_vehicle_count <= [DEP] + 4))
        and store.s_store_name = 'ese') s2,
     (select count(*) h9_30_to_10
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 9
        and time_dim.t_minute >= 30
        and ((household_demographics.hd_dep_count = [DEP] and household_demographics.hd_vehicle_count <= [DEP] + 2)
          or (household_demographics.hd_dep_count = [DEP] + 1 and household_demographics.hd_vehicle_count <= [DEP] + 3)
          or (household_demographics.hd_dep_count = [DEP] + 2 and household_demographics.hd_vehicle_count <= [DEP] + 4))
        and store.s_store_name = 'ese') s3,
     (select count(*) h10_to_10_30
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 10
        and time_dim.t_minute < 30
        and ((household_demographics.hd_dep_count = [DEP] and household_demographics.hd_vehicle_count <= [DEP] + 2)
          or (household_demographics.hd_dep_count = [DEP] + 1 and household_demographics.hd_vehicle_count <= [DEP] + 3)
          or (household_demographics.hd_dep_count = [DEP] + 2 and household_demographics.hd_vehicle_count <= [DEP] + 4))
        and store.s_store_name = 'ese') s4,
     (select count(*) h10_30_to_11
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 10
        and time_dim.t_minute >= 30
        and ((household_demographics.hd_dep_count = [DEP] and household_demographics.hd_vehicle_count <= [DEP] + 2)
          or (household_demographics.hd_dep_count = [DEP] + 1 and household_demographics.hd_vehicle_count <= [DEP] + 3)
          or (household_demographics.hd_dep_count = [DEP] + 2 and household_demographics.hd_vehicle_count <= [DEP] + 4))
        and store.s_store_name = 'ese') s5,
     (select count(*) h11_to_11_30
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 11
        and time_dim.t_minute < 30
        and ((household_demographics.hd_dep_count = [DEP] and household_demographics.hd_vehicle_count <= [DEP] + 2)
          or (household_demographics.hd_dep_count = [DEP] + 1 and household_demographics.hd_vehicle_count <= [DEP] + 3)
          or (household_demographics.hd_dep_count = [DEP] + 2 and household_demographics.hd_vehicle_count <= [DEP] + 4))
        and store.s_store_name = 'ese') s6,
     (select count(*) h11_30_to_12
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 11
        and time_dim.t_minute >= 30
        and ((household_demographics.hd_dep_count = [DEP] and household_demographics.hd_vehicle_count <= [DEP] + 2)
          or (household_demographics.hd_dep_count = [DEP] + 1 and household_demographics.hd_vehicle_count <= [DEP] + 3)
          or (household_demographics.hd_dep_count = [DEP] + 2 and household_demographics.hd_vehicle_count <= [DEP] + 4))
        and store.s_store_name = 'ese') s7,
     (select count(*) h12_to_12_30
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 12
        and time_dim.t_minute < 30
        and ((household_demographics.hd_dep_count = [DEP] and household_demographics.hd_vehicle_count <= [DEP] + 2)
          or (household_demographics.hd_dep_count = [DEP] + 1 and household_demographics.hd_vehicle_count <= [DEP] + 3)
          or (household_demographics.hd_dep_count = [DEP] + 2 and household_demographics.hd_vehicle_count <= [DEP] + 4))
        and store.s_store_name = 'ese') s8
