--@ YEAR = uniform(1998, 2002)
--@ CAT1 = sample(3, category)
select *
from (select i_category, i_class, i_brand, s_store_name, s_company_name,
             d_moy,
             sum(ss_sales_price) sum_sales,
             avg(sum(ss_sales_price)) over (partition by i_category, i_brand,
                                            s_store_name, s_company_name) avg_monthly_sales
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk
        and ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and d_year in ([YEAR])
        and ((i_category in ('[CAT1.1]', '[CAT1.2]', '[CAT1.3]')
              and i_class in ('personal', 'portable', 'reference'))
          or (i_category in ('Women', 'Music', 'Men')
              and i_class in ('accessories', 'classical', 'fragrances')))
      group by i_category, i_class, i_brand, s_store_name, s_company_name,
               d_moy) tmp1
where case when (avg_monthly_sales <> 0)
           then (abs(sum_sales - avg_monthly_sales) / avg_monthly_sales)
           else null end > 0.1
order by sum_sales - avg_monthly_sales, s_store_name
limit 100
