--@ RC1 = uniform(1, 100)
--@ RC2 = uniform(1, 100)
--@ RC3 = uniform(1, 100)
--@ RC4 = uniform(1, 100)
--@ RC5 = uniform(1, 100)
select case when (select count(*) from store_sales
                  where ss_quantity between 1 and 20) > [RC1]
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 1 and 20)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 1 and 20) end bucket1,
       case when (select count(*) from store_sales
                  where ss_quantity between 21 and 40) > [RC2]
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 21 and 40)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 21 and 40) end bucket2,
       case when (select count(*) from store_sales
                  where ss_quantity between 41 and 60) > [RC3]
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 41 and 60)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 41 and 60) end bucket3,
       case when (select count(*) from store_sales
                  where ss_quantity between 61 and 80) > [RC4]
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 61 and 80)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 61 and 80) end bucket4,
       case when (select count(*) from store_sales
                  where ss_quantity between 81 and 100) > [RC5]
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 81 and 100)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 81 and 100) end bucket5
from reason
where r_reason_sk = 1
