--@ HOUR1 = uniform(6, 12)
--@ HOUR2 = uniform(14, 20)
--@ DEP = uniform(0, 5)
select cast(amc as decimal(15,4)) / cast(pmc as decimal(15,4)) am_pm_ratio
from (select count(*) amc
      from web_sales, household_demographics, time_dim, web_page
      where ws_sold_time_sk = time_dim.t_time_sk
        and ws_ship_hdemo_sk = household_demographics.hd_demo_sk
        and ws_web_page_sk = web_page.wp_web_page_sk
        and time_dim.t_hour between [HOUR1] and [HOUR1] + 1
        and household_demographics.hd_dep_count = [DEP]
        and web_page.wp_char_count between 5000 and 5200) at,
     (select count(*) pmc
      from web_sales, household_demographics, time_dim, web_page
      where ws_sold_time_sk = time_dim.t_time_sk
        and ws_ship_hdemo_sk = household_demographics.hd_demo_sk
        and ws_web_page_sk = web_page.wp_web_page_sk
        and time_dim.t_hour between [HOUR2] and [HOUR2] + 1
        and household_demographics.hd_dep_count = [DEP]
        and web_page.wp_char_count between 5000 and 5200) pt
order by am_pm_ratio
limit 100
