--@ YEAR = uniform(1998, 2002)
--@ MONTH = uniform(11, 12)
--@ GMT = pick(-5, -6, -7, -8)
--@ BP = pool(buy_potential)
select cc_call_center_id Call_Center, cc_name Call_Center_Name,
       cc_manager Manager, sum(cr_net_loss) Returns_Loss
from call_center, catalog_returns, date_dim, customer, customer_address,
     customer_demographics, household_demographics
where cr_call_center_sk = cc_call_center_sk
  and cr_returned_date_sk = d_date_sk
  and cr_returning_customer_sk = c_customer_sk
  and cd_demo_sk = c_current_cdemo_sk
  and hd_demo_sk = c_current_hdemo_sk
  and ca_address_sk = c_current_addr_sk
  and d_year = [YEAR]
  and d_moy = [MONTH]
  and ((cd_marital_status = 'M' and cd_education_status = 'Unknown')
       or (cd_marital_status = 'W' and cd_education_status = 'Advanced Degree'))
  and hd_buy_potential like '[BP]%'
  and ca_gmt_offset = [GMT]
group by cc_call_center_id, cc_name, cc_manager, cd_marital_status,
         cd_education_status
order by sum(cr_net_loss) desc
