--@ SDATE = date(1998-01-01, 2002-10-01)
--@ MANUFACT = uniform(1, 1000)
select sum(ws_ext_discount_amt) as `Excess Discount Amount`
from web_sales, item, date_dim
where i_manufact_id = [MANUFACT]
  and i_item_sk = ws_item_sk
  and d_date between cast('[SDATE]' as date) and (cast('[SDATE]' as date) + interval 90 days)
  and d_date_sk = ws_sold_date_sk
  and ws_ext_discount_amt > (select 1.3 * avg(ws_ext_discount_amt)
                             from web_sales, date_dim
                             where ws_item_sk = i_item_sk
                               and d_date between cast('[SDATE]' as date)
                                              and (cast('[SDATE]' as date) + interval 90 days)
                               and d_date_sk = ws_sold_date_sk)
order by sum(ws_ext_discount_amt)
limit 100
