--@ REASON = pick('Package was damaged', 'Stopped working', 'Did not get it on time', 'Not the product that was ordred', 'Parts missing')
select ss_customer_sk, sum(act_sales) sumsales
from (select ss_item_sk, ss_ticket_number, ss_customer_sk,
             case when sr_return_quantity is not null
                  then (ss_quantity - sr_return_quantity) * ss_sales_price
                  else (ss_quantity * ss_sales_price) end act_sales
      from store_sales
           left outer join store_returns on (sr_item_sk = ss_item_sk
                                             and sr_ticket_number = ss_ticket_number),
           reason
      where sr_reason_sk = r_reason_sk
        and r_reason_desc = '[REASON]') t
group by ss_customer_sk
order by sumsales, ss_customer_sk
limit 100
