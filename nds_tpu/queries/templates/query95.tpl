--@ SDATE = date(1999-02-01, 2002-02-01)
--@ STATE = pool(state)
with ws_wh as
 (select ws1.ws_order_number, ws1.ws_warehouse_sk wh1, ws2.ws_warehouse_sk wh2
  from web_sales ws1, web_sales ws2
  where ws1.ws_order_number = ws2.ws_order_number
    and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
select count(distinct ws_order_number) as `order count`,
       sum(ws_ext_ship_cost) as `total shipping cost`,
       sum(ws_net_profit) as `total net profit`
from web_sales ws1, date_dim, customer_address, web_site
where d_date between cast('[SDATE]' as date) and (cast('[SDATE]' as date) + interval 60 days)
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_ship_addr_sk = ca_address_sk
  and ca_state = '[STATE]'
  and ws1.ws_web_site_sk = web_site_sk
  and web_company_name = 'pri'
  and ws1.ws_order_number in (select ws_order_number from ws_wh)
  and ws1.ws_order_number in (select wr_order_number
                              from web_returns, ws_wh
                              where wr_order_number = ws_wh.ws_order_number)
order by count(distinct ws_order_number)
limit 100
