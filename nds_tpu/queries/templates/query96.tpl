--@ HOUR = pick(15, 16, 20)
--@ DEP = uniform(0, 5)
select count(*)
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = time_dim.t_time_sk
  and ss_hdemo_sk = household_demographics.hd_demo_sk
  and ss_store_sk = s_store_sk
  and time_dim.t_hour = [HOUR]
  and time_dim.t_minute >= 30
  and household_demographics.hd_dep_count = [DEP]
  and store.s_store_name = 'ese'
order by count(*)
limit 100
