--@ MONTH = uniform(1189, 1199)
with ssci as
 (select ss_customer_sk customer_sk, ss_item_sk item_sk
  from store_sales, date_dim
  where ss_sold_date_sk = d_date_sk
    and d_month_seq between [MONTH] and [MONTH] + 11
  group by ss_customer_sk, ss_item_sk),
 csci as
 (select cs_bill_customer_sk customer_sk, cs_item_sk item_sk
  from catalog_sales, date_dim
  where cs_sold_date_sk = d_date_sk
    and d_month_seq between [MONTH] and [MONTH] + 11
  group by cs_bill_customer_sk, cs_item_sk)
select sum(case when ssci.customer_sk is not null and csci.customer_sk is null
                then 1 else 0 end) store_only,
       sum(case when ssci.customer_sk is null and csci.customer_sk is not null
                then 1 else 0 end) catalog_only,
       sum(case when ssci.customer_sk is not null and csci.customer_sk is not null
                then 1 else 0 end) store_and_catalog
from ssci full outer join csci on (ssci.customer_sk = csci.customer_sk
                                   and ssci.item_sk = csci.item_sk)
limit 100
