--@ SDATE = date(1998-01-01, 2002-10-01)
--@ CAT = sample(3, category)
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) as itemrevenue,
       sum(ss_ext_sales_price) * 100 / sum(sum(ss_ext_sales_price)) over (partition by i_class) as revenueratio
from store_sales, item, date_dim
where ss_item_sk = i_item_sk
  and i_category in ('[CAT.1]', '[CAT.2]', '[CAT.3]')
  and ss_sold_date_sk = d_date_sk
  and d_date between cast('[SDATE]' as date) and (cast('[SDATE]' as date) + interval 30 days)
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
