--@ MONTH = uniform(1189, 1199)
select substr(w_warehouse_name, 1, 20), sm_type, cc_name,
       sum(case when (cs_ship_date_sk - cs_sold_date_sk <= 30) then 1 else 0 end) as `30 days`,
       sum(case when (cs_ship_date_sk - cs_sold_date_sk > 30)
                 and (cs_ship_date_sk - cs_sold_date_sk <= 60) then 1 else 0 end) as `31-60 days`,
       sum(case when (cs_ship_date_sk - cs_sold_date_sk > 60)
                 and (cs_ship_date_sk - cs_sold_date_sk <= 90) then 1 else 0 end) as `61-90 days`,
       sum(case when (cs_ship_date_sk - cs_sold_date_sk > 90)
                 and (cs_ship_date_sk - cs_sold_date_sk <= 120) then 1 else 0 end) as `91-120 days`,
       sum(case when (cs_ship_date_sk - cs_sold_date_sk > 120) then 1 else 0 end) as `>120 days`
from catalog_sales, warehouse, ship_mode, call_center, date_dim
where d_month_seq between [MONTH] and [MONTH] + 11
  and cs_ship_date_sk = d_date_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_ship_mode_sk = sm_ship_mode_sk
  and cs_call_center_sk = cc_call_center_sk
group by substr(w_warehouse_name, 1, 20), sm_type, cc_name
order by substr(w_warehouse_name, 1, 20), sm_type, cc_name
limit 100
