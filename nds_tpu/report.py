# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Per-query benchmark report: JSON summary contract + status taxonomy.

TPU-native equivalent of PysparkBenchReport (ref: nds/PysparkBenchReport.py:
60-127). Captures environment (with secret redaction), engine configuration
and version, wall-clock time in ms, task-failure info from the runtime
listener, and exceptions; statuses are ``Completed`` /
``CompletedWithTaskFailures`` / ``Failed``. Summary filename format
``<prefix>-<query>-<startTime>.json`` is preserved verbatim — the reference
documents it as a downstream (Power-BI) pipeline contract
(ref: nds/PysparkBenchReport.py:118-119).
"""

from __future__ import annotations

import json
import os
import time
import traceback

import nds_tpu
from nds_tpu.listener import FailureListener

_REDACT = ("TOKEN", "SECRET", "PASSWORD")


def _redacted_env() -> dict:
    """Environment capture with credential redaction
    (ref: nds/PysparkBenchReport.py:72-73)."""
    out = {}
    for k, v in os.environ.items():
        if any(s in k.upper() for s in _REDACT):
            out[k] = "*******"
        else:
            out[k] = v
    return out


class BenchReport:
    """Wraps one benchmark unit (a query, a table load, a maintenance
    function) and records everything the JSON summary needs."""

    def __init__(self, session=None):
        self.session = session
        self.summary = {
            "env": {
                "envVars": _redacted_env(),
                "engineConf": dict(getattr(session, "conf", {}) or {}),
                "engineVersion": nds_tpu.__version__,
            },
            "queryStatus": [],
            "exceptions": [],
            "startTime": None,
            "queryTimes": [],
        }

    def report_on(self, fn, *args):
        """Run ``fn(*args)``, timing it and translating outcome into the
        status taxonomy (ref: nds/PysparkBenchReport.py:60-108).

        Returns elapsed wall-clock milliseconds (int).
        """
        self.summary["startTime"] = int(time.time() * 1000)
        listener = FailureListener().register()
        start = time.perf_counter()
        try:
            fn(*args)
            end = time.perf_counter()
            if listener.failures:
                self.summary["queryStatus"].append("CompletedWithTaskFailures")
                self.summary["exceptions"].extend(
                    f"{f.where}: {f.reason}" for f in listener.failures
                )
            else:
                self.summary["queryStatus"].append("Completed")
        except Exception:
            end = time.perf_counter()
            self.summary["queryStatus"].append("Failed")
            self.summary["exceptions"].append(traceback.format_exc())
        finally:
            listener.unregister()
        elapsed_ms = int((end - start) * 1000)
        self.summary["queryTimes"].append(elapsed_ms)
        return elapsed_ms

    def write_summary(self, query_name: str, prefix: str = "") -> None:
        """Write ``<prefix>-<query>-<startTime>.json``; filename format is a
        downstream pipeline contract (ref: nds/PysparkBenchReport.py:110-122)."""
        if not prefix:
            return
        self.summary["query"] = query_name
        filename = f"{prefix}-{query_name}-{self.summary['startTime']}.json"
        self.summary["filename"] = filename
        os.makedirs(os.path.dirname(filename) or ".", exist_ok=True)
        with open(filename, "w") as f:
            json.dump(self.summary, f, indent=2)

    def is_success(self) -> bool:
        """True only if every wrapped unit fully Completed — runs with task
        failures are not a success, matching the reference's exit gate
        (ref: nds/PysparkBenchReport.py:124-127, nds/nds_power.py:310-322)."""
        return all(s == "Completed" for s in self.summary["queryStatus"])
