# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""SQL frontend: lexer, parser, and planner lowering Spark-dialect SQL (the
dialect the query templates generate; ref: nds/tpcds-gen/patches/
templates.patch spark.tpl) onto the columnar engine."""

from nds_tpu.sql.parser import parse  # noqa: F401
