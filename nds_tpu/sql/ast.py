# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""SQL AST node definitions (expressions + relational structure)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

# ---------------------------------------------------------------------------
# expressions
# ---------------------------------------------------------------------------


@dataclass
class Expr:
    pass


@dataclass
class Literal(Expr):
    value: object           # int | float | Decimal | str | bool | None


@dataclass
class DateLiteral(Expr):
    text: str


@dataclass
class IntervalLiteral(Expr):
    amount: int
    unit: str               # 'day' | 'month' | 'year'


@dataclass
class ColumnRef(Expr):
    name: str
    table: Optional[str] = None   # qualifier


@dataclass
class Star(Expr):
    table: Optional[str] = None


@dataclass
class UnaryOp(Expr):
    op: str                 # '-', 'not'
    operand: Expr


@dataclass
class BinaryOp(Expr):
    op: str                 # + - * / % = <> < <= > >= and or ||
    left: Expr
    right: Expr


@dataclass
class Between(Expr):
    expr: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass
class InList(Expr):
    expr: Expr
    items: list
    negated: bool = False


@dataclass
class InSubquery(Expr):
    expr: Expr
    query: "Query"
    negated: bool = False


@dataclass
class Exists(Expr):
    query: "Query"
    negated: bool = False


@dataclass
class ScalarSubquery(Expr):
    query: "Query"


@dataclass
class QuantifiedCompare(Expr):
    """expr op ANY/ALL (subquery)"""
    op: str
    expr: Expr
    query: "Query"
    quantifier: str          # 'any' | 'all'


@dataclass
class Like(Expr):
    expr: Expr
    pattern: str
    negated: bool = False


@dataclass
class IsNull(Expr):
    expr: Expr
    negated: bool = False


@dataclass
class Case(Expr):
    branches: list          # [(cond Expr, result Expr)]
    else_: Optional[Expr]
    operand: Optional[Expr] = None   # CASE operand WHEN v THEN ...


@dataclass
class Cast(Expr):
    expr: Expr
    target: str


@dataclass
class FuncCall(Expr):
    name: str
    args: list
    distinct: bool = False
    star: bool = False               # count(*)


@dataclass
class WindowSpec:
    partition_by: list
    order_by: list                   # [(expr, desc, nulls_last)]
    frame: Optional[str] = None      # '{rows,range}_unbounded_preceding' |
                                     # None (= SQL default: RANGE..CURRENT ROW
                                     # with ORDER BY, full partition without)


@dataclass
class WindowFunc(Expr):
    func: FuncCall
    spec: WindowSpec


# ---------------------------------------------------------------------------
# relational structure
# ---------------------------------------------------------------------------


@dataclass
class SelectItem:
    expr: Expr
    alias: Optional[str] = None


@dataclass
class TableRef:
    name: str
    alias: Optional[str] = None


@dataclass
class SubqueryRef:
    query: "Query"
    alias: str


@dataclass
class Join:
    left: object            # TableRef | SubqueryRef | Join
    right: object
    kind: str               # 'inner' | 'left' | 'right' | 'full' | 'cross'
    condition: Optional[Expr] = None


@dataclass
class GroupingSets:
    kind: str               # 'rollup' | 'cube' | 'sets' | 'plain'
    sets: list              # list of lists of Expr (resolved grouping sets)
    exprs: list             # flat list of all grouping exprs


@dataclass
class Select:
    items: list             # [SelectItem]
    from_: object           # TableRef | SubqueryRef | Join | None
    where: Optional[Expr] = None
    group_by: Optional[GroupingSets] = None
    having: Optional[Expr] = None
    distinct: bool = False


@dataclass
class Query:
    """A full query expression: SELECT core + set ops + order/limit + CTEs."""
    body: object            # Select | SetOp
    order_by: list = field(default_factory=list)   # [(expr, desc, nulls_last)]
    limit: Optional[int] = None
    ctes: list = field(default_factory=list)       # [(name, Query)]


@dataclass
class SetOp:
    op: str                 # 'union' | 'union_all' | 'intersect' | 'except'
    left: object            # Select | SetOp
    right: object


# ---------------------------------------------------------------------------
# DML (Data Maintenance)
# ---------------------------------------------------------------------------


@dataclass
class InsertInto:
    table: str
    query: Query


@dataclass
class DeleteFrom:
    table: str
    where: Optional[Expr]


@dataclass
class CreateTempView:
    name: str
    query: Query
