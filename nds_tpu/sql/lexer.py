# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""SQL tokenizer."""

from __future__ import annotations

from dataclasses import dataclass

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "on", "join", "inner", "left", "right", "full", "outer", "cross",
    "union", "all", "intersect", "except", "distinct", "with", "and", "or",
    "not", "in", "exists", "between", "like", "is", "null", "case", "when",
    "then", "else", "end", "cast", "asc", "desc", "nulls", "first", "last",
    "interval", "day", "days", "month", "months", "year", "years", "over",
    "partition", "rows", "range", "unbounded", "preceding", "following",
    "current", "row", "rollup", "cube", "grouping", "sets", "date", "true",
    "false", "substr", "substring", "any", "some", "top", "insert", "into",
    "delete", "values", "create", "temp", "temporary", "view", "table",
    "semi", "anti",
}

TWO_CHAR = {"<=", ">=", "<>", "!=", "||"}
ONE_CHAR = set("+-*/%(),.=<>;")


@dataclass
class Token:
    kind: str   # 'kw', 'ident', 'number', 'string', 'op', 'eof'
    value: str
    pos: int


class LexError(ValueError):
    pass


def tokenize(sql: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c in " \t\r\n":
            i += 1
            continue
        if c == "-" and i + 1 < n and sql[i + 1] == "-":
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "/" and i + 1 < n and sql[i + 1] == "*":
            j = sql.find("*/", i + 2)
            if j < 0:
                raise LexError(f"unterminated comment at {i}")
            i = j + 2
            continue
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            if j >= n:
                raise LexError(f"unterminated string at {i}")
            toks.append(Token("string", "".join(buf), i))
            i = j + 1
            continue
        if c == '"' or c == "`":
            close = c
            j = sql.find(close, i + 1)
            if j < 0:
                raise LexError(f"unterminated quoted identifier at {i}")
            toks.append(Token("ident", sql[i + 1:j], i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            seen_exp = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j + 1 < n and (
                        sql[j + 1].isdigit() or sql[j + 1] in "+-"):
                    seen_exp = True
                    j += 2 if sql[j + 1] in "+-" else 1
                else:
                    break
            toks.append(Token("number", sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j]
            lw = word.lower()
            toks.append(Token("kw" if lw in KEYWORDS else "ident",
                              lw if lw in KEYWORDS else word, i))
            i = j
            continue
        if sql[i:i + 2] in TWO_CHAR:
            toks.append(Token("op", sql[i:i + 2], i))
            i += 2
            continue
        if c in ONE_CHAR:
            toks.append(Token("op", c, i))
            i += 1
            continue
        raise LexError(f"unexpected character {c!r} at {i}")
    toks.append(Token("eof", "", n))
    return toks
