# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Recursive-descent SQL parser for the Spark dialect the query templates
emit. Covers the constructs the 99 TPC-DS queries and the data-maintenance
functions use: CTEs, joins, grouping sets/rollup, window functions, set
operations, subqueries (scalar/IN/EXISTS/quantified), CASE, CAST, interval
date arithmetic, and the INSERT/DELETE/CREATE TEMP VIEW statements."""

from __future__ import annotations

from decimal import Decimal

from nds_tpu.sql import ast as A
from nds_tpu.sql.lexer import Token, tokenize


class ParseError(ValueError):
    def __init__(self, msg, tok: Token | None = None):
        super().__init__(f"{msg} (at token {tok.value!r} pos {tok.pos})" if tok else msg)


AGG_FUNCS = {"sum", "min", "max", "avg", "count", "stddev_samp", "stddev",
             "var_samp", "variance", "approx_count_distinct"}
WINDOW_ONLY_FUNCS = {"rank", "dense_rank", "row_number", "ntile", "lag", "lead"}


class Parser:
    def __init__(self, sql: str):
        self.toks = tokenize(sql)
        self.i = 0

    # -- token helpers ------------------------------------------------------

    def peek(self, k=0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *words) -> bool:
        t = self.peek()
        return t.kind == "kw" and t.value in words

    def at_op(self, *ops) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value in ops

    def accept_kw(self, *words) -> bool:
        if self.at_kw(*words):
            self.next()
            return True
        return False

    def accept_op(self, *ops) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_kw(self, word):
        if not self.accept_kw(word):
            raise ParseError(f"expected {word.upper()}", self.peek())

    def expect_op(self, op):
        if not self.accept_op(op):
            raise ParseError(f"expected {op!r}", self.peek())

    def ident(self) -> str:
        t = self.peek()
        if t.kind == "ident":
            self.next()
            return t.value
        # some keywords double as identifiers/aliases in the templates
        if t.kind == "kw" and t.value in ("date", "year", "day", "month", "first",
                                          "last", "current", "row", "rows", "range",
                                          "top", "sets", "any", "some", "values"):
            self.next()
            return t.value
        raise ParseError("expected identifier", t)

    # -- statements ---------------------------------------------------------

    def parse_statement(self):
        if self.at_kw("insert"):
            return self.parse_insert()
        if self.at_kw("delete"):
            return self.parse_delete()
        if self.at_kw("create"):
            return self.parse_create_view()
        return self.parse_query()

    def parse_insert(self) -> A.InsertInto:
        self.expect_kw("insert")
        self.expect_kw("into")
        self.accept_kw("table")
        name = self.ident()
        q = self.parse_query()
        return A.InsertInto(name, q)

    def parse_delete(self) -> A.DeleteFrom:
        self.expect_kw("delete")
        self.expect_kw("from")
        name = self.ident()
        where = None
        if self.accept_kw("where"):
            where = self.parse_expr()
        return A.DeleteFrom(name, where)

    def parse_create_view(self) -> A.CreateTempView:
        self.expect_kw("create")
        if not (self.accept_kw("temp") or self.accept_kw("temporary")):
            raise ParseError("only CREATE TEMP VIEW supported", self.peek())
        self.expect_kw("view")
        name = self.ident()
        self.expect_kw("as")
        return A.CreateTempView(name, self.parse_query())

    # -- query expression ---------------------------------------------------

    def parse_query(self) -> A.Query:
        ctes = []
        if self.accept_kw("with"):
            while True:
                name = self.ident()
                self.expect_kw("as")
                self.expect_op("(")
                q = self.parse_query()
                self.expect_op(")")
                ctes.append((name, q))
                if not self.accept_op(","):
                    break
        body = self.parse_set_expr()
        order_by, limit = self.parse_order_limit()
        return A.Query(body, order_by, limit, ctes)

    def parse_order_limit(self):
        order_by = []
        limit = None
        if self.accept_kw("order"):
            self.expect_kw("by")
            while True:
                e = self.parse_expr()
                desc = False
                if self.accept_kw("desc"):
                    desc = True
                else:
                    self.accept_kw("asc")
                nulls_last = desc  # Spark default: asc->nulls first, desc->nulls last
                if self.accept_kw("nulls"):
                    if self.accept_kw("first"):
                        nulls_last = False
                    else:
                        self.expect_kw("last")
                        nulls_last = True
                order_by.append((e, desc, nulls_last))
                if not self.accept_op(","):
                    break
        if self.accept_kw("limit"):
            t = self.next()
            if t.kind != "number":
                raise ParseError("expected number after LIMIT", t)
            limit = int(t.value)
        return order_by, limit

    def parse_set_expr(self):
        left = self.parse_select_core()
        while True:
            if self.accept_kw("union"):
                all_ = self.accept_kw("all")
                right = self.parse_select_core()
                left = A.SetOp("union_all" if all_ else "union", left, right)
            elif self.accept_kw("intersect"):
                right = self.parse_select_core()
                left = A.SetOp("intersect", left, right)
            elif self.accept_kw("except"):
                right = self.parse_select_core()
                left = A.SetOp("except", left, right)
            else:
                return left

    def parse_select_core(self):
        if self.accept_op("("):
            # parenthesized query expression (maybe with its own order/limit)
            q = self.parse_query()
            self.expect_op(")")
            if not q.order_by and q.limit is None and not q.ctes:
                return q.body
            return q
        self.expect_kw("select")
        distinct = False
        if self.accept_kw("distinct"):
            distinct = True
        else:
            self.accept_kw("all")
        items = [self.parse_select_item()]
        while self.accept_op(","):
            items.append(self.parse_select_item())
        from_ = None
        if self.accept_kw("from"):
            from_ = self.parse_table_expr()
        where = self.parse_expr() if self.accept_kw("where") else None
        group_by = None
        if self.accept_kw("group"):
            self.expect_kw("by")
            group_by = self.parse_group_by()
        having = self.parse_expr() if self.accept_kw("having") else None
        return A.Select(items, from_, where, group_by, having, distinct)

    def parse_select_item(self) -> A.SelectItem:
        if self.at_op("*"):
            self.next()
            return A.SelectItem(A.Star())
        # table.* form
        if self.peek().kind == "ident" and self.peek(1).kind == "op" and \
                self.peek(1).value == "." and self.peek(2).kind == "op" and \
                self.peek(2).value == "*":
            t = self.ident()
            self.next()
            self.next()
            return A.SelectItem(A.Star(t))
        e = self.parse_expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "ident":
            alias = self.ident()
        return A.SelectItem(e, alias)

    def parse_group_by(self) -> A.GroupingSets:
        if self.accept_kw("rollup"):
            self.expect_op("(")
            exprs = [self.parse_expr()]
            while self.accept_op(","):
                exprs.append(self.parse_expr())
            self.expect_op(")")
            sets = [exprs[:k] for k in range(len(exprs), -1, -1)]
            return A.GroupingSets("rollup", sets, exprs)
        if self.accept_kw("cube"):
            self.expect_op("(")
            exprs = [self.parse_expr()]
            while self.accept_op(","):
                exprs.append(self.parse_expr())
            self.expect_op(")")
            sets = []
            for mask in range(1 << len(exprs)):
                sets.append([e for i, e in enumerate(exprs) if mask & (1 << i)])
            sets.sort(key=len, reverse=True)
            return A.GroupingSets("cube", sets, exprs)
        if self.accept_kw("grouping"):
            self.expect_kw("sets")
            self.expect_op("(")
            sets = []
            while True:
                self.expect_op("(")
                s = []
                if not self.at_op(")"):
                    s.append(self.parse_expr())
                    while self.accept_op(","):
                        s.append(self.parse_expr())
                self.expect_op(")")
                sets.append(s)
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            flat = []
            seen = set()
            for s in sets:
                for e in s:
                    key = expr_key(e)
                    if key not in seen:
                        seen.add(key)
                        flat.append(e)
            return A.GroupingSets("sets", sets, flat)
        exprs = [self.parse_expr()]
        while self.accept_op(","):
            # trailing rollup inside plain group by: GROUP BY a, rollup(b, c)
            if self.at_kw("rollup"):
                inner = self.parse_group_by()
                sets = [exprs + s for s in inner.sets]
                return A.GroupingSets("rollup", sets, exprs + inner.exprs)
            exprs.append(self.parse_expr())
        return A.GroupingSets("plain", [exprs], exprs)

    # -- FROM clause --------------------------------------------------------

    def parse_table_expr(self):
        left = self.parse_table_primary()
        while True:
            if self.accept_op(","):
                right = self.parse_table_primary()
                left = A.Join(left, right, "cross")
            elif self.at_kw("join", "inner", "left", "right", "full", "cross"):
                kind = "inner"
                if self.accept_kw("inner"):
                    kind = "inner"
                elif self.accept_kw("left"):
                    self.accept_kw("outer")
                    kind = "left"
                    if self.accept_kw("semi"):
                        kind = "semi"
                    elif self.accept_kw("anti"):
                        kind = "anti"
                elif self.accept_kw("right"):
                    self.accept_kw("outer")
                    kind = "right"
                elif self.accept_kw("full"):
                    self.accept_kw("outer")
                    kind = "full"
                elif self.accept_kw("cross"):
                    kind = "cross"
                self.expect_kw("join")
                right = self.parse_table_primary()
                cond = None
                if kind != "cross" and self.accept_kw("on"):
                    cond = self.parse_expr()
                left = A.Join(left, right, kind, cond)
            else:
                return left

    def parse_table_primary(self):
        if self.accept_op("("):
            if self.at_kw("select", "with") or self.at_op("("):
                q = self.parse_query()
                self.expect_op(")")
                alias = None
                self.accept_kw("as")
                if self.peek().kind == "ident":
                    alias = self.ident()
                if alias is None:
                    alias = f"_subq{id(q) % 10000}"
                return A.SubqueryRef(q, alias)
            t = self.parse_table_expr()
            self.expect_op(")")
            return t
        name = self.ident()
        alias = None
        if self.accept_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "ident":
            alias = self.ident()
        return A.TableRef(name, alias)

    # -- expressions --------------------------------------------------------

    def parse_expr(self):
        return self.parse_or()

    def parse_or(self):
        left = self.parse_and()
        while self.accept_kw("or"):
            left = A.BinaryOp("or", left, self.parse_and())
        return left

    def parse_and(self):
        left = self.parse_not()
        while self.accept_kw("and"):
            left = A.BinaryOp("and", left, self.parse_not())
        return left

    def parse_not(self):
        if self.accept_kw("not"):
            return A.UnaryOp("not", self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self):
        if self.at_kw("exists"):
            self.next()
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            return A.Exists(q)
        left = self.parse_additive()
        while True:
            negated = False
            if self.at_kw("not") and self.peek(1).kind == "kw" and \
                    self.peek(1).value in ("in", "between", "like"):
                self.next()
                negated = True
            if self.accept_kw("between"):
                low = self.parse_additive()
                self.expect_kw("and")
                high = self.parse_additive()
                left = A.Between(left, low, high, negated)
                continue
            if self.accept_kw("in"):
                self.expect_op("(")
                if self.at_kw("select", "with"):
                    q = self.parse_query()
                    self.expect_op(")")
                    left = A.InSubquery(left, q, negated)
                else:
                    items = [self.parse_expr()]
                    while self.accept_op(","):
                        items.append(self.parse_expr())
                    self.expect_op(")")
                    left = A.InList(left, items, negated)
                continue
            if self.accept_kw("like"):
                t = self.next()
                if t.kind != "string":
                    raise ParseError("expected string pattern after LIKE", t)
                left = A.Like(left, t.value, negated)
                continue
            if self.accept_kw("is"):
                neg = self.accept_kw("not")
                self.expect_kw("null")
                left = A.IsNull(left, neg)
                continue
            if self.peek().kind == "op" and self.peek().value in (
                    "=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.next().value
                if op == "!=":
                    op = "<>"
                # quantified comparison: expr op ANY/ALL/SOME (subquery)
                if self.at_kw("any", "some", "all") and self.peek(1).kind == "op" \
                        and self.peek(1).value == "(":
                    quant = self.next().value
                    quant = "any" if quant == "some" else quant
                    self.expect_op("(")
                    q = self.parse_query()
                    self.expect_op(")")
                    left = A.QuantifiedCompare(op, left, q, quant)
                    continue
                right = self.parse_additive()
                left = A.BinaryOp(op, left, right)
                continue
            return left

    def parse_additive(self):
        left = self.parse_multiplicative()
        while True:
            if self.at_op("+", "-"):
                op = self.next().value
                left = A.BinaryOp(op, left, self.parse_multiplicative())
            elif self.at_op("||"):
                self.next()
                left = A.BinaryOp("||", left, self.parse_multiplicative())
            else:
                return left

    def parse_multiplicative(self):
        left = self.parse_unary()
        while self.at_op("*", "/", "%"):
            op = self.next().value
            left = A.BinaryOp(op, left, self.parse_unary())
        return left

    def parse_unary(self):
        if self.accept_op("-"):
            return A.UnaryOp("-", self.parse_unary())
        if self.accept_op("+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self):
        t = self.peek()
        if t.kind == "number":
            self.next()
            if "." in t.value or "e" in t.value.lower():
                if "e" in t.value.lower():
                    return A.Literal(float(t.value))
                return A.Literal(Decimal(t.value))
            return A.Literal(int(t.value))
        if t.kind == "string":
            self.next()
            return A.Literal(t.value)
        if t.kind == "op" and t.value == "(":
            self.next()
            if self.at_kw("select", "with"):
                q = self.parse_query()
                self.expect_op(")")
                return A.ScalarSubquery(q)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "kw":
            if t.value == "null":
                self.next()
                return A.Literal(None)
            if t.value in ("true", "false"):
                self.next()
                return A.Literal(t.value == "true")
            if t.value == "case":
                return self.parse_case()
            if t.value == "cast":
                self.next()
                self.expect_op("(")
                e = self.parse_expr()
                self.expect_kw("as")
                target = self.parse_type_name()
                self.expect_op(")")
                return A.Cast(e, target)
            if t.value == "date" and self.peek(1).kind == "string":
                self.next()
                lit = self.next()
                return A.DateLiteral(lit.value)
            if t.value == "interval":
                self.next()
                amt_tok = self.next()
                neg = False
                if amt_tok.kind == "op" and amt_tok.value == "-":
                    neg = True
                    amt_tok = self.next()
                if amt_tok.kind == "string":
                    amt = int(amt_tok.value)
                elif amt_tok.kind == "number":
                    amt = int(amt_tok.value)
                else:
                    raise ParseError("expected interval amount", amt_tok)
                unit_tok = self.next()
                unit = unit_tok.value.rstrip("s")
                if unit not in ("day", "month", "year"):
                    raise ParseError(f"unsupported interval unit {unit}", unit_tok)
                return A.IntervalLiteral(-amt if neg else amt, unit)
            if t.value in ("substr", "substring"):
                return self.parse_function(self.next().value)
            if t.value == "grouping":
                return self.parse_function(self.next().value)
            if t.value == "current":
                # current_date etc. not needed by the corpus; fall through
                pass
        if t.kind == "ident" or (t.kind == "kw" and t.value in ("date", "year",
                                                                "day", "month",
                                                                "first", "last")):
            # function call or column ref
            if self.peek(1).kind == "op" and self.peek(1).value == "(":
                name = self.next().value
                return self.parse_function(name)
            name = self.ident()
            if self.accept_op("."):
                col = self.ident()
                return A.ColumnRef(col, name)
            return A.ColumnRef(name)
        raise ParseError("unexpected token in expression", t)

    def parse_case(self) -> A.Case:
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.parse_expr()
        branches = []
        while self.accept_kw("when"):
            cond = self.parse_expr()
            self.expect_kw("then")
            res = self.parse_expr()
            branches.append((cond, res))
        else_ = None
        if self.accept_kw("else"):
            else_ = self.parse_expr()
        self.expect_kw("end")
        return A.Case(branches, else_, operand)

    def parse_type_name(self) -> str:
        t = self.next()
        name = t.value.lower()
        if name == "double" and self.peek().kind == "ident" and \
                self.peek().value.lower() == "precision":
            self.next()
            name = "double"
        if self.accept_op("("):
            args = [self.next().value]
            while self.accept_op(","):
                args.append(self.next().value)
            self.expect_op(")")
            name = f"{name}({','.join(args)})"
        return name

    def parse_function(self, name: str):
        name = name.lower()
        self.expect_op("(")
        distinct = False
        star = False
        args = []
        if self.at_op("*"):
            self.next()
            star = True
        elif not self.at_op(")"):
            if self.accept_kw("distinct"):
                distinct = True
            args.append(self.parse_expr())
            while self.accept_op(","):
                args.append(self.parse_expr())
        self.expect_op(")")
        fc = A.FuncCall(name, args, distinct, star)
        if self.accept_kw("over"):
            self.expect_op("(")
            partition = []
            order = []
            frame = None
            if self.accept_kw("partition"):
                self.expect_kw("by")
                partition.append(self.parse_expr())
                while self.accept_op(","):
                    partition.append(self.parse_expr())
            if self.accept_kw("order"):
                self.expect_kw("by")
                while True:
                    e = self.parse_expr()
                    desc = False
                    if self.accept_kw("desc"):
                        desc = True
                    else:
                        self.accept_kw("asc")
                    nulls_last = desc
                    if self.accept_kw("nulls"):
                        if self.accept_kw("first"):
                            nulls_last = False
                        else:
                            self.expect_kw("last")
                            nulls_last = True
                    order.append((e, desc, nulls_last))
                    if not self.accept_op(","):
                        break
            frame_kw = None
            if self.accept_kw("rows"):
                frame_kw = "rows"
            elif self.accept_kw("range"):
                frame_kw = "range"
            if frame_kw:
                # the corpus uses [ROWS|RANGE] BETWEEN UNBOUNDED PRECEDING
                # AND CURRENT ROW (ROWS and RANGE differ on order-key ties)
                if self.accept_kw("between"):
                    self.expect_kw("unbounded")
                    self.expect_kw("preceding")
                    self.expect_kw("and")
                    self.expect_kw("current")
                    self.expect_kw("row")
                else:
                    self.expect_kw("unbounded")
                    self.expect_kw("preceding")
                frame = f"{frame_kw}_unbounded_preceding"
            self.expect_op(")")
            return A.WindowFunc(fc, A.WindowSpec(partition, order, frame))
        return fc


def expr_key(e) -> str:
    """Canonical textual key for expression identity (GROUP BY matching)."""
    if isinstance(e, A.ColumnRef):
        return f"col:{e.table or ''}.{e.name}".lower()
    if isinstance(e, A.Literal):
        return f"lit:{e.value!r}"
    if isinstance(e, A.BinaryOp):
        return f"({expr_key(e.left)}{e.op}{expr_key(e.right)})"
    if isinstance(e, A.UnaryOp):
        return f"({e.op}{expr_key(e.operand)})"
    if isinstance(e, A.FuncCall):
        inner = ",".join(expr_key(a) for a in e.args)
        return f"fn:{e.name}({'distinct ' if e.distinct else ''}{'*' if e.star else inner})"
    if isinstance(e, A.Cast):
        return f"cast({expr_key(e.expr)} as {e.target})"
    if isinstance(e, A.Case):
        b = ";".join(f"{expr_key(c)}:{expr_key(r)}" for c, r in e.branches)
        el = expr_key(e.else_) if e.else_ else ""
        op = expr_key(e.operand) if e.operand else ""
        return f"case({op}|{b}|{el})"
    if isinstance(e, A.Between):
        return f"between({expr_key(e.expr)},{expr_key(e.low)},{expr_key(e.high)},{e.negated})"
    if isinstance(e, A.InList):
        return f"in({expr_key(e.expr)},{[expr_key(i) for i in e.items]},{e.negated})"
    if isinstance(e, A.Like):
        return f"like({expr_key(e.expr)},{e.pattern},{e.negated})"
    if isinstance(e, A.IsNull):
        return f"isnull({expr_key(e.expr)},{e.negated})"
    if isinstance(e, A.DateLiteral):
        return f"date:{e.text}"
    if isinstance(e, A.IntervalLiteral):
        return f"interval:{e.amount}{e.unit}"
    if isinstance(e, A.WindowFunc):
        part = ",".join(expr_key(p) for p in e.spec.partition_by)
        order = ",".join(f"{expr_key(oe)}:{d}:{nl}" for oe, d, nl in e.spec.order_by)
        return f"win:{expr_key(e.func)}|p={part}|o={order}|f={e.spec.frame}"
    if isinstance(e, (A.ScalarSubquery, A.InSubquery, A.Exists,
                      A.QuantifiedCompare, A.Query, A.Select)) or \
            hasattr(e, "__dataclass_fields__"):
        # subquery/statement nodes key by STRUCTURE, not object identity:
        # the streamed-residual machinery (engine/stream.py) keys
        # pre-planned subquery results — and the pipeline cache keys
        # conjuncts — on expr_key, so two parses of the same text agree
        fields = ",".join(f"{k}={_node_key(v)}" for k, v in vars(e).items())
        return f"{type(e).__name__.lower()}({fields})"
    return f"obj:{id(e)}"


def _node_key(x) -> str:
    """Deterministic structural key of an arbitrary AST node (dataclass
    fields walked recursively; expressions delegate to :func:`expr_key`)."""
    if isinstance(x, A.Expr):
        return expr_key(x)
    if isinstance(x, (list, tuple)):
        return "[" + ",".join(_node_key(i) for i in x) + "]"
    if hasattr(x, "__dataclass_fields__"):
        fields = ",".join(f"{k}={_node_key(v)}" for k, v in vars(x).items())
        return f"{type(x).__name__}({fields})"
    return repr(x)


def parse(sql: str):
    """Parse one SQL statement."""
    p = Parser(sql)
    stmt = p.parse_statement()
    p.accept_op(";")
    if p.peek().kind != "eof":
        raise ParseError("trailing input", p.peek())
    return stmt
