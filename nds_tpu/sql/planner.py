# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Planner/executor: lowers parsed SQL onto the columnar engine.

Table-at-a-time interpretation with the optimizations that matter for the
TPC-DS shape: single-table predicate pushdown before joins, equi-join graph
extraction from WHERE conjuncts (comma joins never cartesian unless truly
unconnected), sort-based grouping, decorrelation of equality-correlated
EXISTS/IN/scalar subqueries into (semi/left) joins, grouping-set expansion,
and shared window-sort contexts.

Columns are internally named ``alias.column``; unqualified references resolve
by unique suffix match, mirroring SQL scoping.
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from nds_tpu.engine import exprs as X
from nds_tpu.engine import ops as E
from nds_tpu.engine.column import Column
from nds_tpu.engine.table import DeviceTable
from nds_tpu.engine.window import WindowContext
from nds_tpu.obs import trace as _obs
from nds_tpu.sql import ast as A
from nds_tpu.sql.parser import expr_key


class ExecError(ValueError):
    pass


# Defer pushed-down filters into the join hash (no compaction sync) up to
# this physical size; above it, compaction pays for itself by shrinking the
# join's sort/probe width. Read at USE time (not import) so tests and
# Throughput children that set the knob after import are honored; its
# effect needs no cache-key member — the routing's RESULT (part physical
# lengths) is already a pipeline/fusion key component.
def _defer_filter_max_rows() -> int:
    return int(os.environ.get("NDS_TPU_DEFER_FILTER_MAX_ROWS", 1 << 21))


# fused predicate programs: (conjunct expr keys, table signature) ->
# (dictionary identity refs, jitted callable | None-for-fallback)
_MASK_FUSE_CACHE: dict = {}
_MASK_FUSE_MAX = 4096
# projection/aggregate-argument twin of the mask-fusion cache:
# key -> (input dict identities, jitted fn | None, output (kind, dict) meta)
_EXPR_FUSE_CACHE: dict = {}
# ONE dedicated lock for both fusion caches (they share _fused_run, whose
# in-flight build registry below spans them): mutations and the
# singleflight claim/landing take the lock; the jitted trace attempt runs
# OFF-lock — a compile under the lock would serialize every concurrent
# Throughput stream (the conc-audit `compile-under-lock` rule).
_FUSE_LOCK = threading.Lock()
# singleflight registry: (cache id, key) -> threading.Event of the thread
# currently tracing that fused program. Waiters block off-lock, then take
# the winner's cache entry — exactly ONE compile per shape, checked by
# tools/conc_audit_diff.py and tests/test_concurrency.py.
_FUSE_BUILDS: dict = {}
# per-(cache id, key) count of jit trace attempts, for the lockstep
# harness's exactly-one-compile assertion; guarded by _FUSE_LOCK.
_FUSE_BUILD_COUNTS: dict = {}


def fuse_build_count() -> int:
    """Total fused-program trace attempts since process start (or the
    last :func:`reset_fuse_caches`) — test/harness observability."""
    with _FUSE_LOCK:
        return sum(_FUSE_BUILD_COUNTS.values())


def fuse_build_counts() -> dict:
    """Per-shape fused-program trace-attempt counts (snapshot): the
    evidence the exactly-one-compile checks read."""
    with _FUSE_LOCK:
        return dict(_FUSE_BUILD_COUNTS)


def reset_fuse_caches() -> None:
    """Drop both fusion caches and the build counters (test/harness
    helper: a cold-cache differential needs a known-empty start)."""
    with _FUSE_LOCK:
        _MASK_FUSE_CACHE.clear()
        _EXPR_FUSE_CACHE.clear()
        _FUSE_BUILD_COUNTS.clear()


def _fuse_claim(bkey):
    """Block until this thread owns the in-flight build claim for
    ``bkey`` (waiting, off-lock, for any other builder to land first) —
    the rebuild path's entry into the singleflight, so a cache entry
    that cannot serve one caller's dictionary identities never triggers
    concurrent duplicate traces."""
    while True:
        with _FUSE_LOCK:
            pending = _FUSE_BUILDS.get(bkey)
            if pending is None:
                claim = _FUSE_BUILDS[bkey] = threading.Event()
                return claim
        pending.wait(timeout=60.0)


@dataclass
class EvalCtx:
    """Expression evaluation context."""
    table: DeviceTable
    agg_values: dict = field(default_factory=dict)      # expr_key -> Column
    group_values: dict = field(default_factory=dict)    # expr_key -> Column
    grouping_flags: dict = field(default_factory=dict)  # expr_key -> 0/1 (per set)
    select_aliases: dict = field(default_factory=dict)  # alias -> Column
    window_values: dict = field(default_factory=dict)   # expr_key -> Column
    post_agg: bool = False


class _StreamedScan:
    """A >HBM base-table scan inside a join graph: the host-resident
    ChunkedTable plus its FROM alias. :func:`Planner._stream_join_parts`
    binds its device chunks one at a time."""

    def __init__(self, chunked, alias: str):
        self.chunked = chunked
        self.alias = alias

    @property
    def nbytes(self) -> int:
        return self.chunked.nbytes

    @property
    def column_names(self):
        return [f"{self.alias.lower()}.{n.split('.')[-1].lower()}"
                for n in self.chunked.column_names]

    def device_chunks(self, planner):
        for chunk in self.chunked.device_chunks():
            yield planner._alias_table(chunk, self.alias)

    def bind_whole(self, planner):
        return planner._alias_table(self.chunked.materialize(), self.alias)


class _OuterProbe:
    """A deferred LEFT join whose PRESERVED side holds the >HBM chunked
    scan (q40/q78/q80/q93: ``fact left join returns on returns-PK``).
    The join rides INTO the streamed graph: every chunk applies the
    sync-free PK gather against the whole probe table inside the compiled
    per-chunk program (``Planner._apply_outer``), so nothing materializes
    whole and the per-chunk unmatched rows — which distribute over the
    preserved side's chunks — null-extend in place."""

    def __init__(self, table: DeviceTable, condition, conjuncts, src):
        self.table = table          # alias-qualified device table
        self.condition = condition  # the original ON expression (AST)
        self.conjuncts = list(conjuncts)
        self.src = src              # pristine catalog name (PK provenance)

    @property
    def column_names(self):
        return self.table.column_names


class _OuterBuild:
    """A deferred LEFT join whose NULL-INTRODUCING side holds the chunked
    scan (q5: ``returns left join sales on sales-PK``). Each chunk emits
    its matched pairs through an inner bound-bucket join and registers the
    matched-build-row mask (``ops.stream_outer_matched``); the pipeline
    ORs the masks into an on-device unmatched-key accumulator and the
    outer extras — build rows no chunk matched — are emitted ONCE at
    materialize time, null-extended to the joined schema."""

    def __init__(self, table: DeviceTable, condition, conjuncts, src):
        self.table = table
        self.condition = condition
        self.conjuncts = list(conjuncts)
        self.src = src

    @property
    def column_names(self):
        return self.table.column_names


def outer_extras_table(build: DeviceTable, idx, n_extras,
                       template: DeviceTable) -> DeviceTable:
    """The outer-extras rows of a deferred outer-build join: unmatched
    build rows gathered by ``idx``, null-extended to the joined output
    schema of ``template`` (columns the build side does not provide come
    back NULL, exactly like the extras arm of a materialized left join)."""
    cols = {}
    cap = int(idx.shape[0])
    for n in template.column_names:
        t = template[n]
        if n in build.columns:
            cols[n] = build[n].take(idx)
        else:
            data = jnp.zeros((cap,) + t.data.shape[1:], dtype=t.data.dtype)
            cols[n] = Column(t.kind, data, jnp.zeros(cap, dtype=bool),
                             t.dict_values, t.enc)
    return DeviceTable(cols, n_extras, plen=cap)


def _table_bytes(t) -> int:
    """Resident byte size of a catalog table (device columns or a
    host-resident ChunkedTable) — the scanBytes term of the per-query
    roofline accounting."""
    if hasattr(t, "nbytes"):               # ChunkedTable
        return int(t.nbytes)
    return sum(c.data.nbytes + (0 if c.valid is None else c.valid.nbytes)
               for c in t.columns.values())


class Planner:
    def __init__(self, catalog: dict, base_tables: set | None = None):
        self.catalog = catalog          # name -> (DeviceTable with plain col names)
        # names the session loaded as pristine base-table scans; only these
        # carry schema guarantees (PK uniqueness for gather joins)
        self.base_tables = base_tables if base_tables is not None else set()
        self.cte_stack: list[dict] = []
        self._synth_keys = 0             # synthetic join-key name counter
        # bare column names the current statement references anywhere
        # (projection pushdown); None = pruning disabled (SELECT * present
        # or not yet computed)
        self._needed_names: set | None = None
        # roofline accounting: catalog tables this statement actually bound,
        # with their resident byte sizes (per-query scanBytes in summaries)
        self.scanned: dict[str, int] = {}
        # multi-pass streaming: per-statement registry of pre-planned
        # subquery residuals (device-resident inner results keyed by the
        # subquery's structural expr_key). Populated by the streamed
        # pipeline's record phase — and by the first eager chunk — so the
        # per-chunk program consumes each residual as an ordinary device
        # operand instead of re-planning the subquery per chunk.
        self._subquery_residuals: dict = {}
        # while a pipeline records, the residual keys the record phase
        # touched (registry hits included) — the pipeline's operand list
        self._residuals_touched: list | None = None

    # ------------------------------------------------------------------ query

    def _collect_needed_names(self, node) -> set | None:
        """Bare (unqualified, lowercased) column names referenced anywhere in
        the statement, or None when pruning is unsafe. Over-approximates
        across subqueries — pruning only ever drops columns NO expression in
        the whole statement mentions, and a miss fails loudly at name
        resolution, never silently.

        SELECT * is resolved SCOPED instead of disabling pruning globally
        (q21-class queries wrap a narrow aggregate in ``select * from (...)``
        — without scoping, every base scan under the subquery drags all of
        its columns through the join). A star over a derived table needs
        nothing (the inner projection is explicit and its refs are walked);
        a star over a catalog table adds that table's full column set; only
        a star over an unresolvable name disables pruning."""
        names: set = set()
        star = False
        # names that resolve to derived tables (CTEs) anywhere in the
        # statement, with their projected OUTPUT names (None when not
        # statically derivable): a star over a CTE needs the CTE's output
        # columns even though nothing references them (q47-class
        # ``select * from v2`` where v2 projects aliased columns)
        cte_outputs: dict = {}

        def output_names(body):
            if isinstance(body, A.Select):
                return self._projected_names(body.items)
            left = getattr(body, "left", None)
            return output_names(left) if left is not None else None

        def collect_ctes(x):
            if isinstance(x, A.Query):
                for cname, cq in x.ctes:
                    cte_outputs[cname.lower()] = output_names(cq.body)
            if hasattr(x, "__dataclass_fields__"):
                for f in vars(x).values():
                    collect_any(f, collect_ctes)

        def collect_any(f, fn):
            if isinstance(f, (list, tuple)):
                for y in f:
                    collect_any(y, fn)
            elif hasattr(f, "__dataclass_fields__"):
                fn(f)
        collect_ctes(node)

        def from_leaves(f, out):
            if f is None:
                return
            if isinstance(f, A.TableRef):
                out.append(f)
            elif isinstance(f, A.Join):
                from_leaves(f.left, out)
                from_leaves(f.right, out)
            # SubqueryRef leaves contribute nothing: their projections are
            # explicit and walked on their own

        def resolve_star(sel: A.Select, qualifier):
            """Add the base columns a star could expand to; returns False
            when any leaf is unresolvable (disable pruning)."""
            leaves: list = []
            from_leaves(sel.from_, leaves)
            for leaf in leaves:
                alias = (leaf.alias or leaf.name).lower()
                if qualifier and qualifier.lower() != alias:
                    continue
                name_l = leaf.name.lower()
                t = self.catalog.get(name_l) or self.catalog.get(leaf.name)
                if t is not None:
                    names.update(n.split(".")[-1].lower()
                                 for n in t.column_names)
                elif name_l in cte_outputs:
                    outs = cte_outputs[name_l]
                    if outs is None:
                        return False          # CTE outputs not derivable
                    names.update(outs)
                else:
                    return False              # unknown leaf: stay safe
            return True

        def walk(x, sel=None):
            nonlocal star
            if star or x is None:
                return
            if isinstance(x, A.Star):
                if sel is None or not resolve_star(sel, x.table):
                    star = True
                return
            if isinstance(x, A.ColumnRef):
                names.add(x.name.lower())
            here = x if isinstance(x, A.Select) else sel
            if hasattr(x, "__dataclass_fields__"):
                for f in vars(x).values():
                    walk_any(f, here)

        def walk_any(f, sel):
            if isinstance(f, (list, tuple)):
                for y in f:
                    walk_any(y, sel)
            elif hasattr(f, "__dataclass_fields__"):
                walk(f, sel)
        walk(node)
        return None if star else names

    def query(self, q: A.Query) -> DeviceTable:
        """Execute a full query; returns a DeviceTable whose column names are
        the output names in order."""
        top_level = self._needed_names is None and not self.cte_stack
        if top_level:
            self._needed_names = self._collect_needed_names(q)
        scope = {}
        self.cte_stack.append(scope)
        # the statement-level plan/execute span (this engine plans as it
        # executes): one per top-level statement, CTE recursion rides
        # inside it. A no-op under replay re-tracing (obs guard).
        plan_span = _obs.span("plan") if top_level else _obs.NULL_SPAN
        try:
            with plan_span:
                for name, cq in q.ctes:
                    scope[name.lower()] = self.query(cq)
                out = self.set_expr(q.body)
                if q.order_by:
                    out = self._apply_order_by(out, q.order_by, q.body)
                if q.limit is not None:
                    out = E.limit_table(out, q.limit)
                return out
        finally:
            self.cte_stack.pop()
            # a reused Planner must not prune the next statement's scans
            # with this statement's column set
            if top_level:
                self._needed_names = None

    def _apply_order_by(self, out: DeviceTable, order_by,
                        body=None) -> DeviceTable:
        names = out.column_names
        keys, desc, nl = [], [], []
        ctx = EvalCtx(out)
        # output aliases are directly addressable in ORDER BY
        for n in names:
            ctx.select_aliases[n.lower()] = out[n]
        # ORDER BY may repeat a select-item expression verbatim (e.g.
        # ``order by count(distinct x)``); resolve those positionally instead
        # of re-evaluating an aggregate over the output
        item_keys = {}
        if body is not None and isinstance(body, A.Select) and \
                not any(isinstance(it.expr, A.Star) for it in body.items):
            # (a Star item expands to several output columns, breaking the
            # positional item -> output-name correspondence)
            for i, it in enumerate(body.items):
                if i < len(names):
                    item_keys.setdefault(expr_key(it.expr), names[i])
        for e, d, last in order_by:
            if isinstance(e, A.Literal) and isinstance(e.value, int):
                col = out[names[e.value - 1]]
            elif expr_key(e) in item_keys:
                col = out[item_keys[expr_key(e)]]
            else:
                col = self.eval_expr(e, ctx)
            keys.append(col)
            desc.append(d)
            nl.append(last)
        order = E.lexsort_indices(keys, desc, nl, n_valid=out.nrows)
        return out.take(order, nrows=out.nrows)

    def set_expr(self, body) -> DeviceTable:
        if isinstance(body, A.Query):
            return self.query(body)
        if isinstance(body, A.Select):
            return self.select(body)
        if isinstance(body, A.SetOp):
            left = self.set_expr(body.left)
            right = self.set_expr(body.right)
            if len(left.column_names) != len(right.column_names):
                raise ExecError("set operands have different arity")
            # align by position onto left's names
            right = DeviceTable(
                {ln: right[rn] for ln, rn in zip(left.column_names, right.column_names)},
                right.nrows)
            # unify each positional pair onto one physical kind (a dec(7,2)
            # column and a literal 0 have different representations; blind
            # concatenation would corrupt values)
            lu, ru = {}, {}
            for name in left.column_names:
                (lc, rc), _ = X.unify_columns([left[name], right[name]])
                lu[name], ru[name] = lc, rc
            left = DeviceTable(lu, left.nrows)
            right = DeviceTable(ru, right.nrows)
            if body.op == "union_all":
                return E.concat_tables([left, right])
            if body.op == "union":
                return self._distinct(E.concat_tables([left, right]))
            # intersect / except: null-safe membership of distinct left rows
            ldist = self._distinct(left)
            lkeys = [ldist[n] for n in ldist.column_names]
            rkeys = [right[n] for n in ldist.column_names]
            mask = E.semi_join_mask(lkeys, rkeys, negate=(body.op == "except"),
                                    null_safe=True, n_left=ldist.nrows,
                                    n_right=right.nrows)
            return E.compact_table(ldist, mask)
        raise ExecError(f"unsupported set expression {type(body).__name__}")

    def _distinct(self, t: DeviceTable) -> DeviceTable:
        if E.count_bound(t.nrows) == 0:
            return t
        gids, ng, rep, cap = E.group_ids([t[n] for n in t.column_names],
                                         n_valid=t.nrows)
        return t.take(rep, nrows=ng)

    # ------------------------------------------------------------------ FROM

    def _lookup_table(self, name: str) -> DeviceTable:
        for scope in reversed(self.cte_stack):
            if name.lower() in scope:
                return scope[name.lower()]
        key = name.lower() if name.lower() in self.catalog else name
        if key in self.catalog:
            t = self.catalog[key]
            if key not in self.scanned:
                self.scanned[key] = _table_bytes(t)
            return t
        raise ExecError(f"unknown table {name!r}")

    def _alias_table(self, t: DeviceTable, alias: str) -> DeviceTable:
        cols = {}
        for n, c in t.columns.items():
            base = n.split(".")[-1]
            cols[f"{alias.lower()}.{base.lower()}"] = c
        return DeviceTable(cols, t.nrows)

    def plan_from(self, from_) -> DeviceTable:
        """Returns a DeviceTable with alias-qualified columns. Comma-joined
        table lists are returned un-joined as a list for the join-graph
        optimizer in select()."""
        if from_ is None:
            # SELECT without FROM: single virtual row
            return DeviceTable({}, 1, plen=E.bucket_len(1))
        parts, join_preds, sources = self._flatten_from(from_)
        return self._join_parts(parts, join_preds, [], sources)

    def _flatten_from(self, from_, where=None, top=True):
        """Flatten a FROM tree into (leaf tables, explicit-join predicates,
        per-leaf catalog source names). Cross/comma joins AND structured
        INNER joins flatten into the list — an inner ON predicate is
        semantically a WHERE conjunct, and flattening lets the join-graph
        orderer see every equi edge at once (q72's item-only explosion
        disappears once the week_seq WHERE edge joins the same slot pair).
        Outer joins keep their structure, but WHERE conjuncts owned entirely
        by the null-preserving side are consumed from ``where`` (a mutable
        list) and pushed below the join. ``sources[i]`` names the catalog
        table a leaf scans (None for subqueries/materialized joins) — the
        provenance the PK gather-join optimization keys on. ``top`` is
        True only for the SELECT's whole FROM node: the outer-BUILD
        deferral (mechanism b2) is sound only there — a parent join
        around it would filter/extend rows the materialize-time extras
        cannot see."""
        if isinstance(from_, A.TableRef):
            alias = from_.alias or from_.name
            name_l = from_.name.lower()
            # a CTE or temp view shadowing a catalog name is NOT the base
            # table — its rows carry no schema uniqueness guarantees
            in_cte = any(name_l in scope for scope in self.cte_stack)
            is_base = not in_cte and name_l in self.base_tables
            raw = self._lookup_table(from_.name)
            from nds_tpu.engine.table import ChunkedTable
            if isinstance(raw, ChunkedTable):
                # >HBM scan: stays host-resident; _join_parts binds device
                # chunks one at a time. Projection pushdown prunes the
                # arrow columns, so only referenced bytes ever upload.
                if self._needed_names is not None:
                    keep = [n for n in raw.column_names
                            if n.lower() in self._needed_names]
                    if keep and len(keep) < len(raw.column_names):
                        raw = raw.select(keep)
                part = _StreamedScan(raw, alias)
                return [part], [], [name_l if is_base else None]
            t = self._alias_table(raw, alias)
            if self._needed_names is not None:
                # projection pushdown: drop scan columns nothing in the
                # statement references (fact tables are 20+ columns wide,
                # queries touch a handful)
                keep = {n for n in t.columns
                        if n.split(".")[-1] in self._needed_names}
                if keep and len(keep) < len(t.columns):
                    t = t.select([n for n in t.column_names if n in keep])
            return [t], [], [name_l if is_base else None]
        if isinstance(from_, A.SubqueryRef):
            t = self.query(from_.query)
            return [self._alias_table(t, from_.alias)], [], [None]
        if isinstance(from_, A.Join):
            if from_.kind in ("cross", "inner"):
                lp, lj, ls = self._flatten_from(from_.left, where,
                                                top=False)
                rp, rj, rs = self._flatten_from(from_.right, where,
                                                top=False)
                cond = [h for c in self._split_conjuncts(from_.condition)
                        for h in self._hoist_or_conjuncts(c)]
                return lp + rp, lj + rj + cond, ls + rs
            # outer join: materialize it, pushing WHERE conjuncts owned by
            # the null-preserving side below the join first (for LEFT, a
            # predicate over left columns only commutes with the join) —
            # UNLESS one side binds a >HBM chunked scan and the join fits
            # one of the multi-pass streamed shapes, in which case the
            # join defers INTO the streamed graph (_OuterProbe /
            # _OuterBuild) instead of materializing the chunked side whole
            lp, lj, ls = self._flatten_from(
                from_.left, where if from_.kind == "left" else None,
                top=False)
            conjs = ([h for c in self._split_conjuncts(from_.condition)
                      for h in self._hoist_or_conjuncts(c)]
                     if from_.condition is not None else [])
            l_chunk = any(isinstance(p, _StreamedScan) for p in lp)
            if from_.kind == "left" and l_chunk and conjs and \
                    not os.environ.get("NDS_TPU_NO_PK_GATHER"):
                # mechanism (b1): chunked scan on the PRESERVED side.
                # Leave WHERE alone — left-side filters push down inside
                # the streamed graph; conjuncts over probe columns apply
                # after the per-chunk gather (_join_parts_outer).
                rp, rj, rs = self._flatten_from(from_.right, top=False)
                if self._probe_eligible(conjs, lp, rp, rj, rs):
                    return (lp + [_OuterProbe(rp[0], from_.condition,
                                              conjs, rs[0])],
                            lj, ls + [rs[0]])
                # ineligible after flattening: today's materialize path,
                # reusing the already-flattened right side
                lw = self._consume_pushable(where, lp)
                left = self._join_parts(lp, lj, lw, ls)
                right = self._join_parts(rp, rj, [], rs)
                right_src = rs[0] if len(rs) == 1 else None
                joined = self._binary_join(left, right, from_.kind,
                                           from_.condition,
                                           right_src=right_src)
                return [joined], [], [None]
            lw = self._consume_pushable(where, lp) \
                if from_.kind == "left" else []
            left = self._join_parts(lp, lj, lw, ls)
            rp, rj, rs = self._flatten_from(
                from_.right, where if from_.kind == "right" else None,
                top=False)
            if from_.kind == "left" and top and conjs and \
                    self._build_eligible(conjs, lp, rp, rj, where):
                # mechanism (b2): chunked scan on the NULL-INTRODUCING
                # side — the materialized left side becomes the BUILD
                # operand of the streamed graph; extras emit at
                # materialize time from the unmatched-key accumulator
                build_src = ls[0] if len(ls) == 1 else None
                return ([rp[0], _OuterBuild(left, from_.condition, conjs,
                                            build_src)],
                        [], [rs[0], None])
            rw = self._consume_pushable(where, rp) \
                if from_.kind == "right" else []
            right = self._join_parts(rp, rj, rw, rs)
            # single-leaf scan provenance survives filtering (uniqueness is
            # key-set property, not row-set) — _binary_join uses it to turn
            # LEFT joins on a declared (composite) PK into gathers
            right_src = rs[0] if len(rs) == 1 else None
            joined = self._binary_join(left, right, from_.kind,
                                       from_.condition, right_src=right_src)
            return [joined], [], [None]
        raise ExecError(f"unsupported FROM clause {type(from_).__name__}")

    def _probe_eligible(self, conjs, lp, rp, rj, rs) -> bool:
        """Mechanism (b1) shape test: the right side must be one pristine
        device scan whose ON keys are exactly its declared (composite)
        primary key, every ON conjunct a plain cross-side equi pair — the
        shape the per-chunk gather serves with zero steady-state syncs
        (composite keys must be numeric to pack, mirroring
        ``_pk_gather_plan``). Mirrored by ``exec_audit._deferred_left``."""
        from nds_tpu.schema import COMPOSITE_PRIMARY_KEYS, PRIMARY_KEYS
        if len(rp) != 1 or rj or not rs or rs[0] is None or \
                not isinstance(rp[0], DeviceTable):
            return False
        lcols = set()
        for p in lp:
            lcols |= set(p.column_names)
        rcols = set(rp[0].column_names)
        rkeys = []
        for c in conjs:
            if self._has_subquery(c):
                return False
            pair = self._equi_pair(c, lcols, rcols)
            if pair is None:
                return False
            rkeys.append(pair[1])
        pk = COMPOSITE_PRIMARY_KEYS.get(rs[0])
        if pk is None and rs[0] in PRIMARY_KEYS:
            pk = (PRIMARY_KEYS[rs[0]],)
        if pk is None or {k.split(".")[-1] for k in rkeys} != set(pk):
            return False
        if len(pk) > 1 and any(
                rp[0][k].kind in ("str", "f64") or
                rp[0][k].kind.startswith("dec") for k in rkeys):
            return False                 # composite pack is int-only
        return True

    def _build_eligible(self, conjs, lp, rp, rj, where) -> bool:
        """Mechanism (b2) shape test: single chunked scan on the right,
        single device part on the left (the build side), plain equi ON,
        and NO remaining WHERE conjunct at all — post-join structure
        (including a ref-less ``1 = 0``) would need the extras (emitted
        only at materialize) to flow through it. The caller additionally
        requires the join to be the SELECT's whole FROM (``top``): a
        parent join would wrap the deferral the same way. Mirrored by
        ``exec_audit._deferred_left``."""
        if len(rp) != 1 or rj or not isinstance(rp[0], _StreamedScan):
            return False
        if len(lp) != 1 or any(isinstance(p, (_StreamedScan, _OuterProbe,
                                              _OuterBuild)) for p in lp):
            return False
        if where:
            return False
        lcols = set(lp[0].column_names)
        rcols = set(rp[0].column_names)
        for c in conjs:
            if self._has_subquery(c) or \
                    self._equi_pair(c, lcols, rcols) is None:
                return False
        return True

    def _refs_touch(self, e, cols) -> bool:
        """True when any column reference of ``e`` resolves in ``cols``.
        Subquery-bearing expressions always touch (their inner scopes are
        not walked, so the conservative answer keeps them post-join —
        WHERE semantics make post-join evaluation always correct)."""
        if self._has_subquery(e):
            return True
        return any(self._resolve_name(r, cols) is not None
                   for r in self._column_refs(e))

    def _consume_pushable(self, where, parts):
        """Remove and return the conjuncts of ``where`` (in place) whose
        every column reference resolves within ``parts`` and which carry no
        subquery — the set safe to evaluate below an outer join on the
        null-preserving side."""
        if not where:
            return []
        cols = set()
        for p in parts:
            cols |= set(p.column_names)
        taken = []
        for c in list(where):
            if self._has_subquery(c):
                continue
            if self._refs_resolve_in(c, cols):
                taken.append(c)
                where.remove(c)
        return taken

    def _refs_resolve_in(self, e, cols) -> bool:
        """True when the expression references at least one column and every
        column it references resolves within ``cols``."""
        refs = []
        ok = True

        def walk(node):
            nonlocal ok
            if isinstance(node, A.ColumnRef):
                refs.append(node)
                if self._resolve_name(node, cols) is None:
                    ok = False
            for ch in self._child_exprs(node):
                walk(ch)
        walk(e)
        return ok and bool(refs)

    # -------------------------------------------------------- join machinery

    def _split_conjuncts(self, e):
        if isinstance(e, A.BinaryOp) and e.op == "and":
            return self._split_conjuncts(e.left) + self._split_conjuncts(e.right)
        return [e] if e is not None else []

    def _split_disjuncts(self, e):
        if isinstance(e, A.BinaryOp) and e.op == "or":
            return self._split_disjuncts(e.left) + self._split_disjuncts(e.right)
        return [e]

    @staticmethod
    def _fold_bool(op: str, exprs):
        out = exprs[0]
        for e in exprs[1:]:
            out = A.BinaryOp(op, out, e)
        return out

    def _hoist_or_conjuncts(self, e):
        """Factor conjuncts common to every disjunct out of an OR:
        ``(A and X) or (A and Y)`` → ``[A, (X or Y)]``. The TPC-DS corpus
        (q13/q48/q85) hides its equi-join keys this way; without hoisting the
        join planner would fall back to a cartesian against the 1.9M-row
        customer_demographics dimension."""
        if not (isinstance(e, A.BinaryOp) and e.op == "or"):
            return [e]
        conj_lists = [self._split_conjuncts(d) for d in self._split_disjuncts(e)]
        common = [c for c in conj_lists[0]
                  if all(any(c == d for d in dl) for dl in conj_lists[1:])]
        if not common:
            return [e]
        rests = []
        for dl in conj_lists:
            rest = [c for c in dl if not any(c == cm for cm in common)]
            if not rest:
                # one disjunct is exactly the common set: OR degenerates
                return common
            rests.append(self._fold_bool("and", rest))
        return common + [self._fold_bool("or", rests)]

    @staticmethod
    def _child_exprs(node):
        """Direct A.Expr children of an AST node (the shared recursion step
        of every expression walker: dataclass fields that are expressions,
        lists of expressions, or lists of tuples containing expressions)."""
        if not hasattr(node, "__dataclass_fields__"):
            return
        for f in vars(node).values():
            if isinstance(f, A.Expr):
                yield f
            elif isinstance(f, list):
                for x in f:
                    if isinstance(x, A.Expr):
                        yield x
                    elif isinstance(x, tuple):
                        for y in x:
                            if isinstance(y, A.Expr):
                                yield y

    def _expr_tables(self, e, available: set) -> set:
        """Set of alias-qualified table names an expression references."""
        out = set()

        def walk(node):
            if isinstance(node, A.ColumnRef):
                key = self._resolve_name(node, available)
                if key is not None:
                    out.add(key.split(".")[0])
            for c in self._child_exprs(node):
                walk(c)
        walk(e)
        return out

    def _resolve_name(self, ref: A.ColumnRef, colnames) -> str | None:
        name = ref.name.lower()
        if ref.table:
            key = f"{ref.table.lower()}.{name}"
            return key if key in colnames else None
        matches = [c for c in colnames if c.split(".")[-1] == name]
        if len(matches) == 1:
            return matches[0]
        if len(matches) > 1:
            # ambiguous unqualified ref: SQL would error; the corpus relies on
            # it only when all candidates are join-equal, pick the first
            return matches[0]
        return None

    def _binary_join(self, left: DeviceTable, right: DeviceTable, kind: str,
                     condition, right_src: str | None = None) -> DeviceTable:
        conjuncts = [h for c in self._split_conjuncts(condition)
                     for h in self._hoist_or_conjuncts(c)]
        lcols, rcols = set(left.column_names), set(right.column_names)
        equi, lkeys, rkeys, residual = [], [], [], []
        all_plain = True
        for c in conjuncts:
            pair = self._equi_pair(c, lcols, rcols)
            if pair:
                equi.append(pair)
                lkeys.append(left[pair[0]])
                rkeys.append(right[pair[1]])
                continue
            keypair = self._equi_key_cols(c, left, right)
            if keypair:
                # expression equi-key (e.g. cast(col as date) = d_date):
                # evaluate each side against its input as a synthetic key
                all_plain = False
                lkeys.append(keypair[0])
                rkeys.append(keypair[1])
                continue
            residual.append(c)
        if kind in ("semi", "anti"):
            if not lkeys:
                raise ExecError("semi/anti join requires equi condition")
            if residual:
                # a left row matches only if some equi-matching right row also
                # satisfies the residual conjuncts
                l_idx, r_idx, n_pairs, _, _, _, _ = E.join_indices(
                    lkeys, rkeys, "inner",
                    n_left=left.nrows, n_right=right.nrows)
                pairs = DeviceTable(
                    {**E.gather_table_rows(left, l_idx, n_pairs).columns,
                     **E.gather_table_rows(right, r_idx, n_pairs).columns},
                    n_pairs)
                ok = self._conjunct_mask(pairs, residual)
                ok = ok & E.live_mask(pairs.plen, pairs.nrows)
                safe = jnp.where(ok, l_idx, left.plen)
                matched = jnp.zeros(left.plen, dtype=bool).at[safe].set(
                    True, mode="drop")
            else:
                matched = E.semi_join_mask(lkeys, rkeys, n_left=left.nrows,
                                           n_right=right.nrows)
            mask = ~matched if kind == "anti" else matched
            return E.compact_table(left, mask)
        if not lkeys:
            # pure cartesian with optional residual filter
            out = self._cartesian(left, right)
            if residual:
                out = self._filter_conjuncts(out, residual)
            if kind != "inner":
                raise ExecError("non-equi outer joins unsupported")
            return out
        if not residual and all_plain:
            l_on = [l for l, _ in equi]
            r_on = [r for _, r in equi]
            if kind == "left" and right_src and \
                    not os.environ.get("NDS_TPU_NO_PK_GATHER"):
                # LEFT join on the right side's declared (composite) PK:
                # at most one match per probe row, so gather right columns
                # onto the left's unchanged physical rows and null-extend
                # misses — no pair machinery, no syncs (q78-class
                # sales x returns joins). Uniqueness is a schema fact.
                from nds_tpu.schema import (COMPOSITE_PRIMARY_KEYS,
                                            PRIMARY_KEYS)
                pk = COMPOSITE_PRIMARY_KEYS.get(right_src)
                if pk is None and right_src in PRIMARY_KEYS:
                    pk = (PRIMARY_KEYS[right_src],)
                bare = {r.split(".")[-1] for r in r_on}
                if pk is not None and bare == set(pk):
                    got = E.pk_gather_join_multi(
                        [left[n] for n in l_on], [right[n] for n in r_on],
                        left.nrows, right.nrows)
                    if got is not None:
                        r_idx, matched = got
                        cols = dict(left.columns)
                        rg = E.gather_table_rows(right, r_idx, left.nrows)
                        for n, c in rg.columns.items():
                            cols[n] = Column(c.kind, c.data,
                                             c.valid_mask() & matched,
                                             c.dict_values, c.enc)
                        return DeviceTable(cols, left.nrows, plen=left.plen)
            return E.join_tables(left, right, l_on, r_on, kind)
        # join with residual and/or expression keys: match pairs on the key
        # columns, filter by the residual conjuncts, then rebuild outer rows
        l_idx, r_idx, n_pairs, _, _, _, _ = E.join_indices(
            lkeys, rkeys, "inner", n_left=left.nrows, n_right=right.nrows)
        pairs = DeviceTable(
            {**E.gather_table_rows(left, l_idx, n_pairs).columns,
             **E.gather_table_rows(right, r_idx, n_pairs).columns}, n_pairs)
        keep_mask = self._conjunct_mask(pairs, residual)
        keep_mask = keep_mask & E.live_mask(pairs.plen, pairs.nrows)
        matched = E.compact_table(pairs, keep_mask)
        if kind == "inner":
            return matched
        out_parts = [matched]
        miss = miss_r = None
        if kind in ("left", "full"):
            safe_l = jnp.where(keep_mask, l_idx, left.plen)
            lmask = jnp.zeros(left.plen, dtype=bool).at[safe_l].set(
                True, mode="drop")
            miss = ~lmask & E.live_mask(left.plen, left.nrows)
            nd_lx = E.DeviceCount(jnp.sum(miss), E.count_bound(left.nrows))
        if kind in ("right", "full"):
            safe_r = jnp.where(keep_mask, r_idx, right.plen)
            rmask = jnp.zeros(right.plen, dtype=bool).at[safe_r].set(
                True, mode="drop")
            miss_r = ~rmask & E.live_mask(right.plen, right.nrows)
            nd_rx = E.DeviceCount(jnp.sum(miss_r), E.count_bound(right.nrows))
        # both extra counts resolve in one batched transfer (one sync)
        if miss is not None:
            n_lx = nd_lx.to_int()
            if n_lx:
                lx = E.compact_indices(miss, n_lx)
                cols = {n: c.take(lx) for n, c in left.columns.items()}
                cols.update({n: E._null_column_like(c, int(lx.shape[0]))
                             for n, c in right.columns.items()})
                out_parts.append(DeviceTable(cols, n_lx))
        if miss_r is not None:
            n_rx = nd_rx.to_int()
            if n_rx:
                rx = E.compact_indices(miss_r, n_rx)
                cols = {n: E._null_column_like(c, int(rx.shape[0]))
                        for n, c in left.columns.items()}
                cols.update({n: c.take(rx) for n, c in right.columns.items()})
                out_parts.append(DeviceTable(cols, n_rx))
        return E.concat_tables(out_parts) if len(out_parts) > 1 else out_parts[0]

    def _pk_gather_plan(self, tables, sources, a, b, es):
        """Eligibility of the (a, b) edge batch for a PK gather join.

        Requires the edge batch's dimension-side key set to be exactly the
        declared primary key — single-column (any surrogate kind) or
        composite (integer kinds; packed into one probe key) — of a still-
        pristine base-table scan (``sources`` survives deferred filters and
        earlier gather joins, which never change a slot's physical rows).
        Uniqueness is a schema fact, so no runtime check or sync is needed.
        Returns ``(fact_slot, dim_slot, [fact_keys], [dim_keys])`` or
        None."""
        from nds_tpu.schema import COMPOSITE_PRIMARY_KEYS, PRIMARY_KEYS
        if os.environ.get("NDS_TPU_NO_PK_GATHER"):
            return None
        pairs = [((lk, rk) if sl == a else (rk, lk)) for (sl, sr, lk, rk)
                 in es]
        for fact_slot, dim_slot, idx in ((a, b, 1), (b, a, 0)):
            src = sources[dim_slot]
            if not src:
                continue
            dks = [p[idx] for p in pairs]
            fks = [p[1 - idx] for p in pairs]
            bare = {d.split(".")[-1] for d in dks}
            if len(es) == 1 and bare == {PRIMARY_KEYS.get(src)}:
                pass                           # single-column PK
            elif bare == set(COMPOSITE_PRIMARY_KEYS.get(src, ())):
                pass                           # composite PK (full cover)
            else:
                continue
            ok = True
            for fk, dk in zip(fks, dks):
                fkc, dkc = tables[fact_slot][fk], tables[dim_slot][dk]
                if fkc.kind == "f64" or dkc.kind == "f64":
                    ok = False                 # surrogate keys only
                if (fkc.kind == "str") != (dkc.kind == "str"):
                    ok = False
                if len(es) > 1 and (fkc.kind == "str" or dkc.kind == "str"):
                    ok = False                 # composite pack is int-only
            if ok:
                return fact_slot, dim_slot, fks, dks
        return None

    def _equi_pair(self, c, lcols, rcols):
        if isinstance(c, A.BinaryOp) and c.op == "=" and \
                isinstance(c.left, A.ColumnRef) and isinstance(c.right, A.ColumnRef):
            lk = self._resolve_name(c.left, lcols)
            rk = self._resolve_name(c.right, rcols)
            if lk and rk:
                return (lk, rk)
            lk2 = self._resolve_name(c.right, lcols)
            rk2 = self._resolve_name(c.left, rcols)
            if lk2 and rk2:
                return (lk2, rk2)
        return None

    def _has_subquery(self, e) -> bool:
        found = False

        def walk(node):
            nonlocal found
            if isinstance(node, (A.ScalarSubquery, A.InSubquery, A.Exists)):
                found = True
                return
            if getattr(node, "query", None) is not None and \
                    isinstance(getattr(node, "query"), A.Query):
                found = True
                return
            for c in self._child_exprs(node):
                walk(c)
        walk(e)
        return found

    def _column_refs(self, e):
        out = []

        def walk(node):
            if isinstance(node, A.ColumnRef):
                out.append(node)
            for c in self._child_exprs(node):
                walk(c)
        walk(e)
        return out

    def _synthetic_edge(self, c, parts, part_cols):
        """Edge for an ``expr = expr`` conjunct whose sides each reference
        exactly one (distinct) part: materialize both expressions as
        synthetic key columns on their parts and return the edge tuple.
        The flattened-join twin of :func:`_equi_key_cols`."""
        def side_owner(e):
            refs = self._column_refs(e)
            if not refs:
                return None
            owner = None
            for r in refs:
                cands = [i for i, pc in enumerate(part_cols)
                         if self._resolve_name(r, pc) is not None]
                if len(cands) != 1:
                    return None
                if owner is None:
                    owner = cands[0]
                elif owner != cands[0]:
                    return None
            return owner

        lo_, ro_ = side_owner(c.left), side_owner(c.right)
        if lo_ is None or ro_ is None or lo_ == ro_:
            return None
        try:
            lcol = self.eval_expr(c.left, EvalCtx(parts[lo_]))
            rcol = self.eval_expr(c.right, EvalCtx(parts[ro_]))
        except Exception:
            return None                   # stays residual, as before
        n = self._synth_keys
        self._synth_keys += 1
        ln, rn = f"__jk{n}_l", f"__jk{n}_r"
        parts[lo_] = DeviceTable({**parts[lo_].columns, ln: lcol},
                                 parts[lo_].nrows, plen=parts[lo_].plen)
        part_cols[lo_].add(ln)
        parts[ro_] = DeviceTable({**parts[ro_].columns, rn: rcol},
                                 parts[ro_].nrows, plen=parts[ro_].plen)
        part_cols[ro_].add(rn)
        return (lo_, ro_, ln, rn)

    def _equi_key_cols(self, c, left: DeviceTable, right: DeviceTable):
        """(left key Column, right key Column) for an ``expr = expr`` conjunct
        whose sides each reference exactly one join input (e.g.
        ``cast(purc_purchase_date as date) = d_date``); None otherwise."""
        if not (isinstance(c, A.BinaryOp) and c.op == "="):
            return None
        lcols, rcols = set(left.column_names), set(right.column_names)
        for a, b, ltab, rtab in ((c.left, c.right, left, right),
                                 (c.right, c.left, left, right)):
            arefs = self._column_refs(a)
            brefs = self._column_refs(b)
            if not arefs or not brefs:
                continue
            if all(self._resolve_name(r, lcols) for r in arefs) and \
                    all(self._resolve_name(r, rcols) for r in brefs):
                return (self.eval_expr(a, EvalCtx(ltab)),
                        self.eval_expr(b, EvalCtx(rtab)))
        return None

    def _cartesian(self, left: DeviceTable, right: DeviceTable) -> DeviceTable:
        pl, pr = left.plen, right.plen
        # the physical expansion is pl x pr either way; host counts lay out
        # the live prefix (both sides resolve in one batched transfer)
        nl, nr = E.count_int(left.nrows), E.count_int(right.nrows)
        total = nl * nr
        if pl == 0 or pr == 0 or total == 0:
            cols = {n: E._null_column_like(c, E.bucket_len(0))
                    for t in (left, right) for n, c in t.columns.items()}
            return DeviceTable(cols, 0)
        li = jnp.repeat(jnp.arange(pl), pr)
        ri = jnp.tile(jnp.arange(pr), pl)
        live = (li < nl) & (ri < nr)
        # logical count is known on host: compact to bucket with no sync
        idx = jnp.nonzero(live, size=E.bucket_len(total), fill_value=pl * pr)[0]
        li = jnp.take(li, idx, mode="fill", fill_value=pl)
        ri = jnp.take(ri, idx, mode="fill", fill_value=pr)
        return DeviceTable(
            {**E.gather_table_rows(left, li, total).columns,
             **E.gather_table_rows(right, ri, total).columns}, total)

    def _conjunct_mask_eager(self, table: DeviceTable, conjuncts) -> jnp.ndarray:
        ctx = EvalCtx(table)
        mask = jnp.ones(table.plen, dtype=bool)
        for c in conjuncts:
            col = self.eval_expr(c, ctx)
            mask = mask & col.data.astype(bool) & col.valid_mask()
        return mask

    def _conjunct_mask(self, table: DeviceTable, conjuncts) -> jnp.ndarray:
        """Predicate mask over a plain table. Subquery-free conjunct sets
        evaluate inside ONE jitted program per (expressions, table
        signature) — a WHERE clause of a dozen predicates costs a single
        device dispatch instead of one per scalar op, which is the dominant
        per-query cost on a remote (tunneled) attachment. Expressions whose
        evaluation needs concrete values on host (calendar interval math,
        string casts of numeric columns) fail the one trace attempt and the
        set permanently falls back to eager evaluation."""
        if not conjuncts:
            return jnp.ones(table.plen, dtype=bool)
        # under an active param binding (compiled replay with bound-
        # literal operands) fusion must stand down: fused programs bake
        # literal values at their own trace time, which would bypass the
        # binding — and inside the pipeline's jit the fused call is
        # inlined anyway, so eager evaluation there is free
        if os.environ.get("NDS_TPU_NO_EXPR_FUSE") or \
                X.param_bindings_active() or \
                any(self._has_subquery(c) for c in conjuncts):
            return self._conjunct_mask_eager(table, conjuncts)
        plen = table.plen

        def build_impl(ev, names, kinds, dict_refs, encs, meta):
            def impl(datas, valids):
                tcols = {n: Column(k, d, v, dv, en) for n, k, d, v, dv, en
                         in zip(names, kinds, datas, valids, dict_refs,
                                encs)}
                # nrows deliberately = plen: expression evaluation must
                # never depend on the logical count (pads are masked later)
                return ev._conjunct_mask_eager(
                    DeviceTable(tcols, plen, plen=plen), conjuncts)
            return impl

        got = self._fused_run(_MASK_FUSE_CACHE, table, conjuncts,
                              build_impl, "predicate")
        if got is None:
            return self._conjunct_mask_eager(table, conjuncts)
        return got[0]

    def _fused_run(self, cache, table, exprs, build_impl, what):
        """Shared expression-fusion machinery for :func:`_conjunct_mask` and
        :func:`_prefuse_exprs`: referenced-column input selection, cache
        keying by (expression keys, physical length, column signature),
        dictionary-identity validation on hits, ONE jitted trace attempt
        with pin-to-eager on trace-class errors, and FIFO eviction.

        ``build_impl(ev, names, kinds, dict_refs, meta)`` returns the
        function to jit (signature ``(datas, valids)``); ``ev`` is a
        detached Planner (capturing ``self`` would pin this query's planner
        and its device-resident contexts in the module cache for process
        lifetime) and ``meta`` a list the impl may fill with static output
        metadata as a tracing side effect. Returns ``(output, meta)`` or
        None when the batch is unfusable/pinned (caller evaluates eager).
        Runtime errors (device OOM, wedged RPC) propagate — swallowing one
        would silently pin a fusable set to eager forever.

        Thread-safe (concurrent Throughput streams share both module
        caches): reads are lock-free (GIL-atomic dict get + identity
        validation), every mutation takes :data:`_FUSE_LOCK`, and a miss
        goes through the :data:`_FUSE_BUILDS` singleflight so concurrent
        first sights of one shape cost exactly ONE jitted trace — the
        trace itself runs OFF-lock (a compile under the lock would
        serialize every stream)."""
        refs = {r.name.lower()
                for c in exprs for r in self._column_refs(c)}
        # inputs cover only the columns the expressions can reference —
        # unrelated columns changing shape must not retrace
        names = [n for n in table.column_names if n.split(".")[-1] in refs]
        if not names:
            return None
        cols = [table.columns[n] for n in names]
        plen = table.plen
        from nds_tpu.engine.column import enc_key, encs_equal
        key = (tuple(expr_key(c) for c in exprs), plen,
               tuple((n, c.kind, int(c.data.shape[0]), c.valid is not None,
                      str(c.data.dtype), enc_key(c.enc))
                     for n, c in zip(names, cols)))
        _PINNED = ("pinned",)            # entry says: permanently eager

        def serve(hit):
            """Run a cache entry against this table, or None when the
            entry is absent / does not cover these dictionary identities
            and encodings (the caller then rebuilds)."""
            if hit is None or \
                    not all(h is c.dict_values
                            for h, c in zip(hit[0], cols)) or \
                    not all(encs_equal(h, c.enc)
                            for h, c in zip(hit[3], cols)):
                return None
            if hit[1] is None:
                return _PINNED
            return hit[1](tuple(c.data for c in cols),
                          tuple(c.valid for c in cols)), hit[2]

        got = serve(cache.get(key))
        if got is _PINNED:
            return None
        if got is not None:
            return got
        # miss (or an entry that cannot serve these dictionary
        # identities): claim the build — waiting out any in-flight
        # builder — then re-check under the claim; the winner's entry
        # usually serves without a trace, and a build only ever runs
        # CLAIMED, so concurrent duplicate compiles of one shape cannot
        # happen
        bkey = (id(cache), key)
        claim = _fuse_claim(bkey)
        try:
            got = serve(cache.get(key))
            if got is not None:
                return None if got is _PINNED else got
            dict_refs = tuple(c.dict_values for c in cols)
            encs = tuple(c.enc for c in cols)
            kinds = tuple(c.kind for c in cols)
            ev = Planner({}, base_tables=set())
            meta: list = []
            fn = jax.jit(build_impl(ev, names, kinds, dict_refs, encs,
                                    meta))
            try:
                out = fn(tuple(c.data for c in cols),
                         tuple(c.valid for c in cols))
            except (TypeError, ValueError, NotImplementedError,
                    jax.errors.TracerArrayConversionError,
                    jax.errors.ConcretizationTypeError,
                    jax.errors.TracerBoolConversionError) as e:
                logging.getLogger(__name__).info(
                    "%s fusion fell back to eager: %s: %s",
                    what, type(e).__name__, e)
                self._fuse_insert(cache, key, bkey,
                                  (dict_refs, None, None, encs))
                return None
            m = list(meta)
            self._fuse_insert(cache, key, bkey, (dict_refs, fn, m, encs))
            return out, m
        finally:
            with _FUSE_LOCK:
                _FUSE_BUILDS.pop(bkey, None)
            claim.set()

    @staticmethod
    def _fuse_insert(cache, key, bkey, entry) -> None:
        """Land one fusion-cache entry (FIFO-evicting past the bound) and
        charge the per-shape build counter — all under the fuse lock.
        The evicted entry's counter leaves with it (bounded counters)."""
        with _FUSE_LOCK:
            if len(cache) >= _MASK_FUSE_MAX:
                evicted = next(iter(cache))
                cache.pop(evicted)
                _FUSE_BUILD_COUNTS.pop((id(cache), evicted), None)
            cache[key] = entry
            _FUSE_BUILD_COUNTS[bkey] = _FUSE_BUILD_COUNTS.get(bkey, 0) + 1

    def _has_window(self, e) -> bool:
        found = False

        def walk(node):
            nonlocal found
            if isinstance(node, A.WindowFunc):
                found = True
                return
            for c in self._child_exprs(node):
                walk(c)
        walk(e)
        return found

    def _prefuse_exprs(self, table: DeviceTable, exprs, ctx: EvalCtx) -> None:
        """Evaluate a batch of scalar expressions over ``table`` inside ONE
        jitted program and seed the results into ``ctx.window_values`` (the
        memo :func:`eval_expr` consults first), so the SELECT list and
        aggregate arguments cost one device dispatch instead of one per
        scalar op — the projection-side twin of :func:`_conjunct_mask`.
        Output metadata (kind, dictionary) is captured as a tracing side
        effect; trace failures (host-dependent expressions) pin the batch to
        eager evaluation. Best-effort: callers proceed identically whether
        or not anything was seeded."""
        if os.environ.get("NDS_TPU_NO_EXPR_FUSE"):
            return
        seen, fusable = set(), []
        for e in exprs:
            k = expr_key(e)
            if k in seen or k in ctx.window_values:
                continue
            seen.add(k)
            if not self._has_subquery(e) and not self._has_window(e):
                fusable.append((k, e))
        # bare refs/literals gain nothing from fusion
        if not any(not isinstance(e, (A.ColumnRef, A.Literal))
                   for _, e in fusable):
            return
        plen = table.plen

        def build_impl(ev, names, kinds, dict_refs, encs, meta):
            def impl(datas, valids):
                tcols = {n: Column(k, d, v, dv, en) for n, k, d, v, dv, en
                         in zip(names, kinds, datas, valids, dict_refs,
                                encs)}
                tctx = EvalCtx(DeviceTable(tcols, plen, plen=plen))
                outs = [ev.eval_expr(e, tctx) for _, e in fusable]
                meta.clear()
                meta.extend((c.kind, c.dict_values, c.enc) for c in outs)
                return (tuple(c.data for c in outs),
                        tuple(c.valid for c in outs))
            return impl

        got = self._fused_run(_EXPR_FUSE_CACHE, table,
                              [e for _, e in fusable], build_impl,
                              "projection")
        if got is None:
            return
        (datas, valids), meta = got
        for (k, _), d, v, (kind, dv, en) in zip(fusable, datas, valids,
                                                meta):
            ctx.window_values[k] = Column(kind, d, v, dv, en)

    def _filter_conjuncts(self, table: DeviceTable, conjuncts) -> DeviceTable:
        if not conjuncts:
            return table
        return E.compact_table(table, self._conjunct_mask(table, conjuncts))

    def _stream_join_parts(self, parts, join_preds, where_conjuncts,
                           sources):
        """Streamed execution of a join graph containing >HBM scans: bind
        the largest streamed part's device chunks one at a time and run
        the join graph per chunk (pushed-down filters and joins shrink the
        chunk before anything is kept), keeping the survivor union.
        Downstream aggregation runs on the union, which is correct because
        joins and filters distribute over row-wise union. Other streamed
        parts materialize whole (one streaming axis per graph).

        Default path: the COMPILED chunk pipeline (engine/stream.py) —
        one traced per-chunk program driven over every padded chunk with
        prefetch, on-device survivor accumulation and a single
        materializing sync, holding streamed queries to the same host-sync
        budget as device-resident ones (tests/test_synccount.py). The
        per-chunk eager loop below survives as the automatic fallback for
        graphs that are not chunk-invariant and as the explicit
        ``NDS_TPU_STREAM_EXEC=eager`` escape hatch."""
        streamed = [i for i, p in enumerate(parts)
                    if isinstance(p, _StreamedScan)]
        keep = max(streamed, key=lambda i: parts[i].nbytes)
        parts = list(parts)
        for i in streamed:
            if i != keep:
                parts[i] = parts[i].bind_whole(self)
        # the span opens exactly where the StreamEvent sync window opens,
        # so its sync delta equals the event's — the invariant
        # tools/exec_audit_diff.py cross-checks (trace layer must never
        # pay for its own metrics)
        with _obs.span("stream", table=parts[keep].alias):
            syncs0 = E.sync_count()
            reason = None
            if os.environ.get("NDS_TPU_STREAM_EXEC",
                              "compiled").lower() != "eager":
                from nds_tpu.engine.stream import stream_execute
                got, reason = stream_execute(self, parts, keep, join_preds,
                                             where_conjuncts, list(sources))
                if got is not None:
                    return got
            else:
                reason = "NDS_TPU_STREAM_EXEC=eager"
            outs = []
            n_chunks = 0
            h2d = 0
            # a bound-bucket overflow discards a COMPLETED compiled run:
            # the rerun gets its own span name so tools/trace_report.py
            # can price the wasted pipeline work separately from ordinary
            # eager fallbacks (which never drove the pipeline at all)
            eager_span = "stream.overflow-rerun" \
                if reason == "bound-bucket overflow" else "stream.eager"
            builds = [p for p in parts if isinstance(p, _OuterBuild)]
            bitmaps = None
            # the eager loop pulls its chunks through the same bounded
            # prefetch ring the compiled pipeline uses (engine/prefetch):
            # the arrow slice + device conversion of chunk k+1 runs on
            # the worker while chunk k's join graph executes here; depth
            # 0 (NDS_TPU_PREFETCH_DEPTH=0) is the inline loop, bit for
            # bit. The ring closes in the finally so a mid-loop planner
            # exception never leaks the worker thread.
            from nds_tpu.engine.prefetch import chunk_ring
            ring = chunk_ring(parts[keep].device_chunks(self),
                              name="nds-prefetch-eager")
            with _obs.span(eager_span,
                           reason=reason or "replay-nested"):
                try:
                    while True:
                        chunk = ring.next_chunk()
                        if chunk is None:
                            break
                        n_chunks += 1
                        # actual prefetch bytes of this scan (buffer
                        # metadata, no sync): the eager loop uploads
                        # unencoded chunks
                        h2d += sum(
                            c.data.nbytes
                            + (0 if c.valid is None else c.valid.nbytes)
                            for c in chunk.columns.values())
                        sub = list(parts)
                        sub[keep] = chunk
                        with E.outer_match_collector() as omc:
                            out = self._join_parts(sub, join_preds,
                                                   where_conjuncts,
                                                   list(sources))
                        if builds:
                            # OR each chunk's matched-build-row masks:
                            # the outer extras (unmatched across EVERY
                            # chunk) append once, after the loop
                            bitmaps = list(omc.masks) if bitmaps is None \
                                else [a | b for a, b in zip(bitmaps,
                                                            omc.masks)]
                        if E.count_bound(out.nrows) or not outs:
                            outs.append(out)
                    stall_ms = ring.stall_ms()
                finally:
                    ring.close()
                result = E.concat_tables(outs) if len(outs) > 1 else outs[0]
                if builds and bitmaps is not None:
                    result = self._append_outer_extras(result, builds,
                                                       bitmaps)
            if reason is not None:
                # recorded AFTER the loop: the event's syncs charge the whole
                # eager path (failed compile attempt + per-chunk loop), which
                # is exactly the cost streamedScans exists to expose. reason
                # None = replay-nested fallback, accounted by the outer pass.
                from nds_tpu.listener import record_stream_event
                record_stream_event(parts[keep].alias, n_chunks,
                                    E.sync_count() - syncs0, "eager", reason,
                                    bytes_h2d=h2d,
                                    prefetch_stall_ms=stall_ms)
                from nds_tpu.engine.kernels import active_arm
                _obs.annotate(path="eager", chunks=n_chunks, reason=reason,
                              bytesH2d=h2d, prefetchStallMs=stall_ms,
                              kernelArm=active_arm(),
                              kernelLaunches=0, kernelStages=0)
            return result

    def _append_outer_extras(self, result, builds, bitmaps):
        """Eager-loop twin of the pipeline's materialize-time extras:
        null-extended unmatched build rows of every deferred outer-build
        join, appended once after the chunk union."""
        parts = [result]
        for w, bm in zip(builds, bitmaps):
            miss = ~bm & E.live_mask(w.table.plen, w.table.nrows)
            n_miss = E.host_sync(jnp.sum(miss))
            if not n_miss:
                continue
            idx = E.compact_indices(miss, n_miss)
            parts.append(outer_extras_table(w.table, idx, n_miss, result))
        return E.concat_tables(parts) if len(parts) > 1 else result

    def _join_parts_outer(self, parts, join_preds, where_conjuncts,
                          sources, outer_idx):
        """One multi-pass outer-join step: runs per chunk inside the
        streamed pipeline (the chunk slot is a bound DeviceTable here) and
        per chunk on the eager loop. Joins the parts connected to the
        chunk side by outer-free conjuncts first, applies each deferred
        LEFT join, then joins any leftover parts/conjuncts that needed
        the probe columns (q93: ``reason`` joins the returns side of the
        gather). WHERE semantics make the post split always correct —
        deferring a conjunct past the outer join only delays a filter."""
        wrappers = [parts[i] for i in outer_idx]
        inner = [p for i, p in enumerate(parts) if i not in outer_idx]
        inner_src = [s for i, s in enumerate(sources) if i not in outer_idx]
        outer_cols = set()
        for w in wrappers:
            outer_cols |= set(w.column_names)
        conjuncts = list(join_preds) + list(where_conjuncts)
        post = [c for c in conjuncts if self._refs_touch(c, outer_cols)]
        pre = [c for c in conjuncts if not any(c is x for x in post)]
        # union-find the inner parts along pre-conjunct ownership; the
        # components providing the wrappers' ON columns join BEFORE the
        # deferred joins, everything else after
        groups = list(range(len(inner)))

        def find(i):
            while groups[i] != i:
                groups[i] = groups[groups[i]]
                i = groups[i]
            return i

        part_colsets = [set(p.column_names) for p in inner]

        def owners_of(e):
            return [i for i, cs in enumerate(part_colsets)
                    if self._refs_touch(e, cs)]

        for c in pre:
            own = owners_of(c)
            for o in own[1:]:
                groups[find(own[0])] = find(o)
        anchors = set()
        for w in wrappers:
            for c in w.conjuncts:
                for o in owners_of(c):
                    anchors.add(find(o))
        if not anchors and inner:
            anchors = {find(0)}
        pre_idx = [i for i in range(len(inner)) if find(i) in anchors]
        post_idx = [i for i in range(len(inner)) if find(i) not in anchors]
        pre_set = set(pre_idx)
        pre_here = [c for c in pre
                    if set(owners_of(c)) <= pre_set]
        leftover = [c for c in conjuncts
                    if not any(c is x for x in pre_here)]
        out = self._join_parts(
            [inner[i] for i in pre_idx],
            [c for c in join_preds if any(c is x for x in pre_here)],
            [c for c in where_conjuncts if any(c is x for x in pre_here)],
            [inner_src[i] for i in pre_idx])
        for w in wrappers:
            out = self._apply_outer(out, w)
        if post_idx or leftover:
            out = self._join_parts(
                [out] + [inner[i] for i in post_idx], [], leftover,
                [None] + [inner_src[i] for i in post_idx])
        return out

    def _apply_outer(self, left: DeviceTable, w) -> DeviceTable:
        """Apply one deferred LEFT join to a (per-chunk) joined table."""
        if isinstance(w, _OuterProbe):
            # preserved chunk side: PK gather against the whole probe
            # table — sync-free, keeps the chunk's physical rows, misses
            # null-extend in place (_binary_join's gather arm)
            return self._binary_join(left, w.table, "left", w.condition,
                                     right_src=w.src)
        # _OuterBuild: build ⟕ chunk — emit THIS dispatch's matched pairs
        # through an inner bound-bucket join and register the matched
        # build rows; the unmatched build rows (the outer extras) emit
        # ONCE at materialize time from the OR of every dispatch's mask
        build = w.table
        lcols = set(left.column_names)
        bcols = set(build.column_names)
        lkeys, bkeys = [], []
        for c in w.conjuncts:
            pair = self._equi_pair(c, lcols, bcols)
            if pair is None:
                raise ExecError("outer-build join requires plain equi keys")
            lkeys.append(left[pair[0]])
            bkeys.append(build[pair[1]])
        # probe FROM the chunk side: the pair bucket stays chunk-sized
        l_idx, r_idx, n_pairs, _, _, _, _ = E.join_indices(
            lkeys, bkeys, "inner", n_left=left.nrows, n_right=build.nrows)
        matched = jnp.zeros(build.plen, dtype=bool).at[r_idx].set(
            True, mode="drop")
        E.stream_outer_matched(matched)
        cols = dict(E.gather_table_rows(build, r_idx, n_pairs).columns)
        for n, c in E.gather_table_rows(left, l_idx, n_pairs).columns.items():
            # chunk-side columns must be NULLABLE in the output template:
            # the extras rows null-extend them at materialize time
            cols.setdefault(n, Column(c.kind, c.data, c.valid_mask(),
                                      c.dict_values, c.enc))
        return DeviceTable(cols, n_pairs)

    def _join_parts(self, parts, join_preds, where_conjuncts, sources=None):
        """Join-graph execution: push single-table predicates down, then join
        parts connected by equi edges, deferring unconnected parts
        (cartesian only as a last resort). ``sources`` carries each part's
        catalog table name (None otherwise) so single-key joins against a
        declared dimension primary key run as exact merge-probe gathers
        with a deferred miss-mask — no host sync, no pair expansion — the
        star-join shape that dominates the TPC-DS corpus."""
        if sources is None:
            sources = [None] * len(parts)
        if any(isinstance(p, _StreamedScan) for p in parts):
            return self._stream_join_parts(parts, join_preds,
                                           where_conjuncts, sources)
        outer_idx = [i for i, p in enumerate(parts)
                     if isinstance(p, (_OuterProbe, _OuterBuild))]
        if outer_idx:
            return self._join_parts_outer(parts, join_preds, where_conjuncts,
                                          sources, outer_idx)
        sources = list(sources)
        conjuncts = list(join_preds) + list(where_conjuncts)
        # split into single-table filters / equi edges / complex residual
        all_cols = set()
        for p in parts:
            all_cols |= set(p.column_names)
        filters_per_part = [[] for _ in parts]
        edges = []      # (li, ri, lcol, rcol)
        residual = []
        part_cols = [set(p.column_names) for p in parts]

        def owner(colkey):
            for i, pc in enumerate(part_cols):
                if colkey in pc:
                    return i
            return None

        for c in conjuncts:
            if self._has_subquery(c):
                # a correlated subquery may reference columns of OTHER parts
                # (q32: cs_item_sk = i_item_sk inside the scalar subquery);
                # only the fully joined row has every correlation column in
                # scope, so never push these down
                residual.append(c)
                continue
            tables = self._expr_tables(c, all_cols)
            owners = set()
            for p_i, pc in enumerate(part_cols):
                for t in tables:
                    if any(cc.startswith(t + ".") for cc in pc):
                        owners.add(p_i)
            if len(owners) == 1:
                filters_per_part[owners.pop()].append(c)
                continue
            pair = None
            if isinstance(c, A.BinaryOp) and c.op == "=" and \
                    isinstance(c.left, A.ColumnRef) and isinstance(c.right, A.ColumnRef):
                lk = self._resolve_name(c.left, all_cols)
                rk = self._resolve_name(c.right, all_cols)
                if lk and rk:
                    li, ri = owner(lk), owner(rk)
                    if li is not None and ri is not None and li != ri:
                        pair = (li, ri, lk, rk)
            if pair is None and isinstance(c, A.BinaryOp) and c.op == "=" \
                    and len(owners) == 2:
                # expression equi edge (``cast(a.x as date) = b.d + 1``):
                # when each side's references live wholly in one part,
                # materialize synthetic key columns and join on those —
                # without this a flattened inner join whose only equi
                # condition is an expression degrades to a cartesian
                pair = self._synthetic_edge(c, parts, part_cols)
            if pair:
                edges.append(pair)
            else:
                residual.append(c)

        # deferred filter materialization: keep each part's pushed-down
        # predicate as a boolean mask and fold it into its first equi-join
        # (filtered rows hash as unmatchable), skipping one compaction sync
        # and a full-width gather per filtered part. Big parts compact
        # up front instead so join sorts don't run at raw-table width.
        masks = []
        tables = list(parts)
        for i, (p, f) in enumerate(zip(parts, filters_per_part)):
            if not f:
                masks.append(None)
            elif p.plen > _defer_filter_max_rows():
                tables[i] = self._filter_conjuncts(p, f)
                masks.append(None)
            else:
                masks.append(~self._conjunct_mask(p, f))

        # iteratively merge parts along equi edges
        groups = list(range(len(parts)))  # part index -> current table slot

        def slot(i):
            while groups[i] != i:
                i = groups[i]
            return i

        pending = list(edges)
        while pending:
            # gather every edge connecting the same two slots in one join
            by_slots = {}
            for (li, ri, lk, rk) in pending:
                sl, sr = slot(li), slot(ri)
                if sl == sr:
                    continue
                by_slots.setdefault(tuple(sorted((sl, sr))), []).append((sl, sr, lk, rk))
            if not by_slots:
                break
            # order heuristic: take PK gather edges first — they never
            # pair-expand, and their miss-masks shrink every later hash
            # join's candidate set (q72-class fact x fact joins explode
            # when run before the dimension predicates mask the facts)
            (a, b), es, gather = next(
                ((pair, pes, plan) for pair, pes in by_slots.items()
                 if (plan := self._pk_gather_plan(
                     tables, sources, pair[0], pair[1], pes)) is not None),
                (*next(iter(by_slots.items())), None))
            got = None
            if gather is not None:
                fact_slot, dim_slot, fk_names, dk_names = gather
                fact_t, dim_t = tables[fact_slot], tables[dim_slot]
                got = E.pk_gather_join_multi(
                    [fact_t[n] for n in fk_names],
                    [dim_t[n] for n in dk_names],
                    fact_t.nrows, dim_t.nrows,
                    f_excl=masks[fact_slot], d_excl=masks[dim_slot])
            if got is not None:
                r_idx, matched = got
                cols = dict(fact_t.columns)
                cols.update(E.gather_table_rows(
                    dim_t, r_idx, fact_t.nrows).columns)
                tables[a] = DeviceTable(cols, fact_t.nrows, plen=fact_t.plen)
                masks[a] = ~matched          # accumulates misses + old masks
                masks[b] = None
                sources[a] = sources[fact_slot]   # fact physical survives
            else:
                l_on = [lk if sl == a else rk for (sl, sr, lk, rk) in es]
                r_on = [rk if sl == a else lk for (sl, sr, lk, rk) in es]
                # residual conjuncts fully in scope of this pair evaluate
                # INSIDE the join (per chunk when it exceeds the pair
                # budget): the q72-class expansion is filtered before it is
                # ever materialized whole
                pair_cols = set(tables[a].column_names) | \
                    set(tables[b].column_names)
                res_here = [c for c in residual
                            if not self._has_subquery(c) and
                            self._refs_resolve_in(c, pair_cols)]
                residual = [c for c in residual if c not in res_here]
                res_fn = (lambda t, rh=res_here: self._conjunct_mask(t, rh)) \
                    if res_here else None
                tables[a] = E.join_tables(tables[a], tables[b], l_on, r_on,
                                          "inner",
                                          l_excl=masks[a], r_excl=masks[b],
                                          residual_fn=res_fn)
                masks[a] = masks[b] = None   # consumed by the join
                sources[a] = None            # physical rows are pair-expanded
            groups[b] = a
            pending = [e for e in pending if slot(e[0]) != slot(e[1])]
        # cartesian any remaining disconnected slots (materialize any
        # still-deferred mask first)
        live = sorted({slot(i) for i in range(len(parts))})
        for s in live:
            if masks[s] is not None:
                tables[s] = E.compact_table(tables[s], ~masks[s])
                masks[s] = None
        out = tables[live[0]]
        for s in live[1:]:
            out = self._cartesian(out, tables[s])
        # residual predicates apply on the fully joined result
        out = self._filter_conjuncts(out, residual)
        # synthetic join keys must not leak into SELECT * expansion
        if any(n.startswith("__jk") for n in out.column_names):
            out = out.select([n for n in out.column_names
                              if not n.startswith("__jk")])
        return out

    # ---------------------------------------------------------------- SELECT

    def select(self, sel: A.Select) -> DeviceTable:
        where_conjuncts = [h for c in self._split_conjuncts(sel.where)
                           for h in self._hoist_or_conjuncts(c)]
        # _flatten_from consumes conjuncts it pushes below outer joins
        parts, join_preds, sources = (([], [], []) if sel.from_ is None
                                      else self._flatten_from(sel.from_,
                                                              where_conjuncts))
        if sel.from_ is None:
            table = DeviceTable({}, 1, plen=E.bucket_len(1))
            table = self._filter_conjuncts(table, where_conjuncts)
        else:
            table = self._join_parts(parts, join_preds, where_conjuncts,
                                     sources)

        agg_calls = {}
        self._collect_aggs(
            [it.expr for it in sel.items] + ([sel.having] if sel.having else []),
            agg_calls)
        has_group = sel.group_by is not None
        if has_group or agg_calls:
            out, _ = self._aggregate(sel, table, agg_calls)
        else:
            ctx = EvalCtx(table)
            self._eval_windows(sel, ctx)
            self._prefuse_exprs(
                table, [it.expr for it in sel.items
                        if not isinstance(it.expr, A.Star)], ctx)
            out = self._project(sel, ctx)
        if sel.distinct:
            out = self._distinct(out)
        return out

    @staticmethod
    def _item_name(item, i: int) -> str:
        """Output name of one non-star SELECT item BEFORE collision
        renaming. Single source of truth for _project and the pruning
        side's _projected_names — they must never disagree, or projection
        pruning drops a column the star over a CTE still needs."""
        name = item.alias
        if name is None:
            if isinstance(item.expr, A.ColumnRef):
                name = item.expr.name.lower()
            elif isinstance(item.expr, A.FuncCall):
                name = f"{item.expr.name}_{i}"
            else:
                name = f"col{i}"
        return name.lower()

    @classmethod
    def _projected_names(cls, items):
        """The exact output names :meth:`_project` will emit for a SELECT
        list — including the duplicate-name ``_{i}`` suffixing — or None
        when not statically derivable (a star expansion depends on the
        input table, so callers must disable pruning)."""
        outs: list = []
        for i, item in enumerate(items):
            if isinstance(item.expr, A.Star):
                return None
            name = cls._item_name(item, i)
            if name in outs:
                name = f"{name}_{i}"
            outs.append(name)
        return outs

    def _project(self, sel: A.Select, ctx: EvalCtx) -> DeviceTable:
        cols = {}
        for i, item in enumerate(sel.items):
            if isinstance(item.expr, A.Star):
                for n, c in ctx.table.columns.items():
                    if item.expr.table and not n.startswith(item.expr.table.lower() + "."):
                        continue
                    base = n.split(".")[-1]
                    cols[base if base not in cols else n] = c
                continue
            name = self._item_name(item, i)
            if name in cols:
                name = f"{name}_{i}"
            col = self.eval_expr(item.expr, ctx)
            if len(col) != ctx.table.plen:
                raise ExecError(f"projection arity mismatch for {name}")
            cols[name] = col
            ctx.select_aliases[name] = col
        return DeviceTable(cols, ctx.table.nrows, plen=ctx.table.plen)

    # ------------------------------------------------------------ aggregation

    def _collect_aggs(self, exprs, out: dict):
        from nds_tpu.sql.parser import AGG_FUNCS

        def walk(e, in_window=False):
            if isinstance(e, A.WindowFunc):
                # the window func itself is not a group agg, but its args can be
                for a in e.func.args:
                    walk(a)
                for p in e.spec.partition_by:
                    walk(p)
                for (oe, _, _) in e.spec.order_by:
                    walk(oe)
                return
            if isinstance(e, A.FuncCall) and e.name in AGG_FUNCS:
                out[expr_key(e)] = e
                return  # no nested aggs
            for c in self._child_exprs(e):
                walk(c)
        for e in exprs:
            if e is not None:
                walk(e)

    def _aggregate(self, sel: A.Select, table: DeviceTable, agg_calls: dict):
        group_by = sel.group_by or A.GroupingSets("plain", [[]], [])
        base_ctx = EvalCtx(table)
        group_exprs = group_by.exprs
        # one fused dispatch for the group keys and every aggregate's
        # argument expression (q4/q11-class SELECTs aggregate arithmetic
        # over 4-5 columns x 8 aggregates; eager evaluation pays per-op)
        self._prefuse_exprs(
            table,
            list(group_exprs) + [c.args[0] for c in agg_calls.values()
                                 if c.args and not c.star],
            base_ctx)
        key_cols = [self.eval_expr(e, base_ctx) for e in group_exprs]
        key_names = [expr_key(e) for e in group_exprs]

        set_tables = self._rollup_fast(sel, group_by, agg_calls, base_ctx,
                                       key_cols, key_names, table)
        if set_tables is not None:
            pass
        elif not group_exprs and group_by.kind == "plain" and \
                not any(c.distinct or c.name == "approx_count_distinct"
                        for c in agg_calls.values()):
            # GLOBAL aggregate: the output row count is statically 1 and
            # SQL's empty-input semantics already live in the aggregates'
            # device-side validity (a zero-contribution group yields
            # count 0 and NULL sum/min/max) — so the input count is never
            # resolved on host. q9-class queries pay one sync per scalar
            # subquery through the generic arm; this path pays none.
            ng, cap = 1, E.bucket_len(1)
            gids = jnp.where(E.live_mask(table.plen, table.nrows),
                             0, cap).astype(jnp.int64)
            agg_vals = {akey: self._compute_agg(call, base_ctx, gids,
                                                cap, [])
                        for akey, call in agg_calls.items()}
            set_tables = [self._finish_set(sel, set(), key_names, key_cols,
                                           {}, agg_vals, ng, cap)]
        else:
            set_tables = []
            for gset in group_by.sets:
                gset_keys = [expr_key(e) for e in gset]
                active = [key_cols[i] for i, k in enumerate(key_names)
                          if k in gset_keys]
                if active:
                    # group_ids' ngroups resolve DRAINS every pending lazy
                    # count — including the input count — so the empty-
                    # input test rides the same transfer (ng == 0 iff no
                    # live input rows): ONE sync per grouping set, not two
                    gids, ng, rep, cap = E.group_ids(active,
                                                     n_valid=table.nrows)
                    if ng == 0:
                        # keyed set over empty input contributes no rows
                        continue
                else:
                    # keyless set: inside rollup/cube/grouping-sets an
                    # empty input contributes no row; a PLAIN keyless
                    # aggregate (only distinct aggs reach this arm) still
                    # yields one row over empty input. A sibling keyed
                    # set usually resolved the input count already,
                    # making this test free.
                    if E.count_int(table.nrows) == 0 and \
                            (group_by.kind != "plain" or group_exprs):
                        continue
                    # global aggregate: live rows in group 0, pads in a
                    # dropped trailing slot
                    ng, cap = 1, E.bucket_len(1)
                    gids = jnp.where(E.live_mask(table.plen, table.nrows),
                                     0, cap).astype(jnp.int64)
                    rep = jnp.zeros(cap, dtype=jnp.int64)
                group_cols = {
                    k: key_cols[i].take(rep)
                    for i, k in enumerate(key_names) if k in gset_keys}
                # aggregates (segment capacity = cap keeps shapes canonical;
                # pad contributions land past ng or are dropped)
                agg_vals = {akey: self._compute_agg(call, base_ctx, gids,
                                                    cap, active)
                            for akey, call in agg_calls.items()}
                set_tables.append(self._finish_set(
                    sel, set(gset_keys), key_names, key_cols, group_cols,
                    agg_vals, ng, cap))
        if not set_tables:
            # grouped query over empty input -> empty result with right
            # names. Keep the physical floor bucket (plen >= 16, nrows = 0):
            # a zero-length physical table would break the padded-prefix
            # invariant every downstream consumer (joins, sorts) relies on.
            cap0 = E.bucket_len(0)
            post = EvalCtx(DeviceTable({}, 0, plen=cap0), post_agg=True)
            pad_idx = jnp.full(cap0, base_ctx.table.plen, dtype=jnp.int64)
            for kname, kcol in zip(key_names, key_cols):
                post.group_values[kname] = (
                    kcol.take(pad_idx) if len(kcol)
                    else E._null_column_like(kcol, cap0))
                post.grouping_flags[kname] = 0
            gids0 = jnp.full(base_ctx.table.plen, cap0, dtype=jnp.int64)
            for akey, call in agg_calls.items():
                post.agg_values[akey] = self._compute_agg(
                    call, base_ctx, gids0, cap0, [])
            self._eval_windows(sel, post)
            out = self._project(sel, post)
            return out, post
        if len(set_tables) == 1:
            return set_tables[0]
        tables = [t for t, _ in set_tables]
        return E.concat_tables(tables), set_tables[0][1]

    def _finish_set(self, sel: A.Select, gset_keys: set, key_names, key_cols,
                    group_cols: dict, agg_vals: dict, ng: int, cap: int):
        """Build one grouping set's output: post-agg context (active keys
        from ``group_cols``, inactive keys as typed nulls, grouping flags),
        HAVING, windows, projection."""
        post = EvalCtx(DeviceTable({}, ng, plen=cap), post_agg=True)
        for kname, kcol in zip(key_names, key_cols):
            if kname in gset_keys:
                post.group_values[kname] = group_cols[kname]
                post.grouping_flags[kname] = 0
            else:
                if kcol.kind == "str":
                    null = Column("str", jnp.zeros(cap, dtype=jnp.int32),
                                  jnp.zeros(cap, dtype=bool),
                                  kcol.dict_values)
                else:
                    null = Column(kcol.kind,
                                  jnp.zeros(cap, dtype=kcol.data.dtype),
                                  jnp.zeros(cap, dtype=bool),
                                  kcol.dict_values, kcol.enc)
                post.group_values[kname] = null
                post.grouping_flags[kname] = 1
        post.agg_values.update(agg_vals)
        post.table = DeviceTable({}, ng, plen=cap)
        # HAVING before projection
        if sel.having is not None:
            mask_col = self.eval_expr(sel.having, post)
            post = self._mask_ctx(
                post, mask_col.data.astype(bool) & mask_col.valid_mask())
        self._eval_windows(sel, post)
        out = self._project(sel, post)
        return out, post

    _ROLLUP_REAGG = {"sum", "count", "avg", "min", "max"}

    def _rollup_fast(self, sel, group_by, agg_calls, base_ctx, key_cols,
                     key_names, table):
        """Hierarchical ROLLUP: grouping sets are prefixes of one another
        (finest first), so each coarser level re-aggregates the PREVIOUS
        level's partial aggregates (thousands of groups) instead of
        re-grouping the base table (millions of rows) — the rollup twin of
        partial/final aggregation. Engages when every aggregate is
        algebraically decomposable (sum/count/avg/min/max, no DISTINCT);
        returns None to fall back to the per-set generic path."""
        if group_by.kind != "rollup" or E.count_int(table.nrows) == 0:
            return None
        if not agg_calls or not all(
                c.name in self._ROLLUP_REAGG and not c.distinct
                for c in agg_calls.values()):
            return None
        expected = [[expr_key(e) for e in s] for s in group_by.sets]
        if any(ks != key_names[:len(ks)] for ks in expected) or \
                not expected or not expected[0]:
            return None
        set_tables = []
        prev = None          # (level key Columns, partials, ng, cap)
        for gkeys in expected:
            k = len(gkeys)
            if prev is None:
                gids, ng, rep, cap = E.group_ids(key_cols[:k],
                                                 n_valid=table.nrows)
                lvl_keys = [c.take(rep) for c in key_cols[:k]]
                partials = {akey: self._agg_partials(call, base_ctx, gids,
                                                     cap)
                            for akey, call in agg_calls.items()}
            else:
                p_keys, p_partials, p_ng, p_cap = prev
                if k:
                    gids, ng, rep, cap = E.group_ids(p_keys[:k], n_valid=p_ng)
                    lvl_keys = [c.take(rep) for c in p_keys[:k]]
                else:
                    ng, cap = 1, E.bucket_len(1)
                    gids = jnp.where(E.live_mask(p_cap, p_ng), 0,
                                     cap).astype(jnp.int64)
                    lvl_keys = []
                partials = {akey: self._reagg_partials(p, gids, cap)
                            for akey, p in p_partials.items()}
            agg_vals = {akey: self._finalize_partial(call, partials[akey])
                        for akey, call in agg_calls.items()}
            group_cols = dict(zip(gkeys, lvl_keys))
            set_tables.append(self._finish_set(
                sel, set(gkeys), key_names, key_cols, group_cols, agg_vals,
                ng, cap))
            prev = (lvl_keys, partials, ng, cap)
        return set_tables

    def _agg_partials(self, call: A.FuncCall, base_ctx: EvalCtx, gids, cap):
        """Decomposed (re-aggregatable) components of one aggregate at the
        finest rollup level."""
        arg = self.eval_expr(call.args[0], base_ctx) if call.args else None
        n = call.name
        if n == "count":
            return {"count": self._as_plain_count(
                E.agg_count(arg, gids, cap))}
        if n == "sum":
            return {"sum": E.agg_sum(arg, gids, cap)}
        if n == "avg":
            return {"sum": E.agg_sum(arg, gids, cap),
                    "count": self._as_plain_count(
                        E.agg_count(arg, gids, cap))}
        return {n: E.agg_min(arg, gids, cap, is_max=(n == "max"))}

    @staticmethod
    def _as_plain_count(col: Column) -> Column:
        # COUNT is never NULL: empty slots are zero, not invalid
        return Column(col.kind, col.data, None)

    def _reagg_partials(self, partials: dict, gids, cap):
        out = {}
        for part, col in partials.items():
            if part == "count":
                s = E.agg_sum(col, gids, cap)
                out[part] = Column(col.kind, s.data, None)
            elif part == "sum":
                out[part] = E.agg_sum(col, gids, cap)
            else:                                    # "min" / "max"
                out[part] = E.agg_min(col, gids, cap,
                                      is_max=(part == "max"))
        return out

    def _finalize_partial(self, call: A.FuncCall, partials: dict) -> Column:
        n = call.name
        if n in ("count", "sum", "min", "max"):
            return partials[n]
        # avg = sum / count with the decimal descale agg_avg applies
        s, c = partials["sum"], partials["count"]
        data = s.data.astype(jnp.float64)
        if s.scale:
            data = data / (10.0 ** s.scale)
        cnt = c.data.astype(jnp.float64)
        out = jnp.where(cnt > 0, data / jnp.maximum(cnt, 1.0), 0.0)
        return Column("f64", out, c.data > 0)

    def _mask_ctx(self, ctx: EvalCtx, mask) -> EvalCtx:
        """Compact an aggregation context by a boolean mask (HAVING).

        LAZY (DESIGN.md item 1): HAVING can only shrink, so the input's
        bound is a valid capacity — live rows gather to the prefix of the
        bound-sized bucket and the exact count rides as a DeviceCount,
        resolved batched by whatever downstream consumer truly needs it
        (ORDER BY/LIMIT, collect). No sync here."""
        m = mask & E.live_mask(ctx.table.plen, ctx.table.nrows)
        bound = E.count_bound(ctx.table.nrows)
        n = E.DeviceCount(jnp.sum(m), bound)
        idx = E.compact_indices(m, bound)
        new = EvalCtx(DeviceTable(
            {nm: c.take(idx) for nm, c in ctx.table.columns.items()}, n,
            plen=int(idx.shape[0])), post_agg=True)
        new.group_values = {k: c.take(idx) for k, c in ctx.group_values.items()}
        new.agg_values = {k: c.take(idx) for k, c in ctx.agg_values.items()}
        new.grouping_flags = dict(ctx.grouping_flags)
        new.window_values = {k: c.take(idx) for k, c in ctx.window_values.items()}
        return new

    def _compute_agg(self, call: A.FuncCall, base_ctx: EvalCtx, gids, ng, key_cols):
        name = call.name
        if name == "count" and call.star:
            return E.agg_count(None, gids, ng)
        arg = self.eval_expr(call.args[0], base_ctx) if call.args else None
        if call.distinct:
            # only the distinct re-grouping needs the exact host count
            # (memoized by the generic arm's resolve when it ran; the
            # sync-free global arm never reaches here with distinct)
            n_base = E.count_int(base_ctx.table.nrows)
            if name == "count":
                return self._count_distinct(arg, gids, ng, n_base)
            if name in ("sum", "avg"):
                return self._sum_avg_distinct(name, arg, gids, ng, n_base)
            # min/max distinct == plain
        if name == "count":
            return E.agg_count(arg, gids, ng)
        if name == "sum":
            return E.agg_sum(arg, gids, ng)
        if name == "avg":
            return E.agg_avg(arg, gids, ng)
        if name == "min":
            return E.agg_min(arg, gids, ng, is_max=False)
        if name == "max":
            return E.agg_min(arg, gids, ng, is_max=True)
        if name in ("stddev_samp", "stddev"):
            return E.agg_stddev_samp(arg, gids, ng)
        if name in ("var_samp", "variance"):
            sd = E.agg_stddev_samp(arg, gids, ng)
            return Column("f64", sd.data * sd.data, sd.valid)
        if name == "approx_count_distinct":
            return self._count_distinct(arg, gids, ng,
                                        E.count_int(base_ctx.table.nrows))
        raise ExecError(f"unsupported aggregate {name}")

    def _count_distinct(self, arg: Column, gids, ng, n_base: int):
        # empty-input fallback: gids comes from the zero-length path, but the
        # padded arg still has plen >= 16, so test the base row count
        if n_base == 0 or gids.shape[0] == 0:
            return Column("i64", jnp.zeros(ng, dtype=jnp.int64))
        gid_col = Column("i64", gids)
        inner_gids, inner_ng, inner_rep, inner_cap = E.group_ids(
            [gid_col, arg], n_valid=n_base)
        # inner_rep pad slots are out of range: route them to the dropped
        # segment instead of letting a clipped gather pollute a real group
        outer_at_rep = jnp.take(gids, inner_rep, mode="fill", fill_value=ng)
        valid_at_rep = jnp.take(arg.valid_mask(), inner_rep, mode="fill",
                                fill_value=False).astype(jnp.int64)
        import jax
        out = jax.ops.segment_sum(valid_at_rep, outer_at_rep, num_segments=ng)
        return Column("i64", out)

    def _sum_avg_distinct(self, name, arg: Column, gids, ng, n_base: int):
        if n_base == 0 or gids.shape[0] == 0:
            return Column("f64" if name == "avg" else arg.kind,
                          jnp.zeros(ng, dtype=jnp.float64 if name == "avg" else jnp.int64))
        gid_col = Column("i64", gids)
        inner_gids, inner_ng, inner_rep, inner_cap = E.group_ids(
            [gid_col, arg], n_valid=n_base)
        outer_at_rep = jnp.take(gids, inner_rep, mode="fill", fill_value=ng)
        rep_arg = arg.take(inner_rep)
        if name == "sum":
            return E.agg_sum(rep_arg, outer_at_rep, ng)
        return E.agg_avg(rep_arg, outer_at_rep, ng)

    # --------------------------------------------------------------- windows

    def _eval_windows(self, sel: A.Select, ctx: EvalCtx):
        """Evaluate every window function in the select list, sharing one
        WindowContext per (partition, order) spec."""
        wins = []

        def walk(e):
            if isinstance(e, A.WindowFunc):
                wins.append(e)
                return
            for c in self._child_exprs(e):
                walk(c)
        for it in sel.items:
            walk(it.expr)
        if sel.having is not None:
            walk(sel.having)
        if not wins:
            return
        contexts = {}
        for w in wins:
            skey = (tuple(expr_key(p) for p in w.spec.partition_by),
                    tuple((expr_key(e), d, nl) for e, d, nl in w.spec.order_by))
            if skey not in contexts:
                pcols = [self.eval_expr(p, ctx) for p in w.spec.partition_by]
                ocols = [self.eval_expr(e, ctx) for e, _, _ in w.spec.order_by]
                desc = [d for _, d, _ in w.spec.order_by]
                nl = [n for _, _, n in w.spec.order_by]
                contexts[skey] = WindowContext(pcols, ocols, desc, nl,
                                               n_valid=ctx.table.nrows)
            wc = contexts[skey]
            fname = w.func.name
            if fname == "row_number":
                col = wc.row_number()
            elif fname == "rank":
                col = wc.rank()
            elif fname == "dense_rank":
                col = wc.dense_rank()
            elif fname in ("sum", "avg", "min", "max", "count"):
                arg = (self.eval_expr(w.func.args[0], ctx) if w.func.args
                       else Column("i64", jnp.ones(ctx.table.plen, dtype=jnp.int64)))
                frame = w.spec.frame
                if frame is None and w.spec.order_by:
                    # SQL default with ORDER BY: RANGE UNBOUNDED PRECEDING ..
                    # CURRENT ROW (a running, not whole-partition, aggregate)
                    frame = "range_unbounded_preceding"
                if frame is not None and w.spec.order_by:
                    col = wc.running_agg(arg, fname,
                                         rows_frame=frame.startswith("rows"))
                else:
                    col = wc.partition_agg(arg, fname)
            else:
                raise ExecError(f"unsupported window function {fname}")
            ctx.window_values[expr_key(w)] = col

    # ----------------------------------------------------------- expressions

    def eval_expr(self, e, ctx: EvalCtx) -> Column:
        n = ctx.table.plen     # new columns are built at physical length
        k = expr_key(e)
        if ctx.window_values and k in ctx.window_values:
            return ctx.window_values[k]
        if ctx.post_agg:
            if k in ctx.agg_values:
                return ctx.agg_values[k]
            hit = self._lookup_group(e, ctx)
            if hit is not None:
                return hit

        if isinstance(e, A.Literal):
            # audited-bindable slots replay from jit operands (one
            # compile, many parameter vectors); everything else bakes.
            bound = X.bound_literal(e, n)
            if bound is not None:
                return bound
            return X.literal(e.value, n)
        if isinstance(e, A.DateLiteral):
            days = X.parse_date_literal(e.text)
            return Column("date", jnp.full(n, days, dtype=jnp.int32))
        if isinstance(e, A.ColumnRef):
            return self._eval_column_ref(e, ctx)
        if isinstance(e, A.UnaryOp):
            if e.op == "not":
                return X.logical_not(self.eval_expr(e.operand, ctx))
            return X.negate(self.eval_expr(e.operand, ctx))
        if isinstance(e, A.BinaryOp):
            return self._eval_binary(e, ctx)
        if isinstance(e, A.Between):
            v = self.eval_expr(e.expr, ctx)
            lo = self.eval_expr(e.low, ctx)
            hi = self.eval_expr(e.high, ctx)
            v1, lo = self._coerce_pair(v, lo)
            v2, hi = self._coerce_pair(v, hi)
            res = X.logical_and(X.compare(">=", v1, lo), X.compare("<=", v2, hi))
            return X.logical_not(res) if e.negated else res
        if isinstance(e, A.InList):
            return self._eval_in_list(e, ctx)
        if isinstance(e, A.InSubquery):
            return self._eval_in_subquery(e, ctx)
        if isinstance(e, A.Exists):
            return self._eval_exists(e, ctx)
        if isinstance(e, A.ScalarSubquery):
            return self._eval_scalar_subquery(e, ctx)
        if isinstance(e, A.QuantifiedCompare):
            return self._eval_quantified(e, ctx)
        if isinstance(e, A.Like):
            col = self.eval_expr(e.expr, ctx)
            return X.fn_like(col, e.pattern, e.negated)
        if isinstance(e, A.IsNull):
            return X.is_null(self.eval_expr(e.expr, ctx), e.negated)
        if isinstance(e, A.Case):
            return self._eval_case(e, ctx)
        if isinstance(e, A.Cast):
            return X.cast(self.eval_expr(e.expr, ctx), e.target)
        if isinstance(e, A.FuncCall):
            return self._eval_func(e, ctx)
        if isinstance(e, A.WindowFunc):
            raise ExecError("window function outside select list")
        raise ExecError(f"unsupported expression {type(e).__name__}")

    def _lookup_group(self, e, ctx: EvalCtx):
        """Match an expression against the grouped key columns, tolerating
        qualified/unqualified column-ref mismatches."""
        k = expr_key(e)
        if k in ctx.group_values:
            return ctx.group_values[k]
        if isinstance(e, A.ColumnRef):
            suffix = f".{e.name.lower()}"
            hits = [v for gk, v in ctx.group_values.items()
                    if gk.startswith("col:") and gk.endswith(suffix)]
            if len(hits) == 1:
                return hits[0]
            if e.table:  # qualified ref vs unqualified group key
                alt = f"col:.{e.name.lower()}"
                if alt in ctx.group_values:
                    return ctx.group_values[alt]
        return None

    def _lookup_grouping_flag(self, e, ctx: EvalCtx):
        k = expr_key(e)
        if k in ctx.grouping_flags:
            return ctx.grouping_flags[k]
        if isinstance(e, A.ColumnRef):
            suffix = f".{e.name.lower()}"
            hits = [v for gk, v in ctx.grouping_flags.items()
                    if gk.startswith("col:") and gk.endswith(suffix)]
            if len(hits) == 1:
                return hits[0]
        raise ExecError(f"grouping() argument is not a grouping column")

    def _eval_column_ref(self, e: A.ColumnRef, ctx: EvalCtx) -> Column:
        key = self._resolve_name(e, set(ctx.table.column_names))
        if key is not None:
            return ctx.table[key]
        if not e.table and e.name.lower() in ctx.select_aliases:
            return ctx.select_aliases[e.name.lower()]
        if ctx.post_agg:
            hit = self._lookup_group(e, ctx)
            if hit is not None:
                return hit
        # ORDER BY over projected output: a qualified ref (dt.d_year) still
        # addresses the bare output column name
        if e.table and e.name.lower() in ctx.select_aliases:
            return ctx.select_aliases[e.name.lower()]
        raise ExecError(f"cannot resolve column "
                        f"{(e.table + '.') if e.table else ''}{e.name}")

    def _coerce_pair(self, a: Column, b: Column):
        """Type coercions the corpus relies on: string literal vs date."""
        if a.kind == "date" and b.kind == "str":
            return a, X.cast(b, "date")
        if b.kind == "date" and a.kind == "str":
            return X.cast(a, "date"), b
        return a, b

    def _eval_binary(self, e: A.BinaryOp, ctx: EvalCtx) -> Column:
        if e.op == "and":
            return X.logical_and(self.eval_expr(e.left, ctx),
                                 self.eval_expr(e.right, ctx))
        if e.op == "or":
            return X.logical_or(self.eval_expr(e.left, ctx),
                                self.eval_expr(e.right, ctx))
        # interval date arithmetic
        if isinstance(e.right, A.IntervalLiteral):
            base = self.eval_expr(e.left, ctx)
            return self._add_interval(base, e.right, negate=(e.op == "-"))
        if isinstance(e.left, A.IntervalLiteral):
            base = self.eval_expr(e.right, ctx)
            return self._add_interval(base, e.left, negate=False)
        a = self.eval_expr(e.left, ctx)
        b = self.eval_expr(e.right, ctx)
        if e.op == "||":
            return X.fn_concat([a, b])
        a, b = self._coerce_pair(a, b)
        if e.op in ("=", "<>", "<", "<=", ">", ">="):
            return X.compare(e.op, a, b)
        return X.arith(e.op, a, b)

    def _add_interval(self, base: Column, iv: A.IntervalLiteral, negate: bool) -> Column:
        amt = -iv.amount if negate else iv.amount
        if base.kind == "str":
            base = X.cast(base, "date")
        base = E.plain_col(base)
        if iv.unit == "day":
            return Column("date", (base.data + amt).astype(base.data.dtype), base.valid)
        # month/year arithmetic via numpy calendar math on host (a whole-
        # column fetch — routed through the trace-replay log)
        def fetch():
            days = np.asarray(base.data)
            months = amt * (12 if iv.unit == "year" else 1)
            d64 = _EPOCH64 + days.astype("timedelta64[D]")
            m = d64.astype("datetime64[M]")
            dom = (d64 - m.astype("datetime64[D]")).astype(int)
            shifted_m = m + np.timedelta64(months, "M")
            next_m = shifted_m + np.timedelta64(1, "M")
            last_dom = ((next_m.astype("datetime64[D]")
                         - np.timedelta64(1, "D"))
                        - shifted_m.astype("datetime64[D]")).astype(int)
            new_dom = np.minimum(dom, last_dom)
            out = (shifted_m.astype("datetime64[D]")
                   - _EPOCH64).astype(int) + new_dom
            return out.astype(np.int32)

        out = E.timed_read("month_arith", fetch)
        return Column("date", jnp.asarray(out), base.valid)

    def _eval_in_list(self, e: A.InList, ctx: EvalCtx) -> Column:
        col = self.eval_expr(e.expr, ctx)
        values = []
        for item in e.items:
            if not isinstance(item, A.Literal):
                # general fallback: OR of equalities
                res = None
                for it in e.items:
                    cmp = X.compare("=", col, self.eval_expr(it, ctx))
                    res = cmp if res is None else X.logical_or(res, cmp)
                return X.logical_not(res) if e.negated else res
            values.append(item.value)
        has_null = any(v is None for v in values)
        values = [v for v in values if v is not None]
        if e.negated and has_null:
            # ANSI: NOT IN with a NULL in the list is never true
            return Column("bool", jnp.zeros(len(col), dtype=bool))
        col = E.plain_col(col)
        if col.kind == "str":
            res = X.fn_in_strings(col, [str(v) for v in values])
        elif col.kind == "f64":
            data = jnp.isin(col.data, jnp.asarray(
                [float(v) for v in values], dtype=jnp.float64))
            res = Column("bool", data, col.valid)
        else:
            from decimal import Decimal
            scale = col.scale
            nums = []
            for v in values:
                if not isinstance(v, Decimal):
                    if not isinstance(v, (int, float)):
                        raise ExecError(f"bad IN-list literal {v!r}")
                    v = Decimal(str(v))
                scaled = v.scaleb(scale)
                # a literal that is fractional at this column's scale can
                # never match an int/decimal column — drop it, don't round
                if scaled == scaled.to_integral_value():
                    nums.append(int(scaled))
            if not nums:
                res = Column("bool", jnp.zeros(len(col), dtype=bool), col.valid)
            else:
                data = jnp.isin(col.data, jnp.asarray(nums, dtype=jnp.int64))
                res = Column("bool", data, col.valid)
        return X.logical_not(res) if e.negated else res

    def _eval_case(self, e: A.Case, ctx: EvalCtx) -> Column:
        n = ctx.table.plen
        branches = []
        if e.operand is not None:
            op = self.eval_expr(e.operand, ctx)
            for cond, res in e.branches:
                c = X.compare("=", op, self.eval_expr(cond, ctx))
                branches.append((c, self.eval_expr(res, ctx)))
        else:
            for cond, res in e.branches:
                branches.append((self.eval_expr(cond, ctx),
                                 self.eval_expr(res, ctx)))
        else_col = (self.eval_expr(e.else_, ctx) if e.else_ is not None
                    else X.literal(None, n))
        return X.case_when(branches, else_col)

    def _eval_func(self, e: A.FuncCall, ctx: EvalCtx) -> Column:
        name = e.name
        n = ctx.table.plen
        if name == "grouping":
            flag = self._lookup_grouping_flag(e.args[0], ctx)
            return Column("i64", jnp.full(n, flag, dtype=jnp.int64))
        if name in ("substr", "substring"):
            col = self.eval_expr(e.args[0], ctx)
            start = self._const_int(e.args[1])
            length = self._const_int(e.args[2]) if len(e.args) > 2 else None
            return X.fn_substr(col, start, length)
        if name == "coalesce":
            return X.coalesce([self.eval_expr(a, ctx) for a in e.args])
        if name == "nullif":
            a = self.eval_expr(e.args[0], ctx)
            b = self.eval_expr(e.args[1], ctx)
            eq = X.compare("=", a, b)
            new_valid = a.valid_mask() & ~(eq.data.astype(bool) & eq.valid_mask())
            return Column(a.kind, a.data, new_valid, a.dict_values, a.enc)
        if name in ("abs",):
            return X.fn_abs(self.eval_expr(e.args[0], ctx))
        if name == "round":
            col = self.eval_expr(e.args[0], ctx)
            digits = self._const_int(e.args[1]) if len(e.args) > 1 else 0
            return X.fn_round(col, digits)
        if name == "floor":
            return X.fn_floor(self.eval_expr(e.args[0], ctx))
        if name in ("ceil", "ceiling"):
            return X.fn_ceil(self.eval_expr(e.args[0], ctx))
        if name == "sqrt":
            return X.fn_sqrt(self.eval_expr(e.args[0], ctx))
        if name in ("upper", "ucase"):
            return X.fn_upper(self.eval_expr(e.args[0], ctx))
        if name in ("lower", "lcase"):
            return X.fn_lower(self.eval_expr(e.args[0], ctx))
        if name == "trim":
            return X.fn_trim(self.eval_expr(e.args[0], ctx))
        if name in ("length", "char_length", "character_length"):
            return X.fn_length(self.eval_expr(e.args[0], ctx))
        if name == "concat":
            return X.fn_concat([self.eval_expr(a, ctx) for a in e.args])
        if name in ("year", "month", "day", "dayofmonth"):
            col = self.eval_expr(e.args[0], ctx)
            return self._date_part(col, "day" if name == "dayofmonth" else name)
        if name in ("d_date", ):
            pass
        raise ExecError(f"unsupported function {name}")

    def _date_part(self, col: Column, part: str) -> Column:
        col = E.plain_col(col)
        def fetch():
            # host calendar math on the whole column — replay-logged
            days = np.asarray(col.data)
            d64 = _EPOCH64 + days.astype("timedelta64[D]")
            y = d64.astype("datetime64[Y]").astype(int) + 1970
            if part == "year":
                out = y
            else:
                m_idx = d64.astype("datetime64[M]").astype(int)
                month = m_idx % 12 + 1
                if part == "month":
                    out = month
                else:
                    dom = (d64 - d64.astype("datetime64[M]")
                           .astype("datetime64[D]")).astype(int) + 1
                    out = dom
            return out.astype(np.int64)

        return Column("i64", jnp.asarray(E.timed_read("date_part", fetch)),
                      col.valid)

    def _const_int(self, e) -> int:
        if isinstance(e, A.Literal) and isinstance(e.value, int):
            return e.value
        if isinstance(e, A.UnaryOp) and e.op == "-":
            return -self._const_int(e.operand)
        raise ExecError("expected integer literal argument")

    # -------------------------------------------------------- subquery plans

    def _select_output_cols(self, from_) -> set:
        """Alias-qualified column names a FROM clause exposes, without
        executing it (for correlation analysis)."""
        out = set()
        if isinstance(from_, A.TableRef):
            alias = (from_.alias or from_.name).lower()
            try:
                cols = self._lookup_table(from_.name).column_names
            except ExecError:
                # the traced per-chunk planner has an EMPTY catalog; its
                # correlation analysis must still resolve subquery scopes
                # exactly like the record phase did, so the pipeline seeds
                # a NAMES-ONLY snapshot of the record-time catalog
                nc = getattr(self, "name_catalog", None)
                cols = (nc or {}).get(from_.name.lower())
                if cols is None:
                    return out
            for c in cols:
                out.add(f"{alias}.{c.split('.')[-1].lower()}")
        elif isinstance(from_, A.SubqueryRef):
            body = from_.query.body
            names = self._query_output_names(from_.query)
            for nm in names:
                out.add(f"{from_.alias.lower()}.{nm}")
        elif isinstance(from_, A.Join):
            out |= self._select_output_cols(from_.left)
            out |= self._select_output_cols(from_.right)
        return out

    def _query_output_names(self, q: A.Query) -> list:
        body = q.body
        while isinstance(body, A.SetOp):
            body = body.left
        if isinstance(body, A.Query):
            return self._query_output_names(body)
        names = []
        for i, it in enumerate(body.items):
            if isinstance(it.expr, A.Star):
                cols = self._select_output_cols(body.from_)
                names.extend(sorted({c.split(".")[-1] for c in cols}))
            elif it.alias:
                names.append(it.alias.lower())
            elif isinstance(it.expr, A.ColumnRef):
                names.append(it.expr.name.lower())
            else:
                names.append(f"col{i}")
        return names

    # -------------------------------------------- subquery residuals
    # Multi-pass streaming, mechanism (a): a subquery nested in a streamed
    # graph's conjuncts is CHUNK-INVARIANT once decorrelated (its plan
    # references only its own tables), so the pipeline streams the inner
    # query FIRST — eagerly, outside the recording, with its own compiled
    # pipeline if the inner binds a chunked scan — into a device-resident
    # residual, then records/drives the outer scan with the residual as an
    # ordinary device operand. Two compiled pipelines, one materializing
    # sync each, chained without a host round trip per chunk.

    def _residual_key(self, payload) -> str:
        return payload[0] + "|" + "|".join(
            expr_key(x) if x is not None else "-" for x in payload[1:])

    def _plan_residual(self, payload):
        """Plan one subquery residual with the real planner/catalog."""
        if payload[0] == "query":
            return self.query(payload[1])
        # ("exists_inner", from_, where): correlated EXISTS with a
        # non-equality residual (q16/q94) — the inner join graph,
        # stripped of its correlation conjuncts, materialized whole
        _tag, from_, where = payload
        parts, preds, srcs = self._flatten_from(from_)
        return self._join_parts(parts, preds,
                                self._split_conjuncts(where), srcs)

    def _residual_table(self, payload) -> DeviceTable:
        """The device-resident residual of one chunk-invariant subquery,
        planned at most once per statement. Inside a record phase the
        inner plan runs under ``ops.suspend_stream_record()`` — its host
        reads must never interleave with the outer recording, and freed
        of the stream-bounds guard it may sync (once) or stream through
        its own compiled pipeline. Inside the traced per-chunk program
        the registry is pre-seeded from the pipeline's operands; a miss
        there means the pipeline cannot serve the statement
        (StreamSyncError => eager fallback)."""
        key = self._residual_key(payload)
        hit = self._subquery_residuals.get(key)
        if hit is None:
            if E.stream_bounds_on():
                if E.replay_mode() == "replay":
                    raise E.StreamSyncError(
                        f"unplanned subquery residual {key[:80]}")
                with E.suspend_stream_record():
                    rt = E.resolve_table(self._plan_residual(payload))
            else:
                # outside a pipeline the residual stays LAZY (a q9-class
                # projection subquery must keep its no-sync broadcast
                # arm); the registry still dedupes repeated subqueries
                # and caches across eager chunks
                rt = self._plan_residual(payload)
            hit = (payload, rt)
            self._subquery_residuals[key] = hit
        if self._residuals_touched is not None and \
                E.stream_bounds_on() and E.replay_mode() == "record" and \
                all(k != key for (k, _p, _t) in self._residuals_touched):
            self._residuals_touched.append((key, hit[0], hit[1]))
        return hit[1]

    def _find_correlation(self, q: A.Query, ctx: EvalCtx):
        """Detect equality correlation between a subquery and the outer row.

        Returns (corr_pairs, stripped_query) where corr_pairs is a list of
        (outer ColumnRef, inner Expr); or None if uncorrelated."""
        if not isinstance(q.body, A.Select) or q.ctes:
            return None
        sel = q.body
        if sel.from_ is None:
            return None
        inner_cols = self._select_output_cols(sel.from_)
        outer_cols = set(ctx.table.column_names)
        # hoist common conjuncts out of ORs first: q41's correlation equality
        # appears as (i_manufact = i1.i_manufact and X) or (i_manufact =
        # i1.i_manufact and Y)
        conjs = [h for c in self._split_conjuncts(sel.where)
                 for h in self._hoist_or_conjuncts(c)]
        corr, keep, residual = [], [], []
        for c in conjs:
            pair = None
            if isinstance(c, A.BinaryOp) and c.op == "=" and \
                    isinstance(c.left, A.ColumnRef) and isinstance(c.right, A.ColumnRef):
                l_in = self._resolve_name(c.left, inner_cols)
                r_in = self._resolve_name(c.right, inner_cols)
                l_out = self._resolve_name(c.left, outer_cols)
                r_out = self._resolve_name(c.right, outer_cols)
                if l_in is None and l_out is not None and r_in is not None:
                    pair = (c.left, c.right)
                elif r_in is None and r_out is not None and l_in is not None:
                    pair = (c.right, c.left)
            if pair:
                corr.append(pair)
            elif all(self._resolve_name(r, inner_cols)
                     for r in self._column_refs(c)):
                keep.append(c)
            else:
                # references both scopes without being an equality (e.g.
                # q16's cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
                residual.append(c)
        if not corr:
            return None
        new_where = None
        for c in keep:
            new_where = c if new_where is None else A.BinaryOp("and", new_where, c)
        stripped = A.Query(
            A.Select(sel.items, sel.from_, new_where, sel.group_by, sel.having,
                     sel.distinct),
            [], None, [])
        return corr, stripped, residual

    def _eval_exists(self, e: A.Exists, ctx: EvalCtx) -> Column:
        n = ctx.table.plen
        found = self._find_correlation(e.query, ctx)
        if found is None:
            t = self._residual_table(("query", e.query))
            val = E.count_int(t.nrows) > 0
            res = Column("bool", jnp.full(n, val, dtype=bool))
            return X.logical_not(res) if e.negated else res
        corr, stripped, residual = found
        sel = stripped.body
        if residual:
            # non-equality correlated conjuncts (q16/q94: cs1.x <> cs2.x):
            # match pairs on the equality keys, then evaluate the residual on
            # the joined pair table
            if sel.group_by or sel.having:
                raise ExecError("correlated EXISTS with residual predicate "
                                "and grouping unsupported")
            inner_t = self._residual_table(("exists_inner", sel.from_,
                                            sel.where))
            lkeys = [self.eval_expr(outer, ctx) for outer, _ in corr]
            rkeys = [self.eval_expr(inner, EvalCtx(inner_t))
                     for _, inner in corr]
            l_idx, r_idx, n_pairs, _, _, _, _ = E.join_indices(
                lkeys, rkeys, "inner",
                n_left=ctx.table.nrows, n_right=inner_t.nrows)
            pair_cols = dict(E.gather_table_rows(
                inner_t, r_idx, n_pairs).columns)
            outer_g = E.gather_table_rows(ctx.table, l_idx, n_pairs).columns
            for nm, c in outer_g.items():
                pair_cols.setdefault(nm, c)
            pairs = DeviceTable(pair_cols, n_pairs)
            ok = self._conjunct_mask(pairs, residual)
            ok = ok & E.live_mask(pairs.plen, pairs.nrows)
            safe = jnp.where(ok, l_idx, n)
            matched = jnp.zeros(n, dtype=bool).at[safe].set(True, mode="drop")
            return Column("bool", ~matched if e.negated else matched)
        inner_items = [A.SelectItem(inner, f"_ck{i}")
                       for i, (_, inner) in enumerate(corr)]
        sub = A.Query(A.Select(inner_items, sel.from_, sel.where, sel.group_by,
                               sel.having, True), [], None, [])
        rt = self._residual_table(("query", sub))
        lkeys = [self.eval_expr(outer, ctx) for outer, _ in corr]
        rkeys = [rt[c] for c in rt.column_names]
        mask = E.semi_join_mask(lkeys, rkeys, negate=e.negated,
                                n_left=ctx.table.nrows, n_right=rt.nrows)
        return Column("bool", mask)

    def _eval_in_subquery(self, e: A.InSubquery, ctx: EvalCtx) -> Column:
        found = self._find_correlation(e.query, ctx)
        if found is None:
            rt = self._residual_table(("query", e.query))
            rcol = rt[rt.column_names[0]]
            lcol = self.eval_expr(e.expr, ctx)
            lcol2, rcol2 = self._coerce_pair(lcol, rcol)
            mask = E.semi_join_mask([lcol2], [rcol2], negate=e.negated,
                                    n_left=ctx.table.nrows, n_right=rt.nrows)
            if e.negated:
                # ANSI NOT IN: any NULL on the right makes the predicate
                # NULL (never true); a NULL lhs is NULL too
                if rcol2.null_count(rt.nrows) > 0:
                    return Column("bool", jnp.zeros(len(lcol2), dtype=bool))
                return Column("bool", mask & lcol2.valid_mask())
            return Column("bool", mask)
        corr, stripped, residual = found
        if residual:
            raise ExecError("correlated subquery with non-equality correlation unsupported here")
        sel = stripped.body
        items = [sel.items[0]] + [A.SelectItem(inner, f"_ck{i}")
                                  for i, (_, inner) in enumerate(corr)]
        sub = A.Query(A.Select(items, sel.from_, sel.where, sel.group_by,
                               sel.having, True), [], None, [])
        rt = self._residual_table(("query", sub))
        rcols = [rt[c] for c in rt.column_names]
        lcols = [self.eval_expr(e.expr, ctx)] + \
            [self.eval_expr(outer, ctx) for outer, _ in corr]
        lcols2 = []
        for lc, rc in zip(lcols, rcols):
            lc2, _ = self._coerce_pair(lc, rc)
            lcols2.append(lc2)
        mask = E.semi_join_mask(lcols2, rcols, n_left=ctx.table.nrows,
                                n_right=rt.nrows)
        if not e.negated:
            return Column("bool", mask)
        # ANSI NOT IN per correlation group: a NULL lhs, or any NULL value in
        # the row's matching group, makes the predicate NULL (never true)
        keep = ~mask & lcols2[0].valid_mask() & \
            E.live_mask(ctx.table.plen, ctx.table.nrows)
        val_col = rcols[0]
        n_nulls = val_col.null_count(rt.nrows)
        if n_nulls > 0:
            nullm = ~val_col.valid_mask() & E.live_mask(rt.plen, rt.nrows)
            null_rows = E.compact_indices(nullm, n_nulls)
            null_keys = [c.take(null_rows) for c in rcols[1:]]
            group_has_null = E.semi_join_mask(
                lcols2[1:], null_keys, n_left=ctx.table.nrows, n_right=n_nulls)
            keep = keep & ~group_has_null
        return Column("bool", keep)

    def _eval_scalar_subquery(self, e: A.ScalarSubquery, ctx: EvalCtx) -> Column:
        n = ctx.table.plen
        found = self._find_correlation(e.query, ctx)
        if found is None:
            rt = self._residual_table(("query", e.query))
            col = rt[rt.column_names[0]]
            if isinstance(rt.nrows, E.DeviceCount):
                # LAZY scalar: broadcast row 0 with device-side validity
                # (empty subquery -> NULL via nd >= 1); the "more than one
                # row" error check rides the next batched resolution
                # instead of spending a sync here (q58-class queries pay
                # one per scalar subquery otherwise)
                nd = rt.nrows.dev
                ok = nd >= 1
                if col.valid is not None:
                    ok = ok & col.valid[0]
                data = jnp.broadcast_to(col.data[0], (n,))
                valid = jnp.broadcast_to(ok, (n,))

                def check(v):
                    if v > 1:
                        raise ExecError(
                            "scalar subquery returned more than one row")

                E.defer_check(rt.nrows, check)
                return Column(col.kind, data, valid, col.dict_values,
                              col.enc)
            n_rt = E.count_int(rt.nrows)     # host semantics: exact count
            if n_rt == 0:
                return X.literal(None, n)
            if n_rt != 1:
                raise ExecError("scalar subquery returned more than one row")
            data = jnp.broadcast_to(col.data[0], (n,))
            valid = None
            if col.valid is not None:
                valid = jnp.broadcast_to(col.valid[0], (n,))
            return Column(col.kind, data, valid, col.dict_values, col.enc)
        corr, stripped, residual = found
        if residual:
            raise ExecError("correlated subquery with non-equality correlation unsupported here")
        sel = stripped.body
        # grouped-by-correlation-keys aggregate, left-joined back to the outer
        items = [sel.items[0]] + [A.SelectItem(inner, f"_ck{i}")
                                  for i, (_, inner) in enumerate(corr)]
        gexprs = (sel.group_by.exprs if sel.group_by else []) + \
            [inner for _, inner in corr]
        sub = A.Query(A.Select(items, sel.from_, sel.where,
                               A.GroupingSets("plain", [gexprs], gexprs),
                               sel.having, False), [], None, [])
        rt = self._residual_table(("query", sub))
        val_col = rt[rt.column_names[0]]
        rkeys = [rt[c] for c in rt.column_names[1:1 + len(corr)]]
        lkeys = [self.eval_expr(outer, ctx) for outer, _ in corr]
        lkeys = [self._coerce_pair(lc, rc)[0] for lc, rc in zip(lkeys, rkeys)]
        l_idx, r_idx, n_pairs, _, _, _, _ = E.join_indices(
            lkeys, rkeys, "inner", n_left=ctx.table.nrows, n_right=rt.nrows)
        # the subquery was grouped by its correlation keys, so each outer row
        # may match at most once; more than one match means the original
        # subquery was not scalar per outer row
        hits = jnp.zeros(n, dtype=jnp.int32).at[l_idx].add(1, mode="drop")
        # pad pairs drop out of the scatter, so max(hits) alone detects a
        # non-scalar subquery; one counted, batch-draining host read.
        # Inside the compiled per-chunk program the check rides the
        # overflow channel instead (a flagged chunk reruns eagerly, where
        # this arm raises the real error — bit-for-bit semantics)
        if E.stream_bounds_on():
            E.stream_overflow(jnp.max(hits) > 1)
        elif E.DeviceCount(jnp.max(hits), n).to_int() > 1:
            raise ExecError("correlated scalar subquery returned more than one "
                            "row per outer row")
        data = jnp.zeros(n, dtype=val_col.data.dtype)
        valid = jnp.zeros(n, dtype=bool)
        data = data.at[l_idx].set(jnp.take(val_col.data, r_idx), mode="drop")
        valid = valid.at[l_idx].set(jnp.take(val_col.valid_mask(), r_idx),
                                    mode="drop")
        return Column(val_col.kind, data, valid, val_col.dict_values,
                      val_col.enc)

    def _eval_quantified(self, e: A.QuantifiedCompare, ctx: EvalCtx) -> Column:
        n = ctx.table.plen
        if e.op == "=" and e.quantifier == "any":
            return self._eval_in_subquery(A.InSubquery(e.expr, e.query, False), ctx)
        if e.op == "<>" and e.quantifier == "all":
            return self._eval_in_subquery(A.InSubquery(e.expr, e.query, True), ctx)
        rt = self._residual_table(("query", e.query))
        col = rt[rt.column_names[0]]
        lhs = self.eval_expr(e.expr, ctx)
        if E.count_int(rt.nrows) == 0:
            val = e.quantifier == "all"
            return Column("bool", jnp.full(n, val, dtype=bool))
        # live rows reduce into segment 0; pads go to the dropped segment
        gids = jnp.where(E.live_mask(rt.plen, rt.nrows), 0, 1).astype(jnp.int64)

        def broadcast(red):
            return Column(red.kind, jnp.broadcast_to(red.data[0], (n,)),
                          None if red.valid is None
                          else jnp.broadcast_to(red.valid[0], (n,)),
                          red.dict_values, red.enc)

        if e.op in ("=", "<>"):
            # = ALL: every value equals lhs  <=>  min = lhs AND max = lhs
            # <> ANY: some value differs     <=>  NOT (= ALL)
            mn = broadcast(E.agg_min(col, gids, 1))
            mx = broadcast(E.agg_min(col, gids, 1, is_max=True))
            all_eq = X.logical_and(X.compare("=", lhs, mn),
                                   X.compare("=", lhs, mx))
            return all_eq if e.op == "=" else X.logical_not(all_eq)
        use_max = (e.op in (">", ">=")) == (e.quantifier == "all") or \
                  (e.op in ("<", "<=") and e.quantifier == "any")
        scalar = broadcast(E.agg_min(col, gids, 1, is_max=use_max))
        return X.compare(e.op, lhs, scalar)


_EPOCH64 = np.datetime64("1970-01-01", "D")
