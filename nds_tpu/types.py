# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Canonical type system shared by schema, IO, and the device engine.

Canonical type strings (see :mod:`nds_tpu.schema`):

    int32 | int64 | double | date | string | char(N) | varchar(N) | decimal(P,S)

Three lowerings live here:

- ``to_arrow``: canonical -> pyarrow DataType (file/interchange representation)
- ``device_kind``: canonical -> how the column lives on device:
    * ``i32`` / ``i64``    : plain integers
    * ``date``             : int32 days-since-epoch
    * ``dec(P,S)``         : int64 scaled fixed point (value * 10**S) — exact
                             decimal arithmetic on the MXU-adjacent int path,
                             replacing the reference's Spark Decimal
                             (ref: nds/nds_schema.py:43-47)
    * ``f64``              : float64
    * ``str``              : dictionary codes (int32) + host-side value table
"""

from __future__ import annotations

import pyarrow as pa

# Pure-string type predicates live with the schema (pyarrow-free); re-exported
# here so IO/engine code has a single import site.
from nds_tpu.schema import decimal_precision_scale, is_decimal, is_string  # noqa: F401


def to_arrow(t: str) -> pa.DataType:
    """Canonical type -> pyarrow DataType used in Parquet/ORC/CSV files."""
    if t == "int32":
        return pa.int32()
    if t == "int64":
        return pa.int64()
    if t == "double":
        return pa.float64()
    if t == "date":
        return pa.date32()
    if is_string(t):
        return pa.string()
    if is_decimal(t):
        p, s = decimal_precision_scale(t)
        return pa.decimal128(p, s)
    raise ValueError(f"unknown canonical type: {t}")


def device_kind(t: str) -> str:
    """Canonical type -> device representation tag."""
    if t == "int32":
        return "i32"
    if t == "int64":
        return "i64"
    if t == "double":
        return "f64"
    if t == "date":
        return "date"
    if is_string(t):
        return "str"
    if is_decimal(t):
        p, s = decimal_precision_scale(t)
        return f"dec({p},{s})"
    raise ValueError(f"unknown canonical type: {t}")


def arrow_to_canonical(dt: pa.DataType) -> str:
    if pa.types.is_int32(dt):
        return "int32"
    if pa.types.is_int64(dt):
        return "int64"
    if pa.types.is_float64(dt) or pa.types.is_float32(dt):
        return "double"
    if pa.types.is_date(dt):
        return "date"
    if pa.types.is_string(dt) or pa.types.is_large_string(dt) or pa.types.is_dictionary(dt):
        return "string"
    if pa.types.is_decimal(dt):
        return f"decimal({dt.precision},{dt.scale})"
    if pa.types.is_timestamp(dt):
        return "date"
    raise ValueError(f"unsupported arrow type: {dt}")
