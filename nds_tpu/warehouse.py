# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Snapshot warehouse: the mutable-table layer Data Maintenance needs.

Plays the role Iceberg/Delta play for the reference (snapshot isolation for
INSERT/DELETE refresh functions, and time-travel rollback; ref:
nds/nds_maintenance.py:191-268 writes into an Iceberg/Delta warehouse and
nds/nds_rollback.py:46-50 calls ``rollback_to_timestamp``). Layout per table:

    <root>/<table>/snap-<id>.parquet       immutable full-table snapshots
    <root>/<table>/metadata.json           snapshot log (id, timestamp_ms, file)

Each mutation (create / insert / delete-rewrite) lands a new full snapshot and
appends to the log; ``read`` serves the latest, ``rollback_to_timestamp``
truncates the log back to the last snapshot at-or-before the timestamp. Full
(not delta) snapshots keep the commit path one parquet write — the NDS
refresh sets are ~0.1% of the base facts, and the spec times the refresh
function, not compaction.
"""

from __future__ import annotations

import json
import os
import shutil
import time

import pyarrow as pa

from nds_tpu.io.columnar import read_table, write_table


class WarehouseError(RuntimeError):
    pass


class Warehouse:
    def __init__(self, root: str, fmt: str = "parquet"):
        self.root = root
        self.fmt = fmt
        os.makedirs(root, exist_ok=True)

    # -- metadata -----------------------------------------------------------

    def _meta_path(self, table: str) -> str:
        return os.path.join(self.root, table, "metadata.json")

    def _load_meta(self, table: str) -> dict:
        path = self._meta_path(table)
        if not os.path.exists(path):
            raise WarehouseError(f"table '{table}' does not exist in {self.root}")
        with open(path) as f:
            return json.load(f)

    def _store_meta(self, table: str, meta: dict) -> None:
        tmp = self._meta_path(table) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=1)
        os.replace(tmp, self._meta_path(table))

    def _commit(self, table: str, arrow: pa.Table, meta: dict) -> None:
        snap_id = (meta["snapshots"][-1]["id"] + 1) if meta["snapshots"] else 0
        fname = f"snap-{snap_id}.{self.fmt}"
        write_table(arrow, os.path.join(self.root, table, fname), self.fmt)
        meta["snapshots"].append({
            "id": snap_id,
            "timestamp_ms": int(time.time() * 1000),
            "file": fname,
        })
        self._store_meta(table, meta)

    # -- public surface ------------------------------------------------------

    def tables(self) -> list:
        return sorted(
            d for d in os.listdir(self.root)
            if os.path.exists(self._meta_path(d)))

    def exists(self, table: str) -> bool:
        return os.path.exists(self._meta_path(table))

    def create(self, table: str, arrow: pa.Table) -> None:
        os.makedirs(os.path.join(self.root, table), exist_ok=True)
        meta = {"snapshots": []}
        self._commit(table, arrow, meta)

    def read(self, table: str, snapshot_id: int | None = None) -> pa.Table:
        meta = self._load_meta(table)
        snaps = meta["snapshots"]
        if not snaps:
            raise WarehouseError(f"table '{table}' has no snapshots")
        snap = snaps[-1]
        if snapshot_id is not None:
            matches = [s for s in snaps if s["id"] == snapshot_id]
            if not matches:
                raise WarehouseError(
                    f"table '{table}' has no snapshot id {snapshot_id}")
            snap = matches[0]
        return read_table(os.path.join(self.root, table, snap["file"]), self.fmt)

    @staticmethod
    def _cast_like(arrow: pa.Table, schema: pa.Schema) -> pa.Table:
        """Align column order and types with the table schema. Decimal
        expressions widen scale during arithmetic (e.g. price * tax_rate), so
        rescaling back to the declared decimal(p,s) must round, not raise."""
        import pyarrow.compute as pc
        cols = []
        for field in schema:
            col = arrow.column(field.name)
            if col.type != field.type:
                if pa.types.is_decimal(field.type) and \
                        pa.types.is_decimal(col.type):
                    col = pc.round(col, ndigits=field.type.scale)
                col = pc.cast(col, field.type, safe=False)
            cols.append(col)
        return pa.table(cols, schema=schema)

    def insert(self, table: str, arrow: pa.Table) -> None:
        meta = self._load_meta(table)
        current = self.read(table)
        arrow = self._cast_like(arrow, current.schema)
        self._commit(table, pa.concat_tables([current, arrow]), meta)

    def overwrite(self, table: str, arrow: pa.Table) -> None:
        meta = self._load_meta(table)
        current_schema = self.read(table).schema
        self._commit(table, self._cast_like(arrow, current_schema), meta)

    def snapshots(self, table: str) -> list:
        return list(self._load_meta(table)["snapshots"])

    def rollback_to_timestamp(self, table: str, timestamp_ms: int) -> int:
        """Truncate the snapshot log to the last snapshot at-or-before
        ``timestamp_ms``; returns the restored snapshot id (the Iceberg
        ``system.rollback_to_timestamp`` contract, ref: nds/nds_rollback.py:
        46-50)."""
        meta = self._load_meta(table)
        keep = [s for s in meta["snapshots"] if s["timestamp_ms"] <= timestamp_ms]
        if not keep:
            raise WarehouseError(
                f"table '{table}' has no snapshot at or before {timestamp_ms}")
        dropped = meta["snapshots"][len(keep):]
        meta["snapshots"] = keep
        self._store_meta(table, meta)
        for s in dropped:
            path = os.path.join(self.root, table, s["file"])
            if os.path.isdir(path):
                shutil.rmtree(path)
            elif os.path.exists(path):
                os.remove(path)
        return keep[-1]["id"]
