#!/usr/bin/env python3
# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Transcode driver — the NDS "Load Test".

TPU-build equivalent of the reference transcode CLI (ref: nds/nds_transcode.py:
154-315): reads the raw '|'-delimited generator output with the explicit
schemas, writes each table as parquet/orc (date-partitioning the 7 fact
tables, single file for the rest), or lands them in the snapshot warehouse
(the Iceberg/Delta CTAS role), timing each table and emitting the Load Test
report with the spec RNGSEED (end-of-load timestamp, TPC-DS v3.2.0 4.3.1).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from nds_tpu.check import check_version, get_abs_path  # noqa: E402

check_version()


def load(args, table_name, fields):
    """Raw csv -> arrow with explicit schema (ref: nds/nds_transcode.py:56-66)."""
    from nds_tpu.io import read_raw_table
    path = get_abs_path(os.path.join(args.input_prefix, table_name))
    if not os.path.exists(path):
        alt = path + ".dat"
        if os.path.exists(alt):
            path = alt
        else:
            raise FileNotFoundError(f"no raw data for table {table_name} at {path}")
    return read_raw_table(path, fields)


def store(args, arrow, table_name, warehouse):
    """Write one table to the output location (ref: nds/nds_transcode.py:69-152)."""
    from nds_tpu.io import write_table
    from nds_tpu.io.columnar import TABLE_PARTITIONING

    if args.output_format in ("iceberg", "delta"):
        # warehouse CTAS role: snapshot table in the warehouse root
        warehouse.create(table_name, arrow)
        return
    out = os.path.join(args.output_prefix, table_name)
    partition_col = None
    if table_name in TABLE_PARTITIONING and not args.update:
        partition_col = TABLE_PARTITIONING[table_name]
    write_table(arrow, out, args.output_format,
                partition_col=partition_col,
                compression=args.compression)


def transcode(args):
    from nds_tpu.schema import get_schemas, get_maintenance_schemas
    from nds_tpu.warehouse import Warehouse

    start_ts = time.time()

    if args.update:
        schemas = get_maintenance_schemas(use_decimal=not args.floats)
    else:
        schemas = get_schemas(use_decimal=not args.floats)

    if args.tables:
        missing = [t for t in args.tables if t not in schemas]
        if missing:
            raise ValueError(f"unknown tables: {missing}; "
                             f"known: {sorted(schemas)}")
        schemas = {t: schemas[t] for t in args.tables}

    warehouse = None
    if args.output_format in ("iceberg", "delta"):
        warehouse = Warehouse(args.output_prefix, fmt="parquet")

    load_times = {}
    for table, fields in schemas.items():
        start = time.perf_counter()
        try:
            store(args, load(args, table, fields), table, warehouse)
        except FileNotFoundError as e:
            if args.allow_missing:
                print(f"skip {table}: {e}")
                continue
            raise
        load_times[table] = time.perf_counter() - start
        print(f"transcoded {table} in {load_times[table]:.2f}s")

    end = time.time()
    # spec 4.3.1: RNGSEED for stream generation = load end timestamp,
    # format mmddHHMMSSfff (ref: nds/nds_transcode.py:205-229)
    rngseed = time.strftime("%m%d%H%M%S", time.localtime(end)) + \
        f"{int((end % 1) * 1000):03d}"

    report = []
    report.append("NDS Load Test (transcode) report")
    report.append(f"Load Test Time: {sum(load_times.values())}")
    report.append(f"Load Test start time: {start_ts}")
    report.append(f"Load Test end time: {end}")
    report.append(f"RNGSEED used: {rngseed}")
    report.append("")
    report.append("=== Per-table times (seconds) ===")
    for table, t in load_times.items():
        report.append(f"{table}: {t}")
    report.append("")
    report.append("=== Configuration ===")
    report.append(f"input_prefix: {args.input_prefix}")
    report.append(f"output_prefix: {args.output_prefix}")
    report.append(f"output_format: {args.output_format}")
    report.append(f"compression: {args.compression}")
    report.append(f"floats: {args.floats}")
    text = "\n".join(report) + "\n"
    if args.report_file:
        with open(args.report_file, "w") as f:
            f.write(text)
    print(text)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("input_prefix",
                        help="text to prepend to every input file path (the "
                        "raw data root from nds_gen_data.py)")
    parser.add_argument("output_prefix",
                        help="text to prepend to every output file path; the "
                        "warehouse root for iceberg/delta output formats")
    parser.add_argument("report_file",
                        help="location to store a performance report (local)")
    parser.add_argument("--output_format",
                        choices=["parquet", "orc", "avro", "csv", "iceberg",
                                 "delta"],
                        default="parquet",
                        help="output data format")
    parser.add_argument("--tables", nargs="+",
                        help="specify table names by space-separated. Allowed "
                        "values are the 24 source / 12 refresh table names")
    parser.add_argument("--output_mode",
                        choices=["overwrite", "errorifexists"],
                        default="overwrite",
                        help="save mode when writing data")
    parser.add_argument("--compression",
                        help="codec for the output format (snappy/zstd/...)")
    parser.add_argument("--update", action="store_true",
                        help="transcode the refresh (Data Maintenance) tables")
    parser.add_argument("--floats", action="store_true",
                        help="use double instead of decimal for monetary columns")
    parser.add_argument("--allow_missing", action="store_true",
                        help="skip tables whose raw data is absent")
    args = parser.parse_args()

    if args.output_format == "avro" and args.compression not in (
            None, "none", "null", "uncompressed", "deflate", "zlib"):
        # fail before any table is written: the avro writer implements
        # deflate/null only and would otherwise raise mid-transcode
        parser.error(f"avro supports deflate/null compression, "
                     f"not {args.compression!r}")

    if args.output_mode == "errorifexists" and os.path.exists(args.output_prefix) \
            and os.listdir(args.output_prefix):
        print(f"output {args.output_prefix} exists and is not empty", file=sys.stderr)
        sys.exit(1)

    transcode(args)
