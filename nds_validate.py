#!/usr/bin/env python3
# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Validation driver: row-level parity check between two Power Run outputs.

TPU-build equivalent of the reference validator (ref: nds/nds_validate.py:
48-362): for each query in a stream, load both outputs, compare row counts,
optionally sort (non-float columns first, float columns last), then compare
row by row with relative-epsilon float/Decimal handling, NaN==NaN and
None==None semantics, the query78 rounded-ratio tolerance, the permanent
query65 skip and the float-mode query67 skip — and patch
``queryValidationStatus`` (Pass / Fail / NotAttempted) into the per-query
JSON summaries.
"""

import argparse
import glob
import json
import math
import os
import re
import sys
from decimal import Decimal

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from nds_tpu.check import check_version  # noqa: E402

check_version()


def _load_rows(path: str, fmt: str, ignore_ordering: bool):
    """Load a query output directory into a list of row tuples, sorted by
    non-float columns then float columns when ignore_ordering is set (the
    collect_results contract, ref: nds/nds_validate.py:116-144)."""
    from nds_tpu.io import read_table
    table = read_table(path, fmt)
    import pyarrow as pa
    import pyarrow.compute as pc  # noqa: F401
    if ignore_ordering and table.num_rows:
        float_types = (pa.float32(), pa.float64())
        non_float = [f.name for f in table.schema if f.type not in float_types]
        floats = [f.name for f in table.schema if f.type in float_types]
        keys = [(name, "ascending") for name in non_float + floats]
        table = table.take(pa.compute.sort_indices(table, sort_keys=keys))
    cols = [table.column(i).to_pylist() for i in range(table.num_columns)]
    rows = list(zip(*cols)) if cols else []
    return rows


def compare(expected, actual, epsilon=0.00001):
    """Scalar comparison semantics (ref: nds/nds_validate.py:194-215)."""
    if isinstance(expected, float) and isinstance(actual, float):
        if math.isnan(expected) and math.isnan(actual):
            return True
        return math.isclose(expected, actual, rel_tol=epsilon)
    if isinstance(expected, str) and isinstance(actual, str):
        return expected == actual
    if expected is None and actual is None:
        return True
    if (expected is None) != (actual is None):
        return False
    if isinstance(expected, Decimal) and isinstance(actual, Decimal):
        return math.isclose(expected, actual, rel_tol=epsilon)
    # mixed numeric types (Decimal run vs --floats run): epsilon-compare in
    # float space; same-type int pairs stay exact via the == fallthrough
    numeric = (Decimal, float)
    if isinstance(expected, numeric) and isinstance(actual, (int, *numeric)) \
            or isinstance(actual, numeric) and isinstance(expected, (int, *numeric)):
        e, a = float(expected), float(actual)
        if math.isnan(e) and math.isnan(a):
            return True
        return math.isclose(e, a, rel_tol=epsilon)
    return expected == actual


def rowEqual(row1, row2, epsilon, is_q78, q78_problematic_col):
    """Row comparison incl. the q78 rounded-ratio column tolerance
    (ref: nds/nds_validate.py:166-192)."""
    if is_q78:
        if q78_problematic_col not in (2, 4):
            raise Exception("q78 problematic column should be 2nd or 4th, "
                            f"but get {q78_problematic_col}")
        row1 = list(row1)
        row2 = list(row2)
        v1 = row1.pop(q78_problematic_col - 1)
        v2 = row2.pop(q78_problematic_col - 1)
        if v1 is not None and v2 is not None:
            # ratio is round(x, 2): allow diff <= 0.01 + epsilon
            eq = abs(float(v1) - float(v2)) <= 0.01001
        else:
            eq = v1 is None and v2 is None
        return eq and all(compare(a, b, epsilon) for a, b in zip(row1, row2))
    return all(compare(a, b, epsilon) for a, b in zip(row1, row2))


def check_nth_col_problematic_q78(q78_content: str) -> int:
    """Find the 1-based index of the rounded-ratio column in the q78 text
    (ref: nds/nds_validate.py:146-164)."""
    last_between = q78_content.split("select")[-1].split("from")[0]
    target_splits = re.split(", |,\n", last_between)
    nth = -1
    for index, string in enumerate(target_splits):
        if "ratio" in string:
            nth = index
    if nth == -1:
        raise Exception("Cannot find the problematic column in the query78 "
                        f"content. Please check the content.\n{q78_content}")
    return nth + 1


def compare_results(input1, input2, input1_format, input2_format,
                    ignore_ordering, is_q78, q78_problematic_col,
                    max_errors=10, epsilon=0.00001) -> bool:
    """Row-by-row parity between two query output paths
    (ref: nds/nds_validate.py:48-114)."""
    rows1 = _load_rows(input1, input1_format, ignore_ordering)
    rows2 = _load_rows(input2, input2_format, ignore_ordering)
    if len(rows1) != len(rows2):
        print(f"Row counts do not match: {len(rows1)} != {len(rows2)}")
        return False
    errors = 0
    i = 0
    for lhs, rhs in zip(rows1, rows2):
        if errors >= max_errors:
            break
        if not rowEqual(list(lhs), list(rhs), epsilon, is_q78,
                        q78_problematic_col):
            print(f"Row {i}: \n{list(lhs)}\n{list(rhs)}\n")
            errors += 1
        i += 1
    print(f"Processed {i} rows")
    if errors == max_errors:
        print(f"Aborting comparison after reaching maximum of {max_errors} errors")
        return False
    if errors == 0:
        print("Results match")
        return True
    print(f"There were {errors} errors")
    return False


def iterate_queries(input1, input2, input1_format, input2_format,
                    ignore_ordering, query_dict, max_errors=10,
                    epsilon=0.00001, is_float=False):
    """Compare every query output in the stream; returns the unmatched list
    (ref: nds/nds_validate.py:217-260 incl. q65/q67 skips)."""
    unmatch_queries = []
    for query_name in query_dict.keys():
        if query_name == "query65":
            continue
        if query_name == "query67" and is_float:
            continue
        sub_input1 = os.path.join(input1, query_name)
        sub_input2 = os.path.join(input2, query_name)
        print(f"=== Comparing Query: {query_name} ===")
        problematic_col = 2
        if query_name == "query78":
            problematic_col = check_nth_col_problematic_q78(query_dict[query_name])
        if not os.path.exists(sub_input1) or not os.path.exists(sub_input2):
            print(f"Missing output for {query_name}")
            unmatch_queries.append(query_name)
            continue
        ok = compare_results(sub_input1, sub_input2, input1_format,
                             input2_format, ignore_ordering,
                             query_name == "query78", problematic_col,
                             max_errors=max_errors, epsilon=epsilon)
        if not ok:
            unmatch_queries.append(query_name)
    if unmatch_queries:
        print(f"=== Unmatch Queries: {unmatch_queries} ===")
    return unmatch_queries


def update_summary(prefix, unmatch_queries, query_dict):
    """Patch queryValidationStatus into each JSON summary
    (ref: nds/nds_validate.py:262-296)."""
    if not os.path.exists(prefix):
        raise Exception("The json summary folder doesn't exist.")
    print(f"Updating queryValidationStatus in folder {prefix}.")
    for query_name in query_dict.keys():
        summary_wildcard = os.path.join(prefix, f"*{query_name}-*.json")
        file_glob = glob.glob(summary_wildcard)
        if len(file_glob) > 1:
            raise Exception(f"More than one summary file found for query "
                            f"{query_name} in folder {prefix}.")
        if len(file_glob) == 0:
            raise Exception(f"No summary file found for query {query_name} "
                            f"in folder {prefix}.")
        for filename in file_glob:
            with open(filename) as f:
                summary = json.load(f)
            if query_name in unmatch_queries:
                if "Completed" in summary["queryStatus"] or \
                        "CompletedWithTaskFailures" in summary["queryStatus"]:
                    summary["queryValidationStatus"] = ["Fail"]
                else:
                    summary["queryValidationStatus"] = ["NotAttempted"]
            else:
                summary["queryValidationStatus"] = ["Pass"]
            with open(filename, "w") as f:
                json.dump(summary, f, indent=2)


if __name__ == "__main__":
    from nds_tpu.power import gen_sql_from_stream, get_query_subset

    parser = argparse.ArgumentParser()
    parser.add_argument("input1", help="path of the first input data")
    parser.add_argument("input2", help="path of the second input data")
    parser.add_argument("query_stream_file",
                        help="query stream file that contains NDS queries in "
                        "specific order")
    parser.add_argument("--input1_format", default="parquet",
                        help="data source format for input1, e.g. parquet, orc")
    parser.add_argument("--input2_format", default="parquet",
                        help="data source format for input2, e.g. parquet, orc")
    parser.add_argument("--max_errors", type=int, default=10,
                        help="maximum number of differences to report")
    parser.add_argument("--epsilon", type=float, default=0.00001,
                        help="allowed relative difference when comparing "
                        "floating point values")
    parser.add_argument("--ignore_ordering", action="store_true",
                        help="sort the data collected from the DataFrames "
                        "before comparing them")
    parser.add_argument("--use_iterator", action="store_true",
                        help="kept for reference CLI parity; outputs are "
                        "loaded via arrow either way")
    parser.add_argument("--floats", action="store_true",
                        help="the input data requires float/double handling "
                        "(skips query67)")
    parser.add_argument("--json_summary_folder",
                        help="path of a folder that contains json summary "
                        "files to patch with validation status")
    parser.add_argument("--sub_queries",
                        type=lambda s: [x.strip() for x in s.split(",")],
                        help="comma separated list of queries to validate")
    args = parser.parse_args()

    query_dict = gen_sql_from_stream(args.query_stream_file)
    if args.sub_queries:
        query_dict = get_query_subset(query_dict, args.sub_queries)
    unmatch = iterate_queries(args.input1, args.input2,
                              args.input1_format, args.input2_format,
                              args.ignore_ordering, query_dict,
                              max_errors=args.max_errors,
                              epsilon=args.epsilon, is_float=args.floats)
    if args.json_summary_folder:
        update_summary(args.json_summary_folder, unmatch, query_dict)
    sys.exit(1 if unmatch else 0)
