# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Test harness configuration: a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI, so every distributed test runs
against JAX's host-platform device emulation — the "fake pod" mode the
reference lacks entirely (its multi-node behavior is only exercised on real
clusters; SURVEY.md §4). Must run before jax initialises its backends.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

# A site hook may register an external TPU plugin at interpreter start and
# override jax_platforms; re-pin to CPU after import so tests never touch a
# (possibly tunneled) device backend.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
