# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Device admission control (parallel/admission.py): the concurrentGpuTasks
analog must bound in-flight executions across independent acquirers, free
slots on release, and never leak capacity when a holder dies (flock drops
with the process)."""

import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_slots_bound_concurrency(tmp_path):
    from nds_tpu.parallel.admission import DeviceAdmission
    a = DeviceAdmission(2, str(tmp_path))
    b = DeviceAdmission(2, str(tmp_path))
    c = DeviceAdmission(2, str(tmp_path))
    assert a.try_acquire() and b.try_acquire()
    assert not c.try_acquire(), "third acquirer must queue behind 2 slots"
    b.release()
    assert c.try_acquire(), "released slot must be reusable"
    for x in (a, b, c):
        x.close()


def test_acquire_blocks_and_reports_queue_time(tmp_path):
    from nds_tpu.parallel.admission import DeviceAdmission
    a = DeviceAdmission(1, str(tmp_path))
    b = DeviceAdmission(1, str(tmp_path))
    assert a.try_acquire()
    import threading
    release_at = time.perf_counter() + 0.3
    threading.Timer(0.3, a.release).start()
    queued = b.acquire()
    assert time.perf_counter() >= release_at - 0.05
    assert queued >= 0.2
    b.close()
    a.close()


def test_crashed_holder_frees_slot(tmp_path):
    """A process killed mid-hold must not leak the slot."""
    child = subprocess.Popen(
        [sys.executable, "-c", f"""
import sys, time
sys.path.insert(0, {REPO!r})
from nds_tpu.parallel.admission import DeviceAdmission
a = DeviceAdmission(1, {str(tmp_path)!r})
assert a.try_acquire()
print("held", flush=True)
time.sleep(60)
"""], stdout=subprocess.PIPE, text=True)
    assert child.stdout.readline().strip() == "held"
    from nds_tpu.parallel.admission import DeviceAdmission
    mine = DeviceAdmission(1, str(tmp_path))
    assert not mine.try_acquire(), "slot should be held by the child"
    child.kill()
    child.wait()
    deadline = time.perf_counter() + 5
    ok = False
    while time.perf_counter() < deadline:
        if mine.try_acquire():
            ok = True
            break
        time.sleep(0.05)
    assert ok, "kernel must drop a dead holder's flock"
    mine.close()


def test_from_env(monkeypatch, tmp_path):
    from nds_tpu.parallel import admission
    monkeypatch.delenv("NDS_TPU_CONCURRENT_QUERIES", raising=False)
    assert admission.from_env() is None
    monkeypatch.setenv("NDS_TPU_CONCURRENT_QUERIES", "0")
    assert admission.from_env() is None
    monkeypatch.setenv("NDS_TPU_CONCURRENT_QUERIES", "3")
    monkeypatch.setenv("NDS_TPU_ADMISSION_DIR", str(tmp_path))
    a = admission.from_env()
    assert a is not None and a.slots == 3 and a.dir == str(tmp_path)
    with a.slot() as queued:
        assert queued == 0.0 or queued >= 0.0
    a.close()


def test_power_records_admission_fields(tmp_path, monkeypatch):
    """nds_power wires the knob: summaries must carry the queued time and
    slot count when the env knob is set (SURVEY §2.4.5)."""
    pytest.importorskip("pyarrow")
    import pyarrow as pa
    import pyarrow.parquet as pq
    from collections import OrderedDict

    from nds_tpu import power
    from nds_tpu.schema import get_schemas
    from nds_tpu.types import to_arrow as to_pa
    fields = get_schemas(use_decimal=True)["item"]
    monkeypatch.setattr(power, "get_schemas",
                        lambda use_decimal: {"item": fields})
    data = tmp_path / "data"
    (data / "item").mkdir(parents=True)
    cols = {f.name: pa.array([None, None, None], to_pa(f.type))
            for f in fields}
    cols["i_item_sk"] = pa.array([1, 2, 3], to_pa(fields[0].type))
    pq.write_table(pa.table(cols), data / "item" / "part-0.parquet")
    monkeypatch.setenv("NDS_TPU_CONCURRENT_QUERIES", "1")
    monkeypatch.setenv("NDS_TPU_ADMISSION_DIR", str(tmp_path / "slots"))
    out = tmp_path / "json"
    power.run_query_stream(str(data), None,
                           OrderedDict(q="select count(*) cnt from item"),
                           str(tmp_path / "time.csv"),
                           json_summary_folder=str(out))
    import glob
    import json as J
    js = glob.glob(str(out / "*.json"))
    assert js
    doc = J.load(open(js[0]))
    assert doc.get("concurrentQueries") == 1
    assert "admissionQueuedMs" in doc
    # the live-metrics vocabulary for the same number (metrics.py
    # QUEUE_WAIT feed): summaries and ledger records carry queueWaitMs
    assert doc.get("queueWaitMs") == doc["admissionQueuedMs"]


def test_foreign_owned_slot_dir_fails_clearly(tmp_path, monkeypatch):
    """Another user's 0o644 slot files EACCES on O_RDWR; the error must
    name the fix (NDS_TPU_ADMISSION_DIR) instead of crashing with a bare
    PermissionError — or worse, being swallowed as a busy slot and turning
    acquire() into an infinite poll loop."""
    from nds_tpu.parallel.admission import DeviceAdmission
    a = DeviceAdmission(2, str(tmp_path))
    real_open = os.open

    def deny(path, *args, **kw):
        if "slot" in os.path.basename(str(path)):
            raise PermissionError(13, "Permission denied", str(path))
        return real_open(path, *args, **kw)

    monkeypatch.setattr(os, "open", deny)
    with pytest.raises(PermissionError) as ei:
        a.try_acquire()
    assert "NDS_TPU_ADMISSION_DIR" in str(ei.value)
    assert str(tmp_path) in str(ei.value)
    a.close()


def test_foreign_owned_admission_dir_fails_clearly(tmp_path, monkeypatch):
    from nds_tpu.parallel.admission import DeviceAdmission

    def deny(path, *args, **kw):
        raise PermissionError(13, "Permission denied", str(path))

    monkeypatch.setattr(os, "makedirs", deny)
    with pytest.raises(PermissionError) as ei:
        DeviceAdmission(1, str(tmp_path / "foreign"))
    assert "NDS_TPU_ADMISSION_DIR" in str(ei.value)
