# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Static analysis suite (nds_tpu/analysis): the plan auditor must pass the
whole shipped corpus clean (modulo the checked-in baseline), each rule must
trip on a known-bad fixture, in-source suppression must be honored, and the
baseline diff must reject only NEW findings — the CI-gate contract of
tools/lint.py."""

import importlib.util
import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TEMPLATES = os.path.join(REPO, "nds_tpu", "queries", "templates")


def audit(sql: str):
    from nds_tpu.analysis.plan_audit import PlanAuditor
    return PlanAuditor().audit_sql(sql)


def rules(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# plan auditor: full corpus
# ---------------------------------------------------------------------------


def test_corpus_passes_plan_audit_clean():
    """All 99 templates (103 statements) audit clean: the only accepted
    error is the TPC-DS spec's own deliberate cartesian in query77
    (``from cs, cr`` — two per-call-center aggregates), which the
    checked-in baseline carries."""
    from nds_tpu.analysis.plan_audit import audit_corpus
    findings = audit_corpus()
    errors = [f for f in findings if f.severity == "error"]
    assert [(f.file, f.rule) for f in errors] == \
        [("query77.tpl", "cartesian-join")], \
        "\n".join(str(f) for f in errors)


def test_corpus_audit_is_deterministic():
    from nds_tpu.analysis.plan_audit import audit_corpus
    a = [f.key() for f in audit_corpus()]
    b = [f.key() for f in audit_corpus()]
    assert a == b


# ---------------------------------------------------------------------------
# plan auditor: known-bad fixtures trip the expected rule
# ---------------------------------------------------------------------------


def test_unresolvable_column():
    fs = audit("select ss_no_such_col from store_sales")
    assert rules(fs) == {"unresolved-column"}
    assert "ss_no_such_col" in fs[0].message


def test_unresolvable_qualified_column():
    fs = audit("select s.ss_item_sk from store_sales ss")
    assert "unresolved-column" in rules(fs)


def test_unknown_table():
    fs = audit("select 1 x from no_such_table")
    assert "unknown-table" in rules(fs)


def test_dtype_mismatched_join():
    # int32 surrogate key joined against a char(2) state column
    fs = audit("select count(*) c from store_sales, store "
               "where ss_store_sk = s_state")
    assert "type-mismatch" in rules(fs)


def test_dtype_mismatched_literal_comparison():
    fs = audit("select count(*) c from store_sales "
               "where ss_quantity = 'many'")
    assert "type-mismatch" in rules(fs)
    # ...while numeric and date/string coercions the corpus relies on pass
    assert not audit("select count(*) c from date_dim "
                     "where d_date between '1999-01-01' and '1999-02-01'")


def test_cartesian_join_detected():
    fs = audit("select count(*) c from store_sales, customer_demographics "
               "where ss_quantity > 5")
    assert "cartesian-join" in rules(fs)
    assert "customer_demographics" in fs[-1].message


def test_connected_join_not_cartesian():
    fs = audit("select count(*) c from store_sales, store "
               "where ss_store_sk = s_store_sk")
    assert "cartesian-join" not in rules(fs)


def test_single_row_subquery_exempt_from_cartesian():
    # broadcasting a 1-row aggregate is a gather, not a pair explosion
    fs = audit("select count(*) c from store_sales, "
               "(select avg(ss_quantity) aq from store_sales) m "
               "where ss_quantity > aq")
    assert "cartesian-join" not in rules(fs)


def test_constant_projection_subquery_not_single_row():
    # select 1 from t is one row PER INPUT ROW: the exemption needs a
    # real aggregate, or the flagship rule misses a true cross join
    fs = audit("select count(*) c from store_sales, "
               "(select 1 x from customer_demographics) m")
    assert "cartesian-join" in rules(fs)


def test_or_predicate_connects_but_and_does_not():
    # an OR spanning two relations is evaluated per pair — a pair filter,
    # not a cartesian...
    assert "cartesian-join" not in rules(
        audit("select count(*) c from store_sales, store "
              "where ss_store_sk = 1 or s_store_sk = 2"))
    # ...but an AND of single-relation filters decomposes into independent
    # conjuncts and must still flag the unconnected pair
    assert "cartesian-join" in rules(
        audit("select count(*) c from store_sales, store "
              "where ss_store_sk = 1 and s_store_sk = 2"))


def test_unknown_function():
    fs = audit("select percentile_disc(ss_quantity) p from store_sales")
    assert "unknown-function" in rules(fs)


def test_window_misuse_and_nested_aggregate():
    assert "window-misuse" in rules(
        audit("select rank() r from store_sales"))
    assert "nested-aggregate" in rules(
        audit("select sum(avg(ss_quantity)) s from store_sales"))
    # q12-class windowed aggregate-over-aggregate is legal
    assert not audit(
        "select sum(sum(ss_ext_sales_price)) over (partition by ss_store_sk)"
        " w from store_sales group by ss_store_sk, ss_ext_sales_price")


def test_agg_in_where_and_agg_arg_type():
    assert "agg-in-where" in rules(
        audit("select ss_item_sk from store_sales "
              "where sum(ss_quantity) > 5"))
    assert "agg-arg-type" in rules(
        audit("select sum(s_state) s from store group by s_store_sk"))


def test_grouping_misuse():
    assert "grouping-misuse" in rules(
        audit("select grouping(ss_store_sk) g from store_sales"))
    assert "grouping-misuse" in rules(
        audit("select grouping(ss_item_sk) g from store_sales "
              "group by rollup(ss_store_sk)"))
    assert not audit("select grouping(ss_store_sk) g from store_sales "
                     "group by rollup(ss_store_sk)")


def test_setop_arity():
    fs = audit("select ss_item_sk, ss_quantity from store_sales "
               "union all select sr_item_sk from store_returns")
    assert "setop-arity" in rules(fs)


def test_duplicate_projected_names_keep_arity():
    # duplicate output names collapse as scope keys but still count as
    # columns: 2 vs 2 is NOT an arity error...
    assert not audit(
        "select ss_item_sk, ss_item_sk from store_sales "
        "union all select sr_item_sk, sr_ticket_number from store_returns")
    # ...and a dup-name 2-column IN subquery IS one
    fs = audit("select ss_item_sk from store_sales where ss_item_sk in "
               "(select sr_item_sk, sr_item_sk from store_returns)")
    assert "subquery-arity" in rules(fs)


def test_join_edge_through_non_comparison_predicates():
    # IN-list / LIKE predicates spanning two relations connect them: the
    # planner turns them into pair filters, not a cartesian
    assert "cartesian-join" not in rules(
        audit("select s.ss_item_sk from store_sales s, item i "
              "where s.ss_item_sk in (i.i_item_sk)"))
    assert "cartesian-join" not in rules(
        audit("select s.ss_item_sk from store_sales s, item i "
              "where i.i_item_id like 'AAA%' and s.ss_item_sk in "
              "(i.i_item_sk, i_manufact_id)"))


def test_cte_and_correlation_resolve():
    # the query1 shape: CTE referenced twice + correlated scalar subquery
    fs = audit(textwrap.dedent("""
        with ctr as (select sr_customer_sk ctr_customer_sk,
                            sr_store_sk ctr_store_sk,
                            sum(sr_return_amt) ctr_total_return
                     from store_returns, date_dim
                     where sr_returned_date_sk = d_date_sk
                     group by sr_customer_sk, sr_store_sk)
        select c_customer_id from ctr ctr1, store, customer
        where ctr1.ctr_total_return >
              (select avg(ctr_total_return) * 1.2 from ctr ctr2
               where ctr1.ctr_store_sk = ctr2.ctr_store_sk)
          and s_store_sk = ctr1.ctr_store_sk
          and ctr1.ctr_customer_sk = c_customer_sk
        order by c_customer_id
        limit 100"""))
    assert not fs, "\n".join(str(f) for f in fs)


# ---------------------------------------------------------------------------
# jax lint
# ---------------------------------------------------------------------------


def lint_snippet(tmp_path, code, rel="nds_tpu/engine/ops.py"):
    from nds_tpu.analysis.jax_lint import lint_file
    p = tmp_path / "snippet.py"
    p.write_text(textwrap.dedent(code))
    return lint_file(str(p), rel)


def test_jax_lint_host_sync_in_loop(tmp_path):
    fs = lint_snippet(tmp_path, """
        import numpy as np
        def drain(cols):
            out = []
            for c in cols:
                out.append(c.total.item())
                out.append(np.asarray(c.data))
            return out
    """)
    assert [f.rule for f in fs] == ["host-sync-in-loop"] * 2
    assert all(f.severity == "warning" for f in fs)


def test_jax_lint_hot_path_scoping(tmp_path):
    # the same sync outside the hot-path modules is not a finding
    fs = lint_snippet(tmp_path, """
        def drain(cols):
            return [c.total.item() for c in cols]
    """, rel="nds_tpu/report.py")
    assert not fs


def test_jax_lint_tracer_if_and_time(tmp_path):
    fs = lint_snippet(tmp_path, """
        import functools, time
        import jax
        @functools.partial(jax.jit, static_argnums=(1,))
        def kern(x, n):
            t0 = time.time()
            if n > 2:          # static arg: fine
                x = x + 1
            if x > 0:          # traced arg: hazard
                return x
            return x - t0
    """)
    assert sorted(f.rule for f in fs) == ["time-in-jit", "tracer-if"]
    assert all(f.severity == "error" for f in fs)


def test_jax_lint_nested_helper_and_argless_jit(tmp_path):
    # a helper defined inside a jit function still runs under the trace:
    # closures over the traced params keep tracer semantics, and an
    # argless jit function still evaluates time.time() once at trace time
    fs = lint_snippet(tmp_path, """
        import time
        import jax
        @jax.jit
        def f(x):
            def inner():
                if x > 0:
                    return x + 1
                return x
            return inner()
        @jax.jit
        def g():
            return time.time()
    """)
    assert sorted(f.rule for f in fs) == ["time-in-jit", "tracer-if"]
    # ...but a nested helper's OWN params shadow the outer tracers and
    # their tracedness is unknowable — not flagged
    fs = lint_snippet(tmp_path, """
        import jax
        @jax.jit
        def f(x):
            def clamp(x):
                if x is None:
                    return 0
                return x
            return clamp(3)
    """)
    assert not fs, "\n".join(str(f) for f in fs)


def test_jax_lint_static_metadata_if_ok(tmp_path):
    fs = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def kern(x, valid):
            if valid is None:                # pytree structure: fine
                valid = jnp.ones(x.shape[0], bool)
            if x.dtype == jnp.float64:       # static metadata: fine
                x = x.astype(jnp.float32)
            return x, valid
    """)
    assert not fs


def test_jax_lint_span_in_jit(tmp_path):
    # an obs.span(...) context inside a jitted function reads the host
    # clock at trace time — flagged whether spelled obs.span, trace.span
    # or a bare imported span; nested defs inside the jit body count too
    fs = lint_snippet(tmp_path, """
        import jax
        from nds_tpu.obs import trace as obs
        from nds_tpu.obs.trace import span
        @jax.jit
        def kern(x):
            with obs.span("drive"):
                y = x + 1
            with span("bare"):
                y = y * 2
            return y
    """)
    assert [f.rule for f in fs] == ["span-in-jit"] * 2
    assert all(f.severity == "error" for f in fs)
    fs = lint_snippet(tmp_path, """
        import jax
        @jax.jit
        def kern(x):
            def helper():
                with obs.span("nested"):
                    return x
            return helper()
    """)
    assert [f.rule for f in fs] == ["span-in-jit"]


def test_jax_lint_host_sync_in_shard_map(tmp_path):
    """Both directions of the host-sync-in-shard-map rule: host reads,
    engine sync entry points, a one-level-down syncing helper and an
    obs.span inside a shard_map/pjit body are errors; the same calls
    outside any shard body (or a clean body) are not."""
    fs = lint_snippet(tmp_path, """
        import jax
        from jax.experimental.shard_map import shard_map
        from nds_tpu.engine import ops
        from nds_tpu.obs import trace as obs

        def _helper(x):
            return ops.count_int(x.nrows)

        def make(mesh, specs):
            def local(x, n):
                with obs.span("inner"):
                    pass
                ops.host_read("tag", lambda: 1)
                n.to_int()
                _helper(x)
                return x
            return shard_map(local, mesh=mesh, in_specs=specs,
                             out_specs=specs)
    """, rel="nds_tpu/parallel/other.py")
    rules = [f.rule for f in fs]
    assert rules == ["host-sync-in-shard-map"] * 4, fs
    assert all(f.severity == "error" for f in fs)
    # clean body + syncs OUTSIDE the body: no findings (the rule must
    # not leak past the shard_map'd function)
    fs = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp
        from nds_tpu.engine import ops
        from nds_tpu.parallel.exchange import shard_map_compat

        def make(mesh, specs):
            def local(x):
                return jax.lax.psum(x, "shard")
            step = shard_map_compat(local, mesh, specs, specs)
            n = ops.count_int(4)          # outside: legal
            return step, n
    """, rel="nds_tpu/parallel/other.py")
    assert not [f for f in fs if f.rule == "host-sync-in-shard-map"], fs


def test_jax_lint_span_outside_jit_ok(tmp_path):
    # the supported shape: open the span AROUND the jitted call
    fs = lint_snippet(tmp_path, """
        import jax
        from nds_tpu.obs import trace as obs
        @jax.jit
        def kern(x):
            return x + 1
        def drive(x):
            with obs.span("drive", chunk=0):
                return kern(x)
    """)
    assert not [f for f in fs if f.rule == "span-in-jit"], \
        "\n".join(str(f) for f in fs)


def test_jax_lint_span_unrelated_callables_ok(tmp_path):
    # .span() on a non-obs owner (re.Match.span) and a bare local helper
    # named span() are NOT trace contexts — must not trip the CI gate
    fs = lint_snippet(tmp_path, """
        import re
        import jax
        @jax.jit
        def kern(x):
            m = re.match("a+", "aaa")
            a, b = m.span()
            def span(v):
                return v + a
            return span(x) + b
    """)
    assert not [f for f in fs if f.rule == "span-in-jit"], \
        "\n".join(str(f) for f in fs)


def test_jax_lint_span_import_alias_flagged(tmp_path):
    # a non-conventional import alias still resolves to the obs module
    fs = lint_snippet(tmp_path, """
        import jax
        import nds_tpu.obs.trace as tr
        from nds_tpu.obs.trace import span as mark
        @jax.jit
        def kern(x):
            with tr.span("a"):
                x = x + 1
            with mark("b"):
                x = x * 2
            return x
    """)
    assert [f.rule for f in fs] == ["span-in-jit"] * 2


def test_jax_lint_factory_form_jit_decorator(tmp_path):
    # @jax.jit(static_argnums=...) — the decorator-factory spelling — must
    # be recognized like @jax.jit and functools.partial(jax.jit, ...)
    fs = lint_snippet(tmp_path, """
        import jax
        @jax.jit(static_argnums=(1,))
        def kern(x, n):
            if n > 2:          # static arg: fine
                x = x + 1
            if x > 0:          # traced arg: hazard
                return x
            return x
    """)
    assert [f.rule for f in fs] == ["tracer-if"]


def test_jax_lint_cache_through_parameter_alias(tmp_path):
    # the planner threads _MASK_FUSE_CACHE/_EXPR_FUSE_CACHE through
    # _fused_run's `cache` parameter: writes, evictions, and key hazards
    # through the alias must count against the module cache
    fs = lint_snippet(tmp_path, """
        _ALIAS_CACHE: dict = {}
        class P:
            def outer(self, cols):
                return self._run(_ALIAS_CACHE, cols)
            def _run(self, cache, cols):
                cache[(len(cols), [c.kind for c in cols])] = cols
                return cols
    """)
    assert sorted(f.rule for f in fs) == ["cache-key-list",
                                         "unbounded-cache"]
    assert all("_ALIAS_CACHE" in f.message for f in fs)
    # eviction through the alias clears unbounded-cache (the _fused_run
    # shape: len() guard + pop through the parameter)
    fs = lint_snippet(tmp_path, """
        _ALIAS_CACHE: dict = {}
        def outer(cols):
            return _run(_ALIAS_CACHE, cols, 16)
        def _run(cache, cols, cap):
            if len(cache) >= cap:
                cache.pop(next(iter(cache)))
            cache[len(cols)] = cols
            return cache[len(cols)]
    """)
    assert not fs, "\n".join(str(f) for f in fs)


def test_jax_lint_cache_rules(tmp_path):
    fs = lint_snippet(tmp_path, """
        _GROW_CACHE: dict = {}
        _BOUND_CACHE: dict = {}
        _MAX = 16
        def remember(key, cols, val):
            _GROW_CACHE[(key, [c.kind for c in cols])] = val
            if len(_BOUND_CACHE) >= _MAX:
                _BOUND_CACHE.pop(next(iter(_BOUND_CACHE)))
            _BOUND_CACHE[key] = val
    """)
    assert sorted(f.rule for f in fs) == ["cache-key-list", "unbounded-cache"]
    assert all("_GROW_CACHE" in f.message for f in fs)


def test_jax_lint_cache_setdefault_counts_as_write(tmp_path):
    # a cache populated only via .setdefault() grows exactly like a
    # subscript store — same hazard, same rule
    fs = lint_snippet(tmp_path, """
        _MISS_CACHE: dict = {}
        def remember(k, cols, v):
            return _MISS_CACHE.setdefault((k, [c.kind for c in cols]), v)
    """)
    assert sorted(f.rule for f in fs) == ["cache-key-list",
                                         "unbounded-cache"]
    fs = lint_snippet(tmp_path, """
        _MISS_CACHE: dict = {}
        def remember(k, v):
            if len(_MISS_CACHE) >= 16:
                _MISS_CACHE.popitem()
            return _MISS_CACHE.setdefault(k, v)
    """)
    assert not fs, "\n".join(str(f) for f in fs)


def test_jax_lint_swallowed_fault(tmp_path):
    """An except clause catching a classified fault (FaultError family,
    bare / attribute-qualified / inside a tuple) must record a
    FaultEvent or re-raise — anything else is an un-auditable recovery
    (DESIGN.md 'Fault-tolerance contract')."""
    fs = lint_snippet(tmp_path, """
        from nds_tpu.engine import faults as _F
        def recover(fn):
            try:
                return fn()
            except _F.FaultInjected:
                return None                      # swallowed: flagged
        def recover2(fn):
            try:
                return fn()
            except (OSError, _F.FaultError) as exc:
                log(exc)                         # swallowed: flagged
        def recover3(fn):
            try:
                return fn()
            except FaultInjected:
                pass                             # bare name: flagged
    """, rel="nds_tpu/engine/stream.py")
    assert [f.rule for f in fs] == ["swallowed-fault"] * 3
    assert all(f.severity == "error" for f in fs)


def test_jax_lint_swallowed_fault_compliant_ok(tmp_path):
    # recording the event, re-raising, or raising a classified
    # replacement all comply; unrelated except clauses never trip
    fs = lint_snippet(tmp_path, """
        from nds_tpu.engine import faults as _F
        def recover(fn):
            try:
                return fn()
            except _F.FaultInjected as exc:
                _F.record_fault_event(exc.seam, "degrade")
                return None
        def reraise(fn):
            try:
                return fn()
            except _F.StatementTimeout:
                raise
        def classify(fn):
            try:
                return fn()
            except _F.FaultError as exc:
                raise RuntimeError("classified") from exc
        def unrelated(fn):
            try:
                return fn()
            except ValueError:
                return None
    """, rel="nds_tpu/engine/stream.py")
    assert not fs, "\n".join(str(f) for f in fs)


def test_jax_lint_swallowed_fault_suppression_and_tree_clean(tmp_path):
    fs = lint_snippet(tmp_path, """
        from nds_tpu.engine import faults as _F
        def recover(fn):
            try:
                return fn()
            # nds-lint: ignore[swallowed-fault]
            except _F.FaultInjected:
                return None
    """, rel="nds_tpu/engine/stream.py")
    assert not fs
    # the real tree's recovery paths all comply (baseline untouched)
    from nds_tpu.analysis.jax_lint import lint_tree
    got = [f for f in lint_tree() if f.rule == "swallowed-fault"]
    assert not got, "\n".join(str(f) for f in got)


def test_jax_lint_chunk_loop_host_sync(tmp_path):
    # in ANY module (not just hot-path files): a sync per streamed chunk
    # is the O(chunks) cost the compiled executor removes
    fs = lint_snippet(tmp_path, """
        import numpy as np
        from nds_tpu.engine import ops as E
        def eager(table, parts):
            outs = []
            for chunk in table.device_chunks():
                n = E.count_int(chunk.nrows)
                outs.append(np.asarray(chunk.data))
                m = chunk.nrows.to_int()
                k = chunk.total.item()
            for chunk in table.padded_chunks():
                E.resolve_counts()
            return outs
    """, rel="nds_tpu/report.py")
    assert [f.rule for f in fs] == ["chunk-loop-host-sync"] * 5
    assert all(f.severity == "warning" for f in fs)


def test_jax_lint_chunk_loop_scoping(tmp_path):
    # the same syncs OUTSIDE a chunk loop (or in a plain loop) are not
    # this rule's findings; device-resident chunk work is clean
    fs = lint_snippet(tmp_path, """
        from nds_tpu.engine import ops as E
        def fine(table, items):
            n = E.count_int(table.nrows)      # not in a loop
            for x in items:                   # not a chunk loop
                y = E.count_int(x.nrows)
            outs = []
            for chunk in table.device_chunks():
                outs.append(chunk)            # sync-free chunk loop
            return outs
    """, rel="nds_tpu/report.py")
    assert not [f for f in fs if f.rule == "chunk-loop-host-sync"]


def test_jax_lint_chunk_loop_helper_sync(tmp_path):
    # the one-level-down gap: a host sync hidden in a module-local helper
    # (bare name or self.method) called from a chunk-loop body is flagged
    # at the call site, with the helper's sync primitive named
    fs = lint_snippet(tmp_path, """
        from nds_tpu.engine import ops as E
        def _resolve(chunk):
            return E.count_int(chunk.nrows)
        class P:
            def _peek(self, chunk):
                return chunk.total.item()
            def run(self, table):
                outs = []
                for chunk in table.device_chunks():
                    n = _resolve(chunk)
                    m = self._peek(chunk)
                    outs.append(chunk)
                return outs
    """, rel="nds_tpu/report.py")
    assert [f.rule for f in fs] == ["chunk-loop-host-sync"] * 2
    assert "_resolve" in fs[0].message and "count_int()" in fs[0].message
    assert "_peek" in fs[1].message and ".item()" in fs[1].message


def test_jax_lint_chunk_loop_helper_scoping(tmp_path):
    # sync-free helpers, helpers called outside chunk loops, and
    # non-local callees (module attributes) are all clean
    fs = lint_snippet(tmp_path, """
        from nds_tpu.engine import ops as E
        def _shape(chunk):
            return chunk.plen
        def run(table, other):
            n = E.count_int(other.nrows)     # outside any chunk loop
            outs = []
            for chunk in table.device_chunks():
                outs.append(_shape(chunk))   # helper does not sync
                outs.append(E.bucket_len(4)) # non-sync engine call
            return outs, n
    """, rel="nds_tpu/report.py")
    assert not [f for f in fs if f.rule == "chunk-loop-host-sync"], \
        "\n".join(str(f) for f in fs)


def test_jax_lint_chunk_loop_helper_class_scoped(tmp_path):
    # a self.method call resolves only against the ENCLOSING class: a
    # same-named method on an unrelated class in the module that does
    # sync is not evidence against this class's sync-free one
    fs = lint_snippet(tmp_path, """
        class A:
            def _peek(self):
                return self.total.item()
        class B:
            def _peek(self, chunk):
                return chunk.plen
            def run(self, table):
                outs = []
                for chunk in table.device_chunks():
                    outs.append(self._peek(chunk))
                return outs
    """, rel="nds_tpu/report.py")
    assert not [f for f in fs if f.rule == "chunk-loop-host-sync"], \
        "\n".join(str(f) for f in fs)


def test_jax_lint_suppression_honored(tmp_path):
    fs = lint_snippet(tmp_path, """
        def drain(cols):
            out = []
            for c in cols:
                # nds-lint: ignore[host-sync-in-loop]
                out.append(c.total.item())
                v = c.n.item()  # nds-lint: ignore[host-sync-in-loop]
                w = c.m.item()  # nds-lint: ignore[tracer-if] (wrong rule)
            return out, v, w
    """)
    # only the wrong-rule suppression still fires
    assert len(fs) == 1 and fs[0].rule == "host-sync-in-loop"


def test_jax_lint_current_tree_clean():
    """The engine itself must stay hazard-free beyond the baseline (which
    carries none for jax-lint today)."""
    from nds_tpu.analysis.jax_lint import lint_tree
    fs = lint_tree(os.path.join(REPO, "nds_tpu"))
    assert not fs, "\n".join(str(f) for f in fs)


# ---------------------------------------------------------------------------
# driver audit
# ---------------------------------------------------------------------------


def driver_snippet(tmp_path, code):
    from nds_tpu.analysis.driver_audit import audit_file
    p = tmp_path / "driver.py"
    p.write_text(textwrap.dedent(code))
    return audit_file(str(p), "tools/driver.py")


def test_driver_audit_rules(tmp_path):
    fs = driver_snippet(tmp_path, """
        import json, os, subprocess
        def run(cmd, out_path, doc):
            try:
                os.system("rm -rf " + cmd)
                subprocess.run(cmd, shell=True)
            except Exception:
                pass
            json.dump(doc, open(out_path, "w"))
    """)
    assert sorted(f.rule for f in fs) == [
        "shell-injection", "shell-injection", "swallowed-exception",
        "unmanaged-file-handle"]


def test_driver_audit_shell_true_through_aliases(tmp_path):
    # shell=True is the hazard regardless of the callee's spelling:
    # `from subprocess import run` and `import subprocess as sp` must not
    # slip past the error-severity gate
    fs = driver_snippet(tmp_path, """
        import subprocess as sp
        from subprocess import run
        def go(cmd):
            run(cmd, shell=True)
            sp.run(cmd, shell=True)
            sp.check_output(cmd, shell=False)
    """)
    assert [f.rule for f in fs] == ["shell-injection"] * 2


def test_driver_audit_managed_patterns_ok(tmp_path):
    fs = driver_snippet(tmp_path, """
        import json, subprocess
        def run(argv, out_path, doc):
            subprocess.run(argv, capture_output=True)
            with open(out_path, "w") as f:
                json.dump(doc, f)
            g = open(out_path + ".tmp", "w")
            try:
                g.write("x")
            finally:
                g.close()
            try:
                return json.load(open(out_path))  # nds-lint: ignore
            except OSError:
                pass
    """)
    assert not fs, "\n".join(str(f) for f in fs)


def test_driver_audit_rebound_handle_leak(tmp_path):
    # reusing a name for two sequential open()s leaks the first handle;
    # close-then-reopen is fine but the second handle needs its own close
    fs = driver_snippet(tmp_path, """
        def two_logs(a, b):
            f = open(a, "w")
            f.write("x")
            f = open(b, "w")
            f.close()
    """)
    assert [f.rule for f in fs] == ["unmanaged-file-handle"]
    assert fs[0].line == 3   # the FIRST open is the leak
    fs = driver_snippet(tmp_path, """
        def two_logs(a, b):
            f = open(a, "w")
            f.close()
            f = open(b, "w")
            f.write("x")
    """)
    assert [(f.rule, f.line) for f in fs] == [("unmanaged-file-handle", 5)]


def test_driver_audit_annotated_assign_handle(tmp_path):
    # f: IO = open(p) tracks like f = open(p): closed is clean, unclosed
    # is a finding
    fs = driver_snippet(tmp_path, """
        def go(p):
            f: object = open(p)
            f.close()
    """)
    assert not fs, "\n".join(str(f) for f in fs)
    fs = driver_snippet(tmp_path, """
        def go(p):
            f: object = open(p)
            return f.read()
    """)
    assert [f.rule for f in fs] == ["unmanaged-file-handle"]


def test_driver_audit_attribute_held_handle_ok(tmp_path):
    # a handle stored on an object has a deliberate cross-method lifetime
    fs = driver_snippet(tmp_path, """
        class Log:
            def start(self, path):
                self.f = open(path, "w")
            def stop(self):
                self.f.close()
    """)
    assert not fs, "\n".join(str(f) for f in fs)


# ---------------------------------------------------------------------------
# exec audit: static execution-path classification + sync bounds
# ---------------------------------------------------------------------------


def exec_audit(sql, streamed=("store_sales",)):
    from nds_tpu.analysis.exec_audit import ExecAuditor
    return ExecAuditor(streamed=set(streamed)).audit_sql(sql)


def test_exec_audit_ab_templates_classification():
    """The A/B templates pinned by test_synccount: the static auditor
    must predict the exact path the runtime takes — every template now
    streams compiled (the multi-pass conversions cleared the IN-subquery
    fallback too), with every compiled scan's steady-state bound inside
    the streamed budget, and the converted shapes carrying their
    mechanism tags."""
    from nds_tpu.analysis.exec_audit import (CLASS_COMPILED, CLASS_EAGER,
                                             SYNC_BUDGET)
    from test_synccount import _STREAM_AB_QUERIES
    reports = [exec_audit(q) for q, _must in _STREAM_AB_QUERIES]
    got = [r.classification for r in reports]
    want = [CLASS_COMPILED if must else CLASS_EAGER
            for _q, must in _STREAM_AB_QUERIES]
    assert got == want, got
    for r in reports:
        if r.classification == CLASS_COMPILED:
            assert r.sync_bound is not None and r.sync_bound <= SYNC_BUDGET
            for s in r.scans:
                assert s.compiled and s.gate_bound <= SYNC_BUDGET
    mechs = [set(m for s in r.scans for m in s.mechanisms)
             for r in reports]
    # ab4 (IN subquery), ab10 (outer gather), ab11 (outer build),
    # ab13 (NOT IN: recorded scalar)
    assert "streamed-subquery" in mechs[3]
    assert "outer-gather" in mechs[9]
    assert "outer-build" in mechs[10]
    assert {"streamed-subquery", "recorded-scalar"} <= mechs[12]


def test_exec_audit_device_resident():
    from nds_tpu.analysis.exec_audit import CLASS_DEVICE
    r = exec_audit("""
        select d_year, i_brand_id, sum(ss_ext_sales_price) s
        from store_sales, date_dim, item
        where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
        group by d_year, i_brand_id""", streamed=())
    assert r.classification == CLASS_DEVICE
    assert not r.scans
    assert r.sync_bound is not None


def test_exec_audit_reason_codes():
    """Each eager-fallback reason code fires on its canonical shape,
    mirroring the runtime routing of engine/stream.py."""
    # cartesian layout in the streamed graph: _cartesian's host count
    # resolve raises StreamSyncError under stream bounds
    r = exec_audit("select count(*) c from store_sales, item "
                   "where ss_ext_sales_price > 9990 and i_brand_id = 1")
    assert r.classification == "eager-fallback"
    assert r.reasons == ("chunk-dependent-host-read",)
    assert r.sync_bound is None and r.per_chunk >= 1
    # bare scan: the survivor accumulator keeps every chunk row — but the
    # memory proof admits it (pruned SF10 store_sales fits the capacity
    # model), so it streams compiled with the proof-sized accumulator
    r = exec_audit("select ss_item_sk from store_sales")
    assert r.classification == "compiled-stream" and not r.reasons
    # ...and the SAME bare scan against a capacity model that cannot
    # admit the bound keeps the accumulator-overflow fallback (lockstep
    # with the runtime's legacy-ceiling clamp + overflow rerun)
    from nds_tpu.analysis.exec_audit import ExecAuditor
    from nds_tpu.analysis.mem_audit import MemModel
    tiny = ExecAuditor(streamed={"store_sales"},
                       mem_model=MemModel(capacity_bytes=1 << 20))
    r = tiny.audit_sql("select ss_item_sk from store_sales")
    assert r.reasons == ("accumulator-overflow",)
    # an explicit NDS_TPU_STREAM_ACC_ROWS ceiling below the table's rows
    # also forbids the proof (the hard ceiling wins; overflow certain)
    capped = ExecAuditor(streamed={"store_sales"},
                         mem_model=MemModel(acc_ceiling=1 << 10))
    r = capped.audit_sql("select ss_item_sk from store_sales")
    assert r.reasons == ("accumulator-overflow",)
    # chunked scan on the null-introducing side of a LEFT join: the
    # multi-pass outer-build conversion (unmatched-key accumulator,
    # extras at materialize) streams it compiled
    r = exec_audit("select d_year, ss_item_sk from date_dim left join "
                   "store_sales on d_date_sk = ss_sold_date_sk")
    assert r.classification == "compiled-stream"
    assert any("outer-build" in s.mechanisms for s in r.scans)
    # ...but a remaining WHERE conjunct over either side needs the extras
    # to flow through post-join structure: ineligible, the side
    # materializes whole and outer-join-extras still fires
    r = exec_audit("select d_year, ss_item_sk from date_dim left join "
                   "store_sales on d_date_sk = ss_sold_date_sk "
                   "where ss_item_sk > 5 or d_year = 1999")
    assert "outer-join-extras" in r.reasons
    # chunked scan PRESERVED with ON keys that do NOT cover the right
    # side's primary key: no sync-free per-chunk gather exists, the left
    # side materializes whole — outer-join-extras
    r = exec_audit("select ss_item_sk, i_brand_id from store_sales "
                   "left join item on ss_item_sk = i_brand_id")
    assert "outer-join-extras" in r.reasons
    # chunked scan PRESERVED with ON keys = the right side's PK: the
    # outer-gather conversion rides the join into the per-chunk program
    r = exec_audit("select ss_item_sk, i_brand_id from store_sales "
                   "left join item on ss_item_sk = i_item_sk "
                   "where ss_ext_sales_price > 9900")
    assert r.classification == "compiled-stream"
    assert any("outer-gather" in s.mechanisms for s in r.scans)
    # subquery conjunct: formerly the canonical subquery-residual eager
    # fallback — now pre-planned into a device residual, compiled
    r = exec_audit("select count(*) c from store_sales "
                   "where ss_sold_date_sk in "
                   "(select d_date_sk from date_dim where d_moy = 11)")
    assert r.classification == "compiled-stream" and not r.reasons
    assert any("streamed-subquery" in s.mechanisms for s in r.scans)


def test_exec_audit_cte_shadowing_not_streamed():
    # a CTE shadowing a chunked catalog name resolves to the CTE (the
    # planner checks the cte stack first): nothing streams
    from nds_tpu.analysis.exec_audit import CLASS_DEVICE
    r = exec_audit("""
        with store_sales as (select d_date_sk x from date_dim)
        select count(*) c from store_sales""")
    assert r.classification == CLASS_DEVICE


def test_exec_audit_gate_trips_on_sync_heavy_plan():
    """Negative case: a deliberately sync-heavy — but still streamable —
    toy plan must trip the stream-sync-budget gate: two chained non-PK
    outer joins (2 syncs each: probe + batched extras) on top of the
    pipeline's materializing sync, a multi-key grouping (batched resolve
    + packed range probe) and the output resolution exceed the budget."""
    from nds_tpu.analysis.exec_audit import (SYNC_BUDGET,
                                             reports_to_findings)
    r = exec_audit("""
        select ss_item_sk, d_year, count(*) c
        from store_sales
             left join date_dim on ss_sold_date_sk = d_moy
             left join item on ss_item_sk = i_brand_id
        where ss_quantity > 0
        group by ss_item_sk, d_year""")
    assert r.classification == "compiled-stream"
    assert r.scans[0].gate_bound > SYNC_BUDGET
    fs = reports_to_findings([r])
    assert [f.rule for f in fs] == ["stream-sync-budget"]
    assert fs[0].severity == "error"


def test_exec_audit_corpus_full_coverage():
    """Every template statement receives a classification with reasons,
    deterministically, and no streamable plan's static bound exceeds the
    streamed budget — the lint-gate contract over the shipped corpus."""
    from nds_tpu.analysis.exec_audit import (CLASS_COMPILED, CLASS_EAGER,
                                             CLASS_DEVICE, SYNC_BUDGET,
                                             audit_exec_corpus,
                                             reports_to_findings)
    reports = audit_exec_corpus()
    assert len(reports) >= 99
    allowed = {CLASS_COMPILED, CLASS_EAGER, CLASS_DEVICE}
    for r in reports:
        assert r.classification in allowed, (r.query, r.classification)
        if r.classification == CLASS_EAGER:
            assert r.reasons, f"{r.query}: eager with no reason code"
        for s in r.scans:
            if s.compiled:
                assert s.gate_bound <= SYNC_BUDGET, (r.query, s)
    assert not reports_to_findings(reports)
    again = audit_exec_corpus()
    assert [r.to_dict() for r in again] == [r.to_dict() for r in reports]


def test_exec_audit_collective_budget_and_gate():
    """Sharded collective budget: under a forced mesh env the model
    prices the exchange pass from the scan's pruned width and keys, the
    corpus stays within the collective-budget gate, and a hand-built
    over-budget verdict trips the gate (a gate that cannot fail proves
    nothing). Without the env, every budget is zero — the corpus
    classification cannot move."""
    from nds_tpu.analysis.exec_audit import (COLLECTIVE_CHUNK_BUDGET,
                                             COLLECTIVE_FINAL_BUDGET,
                                             ExecReport, ScanVerdict,
                                             reports_to_findings)
    # unsharded default: zero budgets
    r = exec_audit("""
        select ss_item_sk, count(*) c from store_sales, store_returns
        where ss_item_sk = sr_item_sk group by ss_item_sk""")
    assert r.scans[0].shards == 1 and r.scans[0].a2a_chunk == 0
    old = os.environ.get("NDS_TPU_STREAM_SHARDS")
    os.environ["NDS_TPU_STREAM_SHARDS"] = "2"
    try:
        r = exec_audit("""
            select ss_item_sk, count(*) c from store_sales, store_returns
            where ss_item_sk = sr_item_sk group by ss_item_sk""")
        s = r.scans[0]
        assert s.shards == 2
        # keys present: the exchange MAY run — bounded by 2 x width + 2
        assert 0 < s.a2a_chunk <= COLLECTIVE_CHUNK_BUDGET
        assert s.coll_final == 3
        assert not reports_to_findings([r])
        # a keyless scan can never exchange: per-chunk budget zero
        r2 = exec_audit("select ss_item_sk, count(*) c from store_sales "
                        "group by ss_item_sk")
        assert r2.scans[0].a2a_chunk == 0 and r2.scans[0].coll_final == 3
    finally:
        if old is None:
            del os.environ["NDS_TPU_STREAM_SHARDS"]
        else:
            os.environ["NDS_TPU_STREAM_SHARDS"] = old
    # the gate can fail: an over-budget verdict is an error finding
    bad = ExecReport(
        "toy.tpl", "toy", "compiled-stream",
        scans=(ScanVerdict("ss", "store_sales", True, shards=2,
                           a2a_chunk=COLLECTIVE_CHUNK_BUDGET + 1,
                           coll_final=COLLECTIVE_FINAL_BUDGET + 1),))
    fs = reports_to_findings([bad])
    assert [f.rule for f in fs] == ["collective-budget"]
    assert fs[0].severity == "error"


def test_exec_audit_differential_harness():
    """The lockstep contract: static path/sync predictions must match the
    runtime StreamEvent evidence on the A/B templates, and the harness
    must FAIL on the injected model-drift fixture (flipped paths) — a
    gate that cannot fail proves nothing."""
    import importlib.util
    path = os.path.join(REPO, "tools", "exec_audit_diff.py")
    spec = importlib.util.spec_from_file_location("exec_audit_diff", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    queries, _ = mod._load_ab_templates()
    reports = mod.predict(queries)
    evidence = mod.collect_runtime_evidence()
    ok, lines = mod.compare(reports, evidence)
    assert ok, "\n".join(lines)
    drift_ok, drift_lines = mod.compare(reports, evidence,
                                        inject_drift=True)
    assert not drift_ok, "drift fixture failed to fail"
    assert any("MISMATCH" in ln for ln in drift_lines)


def test_exec_audit_sharded_collective_differential():
    """The sharded half of the lockstep contract: the measured
    ``StreamEvent.collectives`` of the shard_map'd pipeline (forced
    2-shard mesh) must fit the static budget ``a2a_chunk x chunks +
    coll_final`` on the sharded A/B subset, the exchange pass must
    charge zero host syncs, and the zeroed-budget drift fixture must
    fail — the partitioned template really crosses shards, so a zero
    budget cannot hold."""
    import importlib.util
    path = os.path.join(REPO, "tools", "exec_audit_diff.py")
    spec = importlib.util.spec_from_file_location("exec_audit_diff2", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    shard_ev, n_shards = mod.collect_sharded_evidence()
    assert shard_ev, "sharded sweep found no multi-device mesh"
    ab = mod._load_ab_module()
    with ab._forced_stream_partitions():
        with ab._forced_stream_shards():
            reports = mod.predict(ab._STREAM_AB_QUERIES)
    ok, lines = mod.compare_sharded(reports, shard_ev, n_shards)
    assert ok, "\n".join(lines)
    drift_ok, drift_lines = mod.compare_sharded(reports, shard_ev,
                                                n_shards,
                                                inject_drift=True)
    assert not drift_ok, "sharded drift fixture failed to fail"
    assert any("collectives > static budget" in ln for ln in drift_lines)


# ---------------------------------------------------------------------------
# mem audit: static peak-HBM bounds + accumulator proofs
# ---------------------------------------------------------------------------


def mem_audit(sql, streamed=("store_sales",), **model_kw):
    from nds_tpu.analysis.mem_audit import MemAuditor, MemModel
    return MemAuditor(streamed=set(streamed),
                      model=MemModel(**model_kw)).audit_sql(sql)


def test_mem_audit_corpus_finite_and_deterministic():
    """Every template statement gets a finite positive byte bound, the
    walk is deterministic, and the partition decomposition clears EVERY
    capacity finding: the 7 former fan-out accumulators
    (query17/24x2/25/29/64/72) are now proven per partition, each
    per-partition bound inside the capacity model."""
    from nds_tpu.analysis.mem_audit import (audit_mem_corpus,
                                            hbm_capacity_bytes,
                                            reports_to_findings)
    reports = audit_mem_corpus()
    assert len(reports) >= 99
    for r in reports:
        assert r.mode in ("streamed", "device"), (r.query, r.detail)
        assert r.peak_bytes > 0 and r.out_rows >= 0
    assert reports_to_findings(reports) == []
    partitioned = {r.query: s for r in reports for s in r.scans
                   if s.partitions > 1}
    # query54 joined the set when its subquery conjuncts became
    # residual-planned filters: the graph turned provable and its
    # whole-statement bound is past capacity, so it decomposes too.
    # query17 LEFT the set when encoded columnar execution shrank its
    # streamed row width: the whole-statement bound now fits capacity,
    # so its static partition count dropped from 4 to 1 (asserted below)
    assert sorted(partitioned) == \
        ["query24_part1", "query24_part2", "query25",
         "query29", "query54", "query64", "query72"]
    cap = hbm_capacity_bytes()
    q17 = [s for r in reports if r.query == "query17" for s in r.scans]
    assert q17 and all(s.partitions == 1 for s in q17)
    assert any(s.provable and s.acc_bytes <= cap for s in q17)
    for q, s in partitioned.items():
        assert s.provable and s.part_bytes <= cap, (q, s)
        assert s.part_rows * s.partitions >= s.acc_rows, \
            (q, "partition shares must cover the whole bound")
    again = audit_mem_corpus()
    assert [r.to_dict() for r in again] == [r.to_dict() for r in reports]


def test_mem_audit_bound_rules():
    """The bound rules of DESIGN.md's static memory model, each on its
    canonical shape."""
    # PK star join: every batch covers a dimension primary key, so the
    # survivor multiplicity is 1 (k=0) and the accumulator is bounded by
    # the fact side's bucketed rows
    r = mem_audit("""
        select d_year, sum(ss_ext_sales_price) s
        from store_sales, date_dim, item
        where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
        group by d_year""")
    (s,) = r.scans
    assert s.provable and s.fanout_k == 0
    # group-by domain rule: d_year's value domain is at most date_dim's
    # row bound, far below the fact's
    from nds_tpu.analysis.mem_audit import DEFAULT_ROW_BOUNDS
    assert r.out_rows <= DEFAULT_ROW_BOUNDS["date_dim"]
    # non-PK equi join: bounded only by the enforced fanout pair bucket
    r = mem_audit("""
        select count(*) c from store_sales, item
        where ss_item_sk = i_brand_id""")
    (s,) = r.scans
    assert s.provable and s.fanout_k == 1
    assert r.out_rows == 1               # keyless aggregate: one row
    # a subquery conjunct is a residual-planned FILTER (multi-pass
    # streaming): it neither grows rows nor breaks the proof, so the
    # scan keeps the bare-scan bound
    r = mem_audit("""
        select count(*) c from store_sales where ss_sold_date_sk in
        (select d_date_sk from date_dim where d_moy = 11)""")
    assert r.scans and r.scans[0].provable and r.scans[0].fanout_k == 0
    # unconnected parts (cartesian layout): unprovable too
    r = mem_audit("select count(*) c from store_sales, item "
                  "where ss_ext_sales_price > 0 and i_brand_id = 1")
    assert r.scans and not r.scans[0].provable
    # filters assume no reduction: the filtered bare scan keeps the same
    # accumulator bound as the unfiltered one
    a = mem_audit("select ss_item_sk from store_sales")
    b = mem_audit("select ss_item_sk from store_sales "
                  "where ss_item_sk > 10")
    assert a.scans[0].acc_rows == b.scans[0].acc_rows
    # column pruning: referencing fewer columns shrinks the byte bound
    wide = mem_audit("select ss_item_sk, ss_ext_sales_price, "
                     "ss_sold_date_sk from store_sales")
    assert a.scans[0].acc_bytes < wide.scans[0].acc_bytes
    # LIMIT clamps the output-row bound exactly
    r = mem_audit("select ss_item_sk from store_sales "
                  "order by ss_item_sk limit 7")
    assert r.out_rows == 7
    # intersect/except output is a subset of the LEFT branch, never the
    # branch sum
    r = mem_audit("select d_year from date_dim except "
                  "select d_year from date_dim where d_moy = 1",
                  streamed=())
    assert r.out_rows == DEFAULT_ROW_BOUNDS["date_dim"]


def test_mem_audit_capacity_gate():
    """hbm-capacity trips when a proven accumulator bound (streamed) or a
    device-resident peak bound exceeds the configured capacity."""
    from nds_tpu.analysis.mem_audit import reports_to_findings
    r = mem_audit("select ss_item_sk from store_sales",
                  capacity_bytes=1 << 20)
    fs = reports_to_findings([r], capacity_bytes=1 << 20)
    assert [f.rule for f in fs] == ["hbm-capacity"]
    assert "accumulator" in fs[0].message
    # same statement under the default model: clean
    assert not reports_to_findings([mem_audit(
        "select ss_item_sk from store_sales")])
    # device-resident peak gate
    r = mem_audit("select * from customer", streamed=())
    assert r.mode == "device"
    fs = reports_to_findings([r], capacity_bytes=1 << 10)
    assert [f.rule for f in fs] == ["hbm-capacity"]
    assert "device-resident" in fs[0].message


def test_mem_audit_partition_rules(monkeypatch):
    """The grace-style partition proof: choose_partitions picks the
    smallest power-of-two count whose skew-factored per-partition bound
    fits capacity, NDS_TPU_STREAM_PARTITIONS pins it, scans with no
    chunk-side equi key never partition, and the hbm-capacity gate moves
    to the per-partition bound for partitioned scans."""
    from nds_tpu.analysis.mem_audit import (choose_partitions,
                                            partition_row_bound,
                                            reports_to_findings,
                                            stream_partition_keys,
                                            structural_row_bound)
    rows, k, fanout = 28_900_000, 1, 4
    whole = structural_row_bound(rows, k, fanout)
    # auto: whole bound fits -> unpartitioned
    assert choose_partitions(rows, k, fanout, 150,
                             whole * 150 + 1) == (1, None)
    # auto: over capacity -> smallest admitting power of two
    p, bound = choose_partitions(rows, k, fanout, 150, 16 << 30)
    assert p == 4 and bound == partition_row_bound(rows, 4, k, fanout)
    assert bound * 150 <= 16 << 30
    assert partition_row_bound(rows, 2, k, fanout) >= bound
    # the skew-factored shares always cover the whole bound
    assert bound * p >= whole // 2
    # forced count wins, rounded up to a power of two
    assert choose_partitions(rows, k, fanout, 150, 16 << 30,
                             forced=3)[0] == 4
    assert choose_partitions(rows, k, fanout, 150, 16 << 30,
                             forced=1) == (1, None)
    # nothing admits -> (1, None): the runtime keeps the legacy clamp
    assert choose_partitions(rows, k, fanout, 150, 1 << 10) == (1, None)

    # partition keys: the fan-out batch's chunk-side keys win over a
    # PK-covered batch; a bare scan (no equi edge) has none
    from nds_tpu.sql.parser import parse
    from nds_tpu.analysis.exec_audit import _conjuncts_of
    sel = parse("""select 1 from store_sales, date_dim, store_returns
                   where ss_sold_date_sk = d_date_sk
                     and ss_item_sk = sr_item_sk""").body
    part_cols = [{"store_sales.ss_sold_date_sk", "store_sales.ss_item_sk"},
                 {"date_dim.d_date_sk"},
                 {"store_returns.sr_item_sk",
                  "store_returns.sr_ticket_number"}]
    sources = ["store_sales", "date_dim", "store_returns"]
    keys = stream_partition_keys(part_cols, sources, 0,
                                 _conjuncts_of(sel.where))
    assert keys == ("ss_item_sk",)       # the k=1 batch, not the PK one
    assert stream_partition_keys(part_cols[:1], sources[:1], 0, []) is None

    # gate rule: a partitioned scan whose PER-PARTITION bound fits is
    # clean even though the whole-scan bound is past capacity...
    r = mem_audit("""select ss_item_sk, sr_return_amt
                     from store_sales, store_returns
                     where ss_item_sk = sr_item_sk""",
                  capacity_bytes=1 << 30)
    (s,) = r.scans
    assert s.partitions > 1 and s.acc_bytes > (1 << 30)
    assert s.part_bytes <= (1 << 30)
    assert not reports_to_findings([r], capacity_bytes=1 << 30)
    # ...and a forced under-partitioned count that cannot fit IS a
    # finding, named per partition
    monkeypatch.setenv("NDS_TPU_STREAM_PARTITIONS", "2")
    r = mem_audit("""select ss_item_sk, sr_return_amt
                     from store_sales, store_returns
                     where ss_item_sk = sr_item_sk""",
                  capacity_bytes=1 << 30)
    fs = reports_to_findings([r], capacity_bytes=1 << 30)
    assert [f.rule for f in fs] == ["hbm-capacity"]
    assert "per-partition" in fs[0].message


def test_mem_audit_scoped_star_pruning():
    """statement_needed_names mirrors the planner's scoped-star pruning:
    a star over a derived table disables nothing, a star over a catalog
    table adds that table's columns, an unresolvable star disables."""
    from nds_tpu.analysis.mem_audit import statement_needed_names
    from nds_tpu.sql.parser import parse
    got = statement_needed_names(parse(
        "with v as (select d_year y from date_dim) select * from v"))
    assert got is not None and "d_year" in got and "d_moy" not in got
    # a qualified star over an ALIASED CTE reference is still derived —
    # it must not disable pruning for the whole statement
    got = statement_needed_names(parse(
        "with v as (select d_year y from date_dim) select x.* from v x"))
    assert got is not None and "d_moy" not in got
    got = statement_needed_names(parse("select * from warehouse"))
    assert got is not None and "w_warehouse_sq_ft" in got
    got = statement_needed_names(parse("select t.* from nowhere t"))
    assert got is None


def test_mem_audit_env_knobs_read_at_model_build(monkeypatch):
    """MemModel reads NDS_TPU_HBM_BYTES / STREAM_ACC_ROWS / FANOUT at
    construction, not import — the same build-time discipline the
    executor follows."""
    from nds_tpu.analysis.mem_audit import MemModel, hbm_capacity_bytes
    monkeypatch.setenv("NDS_TPU_HBM_BYTES", "12345")
    monkeypatch.setenv("NDS_TPU_STREAM_ACC_ROWS", "777")
    monkeypatch.setenv("NDS_TPU_STREAM_FANOUT", "8")
    m = MemModel()
    assert hbm_capacity_bytes() == 12345
    assert m.capacity_bytes == 12345
    assert m.acc_ceiling == 777
    assert m.fanout == 8


def test_mem_audit_differential_harness():
    """The soundness contract: measured survivor/output counts must fit
    the static bounds on the A/B templates, and the harness must FAIL on
    the injected drift fixture (zeroed bounds)."""
    path = os.path.join(REPO, "tools", "mem_audit_diff.py")
    spec = importlib.util.spec_from_file_location("mem_audit_diff", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    queries, _ = mod._load_ab_templates()
    evidence, bounds = mod.collect_runtime_evidence()
    assert bounds["store_sales"] == 20_000      # the toy session's truth
    reports = mod.predict(queries, bounds)
    ok, lines = mod.compare(reports, evidence)
    assert ok, "\n".join(lines)
    drift_ok, drift_lines = mod.compare(reports, evidence,
                                        inject_drift=True)
    assert not drift_ok, "drift fixture failed to fail"
    assert any("UNSOUND" in ln for ln in drift_lines)


def test_mem_audit_sharded_bound_differential():
    """The sharded half of the soundness contract: every per-shard
    survivor count (``StreamEvent.shard_rows``) of the shard_map'd
    pipeline must fit the proven per-shard bound
    (``mem_audit.shard_row_bound`` — rows/shards x skew through the
    fan-out), the runtime shard count must equal the model's, and the
    zeroed-bound drift fixture must fail."""
    path = os.path.join(REPO, "tools", "mem_audit_diff.py")
    spec = importlib.util.spec_from_file_location("mem_audit_diff2", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    shard_ev, bounds, n_shards = mod.collect_sharded_evidence()
    assert shard_ev, "sharded sweep found no multi-device mesh"
    ab = mod._load_ab_module()
    with ab._forced_stream_partitions():
        with ab._forced_stream_shards():
            reports = mod.predict(ab._STREAM_AB_QUERIES, bounds)
    ok, lines = mod.compare_sharded(reports, shard_ev, n_shards)
    assert ok, "\n".join(lines)
    drift_ok, drift_lines = mod.compare_sharded(reports, shard_ev,
                                                n_shards,
                                                inject_drift=True)
    assert not drift_ok, "sharded drift fixture failed to fail"
    assert any("UNSOUND" in ln for ln in drift_lines)


# ---------------------------------------------------------------------------
# perf auditor: the static byte/roofline cost model
# ---------------------------------------------------------------------------


def _load_perf_diff(name="perf_audit_diff_t"):
    path = os.path.join(REPO, "tools", "perf_audit_diff.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_perf_audit_corpus_prices_clean():
    """Every corpus statement prices host-only with zero findings: no
    compiled scan fell to the unknown-table default width, and every
    compiled-stream statement carries a nonzero byte/roofline wall."""
    from nds_tpu.analysis.perf_audit import (audit_perf_corpus,
                                             reports_to_findings)
    reports = audit_perf_corpus()
    assert len(reports) == 103
    assert reports_to_findings(reports) == []
    for r in reports:
        if r.classification in ("compiled-stream", "device-resident"):
            assert r.roofline_ms > 0, r.query
            assert r.bytes_hbm > 0, r.query
        if r.classification == "compiled-stream":
            assert r.bytes_h2d > 0, r.query
            assert all(s.priced for s in r.scans if s.compiled), r.query


def test_perf_bottleneck_histogram_pinned():
    """The corpus cost story is a tier-1 contract, pinned like the 96/7
    classification counts: a width-model or stage-model change that
    silently shifts which link bounds a statement must fail loudly.
    Update these counts ONLY together with the matching engine/model
    change — the lockstep rule."""
    from nds_tpu.analysis.perf_audit import (audit_perf_corpus,
                                             bottleneck_counts)
    counts = bottleneck_counts(audit_perf_corpus())
    assert counts == {"h2d-bound": 89, "hbm-bound": 14}, counts


def test_perf_roofline_knobs_move_walls_not_bytes(monkeypatch):
    """NDS_TPU_ROOFLINE_*_GBS re-rates the walls (and can flip the
    bottleneck tag) but NEVER the byte totals — rates are frozen at
    auditor construction, bytes are pure chunk-shape arithmetic."""
    from nds_tpu.analysis.mem_audit import MemModel
    from nds_tpu.analysis.perf_audit import PerfAuditor, roofline_gbs
    monkeypatch.setenv("NDS_TPU_ROOFLINE_ICI_GBS", "93")
    assert roofline_gbs()["ici"] == 93.0
    assert roofline_gbs()["hbm"] == 819.0        # untouched -> default
    sql = ("select ss_item_sk, count(*) c from store_sales "
           "group by ss_item_sk")

    def price():
        model = MemModel(row_bounds={"store_sales": 20_000})
        return PerfAuditor(streamed={"store_sales"},
                           model=model).audit_sql(sql)

    base = price()
    assert base.classification == "compiled-stream"
    assert base.bound == "h2d-bound"             # 32 GB/s PCIe vs HBM
    monkeypatch.setenv("NDS_TPU_ROOFLINE_H2D_GBS", "1e9")
    monkeypatch.setenv("NDS_TPU_ROOFLINE_HBM_GBS", "0.001")
    rerated = price()
    assert rerated.bound == "hbm-bound"
    assert rerated.bytes_h2d == base.bytes_h2d
    assert rerated.bytes_hbm == base.bytes_hbm
    assert rerated.wall_hbm_ms > base.wall_hbm_ms


def test_perf_audit_differential_harness():
    """The exactness contract: measured ``StreamEvent.bytes_h2d`` must
    EQUAL the closed-form prediction on every A/B template (live wire
    widths + the toy session's real rows/chunk geometry), warm must be
    byte-identical to cold, and the zeroed-prediction drift fixture must
    fail."""
    import numpy as np
    mod = _load_perf_diff()
    ab = mod._load_ab_module()
    queries = ab._STREAM_AB_QUERIES
    with ab._forced_stream_partitions():
        session = ab._chunked_star_session(np.random.default_rng(42))
        bounds, chunk_rows = mod._session_params(session)
        assert bounds["store_sales"] == 20_000  # the toy session's truth
        assert chunk_rows == 2048       # passed to ChunkedTable, not env
        reports = mod.predict(queries, bounds, chunk_rows,
                              mod._wire_cols(session))
        evidence = mod._run_sweep(ab, session, list(range(len(queries))))
    # live wire widths upgrade every prediction from bound to equality
    assert all(r.h2d_exact for r in reports)
    # ab12's scalar-subquery chain prices TWO store_sales pipelines,
    # both at the statement-level pruning (the planner prunes once)
    assert sum(1 for c in reports[11].scans if c.compiled) == 2
    ok, lines = mod.compare(reports, evidence)
    assert ok, "\n".join(lines)
    drift_ok, drift_lines = mod.compare(reports, evidence, inject=True)
    assert not drift_ok, "drift fixture failed to fail"
    assert any("EXACTNESS LOST" in ln for ln in drift_lines)


def test_perf_audit_kernel_arm_differential():
    """Fused-kernel arm: the upload equality holds unchanged (the
    kernels collapse HBM re-reads, not h2d) and measured launches land
    inside the nonzero static band; zeroed bands must fail."""
    import numpy as np
    mod = _load_perf_diff("perf_audit_diff_t2")
    ab = mod._load_ab_module()
    queries = ab._STREAM_AB_QUERIES
    idxs = list(ab._STREAM_AB_KERNEL)
    with ab._forced_stream_partitions():
        with ab._forced_pallas("interpret"):
            session = ab._chunked_star_session(np.random.default_rng(42))
            bounds, chunk_rows = mod._session_params(session)
            reports = mod.predict(queries, bounds, chunk_rows,
                                  mod._wire_cols(session))
            evidence = mod._run_sweep(ab, session, idxs)
    assert any(c.kernel_max > 0 for i in idxs for c in reports[i].scans)
    ok, lines = mod.compare_kernels(reports, evidence)
    assert ok, "\n".join(lines)
    drift_ok, _lines = mod.compare_kernels(reports, evidence, inject=True)
    assert not drift_ok, "kernel drift fixture failed to fail"


def test_perf_audit_sharded_ici_differential():
    """Sharded arm: measured ``StreamEvent.bytes_ici`` must EQUAL the
    static exchange+reduce aval arithmetic (every subset template is
    ici-exact — no outer builds), and zeroed predictions must fail."""
    import jax
    import numpy as np
    mod = _load_perf_diff("perf_audit_diff_t3")
    ab = mod._load_ab_module()
    queries = ab._STREAM_AB_QUERIES
    with ab._forced_stream_partitions():
        with ab._forced_stream_shards() as n_shards:
            assert len(jax.local_devices()) >= n_shards, \
                "sharded arm needs the forced multi-device mesh"
            session = ab._chunked_star_session(np.random.default_rng(42))
            bounds, chunk_rows = mod._session_params(session)
            reports = mod.predict(queries, bounds, chunk_rows,
                                  mod._wire_cols(session))
            evidence = mod._run_sweep(ab, session,
                                      list(ab._STREAM_AB_SHARDED))
    # the exchange pass is live on at least one subset statement (the
    # arm would be vacuous if every pipeline were reduce-only)
    assert any(c.exchange for i in ab._STREAM_AB_SHARDED
               for c in reports[i].scans)
    ok, lines = mod.compare_sharded(reports, evidence, n_shards)
    assert ok, "\n".join(lines)
    drift_ok, drift_lines = mod.compare_sharded(reports, evidence,
                                                n_shards, inject=True)
    assert not drift_ok, "sharded drift fixture failed to fail"
    assert any("EXACTNESS LOST" in ln for ln in drift_lines)


def test_perf_audit_encoded_off_differential():
    """NDS_TPU_ENCODED=0 arm: the same h2d equality at PLAIN widths —
    the arm that catches a width table hard-wired to the encoded path.
    The toy star's int64 columns ride 8+1 wire bytes unencoded."""
    import numpy as np
    mod = _load_perf_diff("perf_audit_diff_t4")
    ab = mod._load_ab_module()
    queries = ab._STREAM_AB_QUERIES
    with mod._encoded_off():
        with ab._forced_stream_partitions():
            session = ab._chunked_star_session(np.random.default_rng(42))
            bounds, chunk_rows = mod._session_params(session)
            wire = mod._wire_cols(session)
            reports = mod.predict(queries, bounds, chunk_rows, wire)
            evidence = mod._run_sweep(ab, session,
                                      list(mod._ENCODED_OFF_SUBSET))
    assert set(wire["store_sales"].values()) == {9}
    ok, lines = mod.compare(reports, evidence)
    assert ok, "\n".join(lines)
    drift_ok, _lines = mod.compare(reports, evidence, inject=True)
    assert not drift_ok, "encoded-off drift fixture failed to fail"


# ---------------------------------------------------------------------------
# baseline diffing + CI gate
# ---------------------------------------------------------------------------


def test_baseline_rejects_only_new_findings():
    from nds_tpu.analysis import Finding, diff_against_baseline
    old = Finding("a.py", "f", "rule-x", "warning", "msg")
    dup = Finding("a.py", "f", "rule-x", "warning", "msg")
    new = Finding("b.py", "g", "rule-y", "error", "other")
    baseline = {old.key(): 1}
    assert diff_against_baseline([old, new], baseline) == [new]
    # a second instance of an accepted finding is NEW (count semantics)
    assert diff_against_baseline([old, dup], baseline) == [dup]
    assert diff_against_baseline([old], {}) == [old]


def test_baseline_roundtrip(tmp_path):
    from nds_tpu.analysis import (Finding, diff_against_baseline,
                                  load_baseline, write_baseline)
    fs = [Finding("a.py", "f", "r", "warning", "m"),
          Finding("a.py", "f", "r", "warning", "m")]
    path = str(tmp_path / "baseline.json")
    write_baseline(fs, path)
    assert diff_against_baseline(fs, load_baseline(path)) == []


def _run_lint(*argv):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"), *argv],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


@pytest.fixture(scope="module")
def lint_combined(tmp_path_factory):
    """ONE clean-tree lint subprocess shared by every report-plumbing
    test below. The nine passes run identically whichever report flags
    ride, so the per-flag CLI tests differ only in FORMATTING — a single
    combined ``--format json`` run (machine document on stdout, every
    human table on stderr, ``--json`` file alongside) covers them all
    for the price of one subprocess instead of eight on a one-core
    runner. Seeded-corpus and exit-code-contract runs stay per-test."""
    json_path = str(tmp_path_factory.mktemp("lint") / "report.json")
    r = _run_lint("--format", "json", "--json", json_path,
                  "--stream-report", "--mem-report", "--perf-report",
                  "--num-report", "--param-report")
    assert r.returncode == 0, r.stdout + r.stderr
    return r, json.loads(r.stdout), json_path


def test_lint_cli_gate(tmp_path, lint_combined):
    """The shipped baseline gates clean; a seeded bad template fails.
    Rides the shared subprocess to check --num-report plumbing (the
    proof table, on stderr under --format json) and the ``num_report``
    field in the --json file document."""
    r, _doc, json_path = lint_combined
    assert "# num-audit: per-statement value-range/precision proofs" \
        in r.stderr
    assert "proven-safe compiled-stream" in r.stderr
    report = json.load(open(json_path))
    assert report["pass_counts"]["plan-audit"] >= 1
    assert report["pass_counts"]["num-audit"] == 0
    assert len(report["num_report"]) == 103
    assert not report["new"]

    seeded = tmp_path / "templates"
    shutil.copytree(TEMPLATES, seeded)
    (seeded / "querybad.tpl").write_text(
        "select ss_no_such from store_sales, customer_demographics\n")
    with open(seeded / "templates.lst", "a") as f:
        f.write("querybad.tpl\n")
    r = _run_lint("--templates", str(seeded))
    assert r.returncode == 2
    assert "unresolved-column" in r.stdout
    assert "cartesian-join" in r.stdout


def test_lint_cli_format_json(tmp_path, lint_combined):
    """--format json: stable machine-readable findings on stdout (rule,
    file, symbol, count, baselined) with the exit-code contract
    unchanged."""
    _r, doc, _path = lint_combined
    assert doc["version"] == 1
    assert set(doc["pass_counts"]) == {"plan-audit", "exec-audit",
                                       "mem-audit", "perf-audit",
                                       "num-audit", "param-audit",
                                       "jax-lint", "driver-audit",
                                       "conc-audit"}
    entries = doc["findings"]
    assert entries == sorted(
        entries, key=lambda e: (e["rule"], e["file"], e["symbol"]))
    for e in entries:
        assert set(e) == {"rule", "file", "symbol", "severity", "count",
                          "baselined"}
    # the shipped tree is fully baselined: exactly q77's spec-deliberate
    # cartesian (partitioned accumulation cleared the 7 former
    # hbm-capacity fan-out findings), nothing new
    assert doc["new"] == 0
    assert [(e["rule"], e["baselined"]) for e in entries] == \
        [("cartesian-join", True)]
    # a failing corpus keeps stdout pure JSON and still exits 2
    seeded = tmp_path / "templates"
    shutil.copytree(TEMPLATES, seeded)
    (seeded / "querybad.tpl").write_text("select ss_no_such from store_sales\n")
    with open(seeded / "templates.lst", "a") as f:
        f.write("querybad.tpl\n")
    r = _run_lint("--templates", str(seeded), "--format", "json")
    assert r.returncode == 2
    doc = json.loads(r.stdout)
    assert doc["new"] >= 1
    assert any(e["rule"] == "unresolved-column" and not e["baselined"]
               for e in doc["findings"])


def test_lint_cli_stream_report(lint_combined):
    r, doc, _path = lint_combined
    assert "per-template execution-path classification" in r.stderr
    for klass in ("compiled-stream", "device-resident"):
        assert klass in r.stderr
    # multi-pass streaming: the report names the conversion mechanisms
    # that serve the formerly-eager statements
    for mech in ("streamed-subquery", "outer-gather", "outer-build"):
        assert mech in r.stderr
    # --format json: the machine-readable report carries the mechanism
    # field per scan, stdout stays ONE parseable document
    scans = [s for e in doc["stream_report"] for s in e["scans"]]
    assert any("streamed-subquery" in s["mechanisms"] for s in scans)
    assert any("outer-gather" in s["mechanisms"] for s in scans)


def test_stream_report_classification_counts_pinned():
    """The corpus classification is a tier-1 contract, pinned the same
    way baseline.json is: --stream-report drift (a statement silently
    reclassifying to eager-fallback, or a conversion quietly lost) must
    fail loudly, not surface months later in an SF10 campaign. Update
    these counts ONLY together with the matching engine/audit change —
    the lockstep rule."""
    from collections import Counter

    from nds_tpu.analysis.exec_audit import audit_exec_corpus
    counts = Counter(r.classification for r in audit_exec_corpus())
    assert counts == {"compiled-stream": 96, "device-resident": 7}, counts


def test_lint_cli_mem_report(lint_combined):
    r, doc, _path = lint_combined
    assert "per-statement peak-HBM byte bounds" in r.stderr
    assert "capacity model" in r.stderr
    # provable accumulators print their row bound; the multi-pass
    # conversions left no unprovable corpus scan (subquery conjuncts are
    # residual-planned filters now)
    assert "rows, k=" in r.stderr
    assert "unprovable (eager loop)" not in r.stderr
    # --format json keeps stdout a single document with the report inline
    assert len(doc["mem_report"]) >= 99
    assert all(e["peak_bytes"] > 0 for e in doc["mem_report"])


def test_lint_cli_perf_report(lint_combined):
    r, doc, _path = lint_combined
    assert "per-statement static cost model" in r.stderr
    assert "rates GB/s" in r.stderr
    # the pinned histogram rides the summary line
    assert "h2d-bound" in r.stderr and "hbm-bound" in r.stderr
    # --format json keeps stdout ONE parseable document with the full
    # cost table inline — the machine-readable round trip
    entries = doc["perf_report"]
    assert len(entries) == 103
    for e in entries:
        assert e["bound"] in ("h2d-bound", "hbm-bound", "ici-bound",
                              "sync-bound")
        if e["classification"] == "compiled-stream":
            assert e["bytes_h2d"] > 0 and e["roofline_ms"] > 0
            assert e["scans"] and all(s["priced"] for s in e["scans"]
                                      if s["compiled"])


def test_lint_cli_changed_fast_path():
    """--changed lints only the current git diff; in this checkout it must
    still honor the baseline gate, and it is incompatible with
    --update-baseline (which needs the full findings set)."""
    r = _run_lint("--changed")
    assert r.returncode in (0, 2), r.stdout + r.stderr
    assert "changed files)" in r.stdout or "# lint" in r.stdout
    r = _run_lint("--changed", "--update-baseline")
    assert r.returncode != 0
    assert "--changed" in r.stderr


def test_lint_cli_update_baseline_refuses_foreign_corpus(tmp_path):
    """--update-baseline over a --templates corpus must not clobber the
    checked-in baseline; an explicit --baseline path makes it legal."""
    seeded = tmp_path / "templates"
    shutil.copytree(TEMPLATES, seeded)
    shipped = os.path.join(REPO, "nds_tpu", "analysis", "baseline.json")
    before = open(shipped).read()
    r = _run_lint("--templates", str(seeded), "--update-baseline")
    assert r.returncode != 0
    assert "foreign corpus" in r.stderr
    assert open(shipped).read() == before
    alt = str(tmp_path / "alt_baseline.json")
    report = tmp_path / "accepted.json"
    r = _run_lint("--templates", str(seeded), "--update-baseline",
                  "--baseline", alt, "--json", str(report))
    assert r.returncode == 0, r.stdout + r.stderr
    assert os.path.exists(alt)
    # --json alongside --update-baseline still writes the report, showing
    # what was just accepted relative to the pre-update baseline
    assert json.load(open(report))["all"]
    r = _run_lint("--templates", str(seeded), "--baseline", alt)
    assert r.returncode == 0, r.stdout + r.stderr


# ---------------------------------------------------------------------------
# fused Pallas chunk kernels: lint rule + static prediction + lockstep
# ---------------------------------------------------------------------------


def test_jax_lint_host_read_in_pallas(tmp_path):
    """Both directions of the host-read-in-pallas rule: host reads,
    engine sync entry points, a one-level-down syncing helper and an
    obs.span inside a pallas_call kernel body are errors; the same
    calls outside any kernel body (or a clean body) are not."""
    fs = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import pallas as pl
        from nds_tpu.engine import ops
        from nds_tpu.obs import trace as obs

        def _helper(x):
            return ops.count_int(x.nrows)

        def make(x):
            def kernel(in_ref, out_ref):
                with obs.span("inner"):
                    pass
                ops.host_read("tag", lambda: 1)
                in_ref.to_int()
                _helper(in_ref)
                out_ref[:] = in_ref[:]
            return pl.pallas_call(kernel, out_shape=None)(x)
    """, rel="nds_tpu/engine/other.py")
    rules = [f.rule for f in fs]
    assert rules == ["host-read-in-pallas"] * 4, fs
    assert all(f.severity == "error" for f in fs)
    # clean kernel body + syncs OUTSIDE the body: no findings (the rule
    # must not leak past the pallas_call'd function)
    fs = lint_snippet(tmp_path, """
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from nds_tpu.engine import ops

        def make(x):
            def kernel(in_ref, out_ref):
                out_ref[:] = in_ref[:] * 2
            got = pl.pallas_call(kernel, out_shape=None)(x)
            n = ops.count_int(4)          # outside: legal
            return got, n
    """, rel="nds_tpu/engine/other.py")
    assert not [f for f in fs if f.rule == "host-read-in-pallas"], fs


def test_jax_lint_pallas_rule_baseline_untouched():
    """The shipped kernel bodies (engine/kernels.py) must be clean under
    the new rule — the baseline gains nothing."""
    from nds_tpu.analysis.jax_lint import lint_file
    path = os.path.join(REPO, "nds_tpu", "engine", "kernels.py")
    fs = lint_file(path, "nds_tpu/engine/kernels.py")
    assert not [f for f in fs if f.rule == "host-read-in-pallas"], fs


def test_jax_lint_host_sync_in_prefetch_worker(tmp_path):
    """Both directions of the host-sync-in-prefetch-worker rule: host
    reads, engine sync entry points, a one-level-down syncing helper
    and an obs.span inside a callable handed to the prefetch ring
    (positional or prepare=, bare name or self.method) are errors; the
    same calls outside any ring callable (or a clean prepare) are
    not."""
    fs = lint_snippet(tmp_path, """
        from nds_tpu.engine import ops
        from nds_tpu.engine.prefetch import chunk_ring
        from nds_tpu.obs import trace as obs

        def _helper(x):
            return ops.count_int(x.nrows)

        def _prepare(chunk):
            with obs.span("inner"):
                pass
            ops.host_read("tag", lambda: 1)
            n = chunk.nrows.to_int()
            _helper(chunk)
            return chunk

        def drive(chunks):
            ring = chunk_ring(chunks, prepare=_prepare)
            return ring
    """, rel="nds_tpu/engine/other.py")
    rules = [f.rule for f in fs]
    assert rules == ["host-sync-in-prefetch-worker"] * 4, fs
    assert all(f.severity == "error" for f in fs)
    # self.method spelling + constructor form resolve too
    fs = lint_snippet(tmp_path, """
        from nds_tpu.engine import ops
        from nds_tpu.engine.prefetch import ChunkRing

        class Pipe:
            def _prep(self, chunk):
                return ops.resolve_counts()

            def run(self, chunks):
                return ChunkRing(chunks, self._prep, depth=2)
    """, rel="nds_tpu/engine/other.py")
    assert [f.rule for f in fs] == ["host-sync-in-prefetch-worker"], fs
    # the SOURCE iterator's generator body runs on the worker too: a
    # call expression passed as the source resolves by its callee name
    fs = lint_snippet(tmp_path, """
        from nds_tpu.engine import ops
        from nds_tpu.engine.prefetch import chunk_ring

        class Scan:
            def device_chunks(self, planner):
                for c in self.chunks:
                    ops.host_sync(c.nrows)
                    yield c

            def drive(self, planner):
                return chunk_ring(self.device_chunks(planner))
    """, rel="nds_tpu/engine/other.py")
    assert [f.rule for f in fs
            if f.rule == "host-sync-in-prefetch-worker"] == \
        ["host-sync-in-prefetch-worker"], fs
    # clean prepare + syncs OUTSIDE the ring callable: no findings
    fs = lint_snippet(tmp_path, """
        from nds_tpu.engine import ops
        from nds_tpu.engine.prefetch import chunk_ring

        def _prepare(chunk):
            return tuple(chunk.columns.values())

        def drive(chunks):
            ring = chunk_ring(chunks, prepare=_prepare)
            n = ops.count_int(4)          # outside: legal
            return ring, n
    """, rel="nds_tpu/engine/other.py")
    assert not [f for f in fs
                if f.rule == "host-sync-in-prefetch-worker"], fs


def test_jax_lint_prefetch_rule_baseline_untouched():
    """The shipped ring callables (engine/stream.py's prepare methods,
    engine/prefetch.py itself, the planner's eager-loop ring) must be
    clean under the new rule — the baseline gains nothing."""
    from nds_tpu.analysis.jax_lint import lint_file
    for rel in ("nds_tpu/engine/stream.py", "nds_tpu/engine/prefetch.py",
                "nds_tpu/sql/planner.py"):
        fs = lint_file(os.path.join(REPO, *rel.split("/")), rel)
        assert not [f for f in fs
                    if f.rule == "host-sync-in-prefetch-worker"], (rel, fs)


def test_kernel_spec_eligibility_rule():
    """The shared eligibility rule (analysis/kernel_spec.py) on its
    canonical shapes — the ONE rule the runtime lowering and the static
    kernel prediction both consume."""
    from nds_tpu.analysis.kernel_spec import (count_eligible,
                                              eligible_conjunct)
    from nds_tpu.sql.parser import parse

    def conjs(sql):
        q = parse(f"select 1 from t where {sql}")
        w = q.body.where
        out = []

        def split(e):
            import nds_tpu.sql.ast as A
            if isinstance(e, A.BinaryOp) and e.op == "and":
                split(e.left)
                split(e.right)
            else:
                out.append(e)
        split(w)
        return out

    classes = {"a": "num", "d": "date", "s": "str", "b": "bool"}

    def class_of(ref):
        return classes.get(ref.name.lower())

    cs = conjs("a > 5 and 5 < a and a = 2.5 and s = 'x' and s > 'x' "
               "and a in (1, 2, 3) and a between 1 and 9 "
               "and s is not null and b = 1 and a > s")
    want = [True, True, True, True, False,
            True, True, True, False, False]
    got = [eligible_conjunct(c, class_of) for c in cs]
    assert got == want, list(zip(got, want, cs))
    assert count_eligible(cs, class_of) == sum(want)
    # the IN-list cap is part of the rule (kernel code size bound)
    big = conjs(f"a in ({', '.join(str(i) for i in range(17))})")
    assert not eligible_conjunct(big[0], class_of)


def test_kernel_spec_threshold_math():
    """Exact rational -> stored-space threshold mapping (the encoded-
    space evaluation): boundaries, non-integral equalities, FOR rebase
    and sorted-dict bisect."""
    from fractions import Fraction

    from nds_tpu.analysis.kernel_spec import (dict_map, shift_for,
                                              value_cmp)
    F = Fraction
    assert value_cmp("<", F(11, 2)) == ("ile", 5)    # v < 5.5 -> v <= 5
    assert value_cmp("<=", F(11, 2)) == ("ile", 5)
    assert value_cmp(">", F(11, 2)) == ("ige", 6)
    assert value_cmp(">=", F(11, 2)) == ("ige", 6)
    assert value_cmp("<", F(5)) == ("ile", 4)        # v < 5 -> v <= 4
    assert value_cmp("=", F(11, 2)) == ("false",)
    assert value_cmp("<>", F(11, 2)) == ("true",)
    assert value_cmp("=", F(7)) == ("ieq", 7)
    assert shift_for(("ile", 100), 40) == ("ile", 60)
    assert shift_for(("irange", 10, 20), 5) == ("irange", 5, 15)
    vals = [10, 20, 30]
    assert dict_map(("ieq", 20), vals) == ("ieq", 1)
    assert dict_map(("ieq", 25), vals) == ("false",)
    assert dict_map(("ile", 25), vals) == ("ile", 1)
    assert dict_map(("ige", 25), vals) == ("ige", 2)
    assert dict_map(("irange", 15, 30), vals) == ("irange", 1, 2)


def test_exec_audit_kernel_prediction():
    """The static kernel budget: exact scan/stage predictions from the
    shared eligibility rule under an explicit NDS_TPU_PALLAS mode, and
    all-zero under auto/off (the auditor cannot see the backend)."""
    from nds_tpu.analysis.exec_audit import ExecAuditor
    sql = ("select ss_item_sk from store_sales "
           "where ss_quantity > 5 and ss_item_sk in (1, 2)")
    old = os.environ.get("NDS_TPU_PALLAS")
    try:
        os.environ["NDS_TPU_PALLAS"] = "interpret"
        rep = ExecAuditor(streamed={"store_sales"}).audit_sql(sql)
        (scan,) = [s for s in rep.scans if s.compiled]
        assert scan.kernel_scan_chunk == 1
        assert scan.kernel_stages == 2          # two eligible conjuncts
        os.environ["NDS_TPU_PALLAS"] = "off"
        rep2 = ExecAuditor(streamed={"store_sales"}).audit_sql(sql)
        (scan2,) = [s for s in rep2.scans if s.compiled]
        assert (scan2.kernel_scan_chunk, scan2.kernel_stages,
                scan2.kernel_probe_chunk) == (0, 0, 0)
    finally:
        if old is None:
            os.environ.pop("NDS_TPU_PALLAS", None)
        else:
            os.environ["NDS_TPU_PALLAS"] = old


def test_exec_audit_kernel_differential():
    """The fused-kernel half of the lockstep contract: drained
    StreamEvent kernel evidence (NDS_TPU_PALLAS=interpret sweep) must
    match the static kernel predictions — stage counts exactly, launch
    totals inside the scan-floor/probe-ceiling window, stream.kernel
    spans sync-free — and the zeroed-prediction drift fixture must
    fail."""
    import importlib.util
    path = os.path.join(REPO, "tools", "exec_audit_diff.py")
    spec = importlib.util.spec_from_file_location("exec_audit_diff3", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    kern_ev = mod.collect_kernel_evidence()
    ab = mod._load_ab_module()
    with ab._forced_stream_partitions():
        with ab._forced_pallas("interpret"):
            reports = mod.predict(ab._STREAM_AB_QUERIES)
    ok, lines = mod.compare_kernels(reports, kern_ev)
    assert ok, "\n".join(lines)
    drift_ok, drift_lines = mod.compare_kernels(reports, kern_ev,
                                                inject_drift=True)
    assert not drift_ok, "kernel drift fixture failed to fail"
    assert any("kernel model drift" in ln or "static window" in ln
               for ln in drift_lines)


def test_mem_audit_kernel_differential():
    """Kernel-arm soundness: the fused scan/probe kernels reuse the SAME
    proof-sized accumulators, so every survivor/partition bound holds on
    the Pallas arm, the subset really engages the kernels, and zeroed
    bounds must fail."""
    import importlib.util
    path = os.path.join(REPO, "tools", "mem_audit_diff.py")
    spec = importlib.util.spec_from_file_location("mem_audit_diff3", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    kern_ev, bounds, idxs = mod.collect_kernel_evidence()
    assert kern_ev and idxs
    ab = mod._load_ab_module()
    reports = mod.predict(ab._STREAM_AB_QUERIES, bounds)
    subset = [reports[i] for i in idxs]
    ok, lines = mod.compare_kernels(subset, kern_ev)
    assert ok, "\n".join(lines)
    drift_ok, drift_lines = mod.compare_kernels(subset, kern_ev,
                                                inject_drift=True)
    assert not drift_ok, "kernel-arm drift fixture failed to fail"
    assert any("UNSOUND" in ln for ln in drift_lines)


def test_lint_changed_covers_kernels():
    """tools/lint.py --changed: an edit to engine/kernels.py must rerun
    the corpus passes (the kernel prediction lives in exec_audit and the
    shared rule in analysis/kernel_spec.py — all under _CORPUS_ROOTS)."""
    import importlib.util
    path = os.path.join(REPO, "tools", "lint.py")
    spec = importlib.util.spec_from_file_location("lint_tool_k", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for p in ("nds_tpu/engine/kernels.py",
              "nds_tpu/analysis/kernel_spec.py",
              # async ingest data plane: the prefetch ring (admission
              # pricing + worker lint contract) and the persistent
              # chunk store (the streamed wire format) rerun the
              # corpus passes on edit
              "nds_tpu/engine/prefetch.py",
              "nds_tpu/io/chunk_store.py",
              # fault-tolerance layer: seam/classification edits move
              # exec_audit's retry-paths row and the swallowed-fault
              # contract
              "nds_tpu/engine/faults.py",
              # campaign driver: its arm-failure handling is a client
              # of the swallowed-fault contract and its fingerprint
              # stamp is the provenance every ledger record keys on
              "nds_tpu/obs/campaign.py",
              # numeric-safety layer: the value-range interpreter and
              # the saturating encoded-compare rebase it models
              "nds_tpu/analysis/num_audit.py",
              "nds_tpu/engine/exprs.py",
              # parameterization layer: the literal-bindability prover
              # whose shared rule the stream dispatcher imports
              "nds_tpu/analysis/param_audit.py"):
        assert p.startswith(mod._CORPUS_ROOTS), \
            f"{p} not covered by _CORPUS_ROOTS"


# ---------------------------------------------------------------------------
# concurrency audit: shared-state classification + lock discipline
# ---------------------------------------------------------------------------


def conc_audit_tree(tmp_path, files, registry=None, entry_points=None):
    """Audit a throwaway package: ``files`` maps name -> source. Default
    entry points make EVERY function a concurrent root (severity error),
    matching how snippet rules are asserted."""
    import shutil
    from nds_tpu.analysis.conc_audit import audit_package
    pkg = tmp_path / "pkg"
    shutil.rmtree(pkg, ignore_errors=True)   # fresh tree per call
    pkg.mkdir()
    for name, code in files.items():
        (pkg / name).write_text(textwrap.dedent(code))
    return audit_package(str(pkg), repo=str(tmp_path),
                         registry=registry if registry is not None else {},
                         entry_points=entry_points or (("", ""),))


def test_conc_audit_accepted_state_classes(tmp_path):
    """Thread-local stores, bounded-ring appends, atomic latch rebinds,
    lock-guarded (consistently) mutations and import-time construction
    are the ACCEPTED classes — none may produce a finding."""
    fs = conc_audit_tree(tmp_path, {"mod.py": """
        import threading
        from collections import deque

        _CACHE: dict = {}
        _LOCK = threading.Lock()
        _tls = threading.local()
        RING = deque(maxlen=10)
        FLAG = False
        IMPORT_BUILT = {}
        IMPORT_BUILT["x"] = 1            # import scope: serialized

        def guarded(k, v):
            with _LOCK:
                if len(_CACHE) >= 8:
                    _CACHE.pop(next(iter(_CACHE)))
                _CACHE[k] = v

        def tls_write():
            _tls.ring = []

        def ring_write(x):
            RING.append(x)

        def latch():
            global FLAG
            FLAG = True
    """})
    assert not [f for f in fs if f.rule != "cache-unregistered"], fs


def test_conc_audit_unguarded_and_rmw(tmp_path):
    """A bare container mutation and an augmented (read-modify-write)
    rebind of a module global are findings; severity is error because
    the snippet entry points make everything concurrently reachable."""
    fs = conc_audit_tree(tmp_path, {"mod.py": """
        _STATE: dict = {}
        COUNT = 0

        def unguarded(k, v):
            _STATE[k] = v

        def rmw():
            global COUNT
            COUNT += 1
    """})
    rules = sorted(f.rule for f in fs)
    assert rules == ["unguarded-mutation", "unguarded-mutation"], fs
    assert all(f.severity == "error" for f in fs)


def test_conc_audit_mixed_guard(tmp_path):
    """State mutated under its lock at one site and off-lock at another:
    the off-lock site is flagged (the lock protects nothing)."""
    fs = conc_audit_tree(tmp_path, {"mod.py": """
        import threading
        _CACHE: dict = {}
        _LOCK = threading.Lock()

        def guarded(k, v):
            with _LOCK:
                _CACHE[k] = v

        def sneaky(k, v):
            _CACHE[k] = v
    """})
    assert [f.rule for f in fs if f.rule == "mixed-guard"], fs
    hit = next(f for f in fs if f.rule == "mixed-guard")
    assert hit.query == "sneaky"


def test_conc_audit_sync_compile_wait_under_lock(tmp_path):
    """host_read-family calls, jax.jit compiles and blocking waits held
    under a lock are errors — directly and one level down into a
    module-local helper."""
    fs = conc_audit_tree(tmp_path, {"mod.py": """
        import threading
        import jax
        from nds_tpu.engine import ops
        _LOCK = threading.Lock()

        def _helper(x):
            return ops.count_int(x)

        def bad(x, f, ev):
            with _LOCK:
                n = x.item()
                g = jax.jit(f)
                ev.wait()
                m = _helper(x)
            return n, g, m

        def good(x, f):
            n = x.item()                 # off-lock: fine
            g = jax.jit(f)
            with _LOCK:
                pass
            return n, g
    """})
    rules = sorted(f.rule for f in fs)
    assert rules == ["compile-under-lock", "sync-under-lock",
                     "sync-under-lock", "wait-under-lock"], fs
    assert all(f.query == "bad" for f in fs)


def test_conc_audit_lock_order_cycle(tmp_path):
    """Opposite-order nested acquisition across functions is a deadlock
    finding; one consistent global order is clean."""
    fs = conc_audit_tree(tmp_path, {"mod.py": """
        import threading
        _A = threading.Lock()
        _B = threading.Lock()

        def ab():
            with _A:
                with _B:
                    pass

        def ba():
            with _B:
                with _A:
                    pass
    """})
    assert [f for f in fs if f.rule == "lock-order-cycle"], fs
    fs = conc_audit_tree(tmp_path, {"mod2.py": """
        import threading
        _A = threading.Lock()
        _B = threading.Lock()

        def ab():
            with _A:
                with _B:
                    pass

        def ab2():
            with _A:
                with _B:
                    pass
    """})
    assert not [f for f in fs if f.rule == "lock-order-cycle"], fs


def test_conc_audit_param_alias(tmp_path):
    """A module cache passed as a plain parameter: mutations inside the
    callee count against the module global with the CALLEE's guard —
    guarded helper clean, unguarded helper flagged (the _identity_cache
    pattern)."""
    guarded = {"mod.py": """
        import threading
        _RANK_CACHE: dict = {}
        _LOCK = threading.Lock()

        def memo(cache, key, value):
            with _LOCK:
                cache[key] = value

        def use(key, value):
            return memo(_RANK_CACHE, key, value)
    """}
    fs = conc_audit_tree(tmp_path, guarded)
    assert not [f for f in fs
                if f.rule in ("unguarded-mutation", "mixed-guard")], fs
    bad = {"mod2.py": """
        _RANK_CACHE: dict = {}

        def memo(cache, key, value):
            cache[key] = value

        def use(key, value):
            return memo(_RANK_CACHE, key, value)
    """}
    fs = conc_audit_tree(tmp_path, bad)
    hits = [f for f in fs if f.rule == "unguarded-mutation"]
    assert hits and "_RANK_CACHE" in hits[0].message, fs


def test_conc_audit_cache_key_completeness(tmp_path):
    """A registered cache whose value-builder reads an env knob the key
    expression never sees is an error; adding the knob to the key (or
    an explicit justified exemption) clears it."""
    from nds_tpu.analysis.conc_audit import CacheSpec
    missing = {"keyed.py": """
        import os
        import threading
        _STEP_CACHE: dict = {}
        _LOCK = threading.Lock()

        def knob():
            return int(os.environ.get("MY_KNOB", "4"))

        def build(n):
            return n * knob()

        def make_key(n):
            return (n,)

        def lookup(n):
            k = make_key(n)
            got = _STEP_CACHE.get(k)
            if got is None:
                built = build(n)
                with _LOCK:
                    got = _STEP_CACHE.setdefault(k, built)
            return got
    """}
    reg = {("pkg/keyed.py", "_STEP_CACHE"): CacheSpec(
        key_fns=("make_key",), builder_fns=("build",),
        modules=("pkg/keyed.py",))}
    fs = conc_audit_tree(tmp_path, missing, registry=reg)
    hits = [f for f in fs if f.rule == "cache-key-missing-knob"]
    assert hits and "MY_KNOB" in hits[0].message, fs
    # knob joins the key expression -> clean
    complete = dict(missing)
    complete["keyed.py"] = missing["keyed.py"].replace(
        "return (n,)", "return (n, knob())")
    fs = conc_audit_tree(tmp_path, complete, registry=reg)
    assert not [f for f in fs if f.rule == "cache-key-missing-knob"], fs
    # ... or an exemption WITH a justification
    reg_ex = {("pkg/keyed.py", "_STEP_CACHE"): CacheSpec(
        key_fns=("make_key",), builder_fns=("build",),
        modules=("pkg/keyed.py",),
        exempt={"MY_KNOB": "fixture: declared stale-safe"})}
    fs = conc_audit_tree(tmp_path, missing, registry=reg_ex)
    assert not [f for f in fs if f.rule == "cache-key-missing-knob"], fs


def test_conc_audit_cache_unregistered(tmp_path):
    """A keyed, query-path-written *_CACHE dict that no CACHE_REGISTRY
    entry declares prompts registration (warning)."""
    fs = conc_audit_tree(tmp_path, {"mod.py": """
        import threading
        _NEW_CACHE: dict = {}
        _LOCK = threading.Lock()

        def put(k, v):
            with _LOCK:
                _NEW_CACHE[k] = v
    """})
    assert [f for f in fs if f.rule == "cache-unregistered"], fs


def test_conc_audit_env_freeze_and_suppression(tmp_path):
    """A module-level os.environ snapshot is flagged; the documented
    in-source suppression (the _MIN_BUCKET process contract) waives it."""
    fs = conc_audit_tree(tmp_path, {"mod.py": """
        import os
        FROZEN = int(os.environ.get("SOME_KNOB", "1"))
    """})
    assert [f.rule for f in fs] == ["env-freeze"], fs
    fs = conc_audit_tree(tmp_path, {"mod2.py": """
        import os
        # nds-lint: ignore[env-freeze]
        FROZEN = int(os.environ.get("SOME_KNOB", "1"))
    """})
    assert not fs, fs


def test_conc_audit_current_tree_clean():
    """The shipped package must pass its own concurrency audit with ZERO
    findings — the acceptance bar: no accepted unguarded-mutation
    findings on the query path, every cache registered and key-complete,
    the deliberate freezes suppressed in-source."""
    from nds_tpu.analysis.conc_audit import audit_concurrency
    fs = audit_concurrency()
    assert not fs, "\n".join(str(f) for f in fs)


def test_conc_audit_differential_harness():
    """The runtime half of the concurrency contract, both directions:
    the threaded stress differential (bit-for-bit rows, exactly-one-
    compile-per-shape, zero cross-thread bleed, lock-liveness probes)
    must pass on the clean tree, and no-op'ing EACH named lock must make
    its probe fail — a gate that cannot fail proves nothing."""
    import importlib.util
    path = os.path.join(REPO, "tools", "conc_audit_diff.py")
    spec = importlib.util.spec_from_file_location("conc_audit_diff", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    ok, lines = mod.run_diff()
    assert ok, "\n".join(lines)
    caught, drift_lines = mod.run_drift()
    assert caught, "\n".join(drift_lines)
    assert sum("ok drift" in ln for ln in drift_lines) == \
        len(mod._named_locks())


def test_lint_jobs_thread_pool_matches_sequential():
    """--jobs N runs the nine passes in a thread pool with identical
    findings/counts — the analysis layer passing its own audit, live."""
    import importlib.util
    path = os.path.join(REPO, "tools", "lint.py")
    spec = importlib.util.spec_from_file_location("lint_tool_j", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    f1, c1, _r1, _m1, _p1, _n1, _pp1, _e1 = mod.run_passes(jobs=1)
    f6, c6, _r6, _m6, _p6, _n6, _pp6, _e6 = mod.run_passes(jobs=6)
    assert c1 == c6
    assert [str(f) for f in f1] == [str(f) for f in f6]
    assert "conc-audit" in c1
    assert "perf-audit" in c1
    assert "num-audit" in c1
    assert "param-audit" in c1


# ---------------------------------------------------------------------------
# numeric-safety audit: value-range/precision proofs + boundary lockstep
# ---------------------------------------------------------------------------


def test_num_ival_abstraction():
    """The interval/scale/mass lattice the proofs run on: scaled decimal
    endpoints, additive mass under union, exact x10^d rescaling, and the
    codec width rules at their edges."""
    from nds_tpu.analysis.num_audit import (FOR16_SPAN, FOR32_SPAN, IVal,
                                            codec_width_verdict,
                                            column_interval)
    iv = column_interval("ss_ext_sales_price", "decimal(7,2)", {})
    assert (iv.lo, iv.hi, iv.scale) == (-(10 ** 7 - 1), 10 ** 7 - 1, 2)
    a = IVal(-3, 5, mass=10)
    b = IVal(0, 9, mass=7)
    u = a.union(b)
    assert (u.lo, u.hi, u.mass) == (-3, 9, 17)
    r = IVal(-25, 50, scale=1).at_scale(3)
    assert (r.lo, r.hi, r.scale) == (-2500, 5000, 3)
    # width rules at the exact spans the codec refuses past
    assert codec_width_verdict(IVal(0, FOR16_SPAN - 1), 8)[0] == 2
    assert codec_width_verdict(IVal(0, FOR16_SPAN), 8)[0] == 4
    assert codec_width_verdict(IVal(0, FOR32_SPAN - 1), 8)[0] == 4
    assert codec_width_verdict(IVal(0, FOR32_SPAN), 8) is None
    assert codec_width_verdict(None, 8) is None


def test_num_audit_corpus_proves_clean():
    """Every corpus statement's numeric proofs land host-only with ZERO
    findings — no codec overflow, no unprovable accumulator, no hash-bit
    spill — and the claim checks hold: the shipped tree's numeric story
    is fully proven, so the baseline carries nothing."""
    import time
    from nds_tpu.analysis.num_audit import (audit_num_corpus, check_counts,
                                            claim_findings,
                                            reports_to_findings)
    t0 = time.time()
    reports = audit_num_corpus()
    elapsed = time.time() - t0
    assert len(reports) == 103
    assert reports_to_findings(reports) == []
    assert claim_findings() == []
    assert elapsed < 60, f"host-only audit took {elapsed:.1f}s"
    # the proof histogram is a tier-1 contract, pinned like the perf
    # bottleneck counts: a rule change that silently drops checks (or
    # un-proves one) must fail loudly — update ONLY together with the
    # matching engine/model change (the lockstep rule)
    assert check_counts(reports) == {
        "agg": (287, 287), "arith": (61, 61), "codec": (406, 406),
        "hash-bits": (150, 150), "rebase": (35, 35), "scale": (24, 24)}
    assert sum(1 for r in reports if r.proven_safe) == 96


def test_num_audit_scale_lockstep():
    """MAX_DEC_SCALE mirrors the engine's decimal-scale ceiling so a
    widened runtime scale cannot outrun the static proofs silently."""
    from nds_tpu.analysis.num_audit import MAX_DEC_SCALE
    from nds_tpu.engine import exprs
    assert MAX_DEC_SCALE == exprs._MAX_DEC_SCALE


def _load_num_diff(name="num_audit_diff_t"):
    path = os.path.join(REPO, "tools", "num_audit_diff.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_num_audit_differential_harness():
    """The boundary-value lockstep: every arm of the sweep (base,
    fused-kernel, sharded, encoded-off) returns bit-identical rows to
    the plain-width eager reference over the adversarial tables (FOR
    spans at the int16 edge over 10^9 / negative bases, full dict code
    space, decimal(7,2) extremes, a hot-hash key), and the static
    verdicts agree exactly with the runtime overflow-flag evidence."""
    import numpy as np
    mod = _load_num_diff()
    tables = mod._boundary_tables(np.random.default_rng(1729))
    expect = mod.reference(tables)
    arms = [mod.run_arm(name, env_kv, tables)
            for name, env_kv in mod._ARMS if name != "sharded"]
    import jax
    if jax.device_count() >= 2:
        arms.append(mod.run_arm("sharded",
                                {"NDS_TPU_STREAM_SHARDS": "2"}, tables))
    reports = mod.static_verdicts(
        {k: t.num_rows for k, t in tables.items()})
    ok, lines = mod.compare(expect, arms, reports, arms[0])
    assert ok, "\n".join(lines)
    assert all(r.proven for r in reports)
    # direction A of the drift contract: an explicit accumulator
    # ceiling forces the runtime overflow rerun, contradicting the
    # (still proven) static verdicts — the harness must flag it
    with mod._env(NDS_TPU_STREAM_ACC_ROWS="1024"):
        over = mod.run_arm("base+acc-ceiling", {}, tables)
    ok_a, lines_a = mod.compare(expect, [over], reports, over)
    assert not ok_a, "runtime overflow drift fixture failed to fail"
    assert any("overflow rerun" in ln for ln in lines_a)
    # direction B: widened static ranges (row bounds x10^9) un-prove
    # the accumulator checks against a clean runtime — flagged too
    drift = mod.static_verdicts(
        {k: t.num_rows for k, t in tables.items()}, inflate=10 ** 9)
    ok_b, lines_b = mod.compare(expect, [arms[0]], drift, arms[0])
    assert not ok_b, "widened-range drift fixture failed to fail"
    assert any("statically unproven" in ln for ln in lines_b)


# ---------------------------------------------------------------------------
# parameterization audit: literal bindability + one-compile-many-params
# ---------------------------------------------------------------------------


def test_param_literal_rule():
    """The shared bindability vocabulary: type tags, safe domains and
    the operand conversion the stream dispatcher feeds jnp.asarray."""
    from decimal import Decimal

    from nds_tpu.analysis.param_audit import (SAFE_INT_ABS, domain_contains,
                                              literal_typetag,
                                              slot_param_value)
    assert literal_typetag(42) == "i64"
    assert literal_typetag(1.5) == "f64"
    assert literal_typetag(Decimal("99.99")) == "dec:2"
    assert literal_typetag(Decimal("7")) == "dec:0"
    # None / bool / str never bind (codec selection, plan-time parses)
    for v in (None, True, "GA"):
        assert literal_typetag(v) is None
    # i64: inside the rebase margin, not at it
    assert domain_contains("i64", SAFE_INT_ABS - 1)
    assert not domain_contains("i64", SAFE_INT_ABS + 1)
    # dec:s domains live in LITERAL units; operands in scaled ints
    assert domain_contains("dec:2", Decimal("99999.99"))
    assert slot_param_value(Decimal("99999.99"), "dec:2") == 9999999
    assert slot_param_value(5, "i64") == 5
    # f64 binds at any finite value (no codec or rebase interaction)
    assert domain_contains("f64", 1e300)


def test_param_audit_statement_classification():
    """One statement, every verdict family: direct streamed comparands
    bind; dimension-owned, in-list, subquery and LIMIT literals fold
    with machine-readable reasons."""
    from nds_tpu.analysis.param_audit import ParamAuditor
    a = ParamAuditor()
    rep = a.audit_sql("""
        select ss_item_sk, count(*) c from store_sales, date_dim
        where ss_sold_date_sk = d_date_sk
          and ss_quantity between 5 and 95
          and ss_ext_sales_price > 100.00
          and d_moy = 11
          and ss_item_sk in (1, 2, 3)
          and ss_wholesale_cost > (select avg(ss_wholesale_cost)
                                   from store_sales)
        group by ss_item_sk order by ss_item_sk limit 10""")
    assert rep.classification == "compiled-stream"
    # between low/high + the decimal compare = three bindable slots
    assert rep.n_bindable == 3
    assert rep.signature() == ("ss_quantity:i64, ss_quantity:i64, "
                               "ss_ext_sales_price:dec:2")
    assert all(s.domain for s in rep.slots)
    # 3 in-list members + LIMIT shape the output; d_moy is dimension-
    # owned (its compare replays against a host-gathered dimension)
    assert rep.folds == {"shape-affecting": 4, "replayed-host-read": 1}
    # every literal is accounted for: bound or folded, none dropped
    assert sum(rep.folds.values()) + rep.n_bindable == rep.n_literals


def test_param_skeleton_key_canonicalization():
    """The cache-key half of the contract: swapping a bindable literal's
    VALUE leaves the skeleton key unchanged (one compile serves all
    vectors), while changing its decimal SCALE — a different codec
    layout — changes it."""
    from nds_tpu.analysis.exec_audit import _conjuncts_of
    from nds_tpu.analysis.param_audit import (conjunct_bind_slots,
                                              skeleton_conjunct_key)
    from nds_tpu.sql.parser import parse

    def conj(sql):
        q = parse(sql).body
        return _conjuncts_of(q.where)[0]

    def skel(sql):
        c = conj(sql)
        slots = conjunct_bind_slots(c, owned=True, has_subquery=False)
        assert slots, sql
        return skeleton_conjunct_key(c, [(p, n, t) for p, n, t in slots])

    base = "select 1 from store_sales where ss_ext_sales_price > {}"
    assert skel(base.format("100.00")) == skel(base.format("9999.99"))
    assert skel(base.format("100.00")) != skel(base.format("100.0"))
    # the swap restores the literal value afterwards
    c = conj(base.format("100.00"))
    skeleton_conjunct_key(
        c, [(p, n, t) for p, n, t in
            conjunct_bind_slots(c, owned=True, has_subquery=False)])
    from decimal import Decimal
    assert c.right.value == Decimal("100.00")


def test_param_binding_hook_roundtrip():
    """The engine half: exprs.param_binding overlays a Literal node's
    value as a broadcast device column inside the scope and stands down
    outside it (the planner consults bound_literal before X.literal)."""
    from nds_tpu.engine import exprs as X
    from nds_tpu.sql.parser import parse
    q = parse("select 1 from store_sales where ss_quantity > 5").body
    lit = q.where.right
    assert X.bound_literal(lit, 4) is None
    assert not X.param_bindings_active()
    with X.param_binding({id(lit): ("i64", 37)}):
        assert X.param_bindings_active()
        col = X.bound_literal(lit, 4)
        assert col is not None and int(col.data[0]) == 37
        assert col.data.shape == (4,)
    assert X.bound_literal(lit, 4) is None


def test_param_audit_corpus_counts_pinned():
    """The corpus bindability census is a tier-1 contract, pinned like
    the perf bottleneck and num proof histograms: a rule change that
    silently binds more (unsound) or fewer (lost coverage) literals
    must fail loudly. Update ONLY together with the matching engine
    change — the lockstep rule."""
    import time

    from nds_tpu.analysis.param_audit import (audit_param_corpus,
                                              bindability_counts,
                                              reports_to_findings)
    t0 = time.time()
    reports = audit_param_corpus()
    elapsed = time.time() - t0
    assert len(reports) == 103
    assert reports_to_findings(reports) == []
    assert elapsed < 60, f"host-only audit took {elapsed:.1f}s"
    assert bindability_counts(reports) == {
        "bindable": 63,
        "codec-threshold": 267,
        "date-parse-at-plan": 23,
        "non-comparand": 315,
        "non-streamed-statement": 714,
        "replayed-host-read": 599,
        "residual-key": 13,
        "shape-affecting": 86,
        "statements-with-bindable": 7,
    }
    # every bindable slot the pinned-seed instantiation produced sits
    # inside its proven safe domain with a live signature
    for r in reports:
        for s in r.slots:
            assert s.typetag in ("i64", "f64") or \
                s.typetag.startswith("dec:")
        if r.n_bindable:
            assert r.signature()


def test_param_generator_dials_inside_safe_domains():
    """Satellite lockstep with the stream generator: every numeric dial
    range a template defines (uniform/sample bounds — what
    nds_gen_query_stream substitutes per stream) sits inside the proven
    safe i64 domain, and instantiations under OTHER seeds than the
    audit's pinned one keep every bindable slot value in-domain."""
    import re

    import numpy as np

    from nds_tpu.analysis.param_audit import (SAFE_INT_ABS, ParamAuditor,
                                              domain_contains)
    from nds_tpu.queries import (_DEFINE_RE, instantiate_template,
                                 list_templates, load_template)
    call = re.compile(r"^(\w+)\((.*)\)$", re.DOTALL)
    n_dials = 0
    for name in list_templates():
        for m in _DEFINE_RE.finditer(load_template(name)):
            c = call.match(m.group(2).strip())
            if not c or c.group(1) not in ("uniform", "sample"):
                continue
            args = [a.strip() for a in c.group(2).split(",")]
            bounds = args[-2:] if c.group(1) == "sample" else args
            for tok in bounds:
                if re.fullmatch(r"-?\d+", tok):
                    assert abs(int(tok)) < SAFE_INT_ABS, \
                        f"{name}: dial bound {tok} escapes the domain"
                    n_dials += 1
    assert n_dials >= 20, "the dial scan went dark"
    auditor = ParamAuditor()
    for seed in (7, 4242):
        rng = np.random.default_rng(seed)
        for name in list_templates():
            sql = instantiate_template(load_template(name), rng)
            for stmt in (s for s in sql.split(";") if s.strip()):
                rep = auditor.audit_sql(stmt, file=name, query=name)
                for s in rep.slots:
                    assert s.value is None or \
                        domain_contains(s.typetag, s.value), \
                        (name, s.column, s.value)


def test_lint_cli_param_report(lint_combined):
    """--param-report plumbing under --format json: the ``param_report``
    field rides the SAME single parseable stdout document and the human
    signature table rides stderr (one subprocess covers both — the
    plain-stdout rendering is the same format_param_report text)."""
    r, doc, _path = lint_combined         # single-document stdout
    assert doc["pass_counts"]["param-audit"] == 0
    entries = doc["param_report"]
    assert len(entries) == 103
    assert sum(1 for e in entries if e["slots"]) == 7
    for e in entries:
        for s in e["slots"]:
            assert s["typetag"] in ("i64", "f64") or \
                s["typetag"].startswith("dec:")
    # the human signature table rides stderr, off the parseable stream
    assert "# param-audit: literal bindability" in r.stderr
    assert "ss_quantity:i64" in r.stderr
    assert "bindable: 63" in r.stderr


def test_param_audit_differential_harness():
    """The one-compile-many-params lockstep, live: K=4 boundary
    parameter vectors per bindable template share ONE compiled pipeline
    (singleflight build counters + cache hit/miss metrics) bit-for-bit
    with per-value fresh recording AND the plain-width eager reference,
    fold-required slots keep changing the cache key, and the static
    signatures match the runtime slot counts — across the base,
    partitioned and (mesh permitting) sharded arms."""
    path = os.path.join(REPO, "tools", "param_audit_diff.py")
    spec = importlib.util.spec_from_file_location("param_audit_diff_t",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    ok, lines = mod.run_diff(inject_drift=False)
    assert ok, "\n".join(lines)
    assert any("ONE compile served 4 parameter vectors" in ln
               for ln in lines)
    assert any("fold-required slots changed the key" in ln
               for ln in lines)
    # the drift self-test: misclassifying IN-list members as bindable
    # must be rejected in BOTH directions (wrong results on cache hit,
    # fold slots no longer varying the key)
    ok_d, lines_d = mod.run_diff(inject_drift=True)
    assert ok_d, "\n".join(lines_d)
    assert any("correctly rejected" in ln for ln in lines_d)
