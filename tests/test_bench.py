# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Bench harness policy tests (no device work)."""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

spec = importlib.util.spec_from_file_location(
    "bench_mod", os.path.join(REPO, "bench.py"))
bench = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bench)


def _times(ms, n, start=0):
    return {f"query{i}": float(ms) for i in range(start, start + n)}


@pytest.fixture(autouse=True)
def _allow_seed(monkeypatch):
    # tests exercise lineage mechanics from scratch; production refuses a
    # missing baseline unless seeding is explicit (see the refusal test)
    monkeypatch.setenv("NDS_BENCH_SEED_BASELINE", "1")


class TestResolveBaseline:
    def test_first_full_run_writes_baseline(self, tmp_path):
        f = tmp_path / "base.json"
        vs = bench.resolve_baseline(str(f), _times(100, 99), 99)
        assert vs == 1.0
        assert json.load(open(f))["n_queries"] == 99

    def test_missing_baseline_refused_without_explicit_seed(
            self, tmp_path, monkeypatch):
        """Losing the committed lineage must be LOUD, not a silent
        restart: vs_baseline degrades to 0.0 and nothing is written
        (round-3 verdict weak #1)."""
        monkeypatch.delenv("NDS_BENCH_SEED_BASELINE", raising=False)
        f = tmp_path / "base.json"
        vs = bench.resolve_baseline(str(f), _times(100, 99), 99)
        assert vs == 0.0
        assert not f.exists()

    def test_note_field_survives_merge(self, tmp_path):
        f = tmp_path / "base.json"
        bench.resolve_baseline(str(f), _times(100, 95), 99)
        d = json.load(open(f))
        d["note"] = "lineage provenance"
        json.dump(d, open(f, "w"))
        bench.resolve_baseline(str(f), _times(90, 99), 99)
        assert json.load(open(f))["note"] == "lineage provenance"

    def test_same_set_compares(self, tmp_path):
        f = tmp_path / "base.json"
        bench.resolve_baseline(str(f), _times(100, 99), 99)
        vs = bench.resolve_baseline(str(f), _times(50, 99), 99)
        assert abs(vs - 2.0) < 1e-9            # 2x faster than baseline

    def test_partial_run_compares_common_set_without_overwriting(self, tmp_path):
        f = tmp_path / "base.json"
        bench.resolve_baseline(str(f), _times(100, 99), 99)
        vs = bench.resolve_baseline(str(f), _times(10, 95), 99)  # wedged chunk
        assert abs(vs - 10.0) < 1e-9   # geomean over the 95 common queries
        assert abs(json.load(open(f))["value"] - 100.0) < 1e-6   # no clobber
        assert abs(bench.resolve_baseline(str(f), _times(100, 99), 99)
                   - 1.0) < 1e-9

    def test_faster_partial_with_more_queries_never_clobbers(self, tmp_path):
        # a later, slower run that happens to measure MORE queries must not
        # replace existing first-recorded entries, only fill in new ones
        f = tmp_path / "base.json"
        bench.resolve_baseline(str(f), _times(100, 95), 99)
        bench.resolve_baseline(str(f), _times(200, 96), 99)
        base = json.load(open(f))["times"]
        assert len(base) == 96
        assert base["query0"] == 100.0       # first recording kept
        assert base["query95"] == 200.0      # gap filled

    def test_disjoint_partial_is_neutral(self, tmp_path):
        f = tmp_path / "base.json"
        bench.resolve_baseline(str(f), _times(100, 50), 50)
        vs = bench.resolve_baseline(str(f), _times(10, 5, start=90), 99)
        assert vs == 1.0                       # nothing comparable

    def test_ratchet_growth_extends_baseline(self, tmp_path):
        f = tmp_path / "base.json"
        bench.resolve_baseline(str(f), _times(100, 80), 80)
        vs = bench.resolve_baseline(str(f), _times(120, 99), 99)  # set grew
        assert abs(vs - 100.0 / 120.0) < 1e-9  # compared over 80 common
        assert json.load(open(f))["n_queries"] == 99

    def test_legacy_value_only_baseline_is_migrated(self, tmp_path):
        f = tmp_path / "base.json"
        json.dump({"value": 100.0, "n_queries": 99}, open(f, "w"))
        vs = bench.resolve_baseline(str(f), _times(50, 99), 99)
        assert vs == 1.0                      # nothing comparable yet
        assert json.load(open(f))["times"]    # migrated to per-query format
        vs2 = bench.resolve_baseline(str(f), _times(25, 99), 99)
        assert abs(vs2 - 2.0) < 1e-9


def test_load_resume_prepopulates_and_skips(tmp_path):
    """A results JSONL from an interrupted campaign must pre-load times
    and perf (at-scale runs are resumable; round-4 SF10 lost 30 measured
    queries to a budget kill)."""
    p = tmp_path / "results.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"name": "query3", "ms": 1234.5,
                            "hostSyncs": 2, "warmS": 9.8,
                            "compileS": 7.7}) + "\n")
        f.write("not json\n")                        # tolerated garbage
        f.write(json.dumps({"name": "query9", "error": "boom"}) + "\n")
    times, perf = {}, {}
    assert bench.load_resume(str(p), times, perf) is None
    assert times == {"query3": 1234.5}
    assert perf["query3"]["compileS"] == 7.7
    assert "query9" not in times                     # errors not resumed


def test_load_resume_recovers_platform(tmp_path):
    """A rerun satisfied entirely from the resume file never starts a
    child — load_resume must return the original campaign's platform meta
    line so PERF.md's provenance doesn't regress to 'unknown'."""
    p = tmp_path / "results.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"name": "query1", "ms": 10.0,
                            "hostSyncs": 1}) + "\n")
        f.write(json.dumps({"platform": "axon"}) + "\n")
    times, perf = {}, {}
    assert bench.load_resume(str(p), times, perf) == "axon"
    assert times == {"query1": 10.0}


def test_bench_queries_names_match_stream_names():
    queries = bench.bench_queries()
    names = [n for n, _ in queries]
    assert len(names) == len(set(names))
    assert all(n.startswith("query") for n in names)
    # the four split templates surface as _part1/_part2 names
    if len(names) > 1:
        assert "query14_part1" in names and "query14_part2" in names


def test_first_partial_run_seeds_baseline(tmp_path):
    """A query that can never run (OOM-bound outlier) must not block
    baselining forever: the first run seeds whatever it measured."""
    f = tmp_path / "base.json"
    vs = bench.resolve_baseline(str(f), _times(100, 102), 103)
    assert vs == 1.0
    assert len(json.load(open(f))["times"]) == 102
    assert json.load(open(f))["n_queries"] == 102   # what was measured
    vs2 = bench.resolve_baseline(str(f), _times(50, 102), 103)
    assert abs(vs2 - 2.0) < 1e-9


def test_derive_budgets_from_baseline(tmp_path):
    """Per-query budgets: baseline wall x headroom, clamped to
    [floor, cap]; queries with no history keep the cap (their first
    measurement must not be killed by a budget nobody derived)."""
    f = tmp_path / "base.json"
    json.dump({"times": {"q_cheap": 10.0, "q_mid": 2000.0,
                         "q_heavy": 200000.0}}, open(f, "w"))
    budgets = bench.derive_budgets(
        ["q_cheap", "q_mid", "q_heavy", "q_new"], str(f),
        headroom=30.0, floor_s=30.0, cap_s=100.0)
    assert budgets["q_cheap"] == 30.0        # floor absorbs cold compile
    assert budgets["q_mid"] == 60.0          # 2 s x 30
    assert budgets["q_heavy"] == 100.0       # capped at the old allowance
    assert budgets["q_new"] == 100.0         # no history -> cap
    # a missing/unreadable baseline derives nothing: every query keeps
    # the cap (never a zero budget)
    budgets = bench.derive_budgets(["q1"], str(tmp_path / "nope.json"),
                                   headroom=30.0, floor_s=30.0,
                                   cap_s=100.0)
    assert budgets == {"q1": 100.0}


def test_derive_budgets_off_at_foreign_scale(tmp_path, monkeypatch):
    """The committed baseline is bench-scale (0.05) history: at SF10 the
    walls are incommensurable (minutes/query), so derivation must stay
    OFF — every query keeps the cap — unless the operator sets the
    headroom explicitly for that campaign."""
    monkeypatch.delenv("NDS_BENCH_BUDGET_HEADROOM", raising=False)
    f = tmp_path / "base.json"
    json.dump({"times": {"q1": 800.0}}, open(f, "w"))
    assert bench.derive_budgets(["q1"], str(f), floor_s=30.0, cap_s=400.0,
                                scale="10") == {"q1": 400.0}
    # bench scale: derivation active
    assert bench.derive_budgets(["q1"], str(f), floor_s=30.0, cap_s=400.0,
                                scale="0.05") == {"q1": 30.0}
    # explicit opt-in at scale: active again
    monkeypatch.setenv("NDS_BENCH_BUDGET_HEADROOM", "200")
    assert bench.derive_budgets(["q1"], str(f), floor_s=30.0, cap_s=400.0,
                                scale="10") == {"q1": 160.0}


def test_budget_enforcement_hung_child(tmp_path, monkeypatch, capsys):
    """The BENCH_r05 failure mode, pinned as a regression: one query
    hangs past its DERIVED budget — the round must finish, with that
    query marked ``timeout`` in the ledger, a NON-NULL geomean over the
    completed queries, and finalize()'s output complete (PERF.md + a
    terminal ``completed`` record; the hang cost its budget, not the
    campaign)."""
    monkeypatch.setattr(bench, "REPO", str(tmp_path))
    monkeypatch.setattr(bench, "ensure_data", lambda: None)
    monkeypatch.setattr(bench, "bench_queries",
                        lambda: [("query1", "s1"), ("query2", "s2"),
                                 ("query3", "s3")])
    monkeypatch.setattr(bench, "_emitted", False)
    json.dump({"times": {"query1": 100.0, "query2": 1000.0,
                         "query3": 100.0}},
              open(tmp_path / "BASELINE_TIMES.json", "w"))
    ledger_path = tmp_path / "campaign.jsonl"
    monkeypatch.setenv("NDS_BENCH_RESULTS_JSONL", str(ledger_path))
    monkeypatch.setenv("NDS_BENCH_BUDGET_FLOOR_S", "5")
    monkeypatch.setenv("NDS_BENCH_BUDGET_HEADROOM", "2")
    monkeypatch.setenv("NDS_BENCH_HEARTBEAT_S", "0")   # deterministic file

    deadlines = {}

    class HangingChild:
        def __init__(self):
            self.proc = None
            self.started = False

        def alive(self):
            return self.started

        def start(self, deadline_left):
            self.started = True
            return {"ready": True, "platform": "axon"}

        def run_query(self, name, timeout):
            deadlines.setdefault(name, timeout)
            if name == "query2":
                return None        # hung in-flight: supervisor's timeout
            return {"name": name, "ms": 100.0, "hostSyncs": 1,
                    "syncWaitMs": 1.0}

        def stop(self):
            self.started = False   # the hung child gets killed

    monkeypatch.setattr(bench, "ChildServer", HangingChild)
    import time as _time
    bench.run_parent(_time.perf_counter())
    out = capsys.readouterr()
    msg = json.loads(out.out.strip().splitlines()[-1])
    assert msg["n_queries"] == 2
    assert msg["value"] == pytest.approx(100.0)        # non-null geomean
    assert "aborted" not in msg                        # the round FINISHED
    # the derived budget was enforced: query2's baseline wall (1 s) x
    # headroom 2 = 2 s, floored at 5 s — not the 420 s global cap
    assert deadlines["query2"] == pytest.approx(5.0)
    assert "timeout after 5s (budget)" in out.err
    data = bench.ledger_mod().load_ledger(str(ledger_path))
    assert data.queries["query2"]["status"] == "timeout"
    assert [r["status"] for r in data.attempts
            if r["name"] == "query2"] == ["timeout", "timeout"]
    assert data.queries["query2"]["budgetS"] == pytest.approx(5.0)
    assert data.times() == {"query1": 100.0, "query3": 100.0}
    assert data.complete() and data.end["status"] == "completed"
    assert data.end["queries"] == 2 and data.end["platform"] == "axon"
    assert "query1" in open(tmp_path / "PERF.md").read()


def test_setup_timeout_circuit_breaker(monkeypatch, capsys):
    """Two consecutive child-setup failures must trip the breaker: stop
    burning budget and emit a LABELED partial artifact (BENCH_r05 spent
    its entire 3000s on six 300s setup timeouts, yielding n_queries: 0
    with no indication why)."""
    starts = []

    class DeadChild:
        def __init__(self):
            self.proc = None

        def alive(self):
            return False

        def start(self, deadline_left):
            starts.append(deadline_left)
            return None                         # setup timeout / dead child

        def stop(self):
            pass

    monkeypatch.setattr(bench, "ChildServer", DeadChild)
    monkeypatch.setattr(bench, "ensure_data", lambda: None)
    monkeypatch.setattr(bench, "bench_queries",
                        lambda: [("query1", "select 1")])
    monkeypatch.setattr(bench, "_emitted", False)
    import time as _time
    with pytest.raises(SystemExit):
        bench.run_parent(_time.perf_counter())
    assert len(starts) == 2, "breaker must trip after exactly 2 failures"
    out = capsys.readouterr()
    msg = json.loads(out.out.strip().splitlines()[-1])
    assert msg["n_queries"] == 0
    assert msg["aborted"] == "child-setup-failure"
    assert "failing fast" in out.err


def test_external_timeout_flushes_partial_geomean(tmp_path, monkeypatch,
                                                  capsys):
    """An external `timeout` kill (rc=124) mid-campaign must still record
    the partial geomean of every COMPLETED query — PERF.md + metric line
    — not BENCH_r05's {"value": null, "n_queries": 0}. Simulated: the
    child serves query1, then the SIGTERM handler fires while query2 is
    in flight. The handler must also close the ledger with a terminal
    ``aborted`` record (reason: signal) so the artifact is
    self-describing — a resume sees query1 done, query2 unfinished."""
    monkeypatch.setattr(bench, "REPO", str(tmp_path))
    monkeypatch.setattr(bench, "ensure_data", lambda: None)
    monkeypatch.setattr(bench, "bench_queries",
                        lambda: [("query1", "select 1"),
                                 ("query2", "select 2")])
    monkeypatch.setattr(bench, "_emitted", False)
    ledger_path = tmp_path / "campaign.jsonl"
    monkeypatch.setenv("NDS_BENCH_RESULTS_JSONL", str(ledger_path))
    monkeypatch.setenv("NDS_BENCH_HEARTBEAT_S", "0")

    handlers = {}
    monkeypatch.setattr(bench.signal, "signal",
                        lambda signum, fn: handlers.setdefault(signum, fn))

    def fake_exit(code):
        raise SystemExit(code)

    monkeypatch.setattr(bench.os, "_exit", fake_exit)

    class OneQueryChild:
        def __init__(self):
            self.proc = None
            self.started = False

        def alive(self):
            return self.started

        def start(self, deadline_left):
            self.started = True
            return {"ready": True, "platform": "axon"}

        def run_query(self, name, timeout):
            if name == "query1":
                return {"name": "query1", "ms": 123.0, "hostSyncs": 1,
                        "syncWaitMs": 2.0}
            # query2 in flight when the external timeout lands
            handlers[bench.signal.SIGTERM](bench.signal.SIGTERM, None)
            raise AssertionError("handler must not return")

        def stop(self):
            pass

    monkeypatch.setattr(bench, "ChildServer", OneQueryChild)
    import time as _time
    with pytest.raises(SystemExit):
        bench.run_parent(_time.perf_counter())
    out = capsys.readouterr()
    msg = json.loads(out.out.strip().splitlines()[-1])
    assert msg["n_queries"] == 1
    assert msg["value"] == pytest.approx(123.0)
    perf_text = open(tmp_path / "PERF.md").read()
    assert "query1" in perf_text and "platform: axon." in perf_text
    # terminal ledger record: the kill is labeled, not inferred
    data = bench.ledger_mod().load_ledger(str(ledger_path))
    assert data.times() == {"query1": 123.0}
    assert data.complete() and data.end["status"] == "aborted"
    assert data.end["reason"] == "signal"
    assert data.end["queries"] == 1 and data.end["platform"] == "axon"


def test_round_budget_exhaustion_labeled_truthfully(tmp_path, monkeypatch,
                                                    capsys):
    """A healthy query killed because the ROUND's budget ran out must be
    labeled 'round-budget', not blamed on a per-query budget that never
    limited it (the ledger is the durable post-hoc record — the cause
    must be the real one)."""
    monkeypatch.setattr(bench, "REPO", str(tmp_path))
    monkeypatch.setattr(bench, "ensure_data", lambda: None)
    monkeypatch.setattr(bench, "bench_queries",
                        lambda: [("query1", "s1")])
    monkeypatch.setattr(bench, "_emitted", False)
    # round budget leaves ~8s; the per-query floor is far larger, so the
    # deadline is the round remainder, not the derived budget
    monkeypatch.setenv("NDS_BENCH_BUDGET_S", "28")
    monkeypatch.setenv("NDS_BENCH_RESULTS_JSONL",
                       str(tmp_path / "led.jsonl"))
    monkeypatch.setenv("NDS_BENCH_HEARTBEAT_S", "0")

    class HungChild:
        def __init__(self):
            self.proc = None
            self.started = False

        def alive(self):
            return self.started

        def start(self, deadline_left):
            self.started = True
            return {"ready": True, "platform": "axon"}

        def run_query(self, name, timeout):
            return None                  # hung until the deadline

        def stop(self):
            self.started = False

    monkeypatch.setattr(bench, "ChildServer", HungChild)
    import time as _time
    with pytest.raises(SystemExit):      # nothing measured -> exit 1
        bench.run_parent(_time.perf_counter())
    err = capsys.readouterr().err
    assert "(round-budget)" in err and "(budget)" not in err
    data = bench.ledger_mod().load_ledger(str(tmp_path / "led.jsonl"))
    assert data.queries["query1"]["status"] == "timeout"
    assert "round-budget" in data.queries["query1"]["error"]


def test_round_with_hang_and_sigterm_still_yields_ledger(
        tmp_path, monkeypatch, capsys):
    """The acceptance scenario end to end: ONE round suffers an injected
    hang (query2 blows its derived budget) AND an injected SIGTERM
    (while query4 is in flight) — and still produces a complete ledger
    (timeout attempt + terminal aborted record), a non-null geomean over
    the completed queries, and a regenerated PERF.md."""
    monkeypatch.setattr(bench, "REPO", str(tmp_path))
    monkeypatch.setattr(bench, "ensure_data", lambda: None)
    monkeypatch.setattr(bench, "bench_queries",
                        lambda: [(f"query{i}", f"s{i}")
                                 for i in (1, 2, 3, 4)])
    monkeypatch.setattr(bench, "_emitted", False)
    json.dump({"times": {f"query{i}": 100.0 * i for i in (1, 2, 3, 4)}},
              open(tmp_path / "BASELINE_TIMES.json", "w"))
    ledger_path = tmp_path / "campaign.jsonl"
    monkeypatch.setenv("NDS_BENCH_RESULTS_JSONL", str(ledger_path))
    monkeypatch.setenv("NDS_BENCH_BUDGET_FLOOR_S", "5")
    monkeypatch.setenv("NDS_BENCH_HEARTBEAT_S", "0")

    handlers = {}
    monkeypatch.setattr(bench.signal, "signal",
                        lambda signum, fn: handlers.setdefault(signum, fn))

    def fake_exit(code):
        raise SystemExit(code)

    monkeypatch.setattr(bench.os, "_exit", fake_exit)

    class ChaosChild:
        def __init__(self):
            self.proc = None
            self.started = False

        def alive(self):
            return self.started

        def start(self, deadline_left):
            self.started = True
            return {"ready": True, "platform": "axon"}

        def run_query(self, name, timeout):
            if name == "query2":
                return None              # the injected hang
            if name == "query4":
                # the injected external kill, mid-flight
                handlers[bench.signal.SIGTERM](bench.signal.SIGTERM, None)
                raise AssertionError("handler must not return")
            return {"name": name, "ms": 100.0, "hostSyncs": 1,
                    "syncWaitMs": 1.0}

        def stop(self):
            self.started = False

    monkeypatch.setattr(bench, "ChildServer", ChaosChild)
    import time as _time
    with pytest.raises(SystemExit):
        bench.run_parent(_time.perf_counter())
    out = capsys.readouterr()
    msg = json.loads(out.out.strip().splitlines()[-1])
    assert msg["n_queries"] == 2
    assert msg["value"] == pytest.approx(100.0)        # non-null geomean
    data = bench.ledger_mod().load_ledger(str(ledger_path))
    assert data.times() == {"query1": 100.0, "query3": 100.0}
    assert data.queries["query2"]["status"] == "timeout"
    assert data.complete() and data.end["status"] == "aborted"
    assert data.end["reason"] == "signal" and data.end["queries"] == 2
    perf_text = open(tmp_path / "PERF.md").read()
    assert "query1" in perf_text and "query3" in perf_text


def test_write_perf_stamps_platform_and_streamed(tmp_path, monkeypatch):
    """PERF.md header carries the measured jax platform (provenance) and
    the streamed->HBM scan path aggregate when any query streamed."""
    monkeypatch.setattr(bench, "REPO", str(tmp_path))
    times = {"query1": 100.0, "query2": 50.0}
    perf = {
        "query1": {"hostSyncs": 2, "syncWaitMs": 5.0,
                   "streamedScans": [
                       {"table": "store_sales", "chunks": 12, "syncs": 1,
                        "path": "compiled"},
                       {"table": "catalog_sales", "chunks": 4, "syncs": 9,
                        "path": "eager", "reason": "not chunk-invariant"}]},
        "query2": {"hostSyncs": 1, "syncWaitMs": 1.0},
    }
    bench.write_perf(times, perf, platform="axon")
    text = open(tmp_path / "PERF.md").read()
    assert "platform: axon." in text
    assert "attached chip" not in text
    assert "Streamed >HBM scans: 2 (1 compiled chunk pipeline, "\
           "1 eager fallback)." in text


def test_collect_sf10_failure_capture_excludes_restart_suffix(tmp_path):
    """The abort-regex capture must stop at the cause: the launcher's
    '; restarting child' suffix is launcher noise, not failure reason
    (ADVICE.md round-5 item 4)."""
    spec2 = importlib.util.spec_from_file_location(
        "collect_sf10", os.path.join(REPO, "tools", "collect_sf10.py"))
    collect = importlib.util.module_from_spec(spec2)
    spec2.loader.exec_module(collect)
    jsonl = tmp_path / "results.jsonl"
    jsonl.write_text(json.dumps({"name": "query1", "ms": 1234.5}) + "\n")
    log = tmp_path / "stderr.log"
    log.write_text(
        "# query9 aborted (timeout after 600s); restarting child\n"
        "# query70 failed: ExecError boom; restarting child\n"
        "# query88 failed: plain failure line\n")
    out = tmp_path / "SF10.json"
    argv = sys.argv
    sys.argv = ["collect_sf10.py", str(jsonl), str(log), str(out)]
    try:
        collect.main()
    finally:
        sys.argv = argv
    doc = json.load(open(out))
    assert doc["queries"]["query1"]["timed_s"] == 1.234
    assert doc["failures"]["query9"] == "(timeout after 600s)"
    assert doc["failures"]["query70"] == "ExecError boom"
    assert doc["failures"]["query88"] == "plain failure line"


def test_restart_backoff_deterministic_and_jittered(monkeypatch):
    """The jittered backoff between child restarts (the bench-child
    seam's spacing policy): zero before the FIRST start, exponential +
    deterministic hash-jitter afterwards — the same index always yields
    the same delay (tests and wall bounds hold), 0 disables."""
    monkeypatch.setenv("NDS_BENCH_RESTART_BACKOFF_S", "1.0")
    assert bench.restart_backoff_s(1) == 0.0
    b2, b3, b4 = (bench.restart_backoff_s(n) for n in (2, 3, 4))
    assert 1.0 <= b2 <= 1.5 and 2.0 <= b3 <= 3.0 and 4.0 <= b4 <= 6.0
    assert bench.restart_backoff_s(2) == b2, "jitter must be deterministic"
    assert bench.restart_backoff_s(20) <= 30.0, "backoff must cap"
    monkeypatch.setenv("NDS_BENCH_RESTART_BACKOFF_S", "0")
    assert bench.restart_backoff_s(5) == 0.0


def test_restart_backoff_applied_between_restarts(monkeypatch, capsys):
    """The parent loop backs off (visibly) between consecutive child
    restarts before the 2-strike breaker trips."""
    monkeypatch.setenv("NDS_BENCH_RESTART_BACKOFF_S", "0.01")

    class DeadChild:
        def __init__(self):
            self.proc = None

        def alive(self):
            return False

        def start(self, deadline_left):
            return None

        def stop(self):
            pass

    monkeypatch.setattr(bench, "ChildServer", DeadChild)
    monkeypatch.setattr(bench, "ensure_data", lambda: None)
    monkeypatch.setattr(bench, "bench_queries",
                        lambda: [("query1", "select 1")])
    monkeypatch.setattr(bench, "_emitted", False)
    import time as _time
    with pytest.raises(SystemExit):
        bench.run_parent(_time.perf_counter())
    err = capsys.readouterr().err
    assert "backing off" in err, "no backoff between restarts"
    assert "failing fast" in err, "breaker must still trip"


def test_bench_child_fault_injection_degrades_to_restart_path(monkeypatch):
    """The bench-child seam: an injected start fault takes the same path
    as a real setup failure (start returns None — the caller's backoff +
    breaker own the recovery) and records the FaultEvent."""
    F = bench.faults_mod()
    F.reset_fault_counts()
    F.drain_fault_events()
    monkeypatch.setenv("NDS_TPU_FAULT", "bench-child:error:1")
    try:
        cs = bench.ChildServer()
        assert cs.start(5.0) is None, "injected start fault must degrade"
        events = F.drain_fault_events()
        assert [(e.seam, e.action) for e in events] == \
            [("bench-child", "degrade")], events
    finally:
        F.reset_fault_counts()


def test_heartbeat_survives_beat_exception(tmp_path):
    """A heartbeat-thread exception must record a ledger progress note
    and CONTINUE beating — a silently dead liveness thread would
    un-detect the very hangs it exists to surface."""
    import time as _time
    lm = bench.ledger_mod()
    path = str(tmp_path / "l.jsonl")
    led = lm.Ledger(path, driver="bench")
    hb = lm.Heartbeat(0.05, ledger=led, out=None)
    orig = led.progress
    calls = {"n": 0}

    def flaky(**fields):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("beat bug")      # escapes beat()
        return orig(**fields)

    led.progress = flaky
    hb.start()
    deadline = _time.monotonic() + 10.0
    while (hb.beats < 3 or hb._survived < 1) and \
            _time.monotonic() < deadline:
        _time.sleep(0.02)
    hb.stop()
    led.close(None)
    assert hb._survived >= 1, "loop never saw the exception"
    assert hb.beats >= 3, "heartbeat died instead of continuing"
    recs = [rec for _ln, rec in lm.iter_ledger(path)
            if rec["kind"] == "progress"]
    notes = [r for r in recs if r.get("note") == "heartbeat-exception"]
    assert notes and "beat bug" in notes[0]["error"], \
        "exception note must land in the ledger"
    assert any("beat" in r for r in recs if r is not notes[0]), \
        "beats must continue after the note"


def test_ledger_write_fault_retries_then_degrades(tmp_path, monkeypatch):
    """The ledger-write seam: one injected write fault recovers through
    the bounded retry (record lands, zero write_failures); a persistent
    failure degrades — record dropped with a note, campaign continues."""
    lm = bench.ledger_mod()
    F = bench.faults_mod()
    path = str(tmp_path / "l.jsonl")
    led = lm.Ledger(path, driver="bench")
    F.reset_fault_counts()
    monkeypatch.setenv("NDS_TPU_FAULT", "ledger-write:error:1")
    led.query("query1", status="ok", ms=1.0)
    monkeypatch.delenv("NDS_TPU_FAULT")
    F.reset_fault_counts()
    assert led.write_failures == 0, "one injected fault must retry clean"
    data = lm.load_ledger(path)
    assert "query1" in data.queries, "retried record must persist"
    # persistent failure: every attempt raises -> degrade, keep serving
    real_open_write = led._f.write

    def broken(_s):
        raise OSError("disk full")

    led._f.write = broken
    led.query("query2", status="ok", ms=2.0)
    assert led.write_failures == 1, "persistent failure must degrade"
    led._f.write = real_open_write
    led.query("query3", status="ok", ms=3.0)
    led.close("completed")
    data = lm.load_ledger(path)
    assert "query3" in data.queries and "query2" not in data.queries


def test_server_error_result_drains_fault_events():
    """The serving loop's FAILURE path must drain the thread's fault
    ring into the failed query's own result line: left behind, a failed
    query's events (incl. the watchdog's `timeout`) would misattribute
    to the NEXT query's success-path drain."""
    from nds_tpu.engine import faults as F
    F.drain_fault_events()
    F.record_fault_event("sync", "timeout", detail="blocked")
    out = bench.error_result("query9", F.StatementTimeout("sync", "late"))
    assert out["timeout"] is True
    assert [e["seam"] for e in out["faultEvents"]] == ["sync"]
    assert not F.drain_fault_events(), \
        "the failure path must leave the ring EMPTY for the next query"
    # and a plain error with no events carries neither key
    out2 = bench.error_result("query10", ValueError("boom"))
    assert "faultEvents" not in out2 and "timeout" not in out2


def test_drain_parent_faults_ledgers_bench_child_events(tmp_path):
    """bench-child seam evidence is recorded in the PARENT's ring (the
    child is the thing that failed): run_parent's drain must land it in
    the campaign ledger as a progress note, not let it die in the
    ring."""
    lm = bench.ledger_mod()
    F = bench.faults_mod()
    F.drain_fault_events()
    path = str(tmp_path / "l.jsonl")
    led = lm.Ledger(path, driver="bench")
    F.record_fault_event("bench-child", "degrade", detail="injected")
    events = bench.drain_parent_faults(led)
    led.close(None)
    assert [(e.seam, e.action) for e in events] == \
        [("bench-child", "degrade")]
    assert not F.drain_fault_events(), "ring must be drained"
    recs = [rec for _ln, rec in lm.iter_ledger(path)
            if rec["kind"] == "progress"]
    (note,) = [r for r in recs if r.get("note") == "fault-event"]
    assert note["seam"] == "bench-child" and note["action"] == "degrade"
    # ledger off: events still drain (no misattribution), none written
    F.record_fault_event("bench-child", "degrade")
    assert len(bench.drain_parent_faults(None)) == 1
