# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Bench harness policy tests (no device work)."""

import importlib.util
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

spec = importlib.util.spec_from_file_location(
    "bench_mod", os.path.join(REPO, "bench.py"))
bench = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bench)


class TestResolveBaseline:
    def test_first_full_run_writes_baseline(self, tmp_path):
        f = tmp_path / "base.json"
        vs = bench.resolve_baseline(str(f), 100.0, 99, 99)
        assert vs == 1.0
        assert json.load(open(f))["n_queries"] == 99

    def test_same_set_compares(self, tmp_path):
        f = tmp_path / "base.json"
        bench.resolve_baseline(str(f), 100.0, 99, 99)
        vs = bench.resolve_baseline(str(f), 50.0, 99, 99)
        assert vs == 2.0                       # 2x faster than baseline

    def test_partial_run_never_overwrites(self, tmp_path):
        f = tmp_path / "base.json"
        bench.resolve_baseline(str(f), 100.0, 99, 99)
        vs = bench.resolve_baseline(str(f), 10.0, 95, 99)  # wedged chunk
        assert vs == 1.0                       # not comparable, no clobber
        assert json.load(open(f))["value"] == 100.0
        assert bench.resolve_baseline(str(f), 100.0, 99, 99) == 1.0

    def test_ratchet_growth_rebaselines(self, tmp_path):
        f = tmp_path / "base.json"
        bench.resolve_baseline(str(f), 100.0, 80, 80)
        vs = bench.resolve_baseline(str(f), 120.0, 99, 99)  # set grew
        assert vs == 1.0
        assert json.load(open(f))["n_queries"] == 99


def test_bench_queries_names_match_stream_names():
    queries = bench.bench_queries()
    names = [n for n, _ in queries]
    assert len(names) == len(set(names))
    assert all(n.startswith("query") for n in names)
    # the four split templates surface as _part1/_part2 names
    if len(names) > 1:
        assert "query14_part1" in names and "query14_part2" in names
