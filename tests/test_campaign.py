# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Campaign driver tests: arm matrix expansion, env fingerprints,
manifest round-trip, kill-proof resume (SIGKILL mid-arm), classified arm
failures, the bench-side provenance stamp, and the cross-arm report —
all against a FAKE bench child (subprocess stub), no device work."""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import types

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools._ledger_load import campaign_mod, ledger_mod  # noqa: E402

C = campaign_mod()
L = ledger_mod()


def _load_tool(name, relpath):
    mod = sys.modules.get(name)
    if mod is None:
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(REPO, relpath))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def campaign_tool():
    return _load_tool("_t_campaign_tool", "tools/campaign.py")


@pytest.fixture(scope="module")
def bench_compare():
    return _load_tool("_nds_bench_compare", "tools/bench_compare.py")


@pytest.fixture(autouse=True)
def _clean_knobs(monkeypatch):
    # arm fingerprints must be deterministic regardless of the invoking
    # shell's knob set
    for k in C.FINGERPRINT_KNOBS + ("NDS_CAMPAIGN_ARM", "NDS_FAKE_MODE",
                                    "NDS_FAKE_CALLS"):
        monkeypatch.delenv(k, raising=False)


@pytest.fixture
def no_signals(monkeypatch):
    # in-process driver runs must not install real handlers over
    # pytest's; the driver only needs .signal/.SIGTERM/.SIGINT
    monkeypatch.setattr(C, "signal", types.SimpleNamespace(
        signal=lambda signum, fn: None,
        SIGTERM=signal.SIGTERM, SIGINT=signal.SIGINT))


# the fake bench child: writes a STAMPED ledger exactly like bench.py's
# parent would, honoring resume (a preexisting ledger means the first
# segment's queries are not re-paid). NDS_FAKE_MODE (per-arm overlay):
#   ok            both queries + terminal completed record
#   fail          exit 3 before touching the ledger
#   kill-campaign first segment: query1 then SIGKILL the DRIVER
#                 (resume segment: query2 + terminal record)
_STUB = """\
import json, os, signal, sys
sys.path.insert(0, {repo!r})
from tools._ledger_load import ledger_mod, campaign_mod
L, C = ledger_mod(), campaign_mod()
path = os.environ["NDS_BENCH_RESULTS_JSONL"]
calls = os.environ.get("NDS_FAKE_CALLS")
if calls:
    with open(calls, "a") as f:
        f.write(os.environ.get("NDS_CAMPAIGN_ARM", "?") + "\\n")
mode = os.environ.get("NDS_FAKE_MODE", "ok")
if mode == "fail":
    sys.exit(3)
resuming = os.path.exists(path) and os.path.getsize(path) > 0
led = L.Ledger(path, stamp=C.campaign_stamp(), driver="bench", scale="10")
if not resuming:
    led.query("query1", ms=100.0, hostSyncs=1)
    if mode == "kill-campaign":
        os.kill(os.getppid(), signal.SIGKILL)
        sys.exit(7)
led.query("query2", ms=200.0, hostSyncs=1)
led.close("completed", queries=2)
"""


@pytest.fixture
def stub(tmp_path):
    p = tmp_path / "fake_bench.py"
    p.write_text(_STUB.format(repo=REPO))
    return [sys.executable, str(p)]


def _matrix(*arm_specs):
    return {"v": C.CAMPAIGN_VERSION, "env": {"NDS_BENCH_SCALE": "10"},
            "arms": [{"name": n, "env": e} for n, e in arm_specs]}


class TestArmModel:
    def test_expand_substitutes_dir_and_merges(self, tmp_path):
        arms = C.expand_arms(
            {"env": {"NDS_TPU_CHUNK_STORE": "{dir}/store"},
             "arms": [{"name": "base", "env": {}},
                      {"name": "cold",
                       "env": {"NDS_TPU_CHUNK_STORE": ""}}]},
            str(tmp_path))
        assert arms[0].env["NDS_TPU_CHUNK_STORE"] == \
            str(tmp_path) + "/store"
        assert arms[1].env["NDS_TPU_CHUNK_STORE"] == ""  # unset marker

    @pytest.mark.parametrize("matrix,msg", [
        ({"arms": []}, "non-empty"),
        ({"v": 99, "arms": [{"name": "a"}]}, "version"),
        ({"arms": [{"name": "a"}, {"name": "a"}]}, "duplicate"),
        ({"arms": [{"name": "../evil"}]}, "safe"),
        ({"arms": [{"env": {}}]}, "name"),
    ])
    def test_matrix_validation_is_loud(self, matrix, msg, tmp_path):
        with pytest.raises(C.CampaignError, match=msg):
            C.expand_arms(matrix, str(tmp_path))

    def test_fingerprint_distinguishes_unset_from_value(self):
        a = C.env_fingerprint({})
        b = C.env_fingerprint({"NDS_TPU_PALLAS": "auto"})
        assert a != b and "<unset>" in a and "NDS_TPU_PALLAS=auto" in b

    def test_overlay_removal_changes_fingerprint(self):
        base = {"NDS_TPU_CHUNK_STORE": "/warm"}
        warm = C.arm_fingerprint(C.Arm("w", {}), base)
        cold = C.arm_fingerprint(
            C.Arm("c", {"NDS_TPU_CHUNK_STORE": ""}), base)
        assert "CHUNK_STORE=/warm" in warm
        assert "CHUNK_STORE=<unset>" in cold

    def test_stamp_carries_arm_only_inside_campaign(self):
        assert "arm" not in C.campaign_stamp({})
        st = C.campaign_stamp({"NDS_CAMPAIGN_ARM": "base"})
        assert st["arm"] == "base" and "envFingerprint" in st


class TestManifest:
    def test_round_trip(self, tmp_path):
        arms = C.expand_arms(_matrix(("a", {}), ("b", {})), str(tmp_path))
        m = C.new_manifest(arms, str(tmp_path))
        C.write_manifest(str(tmp_path), m)
        got = C.load_manifest(str(tmp_path))
        assert got == m
        assert [a["name"] for a in got["arms"]] == ["a", "b"]
        assert all(a["fingerprint"] for a in got["arms"])

    def test_missing_is_none_and_unknown_version_refused(self, tmp_path):
        assert C.load_manifest(str(tmp_path)) is None
        with open(C.manifest_path(str(tmp_path)), "w") as f:
            json.dump({"v": 99}, f)
        with pytest.raises(C.CampaignError, match="version"):
            C.load_manifest(str(tmp_path))


class TestLedgerStamp:
    def test_stamp_rides_every_record_including_terminal(self, tmp_path):
        p = tmp_path / "led.jsonl"
        led = L.Ledger(str(p), stamp={"arm": "base",
                                      "envFingerprint": "fp-x"},
                       driver="bench", scale="10")
        led.query("query1", ms=10.0)
        led.progress(done=1)
        led.close("completed", queries=1)
        recs = [json.loads(ln) for ln in open(p)]
        assert {r["kind"] for r in recs} == \
            {"meta", "query", "progress", "end"}
        for r in recs:
            assert r["arm"] == "base" and r["envFingerprint"] == "fp-x"

    def test_unstamped_ledger_unchanged(self, tmp_path):
        p = tmp_path / "led.jsonl"
        led = L.Ledger(str(p), driver="bench")
        led.query("query1", ms=10.0)
        led.close("completed")
        for r in (json.loads(ln) for ln in open(p)):
            assert "arm" not in r and "envFingerprint" not in r


class TestResumeAdmission:
    def _arm(self, tmp_path, **env):
        return C.Arm("a1", {k: str(v) for k, v in env.items()})

    def _write(self, tmp_path, arm, end=None, fingerprint=None):
        path = C.arm_paths(str(tmp_path), arm.name)["ledger"]
        fp = fingerprint or C.arm_fingerprint(arm, {})
        led = L.Ledger(path, stamp={"envFingerprint": fp, "arm": arm.name},
                       driver="bench")
        led.query("query1", ms=10.0)
        led.close(end)
        return path

    def test_pending_partial_done(self, tmp_path):
        arm = self._arm(tmp_path)
        assert C.arm_status(arm, str(tmp_path), {})[0] == "pending"
        self._write(tmp_path, arm)                 # no terminal record
        assert C.arm_status(arm, str(tmp_path), {})[0] == "partial"
        os.remove(C.arm_paths(str(tmp_path), arm.name)["ledger"])
        self._write(tmp_path, arm, end="completed")
        assert C.arm_status(arm, str(tmp_path), {})[0] == "done"

    def test_aborted_round_resumes_not_skips(self, tmp_path):
        arm = self._arm(tmp_path)
        self._write(tmp_path, arm, end="aborted")  # signal-killed round
        assert C.arm_status(arm, str(tmp_path), {})[0] == "partial"

    def test_fingerprint_mismatch_refused_naming_both(self, tmp_path):
        arm = self._arm(tmp_path, NDS_TPU_PALLAS="off")
        self._write(tmp_path, arm, fingerprint="NDS_TPU_PALLAS=auto;...")
        with pytest.raises(C.CampaignResumeError) as ei:
            C.arm_status(arm, str(tmp_path), {})
        msg = str(ei.value)
        assert "NDS_TPU_PALLAS=auto;..." in msg          # recorded
        assert "NDS_TPU_PALLAS=off" in msg               # current
        assert "refusing" in msg

    def test_legacy_unstamped_ledger_resumes_freely(self, tmp_path):
        arm = self._arm(tmp_path)
        path = C.arm_paths(str(tmp_path), arm.name)["ledger"]
        led = L.Ledger(path, driver="bench")       # pre-campaign artifact
        led.query("query1", ms=10.0)
        led.close(None)
        assert C.arm_status(arm, str(tmp_path), {})[0] == "partial"

    def test_corrupt_ledger_reported_not_rerun(self, tmp_path):
        arm = self._arm(tmp_path)
        path = C.arm_paths(str(tmp_path), arm.name)["ledger"]
        os.makedirs(os.path.dirname(path))
        with open(path, "w") as f:
            f.write(json.dumps({"v": 99, "kind": "meta", "t": 0}) + "\n")
        status, why = C.arm_status(arm, str(tmp_path), {})
        assert status == "corrupt" and why


class TestDriver:
    def test_full_matrix_completes_all_arms(self, tmp_path, stub,
                                            no_signals, capsys):
        d = str(tmp_path / "camp")
        arms = C.expand_arms(_matrix(("a1", {}), ("a2", {}), ("a3", {})),
                             d)
        m = C.run_campaign(arms, d, bench_cmd=stub)
        assert [a["status"] for a in m["arms"]] == ["completed"] * 3
        assert m["status"] == "completed" and m["completedArms"] == 3
        assert C.load_manifest(d)["completedArms"] == 3   # durable
        for a in arms:
            data = L.load_ledger(C.arm_paths(d, a.name)["ledger"])
            assert data.end["status"] == "completed"
            assert data.meta["arm"] == a.name             # stamped
            assert data.meta["envFingerprint"] == C.arm_fingerprint(a)

    def test_completed_arms_skipped_on_rerun(self, tmp_path, stub,
                                             no_signals, monkeypatch):
        d = str(tmp_path / "camp")
        calls = tmp_path / "calls.txt"
        monkeypatch.setenv("NDS_FAKE_CALLS", str(calls))
        arms = C.expand_arms(_matrix(("a1", {}), ("a2", {})), d)
        C.run_campaign(arms, d, bench_cmd=stub)
        C.run_campaign(arms, d, bench_cmd=stub)   # same command again
        # rerun invoked NO bench child: both arms carried clean
        # terminal records
        assert calls.read_text().splitlines() == ["a1", "a2"]
        m = C.load_manifest(d)
        assert [a["status"] for a in m["arms"]] == ["done", "done"]

    def test_failing_arm_classified_without_aborting_rest(
            self, tmp_path, stub, no_signals, capsys):
        d = str(tmp_path / "camp")
        arms = C.expand_arms(
            _matrix(("a1", {}), ("bad", {"NDS_FAKE_MODE": "fail"}),
                    ("a3", {})), d)
        m = C.run_campaign(arms, d, bench_cmd=stub)
        by = {a["name"]: a for a in m["arms"]}
        assert by["a1"]["status"] == "completed"
        assert by["a3"]["status"] == "completed"   # ran despite the fail
        rec = by["bad"]
        assert rec["status"] == "failed" and rec["rc"] == 3
        # the fault-matrix ladder, not an ad-hoc label: the bench-child
        # seam's registered class and recovery policy
        assert rec["classified"]["seam"] == "bench-child"
        assert rec["classified"]["class"] == "transient"
        assert "backoff" in rec["classified"]["recovery"]

    def test_spawn_failure_classified(self, tmp_path, no_signals, capsys):
        d = str(tmp_path / "camp")
        arms = C.expand_arms(_matrix(("a1", {})), d)
        m = C.run_campaign(arms, d,
                           bench_cmd=["/nonexistent-bench-binary"])
        rec = m["arms"][0]
        assert rec["status"] == "failed"
        assert rec["classified"]["seam"] == "bench-child"

    def test_injected_spawn_fault_classified(self, tmp_path, stub,
                                             no_signals, monkeypatch,
                                             capsys):
        # the arm spawn is a REGISTERED seam: the fault-injection matrix
        # can prove the ladder end to end without a real failure
        monkeypatch.setenv("NDS_TPU_FAULT", "bench-child:error:1")
        d = str(tmp_path / "camp")
        arms = C.expand_arms(_matrix(("a1", {}), ("a2", {})), d)
        m = C.run_campaign(arms, d, bench_cmd=stub)
        by = {a["name"]: a for a in m["arms"]}
        assert by["a1"]["status"] == "failed"
        assert by["a1"]["classified"]["seam"] == "bench-child"
        monkeypatch.delenv("NDS_TPU_FAULT")
        assert by["a2"]["status"] == "completed"

    def test_mismatched_arm_refused_campaign_continues(
            self, tmp_path, stub, no_signals, capsys):
        d = str(tmp_path / "camp")
        arms = C.expand_arms(_matrix(("a1", {}), ("a2", {})), d)
        # a1's ledger was recorded under OTHER knobs
        path = C.arm_paths(d, "a1")["ledger"]
        led = L.Ledger(path, stamp={"envFingerprint": "alien-fp"},
                       driver="bench")
        led.query("query1", ms=10.0)
        led.close(None)
        m = C.run_campaign(arms, d, bench_cmd=stub)
        by = {a["name"]: a for a in m["arms"]}
        assert by["a1"]["status"] == "failed"
        assert "fingerprint" in by["a1"]["error"]
        assert "alien-fp" in by["a1"]["error"]     # both fps named
        assert by["a2"]["status"] == "completed"


class TestKillResume:
    def test_sigkill_mid_arm_then_rerun_resumes(self, tmp_path):
        """The acceptance scenario: the campaign process is SIGKILLed
        while arm k2 is mid-flight; rerunning the SAME command skips the
        completed arm (its bench child is never re-invoked) and resumes
        the partial arm off its own ledger — the first segment's
        measured query is never re-paid."""
        d = str(tmp_path / "camp")
        stub_py = tmp_path / "fake_bench.py"
        stub_py.write_text(_STUB.format(repo=REPO))
        matrix_path = tmp_path / "arms.json"
        matrix_path.write_text(json.dumps(_matrix(
            ("k1", {}),
            ("k2", {"NDS_FAKE_MODE": "kill-campaign"}),
            ("k3", {}))))
        calls = tmp_path / "calls.txt"
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("NDS_TPU_", "NDS_BENCH_",
                                    "NDS_CAMPAIGN_", "NDS_FAKE_"))}
        env["NDS_FAKE_CALLS"] = str(calls)
        cmd = [sys.executable, os.path.join(REPO, "tools", "campaign.py"),
               "--matrix", str(matrix_path), "--dir", d,
               "--bench-cmd", f"{sys.executable} {stub_py}"]
        r1 = subprocess.run(cmd, env=env, capture_output=True, text=True,
                            timeout=120)
        assert r1.returncode == -signal.SIGKILL, (r1.stdout, r1.stderr)
        # the kill landed mid-k2: k1 clean-completed, k2's ledger holds
        # exactly the first segment, no terminal record
        k2 = L.load_ledger(C.arm_paths(d, "k2")["ledger"])
        assert k2.times() == {"query1": 100.0} and k2.end is None
        r2 = subprocess.run(cmd, env=env, capture_output=True, text=True,
                            timeout=120)
        assert r2.returncode == 0, (r2.stdout, r2.stderr)
        assert "k1: already completed" in r2.stderr
        assert "k2: resuming off its ledger" in r2.stderr
        # k1 ran ONCE across both invocations; k2 ran twice (kill +
        # resume); k3 ran once (after the resume)
        seq = calls.read_text().splitlines()
        assert seq == ["k1", "k2", "k2", "k3"]
        k2 = L.load_ledger(C.arm_paths(d, "k2")["ledger"])
        assert k2.times() == {"query1": 100.0, "query2": 200.0}
        assert k2.end["status"] == "completed"
        m = C.load_manifest(d)
        assert [a["status"] for a in m["arms"]] == \
            ["done", "completed", "completed"]
        assert m["status"] == "completed"


class TestBenchStamp:
    @pytest.fixture()
    def bench(self):
        spec = importlib.util.spec_from_file_location(
            "bench_mod", os.path.join(REPO, "bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_every_record_carries_arm_and_fingerprint(
            self, bench, tmp_path, monkeypatch, capsys):
        """bench.py under a campaign arm stamps provenance into EVERY
        ledger record — the query records AND the terminal end record a
        signal handler writes — so cross-arm merges key on recorded
        provenance, not file paths."""
        monkeypatch.setenv("NDS_BENCH_SEED_BASELINE", "1")
        monkeypatch.setattr(bench, "REPO", str(tmp_path))
        monkeypatch.setattr(bench, "ensure_data", lambda: None)
        monkeypatch.setattr(bench, "bench_queries",
                            lambda: [("query1", "s1"), ("query2", "s2")])
        monkeypatch.setattr(bench, "_emitted", False)
        ledger_path = tmp_path / "campaign.jsonl"
        monkeypatch.setenv("NDS_BENCH_RESULTS_JSONL", str(ledger_path))
        monkeypatch.setenv("NDS_BENCH_HEARTBEAT_S", "0")
        monkeypatch.setenv("NDS_CAMPAIGN_ARM", "pallas-off")
        monkeypatch.setenv("NDS_TPU_PALLAS", "off")

        handlers = {}
        monkeypatch.setattr(bench.signal, "signal",
                            lambda signum, fn:
                            handlers.setdefault(signum, fn))
        monkeypatch.setattr(bench.os, "_exit",
                            lambda code: (_ for _ in ()).throw(
                                SystemExit(code)))

        class OneQueryChild:
            def __init__(self):
                self.proc = None
                self.started = False

            def alive(self):
                return self.started

            def start(self, deadline_left):
                self.started = True
                return {"ready": True, "platform": "axon"}

            def run_query(self, name, timeout):
                if name == "query1":
                    return {"name": "query1", "ms": 123.0, "hostSyncs": 1,
                            "syncWaitMs": 2.0}
                handlers[bench.signal.SIGTERM](bench.signal.SIGTERM, None)
                raise AssertionError("handler must not return")

            def stop(self):
                pass

        monkeypatch.setattr(bench, "ChildServer", OneQueryChild)
        import time as _time
        with pytest.raises(SystemExit):
            bench.run_parent(_time.perf_counter())
        capsys.readouterr()
        expect_fp = C.env_fingerprint()
        recs = [json.loads(ln) for ln in open(ledger_path)]
        kinds = {r["kind"] for r in recs}
        assert "end" in kinds and "query" in kinds
        for r in recs:
            assert r["arm"] == "pallas-off", r
            assert r["envFingerprint"] == expect_fp, r
        assert "NDS_TPU_PALLAS=off" in expect_fp

    def test_load_resume_refuses_mismatched_fingerprint(
            self, bench, tmp_path, monkeypatch):
        """Satellite: a resumed run under DIFFERENT knobs must refuse
        loudly instead of silently mixing two arms into one artifact —
        CampaignResumeError names both fingerprints."""
        p = tmp_path / "results.jsonl"
        monkeypatch.setenv("NDS_TPU_PALLAS", "auto")
        led = L.Ledger(str(p), stamp=C.campaign_stamp(), driver="bench")
        led.query("query1", ms=10.0)
        led.close(None)
        recorded = C.env_fingerprint()
        monkeypatch.setenv("NDS_TPU_PALLAS", "off")
        with pytest.raises(C.CampaignResumeError) as ei:
            bench.load_resume(str(p), {}, {})
        assert recorded in str(ei.value)
        assert "NDS_TPU_PALLAS=off" in str(ei.value)
        # same knobs: resumes normally
        monkeypatch.setenv("NDS_TPU_PALLAS", "auto")
        times = {}
        bench.load_resume(str(p), times, {})
        assert times == {"query1": 10.0}


def _arm_ledger(path, arm, times, ici=0, stall=0.0, exchange_ms=0.0):
    led = L.Ledger(str(path), stamp={"arm": arm, "envFingerprint": "fp-t"},
                   driver="bench", platform="axon", scale="10")
    for q, ms in times.items():
        scan = {"chunks": 4, "syncs": 0, "bytesH2d": 1_000_000,
                "path": "compiled", "prefetchStallMs": stall}
        if ici:
            scan["bytesIci"] = ici
            scan["shards"] = 2
            scan["collectives"] = 2
        phases = {"query": {"ms": ms}, "plan": {"ms": ms}}
        if exchange_ms:
            phases["stream.exchange"] = {"ms": exchange_ms}
        led.query(q, ms=ms, hostSyncs=2, streamedScans=[scan],
                  tracePhases={"phases": phases})
    led.close("completed", queries=len(times))
    return str(path)


class TestCrossArm:
    def test_bench_compare_multi_round_table(self, bench_compare,
                                             tmp_path, capsys):
        """Satellite: >2 ledgers render the cross-arm table (labeled by
        RECORDED arm names), while --gate keeps its strict two-round
        contract."""
        paths = [
            _arm_ledger(tmp_path / f"{n}.jsonl", n,
                        {"query1": t, "query2": 2 * t})
            for n, t in (("base", 100.0), ("pallas-off", 150.0),
                         ("prefetch-off", 120.0))]
        rc = bench_compare.main(paths)
        out = capsys.readouterr().out
        assert rc == 0
        assert "cross-arm" in out and "primary = base" in out
        for label in ("base", "pallas-off", "prefetch-off"):
            assert f"| {label} |" in out
        assert "x1.50" in out            # pallas-off mover named
        with pytest.raises(SystemExit) as ei:
            bench_compare.main(paths + ["--gate"])
        assert ei.value.code == 2        # gate stays two-round

    def test_two_round_diff_unchanged(self, bench_compare, tmp_path,
                                      capsys):
        a = _arm_ledger(tmp_path / "a.jsonl", "base", {"query1": 100.0})
        b = _arm_ledger(tmp_path / "b.jsonl", "arm-b", {"query1": 100.0})
        assert bench_compare.main([a, b, "--gate"]) == 0
        assert "geomean" in capsys.readouterr().out

    def test_report_renders_named_deltas(self, campaign_tool, tmp_path,
                                         capsys):
        """Acceptance: the merged cross-arm report renders the fused/
        prefetch/shard delta lines and the static-roofline column from
        the arm ledgers alone."""
        d = str(tmp_path / "camp")
        arms = C.expand_arms(
            _matrix(("base", {}), ("pallas-off", {}),
                    ("prefetch-off", {}), ("shards-2", {})), d)
        specs = {
            "base": dict(times={"query1": 100.0, "query2": 50.0},
                         stall=5.0),
            "pallas-off": dict(times={"query1": 160.0, "query2": 80.0}),
            "prefetch-off": dict(times={"query1": 130.0, "query2": 60.0},
                                 stall=0.0),
            "shards-2": dict(times={"query1": 90.0, "query2": 45.0},
                             ici=50_000_000, exchange_ms=10.0),
        }
        for a in arms:
            path = C.arm_paths(d, a.name)["ledger"]
            os.makedirs(os.path.dirname(path), exist_ok=True)
            _arm_ledger(path, a.name, **specs[a.name])
        lines = campaign_tool.report_lines(arms, d, "base")
        text = "\n".join(lines)
        assert "| base |" in text and "primary = base" in text
        assert "fused-kernel delta" in text and "x1.60" in text
        assert "prefetch overlap delta" in text
        assert "# shard scaling: shards-2" in text
        assert "static-roofline %" in text       # column present
        assert "ici GB/s" in text
        # ici GB/s = 50 MB over 10 ms exchange wall = 5.0 GB/s
        assert "| 5.0 |" in text

    def test_report_written_to_campaign_dir(self, campaign_tool, stub,
                                            tmp_path, monkeypatch,
                                            capsys, no_signals):
        d = str(tmp_path / "camp")
        matrix = tmp_path / "m.json"
        matrix.write_text(json.dumps(_matrix(("base", {}))))
        rc = campaign_tool.main(
            ["--matrix", str(matrix), "--dir", d,
             "--bench-cmd", " ".join(stub)])
        assert rc == 0
        assert os.path.exists(os.path.join(d, "report.md"))
        assert "| base |" in open(os.path.join(d, "report.md")).read()


class TestCLI:
    def test_dry_run_prints_exact_matrix(self, campaign_tool, capsys):
        """Acceptance: --preset sf10-full --dry-run prints every arm
        with its env overlay, fingerprint and ledger path, and runs
        nothing."""
        assert campaign_tool.main(["--preset", "sf10-full",
                                   "--dry-run"]) == 0
        out = capsys.readouterr().out
        assert "9 arms" in out
        for arm in ("base", "pallas-off", "prefetch-off", "store-cold",
                    "encoded-off", "shards-1", "shards-2", "shards-4",
                    "shards-8"):
            assert f"arm {arm}\n" in out
        assert "NDS_TPU_PALLAS=off" in out
        assert "NDS_TPU_STREAM_SHARDS=8" in out
        assert "NDS_TPU_CHUNK_STORE=<unset>" in out     # store-cold
        assert "fingerprint: " in out and "ledger: " in out

    def test_unknown_preset_refused(self, campaign_tool, capsys):
        assert campaign_tool.main(["--preset", "nope",
                                   "--dry-run"]) == 2
        assert "unknown preset" in capsys.readouterr().err

    def test_list_presets(self, campaign_tool, capsys):
        assert campaign_tool.main(["--list-presets"]) == 0
        out = capsys.readouterr().out
        assert "sf10-full: 9 arms" in out
