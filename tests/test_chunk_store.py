# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Persistent pre-encoded chunk store (io/chunk_store.py).

Round-trip: a warm run must slice mmapped wire arrays into the SAME
padded chunks (bit-for-bit query results) without touching arrow
slicing or codec planning. Edges per the store contract: version gate
REFUSED loudly (ChunkStoreError — fatal), a corrupt entry (checksum
mismatch) refused at load_plan but RECOVERED on the engine path
(delete + re-encode from source, FaultEvent evidence — the
chunk-store-read seam), a stale codec plan (data changed under the
same shape) INVALIDATES silently (miss -> re-encode -> overwrite),
empty / single-row tables round-trip, and a writer KILLED mid-write
leaves the slot old-valid-or-none with a stale lock the next writer
steals (the chunk-store-write seam).
"""

import json
import os

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.engine.session import Session
from nds_tpu.engine.table import ChunkedTable
from nds_tpu.io import chunk_store as CS


def _table(n=5000, seed=3, shift=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": pa.array(rng.integers(0, 50, n) + shift, pa.int64()),
        "v": pa.array(rng.integers(0, 10_000, n), pa.int64()),
        "s": pa.array([f"x{i % 7}" for i in range(n)], pa.string()),
        "f": pa.array(rng.random(n), pa.float64()),
    })


_SQL = ("select k, s, count(*) c, sum(v) sv from t where v > 100 "
        "group by k, s order by k, s")


def _run(tbl, chunk_rows=800):
    s = Session()
    s.create_temp_view("t", ChunkedTable(tbl, chunk_rows=chunk_rows),
                       base=True)
    return s.sql(_SQL).collect()


def _entry(root):
    (e,) = [d for d in os.listdir(root) if not d.startswith(".")]
    return os.path.join(root, e)


def test_store_round_trip_bit_for_bit(tmp_path, monkeypatch):
    """Cold run (build + persist), warm run (load + mmap), and the
    store-off baseline must all produce identical rows; the warm run
    must go through load_plan, not re-save."""
    tbl = _table()
    base = _run(tbl)
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", str(tmp_path))
    cold = _run(tbl)
    entry = _entry(str(tmp_path))
    manifest0 = open(os.path.join(entry, "manifest.json")).read()
    saves = []
    orig_save = CS.save_plan
    monkeypatch.setattr(CS, "save_plan",
                        lambda *a, **k: saves.append(1) or
                        orig_save(*a, **k))
    warm = _run(tbl)
    assert cold == base == warm and base
    assert not saves, "warm run re-encoded instead of loading the store"
    assert open(os.path.join(entry, "manifest.json")).read() == manifest0


def test_store_warm_run_skips_arrow_and_codec_planning(tmp_path,
                                                       monkeypatch):
    """The tentpole claim: a warm run never lowers from arrow and never
    re-plans codecs or re-encodes dictionaries — padded_chunks serves
    mmapped wire arrays only."""
    from nds_tpu.engine import column as _column
    from nds_tpu.io import columnar as _col
    tbl = _table()
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", str(tmp_path))
    _run(tbl)                              # cold: build + persist

    def _refuse(what):
        def f(*a, **k):
            raise AssertionError(f"warm store run called {what}")
        return f

    monkeypatch.setattr(_col, "plan_column_codec",
                        _refuse("plan_column_codec (codec re-planning)"))
    monkeypatch.setattr(_column, "from_arrow_array",
                        _refuse("from_arrow_array (arrow chunk "
                                "lowering)"))
    monkeypatch.setattr(ChunkedTable, "_build_wire_plan",
                        _refuse("_build_wire_plan (re-encode)"))
    monkeypatch.setattr(ChunkedTable, "_string_encodings",
                        _refuse("_string_encodings (dictionary "
                                "re-encode)"))
    got = _run(tbl)
    assert got, "warm store run produced nothing"


def test_store_version_gate_refused_loudly(tmp_path, monkeypatch):
    tbl = _table()
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", str(tmp_path))
    _run(tbl)
    mp = os.path.join(_entry(str(tmp_path)), "manifest.json")
    m = json.load(open(mp))
    m["version"] = CS.STORE_VERSION + 1
    json.dump(m, open(mp, "w"))
    with pytest.raises(CS.ChunkStoreError, match="layout version"):
        _run(tbl)


def _corrupt_entry(entry):
    (data0,) = [f for f in sorted(os.listdir(entry))
                if f.endswith("000.data.npy")]
    p = os.path.join(entry, data0)
    with open(p, "r+b") as f:
        f.seek(-1, 2)
        b = f.read(1)
        f.seek(-1, 2)
        f.write(bytes([b[0] ^ 0xFF]))


def test_store_checksum_mismatch_refused_then_recovered(tmp_path,
                                                        monkeypatch):
    """Two halves of the corrupt-entry contract (DESIGN.md
    "Fault-tolerance contract", chunk-store-read seam): a DIRECT
    load_plan refuses the corrupt entry loudly (ChunkStoreCorrupt —
    corrupt codes are never handed out), while the ENGINE path recovers
    by deleting + re-encoding from source — correct rows, a recorded
    FaultEvent, and a fresh valid entry on disk."""
    from nds_tpu.engine import faults as F
    tbl = _table()
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", str(tmp_path))
    expect = _run(tbl)
    entry = _entry(str(tmp_path))
    # the store entry is keyed to the PRUNED scan (the query's column
    # set, in plan order) — read that identity off the manifest
    pruned = tbl.select([c["name"] for c in json.load(
        open(os.path.join(entry, "manifest.json")))["columns"]])
    _corrupt_entry(entry)
    with pytest.raises(CS.ChunkStoreCorrupt, match="checksum mismatch"):
        CS.load_plan(str(tmp_path), pruned, {})
    F.drain_fault_events()
    got = _run(tbl)
    assert got == expect and got, "recovery changed the results"
    events = F.drain_fault_events()
    assert [e.seam for e in events] == ["chunk-store-read"], events
    assert events[0].action == "recovered"
    # the slot was re-encoded whole: a further warm run loads clean
    assert CS.load_plan(str(tmp_path), pruned, {}) is not None
    assert _run(tbl) == expect


def test_store_stale_codec_plan_invalidates(tmp_path, monkeypatch):
    """Same shape, different DATA (shifted key domain => different FOR
    base): the old entry must read as a miss, the query must re-encode
    against the new data (correct results), and the entry on disk must
    be overwritten with the new fingerprint."""
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", str(tmp_path))
    old = _table(shift=0)
    _run(old)
    entry = _entry(str(tmp_path))
    fp_old = json.load(open(os.path.join(entry, "manifest.json")))[
        "fingerprint"]
    new = _table(shift=1000)               # same schema/rows, new values
    monkeypatch.delenv("NDS_TPU_CHUNK_STORE")
    expect = _run(new)                     # store-off truth
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", str(tmp_path))
    got = _run(new)
    assert got == expect and got, \
        "stale store entry served old codes for new data"
    fp_new = json.load(open(os.path.join(entry, "manifest.json")))[
        "fingerprint"]
    assert fp_new != fp_old, "entry was not rewritten after data change"
    assert _run(new) == expect             # and the new entry is warm


def test_store_empty_and_single_row_tables(tmp_path, monkeypatch):
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", str(tmp_path))
    empty = _table(n=0)
    one = _table(n=1, seed=9)
    for tbl in (empty, one):
        s = Session()
        s.create_temp_view("t", ChunkedTable(tbl, chunk_rows=800),
                           base=True)
        cold = s.sql("select k, v, s from t order by k").collect()
        s2 = Session()
        s2.create_temp_view("t", ChunkedTable(tbl, chunk_rows=800),
                            base=True)
        warm = s2.sql("select k, v, s from t order by k").collect()
        assert cold == warm
        assert len(cold) == tbl.num_rows


def test_store_off_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("NDS_TPU_CHUNK_STORE", raising=False)
    assert CS.store_root() is None
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", "")
    assert CS.store_root() is None         # empty = off
    _run(_table(n=64))
    assert not os.listdir(str(tmp_path))


def test_store_and_ring_compose(tmp_path, monkeypatch):
    """The warm store feeds the prefetch ring: mmapped wire arrays slice
    inside the worker thread, results identical to the inline no-store
    path at both depths."""
    tbl = _table()
    base = _run(tbl)
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", str(tmp_path))
    _run(tbl)                              # persist
    for depth in ("0", "3"):
        monkeypatch.setenv("NDS_TPU_PREFETCH_DEPTH", depth)
        from nds_tpu.engine import stream
        stream.reset_pipeline_cache()
        assert _run(tbl) == base, f"store+ring divergence at depth {depth}"


def test_store_killed_writer_leaves_valid_state_and_stale_lock_steals(
        tmp_path, monkeypatch):
    """Concurrent-writer safety (chunk-store-write seam): a writer
    process SIGKILLed mid-write must leave the entry slot either
    old-valid or absent — never a half entry the loader would trust —
    plus a stale lock file that the next writer steals by pid liveness,
    after which the slot persists clean and loads bit-for-bit."""
    import signal
    import subprocess
    import sys
    import time as _time

    import pyarrow.parquet as pq

    tbl = _table(n=2000)
    src = str(tmp_path / "src.parquet")
    pq.write_table(tbl, src)
    root = str(tmp_path / "store")
    script = (
        "import os, sys\n"
        "import pyarrow.parquet as pq\n"
        f"sys.path.insert(0, {repr(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))})\n"
        "from nds_tpu.engine.table import ChunkedTable\n"
        f"tbl = pq.read_table({src!r})\n"
        "ct = ChunkedTable(tbl, chunk_rows=800)\n"
        "plan = ct._build_wire_plan()\n"
        "from nds_tpu.io import chunk_store as CS\n"
        "print('SAVING', flush=True)\n"
        f"CS.save_plan({root!r}, tbl, {{}}, plan)\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               # hang-kind injection parks the writer BETWEEN buffer
               # writes (after the first column's .npy landed in the
               # temp dir) — the deterministic mid-write kill point
               NDS_TPU_FAULT="chunk-store-write:hang:1",
               NDS_TPU_FAULT_HANG_S="60")
    proc = subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE, text=True, env=env)
    assert proc.stdout.readline().strip() == "SAVING"
    _time.sleep(1.0)                       # inside the injected hang
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=30)
    # the slot: no entry directory was ever swapped in (old-valid-or-
    # none; here: none), only the temp dir + the stale lock remain
    entries = [d for d in os.listdir(root) if not d.startswith(".")
               and not d.endswith(".lock")]
    assert entries == [], f"killed writer left a half entry: {entries}"
    locks = [d for d in os.listdir(root) if d.endswith(".lock")]
    assert len(locks) == 1, "killed writer should leave its lock behind"
    # a fresh writer steals the dead pid's lock and lands a whole entry
    monkeypatch.delenv("NDS_TPU_FAULT", raising=False)
    from nds_tpu.engine.table import ChunkedTable as CT
    ct = CT(tbl, chunk_rows=800)
    out = CS.save_plan(root, tbl, {}, ct._build_wire_plan())
    assert out is not None, "stale lock was not stolen"
    assert not os.path.exists(out + ".lock"), "lock not released"
    assert CS.load_plan(root, tbl, {}) is not None
    # and the store now serves queries bit-for-bit
    expect = _run(tbl)
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", root)
    assert _run(tbl) == expect


def test_store_live_writer_lock_is_respected(tmp_path):
    """Two processes warming one store directory cannot interleave: while
    a LIVE writer holds the entry lock, a second save_plan skips (returns
    None) and the caller serves its in-memory plan."""
    tbl = _table(n=256)
    root = str(tmp_path)
    ct = ChunkedTable(tbl, chunk_rows=128)
    plan = ct._build_wire_plan()
    final = CS._entry_dir(root, tbl, {})
    os.makedirs(root, exist_ok=True)
    lock = CS._acquire_entry_lock(final)
    assert lock is not None
    try:
        assert CS.save_plan(root, tbl, {}, plan) is None, \
            "second writer must yield to a live lock holder"
    finally:
        os.unlink(lock)
    assert CS.save_plan(root, tbl, {}, plan) is not None


def test_store_unstamped_lock_not_stolen_until_age(tmp_path, monkeypatch):
    """An UNSTAMPED lock (a writer caught between its O_EXCL create and
    its pid write) must not be treated as dead-on-arrival: only the age
    bound may steal it — stealing by the unreadable pid would unlink a
    live writer's fresh lock and let two writers interleave in one
    slot."""
    tbl = _table(n=256)
    root = str(tmp_path)
    ct = ChunkedTable(tbl, chunk_rows=128)
    plan = ct._build_wire_plan()
    final = CS._entry_dir(root, tbl, {})
    os.makedirs(root, exist_ok=True)
    open(final + ".lock", "w").close()          # empty: pid never landed
    assert CS.save_plan(root, tbl, {}, plan) is None, \
        "a fresh unstamped lock must be honored, not stolen"
    # ... but past the staleness age it IS reclaimed (a kill in that
    # window must not wedge the slot forever)
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE_LOCK_STALE_S", "0")
    assert CS.save_plan(root, tbl, {}, plan) is not None
    assert not os.path.exists(final + ".lock")
    assert CS.load_plan(root, tbl, {}) is not None


def test_store_lock_release_is_ownership_checked(tmp_path):
    """A writer whose lock was stolen (age bound) must NOT unlink the
    stealer's lock on its way out — only a lock still holding our own
    pid is released."""
    tbl = _table(n=256)
    root = str(tmp_path)
    plan = ChunkedTable(tbl, chunk_rows=128)._build_wire_plan()
    final = CS._entry_dir(root, tbl, {})
    os.makedirs(root, exist_ok=True)
    # simulate the steal: the slot's lock belongs to someone else now
    with open(final + ".lock", "w") as f:
        f.write("999999")
    CS._release_entry_lock(final + ".lock")
    assert os.path.exists(final + ".lock"), \
        "released a lock that was not ours"
    os.unlink(final + ".lock")
    # the normal path still releases its own lock
    assert CS.save_plan(root, tbl, {}, plan) is not None
    assert not os.path.exists(final + ".lock")
