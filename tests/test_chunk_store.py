# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Persistent pre-encoded chunk store (io/chunk_store.py).

Round-trip: a warm run must slice mmapped wire arrays into the SAME
padded chunks (bit-for-bit query results) without touching arrow
slicing or codec planning. Edges per the store contract: version gate
and checksum mismatch REFUSED loudly (ChunkStoreError, never silently
served), a stale codec plan (data changed under the same shape)
INVALIDATES silently (miss -> re-encode -> overwrite), and empty /
single-row tables round-trip.
"""

import json
import os

import numpy as np
import pyarrow as pa
import pytest

from nds_tpu.engine.session import Session
from nds_tpu.engine.table import ChunkedTable
from nds_tpu.io import chunk_store as CS


def _table(n=5000, seed=3, shift=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": pa.array(rng.integers(0, 50, n) + shift, pa.int64()),
        "v": pa.array(rng.integers(0, 10_000, n), pa.int64()),
        "s": pa.array([f"x{i % 7}" for i in range(n)], pa.string()),
        "f": pa.array(rng.random(n), pa.float64()),
    })


_SQL = ("select k, s, count(*) c, sum(v) sv from t where v > 100 "
        "group by k, s order by k, s")


def _run(tbl, chunk_rows=800):
    s = Session()
    s.create_temp_view("t", ChunkedTable(tbl, chunk_rows=chunk_rows),
                       base=True)
    return s.sql(_SQL).collect()


def _entry(root):
    (e,) = [d for d in os.listdir(root) if not d.startswith(".")]
    return os.path.join(root, e)


def test_store_round_trip_bit_for_bit(tmp_path, monkeypatch):
    """Cold run (build + persist), warm run (load + mmap), and the
    store-off baseline must all produce identical rows; the warm run
    must go through load_plan, not re-save."""
    tbl = _table()
    base = _run(tbl)
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", str(tmp_path))
    cold = _run(tbl)
    entry = _entry(str(tmp_path))
    manifest0 = open(os.path.join(entry, "manifest.json")).read()
    saves = []
    orig_save = CS.save_plan
    monkeypatch.setattr(CS, "save_plan",
                        lambda *a, **k: saves.append(1) or
                        orig_save(*a, **k))
    warm = _run(tbl)
    assert cold == base == warm and base
    assert not saves, "warm run re-encoded instead of loading the store"
    assert open(os.path.join(entry, "manifest.json")).read() == manifest0


def test_store_warm_run_skips_arrow_and_codec_planning(tmp_path,
                                                       monkeypatch):
    """The tentpole claim: a warm run never lowers from arrow and never
    re-plans codecs or re-encodes dictionaries — padded_chunks serves
    mmapped wire arrays only."""
    from nds_tpu.engine import column as _column
    from nds_tpu.io import columnar as _col
    tbl = _table()
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", str(tmp_path))
    _run(tbl)                              # cold: build + persist

    def _refuse(what):
        def f(*a, **k):
            raise AssertionError(f"warm store run called {what}")
        return f

    monkeypatch.setattr(_col, "plan_column_codec",
                        _refuse("plan_column_codec (codec re-planning)"))
    monkeypatch.setattr(_column, "from_arrow_array",
                        _refuse("from_arrow_array (arrow chunk "
                                "lowering)"))
    monkeypatch.setattr(ChunkedTable, "_build_wire_plan",
                        _refuse("_build_wire_plan (re-encode)"))
    monkeypatch.setattr(ChunkedTable, "_string_encodings",
                        _refuse("_string_encodings (dictionary "
                                "re-encode)"))
    got = _run(tbl)
    assert got, "warm store run produced nothing"


def test_store_version_gate_refused_loudly(tmp_path, monkeypatch):
    tbl = _table()
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", str(tmp_path))
    _run(tbl)
    mp = os.path.join(_entry(str(tmp_path)), "manifest.json")
    m = json.load(open(mp))
    m["version"] = CS.STORE_VERSION + 1
    json.dump(m, open(mp, "w"))
    with pytest.raises(CS.ChunkStoreError, match="layout version"):
        _run(tbl)


def test_store_checksum_mismatch_refused_loudly(tmp_path, monkeypatch):
    tbl = _table()
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", str(tmp_path))
    _run(tbl)
    entry = _entry(str(tmp_path))
    (data0,) = [f for f in sorted(os.listdir(entry))
                if f.endswith("000.data.npy")]
    p = os.path.join(entry, data0)
    with open(p, "r+b") as f:
        f.seek(-1, 2)
        b = f.read(1)
        f.seek(-1, 2)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(CS.ChunkStoreError, match="checksum mismatch"):
        _run(tbl)


def test_store_stale_codec_plan_invalidates(tmp_path, monkeypatch):
    """Same shape, different DATA (shifted key domain => different FOR
    base): the old entry must read as a miss, the query must re-encode
    against the new data (correct results), and the entry on disk must
    be overwritten with the new fingerprint."""
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", str(tmp_path))
    old = _table(shift=0)
    _run(old)
    entry = _entry(str(tmp_path))
    fp_old = json.load(open(os.path.join(entry, "manifest.json")))[
        "fingerprint"]
    new = _table(shift=1000)               # same schema/rows, new values
    monkeypatch.delenv("NDS_TPU_CHUNK_STORE")
    expect = _run(new)                     # store-off truth
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", str(tmp_path))
    got = _run(new)
    assert got == expect and got, \
        "stale store entry served old codes for new data"
    fp_new = json.load(open(os.path.join(entry, "manifest.json")))[
        "fingerprint"]
    assert fp_new != fp_old, "entry was not rewritten after data change"
    assert _run(new) == expect             # and the new entry is warm


def test_store_empty_and_single_row_tables(tmp_path, monkeypatch):
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", str(tmp_path))
    empty = _table(n=0)
    one = _table(n=1, seed=9)
    for tbl in (empty, one):
        s = Session()
        s.create_temp_view("t", ChunkedTable(tbl, chunk_rows=800),
                           base=True)
        cold = s.sql("select k, v, s from t order by k").collect()
        s2 = Session()
        s2.create_temp_view("t", ChunkedTable(tbl, chunk_rows=800),
                            base=True)
        warm = s2.sql("select k, v, s from t order by k").collect()
        assert cold == warm
        assert len(cold) == tbl.num_rows


def test_store_off_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("NDS_TPU_CHUNK_STORE", raising=False)
    assert CS.store_root() is None
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", "")
    assert CS.store_root() is None         # empty = off
    _run(_table(n=64))
    assert not os.listdir(str(tmp_path))


def test_store_and_ring_compose(tmp_path, monkeypatch):
    """The warm store feeds the prefetch ring: mmapped wire arrays slice
    inside the worker thread, results identical to the inline no-store
    path at both depths."""
    tbl = _table()
    base = _run(tbl)
    monkeypatch.setenv("NDS_TPU_CHUNK_STORE", str(tmp_path))
    _run(tbl)                              # persist
    for depth in ("0", "3"):
        monkeypatch.setenv("NDS_TPU_PREFETCH_DEPTH", depth)
        from nds_tpu.engine import stream
        stream.reset_pipeline_cache()
        assert _run(tbl) == base, f"store+ring divergence at depth {depth}"
