# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Concurrency contract tests: cache singleflight under real threads and
the read-at-use env-knob discipline.

The static side (``analysis/conc_audit.py``) proves the lock layout;
these tests pin the runtime behavior the serving front depends on —
concurrent streams sharing one engine compile each shape exactly once,
never corrupt each other's results, and honor env knobs set after
import (the PR 6 ``_ACC_ROWS``/``_STREAM_FANOUT`` regression pattern).
The full threaded differential (all mechanisms + lock-liveness probes)
is ``tools/conc_audit_diff.py``, exercised from ``test_analysis.py``.
"""

import threading

import numpy as np
import pyarrow as pa

from nds_tpu.engine.session import Session


def _run_threads(n, fn):
    """Barrier-started workers; returns (results-by-thread, errors)."""
    barrier = threading.Barrier(n)
    out: dict = {}
    errors: list = []

    def worker(t):
        try:
            barrier.wait(timeout=60)
            out[t] = fn(t)
        except Exception as e:            # pragma: no cover - diagnostics
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,), daemon=True)
               for t in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=900)
    return out, errors


def test_pipeline_cache_singleflight_two_threads():
    """Two threads race the SAME chunked template from a cold cache:
    the singleflight registry must hand both the one compiled pipeline
    (per-shape build count exactly 1) and bit-identical rows."""
    from test_synccount import (_chunked_star_session,
                                _forced_stream_partitions,
                                _STREAM_AB_QUERIES)

    from nds_tpu.engine import stream

    q = _STREAM_AB_QUERIES[2][0]          # grouped aggregate, compiled
    with _forced_stream_partitions():
        stream.reset_pipeline_cache()
        s = _chunked_star_session(np.random.default_rng(3))
        out, errors = _run_threads(2, lambda t: s.sql(q).collect())
    assert not errors, errors
    assert out[0] and out[0] == out[1]
    counts = stream.pipeline_build_counts()
    assert counts, "the template stopped streaming compiled"
    assert all(n == 1 for n in counts.values()), counts


def _plain_session():
    s = Session()
    s.create_temp_view("t", pa.table({
        "k": pa.array(list(range(256)), pa.int64()),
        "v": pa.array([i * 3 % 101 for i in range(256)], pa.int64()),
    }), base=True)
    return s


def test_fusion_cache_singleflight_two_threads():
    """Two threads race one fusable WHERE from cold fusion caches:
    exactly one jitted trace per fused shape, identical rows."""
    from nds_tpu.sql import planner

    s = _plain_session()
    q = "select k, v from t where k > 17 and v < 60 order by k"
    want = s.sql(q).collect()             # warm the table path itself
    planner.reset_fuse_caches()
    out, errors = _run_threads(2, lambda t: s.sql(q).collect())
    assert not errors, errors
    assert out[0] == out[1] == want and want
    counts = planner.fuse_build_counts()
    assert counts, "the WHERE stopped going through expression fusion"
    assert all(n == 1 for n in counts.values()), counts


def test_fuse_cache_eviction_under_contention(monkeypatch):
    """Concurrent distinct-shape churn past the FIFO bound: the cache
    never exceeds its cap (evictions and inserts share the lock) and
    every query still answers correctly."""
    from nds_tpu.sql import planner

    monkeypatch.setattr(planner, "_MASK_FUSE_MAX", 8)
    s = _plain_session()
    planner.reset_fuse_caches()

    def churn(t):
        rows = []
        for i in range(12):
            thr = t * 37 + i              # distinct per (thread, step)
            got = s.sql(f"select k from t where k > {thr} and "
                        f"v >= 0 order by k").collect()
            rows.append((thr, len(got)))
        return rows

    out, errors = _run_threads(2, churn)
    assert not errors, errors
    for rows in out.values():
        for thr, n in rows:
            assert n == max(0, 256 - (thr + 1))
    assert len(planner._MASK_FUSE_CACHE) <= 8


def test_stream_mesh_cache_threaded_one_winner():
    """Concurrent stream_mesh() calls for one (shards, axis) key must
    return the SAME Mesh object (double-checked insert: one winner)."""
    from nds_tpu.parallel import exchange

    exchange._STREAM_MESHES.clear()
    out, errors = _run_threads(
        4, lambda t: exchange.stream_mesh(2, axis="conc_test_axis"))
    assert not errors, errors
    meshes = list(out.values())
    assert meshes[0] is not None
    assert all(m is meshes[0] for m in meshes)
    assert len([k for k in exchange._STREAM_MESHES
                if k[1] == "conc_test_axis"]) == 1


def test_env_knobs_read_after_import(monkeypatch):
    """Every converted import-time snapshot now reads its env knob at
    build/use time — the set-after-import regression net (PR 6
    pattern). A knob set after import must be honored immediately."""
    from nds_tpu.engine import kernels, ops, prefetch, replay
    from nds_tpu.obs import trace
    from nds_tpu.sql import planner

    cases = [
        ("NDS_TPU_PREFETCH_DEPTH", prefetch.prefetch_depth, "5", 5),
        ("NDS_TPU_PAIR_BUDGET", ops.pair_budget, "12345", 12345),
        ("NDS_TPU_GROUP_PACK_MIN", ops.group_pack_min, "777", 777),
        ("NDS_TPU_LAZY_SHRINK_ROWS", ops.lazy_shrink_rows, "4096", 4096),
        ("NDS_TPU_PALLAS_MAX_GROUPS", kernels.max_groups, "99", 99),
        ("NDS_TPU_EXACT_ONEHOT_BUDGET", kernels.exact_onehot_budget,
         "1e6", 1_000_000),
        ("NDS_TPU_REPLAY_MAX_EQNS", replay._max_eqns, "222", 222),
        ("NDS_TPU_REPLAY_MAX_SEGMENTS", replay._max_segments, "9", 9),
        ("NDS_TPU_DEFER_FILTER_MAX_ROWS", planner._defer_filter_max_rows,
         "31337", 31337),
        ("NDS_TPU_TRACE_RING", trace._ring_max, "123", 123),
    ]
    for env, accessor, raw, want in cases:
        monkeypatch.setenv(env, raw)
        assert accessor() == want, env
        monkeypatch.delenv(env)
    # the trace ring knob must reach a NEW thread's ring allocation
    monkeypatch.setenv("NDS_TPU_TRACE_RING", "41")
    got = {}

    def attach_and_report():
        trace.attach()
        got["maxlen"] = trace._tls.ring.maxlen

    t = threading.Thread(target=attach_and_report)
    t.start()
    t.join(timeout=30)
    assert got.get("maxlen") == 41


def test_engine_knobs_join_pipeline_cache_key(monkeypatch):
    """The read-at-use knobs that shape the traced per-chunk program are
    pipeline-cache key members: changing one after a compile must MISS
    (fresh build), not serve the stale pipeline — cache-key completeness
    at runtime, mirroring the static conc-audit rule."""
    from test_synccount import (_chunked_star_session,
                                _forced_stream_partitions,
                                _STREAM_AB_QUERIES)

    from nds_tpu.engine import stream

    q = _STREAM_AB_QUERIES[1][0]
    with _forced_stream_partitions():
        stream.reset_pipeline_cache()
        s = _chunked_star_session(np.random.default_rng(5))
        rows1 = s.sql(q).collect()
        n1 = sum(stream.pipeline_build_counts().values())
        assert n1 >= 1
        rows_warm = s.sql(q).collect()    # warm: cache hit, no build
        assert sum(stream.pipeline_build_counts().values()) == n1
        monkeypatch.setenv("NDS_TPU_PAIR_BUDGET", str(1 << 21))
        rows2 = s.sql(q).collect()
        n2 = sum(stream.pipeline_build_counts().values())
        assert n2 > n1, "knob change served the stale compiled pipeline"
    assert rows1 == rows_warm == rows2
