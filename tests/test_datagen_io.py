# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Native generator + CSV ingest + columnar IO tests."""

import filecmp
import os
import subprocess

import pyarrow as pa
import pytest

from nds_tpu.io import read_raw_table, read_table, write_table
from nds_tpu.schema import get_maintenance_schemas, get_schemas

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NDSGEN = os.path.join(REPO, "native", "ndsgen", "ndsgen")

pytestmark = pytest.mark.skipif(
    not os.path.exists(NDSGEN), reason="native generator not built"
)


def gen(tmp, *extra):
    subprocess.run([NDSGEN, "-scale", "0.001", "-dir", str(tmp), *extra], check=True)


def test_generator_emits_all_source_tables(tmp_path):
    gen(tmp_path)
    schemas = get_schemas(use_decimal=True)
    for table, fields in schemas.items():
        f = tmp_path / f"{table}.dat"
        assert f.exists(), table
        with open(f, encoding="iso8859-1") as fh:
            line = fh.readline()
        # trailing delimiter => n_fields + 1 splits
        assert line.count("|") == len(fields), table


def test_generator_deterministic(tmp_path):
    a, b = tmp_path / "a", tmp_path / "b"
    a.mkdir(); b.mkdir()
    gen(a, "-table", "customer")
    gen(b, "-table", "customer")
    assert filecmp.cmp(a / "customer.dat", b / "customer.dat", shallow=False)
    c = tmp_path / "c"
    c.mkdir()
    gen(c, "-table", "customer", "-rngseed", "7")
    assert not filecmp.cmp(a / "customer.dat", c / "customer.dat", shallow=False)


def test_chunks_union_equals_whole(tmp_path):
    """Parallel chunk files concatenate to the single-chunk output, so
    distributed generation is exact (ref: chunk semantics of
    nds/nds_gen_data.py:183-244)."""
    whole, parts = tmp_path / "whole", tmp_path / "parts"
    whole.mkdir(); parts.mkdir()
    gen(whole, "-table", "time_dim")
    for child in (1, 2, 3):
        subprocess.run([NDSGEN, "-scale", "0.001", "-dir", str(parts),
                        "-table", "time_dim", "-parallel", "3",
                        "-child", str(child)], check=True)
    merged = b"".join(
        (parts / f"time_dim_{c}_3.dat").read_bytes() for c in (1, 2, 3))
    assert merged == (whole / "time_dim.dat").read_bytes()


def test_update_mode_emits_refresh_tables(tmp_path):
    gen(tmp_path, "-update", "1")
    schemas = get_maintenance_schemas(use_decimal=True)
    for table, fields in schemas.items():
        fname = f"{table}_1.dat" if table in ("delete", "inventory_delete") \
            else f"{table}.dat"
        f = tmp_path / fname
        assert f.exists(), table
        with open(f) as fh:
            line = fh.readline()
        assert line.count("|") == len(fields), table


def test_csv_ingest_types_and_nulls(tmp_path):
    gen(tmp_path)
    schemas = get_schemas(use_decimal=True)
    t = read_raw_table(str(tmp_path / "store_sales.dat"), schemas["store_sales"])
    assert t.num_columns == 23
    assert t.schema.field("ss_list_price").type == pa.decimal128(7, 2)
    assert t.schema.field("ss_sold_date_sk").type == pa.int32()
    assert t.num_rows > 1000
    # nullable FK columns should actually contain nulls (~4%)
    assert t["ss_customer_sk"].null_count > 0
    # item_sk is non-nullable in the generator output
    assert t["ss_item_sk"].null_count == 0
    d = read_raw_table(str(tmp_path / "date_dim.dat"), schemas["date_dim"])
    assert d.schema.field("d_date").type == pa.date32()
    years = pa.compute.unique(d["d_year"]).to_pylist()
    assert 1900 in years and 2000 in years


def test_csv_ingest_directory_of_chunks(tmp_path):
    d = tmp_path / "time_dim"
    d.mkdir()
    for child in (1, 2):
        subprocess.run([NDSGEN, "-scale", "0.001", "-dir", str(d),
                        "-table", "time_dim", "-parallel", "2",
                        "-child", str(child)], check=True)
    t = read_raw_table(str(d), get_schemas(True)["time_dim"])
    assert t.num_rows == 86400


def test_columnar_roundtrip_partitioned(tmp_path):
    gen(tmp_path)
    schemas = get_schemas(use_decimal=True)
    t = read_raw_table(str(tmp_path / "store_sales.dat"), schemas["store_sales"])
    out = tmp_path / "pq"
    write_table(t, str(out), "parquet", partition_col="ss_sold_date_sk")
    back = read_table(str(out), "parquet")
    assert back.num_rows == t.num_rows
    assert set(back.column_names) == set(t.column_names)
    # partition dirs exist
    assert any(p.name.startswith("ss_sold_date_sk=") for p in out.iterdir())


def test_avro_roundtrip_values_and_partitioning(tmp_path):
    """Avro Load Test target (ref: nds/nds_transcode.py:61,85,257): the
    pure-python container codec must round-trip values exactly — decimals,
    dates, nulls — both flat and hive-partitioned."""
    gen(tmp_path)
    schemas = get_schemas(use_decimal=True)
    t = read_raw_table(str(tmp_path / "store_sales.dat"),
                       schemas["store_sales"])
    flat = tmp_path / "avro_flat"
    write_table(t, str(flat), "avro")
    back = read_table(str(flat), "avro")
    assert back.num_rows == t.num_rows
    assert set(back.column_names) == set(t.column_names)
    for name in ("ss_sold_date_sk", "ss_ticket_number", "ss_sales_price",
                 "ss_ext_list_price"):
        assert back.column(name).to_pylist() == t.column(name).to_pylist(), \
            name
    assert back.schema.field("ss_sales_price").type == \
        t.schema.field("ss_sales_price").type
    # hive-partitioned layout + deflate codec
    part = tmp_path / "avro_part"
    write_table(t, str(part), "avro", partition_col="ss_sold_date_sk",
                compression="deflate")
    assert any(p.name.startswith("ss_sold_date_sk=")
               for p in part.iterdir())
    back = read_table(str(part), "avro")
    assert back.num_rows == t.num_rows
    assert set(back.column_names) == set(t.column_names)
    assert sorted(back.column("ss_sold_date_sk").to_pylist(),
                  key=lambda v: (v is None, v)) == \
        sorted(t.column("ss_sold_date_sk").to_pylist(),
               key=lambda v: (v is None, v))


def test_referential_integrity_returns_match_sales(tmp_path):
    """Returns rows must hit real sale rows: same ticket+item exists in
    store_sales (generator derives returns from their originating sale)."""
    gen(tmp_path)
    schemas = get_schemas(use_decimal=True)
    ss = read_raw_table(str(tmp_path / "store_sales.dat"), schemas["store_sales"])
    sr = read_raw_table(str(tmp_path / "store_returns.dat"), schemas["store_returns"])
    sales_keys = set(zip(ss["ss_ticket_number"].to_pylist(),
                         ss["ss_item_sk"].to_pylist()))
    ret_keys = list(zip(sr["sr_ticket_number"].to_pylist(),
                        sr["sr_item_sk"].to_pylist()))
    hit = sum(1 for k in ret_keys if k in sales_keys)
    assert hit == len(ret_keys)


def test_state_vocabulary_banded_by_scale(tmp_path):
    """Generator state vocabulary and query-sampler band must agree (the
    scale-banded fips-distribution idea): at sub-SF1 both sides use the
    first 8 states, so state predicates stay non-degenerate."""
    import subprocess
    from nds_tpu.queries import POOLS, active_states, instantiate_template
    subprocess.run([NDSGEN, "-scale", "0.01", "-dir", str(tmp_path),
                    "-table", "customer_address"], check=True)
    allowed = set(POOLS["state"][:active_states(0.01)])
    assert len(allowed) == 8
    allowed_city = set(POOLS["city"][:8])
    allowed_county = set(POOLS["county"][:8])
    states, cities, counties = set(), set(), set()
    for ln in open(tmp_path / "customer_address.dat", encoding="iso-8859-1"):
        parts = ln.split("|")
        if parts[8]:
            states.add(parts[8])
        if parts[6]:
            cities.add(parts[6])
        if parts[7]:
            counties.add(parts[7])
    assert states and states <= allowed
    assert cities and cities <= allowed_city
    assert counties and counties <= allowed_county

    import numpy as np
    rng = np.random.default_rng(0)
    for _ in range(20):
        sql = instantiate_template("--@ ST = pool(state)\nselect '[ST]'",
                                   rng, scale=0.01)
        got = sql.split("'")[1]
        assert got in allowed
