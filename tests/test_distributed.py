# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Distributed (mesh) execution vs the single-device engine: same query,
same data, results must agree — the validation-against-baseline idea
(SURVEY.md §4.1) applied to the sharded path."""

import numpy as np
import pytest

import jax

from nds_tpu.parallel import make_mesh
from nds_tpu.parallel.distributed import (
    broadcast_join_agg, dim_probe_map, replicate, run_distributed_q3,
    shard_fact_columns)

import jax.numpy as jnp

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs the virtual multi-device mesh")


def _q3_data(rng, n_fact=10_000, n_items=200, n_dates=400):
    item = {
        "i_item_sk": np.arange(1, n_items + 1, dtype=np.int64),
        "i_manufact_id": rng.integers(1, 10, n_items).astype(np.int64),
        "i_brand_id": rng.integers(1000, 1020, n_items).astype(np.int64),
    }
    date_dim = {
        "d_date_sk": np.arange(1, n_dates + 1, dtype=np.int64),
        "d_moy": rng.integers(1, 13, n_dates).astype(np.int64),
        "d_year": 1998 + (np.arange(n_dates, dtype=np.int64) // 100),
    }
    store_sales = {
        # some keys miss the dimensions (null-ish fk -> inner-join drop)
        "ss_item_sk": rng.integers(1, n_items + 50, n_fact).astype(np.int64),
        "ss_sold_date_sk": rng.integers(1, n_dates + 30, n_fact).astype(np.int64),
        "ss_ext_sales_price": rng.integers(1, 10_000, n_fact).astype(np.int64),
    }
    return store_sales, date_dim, item


def _q3_reference(store_sales, date_dim, item, manufact, moy):
    """Plain numpy evaluation of the q3 aggregation."""
    i_by_sk = {int(sk): i for i, sk in enumerate(item["i_item_sk"])}
    d_by_sk = {int(sk): i for i, sk in enumerate(date_dim["d_date_sk"])}
    sums = {}
    for fk, dk, w in zip(store_sales["ss_item_sk"],
                         store_sales["ss_sold_date_sk"],
                         store_sales["ss_ext_sales_price"]):
        ii = i_by_sk.get(int(fk))
        di = d_by_sk.get(int(dk))
        if ii is None or di is None:
            continue
        if item["i_manufact_id"][ii] != manufact or date_dim["d_moy"][di] != moy:
            continue
        key = (int(date_dim["d_year"][di]), ii)
        sums[key] = sums.get(key, 0) + int(w)
    return sums


@pytest.mark.parametrize("n_fact", [8_000, 8_001])  # even and uneven shards
def test_distributed_q3_matches_reference(n_fact):
    rng = np.random.default_rng(11)
    store_sales, date_dim, item = _q3_data(rng, n_fact=n_fact)
    manufact, moy = 3, 11
    mesh = make_mesh(min(8, len(jax.devices())))

    out = run_distributed_q3(mesh, store_sales, date_dim, item,
                             manufact_id=manufact, moy=moy)
    ref = _q3_reference(store_sales, date_dim, item, manufact, moy)

    got = {(int(y), int(ii)): float(s)
           for y, ii, s in zip(out["d_year"], out["item_index"], out["sum_agg"])}
    assert set(got) == set(ref)
    for k, v in ref.items():
        assert got[k] == pytest.approx(float(v))


def test_broadcast_join_agg_counts_rows():
    rng = np.random.default_rng(12)
    mesh = make_mesh(min(8, len(jax.devices())))
    n = 4096
    fact_key = rng.integers(1, 100, n).astype(np.int64)
    weights = rng.integers(1, 5, n).astype(np.int64)
    dim_key = np.arange(1, 101, dtype=np.int64)
    codes = (dim_key % 7).astype(np.int64)

    fact, alive = shard_fact_columns(
        mesh, {"k": jnp.asarray(fact_key), "w": jnp.asarray(weights)}, n)
    dks, dorder = dim_probe_map(replicate(mesh, jnp.asarray(dim_key)))
    sums, counts = broadcast_join_agg(
        mesh, {"k": fact["k"], "w": fact["w"]}, alive,
        dks, dorder, replicate(mesh, jnp.asarray(codes)), 7,
        weight_name="w", fact_key_name="k")
    assert int(np.asarray(counts).sum()) == n          # every key matches
    ref = np.zeros(7)
    for k, w in zip(fact_key, weights):
        ref[k % 7] += w
    np.testing.assert_allclose(np.asarray(sums), ref)


def _sql_fixture_tables():
    import pyarrow as pa
    rng = np.random.default_rng(11)
    n = 5000
    sales = pa.table({
        "s_item": pa.array(rng.integers(1, 80, n), pa.int64()),
        "s_date": pa.array(rng.integers(1, 300, n), pa.int64()),
        "s_qty": pa.array(rng.integers(1, 50, n), pa.int64()),
        "s_price": pa.array([None if x % 17 == 0 else int(x)
                             for x in rng.integers(1, 9000, n)], pa.int64()),
        "s_tag": pa.array(rng.choice(["a", "b", "c", None], n)),
    })
    items = pa.table({
        "i_item": pa.array(np.arange(1, 81), pa.int64()),
        "i_cat": pa.array([f"cat{k % 7}" for k in range(80)]),
    })
    dates = pa.table({
        "d_date": pa.array(np.arange(1, 301), pa.int64()),
        "d_year": pa.array(1998 + np.arange(300) // 100, pa.int64()),
    })
    return {"sales": sales, "items": items, "dates": dates}


SQL_CASES = [
    # join + group + order: the flagship shape
    """select d_year, i_cat, sum(s_qty) qty, count(*) cnt, avg(s_price)
       from sales, items, dates
       where s_item = i_item and s_date = d_date and s_qty > 5
       group by d_year, i_cat order by d_year, i_cat""",
    # windows over a join
    """select i_cat, s_qty, rank() over (partition by i_cat order by s_qty desc) r
       from sales, items where s_item = i_item and s_qty > 45
       order by i_cat, r, s_qty limit 50""",
    # semi-join + distinct
    """select distinct s_tag from sales
       where s_item in (select i_item from items where i_cat = 'cat3')
       order by s_tag""",
]


@pytest.mark.parametrize("case", range(len(SQL_CASES)))
def test_spmd_session_matches_single_device(case):
    """The generic engine under a GSPMD mesh (Session mesh_shape) must
    produce exactly the single-device results on every query shape."""
    from nds_tpu.engine.session import Session

    tables = _sql_fixture_tables()
    single = Session()
    meshed = Session(conf={"mesh_shape": 8})
    assert meshed.mesh is not None and meshed.mesh.devices.size == 8
    for name, t in tables.items():
        single.create_temp_view(name, t)
        meshed.create_temp_view(name, t)
    sql = SQL_CASES[case]
    a = single.sql(sql).collect()
    b = meshed.sql(sql).collect()
    assert a == b
