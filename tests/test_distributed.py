# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Distributed (mesh) execution vs the single-device engine: same query,
same data, results must agree — the validation-against-baseline idea
(SURVEY.md §4.1) applied to the sharded path."""

import numpy as np
import pytest

import jax

from nds_tpu.parallel import make_mesh
from nds_tpu.parallel.distributed import (
    broadcast_join_agg, dim_probe_map, replicate, run_distributed_q3,
    shard_fact_columns)

import jax.numpy as jnp

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs the virtual multi-device mesh")


def _q3_data(rng, n_fact=10_000, n_items=200, n_dates=400):
    item = {
        "i_item_sk": np.arange(1, n_items + 1, dtype=np.int64),
        "i_manufact_id": rng.integers(1, 10, n_items).astype(np.int64),
        "i_brand_id": rng.integers(1000, 1020, n_items).astype(np.int64),
    }
    date_dim = {
        "d_date_sk": np.arange(1, n_dates + 1, dtype=np.int64),
        "d_moy": rng.integers(1, 13, n_dates).astype(np.int64),
        "d_year": 1998 + (np.arange(n_dates, dtype=np.int64) // 100),
    }
    store_sales = {
        # some keys miss the dimensions (null-ish fk -> inner-join drop)
        "ss_item_sk": rng.integers(1, n_items + 50, n_fact).astype(np.int64),
        "ss_sold_date_sk": rng.integers(1, n_dates + 30, n_fact).astype(np.int64),
        "ss_ext_sales_price": rng.integers(1, 10_000, n_fact).astype(np.int64),
    }
    return store_sales, date_dim, item


def _q3_reference(store_sales, date_dim, item, manufact, moy):
    """Plain numpy evaluation of the q3 aggregation."""
    i_by_sk = {int(sk): i for i, sk in enumerate(item["i_item_sk"])}
    d_by_sk = {int(sk): i for i, sk in enumerate(date_dim["d_date_sk"])}
    sums = {}
    for fk, dk, w in zip(store_sales["ss_item_sk"],
                         store_sales["ss_sold_date_sk"],
                         store_sales["ss_ext_sales_price"]):
        ii = i_by_sk.get(int(fk))
        di = d_by_sk.get(int(dk))
        if ii is None or di is None:
            continue
        if item["i_manufact_id"][ii] != manufact or date_dim["d_moy"][di] != moy:
            continue
        key = (int(date_dim["d_year"][di]), ii)
        sums[key] = sums.get(key, 0) + int(w)
    return sums


@pytest.mark.parametrize("n_fact", [8_000, 8_001])  # even and uneven shards
def test_distributed_q3_matches_reference(n_fact):
    rng = np.random.default_rng(11)
    store_sales, date_dim, item = _q3_data(rng, n_fact=n_fact)
    manufact, moy = 3, 11
    mesh = make_mesh(min(8, len(jax.devices())))

    out = run_distributed_q3(mesh, store_sales, date_dim, item,
                             manufact_id=manufact, moy=moy)
    ref = _q3_reference(store_sales, date_dim, item, manufact, moy)

    got = {(int(y), int(ii)): float(s)
           for y, ii, s in zip(out["d_year"], out["item_index"], out["sum_agg"])}
    assert set(got) == set(ref)
    for k, v in ref.items():
        assert got[k] == pytest.approx(float(v))


def test_broadcast_join_agg_counts_rows():
    rng = np.random.default_rng(12)
    mesh = make_mesh(min(8, len(jax.devices())))
    n = 4096
    fact_key = rng.integers(1, 100, n).astype(np.int64)
    weights = rng.integers(1, 5, n).astype(np.int64)
    dim_key = np.arange(1, 101, dtype=np.int64)
    codes = (dim_key % 7).astype(np.int64)

    fact, alive = shard_fact_columns(
        mesh, {"k": jnp.asarray(fact_key), "w": jnp.asarray(weights)}, n)
    dks, dorder = dim_probe_map(replicate(mesh, jnp.asarray(dim_key)))
    sums, counts = broadcast_join_agg(
        mesh, {"k": fact["k"], "w": fact["w"]}, alive,
        dks, dorder, replicate(mesh, jnp.asarray(codes)), 7,
        weight_name="w", fact_key_name="k")
    assert int(np.asarray(counts).sum()) == n          # every key matches
    ref = np.zeros(7)
    for k, w in zip(fact_key, weights):
        ref[k % 7] += w
    np.testing.assert_allclose(np.asarray(sums), ref)


def _sql_fixture_tables():
    import pyarrow as pa
    rng = np.random.default_rng(11)
    n = 5000
    sales = pa.table({
        "s_item": pa.array(rng.integers(1, 80, n), pa.int64()),
        "s_date": pa.array(rng.integers(1, 300, n), pa.int64()),
        "s_qty": pa.array(rng.integers(1, 50, n), pa.int64()),
        "s_price": pa.array([None if x % 17 == 0 else int(x)
                             for x in rng.integers(1, 9000, n)], pa.int64()),
        "s_tag": pa.array(rng.choice(["a", "b", "c", None], n)),
    })
    items = pa.table({
        "i_item": pa.array(np.arange(1, 81), pa.int64()),
        "i_cat": pa.array([f"cat{k % 7}" for k in range(80)]),
    })
    dates = pa.table({
        "d_date": pa.array(np.arange(1, 301), pa.int64()),
        "d_year": pa.array(1998 + np.arange(300) // 100, pa.int64()),
    })
    return {"sales": sales, "items": items, "dates": dates}


SQL_CASES = [
    # join + group + order: the flagship shape
    """select d_year, i_cat, sum(s_qty) qty, count(*) cnt, avg(s_price)
       from sales, items, dates
       where s_item = i_item and s_date = d_date and s_qty > 5
       group by d_year, i_cat order by d_year, i_cat""",
    # windows over a join
    """select i_cat, s_qty, rank() over (partition by i_cat order by s_qty desc) r
       from sales, items where s_item = i_item and s_qty > 45
       order by i_cat, r, s_qty limit 50""",
    # semi-join + distinct
    """select distinct s_tag from sales
       where s_item in (select i_item from items where i_cat = 'cat3')
       order by s_tag""",
]


@pytest.mark.parametrize("case", range(len(SQL_CASES)))
def test_spmd_session_matches_single_device(case):
    """The generic engine under a GSPMD mesh (Session mesh_shape) must
    produce exactly the single-device results on every query shape."""
    from nds_tpu.engine.session import Session

    tables = _sql_fixture_tables()
    single = Session()
    meshed = Session(conf={"mesh_shape": 8})
    assert meshed.mesh is not None and meshed.mesh.devices.size == 8
    for name, t in tables.items():
        single.create_temp_view(name, t)
        meshed.create_temp_view(name, t)
    sql = SQL_CASES[case]
    a = single.sql(sql).collect()
    b = meshed.sql(sql).collect()
    assert a == b


def test_exchange_repartition_join_matches_single_device(monkeypatch):
    """Two row-sharded (over-threshold) sides must join through the ICI
    all-to-all exchange and agree with the single-device engine — the
    repartition arm of the broadcast/repartition planner choice."""
    import pyarrow as pa
    from nds_tpu.engine import ops as E
    from nds_tpu.engine.session import Session

    monkeypatch.setenv("NDS_TPU_BROADCAST_BYTES", "64")   # shard everything
    rng = np.random.default_rng(5)
    n = 4096
    a = pa.table({
        "a_k": pa.array(rng.integers(1, 300, n), pa.int64()),
        "a_v": pa.array(rng.integers(1, 1000, n), pa.int64()),
    })
    b = pa.table({
        "b_k": pa.array(rng.integers(1, 300, n), pa.int64()),
        "b_v": pa.array(rng.integers(1, 1000, n), pa.int64()),
    })
    sql = ("select a_k, count(*) c, sum(a_v + b_v) s from a, b "
           "where a_k = b_k and a_v < b_v group by a_k order by a_k")
    single = Session()
    meshed = Session(conf={"mesh_shape": 8})
    for name, t in (("a", a), ("b", b)):
        single.create_temp_view(name, t)
        meshed.create_temp_view(name, t)
    # the meshed run must actually take the exchange path
    calls = []
    orig = E._exchange_inner_join
    monkeypatch.setattr(
        E, "_exchange_inner_join",
        lambda *args, **kw: (calls.append(1), orig(*args, **kw))[1])
    got = meshed.sql(sql).collect()
    assert calls, "repartition join did not engage on sharded inputs"
    assert got == single.sql(sql).collect()


def test_exchange_join_dead_rows():
    """Sentinel (dead) rows — null keys, pad rows, deferred-filter exclusions
    — must not corrupt the bucketize: regression for the unsorted-haystack
    bug where dead rows kept dest=0 while the argsort key sent them to the
    end, so searchsorted misplaced every real row once dead rows dominated
    the binary-search midpoints (silently losing most join pairs)."""
    from nds_tpu.parallel import exchange as X

    mesh = make_mesh(8)
    n = 4096
    for frac in (0.5, 0.95):
        rng_ = np.random.default_rng(3)
        keys = rng_.integers(0, 200, n)
        dead = rng_.random(n) < frac
        row_ids = np.arange(n, dtype=np.uint64)
        # _key_hash_impl sentinel layout: bits 0-1 side tag, bit 2 CLEAR,
        # row id from bit 3; real hashes carry bit 2
        lh = np.where(dead, (row_ids << 3) | 2,
                      (keys.astype(np.uint64) << 3) | 4)
        rh = np.where(dead, (row_ids << 3) | 1,
                      (keys.astype(np.uint64) << 3) | 4)
        rows = jnp.arange(n, dtype=jnp.int64)
        li, ri, live = X.exchange_join_pairs(
            jnp.asarray(lh), rows, jnp.asarray(rh), rows, mesh)
        alive = keys[~dead]
        expect = sum(int(c) * int(c) for c in np.bincount(alive))
        assert int(jnp.sum(live)) == expect
        # every returned pair must be a genuine key match between live rows
        li_n = np.asarray(li)[np.asarray(live)]
        ri_n = np.asarray(ri)[np.asarray(live)]
        assert not dead[li_n].any() and not dead[ri_n].any()
        assert (keys[li_n] == keys[ri_n]).all()


def test_exchange_join_overflow_retry(monkeypatch):
    """Undersized initial capacities must be healed by the doubled-capacity
    retry, not lose rows."""
    from nds_tpu.parallel import exchange as X

    mesh = make_mesh(8)
    rng = np.random.default_rng(9)
    n = 1024
    # skewed keys: most rows share one key -> one destination bucket
    # overflows any per-destination capacity sized for the uniform case
    keys = np.where(rng.random(n) < 0.8, 7, rng.integers(0, 50, n))
    # real hashes always carry bit 2 (_key_hash_impl ors in 4); shift keys
    # past the tag bits so distinct keys stay distinct
    lh = jnp.asarray(((keys.astype(np.uint64) << 3) | 4))
    rh = lh
    rows = jnp.arange(n, dtype=jnp.int64)
    li, ri, live = X.exchange_join_pairs(lh, rows, rh, rows, mesh)
    n_pairs = int(jnp.sum(live))
    expect = sum(int(c) * int(c) for c in np.bincount(keys))
    assert n_pairs == expect
